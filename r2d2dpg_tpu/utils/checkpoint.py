"""Checkpoint / resume via orbax (SURVEY.md §5.4).

Reference parity: the reference at most does periodic
``torch.save(state_dict)`` with no optimizer/replay state and no resume path
(SURVEY §5.4).  The build checkpoints the **entire** ``TrainerState`` pytree —
params, optimizer states, target nets, RNG, replay arena (data + priorities +
cursor), env state, episode accumulators — so a restore resumes the run
exactly (for pure-JAX envs) or near-exactly (host-backed envs; see below).

Host-backed envs (``dmc_host``): MuJoCo physics lives on the host, outside
the pytree, so it cannot be checkpointed through this path.  On restore the
env portion of the state is re-initialized (fresh episodes, zeroed carries);
replay, learner and counters resume intact.  The first ``seq_len`` post-resume
steps re-fill the window before sequences are emitted again, exactly like the
initial warm-up — no corrupt sequences enter replay.
"""

from __future__ import annotations

import dataclasses
import inspect
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp


class CheckpointManager:
    """Periodic save + latest-restore of ``TrainerState`` under ``directory``.

    A thin wrapper over ``orbax.checkpoint.CheckpointManager`` that knows how
    to rebuild the abstract pytree template from a ``Trainer`` and to patch
    up host-backed env state on restore.
    """

    def __init__(
        self,
        directory: str,
        *,
        save_every: int = 500,
        max_to_keep: int = 3,
        async_save: bool = False,
        light: bool = False,
    ):
        # ``light``: save only the learner subtree ({"train": state.train} —
        # params, targets, optimizer states, step) instead of the full
        # TrainerState.  MBs instead of GBs (no replay arena / window /
        # env fleet), so periodic saves are affordable mid-measurement,
        # and the on-disk layout is exactly what eval.py restores.  Resume
        # from a light checkpoint continues learning with a FRESH replay
        # and phase schedule (warm-up/fill re-run) — by design.
        self.light = light
        # orbax rejects relative paths at SAVE time (deep inside the first
        # cadence hit — a run can train for minutes and then die); absolutize
        # up front so `--checkpoint-dir runs/x/ckpt` just works.
        self.directory = os.path.abspath(directory)
        self.save_every = save_every
        # Synchronous by default (VERDICT r1 weak #3): orbax's async save
        # finalizes on a background thread, which a busy single-core host
        # starves — the one long round-1 run left ONLY un-finalized
        # ``*.orbax-checkpoint-tmp`` dirs and ``--resume`` found nothing.
        # A blocking save is a few seconds every ``save_every`` phases and
        # is durable the moment it returns.
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                create=True,
                enable_async_checkpointing=async_save,
            ),
        )

    # ------------------------------------------------------------------ save
    # ``save_every`` semantics: N>0 = every N phases (+ the caller's final
    # save); -1 = final-save-only (maybe_save never fires, but the truthy
    # value keeps train.py's finally-block save armed); 0 = off entirely.
    def maybe_save(self, phase: int, state: Any) -> bool:
        """Save if ``phase`` hits the cadence.  Returns True when saved."""
        if self.save_every <= 0 or phase % self.save_every != 0:
            return False
        self.save(phase, state)
        return True

    def save(self, step: int, state: Any) -> None:
        """Save at ``step``, overwriting an existing same-step checkpoint
        (a light-resume run restarts its phase numbering at 0, so a
        resumed run legitimately revisits steps already on disk)."""
        from r2d2dpg_tpu.obs import flight_event

        self._check_layout(saving=True)
        if step in (self._mgr.all_steps() or []):
            self._mgr.delete(step)
        payload = {"train": state.train} if self.light else state
        self._mgr.save(step, args=ocp.args.StandardSave(payload))
        # The flight recorder's checkpoint trail is what the divergence
        # watchdog's "last-good checkpoint" pointer reads at abort time.
        flight_event(
            "checkpoint_save",
            step=int(step),
            directory=self.directory,
            light=self.light,
        )

    def save_final(self, step: int, state: Any) -> None:
        """End-of-run save; no-op when the cadence already saved ``step``
        (orbax raises StepAlreadyExistsError otherwise, which would turn a
        successful run into a failed one at teardown)."""
        if self._mgr.latest_step() == step:
            return
        self.save(step, state)

    _LAYOUT_MARKER = "LIGHT_CHECKPOINTS"

    def _check_layout(self, *, saving: bool) -> None:
        """Refuse light/full mode mismatches against what's on disk, with a
        clear message instead of an opaque orbax tree-structure error."""
        marker = os.path.join(self.directory, self._LAYOUT_MARKER)
        has_steps = bool(self._mgr.all_steps())
        if self.light:
            if has_steps and not os.path.exists(marker):
                raise ValueError(
                    f"{self.directory} holds FULL checkpoints but this "
                    "manager is light=True — drop --checkpoint-light or "
                    "point at a fresh directory"
                )
            if saving and not os.path.exists(marker):
                with open(marker, "w") as f:
                    f.write("train-subtree-only checkpoints\n")
        elif os.path.exists(marker):
            raise ValueError(
                f"{self.directory} holds LIGHT checkpoints but this "
                "manager is light=False — pass --checkpoint-light to match "
                "(eval.py is unaffected: it restores the train subtree "
                "from either layout)"
            )

    def wait(self) -> None:
        """Block until async saves are durable (call before process exit)."""
        self._mgr.wait_until_finished()

    # --------------------------------------------------------------- restore
    @property
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> list:
        """Steps currently on disk (post ``max_to_keep`` pruning) — the
        fleet counter-sidecar pruning keys off this (fleet/ingest.py)."""
        return list(self._mgr.all_steps() or [])

    def restore(self, template: Any) -> Any:
        """Restore the latest checkpoint into the structure of ``template``.

        ``template`` is a concrete ``TrainerState`` (e.g. ``trainer.init()``)
        — its shapes/dtypes/shardings define the restore target, so restored
        arrays land with the same mesh layout the trainer expects.  In
        ``light`` mode only the learner subtree is stored, so the template
        is narrowed to it and the result is the restored ``train`` subtree.
        """
        self._check_layout(saving=False)
        step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}"
            )
        target = {"train": template.train} if self.light else template
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                jnp.shape(x), x.dtype, sharding=getattr(x, "sharding", None)
            )
            if isinstance(x, (jax.Array, np.ndarray))
            else x,
            target,
        )
        out = self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))
        return out["train"] if self.light else out

    def close(self) -> None:
        self._mgr.close()


def _raise_tree_mismatch(missing, mismatched, *, where: str, hint: str) -> None:
    """Shared failure shape for template-vs-checkpoint tree diffs (raised by
    both the metadata pre-validation and the post-restore leaf check)."""
    if not (missing or mismatched):
        return

    def _clip(items):
        return ", ".join(items[:8]) + (" ..." if len(items) > 8 else "")

    raise ValueError(
        f"checkpoint at {where} does not match the restore template "
        f"({hint}): "
        + (f"{len(missing)} leaves missing: {_clip(missing)}; "
           if missing else "")
        + (f"{len(mismatched)} leaves mismatched: {_clip(mismatched)}"
           if mismatched else "")
    )


def check_restored_leaves(restored: Any, template: Any, *, where: str, hint: str) -> None:
    """Strict leaf-for-leaf validation of an orbax restore (VERDICT r4 weak
    #2c, shared by eval and serving hot-reload).

    Two silent orbax behaviors must fail LOUDLY here, not as an opaque
    TypeError later inside a jitted consumer:

    * missing checkpoint key -> the template leaf comes back UNRESTORED
      (still an abstract ``ShapeDtypeStruct``);
    * shape/dtype mismatch -> orbax ignores the template and hands back the
      CHECKPOINT's array (verified against orbax in-tree: a [2,H]
      twin-critic template restores a [H] single-critic checkpoint leaf
      without complaint).
    """
    missing, mismatched = [], []
    for (path, got), want in zip(
        jax.tree_util.tree_leaves_with_path(restored),
        jax.tree_util.tree_leaves(template),
    ):
        if isinstance(got, jax.ShapeDtypeStruct):
            missing.append(jax.tree_util.keystr(path))
        elif got.shape != want.shape or got.dtype != want.dtype:
            mismatched.append(
                f"{jax.tree_util.keystr(path)} (checkpoint "
                f"{got.dtype}{list(got.shape)} vs expected "
                f"{want.dtype}{list(want.shape)})"
            )
    _raise_tree_mismatch(missing, mismatched, where=where, hint=hint)


def abstract_template(tree: Any, *, sharding=None) -> Any:
    """Map a (concrete or ``eval_shape``) pytree to ``ShapeDtypeStruct``
    leaves with an explicit sharding — orbax warns that a restore without
    sharding info is unsafe across topologies (ADVICE r1)."""
    if sharding is None:
        sharding = jax.sharding.SingleDeviceSharding(jax.local_devices()[0])
    return jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(jnp.shape(l), l.dtype, sharding=sharding),
        tree,
    )


def restore_subtree(
    checkpoint_dir: str, item: Any, *, step: Optional[int] = None
) -> tuple:
    """Partial-restore ``item`` (an abstract-template tree keyed like the
    checkpoint, e.g. ``{"train": {"actor_params": tmpl}}``) from the latest
    (or given) step under ``checkpoint_dir``.  Returns ``(restored, step)``.

    Skipped keys are never read from disk, so the (potentially GBs of)
    replay arena costs nothing — this is what lets eval and the serving
    hot-reloader poll a live training run's dir cheaply.

    Version tolerance: orbax >= 0.9 spells partial restore
    ``PyTreeRestore(..., partial_restore=True)``; the 0.7 line (this box)
    only has the legacy ``transforms={}`` path, which additionally requires
    ``restore_args`` matching the result structure.  Feature-detect rather
    than pin — both resolve to the same on-disk reads.
    """
    # orbax rejects relative paths (CheckpointManager.__init__ does the same).
    mgr = ocp.CheckpointManager(os.path.abspath(checkpoint_dir))
    try:
        if step is None:
            step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {checkpoint_dir}"
            )
        sig = inspect.signature(ocp.args.PyTreeRestore.__init__)
        if "partial_restore" in sig.parameters:
            restore_args = ocp.args.PyTreeRestore(item, partial_restore=True)
        else:
            # The legacy transforms path is WORSE than silent about
            # mismatches: ArrayRestoreArgs pads/truncates to global_shape
            # and casts to dtype, so a wrong-net template would restore
            # "successfully" into garbage that the post-restore leaf check
            # cannot distinguish from real weights.  Validate the template
            # against the checkpoint's own metadata FIRST.
            _validate_item_against_metadata(
                os.path.abspath(checkpoint_dir), step, item
            )
            restore_args = ocp.args.PyTreeRestore(
                item=item,
                transforms={},
                restore_args=jax.tree_util.tree_map(
                    lambda l: ocp.ArrayRestoreArgs(
                        sharding=getattr(l, "sharding", None),
                        global_shape=l.shape,
                        dtype=l.dtype,
                    ),
                    item,
                ),
            )
        return mgr.restore(step, args=restore_args), step
    finally:
        mgr.close()


def _validate_item_against_metadata(
    checkpoint_dir: str, step: int, item: Any
) -> None:
    """Check an abstract restore template against the on-disk tree metadata
    (shapes/dtypes only — nothing is read into memory).  Raises the same
    style of ValueError as ``check_restored_leaves`` so callers get ONE
    failure mode for "this checkpoint is not the net you think it is"."""
    step_dir = os.path.join(checkpoint_dir, str(step), "default")
    if not os.path.isdir(step_dir):
        # Refuse rather than skip: on this (legacy) path a skipped check
        # would let ArrayRestoreArgs pad/cast a wrong-net template into
        # garbage the post-restore check cannot distinguish from weights.
        raise ValueError(
            f"checkpoint at {checkpoint_dir} (step {step}) has no "
            f"'default' item dir — layout this orbax version cannot "
            "partial-restore safely"
        )
    md = ocp.PyTreeCheckpointer().metadata(step_dir)

    def keymap(tree):
        # Normalize path entries to bare names so a dataclass template
        # (GetAttrKey ".actor_params") matches the checkpoint's dict
        # metadata (DictKey "['actor_params']") — orbax itself serializes
        # dataclass/namedtuple nodes as dicts keyed by field name.
        def names(path):
            out = []
            for p in path:
                for attr in ("key", "name", "idx"):
                    if hasattr(p, attr):
                        out.append(str(getattr(p, attr)))
                        break
                else:
                    out.append(str(p))
            return "/".join(out)

        return {
            names(path): leaf
            for path, leaf in jax.tree_util.tree_leaves_with_path(tree)
        }

    want, have = keymap(item), keymap(md)
    missing = [k for k in want if k not in have]
    mismatched = [
        f"{k} (checkpoint {have[k].dtype}{list(have[k].shape)} vs expected "
        f"{v.dtype}{list(v.shape)})"
        for k, v in want.items()
        if k in have
        and (tuple(have[k].shape) != tuple(v.shape) or have[k].dtype != v.dtype)
    ]
    _raise_tree_mismatch(
        missing,
        mismatched,
        where=f"{checkpoint_dir} (step {step})",
        hint="on-disk metadata pre-check",
    )


def resume_state(trainer, ckpt: CheckpointManager):
    """``trainer.init()`` overwritten by the latest checkpoint, env-corrected.

    For pure-JAX envs the restored state is returned as-is (bit-exact resume).
    For host-backed (``batched``) envs the host physics is gone, so the env
    slice of the state — env_state/obs/reset/carries/noise/episode_return and
    the assembler window — is taken fresh from ``trainer.init()`` while
    learner/replay/counters come from the checkpoint.

    Light checkpoints carry only the learner subtree: everything else
    (replay, window, env fleet, phase schedule) starts fresh and the
    warm-up/fill phases re-run — learning continues, experience restarts.
    """
    fresh = trainer.init()
    if ckpt.light:
        return dataclasses.replace(fresh, train=ckpt.restore(fresh))
    restored = ckpt.restore(fresh)
    if not getattr(trainer.env, "batched", False):
        return restored
    state = dataclasses.replace(
        restored,
        env_state=fresh.env_state,
        obs=fresh.obs,
        reset=fresh.reset,
        actor_carry=fresh.actor_carry,
        critic_carry=fresh.critic_carry,
        noise_state=fresh.noise_state,
        window=fresh.window,
        episode_return=fresh.episode_return,
    )
    # The zeroed window must re-fill with real steps before any sequence is
    # emitted, or zero-padded garbage would enter replay on the first
    # train_phase (which emits unconditionally).  collect_phase steps the
    # envs without emitting — exactly the initial warm-up, replayed here.
    for _ in range(trainer.window_fill_phases):
        state = trainer.collect_phase(state)
    return state
