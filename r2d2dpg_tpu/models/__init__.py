"""Actor/critic networks (SURVEY.md §2.1): MLP, LSTM carried-state, CNN torso."""

from r2d2dpg_tpu.models.actor_critic import (
    ActorNet,
    CriticNet,
    policy_step_fn,
    time_major,
    unroll,
    zeros_where_reset,
)
from r2d2dpg_tpu.models.torsos import ConvTorso, MLPTorso

__all__ = [
    "ActorNet",
    "ConvTorso",
    "CriticNet",
    "MLPTorso",
    "policy_step_fn",
    "time_major",
    "unroll",
    "zeros_where_reset",
]
