"""Sliding-window sequence assembler.

Reference parity: SURVEY.md §2.3 "Local sequence assembler" — the reference
actor keeps a sliding window over the episode and, every ``stride`` steps,
emits a fixed-length sequence with stored initial LSTM state; adjacent
sequences overlap by ``seq_len - stride`` (SURVEY §2.2: "adjacent sequences
overlap by half").

TPU-native: the window is a struct-of-arrays ``[num_envs, L, ...]`` device
buffer.  Each actor phase collects ``stride`` fresh steps (stacked scan
outputs), shifts them in with one concatenate, and the full window is emitted
as ``num_envs`` sequences — no Python-side deques, no per-step host work.
Episode boundaries are *not* special-cased at emission: the per-step
``reset`` flags ride inside the sequence and the learner's unroll re-zeroes
carries mid-sequence (SURVEY §7 hard part 2).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp

from r2d2dpg_tpu.replay.arena import SequenceBatch


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class StepRecord:
    """Per-step data recorded by the actor phase (leaves ``[..., E, ...]``).

    ``carries`` holds each net's recurrent state *before* processing
    ``obs`` — at emission, position 0's carries become the sequence's stored
    initial state (SURVEY §2.1: learner re-inits from stored state).
    """

    obs: jnp.ndarray
    action: jnp.ndarray
    reward: jnp.ndarray
    discount: jnp.ndarray
    reset: jnp.ndarray
    carries: Dict[str, Any]


def init_window(example: StepRecord, seq_len: int) -> StepRecord:
    """Zero window ``[E, L, ...]`` from a single-step example ``[E, ...]``."""

    def alloc(x):
        return jnp.zeros(x.shape[:1] + (seq_len,) + x.shape[1:], x.dtype)

    return jax.tree_util.tree_map(alloc, example)


def shift_in(window: StepRecord, fresh: StepRecord) -> StepRecord:
    """Append ``stride`` time-major fresh steps ``[S, E, ...]``, drop the oldest.

    ``fresh`` comes straight from ``lax.scan``'s stacked outputs (time-major);
    the window is batch-major, so each leaf is transposed then concatenated.
    """

    def upd(buf, new):
        new_bm = jnp.swapaxes(new, 0, 1)  # [S, E, ...] -> [E, S, ...]
        stride = new_bm.shape[1]
        return jnp.concatenate([buf[:, stride:], new_bm], axis=1)

    return jax.tree_util.tree_map(upd, window, fresh)


def emit(window: StepRecord) -> SequenceBatch:
    """The current window as a batch of sequences (one per env lane).

    Stored carries are the per-step carries at window position 0.
    """
    return SequenceBatch(
        obs=window.obs,
        action=window.action,
        reward=window.reward,
        discount=window.discount,
        reset=window.reset,
        carries=jax.tree_util.tree_map(lambda c: c[:, 0], window.carries),
    )
