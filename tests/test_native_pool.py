"""Native C++ env pool vs dm_control: trajectory-level parity.

The native pool (native/envpool/env_pool.cc) reimplements the suite tasks'
step/observation/reward logic against the MuJoCo C API.  These tests sync a
dm_control env's exact state (qpos, qvel, qacc_warmstart) into a native env
and drive both with identical action sequences: observations and rewards
must match to float32 round-off at every step, since both run the same
libmujoco with dm_control's legacy-step call sequence.
"""

import numpy as np
import pytest

from r2d2dpg_tpu.envs import native_pool

pytestmark = pytest.mark.slow


def _dmc_env(domain, task, seed=0):
    from dm_control import suite

    return suite.load(domain, task, task_kwargs={"random": seed})


def _flat(obs_dict):
    return np.concatenate(
        [np.asarray(v, np.float64).reshape(-1) for v in obs_dict.values()]
    )


def _sync_and_rollout(domain, task, steps, seed=0):
    """Returns (dmc_obs, dmc_rew, nat_obs, nat_rew) over a shared rollout."""
    env = _dmc_env(domain, task, seed)
    ts = env.reset()
    pool = native_pool.NativeEnvPool(domain, task, num_threads=1)
    pool.reset_all(np.asarray([seed]))
    pool.set_state(
        0,
        env.physics.data.qpos.copy(),
        env.physics.data.qvel.copy(),
        env.physics.data.qacc_warmstart.copy(),
    )

    spec = env.action_spec()
    rng = np.random.RandomState(seed + 1)
    dmc_obs, dmc_rew, nat_obs, nat_rew = [], [], [], []
    # First obs must already agree after the state sync.
    np.testing.assert_allclose(
        pool.obs_of(0), _flat(ts.observation).astype(np.float32), rtol=0, atol=0
    )
    for _ in range(steps):
        a = rng.uniform(spec.minimum, spec.maximum, spec.shape).astype(np.float32)
        ts = env.step(a)
        obs, rew, _, reset = pool.step_all(a[None])
        assert reset[0] == 0.0
        dmc_obs.append(_flat(ts.observation))
        dmc_rew.append(ts.reward)
        nat_obs.append(obs[0])
        nat_rew.append(rew[0])
    return (
        np.asarray(dmc_obs),
        np.asarray(dmc_rew),
        np.asarray(nat_obs),
        np.asarray(nat_rew),
    )


@pytest.mark.parametrize(
    "domain,task",
    [("walker", "walk"), ("cheetah", "run"), ("humanoid", "run")],
)
def test_trajectory_parity(domain, task):
    dmc_obs, dmc_rew, nat_obs, nat_rew = _sync_and_rollout(domain, task, steps=50)
    # Same libmujoco, same call sequence: float32 cast is the only noise.
    np.testing.assert_allclose(
        nat_obs, dmc_obs.astype(np.float32), rtol=1e-6, atol=1e-6
    )
    np.testing.assert_allclose(
        nat_rew, dmc_rew.astype(np.float32), rtol=1e-6, atol=1e-6
    )


def test_episode_limit_and_autoreset():
    pool = native_pool.NativeEnvPool("cheetah", "run", num_threads=1)
    obs0, _, _, reset0 = pool.reset_all(np.asarray([7]))
    assert reset0[0] == 1.0
    a = np.zeros((1, pool.action_dim), np.float32)
    for t in range(pool.episode_len):
        obs, _, discount, reset = pool.step_all(a)
        expected = 1.0 if t == pool.episode_len - 1 else 0.0
        assert reset[0] == expected, t
        assert discount[0] == 1.0  # suite tasks never terminate early
    # After auto-reset the next episode runs from a fresh randomized state.
    obs2, _, _, reset2 = pool.step_all(a)
    assert reset2[0] == 0.0


def test_reset_distribution_matches_dmc_rules():
    """Walker resets follow randomize_limited_and_rotational_joints rules:
    limited hinges uniform in range, the unlimited rooty hinge in [-pi, pi],
    slides untouched (= model default 0 for rootx; rootz stays at qpos0)."""
    import mujoco

    pool = native_pool.NativeEnvPool("walker", "walk", num_threads=1)
    pool.reset_all(np.arange(64))
    model = mujoco.MjModel.from_xml_path(native_pool._suite_xml("walker"))
    qpos0 = model.qpos0.copy()
    rooty_vals, limited_ok = [], True
    for i in range(64):
        qpos, _ = pool.get_state(i)
        for j in range(model.njnt):
            adr = model.jnt_qposadr[j]
            lo, hi = model.jnt_range[j]
            if model.jnt_limited[j]:
                limited_ok &= lo - 1e-9 <= qpos[adr] <= hi + 1e-9
            elif model.jnt_type[j] == mujoco.mjtJoint.mjJNT_HINGE:
                rooty_vals.append(qpos[adr])
            elif model.jnt_type[j] == mujoco.mjtJoint.mjJNT_SLIDE:
                assert qpos[adr] == qpos0[adr]
    assert limited_ok
    rooty = np.asarray(rooty_vals)
    assert rooty.min() < -1.0 and rooty.max() > 1.0  # spans [-pi, pi]
    assert np.abs(rooty).max() <= np.pi + 1e-9


def test_humanoid_reset_is_collision_free():
    pool = native_pool.NativeEnvPool("humanoid", "run", num_threads=1)
    obs, _, _, _ = pool.reset_all(np.arange(8))
    assert np.isfinite(obs).all()
    assert pool.obs_dim == 67


def test_threaded_pool_matches_serial():
    serial = native_pool.NativeEnvPool("walker", "walk", num_threads=1)
    threaded = native_pool.NativeEnvPool("walker", "walk", num_threads=4)
    so = serial.reset_all(np.arange(8))[0]
    to = threaded.reset_all(np.arange(8))[0]
    np.testing.assert_array_equal(so, to)
    rng = np.random.RandomState(0)
    for _ in range(10):
        a = rng.uniform(-1, 1, (8, serial.action_dim)).astype(np.float32)
        so = serial.step_all(a)
        to = threaded.step_all(a)
        for s, t in zip(so, to):
            np.testing.assert_array_equal(s, t)


def test_action_repeat_matches_serial_steps():
    """step_all(a, repeat=k) == k serial step_all(a) calls: same final state
    and obs, rewards summed."""
    single = native_pool.NativeEnvPool("walker", "walk", num_threads=1)
    repeated = native_pool.NativeEnvPool("walker", "walk", num_threads=1)
    single.reset_all(np.asarray([3, 4]))
    repeated.reset_all(np.asarray([3, 4]))
    rng = np.random.RandomState(1)
    for _ in range(5):
        a = rng.uniform(-1, 1, (2, single.action_dim)).astype(np.float32)
        rew_sum = np.zeros(2, np.float32)
        for _ in range(4):
            so, sr, _, s_reset = single.step_all(a)
            rew_sum += sr
            assert (s_reset == 0).all()
        ro, rr, _, r_reset = repeated.step_all(a, repeat=4)
        np.testing.assert_array_equal(ro, so)
        np.testing.assert_allclose(rr, rew_sum, rtol=1e-6)
        assert (r_reset == 0).all()


def test_action_repeat_stops_at_episode_boundary():
    """A repeat block straddling the step limit ends the episode exactly at
    the limit (no leakage of the stale action into the fresh episode)."""
    pool = native_pool.NativeEnvPool("cheetah", "run", num_threads=1)
    pool.reset_all(np.asarray([11]))
    a = np.zeros((1, pool.action_dim), np.float32)
    # Walk to 3 steps before the limit, then request repeat=5.
    for _ in range(pool.episode_len - 3):
        _, _, _, reset = pool.step_all(a)
        assert reset[0] == 0.0
    _, _, _, reset = pool.step_all(a, repeat=5)
    assert reset[0] == 1.0  # stopped at the boundary (3 steps), auto-reset
    # The fresh episode is at step 0: it should survive a full repeat block.
    _, _, _, reset = pool.step_all(a, repeat=5)
    assert reset[0] == 0.0
