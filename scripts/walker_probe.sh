#!/bin/bash
# Walker plateau probe (VERDICT r2 "Next round" #5): both CPU evidence runs
# flattened in the 160-250 band after ~300k steps at the 16-env / 1:20
# regime.  This drives one 85-min run per hypothesis at that same regime so
# the curves are directly comparable to runs/walker_cpu_r2 (251 @ 84 min,
# seed 0) and runs/walker_cpu_long (seed 2):
#
#   sigma08   — exploration-capped?   --sigma-max 0.8      (config: 0.4)
#   batch256  — gradient-noise-capped? --batch-size 256 --learner-steps 4
#               (same sampled frames/s as 64x16, 4x the batch)
#   nstep3    — bootstrap-horizon?    --n-step 3           (config: 5)
#   criticlr  — critic-speed-capped?  --critic-lr 2e-3     (config: 1e-3)
#
# Each probe is skipped when its final_eval.json exists, so this driver can
# be re-launched after the TPU campaign (whose VICTIMS list kills it — by
# design: on-chip evidence outranks CPU probes, and at most one partial
# probe is lost).  Waits politely while anything else owns the single core.
HERE="$(cd "$(dirname "$0")" && pwd)"
cd "$HERE/.."
mkdir -p runs
exec >> runs/walker_probe.log 2>&1

wait_for_box() {
  while pgrep -f "r2d2dpg_tpu\.(train|eval)" > /dev/null \
     || pgrep -f "tpu_campaign[0-9]*\.sh" > /dev/null; do
    sleep 60
  done
}

run_probe() {
  local name=$1; shift
  local dir="runs/walker_probe_$name"
  if [ -s "$dir/final_eval.json" ]; then
    echo "probe $name: already done, skipping $(date)"
    return
  fi
  wait_for_box
  echo "=== probe $name start ($*) $(date) ==="
  rm -rf "$dir"
  mkdir -p "$dir"
  nice -n 19 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu R2D2DPG_PALLAS_INTERPRET=1 \
  python -m r2d2dpg_tpu.train --config walker_r2d2 \
    --num-envs 16 --learner-steps 16 --batch-size 64 --min-replay 300 \
    "$@" \
    --seed 3 --minutes 85 --log-every 10 --eval-every 150 --eval-envs 5 \
    --logdir "$dir" --checkpoint-dir "$dir/ckpt" \
    --checkpoint-every 150 > "$dir/stdout.log" 2>&1
  echo "=== probe $name train done rc=$? $(date) ==="
  if [ -d "$dir/ckpt" ] && [ -n "$(ls "$dir/ckpt" 2>/dev/null)" ]; then
    wait_for_box
    timeout --kill-after=30 --signal=TERM 1800 \
      env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu R2D2DPG_PALLAS_INTERPRET=1 \
      python -m r2d2dpg_tpu.eval --config walker_r2d2 \
        --checkpoint-dir "$dir/ckpt" --episodes 10 --rounds 2 \
        > "$dir/final_eval.jsonl" 2> "$dir/final_eval.stderr.log" \
      && tail -1 "$dir/final_eval.jsonl" > "$dir/final_eval.json" \
      || echo "probe $name eval FAILED"
  else
    echo "probe $name: no checkpoint — skipping eval"
  fi
  echo "=== probe $name done $(date) ==="
}

# NB: batch256 keeps sampled frames/s constant (256x4 = 64x16) so the
# comparison isolates batch size from replay ratio.
run_probe sigma08   --sigma-max 0.8
run_probe batch256  --batch-size 256 --learner-steps 4
run_probe nstep3    --n-step 3
run_probe criticlr  --critic-lr 2e-3

echo "=== walker_probe all done $(date) ==="
