"""Full-phase throughput for the host-pool (DMC) training path.

VERDICT r1 weak #4: the learner-only bench overstates the system — the
north star is won or lost in the env pool.  This measures what actually
bounds wall-clock: complete ``train_phase`` rate (collect + emit + learner
updates) at walker_r2d2 shapes, in three modes:

1. ``collect``     — env stepping only (the pool ceiling).
2. ``sequential``  — classic phase: collect, then emit+learn at the end.
3. ``overlap``     — learner substeps interleaved between env steps
                     (TrainerConfig.overlap_learner): on a real TPU the
                     updates hide under the MuJoCo C step.

Prints one JSON line per row: the three modes above, plus one extra
``overlap_ls<K>`` row per requested extra density (4th argv) — on-chip
the learner is nearly free, so the question the extra rows answer is how
many interleaved updates per phase the rate sustains.  Runs on whatever
backend JAX resolves (TPU when the tunnel is up; CPU otherwise — on CPU
'overlap' cannot win since host and device share the single core; the
number that transfers is the TPU one).

Usage:
  python benchmarks/phase_throughput.py [num_envs] [phases] [learner_steps] \
      [extra_overlap_densities_csv]     # e.g. 64 12 48 192
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build(num_envs: int, learner_steps: int, overlap: bool):
    import jax

    from r2d2dpg_tpu.configs import WALKER_R2D2
    from r2d2dpg_tpu.parallel import make_mesh

    cfg = dataclasses.replace(
        WALKER_R2D2,
        trainer=dataclasses.replace(
            WALKER_R2D2.trainer,
            num_envs=num_envs,
            min_replay=num_envs * 2,
            learner_steps=learner_steps,
            overlap_learner=overlap,
        ),
    )
    return cfg.build_spmd(make_mesh(len(jax.devices())))


def measure(trainer, phases: int, mode: str) -> dict:
    import jax

    state = trainer.init()
    for _ in range(trainer.window_fill_phases):
        state = trainer.collect_phase(state)
    for _ in range(trainer.replay_fill_phases):
        state = trainer.fill_phase(state)

    step = (
        trainer.collect_phase
        if mode == "collect"
        else lambda s: trainer.train_phase(s)[0]
    )
    state = step(state)  # compile / warm
    jax.block_until_ready(state.obs)
    t0 = time.perf_counter()
    for _ in range(phases):
        state = step(state)
    jax.block_until_ready(state.train.step)
    dt = time.perf_counter() - t0

    cfg = trainer.config
    return {
        "metric": f"walker_phase_throughput_{mode}",
        "phases_per_sec": round(phases / dt, 3),
        "agent_steps_per_sec": round(phases * cfg.stride * cfg.num_envs / dt, 1),
        "learner_steps_per_sec": round(
            0 if mode == "collect" else phases * cfg.learner_steps / dt, 2
        ),
        "num_envs": cfg.num_envs,
        "stride": cfg.stride,
        "learner_steps_per_phase": cfg.learner_steps,
        "backend": jax.default_backend(),
    }


def main() -> None:
    num_envs = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    phases = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    learner_steps = int(sys.argv[3]) if len(sys.argv) > 3 else 4
    # Optional comma-separated EXTRA overlap densities (e.g. "192"): on-chip
    # the learner is ~free (15k steps/s), so the binding question for the
    # north star is how many interleaved updates the phase rate sustains —
    # each extra density adds one overlap row named overlap_ls<K>.
    extra_overlap = (
        [int(x) for x in sys.argv[4].split(",") if x]
        if len(sys.argv) > 4
        else []
    )

    t = build(num_envs, learner_steps, overlap=False)
    print(json.dumps(measure(t, phases, "collect")), flush=True)
    print(json.dumps(measure(t, phases, "sequential")), flush=True)
    t = build(num_envs, learner_steps, overlap=True)
    print(json.dumps(measure(t, phases, "overlap")), flush=True)
    for k in extra_overlap:
        t = build(num_envs, k, overlap=True)
        row = measure(t, phases, "overlap")
        row["metric"] = f"walker_phase_throughput_overlap_ls{k}"
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
