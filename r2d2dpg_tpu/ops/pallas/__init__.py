"""Pallas TPU kernels (SURVEY.md §2.9 build-side native components)."""

from r2d2dpg_tpu.ops.pallas.scatter import priority_scatter

__all__ = ["priority_scatter"]
