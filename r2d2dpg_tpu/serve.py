"""Serving entry point: ``python -m r2d2dpg_tpu serve --config ... --checkpoint-dir ...``

Stands up a ``PolicyService`` (serving/) over the latest checkpoint of a
training run and speaks newline-delimited JSON on stdio — dependency-free,
scriptable, and enough to drive the service from any language or a shell
pipe while the learner keeps writing new checkpoints into the same dir:

    {"session": "u1", "obs": [..], "reset": true}
        -> {"code": "ok", "action": [..], "params_step": 1500, "latency_ms": 1.9}
    {"cmd": "health"}        -> the HealthSnapshot as JSON
    {"cmd": "end_session", "session": "u1"}   -> {"code": "ok", "released": true}
    {"cmd": "quit"}          -> exits after draining

``--selftest N`` instead drives N synthetic requests through the full
stack (sessions x buckets x hot-reload poll) and prints the final health
snapshot — a one-command smoke of the serving path on any box.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

from r2d2dpg_tpu.configs import CONFIGS, get_config


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="python -m r2d2dpg_tpu serve", description=__doc__
    )
    p.add_argument("--config", required=True, choices=sorted(CONFIGS))
    p.add_argument(
        "--checkpoint-dir", required=True,
        help="training run's checkpoint dir; also watched for hot-reload"
    )
    p.add_argument(
        "--compute-dtype", default=None, choices=["float32", "bfloat16"],
        help="must match the checkpoint's train-time setting (the LSTM "
        "cell's param tree is dtype-specific)"
    )
    # Batching / latency knobs (docs/SERVING.md "Knobs").
    p.add_argument(
        "--bucket-sizes", default="1,2,4,8,16,32",
        help="comma-separated pad-to-bucket sizes (one compile each)"
    )
    p.add_argument(
        "--flush-ms", type=float, default=5.0,
        help="max time the batcher waits for stragglers before launching"
    )
    p.add_argument(
        "--max-queue", type=int, default=256,
        help="admission bound; beyond it requests shed with shed_queue_full"
    )
    # Scale-out (docs/SERVING.md "Scale-out").
    p.add_argument(
        "--serve-workers", type=int, default=1, metavar="N",
        help="worker services behind the session-affine router, one device "
        "each (forced host devices on CPU, one chip each on a real mesh); "
        "1 = the single-worker PolicyService path, no router (the "
        "off-setting determinism anchor)"
    )
    # Sessions.
    p.add_argument(
        "--max-sessions", type=int, default=1024,
        help="session-slab capacity PER WORKER"
    )
    p.add_argument(
        "--session-ttl", type=float, default=300.0,
        help="seconds of idleness before a session's slot is reclaimed"
    )
    # Hot-reload / observability.
    p.add_argument(
        "--poll-every", type=float, default=2.0,
        help="seconds between checkpoint-dir polls for new params"
    )
    p.add_argument("--logdir", default=None, help="health metrics CSV/TB dir")
    p.add_argument(
        "--log-every-s", type=float, default=10.0,
        help="seconds between health rows written to --logdir"
    )
    p.add_argument(
        "--obs-port", type=int, default=None, metavar="PORT",
        help="serve the telemetry registry over HTTP: /metrics (Prometheus "
        "text, incl. the r2d2dpg_serving_* health gauges) + /metrics.json; "
        "0 binds an ephemeral port (printed to stderr)"
    )
    p.add_argument(
        "--obs-host", default="0.0.0.0",
        help="interface the --obs-port exporter binds (127.0.0.1 = "
        "loopback-only on shared hosts)"
    )
    p.add_argument(
        "--flight-path", default=None,
        help="flight-recorder dump path (default <logdir>/flight.jsonl, "
        "or ./flight.jsonl without --logdir)"
    )
    p.add_argument(
        "--selftest", type=int, default=0, metavar="N",
        help="drive N synthetic requests through the service and exit"
    )
    return p.parse_args(argv)


def build_service(args):
    """Construct the serving front end from CLI flags.

    ``--serve-workers 1`` (the default) builds the single-worker
    ``PolicyService`` exactly as PR 1 did — no router in the path, which is
    what the off-setting determinism anchor pins.  ``--serve-workers N``
    replicates the service N times (one device, slab, batcher, and compiled
    step each) behind the session-affine ``ServiceRouter``.
    """
    from r2d2dpg_tpu.serving import (
        CheckpointHotReloader,
        PolicyService,
        build_router,
    )
    from r2d2dpg_tpu.serving.reload import actor_params_template
    from r2d2dpg_tpu.utils import MetricLogger

    cfg = get_config(args.config)
    if args.compute_dtype is not None:
        cfg = dataclasses.replace(cfg, compute_dtype=args.compute_dtype)
    env = cfg.env_factory()
    actor = cfg.build_agent(env).actor
    obs_shape = tuple(env.spec.obs_shape)

    reloader = CheckpointHotReloader(
        args.checkpoint_dir,
        actor_params_template(actor, obs_shape),
        poll_every_s=args.poll_every,
    )
    logger = MetricLogger(args.logdir) if args.logdir else None
    workers = int(getattr(args, "serve_workers", 1) or 1)
    if workers < 1:
        raise SystemExit(f"--serve-workers must be >= 1, got {workers}")
    if workers > 1:
        # No CSV MetricLogger in routed mode: N workers would interleave
        # rows in one file.  The labelled r2d2dpg_serve_* registry family
        # (scrape via --obs-port) and the flight recorder carry per-worker
        # telemetry instead.
        service = build_router(
            actor,
            num_workers=workers,
            obs_shape=obs_shape,
            reloader=reloader,
            bucket_sizes=[int(b) for b in args.bucket_sizes.split(",")],
            max_queue=args.max_queue,
            flush_ms=args.flush_ms,
            max_sessions=args.max_sessions,
            session_ttl_s=args.session_ttl,
        )
        return service, env
    service = PolicyService(
        actor,
        obs_shape=obs_shape,
        bucket_sizes=[int(b) for b in args.bucket_sizes.split(",")],
        max_queue=args.max_queue,
        flush_ms=args.flush_ms,
        max_sessions=args.max_sessions,
        session_ttl_s=args.session_ttl,
        reloader=reloader,
        logger=logger,
        log_every_s=args.log_every_s,
    )
    return service, env


def _health_dict(service) -> dict:
    """JSON-ready health: a PolicyService returns a dataclass snapshot, a
    ServiceRouter an aggregate dict (with per_worker snapshots) already."""
    snap = service.health()
    return snap if isinstance(snap, dict) else dataclasses.asdict(snap)


def _serve_stdio(service) -> None:
    """The JSONL request loop (one line in, one line out, order-preserving)."""
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            msg = json.loads(line)
        except json.JSONDecodeError as e:
            print(json.dumps({"code": "bad_request", "error": str(e)}), flush=True)
            continue
        if not isinstance(msg, dict):
            print(json.dumps({"code": "bad_request",
                              "error": "request must be a JSON object"}),
                  flush=True)
            continue
        cmd = msg.get("cmd")
        if cmd == "quit":
            break
        if cmd == "health":
            print(json.dumps(_health_dict(service)), flush=True)
            continue
        if cmd == "end_session":
            released = service.end_session(str(msg.get("session", "")))
            print(json.dumps({"code": "ok", "released": released}), flush=True)
            continue
        try:
            res = service.act(
                str(msg.get("session", "")),
                msg.get("obs", []),
                reset=bool(msg.get("reset", False)),
            )
            out = {"code": res.code, "params_step": res.params_step,
                   "latency_ms": round(res.latency_s * 1e3, 3)}
            if res.action is not None:
                out["action"] = [float(a) for a in res.action]
        except Exception as e:  # noqa: BLE001 — one bad payload (e.g.
            # non-numeric obs failing np.asarray) must answer THIS client,
            # not take the server and every live session down.
            out = {"code": "bad_request", "error": f"{type(e).__name__}: {e}"}
        print(json.dumps(out), flush=True)


def _selftest(service, obs_shape, n: int) -> None:
    """Drive n synthetic requests (8 interleaved sessions) and print health."""
    import numpy as np

    rng = np.random.default_rng(0)
    pending = []
    for i in range(n):
        sid = f"selftest-{i % 8}"
        pending.append(
            service.act_async(
                sid, rng.standard_normal(obs_shape).astype(np.float32),
                reset=(i < 8),
            )
        )
    codes: dict = {}
    for req in pending:
        req.wait(60.0)
        codes[req.code] = codes.get(req.code, 0) + 1
    print(json.dumps({"selftest": n, "codes": codes,
                      **_health_dict(service)}), flush=True)


def main(argv=None) -> None:
    args = parse_args(argv)
    import os

    import jax

    from r2d2dpg_tpu import obs

    flight_path = args.flight_path or (
        os.path.join(args.logdir, "flight.jsonl")
        if args.logdir
        else "flight.jsonl"
    )
    if args.logdir or args.flight_path:
        # Same gating as train.py: arm the exit-time dump only when the
        # operator named a destination.
        obs.get_flight_recorder().install(flight_path)
    if args.obs_port is not None:
        exporter = obs.start_exporter(args.obs_port, host=args.obs_host)
        # A serving process has no actor fleet: arm /health without the
        # fleet-telemetry expectation so the serve_* rules judge it alone.
        exporter.arm_health(
            obs.HealthEngine(obs.HealthConfig(telem_expected=False))
        )
        print(
            f"obs: /metrics + /metrics.json + /health on port {exporter.port}",
            file=sys.stderr,
            flush=True,
        )

    service, env = build_service(args)
    # Same backend stamp train.py prints — automation gates on it.
    print(f"backend: {jax.default_backend()}", file=sys.stderr, flush=True)
    with service:
        if args.selftest:
            _selftest(service, tuple(env.spec.obs_shape), args.selftest)
        else:
            _serve_stdio(service)


if __name__ == "__main__":
    main()
