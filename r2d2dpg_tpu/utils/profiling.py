"""Tracing / profiling and numeric-debug hooks (SURVEY.md §5.1–5.2).

Reference parity: the reference has no profiling or sanitizers beyond manual
timing prints (SURVEY §5.1).  The build wires the native JAX tooling:

- ``profile_trace(logdir)`` — ``jax.profiler.trace`` context manager; view
  with TensorBoard's profile plugin (installed in this image).  Wrap a few
  representative phases, not the whole run.
- ``nan_debug(True)`` — flips ``jax_debug_nans`` so any NaN produced inside
  a jitted computation raises at the op that made it (the build's answer to
  "sanitizers": there is no shared mutable host state by design — SURVEY
  §5.2 — so numeric poisoning is the failure mode worth a dedicated mode).
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def profile_trace(
    logdir: Optional[str], *, enabled: bool = True
) -> Iterator[None]:
    """Trace the enclosed block into ``logdir`` for the TB profile plugin."""
    if not enabled or logdir is None:
        yield
        return
    with jax.profiler.trace(logdir):
        yield


def nan_debug(enable: bool = True) -> None:
    """Raise-at-source on NaNs inside jitted code (debug runs only: it

    disables some fusions and forces extra device syncs)."""
    jax.config.update("jax_debug_nans", enable)


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Name a HOST-side region so it shows up in profiler timelines.

    Use around dispatch sites in driver loops (``Trainer.run``, the
    pipelined executor's collect/learn threads, the hybrid trainer's host
    loop): the annotation spans the host time of the block, which for
    host-driven collect is the real work.  For regions *inside* jitted
    code use ``scope`` instead — a TraceAnnotation under tracing would
    only mark trace time, not device time."""
    with jax.profiler.TraceAnnotation(name):
        yield


def scope(name: str):
    """Name a region of TRACED code: ops inside the block carry ``name`` in
    their HLO metadata, so the TB profiler timeline groups a fused phase's
    collect/emit/learn stages.  Safe under jit (this is ``jax.named_scope``);
    pairs with ``annotate`` which covers the host side."""
    return jax.named_scope(name)


@contextlib.contextmanager
def timed(window) -> Iterator[None]:
    """Time the enclosed block (seconds) into a ``PercentileWindow``.

    The pipelined executor's per-stage wait instrumentation: wrap the
    queue-blocking section of each stage and read p50/p99 plus the running
    total off the window (``utils.metrics.PercentileWindow`` or an
    ``obs.Histogram`` — anything with ``add``).  ``time`` is imported at
    module scope: this context manager runs inside per-stage hot loops and
    a per-call import was measurable overhead there."""
    t0 = time.monotonic()
    try:
        yield
    finally:
        window.add(time.monotonic() - t0)
