"""Learner-step tests: loss directions, target updates, priorities, burn-in
correctness (SURVEY.md §4.1 — "the §4.1 unit tests before anything learns")."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2dpg_tpu.agents import AgentConfig, R2D2DPG
from r2d2dpg_tpu.agents.ddpg import TrainState
from r2d2dpg_tpu.models import ActorNet, CriticNet
from r2d2dpg_tpu.replay.arena import SequenceBatch

B, OBS, ACT, HID = 4, 3, 2, 16


def make_agent(use_lstm=True, **kw):
    cfg = AgentConfig(
        burnin=kw.pop("burnin", 2 if use_lstm else 0),
        unroll=kw.pop("unroll", 3),
        n_step=kw.pop("n_step", 2),
        **kw,
    )
    actor = ActorNet(action_dim=ACT, hidden=HID, use_lstm=use_lstm)
    critic = CriticNet(hidden=HID, use_lstm=use_lstm)
    return R2D2DPG(actor, critic, cfg)


def make_batch(agent, key=0):
    L = agent.config.seq_len
    ks = jax.random.split(jax.random.PRNGKey(key), 3)
    carries = {
        "actor": agent.actor.initial_carry(B),
        "critic": agent.critic.initial_carry(B),
    }
    return SequenceBatch(
        obs=jax.random.normal(ks[0], (B, L, OBS)),
        action=jax.random.uniform(ks[1], (B, L, ACT), minval=-1, maxval=1),
        reward=jax.random.normal(ks[2], (B, L)),
        discount=jnp.ones((B, L)),
        reset=jnp.zeros((B, L)),
        carries=carries,
    )


@pytest.mark.parametrize("use_lstm", [True, False])
def test_learner_step_runs_and_updates(use_lstm):
    agent = make_agent(use_lstm)
    batch = make_batch(agent)
    state = agent.init(
        jax.random.PRNGKey(0), batch.obs[:, 0], batch.action[:, 0]
    )
    new_state, prios, metrics = jax.jit(agent.learner_step)(
        state, batch, jnp.ones(B)
    )
    assert int(new_state.step) == 1
    assert prios.shape == (B,)
    assert np.all(np.asarray(prios) > 0)
    # Params actually moved.
    moved = jax.tree_util.tree_map(
        lambda a, b: float(jnp.abs(a - b).max()),
        state.critic_params,
        new_state.critic_params,
    )
    assert max(jax.tree_util.tree_leaves(moved)) > 0
    for k in ("critic_loss", "actor_loss", "q_mean", "td_abs_mean"):
        assert np.isfinite(float(metrics[k])), k


def test_target_nets_polyak_not_copy():
    agent = make_agent(False, tau=0.5)
    batch = make_batch(agent)
    state = agent.init(jax.random.PRNGKey(0), batch.obs[:, 0], batch.action[:, 0])
    new_state, _, _ = agent.learner_step(state, batch, jnp.ones(B))
    # target' = tau*online' + (1-tau)*target, with target == old online.
    leaf = lambda t: jax.tree_util.tree_leaves(t)[0]  # noqa: E731
    want = 0.5 * leaf(new_state.critic_params) + 0.5 * leaf(state.critic_params)
    np.testing.assert_allclose(
        np.asarray(leaf(new_state.target_critic_params)),
        np.asarray(want),
        rtol=1e-5,
        atol=1e-6,
    )


def test_critic_loss_decreases_on_fixed_batch():
    """Repeated steps on one batch must reduce critic TD loss (sanity)."""
    agent = make_agent(False, critic_lr=1e-2, actor_lr=0.0, tau=0.0)
    batch = make_batch(agent)
    state = agent.init(jax.random.PRNGKey(0), batch.obs[:, 0], batch.action[:, 0])
    step = jax.jit(agent.learner_step)
    first = last = None
    for _ in range(50):
        state, _, metrics = step(state, batch, jnp.ones(B))
        if first is None:
            first = float(metrics["critic_loss"])
        last = float(metrics["critic_loss"])
    assert last < first * 0.5, (first, last)


def test_is_weights_scale_critic_gradient():
    agent = make_agent(False)
    batch = make_batch(agent)
    state = agent.init(jax.random.PRNGKey(0), batch.obs[:, 0], batch.action[:, 0])
    _, _, m1 = agent.learner_step(state, batch, jnp.ones(B))
    _, _, m2 = agent.learner_step(state, batch, jnp.zeros(B))
    assert float(m2["critic_loss"]) == 0.0
    assert float(m1["critic_loss"]) > 0.0


def test_burn_in_changes_outcome_only_for_lstm():
    """Burn-in must affect the training-window carries for LSTM nets."""
    agent = make_agent(True, burnin=4, unroll=2, n_step=1)
    batch = make_batch(agent)
    state = agent.init(jax.random.PRNGKey(0), batch.obs[:, 0], batch.action[:, 0])
    _, prios_a, _ = agent.learner_step(state, batch, jnp.ones(B))

    # Different burn-in prefix -> different warmed carries -> different TDs.
    obs2 = batch.obs.at[:, : agent.config.burnin].set(
        batch.obs[:, : agent.config.burnin] + 1.0
    )
    batch2 = SequenceBatch(
        obs=obs2,
        action=batch.action,
        reward=batch.reward,
        discount=batch.discount,
        reset=batch.reset,
        carries=batch.carries,
    )
    _, prios_b, _ = agent.learner_step(state, batch2, jnp.ones(B))
    assert not np.allclose(np.asarray(prios_a), np.asarray(prios_b))


def test_reset_inside_window_isolates_past():
    """A reset at window position t makes the LSTM ignore anything before t:
    two batches differing only before the reset yield identical TDs after it
    (SURVEY §7 hard part 2 — the classic silent-correctness bug)."""
    agent = make_agent(True, burnin=2, unroll=3, n_step=1)
    L = agent.config.seq_len
    base = make_batch(agent)
    reset = jnp.zeros((B, L)).at[:, 2].set(1.0)  # reset at start of window

    def with_obs(obs):
        return SequenceBatch(
            obs=obs,
            action=base.action,
            reward=base.reward,
            discount=base.discount,
            reset=reset,
            carries=base.carries,
        )

    state = agent.init(jax.random.PRNGKey(0), base.obs[:, 0], base.action[:, 0])
    obs_b = base.obs.at[:, :2].set(base.obs[:, :2] * 3.0 + 1.0)
    _, p1, _ = agent.learner_step(state, with_obs(base.obs), jnp.ones(B))
    _, p2, _ = agent.learner_step(state, with_obs(obs_b), jnp.ones(B))
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p2), rtol=1e-5)


def test_initial_priority_matches_learner_td():
    """initial_priority must equal the priority the learner would assign
    (same nets, same batch, before any update)."""
    agent = make_agent(True)
    batch = make_batch(agent)
    state = agent.init(jax.random.PRNGKey(0), batch.obs[:, 0], batch.action[:, 0])
    p_init = agent.initial_priority(state, batch)
    _, p_learn, _ = agent.learner_step(state, batch, jnp.ones(B))
    np.testing.assert_allclose(
        np.asarray(p_init), np.asarray(p_learn), rtol=1e-4, atol=1e-5
    )


def test_fused_burnin_matches_unfused():
    """The stacked-params fused burn-in must produce the same warmed carries
    (and hence the same learner step) as four separate unrolls."""
    fused = make_agent(use_lstm=True, burnin=4, fused_burnin=True)
    plain = make_agent(use_lstm=True, burnin=4, fused_burnin=False)
    batch = make_batch(fused, key=3)
    # Non-trivial stored carries + a mid-burnin reset row.
    h = jax.random.normal(jax.random.PRNGKey(9), (B, HID))
    batch = SequenceBatch(
        obs=batch.obs,
        action=batch.action,
        reward=batch.reward,
        discount=batch.discount,
        reset=batch.reset.at[1, 2].set(1.0),
        carries={"actor": (h, 0.5 * h), "critic": (-h, 0.25 * h)},
    )
    state = fused.init(jax.random.PRNGKey(0), batch.obs[:, 0], batch.action[:, 0])
    # Desync targets from online so fused/unfused disagreement would show.
    state = TrainState(
        actor_params=state.actor_params,
        critic_params=state.critic_params,
        target_actor_params=jax.tree_util.tree_map(
            lambda x: x + 0.1, state.actor_params
        ),
        target_critic_params=jax.tree_util.tree_map(
            lambda x: x - 0.1, state.critic_params
        ),
        actor_opt_state=state.actor_opt_state,
        critic_opt_state=state.critic_opt_state,
        step=state.step,
    )
    got = fused._burn_in(state, batch)
    want = plain._burn_in(state, batch)
    for g, w in zip(got, want):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
            g,
            w,
        )
    # And the full learner step agrees.
    w_is = jnp.ones((B,))
    _, p_f, m_f = fused.learner_step(state, batch, w_is)
    _, p_p, m_p = plain.learner_step(state, batch, w_is)
    np.testing.assert_allclose(np.asarray(p_f), np.asarray(p_p), rtol=1e-5)
    for k in m_f:
        np.testing.assert_allclose(float(m_f[k]), float(m_p[k]), rtol=1e-4)
