#!/usr/bin/env bash
# lint_fleet_wire.sh — no pickle on the fleet's SEQS/PARAMS steady-state
# paths (ISSUE 5 satellite).
#
# The tensor hot path (SEQS experience frames, PARAMS snapshot pushes,
# and the shard tier's SEQS/SAMPLE_REQ/BATCH/PRIO traffic — fleet/shard.py
# speaks the same codec on both of its legs, ISSUE 12) must go through
# the zero-copy codec in fleet/wire.py: pickle re-copies every tensor
# byte on both ends and executes arbitrary callables on load.  Control
# frames (HELLO/ACK/BYE — tiny trusted dicts) may keep pickle via
# transport.pack_obj/unpack_obj, but ONLY at call sites annotated
# `# wire-lint: control`, so every pickle crossing is an audited
# whitelist entry, not a drift risk.  The rules below scan ALL of
# r2d2dpg_tpu/fleet/ recursively, so a new fleet module (shard.py being
# the latest) is covered the day it lands.
#
# ISSUE 17 splits the fleet wire into control + data planes: the actor
# dials shard procs directly for SEQS, and the control connection grows
# a K_STATS accounting frame (tiny trusted dict — pickle-with-annotation
# like HELLO/ACK).  The recursive scans below already cover the new
# codec sites (actor.py's data-plane push, ingest.py's K_STATS branch);
# rule 3 pins the plane split itself: SEQS tensor frames must ride the
# zero-copy scatter sender on EVERY leg, whichever plane carries them.
#
# Rules:
#   1. The token `pickle` may appear in fleet/ only inside transport.py
#      (the control-frame codec's single home).
#   2. `pack_obj(` / `unpack_obj(` calls in fleet/ outside transport.py
#      must carry the `# wire-lint: control` annotation.
#   3. K_SEQS frames must be sent via `send_frame_parts` (zero-copy
#      parts), never the whole-buffer `send_frame` control sender — on
#      the forwarded ingest leg, the direct actor->shard data plane,
#      and the learner->shard forward leg alike.
#
# Wired into the test run via tests/test_transport.py::test_lint_fleet_wire.
set -euo pipefail
cd "$(dirname "$0")/.."

fail=0

# Actual pickle USAGE (imports and API calls), not prose mentions in
# comments/docstrings — the hazard is bytes crossing through pickle.
offenders=$(grep -rn -E \
    '(import +pickle|from +pickle|pickle\.(loads|dumps|load|dump|Pickler|Unpickler))' \
    r2d2dpg_tpu/fleet --include='*.py' \
    | grep -v '^r2d2dpg_tpu/fleet/transport\.py:' || true)
if [ -n "$offenders" ]; then
    echo "$offenders"
    echo "lint_fleet_wire: FAIL — pickle outside fleet/transport.py;" \
         "tensor payloads go through fleet/wire.py"
    fail=1
fi

offenders=$(grep -rn -E '(pack_obj|unpack_obj)\(' r2d2dpg_tpu/fleet \
    --include='*.py' \
    | grep -v '^r2d2dpg_tpu/fleet/transport\.py:' \
    | grep -v '# wire-lint: control' || true)
if [ -n "$offenders" ]; then
    echo "$offenders"
    echo "lint_fleet_wire: FAIL — un-annotated pack_obj/unpack_obj in" \
         "fleet/; SEQS/PARAMS must use fleet/wire.py (control frames:" \
         "annotate the call site with '# wire-lint: control')"
    fail=1
fi

# -z lets [^)]* span newlines, so a multi-line send_frame(...) call
# with K_SEQS anywhere in its argument list is still caught.
offenders=$(grep -rzl -E 'send_frame\([^)]*K_SEQS' r2d2dpg_tpu/fleet \
    --include='*.py' | tr '\0' '\n' \
    | grep -v '^r2d2dpg_tpu/fleet/transport\.py$' || true)
if [ -n "$offenders" ]; then
    echo "$offenders"
    echo "lint_fleet_wire: FAIL — K_SEQS sent through the whole-buffer" \
         "send_frame control sender; tensor frames must use" \
         "send_frame_parts on every plane (forwarded, direct, or" \
         "learner->shard forward leg)"
    fail=1
fi

[ "$fail" -eq 0 ] && echo "lint_fleet_wire: OK"
exit "$fail"
