"""Replay arena: ring overwrite, prioritized sampling distribution, priority
write-back via the Pallas kernel (interpret mode) — SURVEY.md §4.1/§4.5."""

import os

os.environ["R2D2DPG_PALLAS_INTERPRET"] = "1"  # exercise the kernel on CPU

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2dpg_tpu.replay import ReplayArena, SequenceBatch

L, OBS, ACT, HID = 4, 3, 2, 8


def make_batch(b, value=0.0):
    zeros = jnp.zeros((b, HID))
    return SequenceBatch(
        obs=jnp.full((b, L, OBS), value),
        action=jnp.zeros((b, L, ACT)),
        reward=jnp.arange(b, dtype=jnp.float32)[:, None] * jnp.ones((b, L)),
        discount=jnp.ones((b, L)),
        reset=jnp.zeros((b, L)),
        carries={"actor": (zeros, zeros), "critic": (zeros, zeros)},
    )


def test_add_and_size():
    arena = ReplayArena(capacity=10)
    state = arena.init_state(make_batch(2))
    assert int(arena.size(state)) == 0
    state = arena.add(state, make_batch(2), jnp.ones(2))
    assert int(arena.size(state)) == 2
    state = arena.add(state, make_batch(3), jnp.ones(3))
    assert int(arena.size(state)) == 5
    assert int(state.cursor) == 5


def test_ring_overwrite_fifo():
    arena = ReplayArena(capacity=4)
    state = arena.init_state(make_batch(1))
    for i in range(6):  # 6 adds into capacity 4 -> slots hold adds 2..5
        b = make_batch(1, value=float(i))
        state = arena.add(state, b, jnp.ones(1))
    obs_vals = np.asarray(state.data.obs)[:, 0, 0]
    # slot k holds add k for k in 4,5 (wrapped to 0,1) and 2,3 at slots 2,3
    np.testing.assert_allclose(sorted(obs_vals), [2.0, 3.0, 4.0, 5.0])
    assert int(arena.size(state)) == 4


def test_prioritized_sampling_distribution():
    """chi^2-style check: empirical sampling freq tracks p^alpha (SURVEY §4.1)."""
    arena = ReplayArena(capacity=4, alpha=1.0)
    state = arena.init_state(make_batch(4))
    prios = jnp.array([1.0, 2.0, 3.0, 6.0])
    state = arena.add(state, make_batch(4), prios)

    n_draws, bsz = 200, 64
    keys = jax.random.split(jax.random.PRNGKey(0), n_draws)
    sample = jax.jit(lambda s, k: arena.sample(s, k, bsz).indices)
    counts = np.zeros(4)
    for k in keys:
        idx, c = np.unique(np.asarray(sample(state, k)), return_counts=True)
        counts[idx] += c
    freq = counts / counts.sum()
    want = np.asarray(prios) / float(prios.sum())
    np.testing.assert_allclose(freq, want, atol=0.02)


def test_sample_probs_match_distribution():
    arena = ReplayArena(capacity=8, alpha=0.7)
    state = arena.init_state(make_batch(4))
    prios = jnp.array([0.5, 1.0, 2.0, 4.0])
    state = arena.add(state, make_batch(4), prios)
    res = arena.sample(state, jax.random.PRNGKey(1), 16)
    scaled = np.asarray(prios) ** 0.7
    want = scaled / scaled.sum()
    np.testing.assert_allclose(
        np.asarray(res.probs), want[np.asarray(res.indices)], rtol=1e-5
    )


def test_empty_slots_never_sampled():
    arena = ReplayArena(capacity=100)
    state = arena.init_state(make_batch(3))
    state = arena.add(state, make_batch(3), jnp.ones(3))
    res = arena.sample(state, jax.random.PRNGKey(2), 256)
    assert np.asarray(res.indices).max() < 3


def test_uniform_sampling():
    arena = ReplayArena(capacity=50, prioritized=False)
    state = arena.init_state(make_batch(10))
    state = arena.add(state, make_batch(10), jnp.ones(10))
    res = arena.sample(state, jax.random.PRNGKey(3), 512)
    idx = np.asarray(res.indices)
    assert idx.min() >= 0 and idx.max() < 10
    np.testing.assert_allclose(np.asarray(res.probs), 0.1, rtol=1e-6)


def test_priority_update_pallas_kernel():
    """update_priorities runs the Pallas kernel (interpret mode on CPU)."""
    arena = ReplayArena(capacity=8)
    state = arena.init_state(make_batch(4))
    state = arena.add(state, make_batch(4), jnp.ones(4))
    state = arena.update_priorities(
        state, jnp.array([0, 2]), jnp.array([5.0, 7.0])
    )
    np.testing.assert_allclose(
        np.asarray(state.priority)[:4], [5.0, 1.0, 7.0, 1.0], rtol=1e-5
    )


def test_priority_update_inside_jit():
    arena = ReplayArena(capacity=8)
    state = arena.init_state(make_batch(4))
    state = arena.add(state, make_batch(4), jnp.ones(4))

    @jax.jit
    def upd(s):
        return arena.update_priorities(s, jnp.array([1, 3]), jnp.array([9.0, 2.0]))

    s2 = upd(state)
    np.testing.assert_allclose(
        np.asarray(s2.priority)[:4], [1.0, 9.0, 1.0, 2.0], rtol=1e-5
    )


def _dp_arena_state(arena, batch, prios, mesh):
    """Place a fresh ArenaState on ``mesh`` with the dp-learner layout
    (data/priority capacity-sharded, cursor/total_added replicated) and
    add ``batch`` through the jitted staged path."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from r2d2dpg_tpu.parallel.mesh import DP_AXIS
    from r2d2dpg_tpu.replay.arena import ArenaState, StagedSequences

    dp = NamedSharding(mesh, P(DP_AXIS))
    rep = NamedSharding(mesh, P())
    state = jax.device_put(
        arena.init_state(batch),
        ArenaState(
            data=dp, priority=dp, cursor=rep, total_added=rep, meta=dp
        ),
    )
    add = jax.jit(arena.add_staged)
    return add(state, StagedSequences(seq=batch, priorities=prios))


def test_dp_sharded_add_staged_and_sample_match_dp1():
    """ISSUE 9: add_staged + sample on a dp=2 capacity-sharded arena give
    the SAME indices/probs/priorities as the dp=1 layout at the same seed
    — sharding is layout, never semantics.  Priorities are small integers
    so every cumsum association is exact."""
    from r2d2dpg_tpu.parallel import make_mesh

    arena = ReplayArena(capacity=16, alpha=1.0, use_pallas=False)
    prios = jnp.array([1.0, 2.0, 3.0, 6.0])
    key = jax.random.PRNGKey(9)
    results = {}
    for d in (1, 2):
        state = _dp_arena_state(arena, make_batch(4), prios, make_mesh(d))
        res = jax.jit(arena.sample, static_argnums=2)(state, key, 32)
        results[d] = jax.device_get(
            (res.indices, res.probs, state.priority, state.cursor)
        )
    for a, b in zip(results[1], results[2]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dp_sharded_arena_layout_and_per_shard_occupancy():
    """The dp=2 arena's storage really is capacity-sharded, and
    per_shard_occupancy counts each contiguous capacity block (= shard)."""
    from jax.sharding import PartitionSpec as P

    from r2d2dpg_tpu.parallel import make_mesh
    from r2d2dpg_tpu.parallel.mesh import DP_AXIS

    arena = ReplayArena(capacity=8, use_pallas=False)
    mesh = make_mesh(2)
    state = _dp_arena_state(arena, make_batch(3), jnp.ones(3), mesh)
    assert state.priority.sharding.spec == P(DP_AXIS)
    assert state.data.obs.sharding.spec == P(DP_AXIS)
    # 3 adds at cursor 0 -> all in shard 0's block (slots 0..3).
    np.testing.assert_array_equal(
        np.asarray(arena.per_shard_occupancy(state, 2)), [3, 0]
    )
    with pytest.raises(ValueError, match="divisible"):
        arena.per_shard_occupancy(state, 3)


# ------------------------------------------- sharded replay (ISSUE 10)
def _np_batch(b, start=0.0):
    return SequenceBatch(
        obs=np.zeros((b, L, OBS), np.float32),
        action=np.zeros((b, L, ACT), np.float32),
        reward=(start + np.arange(b, dtype=np.float32))[:, None]
        * np.ones((b, L), np.float32),
        discount=np.ones((b, L), np.float32),
        reset=np.zeros((b, L), np.float32),
        carries={},
    )


def test_two_level_sharded_sampling_matches_central_distribution():
    """ISSUE 10 fidelity anchor: exact-integer priorities spread over 2
    shards, alpha=1 — the two-level draw (shards ∝ Σp, within-shard
    proportional) and the central ``ReplayArena.sample`` converge to the
    SAME p/Σp distribution over many draws, and the combined two-level
    probabilities equal the central per-draw probabilities exactly."""
    from r2d2dpg_tpu.replay.sharded import (
        ReplayShard,
        combine_probs,
        shard_quotas,
    )

    prios = np.array([1.0, 2.0, 3.0, 6.0], np.float64)
    # Central reference: empirical frequency from the device arena.
    arena = ReplayArena(capacity=4, alpha=1.0)
    state = arena.init_state(make_batch(4))
    state = arena.add(state, make_batch(4), jnp.asarray(prios))
    n_draws, bsz = 200, 64
    sample = jax.jit(lambda s, k: arena.sample(s, k, bsz).indices)
    central = np.zeros(4)
    for k in jax.random.split(jax.random.PRNGKey(0), n_draws):
        idx, c = np.unique(np.asarray(sample(state, k)), return_counts=True)
        central[idx] += c
    central /= central.sum()

    # Sharded: priorities 1,2 on shard 0 and 3,6 on shard 1; reward row
    # value identifies the slot globally.
    shards = [ReplayShard(4, alpha=1.0, shard_id=i) for i in range(2)]
    shards[0].add(_np_batch(2, start=0.0), prios[:2])
    shards[1].add(_np_batch(2, start=2.0), prios[2:])
    rng = np.random.default_rng(1)
    sums = np.array([s.scaled_sum() for s in shards])
    total = float(sums.sum())
    counts = np.zeros(4)
    for _ in range(n_draws):
        quotas = shard_quotas(sums, bsz, rng)
        for sid, q in enumerate(quotas):
            if q == 0:
                continue
            s = shards[sid].sample(int(q), rng)
            keys = s.seq.reward[:, 0].astype(int)
            np.testing.assert_allclose(  # combined == central p/Σ, exact
                combine_probs(s.probs, float(sums[sid]), total),
                prios[keys] / prios.sum(),
                rtol=1e-12,
            )
            np.add.at(counts, keys, 1)
    sharded = counts / counts.sum()
    want = prios / prios.sum()
    np.testing.assert_allclose(sharded, want, atol=0.02)
    np.testing.assert_allclose(central, want, atol=0.02)
    np.testing.assert_allclose(sharded, central, atol=0.03)


def test_shard_priority_write_back_roundtrip_and_stale_version_ignored():
    """Write-back is keyed (slot, generation): a verdict about a
    sequence the ring has since evicted must NOT clobber the newer
    occupant's priority — stale versions are ignored, like param
    regressions (docs/REPLAY.md 'Write-back versioning')."""
    from r2d2dpg_tpu.replay.sharded import ReplayShard

    s = ReplayShard(4, alpha=1.0)
    s.add(_np_batch(4), np.array([1.0, 1.0, 1.0, 1.0]))
    rng = np.random.default_rng(0)
    sam = s.sample(4, rng)
    # Fresh handles: every entry applies; the sum moves accordingly.
    applied = s.update_priorities(
        sam.slots, sam.gens, np.full(4, 3.0)
    )
    assert applied == 4
    hit = np.unique(sam.slots)
    assert s.priority_sum() == 3.0 * len(hit) + 1.0 * (4 - len(hit))
    # Overwrite two slots (ring wrap bumps their generations) …
    before = s.sample(4, rng)  # handles from the OLD generation
    s.add(_np_batch(2, start=10.0), np.array([2.0, 2.0]))
    psum = s.priority_sum()
    # … a stale write-back touches only the un-overwritten slots.
    stale_mask = np.isin(before.slots, [0, 1])
    applied = s.update_priorities(
        before.slots, before.gens, np.full(4, 100.0)
    )
    assert applied == int((~stale_mask).sum())
    # The overwritten slots' fresh 2.0 priorities survived untouched.
    assert s._priority[0] == 2.0 and s._priority[1] == 2.0
    if stale_mask.all():
        assert s.priority_sum() == psum


def test_shard_ring_eviction_semantics():
    """The shard ring is FIFO over capacity: occupancy caps, the oldest
    rows are the evicted ones, generations bump per overwrite, and
    total_added stays monotone (the 'a dead shard loses only
    re-collectable experience' accounting base)."""
    from r2d2dpg_tpu.replay.sharded import ReplayShard

    s = ReplayShard(4, alpha=1.0)
    for i in range(6):  # 6 adds into capacity 4 -> rows 2..5 survive
        s.add(_np_batch(1, start=float(i)), np.array([1.0]))
    assert s.occupancy() == 4 and s.total_added == 6
    rows = sorted(s._data.reward[:, 0].tolist())
    assert rows == [2.0, 3.0, 4.0, 5.0]
    # Slots 0,1 were written twice (generation 2), 2,3 once.
    np.testing.assert_array_equal(s._generation, [2, 2, 1, 1])
    # None priorities enter at the shard max (the central "max" entry
    # semantics) with floor 1.0.
    s.update_priorities(np.array([2]), np.array([1]), np.array([7.0]))
    s.add(_np_batch(1, start=9.0), None)
    assert s._priority[2] == 7.0  # untouched slot keeps its rank
    assert s._priority[s._cursor - 1] == 7.0  # new row entered at max
    # An empty shard refuses to sample (quotas never route draws there).
    import pytest as _pytest

    empty = ReplayShard(2, alpha=1.0)
    with _pytest.raises(ValueError, match="empty"):
        empty.sample(1, np.random.default_rng(0))


def test_degraded_two_level_sampling_over_surviving_subset():
    """ISSUE 12 satellite: the degraded-sampling math.  With a dead
    shard advertising Σp^α = 0 (or simply absent), ``shard_quotas`` over
    the SURVIVING subset is still a valid distribution (non-negative,
    sums to n, zero draws for the dead shard), and the two-level draw
    restricted to survivors matches central proportional sampling
    restricted to the surviving slots — on exact-integer priorities, the
    combined probabilities are exactly ``p / Σ_survivors``."""
    from r2d2dpg_tpu.replay.sharded import (
        ReplayShard,
        combine_probs,
        shard_quotas,
    )

    prios = np.array([1.0, 2.0, 4.0, 8.0, 5.0, 3.0], np.float64)
    shards = [ReplayShard(4, alpha=1.0, shard_id=i) for i in range(3)]
    shards[0].add(_np_batch(2, start=0.0), prios[:2])
    shards[1].add(_np_batch(2, start=2.0), prios[2:4])  # the dead one
    shards[2].add(_np_batch(2, start=4.0), prios[4:])
    # Shard 1 dies: its advertised weight is ZERO (exactly what
    # RemoteShardSet.scaled_sums reports for a dead shard).
    sums = np.array(
        [shards[0].scaled_sum(), 0.0, shards[2].scaled_sum()], np.float64
    )
    total = float(sums.sum())
    surviving = np.array([1.0, 2.0, 5.0, 3.0])  # shards 0 and 2's slots
    rng = np.random.default_rng(5)
    counts: dict = {}
    n_rounds, per_round = 250, 32
    for _ in range(n_rounds):
        quotas = shard_quotas(sums, per_round, rng)
        assert quotas.sum() == per_round and (quotas >= 0).all()
        assert quotas[1] == 0  # a dead shard NEVER receives draws
        for sid, q in enumerate(quotas):
            if q == 0:
                continue
            s = shards[sid].sample(int(q), rng)
            keys = s.seq.reward[:, 0].astype(int)
            # Combined probability == central proportional RESTRICTED to
            # the surviving slots, exactly (integer priorities).
            np.testing.assert_allclose(
                combine_probs(s.probs, float(sums[sid]), total),
                prios[keys] / surviving.sum(),
                rtol=1e-12,
            )
            for k in keys:
                counts[int(k)] = counts.get(int(k), 0) + 1
    assert set(counts) <= {0, 1, 4, 5}  # no draw from the dead shard
    freq = np.array(
        [counts.get(k, 0) for k in (0, 1, 4, 5)], np.float64
    ) / (n_rounds * per_round)
    np.testing.assert_allclose(freq, surviving / surviving.sum(), atol=0.02)
    # An all-dead tier is a caller error, loudly (the sampler WAITS on
    # this instead of fabricating draws).
    import pytest as _pytest

    with _pytest.raises(ValueError, match="empty"):
        shard_quotas([0.0, 0.0, 0.0], 4, np.random.default_rng(0))


def test_ring_wrap_eviction_counter_counts():
    """ISSUE 12 satellite: FIFO eviction (which replaced shedding in
    PR 10) leaves a trace — ``evictions_total`` counts exactly the
    FILLED slots the ring overwrote, and the ``evict_cb`` hook (the obs
    counter's rider) sees the same numbers under the same add lock."""
    from r2d2dpg_tpu.replay.sharded import ReplayShard

    seen = []
    s = ReplayShard(4, alpha=1.0, evict_cb=seen.append)
    s.add(_np_batch(3), np.ones(3))
    assert s.evictions_total == 0 and seen == []  # filling, not evicting
    # Wrap: slots 3,0,1 — slot 3 was still EMPTY, 0 and 1 were filled.
    s.add(_np_batch(3, start=10.0), np.ones(3))
    assert s.evictions_total == 2 and seen == [2]
    # Full ring: every further add evicts its whole width.
    s.add(_np_batch(4, start=20.0), np.ones(4))
    assert s.evictions_total == 6 and seen == [2, 4]
    assert s.occupancy() == 4 and s.total_added == 10


def test_sampled_batch_contents_roundtrip():
    arena = ReplayArena(capacity=16)
    state = arena.init_state(make_batch(4))
    state = arena.add(state, make_batch(4), jnp.array([1e9, 1e-6, 1e-6, 1e-6]))
    res = arena.sample(state, jax.random.PRNGKey(0), 8)
    # Overwhelming priority on slot 0 -> nearly all samples are slot 0 with reward row 0.
    assert (np.asarray(res.indices) == 0).mean() > 0.9
    row0 = np.asarray(res.batch.reward)[np.asarray(res.indices) == 0]
    np.testing.assert_allclose(row0, 0.0)
