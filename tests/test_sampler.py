"""In-network experience sampling (ISSUE 10): sharded replay at the
ingest edge, learner-pulled batches (fleet/sampler.py + replay/sharded.py).

Anchors ``scripts/lib_gate.sh sampler_gate`` enforces before blessing
``--replay-shards N`` evidence dirs:

- **determinism** — ``--replay-shards 1 --actors 0`` routes the untouched
  phase-locked loop, pinned BIT-identical to ``Trainer.run`` end to end
  through the train.py CLI (docs/REPLAY.md "Determinism anchor").
- **equivalence** — the two-level draw (shards ∝ Σp^α, within-shard
  proportional) through the REAL SAMPLE_REQ/BATCH frame codecs matches
  the central proportional distribution on exact-integer priorities.
"""

import queue
import threading

import jax
import numpy as np
import pytest

from r2d2dpg_tpu.configs import PENDULUM_TINY
from r2d2dpg_tpu.fleet import (
    FleetConfig,
    SamplerLearner,
    ShardSet,
    shard_for_actor,
    transport,
    wire,
)
from r2d2dpg_tpu.fleet.ingest import IngestServer
from r2d2dpg_tpu.fleet.transport import (
    K_ACK,
    K_HELLO,
    K_SEQS,
    pack_hello,
    recv_frame,
    send_frame,
    send_frame_parts,
    unpack_obj,
)
from r2d2dpg_tpu.replay.arena import SequenceBatch, StagedSequences
from r2d2dpg_tpu.replay.sharded import shard_quotas
from r2d2dpg_tpu.utils.codes import OK

pytestmark = pytest.mark.sampler

N_TRAIN = 6
LOG_EVERY = 2


def _leaves_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return [
        i
        for i, (x, y) in enumerate(zip(la, lb))
        if not np.array_equal(np.asarray(x), np.asarray(y))
    ]


def _np_staged(b=2, l=3, prios=None):
    rng = np.random.default_rng(1)
    return StagedSequences(
        seq=SequenceBatch(
            obs=rng.normal(size=(b, l, 3)).astype(np.float32),
            action=rng.normal(size=(b, l, 1)).astype(np.float32),
            reward=rng.normal(size=(b, l)).astype(np.float32),
            discount=np.ones((b, l), np.float32),
            reset=np.zeros((b, l), np.float32),
            carries={},
        ),
        priorities=prios,
    )


# ------------------------------------------------------- determinism anchor
def test_replay_shards_off_determinism_bit_identical(
    tmp_path, phase_locked_reference_k6
):
    """--replay-shards 1 --actors 0 == the untouched phase-locked
    Trainer.run, leaf-for-leaf bitwise, end to end through the train.py
    CLI (parse -> guards -> loop -> final checkpoint) — the sampler_gate
    anchor: wiring the knob in changes no bit of the default schedule.
    The reference half is the shared session fixture (tests/conftest.py)
    — the pairing assert keeps it honest."""
    from r2d2dpg_tpu import train
    from r2d2dpg_tpu.utils import CheckpointManager
    from r2d2dpg_tpu.utils.checkpoint import resume_state

    assert (N_TRAIN, LOG_EVERY) == (6, 2)  # the k6 fixture's recipe
    s1 = phase_locked_reference_k6

    train.run(
        train.parse_args(
            [
                "--config", "pendulum_tiny",
                "--actors", "0",
                "--replay-shards", "1",
                # The ISSUE 12 off-setting rides the same anchor: 0 = the
                # in-learner loopback, which must add NOTHING to the run
                # (scripts/lib_gate.sh shard_gate enforces this pin).
                "--shard-procs", "0",
                # The ISSUE 17 off-settings ride it too: --shard-direct 0
                # keeps the learner-forwarded experience path and the
                # serial pull loop, BIT-identical to the run with the
                # flags absent (the direct data plane's fallback IS this
                # path, so the pin is also the fallback's correctness).
                "--shard-direct", "0",
                "--shard-pullers", "0",
                "--shard-prefetch", "0",
                "--phases", str(N_TRAIN),
                "--log-every", str(LOG_EVERY),
                "--checkpoint-dir", str(tmp_path / "ckpt"),
                "--checkpoint-every", "-1",
                "--watchdog", "0",
            ]
        )
    )
    t2 = PENDULUM_TINY.build()
    s2 = resume_state(
        t2, CheckpointManager(str(tmp_path / "ckpt"), save_every=-1)
    )
    bad = _leaves_equal(s1, s2)
    assert not bad, f"state diverged at leaves {bad}"


# ---------------------------------------------------- sampling equivalence
def test_two_level_frame_path_sampling_equivalence():
    """The sampling-equivalence anchor: draws through the FULL sampler
    machinery — ShardSet routing, SAMPLE_REQ/BATCH codec roundtrips,
    two-level quotas, combined probabilities — reproduce the central
    proportional distribution ``p^alpha / sum`` on exact-integer
    priorities, and the combined probs are exactly the central ones."""
    from r2d2dpg_tpu.replay.sharded import combine_probs

    prios = np.array([1.0, 2.0, 3.0, 6.0, 4.0, 8.0], np.float64)
    shards = ShardSet(2, 8, alpha=1.0, prioritized=True)
    # Rows land per shard: shard 0 gets [1,2,3], shard 1 gets [6,4,8] —
    # reward row value identifies the slot globally.
    for shard_id, block in ((0, prios[:3]), (1, prios[3:])):
        seq = _np_staged(b=3).seq
        seq = SequenceBatch(
            obs=seq.obs,
            action=seq.action,
            reward=np.repeat(
                block.astype(np.float32)[:, None], seq.reward.shape[1], 1
            ),
            discount=seq.discount,
            reset=seq.reset,
            carries={},
        )
        shards.shards[shard_id].add(seq, block)

    packer = wire.TreePacker(wire.WireConfig())
    unpacker = wire.TreeUnpacker()
    rng = np.random.default_rng(0)
    counts: dict = {}
    n_rounds, per_round = 300, 32
    total = float(shards.scaled_sums().sum())
    for _ in range(n_rounds):
        quotas = shard_quotas(shards.scaled_sums(), per_round, rng)
        for shard_id, quota in enumerate(quotas):
            if quota == 0:
                continue
            req = wire.unpack_sample_req(
                unpacker.unpack(
                    b"".join(
                        bytes(p)
                        for p in wire.pack_sample_req(
                            packer, req_id=1, shard=shard_id, quota=int(quota)
                        )
                    )
                )
            )
            shard = shards.shards[req["shard"]]
            s = shard.sample(req["quota"], rng)
            resp = wire.unpack_shard_batch(
                unpacker.unpack(
                    b"".join(
                        bytes(p)
                        for p in wire.pack_shard_batch(
                            packer,
                            req_id=1,
                            shard=shard_id,
                            staged=StagedSequences(seq=s.seq, priorities=None),
                            slots=s.slots,
                            gens=s.gens,
                            probs=s.probs,
                            priority_sum=shard.scaled_sum(),
                            occupancy=shard.occupancy(),
                        )
                    )
                )
            )
            # Combined two-level probability == central p/sum, exactly
            # (integer priorities: no float reassociation headroom).
            got = combine_probs(
                resp["probs"], shards.shards[shard_id].scaled_sum(), total
            )
            keys = resp["staged"].seq.reward[:, 0].astype(np.float64)
            np.testing.assert_allclose(got, keys / prios.sum(), rtol=1e-12)
            for k in keys:
                counts[float(k)] = counts.get(float(k), 0) + 1
    draws = n_rounds * per_round
    freq = np.array([counts.get(float(p), 0) / draws for p in prios])
    np.testing.assert_allclose(freq, prios / prios.sum(), atol=0.02)


def test_shard_quotas_and_routing():
    rng = np.random.default_rng(3)
    q = shard_quotas([0.0, 2.0, 6.0], 1000, rng)
    assert q.sum() == 1000 and q[0] == 0  # empty shards get no draws
    np.testing.assert_allclose(q[2] / 1000, 0.75, atol=0.05)
    with pytest.raises(ValueError, match="empty"):
        shard_quotas([0.0, 0.0], 8, rng)
    # Routing is a pure consistent hash: stable per actor id, in range,
    # identical across calls (a reconnecting actor keeps its shard).
    for n in (1, 2, 5):
        for a in range(8):
            r = shard_for_actor(a, n)
            assert 0 <= r < n and r == shard_for_actor(a, n)
    assert shard_for_actor("7", 4) == shard_for_actor(7, 4)  # HELLO strs


# ----------------------------------------------------------- ingest routing
def test_ingest_routes_seqs_into_shards_and_never_sheds():
    """Sharded mode: SEQS go straight to the actor's shard (no staging
    queue), acks are ALWAYS ok (ring eviction replaces shedding — more
    batches than a queue could hold are absorbed without one shed), and
    the accounting deltas land in the bank."""
    shards = ShardSet(2, 8, alpha=0.6)
    q: queue.Queue = queue.Queue(maxsize=1)  # would overflow after 1
    srv = IngestServer(q, address="127.0.0.1:0", shards=shards)
    srv.start()
    try:
        sock = transport.connect(srv.address)
        sock.settimeout(10)
        packer = wire.TreePacker(wire.WireConfig())
        send_frame(
            sock,
            K_HELLO,
            pack_hello(
                {"actor_id": 5, **wire.negotiation_fields(wire.WireConfig())}
            ),
        )
        recv_frame(sock)  # hello ack
        for phase in range(6):  # 6 batches past a depth-1 queue: no sheds
            send_frame_parts(
                sock,
                K_SEQS,
                packer.pack(
                    {
                        "phase": phase,
                        "param_version": 0,
                        "env_steps_delta": 8.0,
                        "ep_return_sum": -1.0,
                        "ep_count": 1.0,
                        "staged": _np_staged(
                            prios=np.array([1.0, 2.0], np.float32)
                        ),
                    }
                ),
            )
            kind, payload = recv_frame(sock)
            assert kind == K_ACK and unpack_obj(payload)["code"] == OK
        sock.close()
        assert srv.shed_total == 0 and q.qsize() == 0
        target = shards.route("5")
        assert shards.shards[target].total_added == 12
        assert shards.shards[1 - target].total_added == 0
        assert shards.shards[target].occupancy() == 8  # ring capped
        stats = shards.pop_stats()
        assert stats["env_steps_delta"] == 48.0 and stats["ep_count"] == 6.0
        assert shards.pop_stats()["env_steps_delta"] == 0.0  # drained
    finally:
        srv.stop()


# ------------------------------------------------------------- learner e2e
def test_sampler_learner_end_to_end_thread_actor():
    """A real FleetActor streaming into a 2-shard sampler learner: the
    run completes its exact step schedule, only sampled sequences cross
    the sampling boundary (bytes accounted), priorities get written back
    (the fed shard's priority sum moves off the actor's initial ranks),
    and nothing sheds."""
    from r2d2dpg_tpu.fleet.actor import FleetActor

    trainer = PENDULUM_TINY.build()
    learner = SamplerLearner(
        trainer,
        FleetConfig(num_actors=1, idle_timeout_s=60),
        num_shards=2,
    )
    address = learner.start()
    actor = FleetActor(
        PENDULUM_TINY, actor_id=0, num_actors=1, address=address, seed=0
    )

    def actor_loop():
        try:
            # Unpaced on purpose: sampler-mode acks never block (ring
            # eviction replaces backpressure), so a phase-capped actor
            # would sprint through its budget during the learner's
            # compile and exit before the run ends — stream until the
            # server teardown cuts the socket.
            actor.run()
        except Exception:  # noqa: BLE001 — server teardown cuts the socket
            pass

    thread = threading.Thread(target=actor_loop, daemon=True)
    thread.start()
    logged = []
    try:
        state = learner.run(
            N_TRAIN,
            log_every=LOG_EVERY,
            metrics_fn=lambda p, s: logged.append((p, dict(s))),
        )
    finally:
        learner.close()
        thread.join(timeout=30)
    tc = trainer.config
    assert int(state.train.step) == N_TRAIN * tc.learner_steps
    stats = learner.stats()
    assert stats["train_phases"] == N_TRAIN
    assert stats["sheds"] == 0
    # Eviction visibility (ISSUE 12 satellite): the stats row carries the
    # ring-overwrite count (0 here — capacity exceeds the run's traffic).
    assert "evictions" in stats and stats["evictions"] >= 0
    n_draws = N_TRAIN * tc.learner_steps * tc.batch_size
    assert stats["trained_seqs"] == n_draws
    assert stats["replay_occupancy"] >= tc.min_replay
    # The sampling boundary carried REQ+BATCH+PRIO for exactly the
    # trained draws — orders of magnitude under the collected stream.
    assert 0 < stats["bytes_per_trained_seq"] < stats["seqs_bytes_total"]
    assert stats["sample_bytes_total"] < stats["seqs_bytes_total"]
    assert [p for p, _ in logged] == [
        p for p in range(1, N_TRAIN + 1) if p % LOG_EVERY == 0
    ]
    for _, scalars in logged:
        assert "env_steps" in scalars and "learner_steps" in scalars
    # env-step accounting stayed monotone through the bank.
    env_steps = [s["env_steps"] for _, s in logged]
    assert env_steps == sorted(env_steps) and env_steps[-1] > 0


@pytest.mark.slow
def test_sampler_learner_checkpoint_resume_in_process(tmp_path):
    """The recovery contract (docs/REPLAY.md): run 4 pull phases with
    periodic checkpoints, abandon the learner, resume a FRESH one from
    the checkpoint + counter sidecar — it re-enters the absorb gate
    (shards are never checkpointed; live actors refill them), completes
    the TOTAL 8-phase target, and every counter continues monotone.

    Slow-marked (ISSUE 12): two full sampler incarnations = two learn
    program compiles, ~1 min of the tier-1 budget — the same recovery
    soak class as the fleet kill/resume soaks, which are slow-marked for
    the same reason.  The in-process recovery machinery it drills
    (sidecar roundtrip, absorb re-entry) is also covered non-slow by the
    FleetLearner checkpoint/resume tests riding the shared code path."""
    from r2d2dpg_tpu.fleet import load_fleet_counters
    from r2d2dpg_tpu.fleet.actor import FleetActor
    from r2d2dpg_tpu.utils import CheckpointManager

    ckpt_dir = str(tmp_path / "ckpt")

    def sampler_run(n_total, resume):
        trainer = PENDULUM_TINY.build()
        learner = SamplerLearner(
            trainer,
            FleetConfig(num_actors=1, idle_timeout_s=120),
            num_shards=2,
        )
        address = learner.start()
        actor = FleetActor(
            PENDULUM_TINY, actor_id=0, num_actors=1, address=address, seed=0
        )

        def loop():
            try:
                actor.run()  # stream until the server teardown
            except Exception:  # noqa: BLE001
                pass

        thread = threading.Thread(target=loop, daemon=True)
        thread.start()
        ckpt = CheckpointManager(ckpt_dir, save_every=2, light=True)
        resume_from = None
        state = None
        if resume:
            import dataclasses as dc

            step = ckpt.latest_step
            state = trainer.init()
            state = dc.replace(state, train=ckpt.restore(state))
            resume_from = load_fleet_counters(ckpt_dir, step)
        try:
            state = learner.run(
                n_total,
                state=state,
                log_every=0,
                ckpt=ckpt,
                checkpoint_every=2,
                resume_from=resume_from,
            )
        finally:
            learner.close()
            ckpt.close()
            thread.join(timeout=30)
        return trainer, learner, state

    t1, l1, s1 = sampler_run(4, resume=False)
    assert l1.counters()["drained"] == 4
    assert int(s1.train.step) == 4 * t1.config.learner_steps
    saved = load_fleet_counters(ckpt_dir, 4)
    assert saved["drained"] == 4 and saved["env_steps_total"] > 0

    t2, l2, s2 = sampler_run(8, resume=True)
    c2 = l2.counters()
    assert c2["drained"] == 8
    assert int(s2.train.step) == 8 * t2.config.learner_steps
    assert c2["env_steps_total"] > saved["env_steps_total"]
    assert c2["param_version"] > saved["param_version"]
    assert l2.stats()["train_phases"] == 4  # this incarnation's share
    assert l2.stats()["train_phases_total"] == 8


# ----------------------------------------------------------------- refusals
def test_sampler_learner_rejections():
    trainer = PENDULUM_TINY.build()
    with pytest.raises(ValueError, match="num_actors"):
        SamplerLearner(trainer, FleetConfig(num_actors=0), num_shards=1)
    with pytest.raises(ValueError, match="num_shards"):
        SamplerLearner(trainer, FleetConfig(num_actors=1), num_shards=0)
    with pytest.raises(ValueError, match="divisible"):
        SamplerLearner(trainer, FleetConfig(num_actors=1), num_shards=3)
    with pytest.raises(ValueError, match="drain"):
        SamplerLearner(
            trainer,
            FleetConfig(num_actors=1, drain_coalesce=2),
            num_shards=1,
        )


def test_train_cli_refuses_sampler_combos():
    from r2d2dpg_tpu import train

    # Shards without a fleet: nothing feeds them.
    args = train.parse_args(
        ["--config", "pendulum_tiny", "--replay-shards", "2"]
    )
    with pytest.raises(SystemExit, match="requires --actors"):
        train.run(args)
    # No central drain to coalesce.  NB --learner-dp is NOT in this list
    # since ISSUE 11: sampler+dp composes (the pulled batch lands
    # mesh-sharded via _put_staged(axis=1) — tests/test_topology.py).
    args = train.parse_args(
        [
            "--config", "pendulum_tiny",
            "--actors", "2",
            "--replay-shards", "2",
            "--drain-coalesce", "4",
        ]
    )
    with pytest.raises(SystemExit, match="does not compose"):
        train.run(args)
    # Sampler-class chaos drills on the central drain would stall the
    # DRAIN thread (queue fills, actors shed) while recording evidence
    # for an invariant that path cannot exhibit — refused loudly.
    for spec in ("stall_sampler@p2:1s", "kill_sampler_conn@p2"):
        args = train.parse_args(
            [
                "--config", "pendulum_tiny",
                "--actors", "2",
                "--chaos-spec", spec,
            ]
        )
        with pytest.raises(SystemExit, match="replay-shards"):
            train.run(args)


# ------------------------------------------------------------ trace + obs
def test_sampler_gauges_and_trace_hops_registered():
    """The obs satellite: per-shard gauges are live set_fn closures (no
    device fetch anywhere), and the two new trace hops are legal HOPS
    with registered histograms."""
    from r2d2dpg_tpu.obs import get_registry
    from r2d2dpg_tpu.obs import trace as obs_trace

    shards = ShardSet(2, 4, alpha=1.0)
    shards.shards[1].add(
        _np_staged().seq, np.array([2.0, 3.0], np.float64)
    )
    snap = get_registry().snapshot()
    occ = {
        s["labels"]["shard"]: s["value"]
        for s in snap["r2d2dpg_replay_shard_occupancy"]["samples"]
    }
    psum = {
        s["labels"]["shard"]: s["value"]
        for s in snap["r2d2dpg_replay_shard_priority_sum"]["samples"]
    }
    assert occ["1"] == 2.0 and occ["0"] == 0.0
    assert psum["1"] == 5.0
    assert "sample_req" in obs_trace.HOPS and "batch_return" in obs_trace.HOPS
    for hop in ("sample_req", "batch_return"):
        obs_trace.record_hop(hop, 1.0, 2.0, trace_id=7)
    with pytest.raises(ValueError, match="unknown trace hop"):
        obs_trace.record_hop("shard_hop", 0.0, 1.0, trace_id=7)
