"""``python -m r2d2dpg_tpu <cmd> ...`` — subcommand dispatch.

``train`` (the default, so the historical ``python -m r2d2dpg_tpu
--config ...`` spelling keeps working), ``eval``, and ``serve``.  Both
``train`` and ``serve`` take ``--obs-port`` to expose the process
telemetry registry (docs/OBSERVABILITY.md).
"""

import sys


def main() -> None:
    cmds = {"train": "r2d2dpg_tpu.train", "eval": "r2d2dpg_tpu.eval",
            "serve": "r2d2dpg_tpu.serve"}
    argv = sys.argv[1:]
    if argv and argv[0] in cmds:
        name, argv = cmds[argv[0]], argv[1:]
    else:
        name = cmds["train"]
    import importlib

    importlib.import_module(name).main(argv)


main()
