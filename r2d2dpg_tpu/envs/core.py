"""Environment API: pure functional, scan/vmap-friendly.

Reference parity: the reference steps ``gym``/``dm_control`` envs inside N
actor processes (SURVEY.md §2.3, §3.2).  TPU-natively the env is a pure
function so a *batch* of envs is one ``vmap`` and a rollout is one
``lax.scan`` — the whole actor fleet becomes one XLA program (SURVEY §7,
BASELINE north star "vmapped on-device environment stepper").

Two families implement this API:

- pure-JAX dynamics (``pendulum.py``) — fully on-device;
- host-callback pools (``dmc_host.py``) — MuJoCo physics steps on host CPU
  via ``io_callback`` while everything else stays on-device (no MJX in this
  image; SURVEY §7 step 5 track (b)).

Auto-reset contract: ``step`` returns a ``TimeStep`` whose ``reset`` flag is 1
when the *returned observation* begins a new episode (the env auto-resets
internally).  ``reward``/``discount`` always describe the transition taken
*before* any auto-reset, so the pair (obs_t, reset_t) aligns with how the
networks consume them (zero LSTM state where reset=1).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Protocol, Tuple

import jax
import jax.numpy as jnp

EnvState = Any


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class TimeStep:
    """One env step's outputs, batched or not.

    obs: observation that *follows* the transition (post-auto-reset).
    reward: reward of the transition taken before any auto-reset (so the
      episode's final reward rides on the step whose ``reset`` flag is 1;
      only ``reset()``'s first TimeStep carries reward 0).
    discount: continuation flag in [0, 1]; 0 when the episode terminated.
    reset: 1 when ``obs`` is the first observation of a new episode.
    """

    obs: jnp.ndarray
    reward: jnp.ndarray
    discount: jnp.ndarray
    reset: jnp.ndarray


@dataclasses.dataclass(frozen=True)
class EnvSpec:
    """Static env metadata."""

    name: str
    obs_shape: Tuple[int, ...]
    action_dim: int
    action_min: float = -1.0
    action_max: float = 1.0
    episode_length: int = 1000
    pixels: bool = False


class Environment(Protocol):
    """Functional environment protocol."""

    spec: EnvSpec

    def reset(self, key: jax.Array) -> Tuple[EnvState, TimeStep]:
        """Fresh episode -> (state, first TimeStep with reset=1, reward=0)."""
        ...

    def step(
        self, state: EnvState, action: jnp.ndarray, key: jax.Array
    ) -> Tuple[EnvState, TimeStep]:
        """Advance one step, auto-resetting on episode end."""
        ...
