"""Fleet-wide observability plane (ISSUE 6): TELEM metric aggregation into
one scrape point + cross-process experience-path tracing.

Leg 1: actors push ~1 Hz TELEM registry snapshots over the fleet wire;
the ingest server folds them into the learner's RemoteMirror under
``actor=``/``host=`` labels with per-actor staleness gauges, and the
exporter serves ONE merged /metrics page for the whole fleet.

Leg 2: sampled staged batches carry a trace sidecar (id + actor-side hop
timestamps) through encode/socket/decode; the learner records the full
collect -> encode -> transit -> decode -> enqueue -> coalesce ->
arena_add -> learn span chain into hop histograms and the flight
recorder's span ring, dumped as a Perfetto-loadable trace.json.
"""

import json
import queue
import threading
import time
import urllib.request

import numpy as np
import pytest

from r2d2dpg_tpu import obs
from r2d2dpg_tpu.configs import PENDULUM_TINY
from r2d2dpg_tpu.fleet import FleetConfig, FleetLearner, IngestServer, transport, wire
from r2d2dpg_tpu.fleet.transport import (
    K_ACK,
    K_HELLO,
    K_SEQS,
    K_TELEM,
    pack_hello,
    pack_obj,
    recv_frame,
    send_frame,
    send_frame_parts,
    unpack_obj,
)
from r2d2dpg_tpu.obs.trace import WIRE_HOPS
from r2d2dpg_tpu.replay.arena import SequenceBatch, StagedSequences
from r2d2dpg_tpu.utils.codes import OK

pytestmark = pytest.mark.fleet

N_TRAIN = 10


def _np_staged(b=2, l=3):
    rng = np.random.default_rng(1)
    return StagedSequences(
        seq=SequenceBatch(
            obs=rng.normal(size=(b, l, 3)).astype(np.float32),
            action=rng.normal(size=(b, l, 1)).astype(np.float32),
            reward=rng.normal(size=(b, l)).astype(np.float32),
            discount=np.ones((b, l), np.float32),
            reset=np.zeros((b, l), np.float32),
            carries={},
        ),
        priorities=np.ones((b,), np.float32),
    )


def _hello(sock, actor_id):
    send_frame(
        sock,
        K_HELLO,
        pack_hello(
            {"actor_id": actor_id, **wire.negotiation_fields(wire.WireConfig())}
        ),
    )
    kind, payload = recv_frame(sock)
    assert kind == K_ACK and unpack_obj(payload)["code"] == OK


def _telem(sock, actor_id, snapshot, host="testhost"):
    send_frame(
        sock,
        K_TELEM,
        pack_obj(
            {
                "actor_id": actor_id,
                "host": host,
                "t_wall": time.time(),
                "snapshot": snapshot,
            }
        ),
    )


# ----------------------------------------------------------- TELEM folding
def test_telem_folds_reconnects_idempotently_and_goes_stale():
    """TELEM edge cases (satellite): snapshots fold under actor=/host=
    labels, a reconnecting actor UPDATES its slot (no duplicate sources),
    and a silent actor's staleness gauge keeps growing instead of its
    series lying flat."""
    mirror = obs.get_remote_mirror()
    mirror.clear()
    remote = obs.Registry()
    remote.counter("r2d2dpg_actor_phases_total").inc(11)
    q: queue.Queue = queue.Queue(maxsize=4)
    srv = IngestServer(q, address="127.0.0.1:0")
    srv.start()
    try:
        sock = transport.connect(srv.address)
        sock.settimeout(10)
        _hello(sock, 3)
        _telem(sock, 3, remote.snapshot())
        # TELEM is fire-and-forget: prove receipt via the next SEQS ack.
        packer = wire.TreePacker(wire.WireConfig())
        send_frame_parts(
            sock,
            K_SEQS,
            packer.pack(
                {"phase": 1, "param_version": 0, "env_steps_delta": 0.0,
                 "ep_return_sum": 0.0, "ep_count": 0.0, "staged": _np_staged()}
            ),
        )
        kind, payload = recv_frame(sock)
        assert kind == K_ACK and unpack_obj(payload)["code"] == OK
        sources = mirror.sources()
        assert len(sources) == 1
        key, labels, snap = sources[0]
        assert key == "actor:3"
        assert labels == {"actor": "3", "host": "testhost"}
        assert snap["r2d2dpg_actor_phases_total"]["samples"][0]["value"] == 11
        reg = obs.get_registry()
        stale = reg.get("r2d2dpg_fleet_telem_staleness_seconds").labels(
            actor="3"
        )
        s0 = stale.value
        assert s0 >= 0.0
        time.sleep(0.06)
        # A dead/wedged actor goes visibly STALE (gauge grows) rather than
        # its mirrored series silently freezing without a marker.
        assert stale.value >= s0 + 0.05
        sock.close()

        # Reconnect (supervised restart): same actor id, fresh connection —
        # the fold re-registers idempotently; still exactly one source.
        sock = transport.connect(srv.address)
        sock.settimeout(10)
        _hello(sock, 3)
        remote.counter("r2d2dpg_actor_phases_total").inc(1)
        _telem(sock, 3, remote.snapshot())
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            sources = mirror.sources()
            snap = sources[0][2] if sources else {}
            if (
                len(sources) == 1
                and snap.get("r2d2dpg_actor_phases_total", {}).get(
                    "samples", [{}]
                )[0].get("value") == 12
            ):
                break
            time.sleep(0.02)
        sources = mirror.sources()
        assert len(sources) == 1 and sources[0][0] == "actor:3"
        sock.close()
    finally:
        srv.stop()
        mirror.clear()


def test_telem_malformed_frame_dropped_with_flight_event():
    """A malformed TELEM frame costs one flight event, never the
    connection: the experience path keeps flowing."""
    mirror = obs.get_remote_mirror()
    mirror.clear()
    q: queue.Queue = queue.Queue(maxsize=4)
    srv = IngestServer(q, address="127.0.0.1:0")
    srv.start()
    try:
        sock = transport.connect(srv.address)
        sock.settimeout(10)
        _hello(sock, 5)
        # Malformed in two ways: a non-dict payload and a dict whose
        # snapshot is not a snapshot.
        send_frame(sock, K_TELEM, pack_obj(["not", "a", "dict"]))
        send_frame(sock, K_TELEM, pack_obj({"actor_id": 5, "snapshot": 42}))
        packer = wire.TreePacker(wire.WireConfig())
        send_frame_parts(
            sock,
            K_SEQS,
            packer.pack(
                {"phase": 1, "param_version": 0, "env_steps_delta": 0.0,
                 "ep_return_sum": 0.0, "ep_count": 0.0, "staged": _np_staged()}
            ),
        )
        kind, payload = recv_frame(sock)  # connection survived both frames
        assert kind == K_ACK and unpack_obj(payload)["code"] == OK
        drops = [
            e
            for e in obs.get_flight_recorder().events()
            if e["kind"] == "telem_malformed" and e.get("actor") == "5"
        ]
        assert len(drops) >= 2
        assert mirror.sources() == []  # nothing folded
        # Staleness is armed at HELLO, not at the first well-formed fold:
        # an actor that only ever sends garbage TELEM still has a GROWING
        # staleness series instead of being silently absent.
        stale = obs.get_registry().get(
            "r2d2dpg_fleet_telem_staleness_seconds"
        ).labels(actor="5")
        assert stale.value >= 0.0
        sock.close()
    finally:
        srv.stop()
        mirror.clear()


# ----------------------------------------------------- 2-actor e2e (accept)
def test_fleet_obs_plane_two_actor_e2e(tmp_path):
    """Acceptance: a 2-actor fleet run (telem + trace sampled at 1.0)
    exposes EVERY actor's labelled series and per-actor staleness in ONE
    scrape of the learner's /metrics, and its sampled spans cover all
    named hops, sum to the observed end-to-end latency within ~10%, and
    dump as a Perfetto-loadable trace.json."""
    from r2d2dpg_tpu.fleet.actor import FleetActor

    mirror = obs.get_remote_mirror()
    mirror.clear()
    fr = obs.get_flight_recorder()
    fr.clear_spans()
    trainer = PENDULUM_TINY.build()
    learner = FleetLearner(
        trainer, FleetConfig(num_actors=2, queue_depth=4, idle_timeout_s=60)
    )
    address = learner.start()
    actors = [
        FleetActor(
            PENDULUM_TINY,
            actor_id=i,
            num_actors=2,
            address=address,
            seed=0,
            telem_every=0.05,
            trace_sample=1.0,
        )
        for i in range(2)
    ]

    def actor_loop(a):
        try:
            a.run(max_phases=400)
        except Exception:  # noqa: BLE001 — server teardown cuts the socket
            pass

    threads = [
        threading.Thread(target=actor_loop, args=(a,), daemon=True)
        for a in actors
    ]
    for t in threads:
        t.start()
    try:
        state = learner.run(N_TRAIN, log_every=0)
    finally:
        learner.close()
        for t in threads:
            t.join(timeout=30)
    assert int(state.train.step) == N_TRAIN * trainer.config.learner_steps

    # --- leg 1: ONE scrape carries every actor's labelled series --------
    ex = obs.MetricsExporter(obs.get_registry(), port=0, mirror=mirror)
    try:
        text = (
            urllib.request.urlopen(f"http://127.0.0.1:{ex.port}/metrics")
            .read()
            .decode()
        )
    finally:
        ex.stop()
    for a in ("0", "1"):
        assert f'r2d2dpg_actor_phases_total{{actor="{a}"' in text, a
        assert f'r2d2dpg_actor_param_version{{actor="{a}"' in text, a
        assert (
            f'r2d2dpg_fleet_telem_staleness_seconds{{actor="{a}"}}' in text
        ), a
    # One TYPE line per family even with two actors folded in.
    assert text.count("# TYPE r2d2dpg_actor_phases_total") == 1
    # The per-hop histograms are scrapeable alongside.
    for hop in WIRE_HOPS:
        assert f"r2d2dpg_trace_{hop}_seconds" in text, hop

    # --- leg 2: sampled spans cover all hops and add up -----------------
    spans = fr.spans()
    by_id = {}
    for s in spans:
        by_id.setdefault(s["trace_id"], {})[s["hop"]] = s
    complete = [
        tid for tid, hops in by_id.items() if set(WIRE_HOPS) <= set(hops)
    ]
    assert complete, f"no complete trace; hops seen: {by_id and set().union(*[set(h) for h in by_id.values()])}"
    # All-or-nothing recording: absorb-phase/shed batches contribute NO
    # partial chain, so every recorded trace id carries all 8 hops and
    # every hop histogram shares one sample population.
    assert all(set(hops) == set(WIRE_HOPS) for hops in by_id.values()), {
        tid: sorted(hops) for tid, hops in by_id.items()
        if set(hops) != set(WIRE_HOPS)
    }
    # The hops are contiguous intervals, so per-hop durations must sum to
    # the observed end-to-end latency of that batch (~10%: the learner-wait
    # budget is attributable).
    for tid in complete[:3]:
        hops = by_id[tid]
        total = sum(s["dur_s"] for s in hops.values())
        t0 = min(s["t_wall"] for s in hops.values())
        t1 = max(s["t_wall"] + s["dur_s"] for s in hops.values())
        e2e = t1 - t0
        assert e2e > 0
        assert abs(total - e2e) <= 0.10 * e2e + 1e-3, (total, e2e)
        # Both ends attributed: every span of this trace names its actor.
        assert all(s.get("actor") in ("0", "1") for s in hops.values())

    # --- trace.json: Perfetto/chrome://tracing-loadable artifact --------
    path = str(tmp_path / "trace.json")
    assert fr.dump_trace(path) == path
    doc = json.loads(open(path).read())
    names = {e["name"] for e in doc["traceEvents"]}
    assert set(WIRE_HOPS) <= names
    assert all(
        e["ph"] == "X" and "ts" in e and "dur" in e and "pid" in e
        for e in doc["traceEvents"]
    )
    mirror.clear()
    fr.clear_spans()
