#!/bin/bash
# Idempotently (re-)arm the round-5 CPU evidence chain (VERDICT r4 next
# #2).  Each driver is launched only if an instance isn't already
# resident — two instances of the same run_evidence driver could race
# each other's attempt loops on the single-core box.  Safe to call any
# time: drivers exit immediately when their .done artifact exists, and
# gate on the box (live trains / TPU campaign) before touching anything.
HERE="$(cd "$(dirname "$0")" && pwd)"
cd "$HERE/.."
# Anchored pattern (see lib_gate.sh): an unanchored match also hits
# resident shells that merely MENTION the script name, which would skip
# the launch forever.
for s in walker_combo_probe walker_mpbf16_probe cheetah_twin_probe walker_bf16acc_probe walker_ns3_long; do
  pgrep -f "^[^ ]*bash [^ ]*scripts/$s\.sh" > /dev/null \
    || setsid nohup bash "$HERE/$s.sh" > /dev/null 2>&1 < /dev/null &
done
