"""Headline benchmark: learner steps/sec/chip (BASELINE.json `metric`).

Measures the sustained rate of the full R2D2-DPG learner step — prioritized
sample from the HBM arena, LSTM burn-in of all four nets, n-step targets,
IS-weighted critic + actor updates, Polyak, Pallas priority write-back — at
config-#3 (walker) shapes: batch 64, seq 20+20+5, obs 24, act 6, hidden 256.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "backend"}.
``vs_baseline`` compares against ``BENCH_BASELINE.json`` (this repo's first
recorded TPU number — the reference repo published no benchmark figures;
see BASELINE.md provenance) or 1.0 if absent.

Resilience (VERDICT r1 weak-point #2): the TPU tunnel on this box flaps and
can HANG (not raise) during backend init, so the measurement runs in a child
process with a hard timeout.  The parent retries the TPU child with backoff,
falls back to a CPU child (axon plugin never registered: the sitecustomize
hook is gated on ``PALLAS_AXON_POOL_IPS``), and ALWAYS prints one parseable
JSON line — including on total failure (value 0.0 + "error").

Usage:
    python bench.py                # measure (TPU, CPU fallback), fp32
    python bench.py bfloat16       # activation-dtype override experiment
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
METRIC = "learner_steps_per_sec_per_chip"
# First TPU compile of the chunked learner scan is slow (~1-2 min on a cold
# cache); give the child plenty, but keep it finite so a hung tunnel cannot
# eat the driver's whole budget.
CHILD_TIMEOUT_S = 420
TPU_TRIES = 3
BACKOFF_S = (5, 20)


def _emit(value: float, vs: float, backend: str, error: str | None = None) -> None:
    rec = {
        "metric": METRIC,
        "value": round(value, 2),
        "unit": "steps/s",
        "vs_baseline": round(vs, 3),
        "backend": backend,
    }
    if error:
        rec["error"] = error[-400:]
    print(json.dumps(rec))


def _baseline() -> float | None:
    path = os.path.join(HERE, "BENCH_BASELINE.json")
    if os.path.exists(path):
        with open(path) as f:
            return json.load(f).get("value")
    return None


def _run_bounded(cmd: list, env: dict, timeout_s: int):
    """Run ``cmd`` with a deadline, SIGTERM first on expiry.

    A SIGKILLed JAX client can leave the axon device grant unreleased and
    hang subsequent TPU ops for minutes; SIGTERM lets the client tear down
    cleanly.  Returns (rc, stdout, stderr); rc is None on timeout, with
    whatever output the child produced before dying (the diagnostics for
    exactly the hang case this exists to debug).
    """
    proc = subprocess.Popen(
        cmd, env=env, cwd=HERE, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        out, err = proc.communicate(timeout=timeout_s)
        return proc.returncode, out, err
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            out, err = proc.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, err = proc.communicate()
        return None, out, err


def _probe_tpu(timeout_s: int = 120) -> bool:
    """Cheap child that just initializes the TPU backend; True if it's alive.

    Init on a dead tunnel HANGS rather than raising, so paying the full
    measurement timeout on every retry would waste ~20 min; this probe
    bounds a hang at ``timeout_s``.
    """
    rc, out, err = _run_bounded(
        [sys.executable, "-c",
         "import jax; d = jax.devices(); print(len(d), d[0].platform)"],
        dict(os.environ),
        timeout_s,
    )
    if rc is None:
        print(f"bench: TPU probe hung >{timeout_s}s; child stderr tail: "
              f"{err[-500:]}", file=sys.stderr)
        return False
    if rc != 0:
        print(f"bench: TPU probe rc={rc}; stderr tail: {err[-500:]}",
              file=sys.stderr)
        return False
    # Require an actual TPU device: on a box where JAX_PLATFORMS=cpu (the
    # documented CPU test mode) the probe initializes fine on CPU, and the
    # "tpu" attempt would silently measure CPU without the interpret-mode
    # pins the dedicated CPU fallback sets.
    platform = out.strip().split()[-1] if out.strip() else ""
    if platform not in ("tpu", "axon"):
        print(f"bench: probe found platform {platform!r}, not tpu",
              file=sys.stderr)
        return False
    return True


def _run_child(dtype: str, backend: str) -> dict | None:
    """Run the measurement worker in a child; return its parsed JSON or None."""
    env = dict(os.environ)
    env["R2D2DPG_BENCH_WORKER"] = "1"
    if backend == "cpu":
        env.pop("PALLAS_AXON_POOL_IPS", None)  # axon never registers
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("R2D2DPG_PALLAS_INTERPRET", "1")
    rc, out, err = _run_bounded(
        [sys.executable, os.path.abspath(__file__), dtype], env, CHILD_TIMEOUT_S
    )
    if rc is None:
        print(f"bench: {backend} child timed out after {CHILD_TIMEOUT_S}s; "
              f"stderr tail: {err[-1500:]}", file=sys.stderr)
        return None
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("metric") == METRIC:
            return rec
    print(f"bench: {backend} child rc={rc}; stderr tail: {err[-1500:]}",
          file=sys.stderr)
    return None


def main() -> None:
    dtype = sys.argv[1] if len(sys.argv) > 1 else "float32"
    last_err = "no attempt ran"
    for i in range(TPU_TRIES):
        if i:
            time.sleep(BACKOFF_S[min(i - 1, len(BACKOFF_S) - 1)])
        if not _probe_tpu():
            last_err = f"tpu probe {i + 1}/{TPU_TRIES} failed (tunnel down)"
            continue
        rec = _run_child(dtype, backend="tpu")
        if rec is not None:
            print(json.dumps(rec))
            return
        last_err = f"tpu attempt {i + 1}/{TPU_TRIES} failed (timeout or init error)"
    rec = _run_child(dtype, backend="cpu")
    if rec is not None:
        print(json.dumps(rec))
        return
    _emit(0.0, 0.0, "none", error=last_err + "; cpu fallback also failed")
    sys.exit(0)  # the JSON line IS the contract; don't fail the driver's parse


def worker() -> None:
    """Measurement body — runs in a child with the backend already pinned."""
    import jax
    import jax.numpy as jnp

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    dtype = jnp.dtype(sys.argv[1]) if len(sys.argv) > 1 else jnp.float32

    from r2d2dpg_tpu.agents import AgentConfig, R2D2DPG
    from r2d2dpg_tpu.models import ActorNet, CriticNet
    from r2d2dpg_tpu.replay import ReplayArena, SequenceBatch

    backend = jax.default_backend()

    # Config-#3 (walker_r2d2) learner shapes.
    batch, obs_dim, act_dim, hidden = 64, 24, 6, 256
    cfg = AgentConfig(burnin=20, unroll=20, n_step=5)
    seq_len = cfg.seq_len
    capacity = 100_000

    actor = ActorNet(action_dim=act_dim, hidden=hidden, use_lstm=True, dtype=dtype)
    critic = CriticNet(hidden=hidden, use_lstm=True, dtype=dtype)
    agent = R2D2DPG(actor, critic, cfg)

    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 8)
    fill = 4096  # sequences resident for realistic sampling
    seqs = SequenceBatch(
        obs=jax.random.normal(ks[0], (fill, seq_len, obs_dim)),
        action=jax.random.uniform(ks[1], (fill, seq_len, act_dim), minval=-1, maxval=1),
        reward=jax.random.normal(ks[2], (fill, seq_len)),
        discount=jnp.ones((fill, seq_len)),
        reset=jnp.zeros((fill, seq_len)),
        carries={
            "actor": actor.initial_carry(fill),
            "critic": critic.initial_carry(fill),
        },
    )
    arena = ReplayArena(capacity, prioritized=True)
    arena_state = arena.init_state(seqs)
    arena_state = arena.add(
        arena_state, seqs, jax.random.uniform(ks[3], (fill,)) + 0.5
    )
    train = agent.init(ks[4], seqs.obs[:batch, 0], seqs.action[:batch, 0])

    def one_step(carry, key):
        train, arena_state = carry
        res = arena.sample(arena_state, key, batch)
        w = jnp.ones((batch,))
        train, prios, _ = agent.learner_step(train, res.batch, w)
        arena_state = arena.update_priorities(arena_state, res.indices, prios)
        return (train, arena_state), prios.mean()

    CHUNK = 50

    @jax.jit
    def run_chunk(train, arena_state, key):
        keys = jax.random.split(key, CHUNK)
        (train, arena_state), out = jax.lax.scan(
            one_step, (train, arena_state), keys
        )
        return train, arena_state, out.mean()

    # Warm-up / compile.
    train, arena_state, _ = run_chunk(train, arena_state, ks[5])
    jax.block_until_ready(train.step)

    n_chunks = 2 if backend == "cpu" else 6  # CPU fallback: keep it finite
    t0 = time.perf_counter()
    for i in range(n_chunks):
        train, arena_state, out = run_chunk(
            train, arena_state, jax.random.fold_in(ks[6], i)
        )
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    steps_per_sec = n_chunks * CHUNK / dt

    baseline = _baseline()
    vs = steps_per_sec / baseline if baseline else 1.0
    _emit(steps_per_sec, vs, backend)


if __name__ == "__main__":
    if os.environ.get("R2D2DPG_BENCH_WORKER"):
        worker()
    else:
        main()
