"""Data-parallel learner (parallel/dp_learner.py, ISSUE 9).

Covers the dp-sharded drain/learn path on the virtual CPU mesh, the
``--learner-dp`` CLI wiring + refused knob combos, the coalesce-width
precompile (the BENCH_FLEET ``fleet_coalesce`` regression fix), and the
determinism anchor extending the ``--actors 0`` bit-identical contract to
``--learner-dp 1`` — ``scripts/lib_gate.sh learner_dp_gate`` refuses to
bless ``--learner-dp N`` evidence dirs unless that anchor passes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2dpg_tpu.configs import PENDULUM_TINY
from r2d2dpg_tpu.parallel import DPLearnerTrainer, make_mesh
from r2d2dpg_tpu.parallel.mesh import DP_AXIS
from r2d2dpg_tpu.training.assembler import emit
from r2d2dpg_tpu.training.pipeline import drain_staged, split_state
from r2d2dpg_tpu.replay.arena import StagedSequences, stack_staged

N_TRAIN = 10
LOG_EVERY = 3


def _leaves_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return [
        i
        for i, (x, y) in enumerate(zip(la, lb))
        if not np.array_equal(np.asarray(x), np.asarray(y))
    ]


def _host_staged(trainer, state):
    """A numpy staged batch shaped exactly like one fleet actor emission
    (E sequences off the trainer's own window), priorities resolved."""
    seq = jax.tree_util.tree_map(np.asarray, jax.device_get(emit(state.window)))
    b = np.shape(seq.reward)[0]
    return StagedSequences(seq=seq, priorities=np.ones((b,), np.float32))


# ------------------------------------------------------- determinism anchor
def test_learner_dp1_actors0_determinism_bit_identical(
    tmp_path, phase_locked_reference_k10
):
    """--learner-dp 1 --actors 0 == the untouched phase-locked Trainer.run,
    leaf-for-leaf bitwise, END TO END through the train.py CLI path — the
    degenerate 1-device mesh must annotate layouts without changing one
    bit of the trajectory (learner_dp_gate runs this by its 'determinism'
    name).  The reference half is the shared session fixture
    (tests/conftest.py) — the pairing assert keeps it honest."""
    from r2d2dpg_tpu import train
    from r2d2dpg_tpu.utils import CheckpointManager
    from r2d2dpg_tpu.utils.checkpoint import resume_state

    assert (N_TRAIN, LOG_EVERY) == (10, 3)  # the k10 fixture's recipe
    s1 = phase_locked_reference_k10

    train.run(
        train.parse_args(
            [
                "--config", "pendulum_tiny",
                "--learner-dp", "1",
                "--actors", "0",
                "--phases", str(N_TRAIN),
                "--log-every", str(LOG_EVERY),
                "--checkpoint-dir", str(tmp_path / "ckpt"),
                "--checkpoint-every", "-1",
                "--watchdog", "0",
            ]
        )
    )
    t2 = PENDULUM_TINY.build()
    s2 = resume_state(
        t2, CheckpointManager(str(tmp_path / "ckpt"), save_every=-1)
    )
    bad = _leaves_equal(s1, s2)
    assert not bad, f"state diverged at leaves {bad}"


# ------------------------------------------------------------ dp=2 learner
def test_dp2_drain_keeps_arena_sharded_and_layout_stable():
    """drain_staged on a dp=2 trainer: the arena stays capacity-sharded
    across donated drain calls (stable avals = stable jit cache), counters
    advance, and the learner step lands."""
    from jax.sharding import PartitionSpec as P

    t = PENDULUM_TINY.build_dp_learner(make_mesh(2), collect_local=True)
    state = t.init()
    staged = _host_staged(t, state)
    _, lstate = split_state(state)
    prog = jax.jit(
        lambda ls, st, learn: drain_staged(t, ls, st, learn=learn),
        donate_argnums=(0,),
        static_argnums=(2,),
    )
    # Absorb past min_replay (8 seqs at E=4 -> 2 absorbs), then learn.
    for _ in range(2):
        lstate, _ = prog(lstate, t._put_staged(staged), False)
    sharding_before = lstate.arena.priority.sharding
    assert sharding_before.spec == P(DP_AXIS)
    lstate, metrics = prog(lstate, t._put_staged(staged), True)
    assert lstate.arena.priority.sharding.spec == sharding_before.spec
    assert int(lstate.train.step) == t.config.learner_steps
    assert int(lstate.arena.total_added) == 12
    assert np.isfinite(float(metrics["critic_loss"]))


def test_dp2_put_staged_layouts():
    """_put_staged lays divisible widths over dp and replicates foreign
    (indivisible) widths instead of failing."""
    t = PENDULUM_TINY.build_dp_learner(make_mesh(2), collect_local=True)
    state = t.init()
    staged = _host_staged(t, state)  # B = 4, divisible by 2
    placed = t._put_staged(staged)
    assert placed.seq.obs.sharding.spec[0] == DP_AXIS
    odd = jax.tree_util.tree_map(lambda x: np.asarray(x)[:3], staged)
    placed_odd = t._put_staged(odd)
    assert not any(placed_odd.seq.obs.sharding.spec)  # replicated
    # Multi-process: divisibility is global (b * nproc), and indivisible
    # widths are refused loudly — the replicate fallback would build
    # per-process-inconsistent arrays.
    t._nproc = 3
    try:
        with pytest.raises(ValueError, match="does not divide"):
            t._put_staged(odd)  # 3 * 3 = 9 rows over a 2-device mesh
    finally:
        t._nproc = 1


def test_dp2_log_extra_refs_publish_shard_gauges():
    """The per-shard occupancy gauges ride the log-cadence fetch hooks."""
    from r2d2dpg_tpu.obs import get_registry

    t = PENDULUM_TINY.build_dp_learner(make_mesh(2), collect_local=True)
    state = t.init()
    staged = _host_staged(t, state)
    _, lstate = split_state(state)
    lstate, _ = jax.jit(
        lambda ls, st: drain_staged(t, ls, st, learn=False),
        donate_argnums=(0,),
    )(lstate, t._put_staged(staged))
    refs = t._log_extra_refs(lstate.arena)
    assert len(refs) == 1
    t._log_extra_publish(jax.device_get(refs))
    t.dp_note_learn_width(4)  # the fleet drain site's dispatch-width note
    snap = get_registry().snapshot()
    samples = snap["r2d2dpg_dp_shard_occupancy"]["samples"]
    by_shard = {s["labels"]["shard"]: s["value"] for s in samples}
    assert by_shard["0"] == 4.0 and by_shard["1"] == 0.0
    width = snap["r2d2dpg_dp_shard_learn_width"]["samples"][0]["value"]
    assert width == 2.0  # 4 rows over 2 shards


def test_dp_learner_divisibility_and_agent_axis_validation():
    from r2d2dpg_tpu.configs import ExperimentConfig  # noqa: F401 (doc)

    env = PENDULUM_TINY.env_factory()
    agent = PENDULUM_TINY.build_agent(env)
    import dataclasses

    bad = dataclasses.replace(PENDULUM_TINY.trainer, batch_size=9)
    with pytest.raises(ValueError, match="divisible"):
        DPLearnerTrainer(env, agent, bad, make_mesh(2))
    spmd_agent = PENDULUM_TINY.build_agent(env, axis_name=DP_AXIS)
    with pytest.raises(ValueError, match="axis_name"):
        DPLearnerTrainer(env, spmd_agent, PENDULUM_TINY.trainer, make_mesh(2))


# ------------------------------------------------------------- CLI wiring
def test_train_cli_refuses_learner_dp_combos():
    from r2d2dpg_tpu import train

    for flags in (
        ["--spmd", "2"],
        ["--pipeline", "1"],
        ["--overlap-learner", "1"],
    ):
        args = train.parse_args(
            ["--config", "pendulum_tiny", "--learner-dp", "2", *flags]
        )
        with pytest.raises(SystemExit, match="does not compose"):
            train.run(args)
    # Indivisible mesh (capacity 256 / batch 8 vs dp=3): refused loudly.
    args = train.parse_args(
        ["--config", "pendulum_tiny", "--learner-dp", "3"]
    )
    with pytest.raises(SystemExit, match="divisible"):
        train.run(args)


# ---------------------------------------------- coalesce-width precompile
def test_warm_drain_widths_precompiles_and_matches_jit():
    """The background coalesce precompile (fleet/ingest.py): every
    power-of-two width lands in _drain_exec keyed by TOTAL staged B,
    _coalesce_ready rises to the cap, and the AOT-compiled width-2 drain
    is BITWISE the jit path's result on identical inputs."""
    from r2d2dpg_tpu.fleet import FleetConfig, FleetLearner
    from r2d2dpg_tpu.fleet.ingest import aval_tree

    t = PENDULUM_TINY.build()
    fl = FleetLearner(t, FleetConfig(num_actors=1, drain_coalesce=4))
    state = t.init()
    _, lstate = split_state(state)
    staged = _host_staged(t, state)
    b0 = int(np.shape(staged.seq.reward)[0])

    fl._warm_drain_widths(aval_tree(lstate), staged)
    # w=1 included: when the first learn pull is coalesced, the jit
    # wrapper's width-1 entry is never populated, so width 1 needs its
    # own AOT object too (ingest.py warm loop comment).
    assert set(fl._drain_exec) == {b0, 2 * b0, 4 * b0}
    assert fl._coalesce_ready == 4

    # Two identical learner states (same seed), absorbed identically past
    # min_replay, drained width-2 through the AOT object vs the jit.
    def fresh_lstate():
        _, ls = split_state(t.init())
        for _ in range(2):
            ls, _ = drain_staged(t, ls, staged, learn=False)
        return ls

    stacked = stack_staged([staged, staged])
    out_a, m_a = fl._drain_exec[2 * b0](fresh_lstate(), stacked)
    out_b, m_b = fl._drain_prog(fresh_lstate(), stacked)
    assert not _leaves_equal(out_a, out_b)
    assert not _leaves_equal(m_a, m_b)
