"""Priority eta-mix, IS weights, noise ladder, Polyak (SURVEY.md §4.1)."""

import jax.numpy as jnp
import numpy as np

from r2d2dpg_tpu.ops import (
    PRIORITY_EPS,
    anneal_beta,
    importance_weights,
    polyak_update,
    sequence_priority,
    sigma_ladder,
)


def test_sequence_priority_eta_mix():
    td = jnp.array([[1.0, -3.0, 2.0]])
    p = sequence_priority(td, eta=0.9)
    want = 0.9 * 3.0 + 0.1 * 2.0 + PRIORITY_EPS
    np.testing.assert_allclose(np.asarray(p), [want], rtol=1e-6)


def test_sequence_priority_eta_extremes():
    td = jnp.array([1.0, 2.0, 3.0])
    np.testing.assert_allclose(
        float(sequence_priority(td, eta=1.0)), 3.0 + PRIORITY_EPS, rtol=1e-6
    )
    np.testing.assert_allclose(
        float(sequence_priority(td, eta=0.0)), 2.0 + PRIORITY_EPS, rtol=1e-6
    )


def test_importance_weights_formula_and_normalization():
    probs = jnp.array([0.5, 0.25, 0.25])
    w = importance_weights(probs, size=100, beta=0.4)
    raw = (100 * np.array([0.5, 0.25, 0.25])) ** (-0.4)
    np.testing.assert_allclose(np.asarray(w), raw / raw.max(), rtol=1e-5)
    assert float(w.max()) == 1.0


def test_importance_weights_beta_zero_is_uniform():
    w = importance_weights(jnp.array([0.9, 0.1]), size=10, beta=0.0)
    np.testing.assert_allclose(np.asarray(w), [1.0, 1.0])


def test_anneal_beta():
    np.testing.assert_allclose(float(anneal_beta(0, beta0=0.4, steps=100)), 0.4, rtol=1e-6)
    np.testing.assert_allclose(float(anneal_beta(50, beta0=0.4, steps=100)), 0.7, rtol=1e-6)
    np.testing.assert_allclose(float(anneal_beta(1000, beta0=0.4, steps=100)), 1.0, rtol=1e-6)


def test_sigma_ladder_geometric_monotone():
    s = np.asarray(sigma_ladder(8, sigma_max=0.4, alpha=7.0))
    assert s[0] == np.float32(0.4)
    assert np.all(np.diff(s) < 0)  # decays toward tiny sigma
    np.testing.assert_allclose(s[-1], 0.4**8, rtol=1e-5)


def test_sigma_ladder_single_actor_and_linear():
    assert np.asarray(sigma_ladder(1, sigma_max=0.3)) == np.float32(0.3)
    lin = np.asarray(sigma_ladder(4, kind="linear", sigma_max=0.4, sigma_min=0.1))
    np.testing.assert_allclose(lin, [0.4, 0.3, 0.2, 0.1], rtol=1e-5)


def test_polyak_update():
    online = {"w": jnp.ones(3)}
    target = {"w": jnp.zeros(3)}
    new = polyak_update(online, target, tau=0.1)
    np.testing.assert_allclose(np.asarray(new["w"]), 0.1 * np.ones(3), rtol=1e-6)
