"""Flight recorder: a bounded ring of structured events for post-mortems.

Queue stalls, param publishes, hot-reloads, TTL evictions, shed codes,
checkpoint saves, watchdog trips — each subsystem drops a small structured
event into a process-wide ring (``flight_event(kind, **fields)``).  The
ring is bounded (old events fall off), recording is a deque append under a
lock (~µs, safe on hot-ish paths), and nothing is written to disk until a
**dump** — on normal exit (atexit), on a watchdog abort, or on demand.

Dumps are JSONL (one event per line, oldest first) written atomically
(tmp + rename) so a crash mid-dump never leaves a torn file.  Each event
carries::

    {"kind": ..., "t_wall": <unix seconds>, "t_mono": <monotonic seconds>,
     "seq": <monotone index>, "thread": <recording thread name>,
     "pid": <os pid>, ...identity, ...fields}

Identity stamping (fleet/multi-host post-mortems): every process in a
fleet writes its own ``flight.jsonl``, and interleaving them by ``t_wall``
is only useful if each line says WHO recorded it.  ``set_flight_identity``
stamps process-wide fields (``process_index`` for
``parallel.distributed.initialize()`` hosts, ``actor`` for fleet actor
subprocesses) onto every subsequent event; ``pid`` is always stamped.

Hard crashes (SIGSEGV & friends) cannot run Python: ``install()`` also
points ``faulthandler`` at a sidecar ``<path>.fault`` file so native
tracebacks land next to the last dumped ring.
"""

from __future__ import annotations

import atexit
import faulthandler
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional


class FlightRecorder:
    """Bounded in-memory event ring + JSONL dump."""

    def __init__(self, capacity: int = 512):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0
        self._installed_path: Optional[str] = None
        self._fault_file = None
        self._identity: Dict[str, object] = {}

    # -------------------------------------------------------------- identity
    def set_identity(self, **fields) -> None:
        """Stamp who-is-recording fields (``process_index``, ``actor``, ...)
        onto every subsequent event.  Merges: later calls add/overwrite keys
        without dropping earlier ones."""
        with self._lock:
            self._identity.update(fields)

    # ---------------------------------------------------------------- record
    def record(self, kind: str, **fields) -> None:
        event = {
            "kind": str(kind),
            "t_wall": time.time(),
            "t_mono": time.monotonic(),
            "thread": threading.current_thread().name,
            "pid": os.getpid(),
        }
        with self._lock:
            event.update(self._identity)
            event.update(fields)  # explicit fields win over identity
            event["seq"] = self._seq
            self._seq += 1
            self._ring.append(event)

    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    @property
    def recorded_total(self) -> int:
        """Events ever recorded (≥ len(events()) once the ring wrapped)."""
        with self._lock:
            return self._seq

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # ------------------------------------------------------------------ dump
    def dump(self, path: Optional[str] = None) -> Optional[str]:
        """Write the ring as JSONL (atomic tmp+rename).  Returns the path,
        or None when neither ``path`` nor an installed path exists."""
        path = path or self._installed_path
        if path is None:
            return None
        events = self.events()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            for e in events:
                f.write(json.dumps(e, default=str) + "\n")
        os.replace(tmp, path)
        return path

    # --------------------------------------------------------------- install
    def install(self, path: str) -> None:
        """Arm exit-time capture: dump to ``path`` at interpreter exit and
        route hard-crash native tracebacks to ``<path>.fault``.

        Idempotent per path; re-installing with a new path re-targets the
        dump (one atexit hook either way).  Watchdog/abort paths call
        ``dump()`` explicitly — atexit is the safety net, not the contract.
        """
        with self._lock:
            first = self._installed_path is None
            self._installed_path = path
        if first:
            atexit.register(self._atexit_dump)
        # faulthandler can't run Python on SIGSEGV; give it a sidecar file
        # so the native traceback survives next to the last dump.
        try:
            fault = open(f"{path}.fault", "w")
            faulthandler.enable(file=fault)
            old, self._fault_file = self._fault_file, fault
            if old is not None:
                old.close()
        except OSError:
            pass  # unwritable dir: the ring (and atexit dump) still work

    def _atexit_dump(self) -> None:
        try:
            self.dump()
        except OSError:
            pass  # exit-time best effort: never turn teardown into a crash


_RECORDER = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    """THE process-wide flight recorder (module singleton)."""
    return _RECORDER


def flight_event(kind: str, **fields) -> None:
    """Record one event into the process recorder (the library-side API)."""
    _RECORDER.record(kind, **fields)


def set_flight_identity(**fields) -> None:
    """Stamp identity fields (``process_index``, ``actor``, ...) onto every
    subsequent event of the process recorder, so fleet post-mortems can
    interleave multiple processes' ``flight.jsonl`` dumps by wall time and
    still attribute each line."""
    _RECORDER.set_identity(**fields)
