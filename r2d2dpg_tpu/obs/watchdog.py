"""Divergence watchdog: loud, early abort on numeric poisoning.

The failure mode worth a dedicated mode in this codebase is numeric
(SURVEY §5.2): there is no shared mutable host state, but one NaN in a
learner update silently poisons params, priorities, and every checkpoint
written afterwards — a run can burn hours "training" garbage.  The
watchdog rides the ONE batched ``jax.device_get`` the log cadence already
performs (trainer/pipeline log paths): it inspects the host-side scalars
that fetch produced — no new device syncs, no graph changes — and checks

- NaN / Inf anywhere in the learner's metric dict (losses, q/target means,
  grad/param norms);
- ``grad_norm``  > ``grad_norm_max``  (default 1e6);
- ``param_norm`` > ``param_norm_max`` (default 1e7).

On trip it records a flight-recorder event and raises ``DivergenceError``;
the CLI layer (train.py) dumps ``flight.jsonl``, prints the last-good
checkpoint pointer, skips the final save (a poisoned "final" checkpoint
would shadow the last good one), and exits non-zero.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional

from r2d2dpg_tpu.obs.flight import FlightRecorder, get_flight_recorder
from r2d2dpg_tpu.obs.registry import Registry, get_registry

# Metric keys the threshold checks look for (absent keys are skipped; the
# NaN/Inf sweep covers every key regardless).
GRAD_NORM_KEY = "grad_norm"
PARAM_NORM_KEY = "param_norm"


class DivergenceError(RuntimeError):
    """A learner-output check tripped; carries the offending scalars."""

    def __init__(self, reason: str, step: int, scalars: Dict[str, float]):
        super().__init__(reason)
        self.reason = reason
        self.step = step
        self.scalars = dict(scalars)


@dataclasses.dataclass(frozen=True)
class WatchdogConfig:
    grad_norm_max: float = 1e6
    param_norm_max: float = 1e7


class DivergenceWatchdog:
    """Stateless check + trip bookkeeping (counter, flight event)."""

    def __init__(
        self,
        config: WatchdogConfig = WatchdogConfig(),
        *,
        registry: Optional[Registry] = None,
        recorder: Optional[FlightRecorder] = None,
    ):
        self.config = config
        self._recorder = recorder if recorder is not None else get_flight_recorder()
        reg = registry if registry is not None else get_registry()
        self._trips = reg.counter(
            "r2d2dpg_watchdog_trips_total",
            "divergence-watchdog trips (the process aborts on the first)",
        )
        self._checks = reg.counter(
            "r2d2dpg_watchdog_checks_total", "log-cadence watchdog sweeps"
        )

    # ----------------------------------------------------------------- check
    def check(self, step: int, scalars: Dict[str, float]) -> None:
        """Sweep one log cadence's host-side scalars; raise on divergence."""
        self._checks.inc()
        reason = self._find_violation(scalars)
        if reason is None:
            return
        self._trips.inc()
        self._recorder.record(
            "watchdog_trip",
            step=int(step),
            reason=reason,
            scalars={k: _jsonable(v) for k, v in scalars.items()},
        )
        raise DivergenceError(reason, int(step), scalars)

    def _find_violation(self, scalars: Dict[str, float]) -> Optional[str]:
        cfg = self.config
        for k, v in scalars.items():
            f = float(v)
            if math.isnan(f) or math.isinf(f):
                return f"non-finite learner output: {k} = {f}"
        g = scalars.get(GRAD_NORM_KEY)
        if g is not None and float(g) > cfg.grad_norm_max:
            return (
                f"{GRAD_NORM_KEY} {float(g):.4g} exceeds "
                f"grad_norm_max {cfg.grad_norm_max:.4g}"
            )
        p = scalars.get(PARAM_NORM_KEY)
        if p is not None and float(p) > cfg.param_norm_max:
            return (
                f"{PARAM_NORM_KEY} {float(p):.4g} exceeds "
                f"param_norm_max {cfg.param_norm_max:.4g}"
            )
        return None


def _jsonable(v) -> float:
    f = float(v)
    # JSON has no NaN/Inf literals; stringify so the flight dump stays valid.
    return f if math.isfinite(f) else str(f)  # type: ignore[return-value]
