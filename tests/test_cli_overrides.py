"""train/eval CLI override plumbing (round 3: the hparam-probe and
mitigation flags must actually reach the configs they claim to set)."""

import jax.numpy as jnp

from r2d2dpg_tpu.configs import get_config
from r2d2dpg_tpu.train import _apply_overrides, parse_args


def apply(config, *flags):
    args = parse_args(["--config", config, *flags])
    return _apply_overrides(get_config(config), args)


def test_trainer_overrides():
    cfg = apply(
        "walker_r2d2",
        "--num-envs", "8", "--batch-size", "32", "--learner-steps", "2",
        "--min-replay", "64", "--param-sync-every", "3",
        "--overlap-learner", "1", "--seed", "9",
        "--sigma-max", "0.8", "--ladder-alpha", "4.5",
    )
    t = cfg.trainer
    assert (t.num_envs, t.batch_size, t.learner_steps) == (8, 32, 2)
    assert (t.min_replay, t.param_sync_every, t.seed) == (64, 3, 9)
    assert t.overlap_learner is True
    assert (t.sigma_max, t.ladder_alpha) == (0.8, 4.5)


def test_agent_overrides():
    cfg = apply(
        "walker_r2d2",
        "--n-step", "3", "--actor-lr", "3e-4", "--critic-lr", "2e-3",
        "--twin-critic", "1", "--target-policy-sigma", "0.2",
    )
    a = cfg.agent
    assert (a.n_step, a.actor_lr, a.critic_lr) == (3, 3e-4, 2e-3)
    assert a.twin_critic is True and a.target_policy_sigma == 0.2


def test_no_overrides_is_identity():
    assert apply("walker_r2d2") == get_config("walker_r2d2")


def test_compute_dtype_override_reaches_nets():
    cfg = apply("walker_r2d2", "--compute-dtype", "bfloat16")
    assert cfg.compute_dtype == "bfloat16"
    env = cfg.env_factory()
    try:
        agent = cfg.build_agent(env)
        assert agent.actor.dtype == jnp.bfloat16
    finally:
        close = getattr(env, "close", None)
        if close:
            close()


def test_eval_twin_critic_flag():
    from r2d2dpg_tpu.eval import parse_args as eval_parse

    args = eval_parse(
        ["--config", "walker_r2d2", "--checkpoint-dir", "/tmp/x",
         "--twin-critic", "1"]
    )
    assert args.twin_critic == 1
