"""Target-network soft (Polyak) update.

Reference parity: SURVEY.md §2.4 "soft target update" — ``theta' <- tau*theta
+ (1-tau)*theta'`` every learner step, tau ~ 5e-3 (BASELINE config #4 names
soft-update explicitly).
"""

from __future__ import annotations

import jax


def polyak_update(online, target, tau: float):
    """``target <- tau * online + (1 - tau) * target`` over a pytree."""
    return jax.tree_util.tree_map(
        lambda o, t: tau * o + (1.0 - tau) * t, online, target
    )


def hard_update(online, target):
    """Target becomes the online params (initialization / periodic sync).

    JAX arrays are immutable, so returning ``online`` is a true snapshot.
    """
    del target
    return online
