"""Model tests: shapes, carried state, reset masking, scan-vs-loop equivalence
(SURVEY.md §4.1-4.2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2dpg_tpu.models import ActorNet, CriticNet, time_major, unroll


B, OBS, ACT, HID = 3, 5, 2, 32


def make_actor(use_lstm=True, pixels=False):
    net = ActorNet(action_dim=ACT, hidden=HID, use_lstm=use_lstm, pixels=pixels)
    obs = jnp.zeros((B, 64, 64, 3)) if pixels else jnp.zeros((B, OBS))
    carry = net.initial_carry(B)
    params = net.init(jax.random.PRNGKey(0), obs, carry, jnp.zeros(B))
    return net, params, carry, obs


def make_critic(use_lstm=True):
    net = CriticNet(hidden=HID, use_lstm=use_lstm)
    obs, act = jnp.zeros((B, OBS)), jnp.zeros((B, ACT))
    carry = net.initial_carry(B)
    params = net.init(jax.random.PRNGKey(0), obs, act, carry, jnp.zeros(B))
    return net, params, carry


@pytest.mark.parametrize("use_lstm", [True, False])
def test_actor_shapes_and_bounds(use_lstm):
    net, params, carry, _ = make_actor(use_lstm)
    obs = jax.random.normal(jax.random.PRNGKey(1), (B, OBS)) * 10
    a, carry2 = net.apply(params, obs, carry, jnp.zeros(B))
    assert a.shape == (B, ACT)
    assert np.all(np.abs(np.asarray(a)) <= 1.0)
    if use_lstm:
        assert jax.tree_util.tree_leaves(carry2)[0].shape == (B, HID)
    else:
        assert carry2 == ()


@pytest.mark.parametrize("use_lstm", [True, False])
def test_critic_shapes(use_lstm):
    net, params, carry = make_critic(use_lstm)
    obs = jax.random.normal(jax.random.PRNGKey(1), (B, OBS))
    act = jax.random.normal(jax.random.PRNGKey(2), (B, ACT))
    q, _ = net.apply(params, obs, act, carry, jnp.zeros(B))
    assert q.shape == (B,)


def test_pixel_actor():
    net, params, carry, obs = make_actor(pixels=True)
    a, _ = net.apply(
        params,
        jnp.zeros((B, 64, 64, 3), jnp.uint8),
        carry,
        jnp.zeros(B),
    )
    assert a.shape == (B, ACT)


def test_lstm_state_changes_and_affects_output():
    net, params, carry, _ = make_actor()
    obs = jax.random.normal(jax.random.PRNGKey(1), (B, OBS))
    a1, carry1 = net.apply(params, obs, carry, jnp.zeros(B))
    a2, _ = net.apply(params, obs, carry1, jnp.zeros(B))
    # Same obs, different carry -> different action (state matters).
    assert not np.allclose(np.asarray(a1), np.asarray(a2))


def test_reset_masks_carry_per_row():
    net, params, carry, _ = make_actor()
    obs = jax.random.normal(jax.random.PRNGKey(1), (B, OBS))
    _, carry1 = net.apply(params, obs, carry, jnp.zeros(B))
    # Row 0 resets: its step must equal a from-zero-state step.
    reset = jnp.array([1.0, 0.0, 0.0])
    a_mixed, _ = net.apply(params, obs, carry1, reset)
    a_zero, _ = net.apply(params, obs, carry, jnp.zeros(B))
    np.testing.assert_allclose(
        np.asarray(a_mixed)[0], np.asarray(a_zero)[0], rtol=1e-5, atol=1e-6
    )
    assert not np.allclose(np.asarray(a_mixed)[1], np.asarray(a_zero)[1])


def test_unroll_equals_step_loop():
    """lax.scan unroll == step-by-step python loop (SURVEY §4.2)."""
    net, params, carry, _ = make_actor()
    T = 7
    obs_seq = jax.random.normal(jax.random.PRNGKey(3), (T, B, OBS))
    resets = jnp.zeros((T, B)).at[3, 1].set(1.0)

    outs, final = unroll(
        lambda c, o, r: net.apply(params, o, c, r), carry, obs_seq, resets
    )

    c = carry
    expected = []
    for t in range(T):
        a, c = net.apply(params, obs_seq[t], c, resets[t])
        expected.append(a)
    np.testing.assert_allclose(
        np.asarray(outs), np.asarray(jnp.stack(expected)), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(jax.tree_util.tree_leaves(final)[0]),
        np.asarray(jax.tree_util.tree_leaves(c)[0]),
        rtol=1e-5,
        atol=1e-6,
    )


def test_time_major():
    x = jnp.zeros((4, 9, 2))
    assert time_major(x).shape == (9, 4, 2)


def test_jit_no_retrace():
    """Every jitted step compiles once across calls (SURVEY §4.2)."""
    net, params, carry, _ = make_actor()
    step = jax.jit(lambda p, o, c, r: net.apply(p, o, c, r))
    obs = jnp.zeros((B, OBS))
    step(params, obs, carry, jnp.zeros(B))
    n0 = step._cache_size()
    for _ in range(3):
        _, carry = step(params, obs, carry, jnp.zeros(B))
    assert step._cache_size() == n0 == 1


# ---------------------------------------------------------------- bf16 core
def test_bf16_net_keeps_fp32_carry():
    """Reduced-precision nets route through MixedPrecisionLSTMCell: the
    recurrent state must STAY float32 across steps (the round-3 dtype A/B
    showed bf16 state accumulation costs ~3x walker learning)."""
    net = ActorNet(action_dim=ACT, hidden=HID, use_lstm=True, dtype=jnp.bfloat16)
    obs = jnp.zeros((B, OBS))
    carry = net.initial_carry(B)
    params = net.init(jax.random.PRNGKey(0), obs, carry, jnp.zeros(B))
    for i in range(3):
        action, carry = net.apply(
            params, jnp.full((B, OBS), float(i)), carry, jnp.zeros(B)
        )
    for leaf in jax.tree_util.tree_leaves(carry):
        assert leaf.dtype == jnp.float32, leaf.dtype
    assert action.dtype == jnp.float32  # head output cast back


def test_mixed_cell_tracks_fp32_reference_better_than_bf16_state():
    """Property behind the design: with gate matmuls in bf16, keeping the
    state update in fp32 must track the all-fp32 reference much closer
    over a long unroll than also truncating the carry to bf16 each step
    (the old behavior)."""
    from r2d2dpg_tpu.models.actor_critic import MixedPrecisionLSTMCell

    T, hidden = 120, HID
    cell_ref = MixedPrecisionLSTMCell(hidden, dtype=jnp.float32)
    cell_mix = MixedPrecisionLSTMCell(hidden, dtype=jnp.bfloat16)
    x0 = jnp.zeros((B, hidden))
    c0 = (jnp.zeros((B, hidden)), jnp.zeros((B, hidden)))
    params = cell_ref.init(jax.random.PRNGKey(1), c0, x0)  # shared structure
    xs = 0.5 * jax.random.normal(jax.random.PRNGKey(2), (T, B, hidden))

    def run(cell, truncate_state):
        carry = c0
        hs = []
        for t in range(T):
            carry, h = cell.apply(params, carry, xs[t])
            if truncate_state:
                carry = jax.tree_util.tree_map(
                    lambda a: a.astype(jnp.bfloat16).astype(jnp.float32), carry
                )
            hs.append(h.astype(jnp.float32))
        return jnp.stack(hs)

    ref = run(cell_ref, False)
    mixed = run(cell_mix, False)
    old_bf16 = run(cell_mix, True)
    err_mixed = float(jnp.abs(mixed - ref).mean())
    err_old = float(jnp.abs(old_bf16 - ref).mean())
    assert err_mixed < err_old, (err_mixed, err_old)
    # And the mixed error is small in absolute terms (h is in [-1, 1]).
    assert err_mixed < 0.02, err_mixed


def test_fp32_default_path_unchanged_by_mixed_cell():
    """dtype=float32 must keep using the stock flax cell (param tree names
    include OptimizedLSTMCell, not the mixed cell)."""
    net, params, carry, obs = make_actor()
    names = str(jax.tree_util.tree_structure(params))
    assert "MixedPrecisionLSTMCell" not in names
    assert "OptimizedLSTMCell" in names  # not merely renamed/rerouted


def test_cross_dtype_param_tree_identical():
    """THE invariant behind fp32<->bf16 checkpoint interchange (VERDICT r4
    weak #2a): dtype selects a different cell IMPLEMENTATION (stock flax vs
    MixedPrecisionLSTMCell), but the param tree — structure, leaf shapes,
    and leaf dtypes (params are float32 under both) — must be identical,
    exactly as models/actor_critic.py's mixed-cell docstring promises.
    Round 3 shipped a mixed cell violating this and every fp32 checkpoint
    became unreadable under bf16 eval; this pins the fix against flax
    upgrades and future cell edits (ADVICE r4 #1)."""
    obs = jnp.zeros((B, OBS))
    act = jnp.zeros((B, ACT))
    reset = jnp.zeros(B)

    def actor_tree(dtype):
        net = ActorNet(action_dim=ACT, hidden=HID, use_lstm=True, dtype=dtype)
        return jax.eval_shape(
            net.init, jax.random.PRNGKey(0), obs, net.initial_carry(B), reset
        )

    def critic_tree(dtype):
        net = CriticNet(hidden=HID, use_lstm=True, dtype=dtype)
        return jax.eval_shape(
            net.init, jax.random.PRNGKey(0), obs, act, net.initial_carry(B), reset
        )

    for make in (actor_tree, critic_tree):
        t32, t16 = make(jnp.float32), make(jnp.bfloat16)
        assert jax.tree_util.tree_structure(t32) == jax.tree_util.tree_structure(
            t16
        ), f"{make.__name__}: fp32/bf16 param trees differ in structure"
        by_path16 = {
            jax.tree_util.keystr(p): l
            for p, l in jax.tree_util.tree_leaves_with_path(t16)
        }
        for path, l32 in jax.tree_util.tree_leaves_with_path(t32):
            l16 = by_path16[jax.tree_util.keystr(path)]
            assert l32.shape == l16.shape, (path, l32.shape, l16.shape)
            assert l32.dtype == l16.dtype == jnp.float32, (path, l32.dtype, l16.dtype)


def test_cross_dtype_params_apply_both_ways():
    """fp32-initialized params must run under the bf16 net and vice versa
    (the apply-side half of checkpoint interchange)."""
    obs = jnp.zeros((B, OBS))
    reset = jnp.zeros(B)
    nets = {
        d: ActorNet(action_dim=ACT, hidden=HID, use_lstm=True, dtype=jnp.dtype(d))
        for d in ("float32", "bfloat16")
    }
    carry = nets["float32"].initial_carry(B)
    for src, dst in (("float32", "bfloat16"), ("bfloat16", "float32")):
        params = nets[src].init(jax.random.PRNGKey(0), obs, carry, reset)
        a, c2 = nets[dst].apply(params, obs, carry, reset)
        assert a.shape == (B, ACT) and a.dtype == jnp.float32
        # the carry contract is fp32 under both cells
        assert all(
            l.dtype == jnp.float32 for l in jax.tree_util.tree_leaves(c2)
        )
