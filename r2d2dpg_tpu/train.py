"""Training entry point: ``python -m r2d2dpg_tpu.train --config walker_r2d2``.

Reference parity: SURVEY.md §2.5 — the reference's ``main.py`` parses flags,
spawns N actor processes + a learner and runs forever.  Here the same entry
drives the Anakin phase schedule (warm-up -> replay-fill -> train) on one
device or an SPMD mesh, wired to the aux subsystems of SURVEY §5:
checkpoint/resume (orbax), metrics (CSV + TensorBoard, return@wall-clock,
SPS), deterministic evaluation, profiler traces, NaN-debug mode.

Stop conditions: ``--phases N`` (exact phase count) and/or ``--minutes M``
(wall-clock budget — the BASELINE metric is return @ 30 min, so
``--minutes 30`` reproduces the north-star measurement).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import time
from typing import Optional

from r2d2dpg_tpu import topology
from r2d2dpg_tpu.configs import CONFIGS, ExperimentConfig, get_config


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="python -m r2d2dpg_tpu.train", description=__doc__
    )
    p.add_argument("--config", required=True, choices=sorted(CONFIGS))
    p.add_argument("--phases", type=int, default=None, help="train phases to run")
    p.add_argument(
        "--minutes", type=float, default=None, help="wall-clock budget (stops at whichever of --phases/--minutes hits first)"
    )
    p.add_argument("--logdir", default=None, help="metrics/TB/profile output dir")
    p.add_argument("--log-every", type=int, default=50, help="phases between logs")
    p.add_argument("--seed", type=int, default=None)
    # Orchestration scale overrides (SURVEY §2.5 hyperparameter flags).
    p.add_argument("--num-envs", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument(
        "--lr-scale-batch", type=int, default=0, choices=[0, 1],
        help="scale actor/critic learning rates linearly with the batch "
        "size (Accelerated Methods, PAPERS.md 1803.02811): the resolved "
        "lrs are multiplied by batch_size / <config default batch> — the "
        "large-batch recipe the composed topology's sampling bandwidth "
        "(--actors x --replay-shards x --learner-dp) makes reachable.  "
        "Applied to the RESOLVED lrs (after --actor-lr/--critic-lr "
        "overrides); a no-op scale of 1.0 is printed, never silent"
    )
    p.add_argument("--learner-steps", type=int, default=None)
    p.add_argument("--min-replay", type=int, default=None)
    p.add_argument(
        "--param-sync-every", type=int, default=None,
        help="refresh behavior params every K phases (0 = always fresh)"
    )
    p.add_argument(
        "--overlap-learner", type=int, default=None, choices=[0, 1],
        help="host-pool trainers: interleave learner updates between env "
        "steps so they hide under the MuJoCo step (1 = on)"
    )
    p.add_argument(
        "--pipeline", type=int, default=0, choices=[0, 1],
        help="run train phases through the pipelined collect/learn "
        "executor (training/pipeline.py): collection and learning overlap "
        "in two threads over a bounded staging queue (1 = on)"
    )
    p.add_argument(
        "--pipeline-depth", type=int, default=2,
        help="staging-queue capacity in collect phases (backpressure bound)"
    )
    # Fleet mode (docs/FLEET.md): supervised out-of-process actors.
    p.add_argument(
        "--actors", type=int, default=0, metavar="N",
        help="spawn N supervised actor subprocesses streaming experience "
        "to a learner-side ingest server (0 = off: the in-process "
        "schedules, untouched)"
    )
    p.add_argument(
        "--fleet-address", default="127.0.0.1:0",
        help="ingest server bind: 'host:port' (port 0 = ephemeral) or "
        "'unix:/path'"
    )
    p.add_argument(
        "--fleet-queue-depth", type=int, default=4,
        help="staging-queue capacity in staged batches (past it the "
        "ingest server sheds loudly)"
    )
    p.add_argument(
        "--fleet-publish-every", type=int, default=1,
        help="drain phases between versioned param publications to actors"
    )
    p.add_argument(
        "--fleet-idle-timeout", type=float, default=300.0,
        help="seconds without a staged batch before the learner aborts as "
        "starved (the first batch gets double: actor spawn + compile)"
    )
    p.add_argument(
        "--fleet-shed-after", type=float, default=None, metavar="S",
        help="seconds a queue-full ingest handler waits before shedding a "
        "staged batch (past the startup grace; default 1.0).  Larger = "
        "backpressure posture: surplus actors park in the ack wait "
        "instead of re-collecting shed experience (the bench probes' "
        "throughput setting); smaller = freshness posture"
    )
    # Fleet wire fast lane (docs/FLEET.md "Wire format"): one negotiated
    # encoding per fleet; actors are spawned with matching flags.
    p.add_argument(
        "--fleet-wire", default="f32", choices=["f32", "bf16"],
        help="payload precision on the fleet wire: f32 = bit-exact "
        "(default), bf16 = observations/carries/params at half the bytes "
        "(rewards/priorities stay f32; restored to f32 learner-side)"
    )
    p.add_argument(
        "--fleet-compress", default="none", choices=["none", "zlib", "zstd"],
        help="fleet frame compression (zstd refused where the zstandard "
        "module is absent; the decompressed-size ceiling is enforced "
        "before allocation)"
    )
    p.add_argument(
        "--drain-coalesce", type=int, default=1, metavar="K",
        help="stack up to K queue-backlogged staged batches into one "
        "compiled arena-add drain call (1 = one call per batch; widths "
        "are bucketed to powers of two <= K to bound drain-program "
        "compiles)"
    )
    # In-network experience sampling (docs/REPLAY.md): replay sharded at
    # the ingest edge, learner pulls training-ready batches.
    p.add_argument(
        "--replay-shards", type=int, default=0, metavar="N",
        help="shard prioritized replay across N ingest-edge shards "
        "(fleet/sampler.py): each actor's SEQS traffic feeds its "
        "consistent-hash shard directly (no central drain thread), and "
        "the learner PULLS batches via SAMPLE_REQ/BATCH frames with "
        "quotas proportional to each shard's priority sum — two-level "
        "sampling that preserves the central proportional distribution; "
        "TD priorities ride back as versioned PRIO frames.  Requires "
        "--actors N (with --actors 0 only --replay-shards 1 is accepted "
        "and routes the untouched phase-locked loop — the determinism "
        "anchor).  0 = off (central drain)"
    )
    p.add_argument(
        "--shard-procs", type=int, default=0, metavar="N",
        help="host the --replay-shards M replay shards in N supervised "
        "STANDALONE shard processes (fleet/shard.py; M %% N == 0, one "
        "listening socket per shard, HELLO-auth'd frames on the "
        "negotiated wire lane): the replay tier becomes its own failure "
        "domain — a dead shard degrades sampling (quotas renormalize "
        "over survivors within a phase, handlers re-route), never "
        "training, and the supervisor's backoff restart rejoins it EMPTY "
        "under a bumped epoch that fences stale BATCH/PRIO traffic.  "
        "0 = in-learner loopback (PR 10's path, pinned bit-identical)"
    )
    # Direct data plane + concurrent pullers (ISSUE 17; docs/REPLAY.md
    # "Direct data plane").
    p.add_argument(
        "--shard-direct", type=int, default=0, choices=[0, 1],
        help="1: the ingest ack advertises each actor's shard assignment "
        "(consistent-hash shard + its dialable address + epoch) and the "
        "actor ships SEQS straight to the shard — the learner wire "
        "carries only params/telem/accounting (a tiny K_STATS frame per "
        "phase), shedding the ingest forward hop from the experience "
        "path.  Requires --actors N --replay-shards M; with "
        "--shard-procs 0 there is no dialable tier, so actors stay on "
        "the learner-forwarded path (the documented fallback, also "
        "taken loudly on any data-leg failure).  0 = learner-forwarded "
        "(pinned bit-identical)"
    )
    p.add_argument(
        "--shard-pullers", type=int, default=0, metavar="N",
        help="concurrent SAMPLE_REQ pullers over the replay shards "
        "(fleet/sampler.py): each quota round keeps one in-flight "
        "request per live shard, up to N at once — draw quotas and "
        "req-id assignment stay in shard-id order, so the pulled batch "
        "is bit-identical to the serial loop regardless of arrival "
        "order.  0 = one puller per shard, capped at 8; 1 = the serial "
        "loop"
    )
    p.add_argument(
        "--shard-prefetch", type=int, default=0, choices=[0, 1],
        help="1: overlap one phase of batch prefetch with training — the "
        "next phase's pull starts while the current batch trains "
        "(priorities it samples under are stale by exactly the one "
        "phase in flight, the documented Reverb-style tradeoff).  "
        "0 = off (pull inline; pinned bit-identical)"
    )
    # Fleet fault tolerance (docs/FLEET.md "Failure modes & recovery").
    p.add_argument(
        "--fleet-heartbeat", type=float, default=None, metavar="S",
        help="liveness read deadline on both fleet wire ends (default "
        "300): a peer silent past it is PINGed once and reaped on a "
        "second silence (peer_dead flight event; the actor exits "
        "retryably and the supervisor restarts it)"
    )
    p.add_argument(
        "--fleet-token", default=None,
        help="shared HELLO-authentication secret (hmac.compare_digest at "
        "the ingest door; mismatched actors are refused with "
        "REFUSED_AUTH).  REQUIRED practice for non-loopback "
        "--fleet-address binds; defaults to $R2D2DPG_FLEET_TOKEN — "
        "PREFER the env var, an argv secret is readable in ps — and is "
        "passed to spawned actors via the environment, never their "
        "command line"
    )
    p.add_argument(
        "--chaos-spec", default=None, metavar="SPEC",
        help="seeded fault-injection schedule (fleet/chaos.py), e.g. "
        "'kill_actor@p3,stall_actor@p5:4s,corrupt_frame@p7,"
        "kill_ingest_conn@p9' — each fault fires once at its drain/actor "
        "phase, at a real boundary (SIGKILL, sleep, byte flip, socket "
        "close), and must recover through the documented path; every "
        "injection lands in flight.jsonl + "
        "r2d2dpg_fleet_chaos_drills_total"
    )
    # Autoscaler (docs/FLEET.md "Autoscaling", ISSUE 16): the
    # health→actuation policy loop over the fleet supervisor.
    p.add_argument(
        "--autoscale", type=int, default=0, choices=[0, 1],
        help="close the health→actuation loop (fleet/autoscaler.py): a "
        "policy thread evaluates the in-process health engine and maps "
        "findings to hysteresis-gated spawn/kill/replace actions through "
        "the supervisor's runtime resize API; crashed actors are "
        "replaced by POLICY (SupervisorConfig restart='policy') instead "
        "of the reflexive backoff ladder.  0 = off (structurally inert; "
        "default)"
    )
    p.add_argument(
        "--autoscale-dry-run", type=int, default=0, choices=[0, 1],
        help="walk the full decision path — streaks, cooldown, window "
        "budget — logging autoscale_decision events, but never actuate "
        "(the supervisor keeps its reflexive ladder)"
    )
    p.add_argument(
        "--autoscale-min", type=int, default=1, metavar="N",
        help="scale-down floor on the actor population (default 1)"
    )
    p.add_argument(
        "--autoscale-max", type=int, default=0, metavar="N",
        help="scale-up ceiling on the actor population; also the GLOBAL "
        "sigma-ladder width (actors spawn with --num-actors max so every "
        "mintable lane has its own exploration sigma).  0 = pinned to "
        "--actors (no scale-up; default)"
    )
    p.add_argument(
        "--autoscale-cooldown", type=float, default=30.0, metavar="S",
        help="minimum seconds between landed autoscale actions (default "
        "30)"
    )
    p.add_argument(
        "--autoscale-every", type=float, default=2.0, metavar="S",
        help="health-evaluation cadence of the policy loop (default 2)"
    )
    p.add_argument(
        "--autoscale-fire", type=int, default=3, metavar="K",
        help="consecutive evaluations a health rule must fire before it "
        "may act (hysteresis; default 3)"
    )
    # Agent/exploration hyperparameter overrides (VERDICT r2 weak #3: probe
    # whether the walker plateau is data-bound or hparam-capped).
    p.add_argument("--sigma-max", type=float, default=None,
                   help="exploration noise ladder max sigma")
    p.add_argument("--ladder-alpha", type=float, default=None,
                   help="noise ladder spread exponent")
    p.add_argument("--n-step", type=int, default=None, help="n-step TD horizon")
    p.add_argument("--actor-lr", type=float, default=None)
    p.add_argument("--critic-lr", type=float, default=None)
    # Overestimation mitigations (agents/ddpg.py AgentConfig; default off).
    p.add_argument(
        "--twin-critic", type=int, default=None, choices=[0, 1],
        help="TD3 clipped double-Q: train a 2-critic ensemble, bootstrap "
        "from min(Q1',Q2') (eval needs the same flag to restore)"
    )
    p.add_argument(
        "--target-policy-sigma", type=float, default=None,
        help="TD3 target-policy smoothing noise scale (0 = off)"
    )
    p.add_argument(
        "--compute-dtype", default=None, choices=["float32", "bfloat16"],
        help="net activation dtype (params/optimizer stay float32)"
    )
    # SPMD.
    p.add_argument(
        "--spmd", type=int, default=0, metavar="D",
        help="run under shard_map on a D-device dp mesh (0 = single device)"
    )
    p.add_argument(
        "--learner-dp", type=int, default=0, metavar="D",
        help="data-parallel LEARNER over a D-device dp mesh "
        "(parallel/dp_learner.py): replay arena capacity-sharded, learner "
        "batch dp-sharded, params replicated.  Composes with --actors N "
        "(the fleet feeds a multi-chip learner — docs/FLEET.md "
        "'Multi-chip learner') and with --actors 0 (pure-JAX env configs "
        "only; --learner-dp 1 is pinned bit-identical to the plain "
        "schedule).  On CPU use XLA_FLAGS="
        "--xla_force_host_platform_device_count=D.  0 = off"
    )
    # Checkpointing.
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument(
        "--checkpoint-every", type=int, default=500,
        help="phases between checkpoints (0 = off entirely; -1 = final-"
        "save-only, e.g. for measurement runs where periodic saves would "
        "drag the GB-scale replay arena device->host mid-run)"
    )
    p.add_argument(
        "--checkpoint-light", action="store_true",
        help="save only the learner subtree (params/targets/opt/step): MBs "
        "instead of GBs, eval-compatible; resume restarts replay fresh"
    )
    p.add_argument("--resume", action="store_true", help="resume from the latest checkpoint in --checkpoint-dir")
    # Evaluation.
    p.add_argument("--eval-every", type=int, default=0, help="train phases between deterministic evals (0 = off)")
    p.add_argument("--eval-envs", type=int, default=10)
    # Debug / profiling.
    p.add_argument("--profile-phases", type=int, default=0, help="trace this many train phases into --logdir/profile")
    p.add_argument(
        "--profile-window", default=None, metavar="P:N",
        help="device-plane profiler capture (obs/device.py): run "
        "jax.profiler for N train/drain phases starting at phase P into "
        "<logdir>/profile_window, on WHICHEVER learner loop the run "
        "resolves to (phase-locked, pipelined, fleet drain, sampler "
        "pull).  profile_start/profile_stop flight events bracket the "
        "capture, and 'obs.flight merge --trace-out' stamps it as a "
        "labelled profile_window span in the fused Perfetto timeline.  "
        "Mutually exclusive with --profile-phases (one jax profiler "
        "session per process); requires --logdir"
    )
    p.add_argument(
        "--device-peak-flops", type=float, default=0.0, metavar="FLOPS",
        help="the accelerator's peak FLOP/s for the r2d2dpg_device_mfu "
        "gauge (e.g. 1.97e14 for a TPU v5p core-pair at bf16).  0 = "
        "unknown: the gauge stays 0 rather than inventing a denominator"
    )
    p.add_argument("--nan-debug", action="store_true")
    # Observability (docs/OBSERVABILITY.md).
    p.add_argument(
        "--obs-port", type=int, default=None, metavar="PORT",
        help="serve the telemetry registry over HTTP: /metrics (Prometheus "
        "text) + /metrics.json (JSON snapshot); 0 binds an ephemeral port "
        "(resolved port printed and written to <logdir>/obs_port.txt)"
    )
    p.add_argument(
        "--obs-host", default="0.0.0.0",
        help="interface the --obs-port exporter binds (127.0.0.1 = "
        "loopback-only on shared hosts)"
    )
    p.add_argument(
        "--flight-path", default=None,
        help="flight-recorder dump path (default <logdir>/flight.jsonl, "
        "or ./flight.jsonl without --logdir); sampled trace spans dump "
        "to trace.json next to it"
    )
    p.add_argument(
        "--obs-fleet", type=int, default=0, choices=[0, 1],
        help="fleet-wide metric aggregation: with --actors N, actors push "
        "~1 Hz TELEM registry snapshots that fold into this process's "
        "/metrics under actor=/host= labels (one scrape point per fleet, "
        "with per-actor staleness gauges); with --shard-procs N the "
        "standalone shard processes push the same TELEM over their "
        "authenticated learner legs (shard=/host= labels, per-shard "
        "staleness armed at HELLO and reset on epoch-bumped rejoin); on "
        "a multi-process SPMD run, registry scalars process_allgather "
        "into process 0's exporter"
    )
    # /health verdict thresholds (obs/health.py; the endpoint rides
    # --obs-port's exporter — docs/OBSERVABILITY.md "/health verdicts").
    p.add_argument(
        "--health-wait-p99", type=float, default=0.5, metavar="S",
        help="/health 'learner_starving' threshold: learner/sampler wait "
        "p99 above this reads as the fleet failing to feed the learner"
    )
    p.add_argument(
        "--health-stale-after", type=float, default=10.0, metavar="S",
        help="/health 'telem_stale' threshold: an actor's or shard's "
        "TELEM staleness above this reads as wedged/partitioned/dead"
    )
    p.add_argument(
        "--quality-max-lag", type=float, default=100.0, metavar="N",
        help="/health 'stale_experience' threshold: policy-lag p99 "
        "(learner param version minus the behavior version stamped on "
        "trained sequences, obs/quality.py) above this reads as the "
        "learner training on stale experience"
    )
    p.add_argument(
        "--trace-sample", type=float, default=0.0, metavar="RATE",
        help="experience-path tracing: sample this fraction of staged "
        "batches and record per-hop spans (collect -> encode -> transit "
        "-> decode -> enqueue -> coalesce -> arena_add -> learn) into "
        "r2d2dpg_trace_*_seconds histograms and a Chrome-trace/Perfetto "
        "trace.json next to flight.jsonl (0 = off: no per-sequence "
        "overhead, wire bytes unchanged)"
    )
    p.add_argument(
        "--watchdog", type=int, default=1, choices=[0, 1],
        help="divergence watchdog on the log cadence: NaN/Inf or norm "
        "blow-up in learner outputs aborts loudly with a flight-recorder "
        "dump and a last-good-checkpoint pointer (1 = on)"
    )
    p.add_argument("--watchdog-grad-norm", type=float, default=1e6,
                   help="watchdog trip threshold for grad_norm")
    p.add_argument("--watchdog-param-norm", type=float, default=1e7,
                   help="watchdog trip threshold for param_norm")
    p.add_argument(
        "--nan-inject-phase", type=int, default=None, metavar="K",
        help="FAULT INJECTION (tests/drills): poison the actor params with "
        "NaN after the K-th train phase, so the next learner update "
        "produces non-finite outputs and the watchdog path is exercised "
        "end to end"
    )
    return p.parse_args(argv)


def _apply_overrides(cfg: ExperimentConfig, args) -> ExperimentConfig:
    t = {}
    for flag, field in (
        ("num_envs", "num_envs"),
        ("batch_size", "batch_size"),
        ("learner_steps", "learner_steps"),
        ("min_replay", "min_replay"),
        ("param_sync_every", "param_sync_every"),
        ("overlap_learner", "overlap_learner"),
        ("seed", "seed"),
        ("sigma_max", "sigma_max"),
        ("ladder_alpha", "ladder_alpha"),
    ):
        v = getattr(args, flag)
        if v is not None:
            t[field] = bool(v) if field == "overlap_learner" else v
    if t:
        cfg = dataclasses.replace(
            cfg, trainer=dataclasses.replace(cfg.trainer, **t)
        )
    a = {}
    for flag in ("n_step", "actor_lr", "critic_lr", "target_policy_sigma"):
        v = getattr(args, flag)
        if v is not None:
            a[flag] = v
    if args.twin_critic is not None:
        a["twin_critic"] = bool(args.twin_critic)
    if a:
        cfg = dataclasses.replace(
            cfg, agent=dataclasses.replace(cfg.agent, **a)
        )
    if args.compute_dtype is not None:
        cfg = dataclasses.replace(cfg, compute_dtype=args.compute_dtype)
    return cfg


def _health_config(args) -> "obs.HealthConfig":
    """The run's resolved /health thresholds + expected process counts.

    One builder for BOTH consumers — the exporter's armed engine and the
    fleet teardown's health_final.json fallback — so evidence stamped by
    a run without a live exporter still judges against the real spawn
    targets (a default HealthConfig has expected_actors=0 and
    expected_shard_procs=0, which disarms actors_down/shards_down and
    would stamp a dead shard tier as 'ok')."""
    from r2d2dpg_tpu import obs

    return obs.HealthConfig(
        learner_wait_p99_s=args.health_wait_p99,
        telem_stale_after_s=args.health_stale_after,
        expected_actors=args.actors or 0,
        expected_shard_procs=args.shard_procs or 0,
        # Staleness clocks arm at HELLO regardless, but TELEM pushes only
        # ride --obs-fleet — without it a growing clock is configuration,
        # not a wedged peer.
        telem_expected=bool(getattr(args, "obs_fleet", 0)),
        quality_max_lag=args.quality_max_lag,
    )


def run(args) -> dict:
    """Drive one experiment; returns the final metrics dict."""
    import jax

    from r2d2dpg_tpu import obs
    from r2d2dpg_tpu.training.evaluator import Evaluator
    from r2d2dpg_tpu.utils import (
        CheckpointManager,
        MetricLogger,
        nan_debug,
        profile_trace,
    )
    from r2d2dpg_tpu.utils.checkpoint import resume_state

    if args.nan_debug:
        nan_debug(True)

    # ONE validation authority (ISSUE 11): every still-refused knob
    # pairing lives in topology.REFUSALS with its documented reason —
    # there are no ad-hoc refusal branches here.  The resolved Topology
    # names the four stages (collect/ingest/sample/learn) this run
    # assembles below (docs/TOPOLOGY.md has the composition matrix).
    topo = topology.validate(args, process_count=jax.process_count())
    if args.actors and args.replay_shards > args.actors:
        # Integer actor ids route round-robin, so only
        # min(actors, shards) shards ever get a feed: the surplus
        # shards stay empty forever and effective replay capacity
        # silently shrinks to that fraction — never silently.
        print(
            f"replay-shards: WARNING — {args.replay_shards} shards "
            f"but only {args.actors} actors: "
            f"{args.replay_shards - args.actors} shards will never "
            f"receive traffic and effective replay capacity is "
            f"{args.actors}/{args.replay_shards} of the configured "
            f"capacity (docs/REPLAY.md 'Topology')",
            flush=True,
        )

    cfg = _apply_overrides(get_config(args.config), args)
    if args.lr_scale_batch:
        # Linear lr/batch co-scaling (PAPERS.md 1803.02811): lr follows
        # batch relative to the config's recorded recipe.  Applied to the
        # RESOLVED values so explicit --actor-lr/--critic-lr overrides
        # scale too; a scale of 1.0 is printed, never silent.
        base_batch = get_config(args.config).trainer.batch_size
        scale = cfg.trainer.batch_size / base_batch
        cfg = dataclasses.replace(
            cfg,
            agent=dataclasses.replace(
                cfg.agent,
                actor_lr=cfg.agent.actor_lr * scale,
                critic_lr=cfg.agent.critic_lr * scale,
            ),
        )
        print(
            f"lr-scale-batch: linear rule (1803.02811) batch "
            f"{base_batch} -> {cfg.trainer.batch_size}, scale {scale:g} "
            f"(actor_lr {cfg.agent.actor_lr:g}, critic_lr "
            f"{cfg.agent.critic_lr:g})",
            flush=True,
        )

    if args.replay_shards and not args.actors:
        print(
            "replay-shards: no fleet (--actors 0) — replay stays in the "
            "central device arena and the phase-locked schedule runs "
            "unchanged (the determinism anchor, docs/REPLAY.md)",
            flush=True,
        )
    replay_capacity = cfg.trainer.capacity
    if args.replay_shards and args.actors:
        reachable = (replay_capacity // args.replay_shards) * min(
            args.actors, args.replay_shards
        )
        if cfg.trainer.min_replay > reachable:
            # The absorb gate waits for min_replay resident sequences,
            # but only min(actors, shards) shards ever receive traffic:
            # an unreachable gate would die after idle_timeout with a
            # misleading "starved" error against a healthy fleet.
            raise SystemExit(
                f"--replay-shards: min_replay {cfg.trainer.min_replay} "
                f"exceeds the reachable shard occupancy {reachable} "
                f"({args.actors} actors feed min(actors, shards) of "
                f"{args.replay_shards} shards x "
                f"{replay_capacity // args.replay_shards} slots) — "
                f"lower --min-replay or --replay-shards"
            )
        # Sampler mode: replay lives in the host-side ingest shards
        # (which get ``replay_capacity``, captured above), so the
        # trainer's device arena is structural only — shrink it to a
        # token allocation instead of reserving the config's full
        # capacity in HBM for buffers that stay init-zeros.  min_replay
        # is untouched (it gates the sampler's absorb phase).
        import dataclasses as _dc

        cfg = _dc.replace(
            cfg,
            trainer=_dc.replace(
                cfg.trainer,
                capacity=max(cfg.trainer.num_envs, cfg.trainer.batch_size),
            ),
        )

    trainer = topology.build_trainer(topo, cfg)

    # Stamp the resolved backend where automation can gate on it: a TPU
    # campaign step that silently fell back to CPU must not be mistaken
    # for an on-chip result (round-3 campaign gates .done markers on this).
    backend = jax.default_backend()
    print(f"backend: {backend}", flush=True)
    print(f"topology: {topo.describe()}", flush=True)
    if args.logdir:
        os.makedirs(args.logdir, exist_ok=True)
        with open(os.path.join(args.logdir, "backend.txt"), "w") as f:
            f.write(backend + "\n")
        with open(os.path.join(args.logdir, "topology.txt"), "w") as f:
            f.write(topo.describe() + "\n")

    # ------------------------------------------------------------ telemetry
    # Flight recorder is ALWAYS armed (an in-memory ring is ~free; the dump
    # is exit-time); the exporter and the CSV-bridge fold are --obs-port
    # opt-in; the watchdog is on by default (--watchdog 0 to drop it).
    registry = obs.get_registry()
    flight = obs.get_flight_recorder()
    # Device plane (ISSUE 14, docs/OBSERVABILITY.md "Device plane"):
    # compile sentinel + HBM/MFU gauges are always armed (the listener is
    # ~free; gauges ride the log cadence); the profiler window is opt-in.
    device_mon = obs.get_device_monitor().install()
    device_mon.configure(peak_flops=args.device_peak_flops)
    if args.profile_window is not None:
        if args.profile_phases:
            raise SystemExit(
                "--profile-window and --profile-phases both drive the one "
                "jax profiler session this process has — pick one "
                "(--profile-window works on every learner loop and is "
                "the superset)"
            )
        if not args.logdir:
            raise SystemExit("--profile-window requires --logdir")
        try:
            pw_phase, pw_steps = device_mon.arm_profile(
                args.profile_window,
                os.path.join(args.logdir, "profile_window"),
            )
        except ValueError as e:
            raise SystemExit(f"--profile-window: {e}")
        print(
            f"obs: profiler capture armed for phases "
            f"{pw_phase}..{pw_phase + pw_steps - 1} -> "
            f"{args.logdir}/profile_window",
            flush=True,
        )
    # Identity stamp (docs/FLEET.md post-mortems): every event this process
    # records says which host of a multi-process fleet it came from, so
    # interleaved flight.jsonl dumps stay attributable.
    obs.set_flight_identity(process_index=jax.process_index())
    flight_path = args.flight_path or (
        os.path.join(args.logdir, "flight.jsonl")
        if args.logdir
        else "flight.jsonl"
    )
    if args.logdir or args.flight_path:
        # Exit-time dump armed only when the operator named a destination
        # (no surprise ./flight.jsonl litter from bare smoke runs); the
        # watchdog abort path dumps explicitly either way.
        flight.install(flight_path)
    exporter = None
    if args.obs_port is not None:
        exporter = obs.start_exporter(args.obs_port, registry, args.obs_host)
        # The /health verdict engine (ISSUE 13 leg 3), armed with this
        # run's RESOLVED topology so actors_down/shards_down compare
        # against the real spawn targets — the autoscaler's input
        # contract, live from the first scrape.  arm_health(): the server
        # is already answering GETs, and the handler's lazy default must
        # never outrace this configured engine.
        exporter.arm_health(
            obs.HealthEngine(
                _health_config(args),
                registry=registry,
                mirror=obs.get_remote_mirror(),
            )
        )
        print(
            f"obs: /metrics + /metrics.json + /health on port "
            f"{exporter.port}",
            flush=True,
        )
        if args.logdir:
            with open(os.path.join(args.logdir, "obs_port.txt"), "w") as f:
                f.write(f"{exporter.port}\n")
    watchdog = (
        obs.DivergenceWatchdog(
            obs.WatchdogConfig(
                grad_norm_max=args.watchdog_grad_norm,
                param_norm_max=args.watchdog_param_norm,
            )
        )
        if args.watchdog
        else None
    )

    ckpt: Optional[CheckpointManager] = None
    if args.checkpoint_dir:
        light = args.checkpoint_light
        if args.actors and not light:
            # The fleet recovery contract (docs/FLEET.md): a fleet
            # checkpoint is the learner subtree + counter sidecar — the
            # replay arena is NEVER checkpointed (GBs of re-collectable
            # experience; resume re-enters absorb-to-min_replay).
            print(
                "fleet: checkpoints under --actors N are always light "
                "(learner subtree + counters; the arena is re-absorbed "
                "on resume — docs/FLEET.md)",
                flush=True,
            )
            light = True
        ckpt = CheckpointManager(
            args.checkpoint_dir,
            save_every=args.checkpoint_every,
            light=light,
        )

    evaluator: Optional[Evaluator] = None
    if args.eval_every:
        evaluator = Evaluator(
            cfg.env_factory(), trainer.agent.actor, num_envs=args.eval_envs
        )

    logger = MetricLogger(
        args.logdir, registry=registry if exporter is not None else None
    )
    deadline = (
        time.monotonic() + args.minutes * 60 if args.minutes is not None else None
    )

    if args.resume and ckpt is None:
        raise SystemExit("--resume requires --checkpoint-dir")
    if args.resume and not args.actors:
        state = resume_state(trainer, ckpt)
        if hasattr(trainer, "_shardings"):
            # dp-mesh trainers: restored leaves land single-device; put
            # them back on the mesh layout or the next jit call sees
            # inputs spanning mismatched device sets.
            state = jax.device_put(state, trainer._shardings)
        print(f"resumed from phase {int(state.phase_idx)}", flush=True)
    else:
        # Fleet resume is handled inside _run_fleet: the learner never
        # collects, so the generic resume_state's window-refill collect
        # phases would compile a program this process never runs.
        state = trainer.init()

    if args.pipeline:
        return _run_pipelined(
            trainer, state, logger, ckpt, args, watchdog, flight, flight_path
        )
    if args.actors:
        return _run_fleet(
            trainer, cfg, state, logger, ckpt, args, watchdog, flight,
            flight_path, replay_capacity=replay_capacity, topo=topo,
        )

    warm = trainer.window_fill_phases
    fill = warm + trainer.replay_fill_phases
    eval_key = jax.random.PRNGKey(cfg.trainer.seed + 1)
    last_learn = {}
    final = {}
    train_phases_done = 0
    diverged = False
    phase = start = int(state.phase_idx)
    # --phases counts *train* phases for this invocation: a fresh run stops
    # after fill + N, a resumed one after N more from wherever it restarted.
    stop_at = (
        max(start, fill) + args.phases if args.phases is not None else None
    )
    profile_until = None
    profiler_cm = None
    device_mon.begin_run()

    try:
        while True:
            if stop_at is not None and phase >= stop_at:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            if stop_at is None and deadline is None and phase >= fill + 1:
                break  # nothing requested: run a single train phase (smoke)

            if phase < warm:
                state = trainer.collect_phase(state)
            elif phase < fill:
                state = trainer.fill_phase(state)
            else:
                if (
                    args.profile_phases
                    and args.logdir
                    and profile_until is None
                ):
                    profile_until = phase + args.profile_phases
                    profiler_cm = profile_trace(f"{args.logdir}/profile")
                    profiler_cm.__enter__()
                device_mon.on_phase(train_phases_done + 1)
                if train_phases_done == 0:
                    # MFU numerator: one lazy lower() of the fused phase
                    # at these avals, evaluated on the log cadence.
                    st_avals = obs.device.avals_of(state)
                    device_mon.set_learn_cost(
                        lambda: obs.device.flops_of(
                            trainer.train_phase.lower(st_avals)
                        )
                    )
                with device_mon.program("train_phase"):
                    state, last_learn = trainer.train_phase(state)
                device_mon.note_learn()
                train_phases_done += 1
                if train_phases_done == 1:
                    # The fused phase program is warm: the compile
                    # sentinel arms — a post-steady compile outside a
                    # declared window (log fetch, eval, drills) is the
                    # aval-re-key alarm (docs/OBSERVABILITY.md).
                    device_mon.mark_steady()
                if train_phases_done == args.nan_inject_phase:
                    with device_mon.expected("nan_inject"):
                        state = _poison_actor_params(state)
                if profiler_cm is not None and phase + 1 >= profile_until:
                    jax.block_until_ready(state.train.step)
                    profiler_cm.__exit__(None, None, None)
                    profiler_cm = None
            phase += 1

            if args.log_every and phase % args.log_every == 0:
                # expected(): the log fetch builds small eager reductions
                # on first use — declared, never a sentinel alarm.
                with device_mon.expected("log_fetch"):
                    state, ep = trainer.pop_episode_metrics(state)
                    scalars = dict(ep)
                    # ONE batched fetch for learn metrics + the step
                    # counter (per-scalar float() casts were N+1 blocking
                    # host syncs).
                    learn_np, lstep = jax.device_get(
                        (last_learn, state.train.step)
                    )
                scalars.update(
                    {k: float(v) for k, v in learn_np.items()}
                )
                trainer._obs_publish({"learner_steps": float(lstep)})
                watch_scalars = dict(scalars)
                scalars.update(
                    logger.rates(
                        env_steps=ep["env_steps"],
                        learner_steps=float(lstep),
                    )
                )
                logger.log(phase, scalars)
                final = scalars
                if args.obs_fleet and jax.process_count() > 1:
                    # Multi-process leg of the fleet observability plane:
                    # COLLECTIVE (every process logs on the same cadence),
                    # folds rank >0 registries into process 0's exporter.
                    obs.allgather_into_mirror()
                if watchdog is not None:
                    # Rides the fetch above — no extra host syncs; checked
                    # AFTER the log call so the poisoned row is on disk as
                    # forensic evidence when the run aborts.
                    watchdog.check(phase, watch_scalars)

            if ckpt is not None and ckpt.save_every:
                ckpt.maybe_save(phase, state)

            if (
                evaluator is not None
                and phase > fill
                and (phase - fill) % args.eval_every == 0
            ):
                eval_key, k = jax.random.split(eval_key)
                # Eval compiles its own programs on first use: a declared
                # window, not an aval re-key of the training chain.
                with device_mon.expected("eval"):
                    ev = evaluator.run(state.train.actor_params, k)
                # Stamp the monotone env-step counter so eval-vs-steps
                # curves read directly off the CSV/TB row.
                ev["env_steps"] = float(state.env_steps)
                logger.log(phase, ev)
                final.update(ev)
    except obs.DivergenceError as e:
        diverged = True
        _abort_on_divergence(e, flight, flight_path, ckpt)
    finally:
        # Sentinel disarmed FIRST: the final save / logger close below
        # belong to teardown, not the steady window.
        device_mon.end_run()
        if profiler_cm is not None:
            profiler_cm.__exit__(None, None, None)
        if ckpt is not None:
            if ckpt.save_every and not diverged:
                # A diverged state must NOT become the "final" checkpoint —
                # it would shadow the last good one the abort points at.
                ckpt.save_final(phase, state)
            ckpt.wait()
            ckpt.close()
        logger.close()
    return final


def _poison_actor_params(state):
    """--nan-inject-phase fault injection: NaN every actor-param leaf, so
    the next learner update's outputs (losses, norms) go non-finite through
    the REAL divergence propagation path."""
    import jax
    import jax.numpy as jnp

    poisoned = jax.tree_util.tree_map(
        lambda x: jnp.full_like(x, jnp.nan), state.train.actor_params
    )
    return dataclasses.replace(
        state, train=dataclasses.replace(state.train, actor_params=poisoned)
    )


def _abort_on_divergence(e, flight, flight_path, ckpt) -> None:
    """Watchdog trip: dump the flight ring, point at the last good
    checkpoint, exit non-zero (SystemExit(2))."""
    import sys

    flight.record("abort", reason=str(e), step=e.step)
    dumped = flight.dump(flight_path)
    if ckpt is not None and ckpt.latest_step is not None:
        # Honesty about the detection window: the watchdog sees learner
        # outputs once per log cadence, so a checkpoint cadence FINER than
        # the log cadence can have saved an already-poisoned state before
        # the trip.  The abort never overwrites anything (final save is
        # skipped); the operator verifies before resuming.
        pointer = (
            f"{ckpt.directory} step {ckpt.latest_step} (verify before "
            f"resuming: a save inside the last log cadence may already "
            f"carry the divergence)"
        )
    else:
        pointer = "none on disk"
    print(
        f"watchdog: DIVERGENCE at step {e.step}: {e.reason}\n"
        f"watchdog: flight recorder dumped to {dumped}\n"
        f"watchdog: last-good checkpoint: {pointer}",
        file=sys.stderr,
        flush=True,
    )
    raise SystemExit(2)


def _make_executor_metrics_fn(logger, watchdog, final):
    """The log-cadence hook shared by the executors that own their phase
    loop (--pipeline 1, --actors N): fold rates in, log, keep the final
    row, and give the watchdog the raw (pre-rates) scalars."""

    def metrics_fn(phase: int, scalars) -> None:
        scalars = dict(scalars)
        watch_scalars = dict(scalars)
        scalars.update(
            logger.rates(
                env_steps=scalars.get("env_steps", 0.0),
                learner_steps=scalars.get("learner_steps", 0.0),
            )
        )
        logger.log(phase, scalars)
        final.clear()
        final.update(scalars)
        if watchdog is not None:
            watchdog.check(phase, watch_scalars)

    return metrics_fn


def _fold_executor_stats(prefix: str, stats: dict, final: dict) -> None:
    """Print an executor's end-of-run stats line and fold the values into
    the final metrics dict under ``<prefix>_`` keys."""
    if stats:
        print(
            f"{prefix}: "
            + " ".join(f"{k} {v:.4g}" for k, v in sorted(stats.items())),
            flush=True,
        )
        final.update({f"{prefix}_{k}": v for k, v in stats.items()})


def _run_pipelined(
    trainer, state, logger, ckpt, args, watchdog, flight, flight_path
) -> dict:
    """Drive the run through the pipelined executor (--pipeline 1).

    The executor owns the warm-up -> fill -> train schedule and the log
    cadence; metrics land in the same MetricLogger (CSV/TB) rows as the
    phase-locked loop, and a final checkpoint is saved when a checkpoint
    dir is configured."""
    from r2d2dpg_tpu.obs import DivergenceError
    from r2d2dpg_tpu.training.pipeline import PipelineConfig, PipelineExecutor

    executor = PipelineExecutor(
        trainer,
        PipelineConfig(
            enabled=True,
            queue_depth=args.pipeline_depth,
            trace_sample=args.trace_sample,
        ),
    )
    if ckpt is not None and ckpt.save_every and ckpt.save_every > 0:
        # The state is split across two threads mid-run, so periodic saves
        # aren't composed with the executor yet — degrade LOUDLY to the
        # --checkpoint-every -1 (final-save-only) semantics.
        print(
            "pipeline: periodic checkpoints not supported with --pipeline 1; "
            "saving the final checkpoint only (--checkpoint-every -1 "
            "semantics)",
            flush=True,
        )
    fill = trainer.window_fill_phases + trainer.replay_fill_phases
    if args.phases is not None:
        num_phases = fill + args.phases
    elif args.minutes is not None:
        num_phases = 10**9  # the wall-clock budget is the stop condition
    else:
        num_phases = fill + 1  # nothing requested: single-train-phase smoke

    final: dict = {}
    # On a watchdog trip metrics_fn raises DivergenceError through the
    # executor's learner loop, whose finally-block stops and joins the
    # collector thread.
    metrics_fn = _make_executor_metrics_fn(logger, watchdog, final)

    try:
        state = executor.run(
            num_phases,
            state=state,
            log_every=args.log_every,
            metrics_fn=metrics_fn,
            minutes=args.minutes,
        )
        _fold_executor_stats("pipeline", executor.stats(), final)
        if ckpt is not None and ckpt.save_every:
            ckpt.save_final(int(state.phase_idx), state)
    except DivergenceError as e:
        _abort_on_divergence(e, flight, flight_path, ckpt)
    finally:
        # Sampled spans -> trace.json next to flight.jsonl (no-op when
        # tracing is off or no dump path is armed).
        flight.dump_trace()
        if ckpt is not None:
            ckpt.wait()
            ckpt.close()
        logger.close()
    return final


def _run_fleet(
    trainer, cfg, state, logger, ckpt, args, watchdog, flight, flight_path,
    replay_capacity=None, topo=None,
) -> dict:
    """Drive the run through the actor fleet (--actors N, docs/FLEET.md).

    This process becomes the learner: an ingest server feeds the staging
    queue, a supervisor owns N actor subprocesses (spawn/monitor/restart
    with backoff), and the drain loop runs here.  ``--phases`` counts
    drain-learn phases; metrics land in the same MetricLogger rows as the
    other schedules."""
    from r2d2dpg_tpu.fleet import (
        ActorSupervisor,
        FleetConfig,
        SupervisorConfig,
        WireConfig,
        default_actor_argv,
    )
    from r2d2dpg_tpu import obs
    from r2d2dpg_tpu.fleet import chaos as fleet_chaos
    from r2d2dpg_tpu.fleet import transport as fleet_transport
    from r2d2dpg_tpu.fleet.ingest import load_fleet_counters
    from r2d2dpg_tpu.obs import DivergenceError, flight_event

    try:
        wire_config = WireConfig(
            encoding=args.fleet_wire, compress=args.fleet_compress
        ).validate()
    except ValueError as e:
        # e.g. zstd on a box without the zstandard module: refuse loudly
        # at startup, not with a crash-looping actor fleet.
        raise SystemExit(f"--fleet-compress: {e}")
    # run() already validated the grammar (fail before the trainer build);
    # this parse only materializes the Fault tuple.
    chaos_faults = (
        fleet_chaos.parse_chaos_spec(args.chaos_spec)
        if args.chaos_spec
        else ()
    )
    # $R2D2DPG_FLEET_TOKEN fallback, same as fleet/actor.py: a secret on
    # the learner's own command line would sit in /proc/<pid>/cmdline for
    # the run's whole lifetime — the exact exposure the env-var hand-off
    # to actors avoids.  Resolved here (fleet-only path), so an exported
    # token never trips the fleet-knobs-without---actors refusal.
    fleet_token = (
        args.fleet_token or os.environ.get("R2D2DPG_FLEET_TOKEN") or None
    )
    if not fleet_transport.is_loopback_address(
        args.fleet_address
    ) and not fleet_token:
        # Routable bind without authentication: anyone who can reach the
        # port can feed the learner experience (the frame parser is safe
        # on untrusted bytes, but the TRAINING DATA would be attacker-
        # chosen).  Allowed — trusted private networks exist — but never
        # silently.
        print(
            f"fleet: WARNING — binding routable address "
            f"{args.fleet_address!r} WITHOUT --fleet-token: any host that "
            f"can reach this port can stream experience into training. "
            f"Set --fleet-token (docs/FLEET.md 'Authentication').",
            flush=True,
        )
        flight_event("fleet_unauthenticated_bind", address=args.fleet_address)
    heartbeat_s = (
        args.fleet_heartbeat
        if args.fleet_heartbeat is not None
        else fleet_transport.READ_DEADLINE_S
    )
    fleet_config = FleetConfig(
        num_actors=args.actors,
        address=args.fleet_address,
        queue_depth=args.fleet_queue_depth,
        publish_every=args.fleet_publish_every,
        idle_timeout_s=args.fleet_idle_timeout,
        shed_after_s=(
            args.fleet_shed_after
            if args.fleet_shed_after is not None
            else 1.0
        ),
        wire=wire_config,
        drain_coalesce=args.drain_coalesce,
        heartbeat_s=heartbeat_s,
        auth_token=fleet_token,
        shard_direct=bool(args.shard_direct),
        shard_pullers=args.shard_pullers,
        shard_prefetch=args.shard_prefetch,
    )
    # The ingest+sample+learn assembly comes from the validated Topology
    # (docs/TOPOLOGY.md): sharded rings + two-level sampling ->
    # SamplerLearner (composes with a dp-mesh trainer since ISSUE 11 —
    # the pulled [K, B] batch lands mesh-sharded via _put_staged);
    # central drain -> FleetLearner.  In sampler mode the shards own the
    # experiment's REAL replay capacity — captured by run() BEFORE it
    # shrank the trainer's unused device arena (one config resolution,
    # no chance to desynchronize).
    if topo is None:
        topo = topology.resolve(args)
    # Standalone shard tier (ISSUE 12, --shard-procs N): spawn the shard
    # processes FIRST (their address files appear asynchronously; every
    # learner-side dial waits them out), hand the RemoteShardSet to the
    # sampler learner in place of the in-learner loopback.
    shard_tier = None
    if args.shard_procs:
        from r2d2dpg_tpu.fleet.shard import ShardProcTier

        if args.logdir:
            shard_dir = os.path.join(args.logdir, "shards")
        else:
            import tempfile

            shard_dir = tempfile.mkdtemp(prefix="r2d2dpg_shards_")
        shard_tier = ShardProcTier(
            num_shards=args.replay_shards,
            num_procs=args.shard_procs,
            capacity_per_shard=replay_capacity // args.replay_shards,
            alpha=cfg.trainer.priority_alpha,
            prioritized=cfg.trainer.prioritized,
            dirpath=shard_dir,
            seed=cfg.trainer.seed,
            wire_config=wire_config,
            auth_token=fleet_token,
            max_frame_bytes=fleet_config.max_frame_bytes,
            heartbeat_s=heartbeat_s,
            chaos_spec=args.chaos_spec,
            flight_dir=args.logdir,
            # The shard tier joins the --obs-fleet plane at the actors'
            # cadence: every shard proc's registry lands in THIS
            # process's /metrics under shard=/host= labels (ISSUE 13).
            telem_every=1.0 if args.obs_fleet else 0.0,
        )
    learner = topology.build_fleet_learner(
        topo, trainer, fleet_config, replay_capacity=replay_capacity,
        shard_set=shard_tier.shard_set if shard_tier is not None else None,
    )
    # NB the tier's processes are SPAWNED inside the try below (beside the
    # actor supervisor): anything that can SystemExit before then — a
    # --resume with no checkpoint, a bind failure — must not orphan
    # shard processes whose only exit is the supervisor's stop.
    address = learner.start()
    print(
        f"fleet: ingest on {address}; spawning {args.actors} actors"
        + (
            f"; {args.replay_shards} replay shards (learner-pulled "
            f"sampling"
            + (
                f", {args.shard_procs} standalone shard procs"
                if args.shard_procs
                else ""
            )
            + ")"
            if args.replay_shards
            else ""
        ),
        flush=True,
    )
    # Learner recovery (docs/FLEET.md "Failure modes"): resume restores
    # the learner subtree into a fresh state and continues the monotone
    # counters from the checkpoint's sidecar; the arena is re-absorbed.
    resume_from = None
    if args.resume:
        step = ckpt.latest_step
        if step is None:
            raise SystemExit(
                f"--resume: no checkpoint found under {args.checkpoint_dir}"
            )
        state = dataclasses.replace(state, train=ckpt.restore(state))
        if hasattr(trainer, "_shardings"):
            # dp-mesh learner: the restored train subtree lands
            # single-device; re-place the state on the mesh layout so the
            # drain programs' inputs keep one device set (--learner-dp).
            import jax

            state = jax.device_put(state, trainer._shardings)
        resume_from = load_fleet_counters(args.checkpoint_dir, step)
        if not resume_from:
            print(
                f"fleet: WARNING — checkpoint step {step} has no counter "
                f"sidecar (pre-ISSUE-7 layout?); counters restart at 0",
                flush=True,
            )
        print(
            f"fleet: resumed learner from step {step} "
            f"(drained {int(resume_from.get('drained', 0))} phases, "
            f"env_steps {resume_from.get('env_steps_total', 0.0):.0f})",
            flush=True,
        )
    # Forward the RESOLVED config values (not the raw flags): the actors'
    # net/param-tree structure and exploration ladder must match the
    # learner's exactly, whichever side of an override they came from.
    # fleet/actor.py owns the flag list (one source, not hand-synced).
    from r2d2dpg_tpu.fleet.actor import structural_argv

    extra = structural_argv(cfg)
    # The wire lane mirrors --fleet-wire/--fleet-compress exactly: the
    # ingest server refuses a mismatched HELLO, so the spawner forwards
    # the negotiated values rather than trusting actor defaults.
    extra += [
        "--wire", args.fleet_wire,
        "--compress", args.fleet_compress,
        # Both ends of the lane enforce ONE frame ceiling: an actor packer
        # pinned to a different default would either FrameTooLarge-crash
        # on frames the server accepts or emit frames the server refuses.
        "--max-frame-bytes", str(learner.config.max_frame_bytes),
    ]
    if args.obs_fleet:
        # The ~1 Hz TELEM cadence: every actor's registry lands in THIS
        # process's /metrics under actor=/host= labels (ISSUE 6).
        extra += ["--telem-every", "1.0"]
    if args.trace_sample and not args.replay_shards:
        # Sharded ingest drops every SEQS trace sidecar (the sampler
        # records its own sample_req -> batch_return -> learn chain via
        # run_kwargs below), so forwarding the rate to actors there
        # would buy 32 wasted wire bytes per sampled frame and nothing.
        extra += ["--trace-sample", str(args.trace_sample)]
    # Liveness: one deadline per fleet, both wire ends (docs/FLEET.md).
    extra += ["--read-deadline", str(heartbeat_s)]
    if args.shard_direct:
        # The direct data plane (ISSUE 17): actors dial the shard the
        # ingest ack advertises and ship SEQS to it directly.
        extra += ["--shard-direct", "1"]
    if args.chaos_spec:
        # Actors fire the stall/corrupt faults that target their id; the
        # learner's engine fires the rest — same seeded schedule.
        extra += ["--chaos-spec", args.chaos_spec]
    spawn_env = None
    if fleet_token:
        # Via the environment, NOT argv: a command-line token would be
        # visible to every user on the host in ps/procfs.
        spawn_env = dict(os.environ)
        spawn_env["R2D2DPG_FLEET_TOKEN"] = fleet_token

    # The GLOBAL sigma-ladder width (ISSUE 16): every lane the autoscaler
    # may ever mint needs its own exploration sigma, so actors spawn with
    # --num-actors max(--actors, --autoscale-max) and slice that ladder.
    # Chaos fault hashing rides the same value on BOTH wire ends (the
    # learner's engine and each actor's ActorChaos must agree on every
    # fault's target).  With --autoscale 0 this is exactly --actors — the
    # structural-inertness anchor.
    ladder_n = max(args.actors, args.autoscale_max if args.autoscale else 0)

    def argv_fn(i: int):
        argv = default_actor_argv(
            i,
            config_name=args.config,
            address=address,
            num_actors=ladder_n,
            seed=cfg.trainer.seed,
            extra=extra,
        )
        if args.logdir:
            argv += [
                "--flight-path",
                os.path.join(args.logdir, f"flight_actor{i}.jsonl"),
            ]
        return argv

    sup_config = SupervisorConfig()
    if args.autoscale and not args.autoscale_dry_run:
        # Crash recovery becomes a DECISION: the ladder records the crash
        # and leaves the slot down for the policy loop's spawn_slot (a
        # dry run keeps the reflexive ladder — observe, don't own).
        sup_config = dataclasses.replace(sup_config, restart="policy")
    supervisor = ActorSupervisor(
        argv_fn,
        args.actors,
        config=sup_config,
        env=spawn_env,
        log_path_fn=(
            (lambda i: os.path.join(args.logdir, f"actor{i}.log"))
            if args.logdir
            else None
        ),
    )
    engine = None
    if chaos_faults:
        engine = fleet_chaos.ChaosEngine(
            chaos_faults,
            seed=cfg.trainer.seed,
            num_actors=ladder_n,
            supervisor=supervisor,
            server=learner.server,
            shard_tier=shard_tier,
        )
    autoscaler = None
    if args.autoscale:
        from r2d2dpg_tpu.fleet.autoscaler import AutoscaleConfig, Autoscaler

        # Reuse the exporter's armed engine when --obs-port is up (the
        # health plane was built re-entrant for exactly this: the policy
        # loop racing an operator's curl); arm a private one otherwise.
        health = getattr(obs.current_exporter(), "health", None)
        if health is None:
            health = obs.HealthEngine(
                _health_config(args),
                registry=obs.get_registry(),
                mirror=obs.get_remote_mirror(),
            )
        autoscaler = Autoscaler(
            health,
            supervisor,
            shard_tier=shard_tier,
            config=AutoscaleConfig(
                min_actors=args.autoscale_min,
                max_actors=args.autoscale_max or args.actors,
                cooldown_s=args.autoscale_cooldown,
                eval_every_s=args.autoscale_every,
                fire_threshold=args.autoscale_fire,
                dry_run=bool(args.autoscale_dry_run),
            ),
            ready_fn=lambda: learner.server.is_steady,
            expected_fn=learner.server.set_expected_actors,
        )

    if args.phases is not None:
        num_phases = args.phases
    elif args.minutes is not None:
        num_phases = 10**9  # the wall-clock budget is the stop condition
    else:
        num_phases = 1  # nothing requested: single-train-phase smoke

    final: dict = {}
    metrics_fn = _make_executor_metrics_fn(logger, watchdog, final)

    run_kwargs = {}
    if args.replay_shards:
        # The sampler learner records its own trace hops (sample_req ->
        # batch_return -> learn); the central drain's hops ride the SEQS
        # sidecar instead, so only the sampler takes the rate directly.
        run_kwargs["trace_sample"] = args.trace_sample
    try:
        if shard_tier is not None:
            shard_tier.start()
        supervisor.start()
        if autoscaler is not None:
            autoscaler.start()
        state = learner.run(
            num_phases,
            state=state,
            log_every=args.log_every,
            metrics_fn=metrics_fn,
            minutes=args.minutes,
            ckpt=ckpt,
            checkpoint_every=args.checkpoint_every,
            resume_from=resume_from,
            phase_fn=engine.on_phase if engine is not None else None,
            **run_kwargs,
        )
        # Supervisor/policy/tier counters join the learner's stats BEFORE
        # the fold so they ride the printed ``fleet:`` line too — the
        # subprocess bench legs parse that line, not the metrics dict.
        fstats = dict(learner.stats())
        fstats["actor_restarts"] = float(supervisor.restarts_total)
        if autoscaler is not None:
            a_stats = autoscaler.stats()
            fstats["autoscale_actions"] = float(
                sum(a_stats["autoscale_actions"].values())
            )
            fstats["autoscale_decisions"] = float(
                a_stats["autoscale_decisions"]
            )
            fstats["autoscale_target"] = float(a_stats["autoscale_target"])
        if shard_tier is not None:
            fstats["shard_restarts"] = float(shard_tier.restarts_total)
        _fold_executor_stats("fleet", fstats, final)
        if engine is not None and engine.unfired():
            # A drill that never got its phase must not read as one that
            # passed: name it loudly in the log and the flight ring.
            names = [f"{f.kind}@p{f.phase}" for f in engine.unfired()]
            print(
                f"fleet: WARNING — chaos faults never fired (run too "
                f"short?): {', '.join(names)}",
                flush=True,
            )
            flight_event("chaos_unfired", faults=names)
        if ckpt is not None and ckpt.save_every:
            from r2d2dpg_tpu.fleet.ingest import (
                prune_fleet_counters,
                save_fleet_counters,
            )

            step = int(state.phase_idx)
            ckpt.save_final(step, state)
            # The final counters sidecar: what a later --resume continues.
            save_fleet_counters(ckpt.directory, step, learner.counters())
            # The final save may have pushed an old orbax step past
            # max_to_keep: prune its sidecar too, or the two drift on disk.
            ckpt.wait()
            prune_fleet_counters(ckpt.directory, ckpt.all_steps())
    except DivergenceError as e:
        _abort_on_divergence(e, flight, flight_path, ckpt)
    finally:
        if args.logdir:
            # The run's FINAL merged scrape + /health verdict as durable
            # evidence (ISSUE 13): lib_gate.sh shard_gate refuses
            # --shard-procs evidence whose scrape lacks a live shard's
            # labelled series, and bench stamps the end-of-run verdict —
            # both read these files, no live exporter needed post-run.
            # Written BEFORE the supervisors stop: the verdict must
            # describe the RUN's end state, not the teardown's (stopped
            # supervisors read alive=0, which would stamp every clean
            # exit as critical/shards_down).
            try:
                snap = obs.get_registry().snapshot()
                sources = obs.get_remote_mirror().sources()
                if sources:
                    snap = obs.merge_remote(snap, sources)
                with open(
                    os.path.join(args.logdir, "metrics_final.prom"), "w"
                ) as f:
                    f.write(obs.render_prometheus(snap))
                engine = getattr(obs.current_exporter(), "health", None)
                if engine is None:
                    # No armed exporter engine (e.g. no --obs-port):
                    # judge with the run's resolved config anyway —
                    # defaults would disarm actors_down/shards_down.
                    engine = obs.HealthEngine(
                        _health_config(args),
                        registry=obs.get_registry(),
                        mirror=obs.get_remote_mirror(),
                    )
                with open(
                    os.path.join(args.logdir, "health_final.json"), "w"
                ) as f:
                    json.dump(engine.evaluate(), f, default=str)
                # The experience-quality plane's end-of-run state (ISSUE
                # 18): lag/age distributions, ESS/saturation, per-actor
                # trained counts, per-shard untrained-eviction fractions.
                # lib_gate.sh quality_gate reads this beside
                # health_final.json.
                with open(
                    os.path.join(args.logdir, "quality_final.json"), "w"
                ) as f:
                    json.dump(
                        obs.get_quality_plane().snapshot_final(),
                        f,
                        default=str,
                    )
            except Exception as e:  # noqa: BLE001 — evidence is optional,
                # the teardown below it is NOT: an exception escaping this
                # finally block would skip supervisor/shard-tier/learner
                # teardown (orphaning their process groups) and mask the
                # run's own error.  Loud note, never a raise.
                print(f"obs: final evidence stamp failed: {e!r}", flush=True)
        # Autoscaler FIRST of all: a policy tick racing the teardown
        # would read stopped supervisors as a fleet to repopulate.
        if autoscaler is not None:
            autoscaler.stop()
        # Supervisor FIRST (its stopping flag makes the actors' connection
        # loss an orderly exit, not a crash to restart), then the SHARD
        # TIER (its stop flag releases any ingest handler parked in the
        # tier-down wait inside RemoteShardSet.add — closing the ingest
        # server first would eat a join timeout per wedged handler and
        # log false handler leaks), then the ingest server.
        supervisor.stop()
        if shard_tier is not None:
            shard_tier.stop()
        learner.close()
        # Sampled spans -> trace.json next to flight.jsonl (no-op when
        # tracing is off or no dump path is armed).
        flight.dump_trace()
        if ckpt is not None:
            ckpt.wait()
            ckpt.close()
        logger.close()
    if chaos_faults and args.logdir:
        # Actor-boundary drills fire in the ACTOR processes; their
        # evidence is the chaos_inject lines in the flight_actor*.jsonl
        # dumps the teardown above just flushed.  A fault with no such
        # line never fired (run too short, target crashed first) and must
        # not read as a drill that passed — same contract as
        # ChaosEngine.unfired() for the learner-side faults.
        missing = fleet_chaos.actor_faults_unfired(
            chaos_faults,
            args.logdir,
            seed=cfg.trainer.seed,
            num_actors=ladder_n,
        )
        if args.shard_procs:
            # Shard-process-boundary drills (stall_shard) fire in the
            # SHARD processes; the same no-evidence-means-unfired
            # contract applies to their flight_shard*.jsonl dumps.
            missing += fleet_chaos.shard_faults_unfired(
                chaos_faults,
                args.logdir,
                seed=cfg.trainer.seed,
                num_shard_procs=args.shard_procs,
            )
        if missing:
            names = [f"{f.kind}@p{f.phase}" for f in missing]
            print(
                f"fleet: WARNING — actor/shard-side chaos faults left no "
                f"injection evidence in {args.logdir!r} (run too short? "
                f"target kept crashing?): {', '.join(names)}",
                flush=True,
            )
            flight_event("chaos_unfired", faults=names)
    return final


def main(argv=None):
    run(parse_args(argv))


if __name__ == "__main__":
    main()
