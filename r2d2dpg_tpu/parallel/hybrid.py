"""Multi-chip training for host-backed (dm_control) env pools.

Reference parity: SURVEY.md §2.8 / §5.8.  The pure-JAX ``SPMDTrainer`` runs
whole phases under ``shard_map``, which cannot contain the ordered
``io_callback`` a host env pool needs.  This trainer closes that gap (the
"known delta #3" of docs/PARITY.md) with the pjit layout style instead:

- every device-resident piece — policy forward, exploration noise, window
  assembler, HBM replay arena, the full learner step — runs under ``jit``
  on arrays laid out over the ``dp`` mesh axis via ``NamedSharding``
  (envs, window, arena, and batch sharded; params replicated);
- gradient synchronization needs no explicit collective: with replicated
  params and a dp-sharded batch, XLA inserts the ``psum`` over ICI on its
  own (the pjit/GSPMD recipe — pick a mesh, annotate shardings, let XLA
  place collectives);
- only the MuJoCo physics step leaves the device: once per collected agent
  step the [E, act] actions cross to host, the C++/Python pool steps all E
  envs, and the [E, obs] batch crosses back, sharded straight onto the mesh.

On one host this trains the DM-Control configs across all local chips.
Multi-host (DCN): each process owns a pool of ``num_envs/process_count``
envs; actions are read from this process's addressable shards, fresh obs
re-enter the mesh via ``jax.make_array_from_process_local_data``, and the
jitted phases run as ordinary multi-process SPMD (every host dispatches the
same computation; XLA routes the gradient/arena collectives over ICI within
a host and DCN across).  Bring-up is ``parallel.distributed.initialize()``;
``tests/test_multihost.py`` validates the full path with two real processes
on a CPU mesh.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6; on older jax device_put performs the same layout move
    _reshard = jax.sharding.reshard
except AttributeError:
    _reshard = jax.device_put

from r2d2dpg_tpu.agents.ddpg import R2D2DPG
from r2d2dpg_tpu.envs.dmc_host import DMCHostEnv
from r2d2dpg_tpu.parallel.mesh import DP_AXIS
from r2d2dpg_tpu.parallel.spmd import _state_spec
from r2d2dpg_tpu.training.assembler import StepRecord, shift_in
from r2d2dpg_tpu.training.trainer import Trainer, TrainerConfig, TrainerState
from r2d2dpg_tpu.utils.profiling import annotate, timed


class HostSPMDTrainer(Trainer):
    """dp-sharded training with the env fleet stepped from the host.

    ``config`` is global (fleet-wide env count, global batch size, total
    capacity); jitted functions see global shapes and XLA splits the work
    across the mesh from the array shardings.
    """

    axis = None  # pjit style: no named axis, XLA inserts the collectives

    def __init__(
        self,
        env: DMCHostEnv,
        agent: R2D2DPG,
        config: TrainerConfig,
        mesh: Mesh,
    ):
        if not getattr(env, "batched", False) or not hasattr(env, "host_step"):
            raise ValueError(
                "HostSPMDTrainer is for host-pool envs (DMCHostEnv); pure-JAX "
                "envs scale with parallel.SPMDTrainer instead"
            )
        if agent.config.axis_name is not None:
            raise ValueError(
                "HostSPMDTrainer uses pjit-style gradient sync; build the "
                "agent with axis_name=None (got "
                f"{agent.config.axis_name!r})"
            )
        self._nproc = jax.process_count()
        if config.num_envs % max(self._nproc, 1):
            raise ValueError(
                f"TrainerConfig.num_envs={config.num_envs} must be divisible "
                f"by the process count {self._nproc} (one env pool per host, "
                f"each owning num_envs/process_count envs)"
            )
        d = mesh.shape[DP_AXIS]
        # The arena is replicated (see layout note in _build_phases), so only
        # the genuinely dp-sharded axes need to divide the mesh.
        for field in ("num_envs", "batch_size"):
            if getattr(config, field) % d:
                raise ValueError(
                    f"TrainerConfig.{field}={getattr(config, field)} must "
                    f"be divisible by the mesh size {d}"
                )
        self.mesh = mesh
        self.num_devices = d
        super().__init__(env, agent, config)
        # Arena buffers carry explicit mesh shardings -> XLA scatter path.
        self.arena.use_pallas = False
        # The one host<->device boundary per collected step, as seen from
        # the stride loop (pool physics + numpy marshalling); the pool's
        # own r2d2dpg_envpool_step_seconds isolates the physics share.
        from r2d2dpg_tpu.obs import get_registry

        self._obs_host_step = get_registry().histogram(
            "r2d2dpg_hybrid_host_env_step_seconds",
            "host env-step boundary latency in the hybrid stride loop",
        )

    # --------------------------------------------------------------- builds
    def _build_phases(self):
        mesh = self.mesh
        # Layout deltas vs the shard_map spec: the host pool owns the real
        # env state (the device token is a scalar -> replicated), and the
        # replay arena is REPLICATED rather than capacity-sharded — per-chip
        # memory equals the single-chip arena, global adds cost one small
        # all-gather of E fresh sequences per phase, and every chip samples
        # the same global batch whose compute is then resharded over dp
        # (``_reshard_batch``).  This keeps the arena's gather/scatter free
        # of cross-shard index collectives.
        from r2d2dpg_tpu.replay.arena import ArenaState

        spec = dataclasses.replace(
            _state_spec(),
            env_state=P(),
            arena=ArenaState(
                data=P(), priority=P(), cursor=P(), total_added=P(), meta=P()
            ),
        )
        self._shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            spec,
            is_leaf=lambda x: isinstance(x, P),
        )
        self._replicated = NamedSharding(mesh, P())
        self._dp1 = NamedSharding(mesh, P(DP_AXIS))  # [E, ...] leading axis
        self._dp2 = NamedSharding(mesh, P(None, DP_AXIS))  # [T, E] stacks
        self._act_step = jax.jit(self._act_step_impl)
        # One dispatch per phase instead of one jnp.where per param leaf
        # (ADVICE r1: _behavior_params evaluated eagerly was pure host-loop
        # overhead on the hot collect path).
        self._collect_setup = jax.jit(self._collect_setup_impl)
        # No donation: the state's obs/reset/carry buffers are also passed
        # as the t=0 entries of the per-step tuples (f(donate(a), a) is
        # rejected by PJRT on real devices).
        self._absorb = jax.jit(self._absorb_impl)
        self._emit_learn = jax.jit(self._emit_learn_impl, donate_argnums=(0,))
        self._emit_only = jax.jit(self._emit_and_add, donate_argnums=(0,))
        # Overlapped-learner substep (one prioritized update).  NO donation:
        # while substeps run, the phase's TrainerState pytree still holds
        # references to the pre-substep train/arena buffers (they ride
        # through _absorb), so donating here would invalidate live inputs.
        # Cost of out-of-place: a fresh [capacity] priority array + param
        # trees per substep — small next to the arena data, which passes
        # through update_priorities untouched (and uncopied).
        self._learn_substep = jax.jit(self._learn_substep_impl)

    # ----------------------------------------------------------------- init
    def _env_reset(self, key: jax.Array):
        """Each process resets only its LOCAL slice of the fleet (its own
        pool), with a process-diversified key so seeds differ across hosts."""
        if self._nproc > 1:
            key = jax.random.fold_in(key, jax.process_index())
        return self.env.reset(key, self.config.num_envs)

    def init(self, key: Optional[jax.Array] = None) -> TrainerState:
        if self._nproc == 1:
            state = super().init(key)  # eager io_callback reset fills the pool
            return jax.device_put(state, self._shardings)
        # Multi-host (SURVEY §5.8 / docs/PARITY.md delta #3): build a state
        # with LOCAL fleet shapes (num_envs/process_count envs in this
        # process's pool; params/arena/counters are process-identical since
        # every host runs the same seed), then assemble the global
        # TrainerState — dp-sharded leaves from each process's local rows,
        # replicated leaves from the (identical) local values.
        saved = self.config
        try:
            # Temporary local view ONLY for the eager init body; the jitted
            # phase functions trace later, against the restored global config.
            self.config = dataclasses.replace(
                saved, num_envs=saved.num_envs // self._nproc
            )
            local = super().init(key)
        finally:
            self.config = saved

        def to_global(leaf, sharding):
            arr = np.asarray(leaf)
            spec = sharding.spec
            if any(ax == DP_AXIS for ax in spec):
                gshape = tuple(
                    dim * self._nproc
                    if i < len(spec) and spec[i] == DP_AXIS
                    else dim
                    for i, dim in enumerate(arr.shape)
                )
                return jax.make_array_from_process_local_data(
                    sharding, arr, gshape
                )
            return jax.make_array_from_callback(
                arr.shape, sharding, lambda idx: arr[idx]
            )

        # ``self._shardings`` is a PREFIX pytree (one sharding can span a
        # whole subtree, as device_put accepts); broadcast it to the full
        # state structure before zipping leaf-wise.
        full_shardings = jax.tree_util.tree_broadcast(
            self._shardings,
            local,
            is_leaf=lambda x: isinstance(x, NamedSharding),
        )
        return jax.tree_util.tree_map(to_global, local, full_shardings)

    # --------------------------------------------------------- device parts
    def _collect_setup_impl(self, state: TrainerState):
        """Per-phase device prep: behavior snapshot + the stride's RNG keys.

        With ``param_sync_every > 0`` the snapshot must also PERSIST (the
        base trainer stores it before collecting so the params acted with
        are exactly the ones carried until the next sync phase); returning
        the updated state from here keeps that store inside this one jitted
        dispatch instead of an eager per-leaf ``jnp.where`` in train_phase.
        """
        rng, sk, sl = jax.random.split(state.rng, 3)
        keys = jax.random.split(sk, self.config.stride)
        lkeys = jax.random.split(sl, max(self.config.learner_steps, 1))
        behavior = self._behavior_params(state)
        if self.config.param_sync_every > 0:
            state = dataclasses.replace(state, behavior_params=behavior)
        return state, behavior, keys, lkeys, rng

    def _learn_substep_impl(self, train, arena, key):
        """One prioritized learner update, dispatchable mid-collect (the
        shared ``Trainer._learn_step`` body, as a standalone jit)."""
        return self._learn_step(train, arena, key)

    def _act_step_impl(
        self, behavior, critic_params, obs, reset, a_carry, c_carry, noise_st,
        keys, t
    ):
        """One policy step for the whole fleet (the device half of hot loop A);
        the semantics live in Trainer._policy_step, shared with the in-graph
        scan collect.  ``keys`` is the phase's [stride, key] stack and ``t``
        a traced scalar so the per-step key gather happens in-graph (no eager
        host indexing per step)."""
        return self._policy_step(
            behavior, critic_params, obs, reset, a_carry, c_carry, noise_st,
            self._local_sigmas(), keys[t],
        )

    def _absorb_impl(
        self,
        state: TrainerState,
        obs_T: Tuple[jnp.ndarray, ...],  # T x [E, obs] — pre-step obs
        reset_T: Tuple[jnp.ndarray, ...],  # T x [E] — pre-step reset flags
        act_T: Tuple[jnp.ndarray, ...],  # T x [E, A]
        a_car_T: Tuple[Any, ...],  # T x carry — pre-step carries
        c_car_T: Tuple[Any, ...],
        rew_T: jnp.ndarray,  # [T, E] from host
        disc_T: jnp.ndarray,  # [T, E]
        done_T: jnp.ndarray,  # [T, E] post-step reset flags
        obs_next: jnp.ndarray,
        reset_next: jnp.ndarray,
        a_carry,
        c_carry,
        noise_st,
        rng,
    ) -> TrainerState:
        """Fold one phase of host-collected steps into the TrainerState."""
        cfg = self.config
        stack = lambda xs: jnp.stack(xs)  # noqa: E731 — time-major [T, E, ...]
        records = StepRecord(
            obs=stack(obs_T),
            action=stack(act_T),
            reward=rew_T,
            discount=disc_T,
            reset=stack(reset_T),
            carries={
                "actor": jax.tree_util.tree_map(lambda *xs: stack(xs), *a_car_T)
                if jax.tree_util.tree_leaves(a_car_T[0])
                else a_car_T[0],
                "critic": jax.tree_util.tree_map(lambda *xs: stack(xs), *c_car_T)
                if jax.tree_util.tree_leaves(c_car_T[0])
                else c_car_T[0],
            },
        )

        def ep_step(ep, inp):
            r, done = inp
            ep = ep + r
            completed = (jnp.where(done > 0, ep, 0.0).sum(), (done > 0).sum())
            return jnp.where(done > 0, 0.0, ep), completed

        ep_ret, (comp_sum, comp_cnt) = jax.lax.scan(
            ep_step, state.episode_return, (rew_T, done_T)
        )

        return dataclasses.replace(
            state,
            obs=obs_next,
            reset=reset_next,
            actor_carry=a_carry,
            critic_carry=c_carry,
            noise_state=noise_st,
            rng=rng,
            env_steps=state.env_steps + cfg.stride * self.global_envs,
            episode_return=ep_ret,
            completed_return_sum=state.completed_return_sum + comp_sum.sum(),
            completed_count=state.completed_count + comp_cnt.sum(),
            window=shift_in(state.window, records),
            phase_idx=state.phase_idx + 1,
        )

    def _emit_learn_impl(
        self, state: TrainerState
    ) -> Tuple[TrainerState, Dict[str, jnp.ndarray]]:
        return self._learn(self._emit_and_add(state))

    # ----------------------------------------------------------- reshards
    def _reshard_add(self, seq, prios):
        """Replicate the E fresh sequences + priorities for the (replicated)
        arena add — after initial_priority ran on the dp-sharded layout."""
        rep = lambda x: _reshard(x, self._replicated)  # noqa: E731
        return jax.tree_util.tree_map(rep, seq), rep(prios)

    def _reshard_batch(self, batch):
        """Shard the sampled batch over dp so learner compute splits and XLA
        psums the gradients (params replicated + batch sharded)."""
        return jax.tree_util.tree_map(
            lambda x: _reshard(
                x, NamedSharding(self.mesh, P(*([DP_AXIS] + [None] * (x.ndim - 1))))
            ),
            batch,
        )

    # ------------------------------------------------------------ host loop
    def _put_fleet(self, x: np.ndarray) -> jnp.ndarray:
        """Lay a host [E_local, ...] batch out over the dp mesh axis (global
        assembly across processes when multi-host)."""
        if self._nproc == 1:
            return jax.device_put(x, self._dp1)
        return jax.make_array_from_process_local_data(
            self._dp1, x, (x.shape[0] * self._nproc,) + x.shape[1:]
        )

    def _put_stack(self, x: np.ndarray) -> jnp.ndarray:
        """[T, E_local] time-major host stack onto the dp mesh axis (axis 1)."""
        if self._nproc == 1:
            return jax.device_put(x, self._dp2)
        return jax.make_array_from_process_local_data(
            self._dp2, x, (x.shape[0], x.shape[1] * self._nproc)
        )

    def _fetch_fleet(self, arr: jnp.ndarray) -> np.ndarray:
        """Device [E, ...] fleet array -> THIS process's rows as numpy."""
        if self._nproc == 1:
            return np.asarray(arr)
        shards = sorted(
            arr.addressable_shards,
            key=lambda s: s.index[0].start if s.index[0].start else 0,
        )
        return np.concatenate([np.asarray(s.data) for s in shards], axis=0)

    def _stride_loop(
        self, state, behavior, critic_params, keys, rng, on_step=None
    ):
        """THE host stride loop: per-step policy dispatch -> action fetch ->
        optional ``on_step(t)`` hook -> batched MuJoCo step -> obs re-entry,
        then one jitted absorb of the whole phase.

        Shared by ``_host_collect`` (hook = the overlap_learner substep
        dispatch) and the pipelined executor's collector thread
        (training/pipeline.py: a ``CollectorState`` and no hook), so the
        fleet stacking / episode bookkeeping cannot drift between the two
        schedules — ``_act_step``/``_absorb`` touch only the env-side
        fields both state pytrees share."""
        obs, reset = state.obs, state.reset
        a_carry, c_carry = state.actor_carry, state.critic_carry
        noise_st = state.noise_state
        obs_T, reset_T, act_T, a_car_T, c_car_T = [], [], [], [], []
        rew_T, disc_T, done_T = [], [], []

        for t in range(self.config.stride):
            obs_T.append(obs)
            reset_T.append(reset)
            a_car_T.append(a_carry)
            c_car_T.append(c_carry)
            action, a_carry, c_carry, noise_st = self._act_step(
                behavior, critic_params, obs, reset, a_carry, c_carry,
                noise_st, keys, np.int32(t),
            )
            act_T.append(action)
            action_np = self._fetch_fleet(action)
            if on_step is not None:
                on_step(t)
            # ═══ the one host<->device boundary per collected step ═══
            with timed(self._obs_host_step), annotate("hybrid/host_env_step"):
                o, r, d, res = self.env.host_step(action_np)
            rew_T.append(r)
            disc_T.append(d)
            done_T.append(res)
            obs = self._put_fleet(o)
            reset = self._put_fleet(res)

        with annotate("hybrid/absorb"):
            return self._absorb(
                state,
                tuple(obs_T),
                tuple(reset_T),
                tuple(act_T),
                tuple(a_car_T),
                tuple(c_car_T),
                self._put_stack(np.stack(rew_T)),
                self._put_stack(np.stack(disc_T)),
                self._put_stack(np.stack(done_T)),
                obs,
                reset,
                a_carry,
                c_carry,
                noise_st,
                rng,
            )

    def _host_collect(
        self, state: TrainerState, learn: bool = False
    ) -> Tuple[TrainerState, Optional[Dict[str, jnp.ndarray]]]:
        """Step the fleet ``stride`` times from the host.

        With ``learn=True`` (the ``overlap_learner`` train path) the phase's
        ``learner_steps`` updates are dispatched one at a time BETWEEN env
        steps, spread evenly over the stride: each update executes on the
        device during the milliseconds the host spends inside the MuJoCo C
        step, so on a real TPU the learner costs ~zero wall-clock.  The
        device queue orders act_step(t+1) after the interleaved update, but
        by the time the host finishes physics for step t the update has
        drained — max(host, device) instead of host + device.

        Semantics delta vs the sequential path (intentional, documented):
        interleaved updates sample the arena as of the PREVIOUS emit — the
        sequence collected this phase enters replay after the phase's
        updates.  That one-phase sampling lag is exactly the reference's
        async actor/learner relationship (its learner never sees in-flight
        actor data either).
        """
        cfg = self.config
        state, behavior, keys, lkeys, rng = self._collect_setup(state)
        critic_params = self.agent.behavior_critic_params(state.train)
        train, arena = state.train, state.arena
        n_sub = cfg.learner_steps if learn else 0
        sub = 0
        metrics_acc = []

        def dispatch_substeps(t: int) -> None:
            # Dispatch this step's share of learner updates AFTER the action
            # crossed to host (so act_step never waits behind an update) and
            # BEFORE the physics step (so the update runs under it).
            nonlocal train, arena, sub
            while sub < n_sub and (sub + 1) * cfg.stride <= (t + 1) * n_sub:
                with annotate("hybrid/learn_substep"):
                    train, arena, m = self._learn_substep(
                        train, arena, lkeys[sub]
                    )
                metrics_acc.append(m)
                sub += 1

        state = self._stride_loop(
            state, behavior, critic_params, keys, rng,
            on_step=dispatch_substeps if n_sub else None,
        )
        if not learn:
            return state, None
        state = dataclasses.replace(state, train=train, arena=arena)
        if not metrics_acc:  # learner_steps=0: a collect-only train phase
            return state, {}
        metrics = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs).mean(), *metrics_acc
        )
        return state, metrics

    # --------------------------------------------------------------- phases
    def collect_phase(self, state: TrainerState) -> TrainerState:
        state, _ = self._host_collect(state)
        return state

    def fill_phase(self, state: TrainerState) -> TrainerState:
        state, _ = self._host_collect(state)
        return self._emit_only(state)

    def train_phase(
        self, state: TrainerState
    ) -> Tuple[TrainerState, Dict[str, jnp.ndarray]]:
        # Behavior-snapshot persistence happens inside _collect_setup (jit).
        if not self.config.overlap_learner:
            state, _ = self._host_collect(state)
            with annotate("hybrid/emit_learn"):
                return self._emit_learn(state)
        state, metrics = self._host_collect(state, learn=True)
        with annotate("hybrid/emit_add"):
            return self._emit_only(state), metrics
