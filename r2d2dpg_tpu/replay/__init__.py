"""Sequence replay (SURVEY.md §2.2): the HBM ring arena with prioritized
sampling, and the host-side ingest-edge shards of in-network sampling
(``replay/sharded.py``, docs/REPLAY.md)."""

from r2d2dpg_tpu.replay.arena import (
    ArenaState,
    ReplayArena,
    SampleResult,
    SequenceBatch,
    StagedSequences,
)
from r2d2dpg_tpu.replay.sharded import (
    ReplayShard,
    ShardSample,
    combine_probs,
    shard_quotas,
)

__all__ = [
    "ArenaState",
    "ReplayArena",
    "ReplayShard",
    "SampleResult",
    "SequenceBatch",
    "ShardSample",
    "StagedSequences",
    "combine_probs",
    "shard_quotas",
]
