"""Autoscaler: the health→actuation loop, closed (ISSUE 16).

PR 13 shipped the machine-readable half — ``obs/health.py`` folds the
whole fleet telemetry plane into ``{verdict, findings[]}`` — and until
now a human read it and edited ``--actors N``.  This module is the other
half: a policy engine that evaluates the in-process ``HealthEngine`` on
its own cadence (no HTTP self-scrape — the engine was built re-entrant
exactly so an autoscaler can race an operator's curl) and maps findings
to typed ``ScaleAction``s actuated through the supervisor's runtime
resize API (``spawn_slot``/``retire_slot``/``set_target``) and the shard
tier's supervisor:

- ``actors_down``      → ``spawn_actor`` on the dead slot (replacement —
                         the planned version of crash-restart; under
                         ``SupervisorConfig(restart="policy")`` the
                         ladder leaves the corpse for THIS decision).
- ``shards_down``      → ``respawn_shard_proc`` (backstop: the shard
                         tier keeps its reflexive ladder, so this stays
                         pending while backoff owns the respawn and only
                         lands on a slot the ladder gave up).
- ``telem_stale``      → ``replace_actor``: kill the wedged peer, then
                         respawn its lane once the corpse is reaped.
- ``learner_starving`` + all-actors-fresh → ``spawn_actor`` scale-up
                         toward ``--autoscale-max`` (Ape-X 1803.00933:
                         add actors until the learner is the bottleneck).
- ``eviction_churn`` with a NOT-starving learner → ``kill_actor``
                         scale-down toward ``--autoscale-min`` (the ring
                         is evicting unseen experience faster than the
                         learner samples it: actors are pure waste).

Every decision passes a hysteresis gate — per-rule consecutive-fire
thresholds, a cooldown between landed actions, a bounded
actions-per-window budget, and a warm-up exemption (load-based rules
wait for the ingest server's ``is_steady``; replacement rules act even
during absorb) — so a single stale sample can never flap the fleet.
Actuation follows the pending-until-landed chaos contract (PR 12): an
action on a slot that is mid-backoff or still draining no-ops and stays
pending for the next tick instead of double-spawning.

Elasticity invariants (why this composes with the data plane):

- New actors slot into the GLOBAL sigma ladder: train.py fixes the
  ladder width at ``max(--actors, --autoscale-max)`` so every mintable
  lane id has a sigma, and ``set_target``'s lane walk never mints past
  it (``lane_limit``).
- Retired slots drain via SIGUSR1 → finish phase → BYE: the final ack
  folds the banked accounting, so scale-down loses zero steps.
- A landed resize moves ``r2d2dpg_fleet_actors_expected``
  (``IngestServer.set_expected_actors``) so the health ``actors_down``
  rule judges against the CURRENT target.

Dry-run (``--autoscale-dry-run``) walks the identical decision path —
streaks, cooldown, window budget — but never actuates and never emits
``autoscale_action``; the decisions log is the evidence.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from r2d2dpg_tpu.obs import flight_event, get_registry

# The rules this policy consumes (a subset of obs/health.py RULES —
# recompile_churn/hbm_pressure/shard_skew are diagnoses, not population
# problems, and engine_error must never drive actuation).
POLICY_RULES = (
    "actors_down",
    "shards_down",
    "telem_stale",
    "learner_starving",
    "eviction_churn",
)

# Which rules are exempt from the warm-up gate: replacing a dead or
# wedged process is safe (and urgent) during absorb; LOAD-based scaling
# must wait until the loop is past its first compiled phase, or the
# warm-up queue-full wait reads as starving/churning and flaps the fleet
# before phase 1.  (The health engine's wait-p99 rules are absorb-split
# too — this is the second, structural layer of the same exemption.)
_LOAD_RULES = frozenset({"learner_starving", "eviction_churn"})

ACTION_KINDS = (
    "spawn_actor",
    "kill_actor",
    "replace_actor",
    "respawn_shard_proc",
)


@dataclasses.dataclass(frozen=True)
class ScaleAction:
    """One typed actuation decision: what to do, to which slot, and the
    health rule + human-readable evidence that drove it.  ``slot`` is
    None for population resizes (``goal`` carries the new target) and a
    concrete lane id for replacements."""

    kind: str  # one of ACTION_KINDS
    slot: Optional[int]
    rule: str
    reason: str
    goal: Optional[int] = None  # population target for resize kinds


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    min_actors: int = 1
    max_actors: int = 0  # 0 = pinned to the startup population
    # Hysteresis: a rule must fire on this many CONSECUTIVE evaluations
    # before it may act (one stale sample can never flap the fleet);
    # ``fire_overrides`` tunes individual rules.
    fire_threshold: int = 3
    fire_overrides: Optional[Dict[str, int]] = None
    # Cooldown between LANDED actions, and a budget of actions per
    # rolling window — the two outer hysteresis rings.
    cooldown_s: float = 30.0
    window_s: float = 300.0
    max_actions_per_window: int = 4
    eval_every_s: float = 2.0
    dry_run: bool = False

    def fire_needed(self, rule: str) -> int:
        if self.fire_overrides and rule in self.fire_overrides:
            return int(self.fire_overrides[rule])
        return self.fire_threshold


class Autoscaler:
    """The decision/actuation loop.

    ``engine`` is an armed ``HealthEngine`` (evaluate() never raises);
    ``supervisor`` the actor fleet's ``ActorSupervisor``; ``shard_tier``
    (optional) anything exposing ``.supervisor`` with the same resize
    API (``ShardProcTier``).  ``ready_fn`` gates load-based rules (wired
    to ``IngestServer.is_steady``); ``expected_fn`` is told the new
    population target after a landed resize (wired to
    ``IngestServer.set_expected_actors``).  The clock is injectable and
    ``tick(now)`` is the whole per-evaluation step — the hysteresis
    tests drive it directly, no sleeps.
    """

    def __init__(
        self,
        engine: Any,
        supervisor: Any,
        *,
        shard_tier: Any = None,
        config: AutoscaleConfig = AutoscaleConfig(),
        clock: Callable[[], float] = time.monotonic,
        ready_fn: Optional[Callable[[], bool]] = None,
        expected_fn: Optional[Callable[[int], None]] = None,
    ):
        if config.min_actors < 0:
            raise ValueError("autoscale: min_actors must be >= 0")
        if config.max_actors and config.max_actors < config.min_actors:
            raise ValueError("autoscale: max bound below min bound")
        self.engine = engine
        self.supervisor = supervisor
        self.shard_tier = shard_tier
        self.config = config
        self._clock = clock
        self._ready_fn = ready_fn
        self._expected_fn = expected_fn
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._streaks: Dict[str, int] = {r: 0 for r in POLICY_RULES}
        self._pending: Optional[Dict[str, Any]] = None
        self._last_action_at: Optional[float] = None
        self._window: List[float] = []  # landed-action times (pruned)
        self._last_eval_at: Optional[float] = None
        self._actions: Dict[str, int] = {k: 0 for k in ACTION_KINDS}
        self._decisions = 0
        self._gated = 0
        self._dry_decisions = 0
        # Flight-ring hygiene: a decision that stays gated re-fires every
        # tick (a dead slot behind a spent window budget is re-decided at
        # eval cadence) — only the FIRST of an identical gated run is
        # flight evidence, the rest would flood the ring.
        self._last_gated_sig: Optional[tuple] = None
        reg = get_registry()
        self._obs_actions = reg.counter(
            "r2d2dpg_autoscale_actions_total",
            "landed autoscale actuations by kind",
            labelnames=("action",),
        )
        self._obs_target = reg.gauge(
            "r2d2dpg_autoscale_target_actors",
            "the autoscaler-managed actor population target",
        )
        self._obs_target.set_fn(lambda: float(self.supervisor.target))
        self._obs_age = reg.gauge(
            "r2d2dpg_autoscale_last_decision_age_seconds",
            "seconds since the policy loop last evaluated the health "
            "engine (a growing value means the loop itself is wedged)",
        )
        self._obs_age.set_fn(self._age)

    def _age(self) -> float:
        with self._lock:
            last = self._last_eval_at
        return 0.0 if last is None else max(self._clock() - last, 0.0)

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "Autoscaler":
        if self._thread is not None:
            raise RuntimeError("autoscaler already started")
        self._thread = threading.Thread(
            target=self._loop, name="fleet-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the policy loop must
                # never die mid-run; a failed tick is flight evidence.
                flight_event(
                    "autoscale_decision",
                    fired=False,
                    error=f"{type(e).__name__}: {e}",
                )
            self._stop.wait(self.config.eval_every_s)

    # ------------------------------------------------------------------ tick
    def tick(self, now: Optional[float] = None) -> Optional[ScaleAction]:
        """One policy evaluation: retry the pending action if one is in
        flight (no new decision while an actuation hasn't landed — the
        no-double-spawn contract), else evaluate the health engine,
        update per-rule streaks, and gate/actuate at most one candidate.
        Returns the action that LANDED this tick (None otherwise)."""
        now = self._clock() if now is None else now
        with self._lock:
            self._last_eval_at = now
            pending = self._pending
        if pending is not None:
            return self._retry_pending(pending, now)
        verdict = self.engine.evaluate()
        findings = verdict.get("findings", [])
        firing: Dict[str, List[Dict]] = {}
        for f in findings:
            firing.setdefault(f.get("rule", "?"), []).append(f)
        with self._lock:
            for rule in POLICY_RULES:
                self._streaks[rule] = (
                    self._streaks[rule] + 1 if rule in firing else 0
                )
            streaks = dict(self._streaks)
        action = self._decide(firing, streaks)
        if action is None:
            return None
        gate = self._gate(action, now)
        sig = (action.kind, action.slot, action.rule, gate)
        with self._lock:
            self._decisions += 1
            if gate is not None:
                self._gated += 1
            repeat = gate is not None and sig == self._last_gated_sig
            self._last_gated_sig = sig if gate is not None else None
        if not repeat:
            flight_event(
                "autoscale_decision",
                action=action.kind,
                slot=action.slot,
                rule=action.rule,
                reason=action.reason,
                fired=gate is None,
                gated_by=gate,
                dry_run=self.config.dry_run,
            )
        if gate is not None:
            return None
        if self.config.dry_run:
            # The identical hysteresis clock ticks — a dry run logs the
            # cadence real actuation would have — but nothing moves and
            # no autoscale_action is emitted (the gate pairing check
            # stays trivially green).
            with self._lock:
                self._dry_decisions += 1
                self._last_action_at = now
                self._window.append(now)
            return None
        return self._actuate(action, now, first=True)

    # -------------------------------------------------------------- decision
    def _decide(
        self, firing: Dict[str, List[Dict]], streaks: Dict[str, int]
    ) -> Optional[ScaleAction]:
        cfg = self.config
        sup = self.supervisor
        hot = lambda rule: streaks.get(rule, 0) >= cfg.fire_needed(rule)  # noqa: E731

        # 1) Dead actor: replace on its own lane (population unchanged).
        if hot("actors_down"):
            states = sup.slot_states()
            down = sorted(i for i, s in states.items() if s == "down")
            if down:
                return ScaleAction(
                    "spawn_actor",
                    down[0],
                    "actors_down",
                    f"slot {down[0]} dead with no respawn owner",
                )
            # All corpses are mid-backoff/gave-up: the ladder (or an
            # operator) owns them — nothing for policy to do yet.

        # 2) Dead shard proc: backstop respawn through the tier's ladder.
        if hot("shards_down") and self.shard_tier is not None:
            states = self.shard_tier.supervisor.slot_states()
            dead = sorted(
                i for i, s in states.items() if s in ("down", "gave_up")
            )
            if dead:
                return ScaleAction(
                    "respawn_shard_proc",
                    dead[0],
                    "shards_down",
                    f"shard proc {dead[0]} {states[dead[0]]}",
                )

        # 3) Wedged actor (alive but silent): kill + respawn its lane.
        if hot("telem_stale"):
            slot = self._stale_actor(firing.get("telem_stale", ()))
            if slot is not None and sup.slot_states().get(slot) == "live":
                return ScaleAction(
                    "replace_actor",
                    slot,
                    "telem_stale",
                    f"actor {slot} TELEM stale but process alive",
                )

        ready = self._ready_fn is None or bool(self._ready_fn())
        target = sup.target
        fresh = "telem_stale" not in firing and "actors_down" not in firing

        # 4) Starving learner + every actor fresh: add an actor.
        if (
            hot("learner_starving")
            and ready
            and fresh
            and cfg.max_actors
            and target < cfg.max_actors
        ):
            return ScaleAction(
                "spawn_actor",
                None,
                "learner_starving",
                f"learner starving with {target} fresh actors",
                goal=target + 1,
            )

        # 5) Eviction churn with a satiated learner: drop an actor.
        if (
            hot("eviction_churn")
            and ready
            and "learner_starving" not in firing
            and target > cfg.min_actors
        ):
            return ScaleAction(
                "kill_actor",
                None,
                "eviction_churn",
                f"ring churning with a satiated learner at {target} actors",
                goal=target - 1,
            )
        return None

    @staticmethod
    def _stale_actor(findings) -> Optional[int]:
        # The finding's detail is "actor {who} TELEM stale — ..."; shard
        # staleness shares the rule but names unit "shard" and is the
        # shard ladder's problem, not this policy's.
        for f in findings:
            parts = str(f.get("detail", "")).split()
            if len(parts) >= 2 and parts[0] == "actor" and parts[1].isdigit():
                return int(parts[1])
        return None

    # ------------------------------------------------------------ hysteresis
    def _gate(self, action: ScaleAction, now: float) -> Optional[str]:
        """None = fire; otherwise the name of the ring that held it."""
        cfg = self.config
        with self._lock:
            if (
                self._last_action_at is not None
                and now - self._last_action_at < cfg.cooldown_s
            ):
                return "cooldown"
            self._window = [
                t for t in self._window if now - t < cfg.window_s
            ]
            if len(self._window) >= cfg.max_actions_per_window:
                return "window_budget"
        if action.rule in _LOAD_RULES:
            if self._ready_fn is not None and not self._ready_fn():
                return "warmup"
        return None

    # ------------------------------------------------------------- actuation
    def _retry_pending(self, pending: Dict[str, Any], now: float):
        action: ScaleAction = pending["action"]
        # A pending replacement/respawn whose slot came back on its own
        # (the ladder respawned it, or the wedge cleared) is superseded:
        # drop it without an autoscale_action — nothing was actuated.
        if action.slot is not None and action.kind != "kill_actor":
            sup = (
                self.shard_tier.supervisor
                if action.kind == "respawn_shard_proc"
                else self.supervisor
            )
            live = sup.slot_states().get(action.slot) == "live"
            if live and (action.kind != "replace_actor" or not pending.get("killed")):
                with self._lock:
                    self._pending = None
                flight_event(
                    "autoscale_decision",
                    action=action.kind,
                    slot=action.slot,
                    rule=action.rule,
                    fired=False,
                    gated_by="superseded",
                )
                return None
        return self._actuate(action, now, first=False, pending=pending)

    def _actuate(
        self,
        action: ScaleAction,
        now: float,
        *,
        first: bool,
        pending: Optional[Dict[str, Any]] = None,
    ) -> Optional[ScaleAction]:
        state = pending if pending is not None else {"action": action}
        landed = self._try_land(action, state)
        if not landed:
            with self._lock:
                self._pending = state
            if first:
                flight_event(
                    "autoscale_pending",
                    action=action.kind,
                    slot=action.slot,
                    rule=action.rule,
                )
            return None
        with self._lock:
            self._pending = None
            self._last_action_at = now
            self._window.append(now)
            self._actions[action.kind] += 1
        self._obs_actions.labels(action=action.kind).inc()
        flight_event(
            "autoscale_action",
            action=action.kind,
            slot=action.slot,
            rule=action.rule,
            goal=action.goal,
            target=self.supervisor.target,
        )
        if action.goal is not None and self._expected_fn is not None:
            self._expected_fn(action.goal)
        return action

    def _try_land(self, action: ScaleAction, state: Dict[str, Any]) -> bool:
        sup = self.supervisor
        if action.kind == "spawn_actor":
            if action.slot is not None:
                return sup.spawn_slot(action.slot, origin="autoscale")
            lim = self.config.max_actors or None
            res = sup.set_target(action.goal, lane_limit=lim)
            return bool(res["spawned"])
        if action.kind == "kill_actor":
            res = sup.set_target(action.goal)
            return bool(res["retiring"])
        if action.kind == "replace_actor":
            st = sup.slot_states().get(action.slot)
            if st == "live" and not state.get("killed"):
                # Stage 1: kill the wedged peer.  The monitor reaps the
                # corpse on its next poll; the spawn stage lands on a
                # later tick (never two processes in one lane).
                state["killed"] = bool(sup.kill_actor(action.slot))
                return False
            if st == "live":
                # Killed and already back: under a reflexive ladder the
                # restart WAS the replacement — count it landed.
                return True
            return sup.spawn_slot(action.slot, origin="autoscale")
        if action.kind == "respawn_shard_proc":
            return self.shard_tier.supervisor.spawn_slot(
                action.slot, origin="autoscale"
            )
        raise ValueError(f"unknown ScaleAction kind: {action.kind}")

    # ------------------------------------------------------------------ info
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "autoscale_decisions": self._decisions,
                "autoscale_gated": self._gated,
                "autoscale_actions": dict(self._actions),
                "autoscale_dry_run_decisions": self._dry_decisions,
                "autoscale_pending": (
                    self._pending["action"].kind
                    if self._pending is not None
                    else None
                ),
                "autoscale_target": self.supervisor.target,
            }
