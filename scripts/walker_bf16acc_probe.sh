#!/bin/bash
# Third walker dtype A/B arm: MixedPrecisionLSTMCell with fp32 matmul
# ACCUMULATORS (preferred_element_type=float32 on the two gate dots —
# models/actor_critic.py round-5 edit).
#
# Chain of evidence: the round-3 A/B (old truncated-carry cell) lost 3x
# to fp32 (145.5 vs 351.7); the round-5 A/B on the fp32-carry cell
# landed within noise of the old cell (146.6) — so the carry was NOT the
# binding path, implicating the bf16-truncated matmul accumulator in the
# recurrence.  With fp32 accumulation the cell's unrolled error vs fp32
# drops ~16x (3.0e-4 mean |h| error over 120 steps vs the carry-only
# cell).  This run repeats the EXACT same arm a third time (seed 3,
# 16 envs, 1:20, --n-step 3, 85 min, only --compute-dtype bfloat16) to
# ask whether fp32 accumulation recovers the fp32 learning curve.
# Success bar unchanged: final 20-ep eval >= ~300 (vs fp32's 351.7)
# flips WALKER_R2D2.compute_dtype; the TPU throughput row
# (runs/tpu/bench_cell_bf16.json) is the other half of that decision —
# preferred_element_type costs nothing on the MXU (it natively
# accumulates bf16 products in fp32) but must be confirmed on-chip.
#
# Queued behind the cheetah twin-critic probe; preemptible by the TPU
# campaign; superseded by the on-chip walker30_bf16 (same cell, same
# question, better hardware).
HERE="$(cd "$(dirname "$0")" && pwd)"
cd "$HERE/.."
mkdir -p runs
exec >> runs/walker_bf16acc_probe.log 2>&1
source "$HERE/lib_gate.sh" || exit 1

run_evidence runs/walker_probe_bf16acc runs/tpu/walker30_bf16/.done \
  "^[^ ]*bash [^ ]*(walker_combo_probe|walker_mpbf16_probe|cheetah_twin_probe)\.sh" \
  85 3 "--config walker_r2d2 --compute-dtype bfloat16" \
  --config walker_r2d2 --compute-dtype bfloat16 \
  --num-envs 16 --learner-steps 16 --batch-size 64 --min-replay 300 \
  --n-step 3
