"""Noise processes and the param-staleness fidelity knob (SURVEY.md §2.3).

The reference's actors act with *stale* params refreshed every K env steps;
``TrainerConfig.param_sync_every=K`` reproduces that.  These tests pin the
staleness semantics and the statistical behavior of the noise processes.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from r2d2dpg_tpu.configs import PENDULUM_TINY
from r2d2dpg_tpu.ops import gaussian_noise, ou_step


def test_gaussian_noise_scales_per_actor():
    key = jax.random.PRNGKey(0)
    sigma = jnp.array([0.1, 1.0, 3.0])
    samples = jnp.stack(
        [
            gaussian_noise(jax.random.fold_in(key, i), jnp.zeros((3, 4)), sigma)
            for i in range(2000)
        ]
    )
    stds = np.asarray(samples.std(axis=(0, 2)))
    np.testing.assert_allclose(stds, np.asarray(sigma), rtol=0.1)


def test_ou_noise_is_autocorrelated_and_mean_reverting():
    key = jax.random.PRNGKey(1)
    sigma = jnp.array([0.3])
    st = jnp.zeros((1, 1))
    path = []
    for i in range(3000):
        st = ou_step(jax.random.fold_in(key, i), st, sigma)
        path.append(float(st[0, 0]))
    path = np.asarray(path)
    # Mean-reverting around 0; successive steps strongly correlated
    # (theta*dt = 1.5e-3 per step -> lag-1 autocorr ~ 1 - theta*dt).
    assert abs(path.mean()) < 0.5
    lag1 = np.corrcoef(path[:-1], path[1:])[0, 1]
    assert lag1 > 0.9
    iid = gaussian_noise(key, jnp.zeros((3000, 1)), jnp.array([0.3]))
    iid_lag1 = np.corrcoef(
        np.asarray(iid)[:-1, 0], np.asarray(iid)[1:, 0]
    )[0, 1]
    assert abs(iid_lag1) < 0.1  # the OU correlation is real, not an artifact


def _stale_trainer(k):
    cfg = dataclasses.replace(
        PENDULUM_TINY,
        trainer=dataclasses.replace(
            PENDULUM_TINY.trainer, param_sync_every=k, num_envs=2,
            batch_size=4, min_replay=2, capacity=32
        ),
    )
    return cfg.build()


def test_param_staleness_behavior_params_refresh_every_k():
    t = _stale_trainer(k=3)
    s = t.init()
    for _ in range(t.window_fill_phases):
        s = t.collect_phase(s)
    s = t.fill_phase(s)

    def flat(p):
        return np.concatenate(
            [np.asarray(x).ravel() for x in jax.tree_util.tree_leaves(p)]
        )

    behaviors, onlines = [], []
    for _ in range(7):
        s, _ = t.train_phase(s)
        behaviors.append(flat(s.behavior_params))
        onlines.append(flat(s.train.actor_params))

    # Online params move every phase...
    for a, b in zip(onlines, onlines[1:]):
        assert not np.array_equal(a, b)
    # ...behavior snapshots only change on refresh phases (every 3rd).
    changes = [
        not np.array_equal(a, b) for a, b in zip(behaviors, behaviors[1:])
    ]
    assert sum(changes) < len(changes)  # some phases kept the stale snapshot
    # And stale phases act with params != current online params.
    assert not np.array_equal(behaviors[-1], onlines[-1]) or changes[-1]


def test_param_fresh_default_tracks_online():
    t = _stale_trainer(k=0)
    s = t.init()
    for _ in range(t.window_fill_phases):
        s = t.collect_phase(s)
    s = t.fill_phase(s)
    s, _ = t.train_phase(s)
    # With always-fresh params the collect phase reads train.actor_params
    # directly; the stored behavior snapshot is untouched from init.
    assert int(s.train.step) == 1
