"""Autoscaler subsystem (ISSUE 16): supervisor runtime resize, the
hysteresis policy engine, and the kill-drill recovery acceptance.

Three layers, mirroring the subsystem's own:

- **Supervisor resize API** — ``set_target``/``spawn_slot``/
  ``retire_slot`` against the backoff/give-up ladder, all fake-clock
  (``_poll_once`` driven directly, no sleeps): an autoscale retire never
  triggers crash-restart churn, a mid-backoff slot no-ops the explicit
  spawn (pending-until-landed — the no-double-spawn pin), a gave-up
  terminal slot is resurrected only by an explicit ``spawn_slot``.
- **Hysteresis math** — per-rule fire streaks, the cooldown ring, the
  actions-per-window budget, warm-up exemptions and dry-run, under an
  injectable clock with a scriptable fake health engine.  Includes the
  acceptance flapping fixture: alternating starving/ok findings produce
  at most one action per cooldown window.
- **Kill-drill e2e** (non-slow; ``scripts/lib_gate.sh autoscale_gate``
  runs it) — a live 2-actor fleet under ``kill_actor@p3`` with the
  supervisor in ``restart="policy"`` mode: the autoscaler (not the
  reflexive ladder) restores the population, evidenced by an
  ``autoscale_action`` paired with an ``origin="autoscale"`` spawn and
  ``restarts_total == 0``.
"""

import sys
import threading
import time

import pytest

from r2d2dpg_tpu.configs import PENDULUM_TINY
from r2d2dpg_tpu.fleet import (
    ActorSupervisor,
    AutoscaleConfig,
    Autoscaler,
    ChaosEngine,
    FleetConfig,
    FleetLearner,
    SupervisorConfig,
    parse_chaos_spec,
)
from r2d2dpg_tpu.obs import get_flight_recorder

pytestmark = pytest.mark.autoscale


# ---------------------------------------------------- fake-clock scaffolding
class _FakeProc:
    """poll()-able stand-in (the test_fleet.py pattern) plus the retire
    path's signal surface: SIGUSR1/terminate/kill are recorded, and the
    test flips ``returncode`` to simulate the worker exiting."""

    def __init__(self, returncode=None):
        self.returncode = returncode
        self.signals = []
        self.terminated = False
        self.killed = False

    def poll(self):
        return self.returncode

    def send_signal(self, sig):
        self.signals.append(sig)

    def terminate(self):
        self.terminated = True

    def kill(self):
        self.killed = True
        self.returncode = -9


def _fake_clock_supervisor(num_actors=1, **cfg):
    sup = ActorSupervisor(
        lambda i: ["unused"],
        num_actors,
        config=SupervisorConfig(**cfg),
        clock=lambda: 0.0,
    )
    spawned = []

    def fake_spawn(actor_id):
        slot = sup._slots[actor_id]
        slot.proc = _FakeProc()
        slot.restart_at = None
        spawned.append(actor_id)

    sup._spawn = fake_spawn
    for i in range(num_actors):
        sup._spawn(i)  # slots start live, no monitor thread
    spawned.clear()
    return sup, spawned


# ------------------------------------------------- supervisor resize: retire
def test_retire_slot_drains_without_crash_restart_churn():
    """The satellite pin: an autoscale retire must never walk the
    crash-restart ladder.  The retired worker's exit is reaped as
    ``actor_drained`` — no crash event, no backoff, no restart."""
    sup, spawned = _fake_clock_supervisor(backoff_base_s=0.5)
    n0 = len(get_flight_recorder().events())
    slot = sup._slots[0]
    proc = slot.proc
    assert sup.retire_slot(0, origin="autoscale")
    assert proc.signals  # SIGUSR1 delivered: the drain request
    assert slot.restart_at is None
    proc.returncode = 0  # worker finished its phase, sent BYE, exited
    sup._poll_once(1.0)
    assert slot.proc is None and not spawned
    sup._poll_once(100.0)  # and STAYS drained — no late respawn either
    assert not spawned and sup.restarts_total == 0
    events = get_flight_recorder().events()[n0:]
    kinds = [e["kind"] for e in events]
    assert "actor_retire" in kinds and "actor_drained" in kinds
    assert "actor_crash" not in kinds and "actor_restart" not in kinds
    retire = next(e for e in events if e["kind"] == "actor_retire")
    assert retire["origin"] == "autoscale" and retire["draining"]


def test_retire_slot_escalates_term_then_kill_on_deadline():
    sup, _ = _fake_clock_supervisor(retire_grace_s=10.0)
    slot = sup._slots[0]
    proc = slot.proc
    assert sup.retire_slot(0)
    assert slot.retire_at == 10.0
    sup._poll_once(9.9)  # inside the drain grace: nothing escalates
    assert not proc.terminated
    sup._poll_once(10.0)  # grace over: SIGTERM
    assert proc.terminated and not proc.killed
    sup._poll_once(19.9)  # second grace running
    assert not proc.killed
    sup._poll_once(20.0)  # ignored SIGTERM too: SIGKILL
    assert proc.killed
    sup._poll_once(20.1)  # the corpse reaps as a drain, not a crash
    assert slot.proc is None and sup.restarts_total == 0


def test_retire_slot_noops_on_retired_gave_up_or_absent():
    sup, _ = _fake_clock_supervisor()
    assert sup.retire_slot(0)
    assert not sup.retire_slot(0)  # already draining
    assert not sup.retire_slot(7)  # absent
    sup._slots[0].retired = False
    sup._slots[0].gave_up = True
    assert not sup.retire_slot(0)  # terminal slots are not retire targets


# -------------------------------------------------- supervisor resize: spawn
def test_spawn_slot_noops_mid_backoff_pending_until_landed():
    """The no-double-spawn fix, pinned: while the backoff ladder owns a
    crashed slot's respawn, an explicit ``spawn_slot`` must no-op (False
    — the caller keeps it pending and retries) instead of putting two
    processes in one ladder lane."""
    sup, spawned = _fake_clock_supervisor(backoff_base_s=0.5)
    slot = sup._slots[0]
    assert not sup.spawn_slot(0)  # live slot: no-op
    slot.proc.returncode = 1
    sup._poll_once(100.0)  # corpse found: ladder arms restart_at=100.5
    assert slot.restart_at == 100.5
    assert not sup.spawn_slot(0)  # mid-backoff: the ladder owns this lane
    assert not spawned
    sup._poll_once(100.5)  # the ladder's own respawn lands
    assert spawned == [0] and sup.restarts_total == 1
    assert not sup.spawn_slot(0)  # and the new incarnation is live: no-op
    assert spawned == [0]


def test_spawn_slot_lands_on_policy_mode_corpse():
    """restart="policy": the ladder records the crash and leaves the slot
    DOWN — no restart_at, no reflexive respawn ever — and the policy
    engine's ``spawn_slot`` is what brings it back (restarts_total stays
    0: replacement is a decision, not a crash-restart)."""
    sup, spawned = _fake_clock_supervisor(restart="policy")
    n0 = len(get_flight_recorder().events())
    slot = sup._slots[0]
    slot.proc.returncode = 1
    sup._poll_once(100.0)
    assert slot.proc is None and slot.restart_at is None
    assert sup.slot_states()[0] == "down"
    sup._poll_once(200.0)  # and stays down: policy owns the recovery
    assert not spawned
    assert sup.spawn_slot(0, origin="autoscale")
    assert spawned == [0] and sup.restarts_total == 0
    events = get_flight_recorder().events()[n0:]
    assert any(e["kind"] == "actor_crash" for e in events)
    spawn = next(e for e in events if e["kind"] == "actor_spawn")
    assert spawn["origin"] == "autoscale" and not spawn["resurrected"]


def test_policy_mode_terminal_exit_still_gives_up():
    from r2d2dpg_tpu.utils.codes import TERMINAL_ACTOR_EXITS

    sup, spawned = _fake_clock_supervisor(restart="policy")
    slot = sup._slots[0]
    slot.proc.returncode = next(iter(TERMINAL_ACTOR_EXITS))
    sup._poll_once(100.0)
    assert slot.gave_up and sup.slot_states()[0] == "gave_up"


def test_spawn_slot_resurrects_gave_up_only_explicitly():
    """A gave-up terminal slot must not be resurrected by scale-up
    (set_target skips it) — only an explicit spawn_slot re-targets it."""
    sup, spawned = _fake_clock_supervisor(num_actors=2)
    n0 = len(get_flight_recorder().events())
    sup._slots[0].gave_up = True
    sup._slots[0].proc = None
    # Scale-up walks PAST the gave-up lane: with lane 1 live and minting
    # capped at 2 lanes, there is nowhere to grow — no spawn, and
    # critically no resurrection.
    res = sup.set_target(2, lane_limit=2)
    assert res["spawned"] == [] and sup._slots[0].gave_up
    assert not spawned
    # Uncapped, it mints the NEXT lane rather than touch the terminal one.
    res = sup.set_target(2)
    assert res["spawned"] == [2] and sup._slots[0].gave_up
    # The explicit escape hatch: spawn_slot resurrects.
    assert sup.spawn_slot(0, origin="autoscale")
    assert not sup._slots[0].gave_up
    spawn = [
        e
        for e in get_flight_recorder().events()[n0:]
        if e["kind"] == "actor_spawn" and e.get("actor") == 0
    ]
    assert spawn and spawn[-1]["resurrected"]


def test_set_target_retires_highest_spawns_lowest_free():
    sup, spawned = _fake_clock_supervisor(num_actors=3)
    assert sup.target == 3
    res = sup.set_target(2)
    assert res["retiring"] == [2] and sup.target == 2
    assert sup.slot_states()[2] == "retired"
    # Scale back up while lane 2 is still draining: the walk must not
    # reuse the draining lane (two processes, one sigma slice) — it
    # mints lane 3 instead.
    res = sup.set_target(3)
    assert res["spawned"] == [3] and spawned == [3]
    # Once lane 2's worker exits and reaps, it becomes free again.
    sup._slots[2].proc.returncode = 0
    sup._poll_once(50.0)
    res = sup.set_target(4)
    assert res["spawned"] == [2]


# ------------------------------------------------------- policy-engine fakes
def _finding(rule, detail="", value=1.0, threshold=0.0):
    return {
        "rule": rule,
        "severity": "degraded",
        "detail": detail,
        "value": value,
        "threshold": threshold,
    }


class _FakeEngine:
    """Scriptable HealthEngine: each evaluate() pops the next findings
    list (the last entry repeats once the script is exhausted)."""

    def __init__(self, *script):
        self.script = list(script)
        self.evaluations = 0

    def evaluate(self):
        self.evaluations += 1
        findings = (
            self.script.pop(0) if len(self.script) > 1 else
            (self.script[0] if self.script else [])
        )
        return {"verdict": "ok", "findings": list(findings), "t_wall": 0.0}


class _FakeSup:
    """The resize API surface the policy engine actuates, scriptable:
    ``spawn_ok=False`` makes every landing attempt fail (the
    pending-until-landed path)."""

    def __init__(self, states=None, target=2):
        self.states = dict(states if states is not None else {0: "live", 1: "live"})
        self._target = target
        self.calls = []
        self.spawn_ok = True

    @property
    def target(self):
        return self._target

    def slot_states(self):
        return dict(self.states)

    def spawn_slot(self, i, *, origin="resize"):
        self.calls.append(("spawn_slot", i))
        if not self.spawn_ok:
            return False
        self.states[i] = "live"
        return True

    def retire_slot(self, i, *, origin="resize"):
        self.calls.append(("retire_slot", i))
        self.states[i] = "retired"
        return True

    def kill_actor(self, i):
        self.calls.append(("kill_actor", i))
        self.states[i] = "down"
        return True

    def set_target(self, n, *, lane_limit=None):
        self.calls.append(("set_target", n))
        spawned, retiring = [], []
        active = sorted(
            i for i, s in self.states.items() if s in ("live", "down")
        )
        while len(active) > n:
            retiring.append(active.pop())
            self.states[retiring[-1]] = "retired"
        while len(active) < n:
            lane = 0
            while lane in active or self.states.get(lane) in (
                "retired", "gave_up",
            ):
                lane += 1
            if lane_limit is not None and lane >= lane_limit:
                break
            if not self.spawn_ok:
                break
            self.states[lane] = "live"
            active.append(lane)
            spawned.append(lane)
        self._target = n
        return {"spawned": spawned, "retiring": retiring}


def _autoscaler(engine, sup, *, clock, ready=None, **cfg):
    cfg.setdefault("min_actors", 1)
    cfg.setdefault("max_actors", 4)
    cfg.setdefault("fire_threshold", 3)
    cfg.setdefault("cooldown_s", 30.0)
    cfg.setdefault("eval_every_s", 1.0)
    return Autoscaler(
        engine,
        sup,
        config=AutoscaleConfig(**cfg),
        clock=lambda: clock[0],
        ready_fn=ready,
    )


# ------------------------------------------------------------ hysteresis math
def test_fire_threshold_needs_consecutive_findings():
    clock = [0.0]
    down = [_finding("actors_down")]
    eng = _FakeEngine(down)
    sup = _FakeSup({0: "down", 1: "live"})
    a = _autoscaler(eng, sup, clock=clock, fire_threshold=3)
    assert a.tick(0.0) is None  # streak 1
    assert a.tick(1.0) is None  # streak 2
    assert sup.calls == []
    act = a.tick(2.0)  # streak 3: fires
    assert act is not None and act.kind == "spawn_actor" and act.slot == 0
    assert ("spawn_slot", 0) in sup.calls


def test_streak_resets_on_a_clean_evaluation():
    clock = [0.0]
    down = [_finding("actors_down")]
    eng = _FakeEngine(down, down, [], down, down, down)
    sup = _FakeSup({0: "down", 1: "live"})
    a = _autoscaler(eng, sup, clock=clock, fire_threshold=3)
    for t in range(2):
        assert a.tick(float(t)) is None  # streak 1, 2
    assert a.tick(2.0) is None  # clean tick: streak resets
    assert a.tick(3.0) is None and a.tick(4.0) is None  # 1, 2 again
    assert sup.calls == []
    assert a.tick(5.0) is not None  # only NOW 3 consecutive


def test_flapping_findings_produce_at_most_one_action_per_cooldown():
    """The acceptance fixture: alternating starving/ok findings.  At
    fire_threshold 1 (maximally twitchy) the cooldown ring still bounds
    actuation to one action per window; at the default threshold the
    streak never builds and NOTHING fires."""
    starving = [_finding("learner_starving")]
    # Maximally twitchy: threshold 1, so only the cooldown protects.
    clock = [0.0]
    eng = _FakeEngine(starving, [], starving, [], starving, [])
    sup = _FakeSup({0: "live", 1: "live"})
    a = _autoscaler(
        eng, sup, clock=clock, fire_threshold=1, cooldown_s=30.0
    )
    landed = [a.tick(float(t)) for t in range(6)]  # one 30 s window
    assert sum(x is not None for x in landed) <= 1
    # Default threshold: the alternation never builds a streak — inert.
    eng2 = _FakeEngine(starving, [], starving, [], starving, [])
    sup2 = _FakeSup({0: "live", 1: "live"})
    a2 = _autoscaler(eng2, sup2, clock=clock, fire_threshold=3)
    assert all(a2.tick(float(t)) is None for t in range(6))
    assert sup2.calls == []


def test_cooldown_blocks_until_window_elapses():
    clock = [0.0]
    down = [_finding("actors_down")]
    eng = _FakeEngine(down)
    sup = _FakeSup({0: "down", 1: "down"})
    a = _autoscaler(eng, sup, clock=clock, fire_threshold=1, cooldown_s=30.0)
    assert a.tick(0.0) is not None  # lands on slot 0
    assert a.tick(10.0) is None  # slot 1 still down, but cooling down
    assert a.tick(29.9) is None
    act = a.tick(30.0)
    assert act is not None and act.slot == 1


def test_actions_per_window_budget_caps_a_hot_rule():
    clock = [0.0]
    down = [_finding("actors_down")]
    eng = _FakeEngine(down)
    sup = _FakeSup({i: "down" for i in range(4)})
    a = _autoscaler(
        eng,
        sup,
        clock=clock,
        fire_threshold=1,
        cooldown_s=10.0,
        window_s=300.0,
        max_actions_per_window=2,
    )
    assert a.tick(0.0) is not None
    assert a.tick(10.0) is not None
    assert a.tick(20.0) is None  # budget spent: gated for the window
    assert a.tick(100.0) is None
    assert a.tick(300.0) is not None  # first action aged out of the window


def test_warmup_exempts_replacement_but_gates_load_scaling():
    clock = [0.0]
    ready = [False]
    # Load rule during warm-up: gated.
    eng = _FakeEngine([_finding("learner_starving")])
    sup = _FakeSup({0: "live", 1: "live"})
    a = _autoscaler(
        eng, sup, clock=clock, fire_threshold=1, ready=lambda: ready[0]
    )
    assert a.tick(0.0) is None and sup.calls == []
    # Replacement during the same warm-up: acts (a dead process is a dead
    # process, absorb or not).
    eng2 = _FakeEngine([_finding("actors_down")])
    sup2 = _FakeSup({0: "down", 1: "live"})
    a2 = _autoscaler(
        eng2, sup2, clock=clock, fire_threshold=1, ready=lambda: ready[0]
    )
    assert a2.tick(0.0) is not None
    # And once steady, the same starving finding scales up.
    ready[0] = True
    assert a.tick(1.0) is not None


def test_scale_up_respects_max_and_scale_down_respects_min():
    clock = [0.0]
    starving = [_finding("learner_starving")]
    churn = [_finding("eviction_churn")]
    sup = _FakeSup({0: "live", 1: "live"}, target=2)
    a = _autoscaler(
        _FakeEngine(starving),
        sup,
        clock=clock,
        fire_threshold=1,
        max_actors=2,  # already at the ceiling
    )
    assert a.tick(0.0) is None and sup.calls == []
    sup2 = _FakeSup({0: "live"}, target=1)
    a2 = _autoscaler(
        _FakeEngine(churn), sup2, clock=clock, fire_threshold=1, min_actors=1
    )
    assert a2.tick(0.0) is None and sup2.calls == []  # at the floor
    # In bounds, both act: up via set_target(+1), down via set_target(-1).
    sup3 = _FakeSup({0: "live", 1: "live"}, target=2)
    a3 = _autoscaler(
        _FakeEngine(starving), sup3, clock=clock, fire_threshold=1,
        max_actors=4,
    )
    act = a3.tick(0.0)
    assert act is not None and act.kind == "spawn_actor" and act.goal == 3
    assert ("set_target", 3) in sup3.calls
    sup4 = _FakeSup({0: "live", 1: "live"}, target=2)
    a4 = _autoscaler(
        _FakeEngine(churn), sup4, clock=clock, fire_threshold=1, min_actors=1
    )
    act = a4.tick(0.0)
    assert act is not None and act.kind == "kill_actor" and act.goal == 1
    assert ("set_target", 1) in sup4.calls


def test_starving_with_stale_actor_replaces_instead_of_scaling():
    """Scale-up requires ALL actors fresh: a starving learner alongside a
    wedged actor means replace the wedge, not mask it with population."""
    clock = [0.0]
    eng = _FakeEngine(
        [
            _finding("learner_starving"),
            _finding("telem_stale", detail="actor 1 TELEM stale — wedged"),
        ]
    )
    sup = _FakeSup({0: "live", 1: "live"})
    a = _autoscaler(eng, sup, clock=clock, fire_threshold=1)
    act_landed = a.tick(0.0)
    # Stage 1 of replace: the kill (pending until the respawn lands).
    assert act_landed is None
    assert ("kill_actor", 1) in sup.calls
    assert not any(c[0] == "set_target" for c in sup.calls)
    act = a.tick(1.0)  # slot now "down": stage 2 spawns — lands
    assert act is not None and act.kind == "replace_actor" and act.slot == 1
    assert ("spawn_slot", 1) in sup.calls


def test_pending_until_landed_never_double_spawns():
    """An actuation that cannot land (mid-backoff lane) stays pending and
    is retried next tick — no new decisions, no second action, and
    exactly one autoscale_action once it lands."""
    clock = [0.0]
    eng = _FakeEngine([_finding("actors_down")])
    sup = _FakeSup({0: "down", 1: "live"})
    sup.spawn_ok = False  # the lane refuses to land (ladder owns it)
    a = _autoscaler(eng, sup, clock=clock, fire_threshold=1)
    n0 = len(get_flight_recorder().events())
    assert a.tick(0.0) is None
    assert a.stats()["autoscale_pending"] == "spawn_actor"
    evals = eng.evaluations
    assert a.tick(1.0) is None  # retry, still not landing
    assert eng.evaluations == evals  # no new evaluation while pending
    sup.spawn_ok = True
    act = a.tick(2.0)
    assert act is not None and a.stats()["autoscale_pending"] is None
    actions = [
        e
        for e in get_flight_recorder().events()[n0:]
        if e["kind"] == "autoscale_action"
    ]
    assert len(actions) == 1
    assert sum(1 for c in sup.calls if c == ("spawn_slot", 0)) == 3


def test_pending_replacement_superseded_by_ladder_recovery():
    """A pending respawn whose slot comes back on its own (the reflexive
    ladder beat the policy to it) is dropped WITHOUT an autoscale_action
    — nothing was actuated, so nothing may claim it was."""
    clock = [0.0]
    eng = _FakeEngine([_finding("actors_down")])
    sup = _FakeSup({0: "down", 1: "live"})
    sup.spawn_ok = False
    a = _autoscaler(eng, sup, clock=clock, fire_threshold=1)
    assert a.tick(0.0) is None  # pending
    sup.states[0] = "live"  # the ladder respawned it meanwhile
    n0 = len(get_flight_recorder().events())
    assert a.tick(1.0) is None
    assert a.stats()["autoscale_pending"] is None
    assert not any(
        e["kind"] == "autoscale_action"
        for e in get_flight_recorder().events()[n0:]
    )


def test_dry_run_logs_decisions_but_never_actuates():
    clock = [0.0]
    eng = _FakeEngine([_finding("actors_down")])
    sup = _FakeSup({0: "down", 1: "live"})
    a = _autoscaler(eng, sup, clock=clock, fire_threshold=1, dry_run=True)
    n0 = len(get_flight_recorder().events())
    assert a.tick(0.0) is None
    assert sup.calls == []  # nothing moved
    s = a.stats()
    assert s["autoscale_dry_run_decisions"] == 1
    assert sum(s["autoscale_actions"].values()) == 0
    events = get_flight_recorder().events()[n0:]
    decisions = [e for e in events if e["kind"] == "autoscale_decision"]
    assert decisions and decisions[0]["dry_run"] and decisions[0]["fired"]
    assert not any(e["kind"] == "autoscale_action" for e in events)
    # The hysteresis clock ticked: an immediate second decision cools down.
    assert a.tick(1.0) is None
    assert a.stats()["autoscale_dry_run_decisions"] == 1


def test_shards_down_respawns_through_the_tier():
    class _Tier:
        def __init__(self):
            self.supervisor = _FakeSup({0: "gave_up"})

    clock = [0.0]
    tier = _Tier()
    eng = _FakeEngine([_finding("shards_down")])
    a = Autoscaler(
        eng,
        _FakeSup({0: "live", 1: "live"}),
        shard_tier=tier,
        config=AutoscaleConfig(fire_threshold=1, max_actors=4),
        clock=lambda: clock[0],
    )
    act = a.tick(0.0)
    assert act is not None and act.kind == "respawn_shard_proc"
    assert ("spawn_slot", 0) in tier.supervisor.calls


# ------------------------------------------------------- kill-drill e2e
def test_autoscale_kill_drill_restores_population(tmp_path):
    """The acceptance drill (non-slow; autoscale_gate runs it): a live
    2-actor fleet, ``kill_actor@p3``, supervisor in policy mode — the
    AUTOSCALER restores the target population (autoscale_action paired
    with an origin="autoscale" spawn, restarts_total == 0: planned
    recovery, not the reflexive crash-restart), counters stay monotone,
    accounting is not lost, sheds == 0."""
    from r2d2dpg_tpu import obs
    from r2d2dpg_tpu.fleet.actor import FleetActor

    seed = 0
    num_actors = 2
    spec = "kill_actor@p3"
    trainer = PENDULUM_TINY.build()
    # Deep queue + patient shed deadline: no chaos fault paces these
    # actors (the one kill hits a supervised sleeper), so they run the
    # ingest queue full flat-out and a post-steady compile gap would trip
    # the 1 s default — this drill's sheds==0 claim is about the
    # RECOVERY dropping nothing, not about the shed contract (pinned by
    # the backpressure tests).
    learner = FleetLearner(
        trainer,
        FleetConfig(
            num_actors=num_actors,
            queue_depth=32,
            idle_timeout_s=120,
            shed_after_s=30.0,
        ),
    )
    address = learner.start()
    actors = [
        FleetActor(
            PENDULUM_TINY,
            actor_id=i,
            num_actors=num_actors,
            address=address,
            seed=seed,
        )
        for i in range(num_actors)
    ]

    def actor_loop(a):
        try:
            a.run(max_phases=400)
        except Exception:  # noqa: BLE001 — server teardown cuts the socket
            pass

    threads = [
        threading.Thread(target=actor_loop, args=(a,), daemon=True)
        for a in actors
    ]
    # The kill victims: supervised jax-free sleepers in POLICY mode — a
    # crash leaves the slot down for the autoscaler, never the ladder.
    sup = ActorSupervisor(
        lambda i: [sys.executable, "-c", "import time; time.sleep(600)"],
        num_actors,
        config=SupervisorConfig(poll_s=0.05, restart="policy"),
    )
    engine = ChaosEngine(
        parse_chaos_spec(spec),
        seed=seed,
        num_actors=num_actors,
        supervisor=sup,
        server=learner.server,
    )
    # telem_expected=False: the drill's experience carriers are in-process
    # threads with no --telem-every cadence (train.py derives this from
    # the resolved --obs-fleet) — a growing staleness clock here is not a
    # wedge, and judging it would have the policy loop replacing healthy
    # sleepers until the window budget starves the REAL recovery.
    health = obs.HealthEngine(
        obs.HealthConfig(expected_actors=num_actors, telem_expected=False),
        registry=obs.get_registry(),
    )
    scaler = Autoscaler(
        health,
        sup,
        config=AutoscaleConfig(
            min_actors=1,
            max_actors=num_actors,
            fire_threshold=2,
            cooldown_s=0.2,
            window_s=60.0,
            max_actions_per_window=4,
            eval_every_s=0.05,
        ),
    )
    n_train = 8
    rows = []
    n0 = len(get_flight_recorder().events())
    for t in threads:
        t.start()
    try:
        sup.start()
        scaler.start()
        state = learner.run(
            n_train,
            log_every=2,
            metrics_fn=lambda p, s: rows.append((p, dict(s))),
            phase_fn=engine.on_phase,
        )
        # Hold the fleet up until the autoscaler's replacement lands (the
        # learner can burn its queue backlog before the ~0.1 s policy
        # loop reacts — same race the chaos drill test holds open).
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and (
            sup.alive_count() < num_actors
            or sum(scaler.stats()["autoscale_actions"].values()) < 1
        ):
            time.sleep(0.05)
        alive_restored = sup.alive_count()  # before teardown reaps the fleet
    finally:
        scaler.stop()
        sup.stop()
        learner.close()
        for t in threads:
            t.join(timeout=30)

    # 1. The run completed its schedule; the drill fired.
    assert int(state.train.step) == n_train * trainer.config.learner_steps
    stats = learner.stats()
    assert stats["train_phases"] == n_train
    assert not engine.unfired()
    assert stats["sheds"] == 0

    # 2. Monotone counters, accounting preserved.
    env_steps = [s["env_steps"] for _, s in rows]
    assert env_steps == sorted(env_steps) and env_steps[-1] > 0

    # 3. Population restored BY POLICY: an autoscale_action paired with
    # an origin="autoscale" spawn on the killed slot — and zero ladder
    # restarts (the planned version of crash-restart).
    events = get_flight_recorder().events()[n0:]
    kill_target = next(
        e["actor"]
        for e in events
        if e["kind"] == "chaos_inject" and e["fault"] == "kill_actor"
    )
    assert any(
        e["kind"] == "actor_crash" and e.get("actor") == kill_target
        for e in events
    )
    actions = [e for e in events if e["kind"] == "autoscale_action"]
    assert any(
        a["action"] == "spawn_actor"
        and a["slot"] == kill_target
        and a["rule"] == "actors_down"
        for a in actions
    )
    spawns = [
        e
        for e in events
        if e["kind"] == "actor_spawn"
        and e.get("actor") == kill_target
        and e.get("origin") == "autoscale"
    ]
    assert spawns, "the replacement spawn must be attributed to autoscale"
    assert not any(e["kind"] == "actor_restart" for e in events)
    assert sup.restarts_total == 0
    assert alive_restored == num_actors

    # 4. Time-to-recover reads off the flight timeline (the bench leg's
    # column): kill -> the autoscale spawn.
    t_kill = next(
        e["t_mono"]
        for e in events
        if e["kind"] == "chaos_inject" and e["fault"] == "kill_actor"
    )
    t_restore = spawns[0]["t_mono"]
    assert t_restore >= t_kill
