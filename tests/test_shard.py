"""Standalone crash-tolerant replay shard tier (ISSUE 12): supervised
shard processes, quota renormalization on shard loss, epoch-fenced
rejoin (fleet/shard.py).

Anchors ``scripts/lib_gate.sh shard_gate`` enforces before blessing
``--shard-procs N`` evidence dirs:

- **determinism** — the loopback-vs-out-of-process boundary is layout,
  never semantics: a BATCH through a REAL socket decodes bit-identically
  to the in-learner loopback roundtrip on the f32 lane (plus the
  ``--shard-procs 0`` off-setting riding the sampler CLI anchor in
  tests/test_sampler.py).
- **kill_shard** — the non-slow chaos e2e: 2 actors x 2 shard procs,
  ``kill_shard`` mid-run -> the run completes, counters stay monotone,
  quotas renormalize to the surviving shard, the restarted shard rejoins
  under a bumped epoch and serves traffic, and stale-epoch PRIO frames
  are ignored with a flight event; ``stall_shard`` pins zero sheds and
  zero false reaps through the stall.
"""

import threading
import time

import numpy as np
import pytest

from r2d2dpg_tpu.configs import PENDULUM_TINY
from r2d2dpg_tpu.fleet import chaos as fleet_chaos
from r2d2dpg_tpu.fleet import transport, wire
from r2d2dpg_tpu.fleet.shard import (
    RemoteShard,
    RemoteShardSet,
    ShardProcTier,
    ShardServer,
    ShardUnavailableError,
)
from r2d2dpg_tpu.fleet.supervisor import SupervisorConfig
from r2d2dpg_tpu.obs import get_flight_recorder
from r2d2dpg_tpu.replay.arena import SequenceBatch, StagedSequences
from r2d2dpg_tpu.replay.sharded import ReplayShard

pytestmark = pytest.mark.shard


def _np_staged(b=3, l=3, prios=(1.0, 2.0, 3.0), seed=1):
    rng = np.random.default_rng(seed)
    return StagedSequences(
        seq=SequenceBatch(
            obs=rng.normal(size=(b, l, 3)).astype(np.float32),
            action=rng.normal(size=(b, l, 1)).astype(np.float32),
            reward=rng.normal(size=(b, l)).astype(np.float32),
            discount=np.ones((b, l), np.float32),
            reset=np.zeros((b, l), np.float32),
            carries={},
        ),
        priorities=(
            None if prios is None else np.asarray(prios, np.float64)
        ),
    )


def _server(shard_id=0, epoch=1, capacity=8, auth=None, chaos=None):
    return ShardServer(
        ReplayShard(capacity, alpha=1.0, shard_id=shard_id),
        epoch=epoch,
        seed=0,
        auth_token=auth,
        chaos=chaos,
    ).start()


def _client(srv, auth=None, **kw):
    return RemoteShard(
        srv.shard.shard_id,
        lambda: srv.address,
        wire_config=wire.WireConfig(),
        auth_token=auth,
        max_frame_bytes=transport.MAX_FRAME_BYTES,
        read_deadline_s=30.0,
        **kw,
    )


# ------------------------------------------------------- determinism anchor
def test_socket_vs_loopback_batch_determinism_bitwise():
    """The shard_gate anchor: the SAME ShardSample through (a) the
    in-learner loopback pack/unpack and (b) a REAL ShardServer socket
    exchange decodes bit-identically on the f32 lane — moving a shard
    out of process is layout, never semantics."""
    staged = _np_staged(b=4, prios=(1.0, 2.0, 3.0, 4.0))
    srv = _server(capacity=8)
    client = _client(srv)
    try:
        # Seed the remote shard, then mirror its exact ring state locally.
        client.forward_seqs(staged)
        local = ReplayShard(8, alpha=1.0, shard_id=0)
        local.add(staged.seq, staged.priorities)
        # Remote draw (real socket), then replay the identical draw
        # locally: the shard process seeds its rng (seed, shard, epoch).
        resp = client.sample(5, req_id=1)
        rng = np.random.default_rng((0, 0, 1))
        s = local.sample(5, rng)
        packer = wire.TreePacker(wire.WireConfig())
        unpacker = wire.TreeUnpacker()
        loop = wire.unpack_shard_batch(
            unpacker.unpack(
                b"".join(
                    bytes(p)
                    for p in wire.pack_shard_batch(
                        packer,
                        req_id=1,
                        shard=0,
                        staged=StagedSequences(seq=s.seq, priorities=None),
                        slots=s.slots,
                        gens=s.gens,
                        probs=s.probs,
                        priority_sum=local.scaled_sum(),
                        occupancy=local.occupancy(),
                        epoch=1,
                    )
                )
            )
        )
        np.testing.assert_array_equal(resp["slots"], loop["slots"])
        np.testing.assert_array_equal(resp["gens"], loop["gens"])
        np.testing.assert_array_equal(resp["probs"], loop["probs"])
        for a, b in zip(
            [resp["staged"].seq.obs, resp["staged"].seq.reward],
            [loop["staged"].seq.obs, loop["staged"].seq.reward],
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert resp["epoch"] == loop["epoch"] == 1
        assert resp["priority_sum"] == loop["priority_sum"]
    finally:
        client.close()
        srv.stop()


# ----------------------------------------------------------- shard protocol
def test_shard_server_auth_epoch_and_stale_prio_fence():
    """Protocol + fences on one in-process server: HELLO auth refusal,
    the SEQS ack advertisement, BATCH epoch stamping, and the
    authoritative shard-side stale-epoch PRIO ignore (applied=0 + flight
    event + counter) that protects a restarted ring from its
    predecessor's verdicts."""
    srv = _server(shard_id=3, epoch=7, auth="sekrit")
    n0 = len(get_flight_recorder().events())
    try:
        # Wrong token: refused at the door.
        bad = _client(srv, auth="wrong")
        with pytest.raises(RuntimeError, match="refused"):
            bad.forward_seqs(_np_staged())
        bad.close()
        client = _client(srv, auth="sekrit")
        ack = client.forward_seqs(_np_staged(prios=(1.0, 2.0, 4.0)))
        assert ack["code"] == "ok" and ack["epoch"] == 7
        assert ack["occupancy"] == 3 and ack["scaled_sum"] == 7.0
        assert ack["priority_sum"] == 7.0 and ack["evictions"] == 0
        assert client.epoch == 7 and client.occupancy == 3
        resp = client.sample(2, req_id=5)
        assert resp["epoch"] == 7 and resp["req_id"] == 5
        # Fresh-epoch write-back applies; stale-epoch is IGNORED loudly.
        ok = client.write_back(
            resp["slots"], resp["gens"],
            np.full(2, 9.0, np.float32), epoch=7,
        )
        assert ok["applied"] == 2 and not ok["stale"]
        stale = client.write_back(
            resp["slots"], resp["gens"],
            np.full(2, 1.0, np.float32), epoch=6,
        )
        assert stale["applied"] == 0 and stale["stale"]
        # A SAMPLE_REQ at a live-but-EMPTY shard answers with an
        # empty-marked advert ack (None here), never a torn connection —
        # a stale quota weight meeting a fresh ring must not read as a
        # dead process (the connection stays usable).
        empty_srv = _server(shard_id=9, epoch=1)
        empty_client = _client(empty_srv)
        try:
            assert empty_client.sample(3, req_id=1) is None
            assert empty_client.scaled_sum == 0.0
            empty_client.forward_seqs(_np_staged())
            # The SAMPLE leg survived the empty answer: the very same
            # connection now serves a real BATCH.
            assert empty_client.sample(2, req_id=2) is not None
        finally:
            empty_client.close()
            empty_srv.stop()
        evs = [
            e for e in get_flight_recorder().events()[n0:]
            if e["kind"] == "stale_epoch_prio_ignored"
        ]
        assert evs and evs[-1]["got_epoch"] == 6 and evs[-1]["epoch"] == 7
        client.close()
    finally:
        srv.stop()


def test_remote_set_reroute_renorm_and_epoch_fenced_rejoin():
    """The degradation half without processes: kill server 0 (stop =
    dial refused), the set marks it dead — quota weights zero, routing
    falls to the survivor in ring order, accounting banks regardless —
    then a NEW incarnation (bumped epoch) rejoins: routing returns home,
    the stale advert is zeroed (an empty restarted ring must not inherit
    the dead ring's sums), and the learner-side epoch fence drops
    write-backs against the old incarnation."""
    addrs = {}
    srv0 = _server(shard_id=0, epoch=1)
    srv1 = _server(shard_id=1, epoch=1)
    addrs[0], addrs[1] = srv0.address, srv1.address
    ss = RemoteShardSet(
        2,
        lambda sid: addrs[sid],
        wire_config=wire.WireConfig(),
        rejoin_interval_s=0.0,
    )
    n0 = len(get_flight_recorder().events())
    try:
        for sid in (0, 1):
            ss.add(sid, {"staged": _np_staged(), "env_steps_delta": 9.0})
        assert ss.occupancy_total() == 6
        np.testing.assert_allclose(ss.scaled_sums(), [6.0, 6.0])
        resp = ss.shards[0].sample(2, req_id=1)
        handles_epoch = resp["epoch"]
        # --- death: server 0 gone, dial refused.
        srv0.stop()
        with pytest.raises(ShardUnavailableError):
            ss.shards[0].sample(1, req_id=2)
        ss._mark_dead(0, "drill")
        np.testing.assert_allclose(ss.scaled_sums(), [0.0, 6.0])
        assert ss.route(0) == 1  # home shard dead -> survivor, in ring order
        # adds (home 0) re-route; the accounting banks either way.
        ss.add(0, {"staged": _np_staged(), "env_steps_delta": 9.0,
                   "actor_id": 0})
        assert ss.shards[1].occupancy == 6  # ring of 8 holds both adds
        assert ss.pop_stats()["env_steps_delta"] == 27.0
        # --- rejoin: new incarnation, bumped epoch, empty ring.
        srv0b = _server(shard_id=0, epoch=2, capacity=8)
        addrs[0] = srv0b.address
        ss.maybe_rejoin()
        assert ss.shards[0].alive and ss.shards[0].epoch == 2
        assert ss.route(0) == 0  # traffic lands back home
        # The rejoined ring is EMPTY: its weight stays 0 (the dead ring's
        # sums are never inherited); the survivor holds both adds' sums.
        np.testing.assert_allclose(ss.scaled_sums(), [0.0, 12.0])
        kinds = [e["kind"] for e in get_flight_recorder().events()[n0:]]
        assert "shard_dead" in kinds and "shard_rejoin" in kinds
        # Learner-side epoch fence: handles from incarnation 1 never even
        # cross the wire (fleet/sampler.py groups per (shard, epoch)).
        assert handles_epoch == 1 != ss.shards[0].epoch
        srv0b.stop()
    finally:
        ss.close()
        srv1.stop()


def test_shard_chaos_stall_gate_arms_and_waits():
    fs = fleet_chaos.parse_chaos_spec("stall_shard@p2:0.3s")
    target = fleet_chaos.fault_target(fs[0], seed=0, num_actors=2)
    chaos = fleet_chaos.ShardChaos(
        fs, seed=0, num_shard_procs=2, proc_index=target
    )
    chaos.on_seqs_frame()
    t0 = time.monotonic()
    chaos.gate()
    assert time.monotonic() - t0 < 0.05  # phase 1: not due yet
    chaos.on_seqs_frame()  # phase 2: arms the stall
    t0 = time.monotonic()
    chaos.gate()
    assert time.monotonic() - t0 >= 0.25
    other = fleet_chaos.ShardChaos(
        fs, seed=0, num_shard_procs=2, proc_index=1 - target
    )
    other.on_seqs_frame()
    other.on_seqs_frame()
    t0 = time.monotonic()
    other.gate()
    assert time.monotonic() - t0 < 0.05  # not its fault


# --------------------------------------------------------------- chaos e2e
@pytest.mark.chaos
def test_chaos_kill_shard_stall_and_partition_e2e(tmp_path):
    """The acceptance drill (non-slow, 2 actors x 2 REAL shard procs):
    ``stall_shard`` + ``partition_shard`` + ``kill_shard`` in one run —
    the run completes its full phase schedule, counters stay monotone,
    zero sheds and zero false reaps through the stall, the dead shard's
    quota renormalizes to the survivor, and after the supervisor's
    backoff restart the shard rejoins EMPTY under a bumped epoch, serves
    traffic on both legs, and fences stale-epoch write-backs."""
    import queue as _q

    from r2d2dpg_tpu.fleet import FleetConfig, SamplerLearner
    from r2d2dpg_tpu.fleet.transport import (
        K_ACK,
        K_HELLO,
        K_SEQS,
        pack_hello,
        recv_frame,
        send_frame,
        send_frame_parts,
    )
    from r2d2dpg_tpu.training.pipeline import split_state

    SEED = 2  # pinned: stall->proc0, partition->shard1, kill->proc0
    N_TRAIN = 6
    spec = "stall_shard@p1:0.6s,partition_shard@p1,kill_shard@p2"
    faults = fleet_chaos.parse_chaos_spec(spec)
    assert fleet_chaos.fault_target(faults[2], SEED, 2) == 0  # kill proc 0
    assert fleet_chaos.fault_target(faults[1], SEED, 2) == 1  # partition 1

    import dataclasses as dc

    import jax

    trainer = PENDULUM_TINY.build()
    state = trainer.init()
    _, lstate = split_state(state)
    # The arena's storage tree IS the staged-batch template (leaves
    # [capacity, L, ...]): synthetic actors emit exactly the structure
    # the learn program expects, without paying a collect-program
    # compile this drill does not test.
    template = jax.device_get(lstate.arena.data)

    def synth_staged(rng, b=4):
        data = jax.tree_util.tree_map(
            lambda buf: (
                rng.normal(size=(b,) + np.shape(buf)[1:]).astype(buf.dtype)
                if buf.dtype.kind == "f"
                else np.zeros((b,) + np.shape(buf)[1:], buf.dtype)
            ),
            template,
        )
        data = dc.replace(
            data,
            discount=np.ones_like(data.discount),
            reset=np.zeros_like(data.reset),
        )
        return StagedSequences(
            seq=data, priorities=rng.uniform(0.5, 4.0, size=b)
        )

    tier = ShardProcTier(
        num_shards=2,
        num_procs=2,
        capacity_per_shard=128,
        alpha=trainer.config.priority_alpha,
        prioritized=True,
        dirpath=str(tmp_path / "shards"),
        seed=SEED,
        wire_config=wire.WireConfig(),
        chaos_spec=spec,
        flight_dir=str(tmp_path),
        supervisor_config=SupervisorConfig(
            backoff_base_s=0.2, poll_s=0.05
        ),
    )
    learner = SamplerLearner(
        trainer,
        FleetConfig(num_actors=2, idle_timeout_s=60),
        num_shards=2,
        shard_set=tier.shard_set,
    )
    engine = fleet_chaos.ChaosEngine(
        faults, seed=SEED, num_actors=2, server=learner.server,
        shard_tier=tier,
    )
    tier.start()
    address = learner.start()
    stop = threading.Event()

    def actor_loop(actor_id):
        # A wire-real synthetic actor: HELLO + streamed SEQS frames (the
        # collect compile is not what this drill tests); param pushes are
        # read and discarded.
        rng = np.random.default_rng(100 + actor_id)
        try:
            sock = transport.connect(address, read_deadline_s=60)
            packer = wire.TreePacker(wire.WireConfig())
            send_frame(
                sock,
                K_HELLO,
                pack_hello(
                    {
                        "actor_id": actor_id,
                        **wire.negotiation_fields(wire.WireConfig()),
                    }
                ),
            )
            while recv_frame(sock)[0] != K_ACK:
                pass
            phase = 0
            while not stop.is_set():
                send_frame_parts(
                    sock,
                    K_SEQS,
                    packer.pack(
                        {
                            "phase": phase,
                            "param_version": 0,
                            "env_steps_delta": 16.0,
                            "ep_return_sum": -1.0,
                            "ep_count": 1.0,
                            "staged": synth_staged(rng),
                        }
                    ),
                )
                while recv_frame(sock)[0] != K_ACK:
                    pass
                phase += 1
            sock.close()
        except Exception:  # noqa: BLE001 — teardown cuts the socket
            pass

    threads = [
        threading.Thread(target=actor_loop, args=(i,), daemon=True)
        for i in range(2)
    ]
    logged = []
    n0 = len(get_flight_recorder().events())
    try:
        for t in threads:
            t.start()
        state = learner.run(
            N_TRAIN,
            state=state,
            log_every=2,
            metrics_fn=lambda p, s: logged.append((p, dict(s))),
            phase_fn=engine.on_phase,
        )
    finally:
        stop.set()
        learner.close()
        for t in threads:
            t.join(timeout=10)

    # Run completed its exact schedule despite a shard dying mid-run.
    assert int(state.train.step) == N_TRAIN * trainer.config.learner_steps
    stats = learner.stats()
    assert stats["train_phases"] == N_TRAIN
    assert stats["sheds"] == 0  # zero sheds through the stall
    assert stats["shard_deaths"] >= 1
    assert engine.unfired() == ()  # kill + partition both landed
    # Monotone counters through stall, partition, death, re-route.
    env_steps = [s["env_steps"] for _, s in logged]
    assert env_steps == sorted(env_steps) and env_steps[-1] > 0
    evs = get_flight_recorder().events()[n0:]
    kinds = [e["kind"] for e in evs]
    assert "shard_dead" in kinds
    assert "shard_quota_renorm" in kinds  # survivors re-quota'd on death
    # Zero false reaps: nothing declared an actor or shard peer dead.
    assert "peer_dead" not in kinds
    # --- epoch-fenced rejoin: the killed proc's shard comes back under a
    # bumped epoch and serves BOTH legs.
    ss = tier.shard_set
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and not ss.shards[0].alive:
        ss.maybe_rejoin()
        time.sleep(0.05)
    try:
        assert ss.shards[0].alive and ss.shards[0].epoch == 2
        occ_before = ss.shards[0].occupancy
        rng = np.random.default_rng(0)
        ss.add(0, {"staged": synth_staged(rng), "actor_id": 0})
        # Restarted shard serves the ingest leg (occupancy grew by B
        # relative to whatever it re-absorbed since rejoin)...
        assert ss.shards[0].occupancy == occ_before + 4
        # ...and the sampler leg.
        resp = ss.shards[0].sample(2, req_id=99)
        assert resp["epoch"] == 2
        # Stale-epoch PRIO against the new incarnation: ignored loudly.
        stale = ss.shards[0].write_back(
            resp["slots"], resp["gens"], np.ones(2, np.float32), epoch=1
        )
        assert stale["applied"] == 0 and stale["stale"]
    finally:
        tier.stop()
    # The shard-side stall drill left durable evidence in its dump, and
    # every scheduled shard-proc fault fired (the unfired contract).
    assert (
        fleet_chaos.shard_faults_unfired(
            faults, str(tmp_path), seed=SEED, num_shard_procs=2
        )
        == ()
    )
    restarts = tier.restarts_total
    assert restarts >= 1  # the supervisor's ladder did the rejoin
