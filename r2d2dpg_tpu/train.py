"""Training entry point: ``python -m r2d2dpg_tpu.train --config walker_r2d2``.

Reference parity: SURVEY.md §2.5 — the reference's ``main.py`` parses flags,
spawns N actor processes + a learner and runs forever.  Here the same entry
drives the Anakin phase schedule (warm-up -> replay-fill -> train) on one
device or an SPMD mesh, wired to the aux subsystems of SURVEY §5:
checkpoint/resume (orbax), metrics (CSV + TensorBoard, return@wall-clock,
SPS), deterministic evaluation, profiler traces, NaN-debug mode.

Stop conditions: ``--phases N`` (exact phase count) and/or ``--minutes M``
(wall-clock budget — the BASELINE metric is return @ 30 min, so
``--minutes 30`` reproduces the north-star measurement).
"""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

from r2d2dpg_tpu.configs import CONFIGS, ExperimentConfig, get_config


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="python -m r2d2dpg_tpu.train", description=__doc__
    )
    p.add_argument("--config", required=True, choices=sorted(CONFIGS))
    p.add_argument("--phases", type=int, default=None, help="train phases to run")
    p.add_argument(
        "--minutes", type=float, default=None, help="wall-clock budget (stops at whichever of --phases/--minutes hits first)"
    )
    p.add_argument("--logdir", default=None, help="metrics/TB/profile output dir")
    p.add_argument("--log-every", type=int, default=50, help="phases between logs")
    p.add_argument("--seed", type=int, default=None)
    # Orchestration scale overrides (SURVEY §2.5 hyperparameter flags).
    p.add_argument("--num-envs", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--learner-steps", type=int, default=None)
    p.add_argument("--min-replay", type=int, default=None)
    p.add_argument(
        "--param-sync-every", type=int, default=None,
        help="refresh behavior params every K phases (0 = always fresh)"
    )
    p.add_argument(
        "--overlap-learner", type=int, default=None, choices=[0, 1],
        help="host-pool trainers: interleave learner updates between env "
        "steps so they hide under the MuJoCo step (1 = on)"
    )
    p.add_argument(
        "--pipeline", type=int, default=0, choices=[0, 1],
        help="run train phases through the pipelined collect/learn "
        "executor (training/pipeline.py): collection and learning overlap "
        "in two threads over a bounded staging queue (1 = on)"
    )
    p.add_argument(
        "--pipeline-depth", type=int, default=2,
        help="staging-queue capacity in collect phases (backpressure bound)"
    )
    # Agent/exploration hyperparameter overrides (VERDICT r2 weak #3: probe
    # whether the walker plateau is data-bound or hparam-capped).
    p.add_argument("--sigma-max", type=float, default=None,
                   help="exploration noise ladder max sigma")
    p.add_argument("--ladder-alpha", type=float, default=None,
                   help="noise ladder spread exponent")
    p.add_argument("--n-step", type=int, default=None, help="n-step TD horizon")
    p.add_argument("--actor-lr", type=float, default=None)
    p.add_argument("--critic-lr", type=float, default=None)
    # Overestimation mitigations (agents/ddpg.py AgentConfig; default off).
    p.add_argument(
        "--twin-critic", type=int, default=None, choices=[0, 1],
        help="TD3 clipped double-Q: train a 2-critic ensemble, bootstrap "
        "from min(Q1',Q2') (eval needs the same flag to restore)"
    )
    p.add_argument(
        "--target-policy-sigma", type=float, default=None,
        help="TD3 target-policy smoothing noise scale (0 = off)"
    )
    p.add_argument(
        "--compute-dtype", default=None, choices=["float32", "bfloat16"],
        help="net activation dtype (params/optimizer stay float32)"
    )
    # SPMD.
    p.add_argument(
        "--spmd", type=int, default=0, metavar="D",
        help="run under shard_map on a D-device dp mesh (0 = single device)"
    )
    # Checkpointing.
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument(
        "--checkpoint-every", type=int, default=500,
        help="phases between checkpoints (0 = off entirely; -1 = final-"
        "save-only, e.g. for measurement runs where periodic saves would "
        "drag the GB-scale replay arena device->host mid-run)"
    )
    p.add_argument(
        "--checkpoint-light", action="store_true",
        help="save only the learner subtree (params/targets/opt/step): MBs "
        "instead of GBs, eval-compatible; resume restarts replay fresh"
    )
    p.add_argument("--resume", action="store_true", help="resume from the latest checkpoint in --checkpoint-dir")
    # Evaluation.
    p.add_argument("--eval-every", type=int, default=0, help="train phases between deterministic evals (0 = off)")
    p.add_argument("--eval-envs", type=int, default=10)
    # Debug / profiling.
    p.add_argument("--profile-phases", type=int, default=0, help="trace this many train phases into --logdir/profile")
    p.add_argument("--nan-debug", action="store_true")
    return p.parse_args(argv)


def _apply_overrides(cfg: ExperimentConfig, args) -> ExperimentConfig:
    t = {}
    for flag, field in (
        ("num_envs", "num_envs"),
        ("batch_size", "batch_size"),
        ("learner_steps", "learner_steps"),
        ("min_replay", "min_replay"),
        ("param_sync_every", "param_sync_every"),
        ("overlap_learner", "overlap_learner"),
        ("seed", "seed"),
        ("sigma_max", "sigma_max"),
        ("ladder_alpha", "ladder_alpha"),
    ):
        v = getattr(args, flag)
        if v is not None:
            t[field] = bool(v) if field == "overlap_learner" else v
    if t:
        cfg = dataclasses.replace(
            cfg, trainer=dataclasses.replace(cfg.trainer, **t)
        )
    a = {}
    for flag in ("n_step", "actor_lr", "critic_lr", "target_policy_sigma"):
        v = getattr(args, flag)
        if v is not None:
            a[flag] = v
    if args.twin_critic is not None:
        a["twin_critic"] = bool(args.twin_critic)
    if a:
        cfg = dataclasses.replace(
            cfg, agent=dataclasses.replace(cfg.agent, **a)
        )
    if args.compute_dtype is not None:
        cfg = dataclasses.replace(cfg, compute_dtype=args.compute_dtype)
    return cfg


def run(args) -> dict:
    """Drive one experiment; returns the final metrics dict."""
    import jax

    from r2d2dpg_tpu.training.evaluator import Evaluator
    from r2d2dpg_tpu.utils import (
        CheckpointManager,
        MetricLogger,
        nan_debug,
        profile_trace,
    )
    from r2d2dpg_tpu.utils.checkpoint import resume_state

    if args.nan_debug:
        nan_debug(True)

    if args.pipeline and (args.resume or args.eval_every or args.profile_phases):
        # The pipelined executor owns the phase loop; the per-phase
        # subsystems of the phase-locked loop below don't compose with it
        # yet — refuse rather than silently skip (docs/PIPELINE.md).
        raise SystemExit(
            "--pipeline 1 does not support --resume/--eval-every/"
            "--profile-phases yet"
        )

    cfg = _apply_overrides(get_config(args.config), args)

    if args.spmd:
        from r2d2dpg_tpu.parallel import make_mesh

        trainer = cfg.build_spmd(make_mesh(args.spmd))
    else:
        trainer = cfg.build()

    # Stamp the resolved backend where automation can gate on it: a TPU
    # campaign step that silently fell back to CPU must not be mistaken
    # for an on-chip result (round-3 campaign gates .done markers on this).
    backend = jax.default_backend()
    print(f"backend: {backend}", flush=True)
    if args.logdir:
        import os

        os.makedirs(args.logdir, exist_ok=True)
        with open(os.path.join(args.logdir, "backend.txt"), "w") as f:
            f.write(backend + "\n")

    ckpt: Optional[CheckpointManager] = None
    if args.checkpoint_dir:
        ckpt = CheckpointManager(
            args.checkpoint_dir,
            save_every=args.checkpoint_every,
            light=args.checkpoint_light,
        )

    evaluator: Optional[Evaluator] = None
    if args.eval_every:
        evaluator = Evaluator(
            cfg.env_factory(), trainer.agent.actor, num_envs=args.eval_envs
        )

    logger = MetricLogger(args.logdir)
    deadline = (
        time.monotonic() + args.minutes * 60 if args.minutes is not None else None
    )

    if args.resume:
        if ckpt is None:
            raise SystemExit("--resume requires --checkpoint-dir")
        state = resume_state(trainer, ckpt)
        print(f"resumed from phase {int(state.phase_idx)}", flush=True)
    else:
        state = trainer.init()

    if args.pipeline:
        return _run_pipelined(trainer, state, logger, ckpt, args)

    warm = trainer.window_fill_phases
    fill = warm + trainer.replay_fill_phases
    eval_key = jax.random.PRNGKey(cfg.trainer.seed + 1)
    last_learn = {}
    final = {}
    phase = start = int(state.phase_idx)
    # --phases counts *train* phases for this invocation: a fresh run stops
    # after fill + N, a resumed one after N more from wherever it restarted.
    stop_at = (
        max(start, fill) + args.phases if args.phases is not None else None
    )
    profile_until = None
    profiler_cm = None

    try:
        while True:
            if stop_at is not None and phase >= stop_at:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            if stop_at is None and deadline is None and phase >= fill + 1:
                break  # nothing requested: run a single train phase (smoke)

            if phase < warm:
                state = trainer.collect_phase(state)
            elif phase < fill:
                state = trainer.fill_phase(state)
            else:
                if (
                    args.profile_phases
                    and args.logdir
                    and profile_until is None
                ):
                    profile_until = phase + args.profile_phases
                    profiler_cm = profile_trace(f"{args.logdir}/profile")
                    profiler_cm.__enter__()
                state, last_learn = trainer.train_phase(state)
                if profiler_cm is not None and phase + 1 >= profile_until:
                    jax.block_until_ready(state.train.step)
                    profiler_cm.__exit__(None, None, None)
                    profiler_cm = None
            phase += 1

            if args.log_every and phase % args.log_every == 0:
                state, ep = trainer.pop_episode_metrics(state)
                scalars = dict(ep)
                # ONE batched fetch for learn metrics + the step counter
                # (per-scalar float() casts were N+1 blocking host syncs).
                learn_np, lstep = jax.device_get(
                    (last_learn, state.train.step)
                )
                scalars.update(
                    {k: float(v) for k, v in learn_np.items()}
                )
                scalars.update(
                    logger.rates(
                        env_steps=ep["env_steps"],
                        learner_steps=float(lstep),
                    )
                )
                logger.log(phase, scalars)
                final = scalars

            if ckpt is not None and ckpt.save_every:
                ckpt.maybe_save(phase, state)

            if (
                evaluator is not None
                and phase > fill
                and (phase - fill) % args.eval_every == 0
            ):
                eval_key, k = jax.random.split(eval_key)
                ev = evaluator.run(state.train.actor_params, k)
                # Stamp the monotone env-step counter so eval-vs-steps
                # curves read directly off the CSV/TB row.
                ev["env_steps"] = float(state.env_steps)
                logger.log(phase, ev)
                final.update(ev)
    finally:
        if profiler_cm is not None:
            profiler_cm.__exit__(None, None, None)
        if ckpt is not None:
            if ckpt.save_every:
                ckpt.save_final(phase, state)
            ckpt.wait()
            ckpt.close()
        logger.close()
    return final


def _run_pipelined(trainer, state, logger, ckpt, args) -> dict:
    """Drive the run through the pipelined executor (--pipeline 1).

    The executor owns the warm-up -> fill -> train schedule and the log
    cadence; metrics land in the same MetricLogger (CSV/TB) rows as the
    phase-locked loop, and a final checkpoint is saved when a checkpoint
    dir is configured."""
    from r2d2dpg_tpu.training.pipeline import PipelineConfig, PipelineExecutor

    executor = PipelineExecutor(
        trainer,
        PipelineConfig(enabled=True, queue_depth=args.pipeline_depth),
    )
    if ckpt is not None and ckpt.save_every and ckpt.save_every > 0:
        # The state is split across two threads mid-run, so periodic saves
        # aren't composed with the executor yet — degrade LOUDLY to the
        # --checkpoint-every -1 (final-save-only) semantics.
        print(
            "pipeline: periodic checkpoints not supported with --pipeline 1; "
            "saving the final checkpoint only (--checkpoint-every -1 "
            "semantics)",
            flush=True,
        )
    fill = trainer.window_fill_phases + trainer.replay_fill_phases
    if args.phases is not None:
        num_phases = fill + args.phases
    elif args.minutes is not None:
        num_phases = 10**9  # the wall-clock budget is the stop condition
    else:
        num_phases = fill + 1  # nothing requested: single-train-phase smoke

    final: dict = {}

    def metrics_fn(phase: int, scalars) -> None:
        scalars = dict(scalars)
        scalars.update(
            logger.rates(
                env_steps=scalars.get("env_steps", 0.0),
                learner_steps=scalars.get("learner_steps", 0.0),
            )
        )
        logger.log(phase, scalars)
        final.clear()
        final.update(scalars)

    try:
        state = executor.run(
            num_phases,
            state=state,
            log_every=args.log_every,
            metrics_fn=metrics_fn,
            minutes=args.minutes,
        )
        stats = executor.stats()
        if stats:
            print(
                "pipeline: "
                + " ".join(f"{k} {v:.4g}" for k, v in sorted(stats.items())),
                flush=True,
            )
            final.update({f"pipeline_{k}": v for k, v in stats.items()})
        if ckpt is not None and ckpt.save_every:
            ckpt.save_final(int(state.phase_idx), state)
    finally:
        if ckpt is not None:
            ckpt.wait()
            ckpt.close()
        logger.close()
    return final


def main(argv=None):
    run(parse_args(argv))


if __name__ == "__main__":
    main()
