#!/usr/bin/env bash
# lint_obs.sh — operator output must flow through the telemetry layer.
#
# Fails on bare `print(` in r2d2dpg_tpu/ library code.  Library modules
# report through the obs registry / flight recorder / MetricLogger so that
# every operator-visible signal is scrapeable and post-mortem-able; a bare
# print is invisible to both.
#
# Exceptions:
#   - CLI entrypoints (train.py, serve.py, eval.py, __main__.py): their
#     job is stdout/stderr.
#   - Lines annotated `# obs-lint: allow` (e.g. MetricLogger's own stdout
#     sink, which IS the telemetry layer's print).
#
# Wired into the test run via tests/test_obs.py::test_lint_obs_clean.
set -euo pipefail
cd "$(dirname "$0")/.."

offenders=$(grep -rn 'print(' r2d2dpg_tpu \
    --include='*.py' \
    --exclude='train.py' \
    --exclude='serve.py' \
    --exclude='eval.py' \
    --exclude='__main__.py' \
    | grep -v '# obs-lint: allow' || true)

if [ -n "$offenders" ]; then
    echo "$offenders"
    echo "lint_obs: FAIL — bare print( in library code; route operator" \
         "output through the obs registry / flight recorder / MetricLogger" \
         "(or annotate deliberate sinks with '# obs-lint: allow')"
    exit 1
fi
echo "lint_obs: OK"
