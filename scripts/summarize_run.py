"""Summarize a training run's metrics.csv as the markdown tables RESULTS.md uses.

Usage: python scripts/summarize_run.py runs/tpu/walker30 [--every N]

Prints:
- a curve table (wall min, env steps, eval return) from the deterministic
  eval rows (falls back to noisy actor returns when no evals were logged);
- the run's final throughput (env/learner steps/sec) and totals.

Pure stdlib — safe to run next to a live training process (no JAX import).
"""

from __future__ import annotations

import argparse
import csv
import os
import sys


def load(logdir: str) -> list:
    path = os.path.join(logdir, "metrics.csv")
    with open(path, newline="") as f:
        return [r for r in csv.DictReader(f)]


def fget(row: dict, key: str):
    v = row.get(key, "")
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("logdir")
    ap.add_argument("--every", type=int, default=1, help="keep every Nth curve row")
    args = ap.parse_args()
    args.every = max(1, args.every)

    rows = load(args.logdir)
    if not rows:
        sys.exit(f"no rows in {args.logdir}/metrics.csv")

    # Eval rows from runs predating train.py's env_steps stamp carry only
    # eval_* scalars; fill env_steps forward from the most recent training
    # row, marking filled values "~N" so approximations are visible in the
    # table (ADVICE r2 #4).
    last_steps = 0.0
    filled = set()
    for i, r in enumerate(rows):
        v = fget(r, "env_steps")
        if v is not None:
            last_steps = v
        else:
            r["env_steps"] = last_steps
            filled.add(i)

    ret_key = "eval_return_mean"
    curve = [r for r in rows if fget(r, ret_key) is not None]
    if not curve:
        ret_key = "episode_return_mean"
        curve = [
            r
            for r in rows
            if fget(r, ret_key) is not None and (fget(r, "episodes") or 0) > 0
        ]
    label = (
        "eval return (deterministic)"
        if ret_key == "eval_return_mean"
        else "actor return (noisy)"
    )

    kept = curve[:: args.every]
    if curve and curve[-1] is not kept[-1]:
        kept.append(curve[-1])

    idx = {id(r): i for i, r in enumerate(rows)}
    print(f"### {args.logdir} — {len(rows)} log rows\n")
    print(f"| wall min | env steps | {label} |")
    print("|---|---|---|")
    for r in kept:
        mins = (fget(r, "wall_seconds") or 0) / 60
        steps = fget(r, "env_steps") or 0
        approx = "~" if idx[id(r)] in filled else ""
        print(f"| {mins:.0f} | {approx}{steps:,.0f} | {fget(r, ret_key):.1f} |")
    if any(idx[id(r)] in filled for r in kept):
        print(
            "\n(~N = env steps forward-filled from the last training row — "
            "pre-stamp run)"
        )

    if curve:
        # curve rows are pre-filtered to numeric returns — no None guard.
        best = max(curve, key=lambda r: fget(r, ret_key))
        print(
            f"\nbest: {fget(best, ret_key):.1f} at "
            f"{(fget(best, 'wall_seconds') or 0) / 60:.0f} min / "
            f"{fget(best, 'env_steps') or 0:,.0f} steps"
        )

    last = rows[-1]
    bits = []
    for k in ("env_steps_per_sec", "learner_steps_per_sec"):
        vals = [fget(r, k) for r in rows if fget(r, k) is not None]
        if vals:
            tail = vals[-5:]
            bits.append(f"{k} (last-5 mean) {sum(tail) / len(tail):,.1f}")
    total_min = (fget(last, "wall_seconds") or 0) / 60
    print(
        f"\nfinal: {total_min:.0f} min, {fget(last, 'env_steps') or 0:,.0f} env "
        f"steps, phase {last.get('step')}" + ("; " + "; ".join(bits) if bits else "")
    )


if __name__ == "__main__":
    main()
