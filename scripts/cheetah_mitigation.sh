#!/bin/bash
# Config-#5 overestimation-mitigation evidence run (VERDICT r2 next #6).
#
# Round-2 CPU baseline (runs/cheetah_pixels_r2: 8 envs, 4 updates/phase,
# batch 8): eval 3.9 -> 4.1 monotone to 73 min / 51k steps, then collapsed
# to 1.5 by 94 min / 67k steps — diagnosed as critic overestimation
# (docs/RESULTS.md).  This run changes the regime cost-neutrally so it can
# REACH the collapse region in budget on the 1-core host:
#   batch 16 x 2 updates/phase  (same 32 samples/phase as 8x4 — isolates
#                                batch size from sample throughput; VERDICT
#                                demands batch >= 16)
#   --actor-lr 5e-5             (halved actor pressure on the critic — the
#                                roadmap's named candidate knob)
# Twin critic is NOT used here (it costs ~2x critic compute the CPU budget
# cannot absorb); the on-chip campaign runs it via the
# runs/tpu/cheetah_extra_flags drop-in where the learner is free.
# Success bar: eval monotone (no collapse) past 67k env steps / ~100 min.
HERE="$(cd "$(dirname "$0")" && pwd)"
cd "$HERE/.."
mkdir -p runs
exec >> runs/cheetah_mitigation.log 2>&1

wait_for_box() {
  while pgrep -f "r2d2dpg_tpu\.(train|eval)" > /dev/null \
     || pgrep -f "walker_probe\.sh" > /dev/null \
     || pgrep -f "tpu_campaign[0-9]*\.sh" > /dev/null; do
    sleep 60
  done
}

# .done marker, not metrics.csv (which appears seconds into a run —
# ADVICE r2 #2), and up to 3 attempts: the TPU campaign's kill-list
# preempts the train python mid-run; when that happens, wait until the
# box frees up and restart the (wall-clock-budgeted) run cleanly.
DIR=runs/cheetah_mitigation
for attempt in 1 2 3; do
  if [ -f "$DIR/.done" ]; then
    echo "cheetah_mitigation: already done; exiting $(date)"
    exit 0
  fi
  wait_for_box
  echo "=== cheetah_mitigation attempt $attempt start $(date) ==="
  rm -rf "$DIR"
  mkdir -p "$DIR"
  nice -n 19 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu R2D2DPG_PALLAS_INTERPRET=1 \
  python -m r2d2dpg_tpu.train --config cheetah_pixels \
    --num-envs 8 --learner-steps 2 --batch-size 16 --min-replay 200 \
    --actor-lr 5e-5 \
    --seed 1 --minutes 115 --log-every 10 --eval-every 150 --eval-envs 3 \
    --logdir "$DIR" --checkpoint-dir "$DIR/ckpt" \
    --checkpoint-every 150 > "$DIR/stdout.log" 2>&1
  rc=$?
  echo "=== cheetah_mitigation attempt $attempt done rc=$rc $(date) ==="
  [ $rc -eq 0 ] && touch "$DIR/.done"
done
