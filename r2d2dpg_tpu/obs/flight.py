"""Flight recorder: a bounded ring of structured events for post-mortems.

Queue stalls, param publishes, hot-reloads, TTL evictions, shed codes,
checkpoint saves, watchdog trips — each subsystem drops a small structured
event into a process-wide ring (``flight_event(kind, **fields)``).  The
ring is bounded (old events fall off), recording is a deque append under a
lock (~µs, safe on hot-ish paths), and nothing is written to disk until a
**dump** — on normal exit (atexit), on a watchdog abort, or on demand.

Dumps are JSONL (one event per line, oldest first) written atomically
(tmp + rename) so a crash mid-dump never leaves a torn file.  Each event
carries::

    {"kind": ..., "t_wall": <unix seconds>, "t_mono": <monotonic seconds>,
     "seq": <monotone index>, "thread": <recording thread name>,
     "pid": <os pid>, ...identity, ...fields}

Identity stamping (fleet/multi-host post-mortems): every process in a
fleet writes its own ``flight.jsonl``, and interleaving them by ``t_wall``
is only useful if each line says WHO recorded it.  ``set_flight_identity``
stamps process-wide fields (``process_index`` for
``parallel.distributed.initialize()`` hosts, ``actor`` for fleet actor
subprocesses) onto every subsequent event; ``pid`` is always stamped.

**Span ring** (ISSUE 6): next to the event ring lives a second bounded
ring of experience-path *spans* — ``record_span(hop, trace_id, t_wall,
dur_s, ...)``, fed by ``obs/trace.py``'s sampled hop recorder.  Spans dump
as a Chrome-trace/Perfetto ``trace.json`` (``dump_trace``; armed next to
``flight.jsonl`` by ``install``), so "why does the learner wait 0.5 s"
loads straight into chrome://tracing.

**Fleet timeline merge** (CLI): each process of a fleet dumps its own
``flight*.jsonl``; ``python -m r2d2dpg_tpu.obs.flight merge <dir|file>...``
concatenates them sorted by ``t_wall`` into one attributable timeline
(the identity stamps say who recorded each line).  The trace dumper
reuses the same sort.  A run DIRECTORY auto-discovers every dump the
run left behind — the learner's ``flight.jsonl``, per-actor
``flight_actor<i>.jsonl``, per-shard-proc ``flight_shard<i>.jsonl``,
AND the span dumps (the learner's Chrome-format ``trace.json`` plus the
shard procs' ``trace_shard<i>.jsonl`` span rings) — and ``--trace-out``
folds every discovered span source into ONE Perfetto timeline spanning
learner + actors + shard procs (ISSUE 13), each span keeping a ``file``
source stamp on top of its identity fields.  Device-plane profiler
captures (``--profile-window``, obs/device.py) appear in the fused
timeline too: ``profile_start``/``profile_stop`` event pairs become
labelled ``profile_window`` spans (``profile_window_spans``), so the
capture's phase coverage is readable off the timeline itself.

Hard crashes (SIGSEGV & friends) cannot run Python: ``install()`` also
points ``faulthandler`` at a sidecar ``<path>.fault`` file so native
tracebacks land next to the last dumped ring.
"""

from __future__ import annotations

import atexit
import faulthandler
import glob
import json
import os
import sys
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple


def sort_by_twall(events: Iterable[Dict]) -> List[Dict]:
    """THE fleet-timeline ordering: stable sort on wall-clock seconds.

    Shared by the merge CLI (N processes' flight dumps -> one timeline)
    and the Chrome-trace dumper (spans -> ordered traceEvents)."""
    return sorted(events, key=lambda e: float(e.get("t_wall", 0.0)))


def chrome_trace(spans: Iterable[Dict]) -> Dict:
    """Spans -> a Chrome Trace Event Format document (Perfetto loads it).

    Each span becomes one complete event (``ph: "X"``): rows group by the
    recording pid, and ``tid`` is the trace id (one lane per sampled
    batch) so a batch's collect->learn hops read left to right."""
    events = []
    for s in sort_by_twall(spans):
        args = {
            k: v
            for k, v in s.items()
            if k not in ("hop", "t_wall", "dur_s", "pid", "trace_id")
        }
        args["trace_id"] = s.get("trace_id", 0)
        events.append(
            {
                "name": str(s.get("hop", "span")),
                "cat": "experience",
                "ph": "X",
                "ts": float(s.get("t_wall", 0.0)) * 1e6,
                "dur": max(float(s.get("dur_s", 0.0)), 0.0) * 1e6,
                "pid": int(s.get("pid", 0)),
                "tid": int(s.get("trace_id", 0)) & 0x7FFFFFFF,
                "args": args,
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


class FlightRecorder:
    """Bounded in-memory event + span rings + JSONL/trace.json dumps."""

    def __init__(self, capacity: int = 512, span_capacity: int = 2048):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._spans: deque = deque(maxlen=max(span_capacity, 1))
        self._seq = 0
        self._installed_path: Optional[str] = None
        self._trace_path: Optional[str] = None
        self._trace_format = "chrome"
        self._fault_file = None
        self._identity: Dict[str, object] = {}

    # -------------------------------------------------------------- identity
    def set_identity(self, **fields) -> None:
        """Stamp who-is-recording fields (``process_index``, ``actor``, ...)
        onto every subsequent event.  Merges: later calls add/overwrite keys
        without dropping earlier ones."""
        with self._lock:
            self._identity.update(fields)

    # ---------------------------------------------------------------- record
    def record(self, kind: str, **fields) -> None:
        event = {
            "kind": str(kind),
            "t_wall": time.time(),
            "t_mono": time.monotonic(),
            "thread": threading.current_thread().name,
            "pid": os.getpid(),
        }
        with self._lock:
            event.update(self._identity)
            event.update(fields)  # explicit fields win over identity
            event["seq"] = self._seq
            self._seq += 1
            self._ring.append(event)

    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    @property
    def recorded_total(self) -> int:
        """Events ever recorded (≥ len(events()) once the ring wrapped)."""
        with self._lock:
            return self._seq

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # ----------------------------------------------------------------- spans
    def record_span(
        self, hop: str, trace_id: int, t_wall: float, dur_s: float, **attrs
    ) -> None:
        """One experience-path hop of one sampled batch (obs/trace.py is
        the recording API; this is the storage).  A deque append under the
        lock — same cost class as ``record``."""
        span = {
            "hop": str(hop),
            "trace_id": int(trace_id),
            "t_wall": float(t_wall),
            "dur_s": float(dur_s),
            "pid": os.getpid(),
        }
        with self._lock:
            span.update(self._identity)
            span.update({k: v for k, v in attrs.items() if v is not None})
            self._spans.append(span)

    def spans(self) -> List[Dict]:
        with self._lock:
            return list(self._spans)

    def clear_spans(self) -> None:
        with self._lock:
            self._spans.clear()

    # ------------------------------------------------------------------ dump
    def dump(self, path: Optional[str] = None) -> Optional[str]:
        """Write the ring as JSONL (atomic tmp+rename).  Returns the path,
        or None when neither ``path`` nor an installed path exists."""
        path = path or self._installed_path
        if path is None:
            return None
        events = self.events()
        _atomic_write(
            path, "".join(json.dumps(e, default=str) + "\n" for e in events)
        )
        return path

    def dump_trace(self, path: Optional[str] = None) -> Optional[str]:
        """Write the span ring (atomic): Chrome-trace JSON by default, or
        raw span JSONL when the recorder was installed with
        ``trace_format="jsonl"`` (shard processes — the merge CLI folds
        those lines into the fleet-wide Perfetto timeline).  Returns the
        path, or None when no path is known OR no spans were recorded — an
        untraced run never litters an empty trace file."""
        path = path or self._trace_path
        spans = self.spans()
        if path is None or not spans:
            return None
        if self._trace_format == "jsonl":
            _atomic_write(
                path,
                "".join(json.dumps(s, default=str) + "\n" for s in spans),
            )
        else:
            _atomic_write(path, json.dumps(chrome_trace(spans), default=str))
        return path

    # --------------------------------------------------------------- install
    def install(
        self,
        path: str,
        *,
        trace_path: Optional[str] = None,
        trace_format: str = "chrome",
    ) -> None:
        """Arm exit-time capture: dump to ``path`` at interpreter exit,
        spans to ``trace_path`` (default: ``path``'s name with its
        ``flight`` prefix swapped for ``trace`` — ``flight.jsonl`` keeps
        the documented ``trace.json``, ``flight_actor0.jsonl`` gets its
        own ``trace_actor0.json``), and route hard-crash native
        tracebacks to ``<path>.fault``.

        ``trace_format="jsonl"`` dumps RAW span lines instead of a
        Chrome-trace document — the shard-process shape (ISSUE 13):
        per-proc ``trace_shard<i>.jsonl`` rings that the merge CLI folds
        into one fleet timeline (a per-proc Chrome doc would need parsing
        back apart to merge).

        Idempotent per path; re-installing with a new path re-targets the
        dump (one atexit hook either way).  Watchdog/abort paths call
        ``dump()``/``dump_trace()`` explicitly — atexit is the safety net,
        not the contract.
        """
        if trace_format not in ("chrome", "jsonl"):
            raise ValueError(f"unknown trace_format {trace_format!r}")
        if trace_path is None:
            # Default derives from the FLIGHT dump's name, so every
            # process in a run dir gets its own span dump: flight.jsonl
            # -> trace.json (the learner, the documented name), but
            # flight_actor0.jsonl -> trace_actor0.json — N actors all
            # defaulting to one shared trace.json would last-exiter-wins
            # clobber each other, and the merged --trace-out timeline
            # would silently hold one process's spans.
            base = os.path.basename(path)
            root = base[: -len(".jsonl")] if base.endswith(".jsonl") else (
                os.path.splitext(base)[0]
            )
            tname = (
                "trace" + root[len("flight"):]
                if root.startswith("flight")
                else f"trace_{root}"
            ) + ".json"
            trace_path = os.path.join(
                os.path.dirname(os.path.abspath(path)), tname
            )
        with self._lock:
            first = self._installed_path is None
            self._installed_path = path
            self._trace_path = trace_path
            self._trace_format = trace_format
        if first:
            atexit.register(self._atexit_dump)
        # faulthandler can't run Python on SIGSEGV; give it a sidecar file
        # so the native traceback survives next to the last dump.
        try:
            fault = open(f"{path}.fault", "w")
            faulthandler.enable(file=fault)
            old, self._fault_file = self._fault_file, fault
            if old is not None:
                old.close()
        except OSError:
            pass  # unwritable dir: the ring (and atexit dump) still work

    def _atexit_dump(self) -> None:
        try:
            self.dump()
            self.dump_trace()
        except OSError:
            pass  # exit-time best effort: never turn teardown into a crash


def _atomic_write(path: str, content: str) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(content)
    os.replace(tmp, path)


_RECORDER = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    """THE process-wide flight recorder (module singleton)."""
    return _RECORDER


def flight_event(kind: str, **fields) -> None:
    """Record one event into the process recorder (the library-side API)."""
    _RECORDER.record(kind, **fields)


def set_flight_identity(**fields) -> None:
    """Stamp identity fields (``process_index``, ``actor``, ...) onto every
    subsequent event of the process recorder, so fleet post-mortems can
    interleave multiple processes' ``flight.jsonl`` dumps by wall time and
    still attribute each line."""
    _RECORDER.set_identity(**fields)


# ----------------------------------------------------------------- merge CLI
def expand_flight_paths(paths: Iterable[str]) -> List[str]:
    """Resolve the merge CLI's arguments: files pass through, directories
    expand to their ``flight*.jsonl`` dumps — the learner's
    ``flight.jsonl``, per-actor ``flight_actorN.jsonl``, and per-shard-
    proc ``flight_shardN.jsonl`` all match one pattern, so a run DIR is
    a complete argument on its own (ISSUE 13 satellite: no more
    enumerating files by hand)."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "flight*.jsonl"))))
        else:
            out.append(p)
    return out


def expand_trace_paths(paths: Iterable[str]) -> List[str]:
    """The span-source half of run-dir discovery: directories expand to
    their ``trace*.jsonl`` span dumps (shard procs) AND ``trace*.json``
    Chrome documents (the learner's dump_trace artifact); explicit files
    pass through.  Both formats feed ``load_spans``."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(
                sorted(
                    glob.glob(os.path.join(p, "trace*.jsonl"))
                    + glob.glob(os.path.join(p, "trace*.json"))
                )
            )
        else:
            out.append(p)
    return out


# Top-level marker stamped into every --trace-out document (Perfetto
# ignores unknown keys): distinguishes a previous merge output — safe to
# exclude from span discovery and overwrite on a re-run — from a process's
# real span dump, which must never be silently clobbered.
_FUSED_KEY = "fusedBy"


def _is_trace_arg(path: str) -> bool:
    """Classify a non-directory merge argument by NAME: ``trace*.jsonl``
    and ``trace*.json`` are span dumps for the ``--trace-out`` fuse, never
    event-timeline sources — a span line parses as a valid JSON dict (it
    even carries ``t_wall``), so feeding one to ``merge_flight_files``
    would silently interleave bogus no-``kind`` events into the fleet
    timeline instead of failing."""
    name = os.path.basename(path)
    return name.startswith("trace") and (
        name.endswith(".jsonl") or name.endswith(".json")
    )


def _span_from_chrome_event(e: Dict) -> Optional[Dict]:
    """Invert ``chrome_trace``'s event shape back into a raw span so an
    already-rendered learner ``trace.json`` merges with the shard procs'
    raw ``trace_shard*.jsonl`` rings on equal footing."""
    if not isinstance(e, dict) or e.get("ph") != "X":
        return None
    args = e.get("args") if isinstance(e.get("args"), dict) else {}
    try:
        span = {
            "hop": str(e.get("name", "span")),
            "trace_id": int(args.get("trace_id", e.get("tid", 0))),
            "t_wall": float(e.get("ts", 0.0)) / 1e6,
            "dur_s": float(e.get("dur", 0.0)) / 1e6,
            "pid": int(e.get("pid", 0)),
        }
    except (TypeError, ValueError):
        # A non-numeric ts/dur/tid (truncated, foreign, or version-skewed
        # dump) is one bad EVENT: None -> the caller's skipped tally,
        # like any other unparseable line — never a merge-wide traceback.
        return None
    span.update({k: v for k, v in args.items() if k != "trace_id"})
    return span


def load_spans(paths: Iterable[str]) -> Tuple[List[Dict], int]:
    """N span dumps (raw ``.jsonl`` lines and/or Chrome ``.json``
    documents) -> one span list + the count of unparseable lines/events.
    Every span gets a ``file`` source stamp (preserved over a merge, like
    the event timeline's), so the fused Perfetto view still says which
    process recorded each hop."""
    spans: List[Dict] = []
    skipped = 0
    for path in paths:
        name = os.path.basename(path)
        try:
            with open(path) as f:
                content = f.read()
        except OSError:
            skipped += 1
            continue
        if path.endswith(".jsonl"):
            for line in content.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    s = json.loads(line)
                except ValueError:
                    skipped += 1
                    continue
                if isinstance(s, dict) and "hop" in s:
                    s.setdefault("file", name)
                    spans.append(s)
                else:
                    skipped += 1
        else:
            try:
                doc = json.loads(content)
            except ValueError:
                skipped += 1
                continue
            if isinstance(doc, dict) and _FUSED_KEY in doc:
                # A previous merge output (any name): derived data, never
                # a source — re-ingesting it would duplicate every span
                # it fused, N+1 copies after N re-runs into one run dir.
                continue
            events = (
                doc.get("traceEvents", ()) if isinstance(doc, dict) else ()
            )
            for e in events:
                s = _span_from_chrome_event(e)
                if s is None:
                    skipped += 1
                    continue
                s.setdefault("file", name)
                spans.append(s)
    return sort_by_twall(spans), skipped


def profile_window_spans(events: Iterable[Dict]) -> List[Dict]:
    """Pair ``profile_start``/``profile_stop`` flight events (the device
    plane's ``--profile-window`` capture brackets, obs/device.py) into
    labelled ``profile_window`` spans for the fused Perfetto timeline —
    the capture window is visible IN the timeline it profiles, so "which
    phases does this trace cover" is answered by the evidence itself.

    Pairing is per (file, pid): each process's own start matches its own
    stop; an unmatched start (the run died mid-capture) still yields a
    zero-duration marker span so the attempt is never invisible."""
    spans: List[Dict] = []
    open_starts: Dict[Tuple, Dict] = {}
    for e in sort_by_twall(events):
        if not isinstance(e, dict):
            continue
        key = (e.get("file"), e.get("pid"))
        if e.get("kind") == "profile_start":
            open_starts[key] = e
        elif e.get("kind") == "profile_stop":
            s = open_starts.pop(key, None)
            if s is None:
                continue
            spans.append(
                {
                    "hop": "profile_window",
                    "trace_id": 0,
                    "t_wall": float(s.get("t_wall", 0.0)),
                    "dur_s": max(
                        float(e.get("t_wall", 0.0))
                        - float(s.get("t_wall", 0.0)),
                        0.0,
                    ),
                    "pid": int(e.get("pid", 0) or 0),
                    "file": e.get("file"),
                    "phase": s.get("phase"),
                    "logdir": s.get("logdir"),
                }
            )
    for key, s in open_starts.items():
        spans.append(
            {
                "hop": "profile_window",
                "trace_id": 0,
                "t_wall": float(s.get("t_wall", 0.0)),
                "dur_s": 0.0,
                "pid": int(s.get("pid", 0) or 0),
                "file": s.get("file"),
                "phase": s.get("phase"),
                "unterminated": True,
            }
        )
    return spans


def merge_flight_files(paths: Iterable[str]) -> Tuple[List[Dict], int]:
    """N processes' flight dumps -> one ``t_wall``-ordered fleet timeline,
    plus the count of lines that could not be parsed.

    Each event is stamped with its source file (``file``) on top of the
    identity fields it already carries; unparseable lines are skipped and
    COUNTED rather than aborting a post-mortem over one torn line — the
    CLI reports the count so a truncated timeline is never mistaken for a
    complete one."""
    events: List[Dict] = []
    skipped = 0
    for path in paths:
        name = os.path.basename(path)
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except ValueError:
                    skipped += 1
                    continue
                if isinstance(e, dict):
                    e.setdefault("file", name)
                    events.append(e)
                else:
                    skipped += 1
    return sort_by_twall(events), skipped


def main(argv=None) -> None:
    """``python -m r2d2dpg_tpu.obs.flight merge <dir|file>... [-o OUT]``"""
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m r2d2dpg_tpu.obs.flight",
        description="flight-recorder tooling (docs/OBSERVABILITY.md)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    m = sub.add_parser(
        "merge",
        help="interleave N processes' flight*.jsonl dumps by t_wall into "
        "one attributable fleet timeline",
    )
    m.add_argument(
        "paths", nargs="+",
        help="flight .jsonl files, trace*.jsonl/trace*.json span dumps "
        "(--trace-out sources), and/or run dirs (dirs expand to both "
        "kinds)",
    )
    m.add_argument(
        "-o", "--out", default=None,
        help="write the merged JSONL here (default: stdout)",
    )
    m.add_argument(
        "--trace-out", default=None, metavar="TRACE_JSON",
        help="also fuse every discovered span dump (the learner's "
        "trace.json + the shard procs' trace_shard*.jsonl rings) into "
        "ONE Perfetto/chrome://tracing timeline at this path",
    )
    args = p.parse_args(argv)
    # Explicit trace* file args are span sources, never timeline sources
    # (see _is_trace_arg); naming one without --trace-out is a request
    # the event merge cannot honor — refuse instead of ignoring it.
    span_args = [
        q for q in args.paths if not os.path.isdir(q) and _is_trace_arg(q)
    ]
    if span_args and not args.trace_out:
        raise SystemExit(
            "flight merge: trace dump args "
            f"({', '.join(os.path.basename(q) for q in span_args)}) are "
            "span sources — pass --trace-out to fuse them"
        )
    paths = expand_flight_paths(
        [q for q in args.paths if q not in span_args]
    )
    if not paths and not args.trace_out:
        raise SystemExit("flight merge: no flight*.jsonl files found")
    # ONE event merge feeds both consumers — the -o/stdout timeline AND
    # the --trace-out profile-window pairing below (re-reading megabytes
    # of flight lines per consumer would be pure waste); it is skipped
    # entirely only when nothing consumes events (no paths, or a
    # --trace-out-without--o run on a dir with no flight dumps).
    merged: List[Dict] = []
    skipped = 0
    if paths:
        merged, skipped = merge_flight_files(paths)
    if paths and (args.out or args.trace_out is None):
        body = "".join(json.dumps(e, default=str) + "\n" for e in merged)
        skip_note = (
            f" ({skipped} unparseable lines skipped)" if skipped else ""
        )
        if args.out:
            _atomic_write(args.out, body)
            sys.stderr.write(
                f"flight merge: {len(merged)} events from {len(paths)} files"
                f"{skip_note} -> {args.out}\n"
            )
        else:
            sys.stdout.write(body)
            if skip_note:
                sys.stderr.write(f"flight merge:{skip_note}\n")
    if args.trace_out:
        # Span sources: every directory arg's trace*.jsonl / trace*.json
        # dumps plus the explicitly-named ones — minus the --trace-out
        # target itself (writing the fused doc INTO a scanned run dir is
        # natural, and a re-run would otherwise re-ingest the previous
        # output and duplicate every span).  The exclusion is only safe
        # when the target IS a previous fused output (the _FUSED_KEY
        # marker below): an existing trace* file WITHOUT the marker is a
        # real span dump (e.g. the learner's trace.json), and
        # exclude+overwrite would drop its spans from the fusion AND
        # destroy them on disk — refuse instead.
        out_abs = os.path.abspath(args.trace_out)
        if os.path.isfile(out_abs) and _is_trace_arg(out_abs):
            try:
                with open(out_abs) as f:
                    prev = json.load(f)
                prev_fused = isinstance(prev, dict) and _FUSED_KEY in prev
            except (OSError, ValueError):
                prev_fused = False
            if not prev_fused:
                raise SystemExit(
                    f"flight merge: --trace-out {args.trace_out} would "
                    "overwrite an existing span dump (not a previous "
                    "merge output) — pick a different output name"
                )
        trace_paths = []
        seen_abs = {out_abs}
        for q in (
            expand_trace_paths([q for q in args.paths if os.path.isdir(q)])
            + span_args
        ):
            # abspath-dedup: a dump named BOTH explicitly and via its
            # containing run-dir arg must feed the fusion once, not
            # twice (duplicate X events per Perfetto lane).
            q_abs = os.path.abspath(q)
            if q_abs in seen_abs:
                continue
            seen_abs.add(q_abs)
            trace_paths.append(q)
        spans, tskipped = load_spans(trace_paths)
        if merged:
            # Profiler capture windows (obs/device.py --profile-window):
            # the start/stop flight events become labelled profile_window
            # spans, so the fused timeline shows WHICH phases the
            # profiler dump under <logdir>/profile_window covers.
            spans = sort_by_twall(spans + profile_window_spans(merged))
        if not spans:
            raise SystemExit(
                "flight merge: --trace-out found no spans (no "
                "trace*.jsonl / trace*.json among the given dirs/files — "
                "was the run traced? --trace-sample 0 records nothing)"
            )
        doc = chrome_trace(spans)
        doc[_FUSED_KEY] = "python -m r2d2dpg_tpu.obs.flight merge"
        _atomic_write(args.trace_out, json.dumps(doc, default=str))
        tnote = f" ({tskipped} unparseable skipped)" if tskipped else ""
        sys.stderr.write(
            f"flight merge: {len(spans)} spans from {len(trace_paths)} "
            f"trace dumps{tnote} -> {args.trace_out}\n"
        )


if __name__ == "__main__":
    main()
