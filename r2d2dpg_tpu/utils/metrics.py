"""Metrics / logging / observability (SURVEY.md §5.5).

Reference parity: the reference logs episode returns to stdout and possibly
TensorBoard scalars (SURVEY §2.7/§5.5).  The build logs:

- TensorBoard scalars (via ``tensorboardX``) when a logdir is given;
- a CSV fallback, always (one row per log call, stable header);
- the BASELINE metric **return @ wall-clock minutes** (every scalar is
  stamped with both ``step`` and seconds-since-start, so return@30min is a
  direct read-off of the CSV/TB curve);
- **SPS** — env steps/sec and learner steps/sec — computed from deltas.
"""

from __future__ import annotations

import collections
import csv
import math
import os
import threading
import time
from typing import Dict, Iterable, Optional, Tuple


class PercentileWindow:
    """Sliding window of scalar observations with percentile read-off.

    Serving health (queue wait, policy-step latency) needs p50/p99 over the
    *recent* past, not the whole process lifetime — a bounded deque of the
    last ``size`` observations is that window.  ``add`` is O(1);
    ``percentiles`` sorts the window (a few thousand floats) only when a
    snapshot is actually taken.  Thread-safe: producers (the serving worker)
    and consumers (health scrapes from request threads) run concurrently.
    """

    def __init__(self, size: int = 2048):
        if size < 1:
            raise ValueError("size must be >= 1")
        self._buf: collections.deque = collections.deque(maxlen=size)
        self._lock = threading.Lock()
        self._count = 0
        self._total = 0.0

    def add(self, value: float) -> None:
        with self._lock:
            self._buf.append(float(value))
            self._count += 1
            self._total += float(value)

    @property
    def count(self) -> int:
        """Total observations ever added (not just those still windowed)."""
        return self._count

    @property
    def total(self) -> float:
        """Running sum of ALL observations ever added (not windowed).

        The pipelined executor derives its overlap fraction from total
        stage-wait seconds over wall-clock; the window alone would forget
        waits older than ``size`` observations."""
        return self._total

    @staticmethod
    def _nearest_rank(data, qs) -> Tuple[float, ...]:
        if not data:
            return tuple(0.0 for _ in qs)
        out = []
        for q in qs:
            # Nearest-rank: ceil(q/100 * n) - 1, clamped to the window.
            rank = math.ceil(q / 100.0 * len(data)) - 1
            out.append(data[max(0, min(len(data) - 1, rank))])
        return tuple(out)

    def percentiles(self, qs: Iterable[float] = (50.0, 99.0)) -> Tuple[float, ...]:
        """Nearest-rank percentiles over the current window (0.0 if empty)."""
        with self._lock:
            data = sorted(self._buf)
        return self._nearest_rank(data, qs)

    def snapshot(self) -> Tuple[int, float, float, float]:
        """One consistent ``(count, total, p50, p99)`` read under ONE lock.

        Stats consumers (the pipelined executor's ``stats()``, the obs
        registry's histogram export) previously took three separate locked
        reads — count, total, percentiles — between which a producer could
        slip observations in, so the triple was mutually inconsistent."""
        with self._lock:
            count, total = self._count, self._total
            data = sorted(self._buf)
        p50, p99 = self._nearest_rank(data, (50.0, 99.0))
        return count, total, p50, p99

    def reset(self) -> None:
        """Drop the window AND the lifetime count/total (measurement-section
        boundaries, e.g. the pipelined executor's per-section stats)."""
        with self._lock:
            self._buf.clear()
            self._count = 0
            self._total = 0.0


class MetricLogger:
    """Scalar logger: stdout + CSV (always) + TensorBoard (if logdir given).

    ``log(step, scalars)`` stamps every row with wall-clock seconds since
    construction; ``rates(env_steps, learner_steps)`` folds steps/sec deltas
    into the next ``log`` call.

    Thread-safe: the pipelined executor's learner thread and the serving
    worker's health logger both call ``log`` concurrently with whoever owns
    the logger, so every method that touches the CSV/TB state serializes on
    one lock.

    ``registry`` (an ``obs.Registry``), when given, folds the registry's
    flat scalar snapshot into every row — extra columns only, so the
    existing return@wall-clock curves read off the CSV/TB unchanged.
    """

    def __init__(
        self,
        logdir: Optional[str] = None,
        *,
        csv_name: str = "metrics.csv",
        stdout: bool = True,
        tensorboard: bool = True,
        registry=None,
    ):
        self.logdir = logdir
        self.stdout = stdout
        self.t0 = time.monotonic()
        self._registry = registry
        self._lock = threading.RLock()
        self._csv_path: Optional[str] = None
        self._csv_file = None
        self._csv_writer = None
        self._csv_fields: Optional[list] = None
        self._tb = None
        self._last_rate_t: Optional[float] = None
        self._last_counts: Dict[str, float] = {}
        if logdir is not None:
            os.makedirs(logdir, exist_ok=True)
            self._csv_path = os.path.join(logdir, csv_name)
            if os.path.exists(self._csv_path):
                # Resume into an existing logdir: keep the old rows and
                # continue the wall-clock from where the previous run left
                # off, so the return@wall-clock curve survives a restart.
                with open(self._csv_path, newline="") as f:
                    old = list(csv.DictReader(f))
                if old:
                    self._csv_fields = list(old[0].keys())
                    try:
                        self.t0 -= max(
                            float(r["wall_seconds"]) for r in old
                            if r.get("wall_seconds")
                        )
                    except ValueError:
                        pass
            if tensorboard:
                try:
                    from tensorboardX import SummaryWriter

                    self._tb = SummaryWriter(logdir)
                except Exception:  # pragma: no cover - tbx is installed here
                    self._tb = None

    # ------------------------------------------------------------------ rates
    def rates(self, **counts: float) -> Dict[str, float]:
        """Steps/sec for monotone counters since the previous ``rates`` call.

        ``rates(env_steps=..., learner_steps=...)`` returns e.g.
        ``{"env_steps_per_sec": ..., "learner_steps_per_sec": ...}``.
        """
        with self._lock:
            now = time.monotonic()
            out: Dict[str, float] = {}
            if self._last_rate_t is not None:
                dt = max(now - self._last_rate_t, 1e-9)
                for k, v in counts.items():
                    prev = self._last_counts.get(k)
                    if prev is not None:
                        out[f"{k}_per_sec"] = (v - prev) / dt
            self._last_rate_t = now
            self._last_counts = dict(counts)
            return out

    # -------------------------------------------------------------------- log
    def log(self, step: int, scalars: Dict[str, float]) -> None:
        elapsed = time.monotonic() - self.t0
        row = {"step": step, "wall_seconds": round(elapsed, 3)}
        row.update({k: float(v) for k, v in scalars.items()})
        if self._registry is not None:
            # Bridge: registry snapshot folds in as EXTRA columns; explicit
            # scalars win a name collision (the curves stay canonical).
            for k, v in self._registry.scalars().items():
                row.setdefault(k, v)

        with self._lock:
            if self.stdout:
                body = " ".join(
                    f"{k} {v:.4g}" for k, v in row.items() if k != "step"
                )
                print(f"[{step}] {body}", flush=True)  # obs-lint: allow

            if self._csv_path is not None:
                if self._csv_writer is None or any(
                    k not in self._csv_fields for k in row
                ):
                    self._reopen_csv(row)
                self._csv_writer.writerow(
                    {k: row.get(k, "") for k in self._csv_fields}
                )
                self._csv_file.flush()

            if self._tb is not None:
                for k, v in row.items():
                    if k == "step":
                        continue
                    self._tb.add_scalar(k, v, global_step=step, walltime=None)

    def _reopen_csv(self, row: Dict[str, float]) -> None:
        """(Re)open the CSV; rewrite existing rows ONLY on a header change.

        Appending under an unchanged header is the common case (resume into
        an existing logdir, or a plain first open); the full
        read-all/rewrite-all pass — O(rows) per occurrence — happens only
        when a genuinely new column appears, not on every (re)open, so a
        long run no longer pays O(rows²) across its lifetime."""
        if self._csv_file is not None:
            self._csv_file.close()
            self._csv_file = self._csv_writer = None
        fields = list(
            dict.fromkeys(
                ["step", "wall_seconds"]
                + (self._csv_fields or [])
                + list(row)
            )
        )
        exists = os.path.exists(self._csv_path)
        if exists and self._csv_fields == fields:
            # Header already covers the row (e.g. resume): append, no rewrite.
            self._csv_file = open(self._csv_path, "a", newline="")
            self._csv_writer = csv.DictWriter(
                self._csv_file, fieldnames=fields
            )
            return
        old_rows = []
        if exists:
            with open(self._csv_path, newline="") as f:
                old_rows = list(csv.DictReader(f))
        self._csv_file = open(self._csv_path, "w", newline="")
        self._csv_writer = csv.DictWriter(self._csv_file, fieldnames=fields)
        self._csv_writer.writeheader()
        for r in old_rows:
            self._csv_writer.writerow({k: r.get(k, "") for k in fields})
        self._csv_fields = fields

    # ------------------------------------------------------------------ close
    def close(self) -> None:
        with self._lock:
            if self._csv_file is not None:
                self._csv_file.close()
                self._csv_file = self._csv_writer = None
            if self._tb is not None:
                self._tb.close()
                self._tb = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
