"""r2d2dpg_tpu — a TPU-native (JAX/XLA/Pallas/pjit) R2D2-DPG framework.

A from-scratch rebuild of the capabilities of ``jinbeizame007/pytorch-r2d2-DPG``
(see SURVEY.md for the structural analysis and its provenance note: the
reference mount was empty at survey time, so component parity is tracked
against SURVEY.md §2 and BASELINE.json's five capability configs rather than
reference ``file:line`` citations).

Architecture (SURVEY.md §7, "design inversion"): the reference's process
topology — N CPU actor processes feeding a CUDA learner over
``multiprocessing.Queue`` — dissolves into a single-controller JAX program in
the Podracer/Anakin style (PAPERS.md, arxiv 2104.06272):

- ``envs``      — pure-JAX environments (on-device) and a host-callback pool
                  for MuJoCo-backed DM-Control tasks.
- ``models``    — flax actor/critic networks: MLP, LSTM (carried-state), CNN.
- ``ops``       — pure update math: n-step targets, eta-mix priorities,
                  IS weights, Polyak, exploration-noise ladder; Pallas kernels.
- ``replay``    — HBM-resident prioritized sequence replay arena.
- ``agents``    — the DDPG/R2D2 learner step as one jittable function.
- ``training``  — actor phase (vmapped env stepping + sequence assembly) and
                  the outer Anakin loop.
- ``parallel``  — device mesh + shard_map SPMD: env batch and replay sharded
                  over the ``dp`` axis, gradient psum over ICI.
- ``utils``     — configs, checkpointing (orbax), metrics/logging.
"""

__version__ = "0.1.0"
