"""Fleet wire protocol (fleet/transport.py).

The ISSUE 4 satellite coverage: truncated frame, CRC mismatch, oversized
payload, and the actor-side param-version regression guard (a delayed
PARAMS frame must never roll the policy backwards).  ISSUE 5 adds the
malformed wire-codec frame (a CRC-valid frame whose PAYLOAD violates
fleet/wire.py), multi-part sends, and the no-pickle lint gate.
"""

import os
import socket
import struct
import subprocess

import numpy as np
import pytest

from r2d2dpg_tpu.fleet import transport
from r2d2dpg_tpu.fleet.transport import (
    HEADER_BYTES,
    K_SEQS,
    FrameBadMagic,
    FrameCRCError,
    FrameTooLarge,
    FrameTruncated,
    encode_frame,
    pack_obj,
    parse_address,
    recv_frame,
    send_frame,
    send_frame_parts,
    unpack_obj,
)
from r2d2dpg_tpu.replay.arena import SequenceBatch, StagedSequences

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.fleet


def _pair():
    a, b = socket.socketpair()
    a.settimeout(5)
    b.settimeout(5)
    return a, b


def _staged(b=2, l=3, obs=4, act=2):
    rng = np.random.default_rng(0)
    return StagedSequences(
        seq=SequenceBatch(
            obs=rng.normal(size=(b, l, obs)).astype(np.float32),
            action=rng.normal(size=(b, l, act)).astype(np.float32),
            reward=rng.normal(size=(b, l)).astype(np.float32),
            discount=np.ones((b, l), np.float32),
            reset=np.zeros((b, l), np.float32),
            carries={},
        ),
        priorities=np.arange(1.0, b + 1.0, dtype=np.float32),
    )


def test_frame_round_trip_with_pytree_payload():
    a, b = _pair()
    staged = _staged()
    send_frame(a, K_SEQS, pack_obj({"staged": staged, "phase": 7}))
    kind, payload = recv_frame(b)
    assert kind == K_SEQS
    msg = unpack_obj(payload)
    assert msg["phase"] == 7
    got = msg["staged"]
    np.testing.assert_array_equal(got.seq.obs, staged.seq.obs)
    np.testing.assert_array_equal(got.priorities, staged.priorities)
    a.close(), b.close()


def test_truncated_frame_raises():
    a, b = _pair()
    frame = encode_frame(K_SEQS, b"x" * 64)
    a.sendall(frame[: HEADER_BYTES + 10])  # header + partial payload
    a.close()
    with pytest.raises(FrameTruncated):
        recv_frame(b)
    b.close()


def test_truncated_header_raises():
    a, b = _pair()
    a.sendall(encode_frame(K_SEQS, b"")[: HEADER_BYTES - 3])
    a.close()
    with pytest.raises(FrameTruncated):
        recv_frame(b)
    b.close()


def test_crc_mismatch_raises():
    a, b = _pair()
    frame = bytearray(encode_frame(K_SEQS, b"hello world"))
    frame[-1] ^= 0xFF  # flip a payload bit AFTER the crc was computed
    a.sendall(bytes(frame))
    with pytest.raises(FrameCRCError):
        recv_frame(b)
    a.close(), b.close()


def test_oversized_payload_refused_both_sides():
    # Sender refuses before any bytes hit the wire...
    a, b = _pair()
    with pytest.raises(FrameTooLarge):
        send_frame(a, K_SEQS, b"x" * 100, max_frame_bytes=64)
    # ...and the receiver refuses on the DECLARED length, before allocating
    # or reading the payload (a corrupt header cannot OOM the learner).
    a.sendall(encode_frame(K_SEQS, b"x" * 100))
    with pytest.raises(FrameTooLarge):
        recv_frame(b, max_frame_bytes=64)
    a.close(), b.close()


def test_send_frame_parts_equivalent_to_joined_send():
    """Multi-part zero-copy send: same bytes on the wire as a joined
    send_frame, byte counts returned, ceiling enforced on the total."""
    from r2d2dpg_tpu.fleet import wire

    a, b = _pair()
    staged = _staged()
    parts = wire.TreePacker(wire.WireConfig()).pack({"staged": staged})
    n = send_frame_parts(a, K_SEQS, parts)
    kind, payload = recv_frame(b)
    assert kind == K_SEQS
    assert n == HEADER_BYTES + len(payload)
    assert payload == b"".join(bytes(p) for p in parts)
    got = wire.TreeUnpacker().unpack(payload)["staged"]
    np.testing.assert_array_equal(got.seq.obs, staged.seq.obs)
    with pytest.raises(FrameTooLarge):
        send_frame_parts(a, K_SEQS, [b"x" * 40, b"y" * 40], max_frame_bytes=64)
    a.close(), b.close()


def test_malformed_wire_payload_kills_decode_not_process():
    """A frame that passes transport framing (length + CRC fine) but whose
    PAYLOAD violates the wire codec must surface as a FrameError subclass
    — the connection dies, the learner does not (ISSUE 5 satellite,
    alongside the truncated/CRC/oversize cases above)."""
    from r2d2dpg_tpu.fleet import wire

    a, b = _pair()
    # Valid transport frame, garbage wire payload (here the junk header
    # declares an absurd decompressed length -> the zip-bomb ceiling).
    send_frame(a, K_SEQS, b"\x01\x00" * 10)
    kind, payload = recv_frame(b)  # transport accepts it...
    with pytest.raises(FrameTooLarge):  # ...the codec refuses it
        wire.TreeUnpacker().unpack(payload)
    # Garbage that passes the header parse dies on the schema reference.
    send_frame(a, K_SEQS, b"\x01" + b"\x00" * 15)
    _, payload = recv_frame(b)
    with pytest.raises(wire.WireFormatError):
        wire.TreeUnpacker().unpack(payload)
    # And WireFormatError IS a FrameError: handler loops that kill the
    # connection on FrameError cover codec violations for free.
    assert issubclass(wire.WireFormatError, transport.FrameError)
    a.close(), b.close()


# ------------------------------------------------------------------ lint gate
def test_lint_fleet_wire_clean():
    """scripts/lint_fleet_wire.sh: no pickle on fleet SEQS/PARAMS paths
    (annotated control-frame call sites excepted)."""
    res = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "lint_fleet_wire.sh")],
        capture_output=True,
        text=True,
    )
    assert res.returncode == 0, res.stdout + res.stderr


def test_lint_fleet_wire_catches_offenders(tmp_path):
    """The gate bites: pickle usage outside transport.py fails, as does an
    un-annotated pack_obj call."""
    import shutil

    tree = tmp_path / "repo"
    (tree / "scripts").mkdir(parents=True)
    shutil.copy(
        os.path.join(REPO, "scripts", "lint_fleet_wire.sh"), tree / "scripts"
    )
    pkg = tree / "r2d2dpg_tpu" / "fleet"
    pkg.mkdir(parents=True)
    (pkg / "offender.py").write_text(
        "import pickle\npayload = pickle.dumps({'staged': None})\n"
    )
    res = subprocess.run(
        ["bash", str(tree / "scripts" / "lint_fleet_wire.sh")],
        capture_output=True,
        text=True,
    )
    assert res.returncode == 1 and "offender.py" in res.stdout

    (pkg / "offender.py").write_text("x = pack_obj({'seqs': 1})\n")
    res = subprocess.run(
        ["bash", str(tree / "scripts" / "lint_fleet_wire.sh")],
        capture_output=True,
        text=True,
    )
    assert res.returncode == 1 and "offender.py" in res.stdout

    # Annotated control-frame call sites pass.
    (pkg / "offender.py").write_text(
        "x = pack_obj({'code': 'ok'})  # wire-lint: control\n"
    )
    res = subprocess.run(
        ["bash", str(tree / "scripts" / "lint_fleet_wire.sh")],
        capture_output=True,
        text=True,
    )
    assert res.returncode == 0, res.stdout


def test_bad_magic_raises():
    a, b = _pair()
    header = struct.Struct("!4sBQI").pack(b"NOPE", K_SEQS, 0, 0)
    a.sendall(header)
    with pytest.raises(FrameBadMagic):
        recv_frame(b)
    a.close(), b.close()


def test_parse_address():
    import socket as s

    assert parse_address("127.0.0.1:7450") == (s.AF_INET, ("127.0.0.1", 7450))
    assert parse_address("unix:/tmp/x.sock") == (s.AF_UNIX, "/tmp/x.sock")
    with pytest.raises(ValueError, match="neither"):
        parse_address("nonsense")


def test_encode_frame_oversized_refused():
    with pytest.raises(FrameTooLarge):
        encode_frame(K_SEQS, b"x" * (transport.MAX_FRAME_BYTES + 1))


def test_param_version_regression_ignored():
    """The actor applies monotonically increasing versions ONLY: a stale or
    replayed PARAMS frame (reconnect races, delayed pushes) leaves the nets
    at the newer snapshot."""
    import jax

    from r2d2dpg_tpu.configs import PENDULUM_TINY
    from r2d2dpg_tpu.fleet.actor import FleetActor

    actor = FleetActor(
        PENDULUM_TINY,
        actor_id=0,
        num_actors=2,
        address="127.0.0.1:1",  # never dialed: run() is not called
        seed=0,
    )

    def snap(version):
        scaled = jax.tree_util.tree_map(
            lambda x: np.asarray(x) * (1.0 + version),
            jax.device_get(actor._train.actor_params),
        )
        return {
            "version": version,
            "params": {
                "actor_params": scaled,
                "critic_params": jax.device_get(actor._train.critic_params),
                "target_actor_params": jax.device_get(
                    actor._train.target_actor_params
                ),
                "target_critic_params": jax.device_get(
                    actor._train.target_critic_params
                ),
            },
        }

    v2 = snap(2)
    assert actor.maybe_apply_params(v2) is True
    assert actor._param_version == 2
    after_v2 = jax.tree_util.tree_leaves(actor._train.actor_params)[0]

    # Stale (1 < 2), replayed (2 == 2): both ignored, nets untouched.
    assert actor.maybe_apply_params(snap(1)) is False
    assert actor.maybe_apply_params(v2) is False
    assert actor._param_version == 2
    np.testing.assert_array_equal(
        jax.tree_util.tree_leaves(actor._train.actor_params)[0], after_v2
    )

    # Fresh version still applies.
    assert actor.maybe_apply_params(snap(3)) is True
    assert actor._param_version == 3
