"""Replay arena: ring overwrite, prioritized sampling distribution, priority
write-back via the Pallas kernel (interpret mode) — SURVEY.md §4.1/§4.5."""

import os

os.environ["R2D2DPG_PALLAS_INTERPRET"] = "1"  # exercise the kernel on CPU

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2dpg_tpu.replay import ReplayArena, SequenceBatch

L, OBS, ACT, HID = 4, 3, 2, 8


def make_batch(b, value=0.0):
    zeros = jnp.zeros((b, HID))
    return SequenceBatch(
        obs=jnp.full((b, L, OBS), value),
        action=jnp.zeros((b, L, ACT)),
        reward=jnp.arange(b, dtype=jnp.float32)[:, None] * jnp.ones((b, L)),
        discount=jnp.ones((b, L)),
        reset=jnp.zeros((b, L)),
        carries={"actor": (zeros, zeros), "critic": (zeros, zeros)},
    )


def test_add_and_size():
    arena = ReplayArena(capacity=10)
    state = arena.init_state(make_batch(2))
    assert int(arena.size(state)) == 0
    state = arena.add(state, make_batch(2), jnp.ones(2))
    assert int(arena.size(state)) == 2
    state = arena.add(state, make_batch(3), jnp.ones(3))
    assert int(arena.size(state)) == 5
    assert int(state.cursor) == 5


def test_ring_overwrite_fifo():
    arena = ReplayArena(capacity=4)
    state = arena.init_state(make_batch(1))
    for i in range(6):  # 6 adds into capacity 4 -> slots hold adds 2..5
        b = make_batch(1, value=float(i))
        state = arena.add(state, b, jnp.ones(1))
    obs_vals = np.asarray(state.data.obs)[:, 0, 0]
    # slot k holds add k for k in 4,5 (wrapped to 0,1) and 2,3 at slots 2,3
    np.testing.assert_allclose(sorted(obs_vals), [2.0, 3.0, 4.0, 5.0])
    assert int(arena.size(state)) == 4


def test_prioritized_sampling_distribution():
    """chi^2-style check: empirical sampling freq tracks p^alpha (SURVEY §4.1)."""
    arena = ReplayArena(capacity=4, alpha=1.0)
    state = arena.init_state(make_batch(4))
    prios = jnp.array([1.0, 2.0, 3.0, 6.0])
    state = arena.add(state, make_batch(4), prios)

    n_draws, bsz = 200, 64
    keys = jax.random.split(jax.random.PRNGKey(0), n_draws)
    sample = jax.jit(lambda s, k: arena.sample(s, k, bsz).indices)
    counts = np.zeros(4)
    for k in keys:
        idx, c = np.unique(np.asarray(sample(state, k)), return_counts=True)
        counts[idx] += c
    freq = counts / counts.sum()
    want = np.asarray(prios) / float(prios.sum())
    np.testing.assert_allclose(freq, want, atol=0.02)


def test_sample_probs_match_distribution():
    arena = ReplayArena(capacity=8, alpha=0.7)
    state = arena.init_state(make_batch(4))
    prios = jnp.array([0.5, 1.0, 2.0, 4.0])
    state = arena.add(state, make_batch(4), prios)
    res = arena.sample(state, jax.random.PRNGKey(1), 16)
    scaled = np.asarray(prios) ** 0.7
    want = scaled / scaled.sum()
    np.testing.assert_allclose(
        np.asarray(res.probs), want[np.asarray(res.indices)], rtol=1e-5
    )


def test_empty_slots_never_sampled():
    arena = ReplayArena(capacity=100)
    state = arena.init_state(make_batch(3))
    state = arena.add(state, make_batch(3), jnp.ones(3))
    res = arena.sample(state, jax.random.PRNGKey(2), 256)
    assert np.asarray(res.indices).max() < 3


def test_uniform_sampling():
    arena = ReplayArena(capacity=50, prioritized=False)
    state = arena.init_state(make_batch(10))
    state = arena.add(state, make_batch(10), jnp.ones(10))
    res = arena.sample(state, jax.random.PRNGKey(3), 512)
    idx = np.asarray(res.indices)
    assert idx.min() >= 0 and idx.max() < 10
    np.testing.assert_allclose(np.asarray(res.probs), 0.1, rtol=1e-6)


def test_priority_update_pallas_kernel():
    """update_priorities runs the Pallas kernel (interpret mode on CPU)."""
    arena = ReplayArena(capacity=8)
    state = arena.init_state(make_batch(4))
    state = arena.add(state, make_batch(4), jnp.ones(4))
    state = arena.update_priorities(
        state, jnp.array([0, 2]), jnp.array([5.0, 7.0])
    )
    np.testing.assert_allclose(
        np.asarray(state.priority)[:4], [5.0, 1.0, 7.0, 1.0], rtol=1e-5
    )


def test_priority_update_inside_jit():
    arena = ReplayArena(capacity=8)
    state = arena.init_state(make_batch(4))
    state = arena.add(state, make_batch(4), jnp.ones(4))

    @jax.jit
    def upd(s):
        return arena.update_priorities(s, jnp.array([1, 3]), jnp.array([9.0, 2.0]))

    s2 = upd(state)
    np.testing.assert_allclose(
        np.asarray(s2.priority)[:4], [1.0, 9.0, 1.0, 2.0], rtol=1e-5
    )


def _dp_arena_state(arena, batch, prios, mesh):
    """Place a fresh ArenaState on ``mesh`` with the dp-learner layout
    (data/priority capacity-sharded, cursor/total_added replicated) and
    add ``batch`` through the jitted staged path."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from r2d2dpg_tpu.parallel.mesh import DP_AXIS
    from r2d2dpg_tpu.replay.arena import ArenaState, StagedSequences

    dp = NamedSharding(mesh, P(DP_AXIS))
    rep = NamedSharding(mesh, P())
    state = jax.device_put(
        arena.init_state(batch),
        ArenaState(data=dp, priority=dp, cursor=rep, total_added=rep),
    )
    add = jax.jit(arena.add_staged)
    return add(state, StagedSequences(seq=batch, priorities=prios))


def test_dp_sharded_add_staged_and_sample_match_dp1():
    """ISSUE 9: add_staged + sample on a dp=2 capacity-sharded arena give
    the SAME indices/probs/priorities as the dp=1 layout at the same seed
    — sharding is layout, never semantics.  Priorities are small integers
    so every cumsum association is exact."""
    from r2d2dpg_tpu.parallel import make_mesh

    arena = ReplayArena(capacity=16, alpha=1.0, use_pallas=False)
    prios = jnp.array([1.0, 2.0, 3.0, 6.0])
    key = jax.random.PRNGKey(9)
    results = {}
    for d in (1, 2):
        state = _dp_arena_state(arena, make_batch(4), prios, make_mesh(d))
        res = jax.jit(arena.sample, static_argnums=2)(state, key, 32)
        results[d] = jax.device_get(
            (res.indices, res.probs, state.priority, state.cursor)
        )
    for a, b in zip(results[1], results[2]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dp_sharded_arena_layout_and_per_shard_occupancy():
    """The dp=2 arena's storage really is capacity-sharded, and
    per_shard_occupancy counts each contiguous capacity block (= shard)."""
    from jax.sharding import PartitionSpec as P

    from r2d2dpg_tpu.parallel import make_mesh
    from r2d2dpg_tpu.parallel.mesh import DP_AXIS

    arena = ReplayArena(capacity=8, use_pallas=False)
    mesh = make_mesh(2)
    state = _dp_arena_state(arena, make_batch(3), jnp.ones(3), mesh)
    assert state.priority.sharding.spec == P(DP_AXIS)
    assert state.data.obs.sharding.spec == P(DP_AXIS)
    # 3 adds at cursor 0 -> all in shard 0's block (slots 0..3).
    np.testing.assert_array_equal(
        np.asarray(arena.per_shard_occupancy(state, 2)), [3, 0]
    )
    with pytest.raises(ValueError, match="divisible"):
        arena.per_shard_occupancy(state, 3)


def test_sampled_batch_contents_roundtrip():
    arena = ReplayArena(capacity=16)
    state = arena.init_state(make_batch(4))
    state = arena.add(state, make_batch(4), jnp.array([1e9, 1e-6, 1e-6, 1e-6]))
    res = arena.sample(state, jax.random.PRNGKey(0), 8)
    # Overwhelming priority on slot 0 -> nearly all samples are slot 0 with reward row 0.
    assert (np.asarray(res.indices) == 0).mean() > 0.9
    row0 = np.asarray(res.batch.reward)[np.asarray(res.indices) == 0]
    np.testing.assert_allclose(row0, 0.0)
