"""Environment-fleet throughput benchmarks.

Measures the two collection paths (SURVEY.md §3.5: actor-side time goes to
env stepping + policy forwards):

1. ``pendulum``: the fully on-device path — vmapped pure-JAX Pendulum fleet
   stepped with the LSTM policy inside one jitted ``lax.scan`` (the Anakin
   hot loop).  Reports agent steps/sec (num_envs x scan steps / wall).
2. ``walker`` / ``humanoid``: the native C++ MuJoCo pool stepped host-side
   (the hybrid / io_callback path's host half), with action repeat 2 —
   whole-pool throughput; see ``bench_native_pool`` for the per-core
   reading.
3. ``pixels``: config-#5 collection — cheetah-run with 64x64 EGL renders on
   the pinned render-thread pool, action repeat 4.

Usage: python benchmarks/env_throughput.py [num_envs] [steps] [modes]
``modes`` is a comma-separated subset of pendulum,walker,humanoid,pixels
(default: pendulum,walker,pixels).  Prints one JSON line per benchmark.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_pendulum(num_envs: int, steps: int) -> dict:
    import jax
    import jax.numpy as jnp

    from r2d2dpg_tpu.envs import Pendulum
    from r2d2dpg_tpu.models import ActorNet

    env = Pendulum()
    actor = ActorNet(action_dim=1, hidden=256, use_lstm=True)
    key = jax.random.PRNGKey(0)
    env_keys = jax.random.split(key, num_envs)
    state, ts = jax.vmap(env.reset)(env_keys)
    carry = actor.initial_carry(num_envs)
    params = actor.init(key, ts.obs, carry, ts.reset)

    @jax.jit
    def rollout(params, state, obs, reset, carry, key):
        def step(c, k):
            state, obs, reset, carry = c
            action, carry = actor.apply(params, obs, carry, reset)
            ks = jax.random.split(k, num_envs)
            state, ts = jax.vmap(env.step)(state, action, ks)
            return (state, ts.obs, ts.reset, carry), ts.reward.mean()

        c, rews = jax.lax.scan(
            step, (state, obs, reset, carry), jax.random.split(key, steps)
        )
        return c, rews.mean()

    c, _ = rollout(params, state, ts.obs, ts.reset, carry, key)  # compile
    jax.block_until_ready(c[1])
    t0 = time.perf_counter()
    c, out = rollout(params, c[0], c[1], c[2], c[3], jax.random.fold_in(key, 1))
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    return {
        "metric": "pendulum_env_steps_per_sec",
        "value": round(num_envs * steps / dt, 1),
        "unit": "agent steps/s",
        "num_envs": num_envs,
        # Device path — automation gates on this being an on-chip number
        # (scripts/tpu_campaign3.sh json_backend_ok).
        "backend": jax.default_backend(),
    }


def bench_native_pool(domain: str, task: str, num_envs: int, steps: int) -> dict:
    """Whole-POOL physics throughput for a native-pool task (walker and
    humanoid supported).  The pool threads over min(cores, num_envs)
    workers, so this equals the per-core ceiling only on a 1-core host;
    divide by the reported ``threads`` for per-core (the number the
    humanoid scaling arithmetic in docs/RESULTS.md multiplies by host
    cores)."""
    import numpy as np

    from r2d2dpg_tpu.envs import native_pool

    pool = native_pool.NativeEnvPool(domain, task)
    pool.reset_all(np.arange(num_envs))
    a = np.zeros((num_envs, pool.action_dim), np.float32)
    pool.step_all(a, repeat=2)  # warm
    t0 = time.perf_counter()
    for _ in range(steps):
        pool.step_all(a, repeat=2)
    dt = time.perf_counter() - t0
    return {
        "metric": f"{domain}_native_pool_steps_per_sec",
        "value": round(num_envs * steps / dt, 1),
        "unit": "agent steps/s (repeat 2)",
        "num_envs": num_envs,
        "threads": pool.num_threads,  # resolved by the pool itself
    }


def bench_cheetah_pixels(num_envs: int, steps: int) -> dict:
    """Config-#5 collection path: threaded physics + pinned-thread renders."""
    import numpy as np

    from r2d2dpg_tpu.envs.dmc_host import DMCHostEnv, _HostPool

    env = DMCHostEnv("cheetah", "run", pixels=True, action_repeat=4)
    import jax

    _, ts = env.reset(jax.random.PRNGKey(0), num_envs)
    a = np.zeros((num_envs, env.spec.action_dim), np.float32)
    env.host_step(a)  # warm (EGL context creation per render thread)
    t0 = time.perf_counter()
    for _ in range(steps):
        env.host_step(a)
    dt = time.perf_counter() - t0
    return {
        "metric": "cheetah_pixels_env_steps_per_sec",
        "value": round(num_envs * steps / dt, 1),
        "unit": "agent steps/s (repeat 4, 64x64 render)",
        "num_envs": num_envs,
        "render_threads": min(_HostPool.RENDER_THREADS, num_envs),
    }


def main() -> None:
    num_envs = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 200
    modes = sys.argv[3].split(",") if len(sys.argv) > 3 else [
        "pendulum", "walker", "pixels"
    ]
    unknown = set(modes) - {"pendulum", "walker", "humanoid", "pixels"}
    if unknown:
        raise SystemExit(
            f"unknown mode(s) {sorted(unknown)}; pick from "
            "pendulum,walker,humanoid,pixels"
        )
    if "pendulum" in modes:
        print(json.dumps(bench_pendulum(num_envs, steps)), flush=True)
    if "walker" in modes:
        print(
            json.dumps(
                bench_native_pool("walker", "walk", num_envs, min(steps, 100))
            ),
            flush=True,
        )
    if "humanoid" in modes:
        print(
            json.dumps(
                bench_native_pool("humanoid", "run", num_envs, min(steps, 100))
            ),
            flush=True,
        )
    if "pixels" in modes:
        print(
            json.dumps(bench_cheetah_pixels(num_envs, min(steps, 50))),
            flush=True,
        )


if __name__ == "__main__":
    main()
