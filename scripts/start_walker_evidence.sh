#!/bin/bash
# CPU evidence run for config #3 at reduced scale (1-core box), high replay
# ratio (16 envs x 16 updates/phase = 1:20). chain_runs.sh picks up configs
# #5 and #4 when this finishes.
cd "$(dirname "$0")/.."
mkdir -p runs/walker_cpu_r2
exec nice -n 19 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu R2D2DPG_PALLAS_INTERPRET=1 \
python -m r2d2dpg_tpu.train --config walker_r2d2 \
  --num-envs 16 --learner-steps 16 --batch-size 64 --min-replay 500 \
  --minutes "${1:-160}" --log-every 20 --eval-every 100 --eval-envs 5 \
  --logdir runs/walker_cpu_r2 --checkpoint-dir runs/walker_cpu_r2/ckpt \
  --checkpoint-every 200 > runs/walker_cpu_r2/stdout.log 2>&1
