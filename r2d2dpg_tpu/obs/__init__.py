"""Unified telemetry (ISSUE 3): registry, exporter, flight recorder, watchdog.

One process-wide namespace for every subsystem's operator signals:

- ``registry``  — typed Counter/Gauge/Histogram instruments with label
  sets (``get_registry()`` is the process singleton all subsystems
  register into).
- ``exporter``  — stdlib-HTTP scrape point (``/metrics`` Prometheus text,
  ``/metrics.json`` snapshot, ``/health`` verdict JSON) on ``--obs-port``.
- ``health``    — the /health rule engine (ISSUE 13): machine-readable
  ``{verdict, findings[]}`` over registry+mirror signals, verdict
  transitions recorded as flight events — the autoscaler's input
  contract, built as observability.
- ``flight``    — bounded ring of structured events dumped to
  ``flight.jsonl`` on exit/abort (``flight_event(kind, **fields)``).
- ``watchdog``  — NaN/Inf + grad/param-norm checks riding the log
  cadence's existing batched ``device_get``; trips abort loudly.
- ``trace``     — sampled experience-path hop spans (collect -> ... ->
  learn) feeding ``r2d2dpg_trace_*_seconds`` histograms and the flight
  recorder's ``trace.json`` dump.
- ``device``    — the device plane (ISSUE 14): compile sentinel
  (``steady_recompile`` alarms on post-warm aval re-keys), per-device
  HBM gauges, MFU against ``--device-peak-flops``, and
  ``--profile-window`` profiler captures stamped into the fused
  timeline.
- ``quality``   — the experience-quality plane (ISSUE 18): sequence
  provenance (behavior param version + collect phase) stamped at the
  actor and carried through wire/arena/shard slots, folded at batch
  assembly into policy-lag/replay-age distributions, ESS/B, IS-weight
  saturation, per-actor trained-seqs and per-shard
  evicted-before-sampled fractions (``r2d2dpg_quality_*``), judged by
  the stale_experience/priority_collapse/untrained_churn/actor_skew
  /health rules and stamped to ``quality_final.json`` at teardown.
- ``RemoteMirror`` / ``allgather_into_mirror`` — other processes'
  registry snapshots merged into this process's exporter: ONE scrape
  point per fleet (fed by fleet TELEM frames or an SPMD allgather).

See docs/OBSERVABILITY.md for the naming scheme, endpoints, event schema
and thresholds.
"""

from r2d2dpg_tpu.obs import device  # noqa: F401 - obs.device.* is the API
from r2d2dpg_tpu.obs.device import (
    DeviceMonitor,
    get_device_monitor,
)
from r2d2dpg_tpu.obs.exporter import (
    MetricsExporter,
    current_exporter,
    start_exporter,
    stop_exporter,
)
from r2d2dpg_tpu.obs.flight import (
    FlightRecorder,
    flight_event,
    get_flight_recorder,
    set_flight_identity,
)
from r2d2dpg_tpu.obs.health import (
    HealthConfig,
    HealthEngine,
)
from r2d2dpg_tpu.obs import quality  # noqa: F401 - obs.quality.* is the API
from r2d2dpg_tpu.obs.quality import (
    QualityPlane,
    get_quality_plane,
    reset_quality_plane,
)
from r2d2dpg_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    RemoteMirror,
    allgather_into_mirror,
    get_registry,
    get_remote_mirror,
    merge_remote,
    render_prometheus,
)
from r2d2dpg_tpu.obs import trace  # noqa: F401 - obs.trace.* is the span API
from r2d2dpg_tpu.obs.watchdog import (
    DivergenceError,
    DivergenceWatchdog,
    WatchdogConfig,
)

__all__ = [
    "Counter",
    "DeviceMonitor",
    "DivergenceError",
    "DivergenceWatchdog",
    "FlightRecorder",
    "Gauge",
    "HealthConfig",
    "HealthEngine",
    "Histogram",
    "MetricsExporter",
    "QualityPlane",
    "Registry",
    "RemoteMirror",
    "WatchdogConfig",
    "allgather_into_mirror",
    "current_exporter",
    "device",
    "flight_event",
    "get_device_monitor",
    "get_flight_recorder",
    "get_quality_plane",
    "get_registry",
    "get_remote_mirror",
    "merge_remote",
    "quality",
    "reset_quality_plane",
    "render_prometheus",
    "set_flight_identity",
    "start_exporter",
    "stop_exporter",
    "trace",
]
