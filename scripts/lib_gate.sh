# Shared gating for one-shot CPU evidence-run drivers (sourced, not run).
#
#   source "$HERE/lib_gate.sh"
#   gate_on_box "<campaign artifact>" ["<extra wait pattern>"] || exit 0
#   wait_on_box ["<extra wait pattern>"]   # wait (never bail) for the core
#
# Blocks while any training process — or anything matching the optional
# extra pgrep pattern (e.g. a predecessor driver script that hasn't spawned
# its python yet) — owns the single-core box; returns 1 (caller should
# exit) if the TPU campaign ever claims the box or already produced the
# superseding artifact.  One implementation so wait/bail fixes don't have
# to be applied per-copy (the round-2 scripts each carried their own).
# NB: never pass a pattern matching the caller's own command line.

# Wait (without ever bailing) while anything that owns the single core is
# live: training/eval pythons, a TPU campaign, or the optional extra
# pattern.  For preemptible drivers that should RESUME after a campaign
# rather than skip (walker_probe/cheetah_mitigation carry private copies
# only because they were live processes when this helper landed — migrate
# them here on their next at-rest edit).
# Liveness patterns are ANCHORED to the real process shapes ("python -m
# r2d2dpg_tpu.train ...", "bash .../script.sh"): an unanchored substring
# match also hits unrelated resident shells whose COMMAND LINE merely
# mentions these names (interactive wrappers, editors, ps/grep pipelines),
# and a wait loop blocked on such a process never wakes up — this
# deadlocked the round-5 evidence queue for 10 minutes behind a stale
# interactive shell.  Kill-lists (campaign VICTIMS, bench preempt) stay
# deliberately unanchored: a rare false-positive kill is recoverable,
# a false-positive WAIT is forever.
TRAIN_PAT='^[^ ]*python[0-9.]* -m r2d2dpg_tpu\.(train|eval)'
CAMPAIGN_PAT='^[^ ]*bash [^ ]*tpu_campaign[0-9]*\.sh'
BENCH_PAT='^[^ ]*python[0-9.]* [^ ]*bench\.py'

# bench: the driver's round-end bench preempts this driver's python train
# by name; without that clause the attempt loop would relaunch a fresh
# train straight into bench's settle window and contend with the TPU
# measurement on the single core.
wait_on_box() {
  local extra="${1:-}"
  while pgrep -f "$TRAIN_PAT" > /dev/null \
     || pgrep -f "$CAMPAIGN_PAT" > /dev/null \
     || pgrep -f "$BENCH_PAT" > /dev/null \
     || { [ -n "$extra" ] && pgrep -f "$extra" > /dev/null; }; do
    sleep 60
  done
}

# Shared CPU evidence-run driver: budgeted train + final 20-ep eval +
# .done stamp, with up to 3 attempts.  A train run that spent its FULL
# wall-clock budget (stamped $dir/.train_complete on rc=0) is never
# discarded over a transient eval failure: the train step re-runs only
# when no completed run exists.  A PREEMPTED train (killed by the TPU
# campaign's kill-list mid-budget) leaves a checkpoint but no marker and
# is restarted from scratch — evaluating a partial train would stamp
# .done on evidence that answers a different (shorter-budget) question.
#   run_evidence <dir> <supersede-artifact|""> <wait-extra-pattern> \
#                <minutes> <seed> "<eval flags>" <train args...>
run_evidence() {
  local dir=$1 supersede=$2 waitpat=$3 minutes=$4 seed=$5 evalflags=$6
  shift 6
  local attempt rc
  for attempt in 1 2 3; do
    if [ -f "$dir/.done" ]; then
      echo "$dir: already done; exiting $(date)"
      return 0
    fi
    if [ -n "$supersede" ] && [ -f "$supersede" ]; then
      echo "$dir: superseded by $supersede; skipping $(date)"
      return 0
    fi
    wait_on_box "$waitpat"
    # One-time migration (ADVICE r5 #3): run dirs whose train completed
    # BEFORE the .train_complete stamp existed would be rm -rf'd and
    # retrained from scratch if ever re-armed.  The completed-train
    # evidence lives in the driver logs next to the run dir ("<dir>
    # attempt N train done rc=0", echoed by this function and by the
    # older private-copy drivers into their exec-redirected logs).  Only
    # the dir's LAST logged train event counts: a stale "done rc=0" must
    # not bless a later attempt's dir that was preempted mid-budget.
    # Chronology is only knowable WITHIN one log file, so every file gets
    # a vote and any file whose last event is not "done rc=0" vetoes
    # (e.g. the relaunch's "train start" with no matching done).
    # grep -F: fixed-string, so regex metachars in $dir can't mis-match.
    if ! [ -f "$dir/.train_complete" ] && [ -d "$dir" ]; then
      local _mig_log _mig_last _mig_verdict=""
      for _mig_log in "$(dirname "$dir")"/*.log; do
        [ -f "$_mig_log" ] || continue
        _mig_last=$(grep -F -- "$dir attempt" "$_mig_log" 2>/dev/null \
                      | grep -F " train " | tail -1)
        [ -z "$_mig_last" ] && continue
        case "$_mig_last" in
          *" train done rc=0 "*) [ -z "$_mig_verdict" ] && _mig_verdict=stamp ;;
          *) _mig_verdict=veto ;;
        esac
      done
      if [ "$_mig_verdict" = stamp ]; then
        echo "$dir: pre-stamp completed train found in logs; stamping .train_complete $(date)"
        touch "$dir/.train_complete"
      fi
    fi
    # Pipelined (--pipeline 1) and fleet (--actors N) runs: those
    # executors own the phase loop and REFUSE periodic eval (train.py
    # guards), so mid-run eval curves are dropped for them — the blessing
    # evidence is the FINAL 20-ep eval below either way, which still runs
    # off the final checkpoint.
    local evalevery=150
    case " $* " in
      *" --pipeline 1 "*|*" --actors "[1-9]*) evalevery=0 ;;
    esac
    if ! [ -f "$dir/.train_complete" ]; then
      echo "=== $dir attempt $attempt train start ($*) $(date) ==="
      rm -rf "$dir"
      mkdir -p "$dir"
      nice -n 19 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu R2D2DPG_PALLAS_INTERPRET=1 \
      python -m r2d2dpg_tpu.train "$@" \
        --seed "$seed" --minutes "$minutes" \
        --log-every 10 --eval-every "$evalevery" --eval-envs 5 \
        --logdir "$dir" --checkpoint-dir "$dir/ckpt" --checkpoint-every 150 \
        > "$dir/stdout.log" 2>&1
      rc=$?
      echo "=== $dir attempt $attempt train done rc=$rc $(date) ==="
      [ $rc -eq 0 ] && touch "$dir/.train_complete"
    else
      echo "$dir: completed train exists; retrying eval only $(date)"
    fi
    if [ -f "$dir/.train_complete" ] \
       && [ -d "$dir/ckpt" ] && [ -n "$(ls "$dir/ckpt" 2>/dev/null)" ]; then
      # Gate AFTER wait_on_box: the determinism pytest is itself a
      # CPU-heavy step and must honor the single-core discipline.
      wait_on_box "$waitpat"
      if ! pipeline_gate "$dir" "$@"; then
        echo "$dir: pipeline determinism gate FAILED (attempt $attempt)"
        continue
      fi
      if ! fleet_gate "$dir" "$@"; then
        echo "$dir: fleet determinism gate FAILED (attempt $attempt)"
        continue
      fi
      if ! chaos_gate "$dir" "$@"; then
        echo "$dir: chaos drill gate FAILED (attempt $attempt)"
        continue
      fi
      if ! learner_dp_gate "$dir" "$@"; then
        echo "$dir: learner-dp determinism gate FAILED (attempt $attempt)"
        continue
      fi
      if ! sampler_gate "$dir" "$@"; then
        echo "$dir: sampler equivalence gate FAILED (attempt $attempt)"
        continue
      fi
      if ! shard_gate "$dir" "$@"; then
        echo "$dir: shard-tier gate FAILED (attempt $attempt)"
        continue
      fi
      if ! topology_gate "$dir" "$@"; then
        echo "$dir: composed-topology gate FAILED (attempt $attempt)"
        continue
      fi
      if ! device_gate "$dir" "$@"; then
        echo "$dir: device-plane gate FAILED (attempt $attempt)"
        continue
      fi
      if ! autoscale_gate "$dir" "$@"; then
        echo "$dir: autoscale recovery gate FAILED (attempt $attempt)"
        continue
      fi
      if ! quality_gate "$dir" "$@"; then
        echo "$dir: experience-quality gate FAILED (attempt $attempt)"
        continue
      fi
      if ! serve_gate "$dir" "$@"; then
        echo "$dir: serving scale-out gate FAILED (attempt $attempt)"
        continue
      fi
      timeout --kill-after=30 --signal=TERM 1800 \
        env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu R2D2DPG_PALLAS_INTERPRET=1 \
        python -m r2d2dpg_tpu.eval $evalflags \
          --checkpoint-dir "$dir/ckpt" --episodes 10 --rounds 2 \
          > "$dir/final_eval.jsonl" 2> "$dir/final_eval.stderr.log" \
        && tail -1 "$dir/final_eval.jsonl" > "$dir/final_eval.json" \
        && touch "$dir/.done" \
        || echo "$dir eval FAILED (attempt $attempt)"
    fi
  done
}

# Pipelined evidence gate (ISSUE 2): a run dir trained with --pipeline 1
# may only be blessed (.done) if the pipeline=off determinism test passes
# on this checkout — proof the executor's schedule is still bit-faithful
# to the phase-locked trainer before any pipelined number becomes
# evidence (docs/PIPELINE.md "Determinism contract").  The verdict is
# stamped per run dir so retries (and the eval-only path) don't re-pay
# the ~2 min test; non-pipelined runs pass through untouched.
#   pipeline_gate <dir> <train args...>
pipeline_gate() {
  local dir=$1
  shift
  case " $* " in
    *" --pipeline 1 "*) ;;
    *) return 0 ;;  # not a pipelined run: nothing to gate
  esac
  if [ -f "$dir/.pipeline_determinism_ok" ]; then
    return 0
  fi
  if timeout --kill-after=30 900 \
       env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu R2D2DPG_PALLAS_INTERPRET=1 \
       XLA_FLAGS= \
       python -m pytest tests/test_pipeline.py -q -p no:cacheprovider \
         -k determinism \
       > "$dir/pipeline_gate.log" 2>&1; then
    touch "$dir/.pipeline_determinism_ok"
    return 0
  fi
  return 1
}

# Fleet evidence gate (ISSUE 4): a run dir trained with --actors N may
# only be blessed (.done) if the fleet=off determinism test passes on this
# checkout — proof that wiring the fleet subsystem into train.py left the
# default schedule bit-faithful to Trainer.run before any fleet number
# becomes evidence (docs/FLEET.md "Determinism anchor").  Same stamping
# discipline as pipeline_gate; non-fleet runs pass through untouched.
#   fleet_gate <dir> <train args...>
fleet_gate() {
  local dir=$1
  shift
  case " $* " in
    *" --actors "[1-9]*) ;;
    *) return 0 ;;  # not a fleet run (or --actors 0): nothing to gate
  esac
  # Record the NEGOTIATED wire lane in the evidence dir (ISSUE 5): a
  # fleet number's meaning depends on what crossed the wire (bf16 and
  # compressed lanes are different — equally valid — trajectories), so
  # the blessing stamps which lane produced it.  Defaults mirror
  # train.py's (--fleet-wire f32 --fleet-compress none --drain-coalesce 1).
  local _fw_enc=f32 _fw_comp=none _fw_coal=1 _fw_prev=""
  local _fw_arg
  for _fw_arg in "$@"; do
    # Both argparse spellings: "--flag value" and "--flag=value".
    case "$_fw_arg" in
      --fleet-wire=*) _fw_enc=${_fw_arg#*=} ;;
      --fleet-compress=*) _fw_comp=${_fw_arg#*=} ;;
      --drain-coalesce=*) _fw_coal=${_fw_arg#*=} ;;
    esac
    case "$_fw_prev" in
      --fleet-wire) _fw_enc=$_fw_arg ;;
      --fleet-compress) _fw_comp=$_fw_arg ;;
      --drain-coalesce) _fw_coal=$_fw_arg ;;
    esac
    _fw_prev=$_fw_arg
  done
  printf 'encoding=%s compress=%s drain_coalesce=%s\n' \
    "$_fw_enc" "$_fw_comp" "$_fw_coal" > "$dir/fleet_wire.txt"
  if [ -f "$dir/.fleet_determinism_ok" ]; then
    return 0
  fi
  if timeout --kill-after=30 900 \
       env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu R2D2DPG_PALLAS_INTERPRET=1 \
       XLA_FLAGS= \
       python -m pytest tests/test_fleet.py -q -p no:cacheprovider \
         -k determinism \
       > "$dir/fleet_gate.log" 2>&1; then
    touch "$dir/.fleet_determinism_ok"
    return 0
  fi
  return 1
}

# Chaos drill gate (ISSUE 7): a run dir trained with --actors N may only
# be blessed (.done) if the non-slow chaos drills pass on this checkout —
# proof that every documented recovery path (heartbeat reap, CRC reject,
# reconnect, backoff restart, checkpoint/resume) still recovers before
# any fleet number becomes evidence (docs/FLEET.md "Failure modes &
# recovery").  The deterministic seeded single-fault drills only; the
# multi-fault subprocess soak stays a slow-marked pytest.  Same stamping
# discipline as fleet_gate; non-fleet runs pass through untouched.
#   chaos_gate <dir> <train args...>
chaos_gate() {
  local dir=$1
  shift
  case " $* " in
    *" --actors "[1-9]*) ;;
    *) return 0 ;;  # not a fleet run (or --actors 0): nothing to gate
  esac
  if [ -f "$dir/.chaos_drills_ok" ]; then
    return 0
  fi
  if timeout --kill-after=30 900 \
       env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu R2D2DPG_PALLAS_INTERPRET=1 \
       XLA_FLAGS= \
       python -m pytest tests/test_chaos.py -q -p no:cacheprovider \
         -m 'not slow' \
       > "$dir/chaos_gate.log" 2>&1; then
    touch "$dir/.chaos_drills_ok"
    return 0
  fi
  return 1
}

# Learner-dp evidence gate (ISSUE 9): a run dir trained with
# --learner-dp N may only be blessed (.done) if the dp determinism anchor
# passes on this checkout — proof the dp-mesh layout annotations change
# no bit of the trajectory before any multi-chip learner number becomes
# evidence (docs/FLEET.md "Multi-chip learner").  The resolved dp width
# is stamped into the evidence dir beside fleet_wire.txt either way, so
# a blessed number always says which mesh produced it.  Same stamping
# discipline as fleet_gate; non-dp runs pass through untouched.
#   learner_dp_gate <dir> <train args...>
learner_dp_gate() {
  local dir=$1
  shift
  local _dp="" _dp_prev=""
  local _dp_arg
  for _dp_arg in "$@"; do
    # Both argparse spellings: "--flag value" and "--flag=value".
    case "$_dp_arg" in
      --learner-dp=*) _dp=${_dp_arg#*=} ;;
    esac
    case "$_dp_prev" in
      --learner-dp) _dp=$_dp_arg ;;
    esac
    _dp_prev=$_dp_arg
  done
  if [ -z "$_dp" ] || [ "$_dp" = 0 ]; then
    return 0  # not a dp-learner run: nothing to gate
  fi
  printf 'learner_dp=%s\n' "$_dp" > "$dir/learner_dp.txt"
  if [ -f "$dir/.learner_dp_determinism_ok" ]; then
    return 0
  fi
  # NB every gate pytest line clears XLA_FLAGS: a --learner-dp evidence
  # run exports --xla_force_host_platform_device_count=D, and an
  # inherited D != 8 fails tests/conftest.py's 8-device assert during
  # collection — the gate would loop "FAILED" forever on a healthy
  # anchor.
  if timeout --kill-after=30 900 \
       env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu R2D2DPG_PALLAS_INTERPRET=1 \
       XLA_FLAGS= \
       python -m pytest tests/test_dp_learner.py -q -p no:cacheprovider \
         -k determinism \
       > "$dir/learner_dp_gate.log" 2>&1; then
    touch "$dir/.learner_dp_determinism_ok"
    return 0
  fi
  return 1
}

# Sampler evidence gate (ISSUE 10): a run dir trained with
# --replay-shards N may only be blessed (.done) if the in-network-
# sampling anchors pass on this checkout — the --replay-shards 1
# --actors 0 CLI path bit-identical to Trainer.run (wiring the knob
# changes no bit of the default schedule) AND the two-level sharded
# draw distribution-equivalent to central proportional sampling on
# exact-integer priorities (docs/REPLAY.md "Determinism anchor").  The
# resolved shard count is stamped into the evidence dir
# (replay_shards.txt) beside fleet_wire.txt, so a blessed number always
# says which replay topology produced it.  Same stamping discipline as
# fleet_gate; non-sharded runs pass through untouched.
#   sampler_gate <dir> <train args...>
sampler_gate() {
  local dir=$1
  shift
  local _rs="" _rs_prev=""
  local _rs_arg
  for _rs_arg in "$@"; do
    # Both argparse spellings: "--flag value" and "--flag=value".
    case "$_rs_arg" in
      --replay-shards=*) _rs=${_rs_arg#*=} ;;
    esac
    case "$_rs_prev" in
      --replay-shards) _rs=$_rs_arg ;;
    esac
    _rs_prev=$_rs_arg
  done
  if [ -z "$_rs" ] || [ "$_rs" = 0 ]; then
    return 0  # not a sharded-replay run: nothing to gate
  fi
  printf 'replay_shards=%s\n' "$_rs" > "$dir/replay_shards.txt"
  if [ -f "$dir/.sampler_equivalence_ok" ]; then
    return 0
  fi
  if timeout --kill-after=30 900 \
       env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu R2D2DPG_PALLAS_INTERPRET=1 \
       XLA_FLAGS= \
       python -m pytest tests/test_sampler.py -q -p no:cacheprovider \
         -k 'determinism or equivalence' \
       > "$dir/sampler_gate.log" 2>&1; then
    touch "$dir/.sampler_equivalence_ok"
    return 0
  fi
  return 1
}

# Scrape-evidence check for --shard-procs dirs (ISSUE 13): every live
# shard 0..N-1 must have its labelled occupancy series in the run's
# final merged scrape, and every shard HOLDING data must have folded at
# least one TELEM snapshot (r2d2dpg_shard_telem_frames_total > 0).  The
# advert-mirror occupancy series alone is the learner talking to itself
# (RemoteShardSet registers it for every shard unconditionally), so it
# cannot distinguish an observability-dark shard proc from a healthy
# one — the TELEM counter only gets a labelled cell when a shard-proc
# snapshot actually crossed the wire and folded.  Idle shards (advert
# occupancy 0; the learner dials lazily, so an untrafficked shard never
# HELLOs and never pushes) are exempt — shard_skew is their signal.
# NB this means --shard-procs evidence must run the health plane
# (--obs-fleet 1 arms the shard-proc TELEM cadence).  Cheap (grep per
# shard), so it re-runs on every gate pass instead of hiding behind the
# anchor stamp.
#   shard_scrape_check <dir> <num_shards>
shard_scrape_check() {
  local dir=$1 n=$2 i occ prom
  prom=$dir/metrics_final.prom
  if [ ! -f "$prom" ]; then
    echo "$dir: shard_gate: metrics_final.prom missing — the run left no" \
         "final scrape to attribute the shard tier's numbers to"
    return 1
  fi
  for i in $(seq 0 $((n - 1))); do
    if ! grep -Eq "r2d2dpg_replay_shard_occupancy\{[^}]*shard=\"$i\"" \
         "$prom"; then
      echo "$dir: shard_gate: scrape lacks shard $i's labelled occupancy" \
         "series (metrics_final.prom) — an observability-dark shard" \
         "cannot be blessed as evidence"
      return 1
    fi
    # The advert-mirror series renders with shard= as its only label;
    # the TELEM-folded copy carries host= attribution.
    occ=$(grep -E "^r2d2dpg_replay_shard_occupancy\{shard=\"$i\"\} " \
            "$prom" | head -1 | awk '{print $2}')
    if [ -n "$occ" ] && awk -v o="$occ" 'BEGIN{exit !(o > 0)}'; then
      if ! grep -E \
           "^r2d2dpg_shard_telem_frames_total\{[^}]*shard=\"$i\"[^}]*\} " \
           "$prom" | awk '{s+=$2} END{exit !(s > 0)}'; then
        echo "$dir: shard_gate: shard $i holds data (advert occupancy" \
          "$occ) but folded no TELEM snapshot (metrics_final.prom has no" \
          "r2d2dpg_shard_telem_frames_total{shard=\"$i\"} > 0) — an" \
          "observability-dark shard proc cannot be blessed as evidence" \
          "(run with --obs-fleet 1)"
        return 1
      fi
    fi
  done
  return 0
}

# Standalone-shard-tier gate (ISSUE 12): a run dir trained with
# --shard-procs N may only be blessed (.done) if the shard-tier anchors
# pass on this checkout — the loopback-vs-out-of-process determinism
# anchor (a BATCH through a real socket decodes bit-identically to the
# in-learner loopback; plus the --shard-procs 0 off-setting riding the
# sampler CLI anchor) AND the non-slow kill_shard chaos drill (2 actors
# x 2 shard procs: run completes, quotas renormalize to the survivor,
# the restarted shard rejoins under a bumped epoch, stale-epoch frames
# fenced — docs/REPLAY.md "Standalone shard tier").  The resolved proc
# count is stamped into the evidence dir (shard_procs.txt) beside
# replay_shards.txt, so a blessed number always says where replay
# LIVED.  Same stamping discipline as fleet_gate; loopback runs pass
# through untouched.
#
# ISSUE 13 adds the scrape-evidence clause: the run's final merged
# scrape (metrics_final.prom, written by train.py's fleet teardown)
# must carry EVERY shard's labelled occupancy series — a shard that is
# observability-dark (its TELEM never folded, its advert mirror never
# registered) must not be blessed as evidence, because the numbers it
# contributed cannot be attributed on the one fleet /metrics page.
#
# ISSUE 17 adds the direct-data-plane clause: a run trained with
# --shard-direct 1 (actors pushing SEQS straight to shard procs,
# learner forward hop shed) may only be blessed if BOTH the
# -m shard_direct suite (assignment acks, K_STATS at-least-once
# accounting, per-plane byte separation, puller bit-determinism,
# coalesced PRIO golden) AND the partition_data_plane fallback drill
# (chaos e2e: dial refused mid-run -> loud fallback to the forwarded
# path, zero lost accounting) pass on this checkout, alongside the
# --shard-direct 0 bitwise CLI anchor that the 'determinism' -k
# selection already carries.  Direct evidence WITHOUT a passing
# fallback drill is refused outright: a data plane that has never
# demonstrated its escape hatch cannot be blessed.  The resolved flag
# is stamped (shard_direct.txt beside shard_procs.txt) so a blessed
# number always says which experience path produced it.
#   shard_gate <dir> <train args...>
shard_gate() {
  local dir=$1
  shift
  local _sp="" _rs="" _sd="" _sp_prev=""
  local _sp_arg
  for _sp_arg in "$@"; do
    # Both argparse spellings: "--flag value" and "--flag=value".
    case "$_sp_arg" in
      --shard-procs=*) _sp=${_sp_arg#*=} ;;
      --replay-shards=*) _rs=${_sp_arg#*=} ;;
      --shard-direct=*) _sd=${_sp_arg#*=} ;;
    esac
    case "$_sp_prev" in
      --shard-procs) _sp=$_sp_arg ;;
      --replay-shards) _rs=$_sp_arg ;;
      --shard-direct) _sd=$_sp_arg ;;
    esac
    _sp_prev=$_sp_arg
  done
  if [ -z "$_sp" ] || [ "$_sp" = 0 ]; then
    return 0  # in-learner loopback (or no sampler path): nothing to gate
  fi
  printf 'shard_procs=%s\n' "$_sp" > "$dir/shard_procs.txt"
  printf 'shard_direct=%s\n' "${_sd:-0}" > "$dir/shard_direct.txt"
  if ! shard_scrape_check "$dir" "${_rs:-$_sp}"; then
    return 1
  fi
  if [ -n "$_sd" ] && [ "$_sd" != 0 ] \
     && ! [ -f "$dir/.shard_direct_ok" ]; then
    # Fallback drill + direct-plane suite, refused-not-skipped: every
    # test in the file carries the shard_direct mark, so -m shard_direct
    # deliberately includes the slow e2e pair (direct run + the
    # partition_data_plane fallback drill) — the drill is the point.
    if ! timeout --kill-after=30 900 \
         env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
         R2D2DPG_PALLAS_INTERPRET=1 XLA_FLAGS= \
         python -m pytest tests/test_shard_direct.py \
           -q -p no:cacheprovider -m shard_direct \
         > "$dir/shard_direct_gate.log" 2>&1; then
      echo "$dir: shard_gate: --shard-direct evidence REFUSED — the" \
        "direct-plane suite or the partition_data_plane fallback drill" \
        "failed on this checkout (shard_direct_gate.log); a data plane" \
        "without a demonstrated escape hatch cannot be blessed"
      return 1
    fi
    touch "$dir/.shard_direct_ok"
  fi
  if [ -f "$dir/.shard_tier_ok" ]; then
    return 0
  fi
  if timeout --kill-after=30 900 \
       env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu R2D2DPG_PALLAS_INTERPRET=1 \
       XLA_FLAGS= \
       python -m pytest tests/test_shard.py tests/test_sampler.py \
         tests/test_shard_direct.py \
         -q -p no:cacheprovider -m 'not slow' \
         -k 'determinism or kill_shard or shard_direct or coalesce' \
       > "$dir/shard_gate.log" 2>&1; then
    touch "$dir/.shard_tier_ok"
    return 0
  fi
  return 1
}

# Composed-topology gate (ISSUE 11): a run dir trained with MORE THAN
# ONE scaling axis (--actors N plus --replay-shards N and/or
# --learner-dp N) may only be blessed (.done) if the per-pairing anchors
# pass on this checkout — the composed off-settings determinism anchor
# (--replay-shards 1 --learner-dp 1 --actors 0 bit-identical to
# Trainer.run through the CLI) and the sampler+dp bitwise learn anchor
# (tests/test_topology.py; docs/TOPOLOGY.md "Determinism anchors").  The
# resolved axis triple is stamped into the evidence dir (topology.txt,
# beside fleet_wire.txt/learner_dp.txt) for ANY multi-axis run, so a
# blessed number always says which composition produced it.  Single-axis
# runs pass through untouched — their own gates (fleet_gate,
# learner_dp_gate, sampler_gate) already cover them.
#   topology_gate <dir> <train args...>
topology_gate() {
  local dir=$1
  shift
  local _tg_actors=0 _tg_shards=0 _tg_dp=0 _tg_prev=""
  local _tg_arg
  for _tg_arg in "$@"; do
    # Both argparse spellings: "--flag value" and "--flag=value".
    case "$_tg_arg" in
      --actors=*) _tg_actors=${_tg_arg#*=} ;;
      --replay-shards=*) _tg_shards=${_tg_arg#*=} ;;
      --learner-dp=*) _tg_dp=${_tg_arg#*=} ;;
    esac
    case "$_tg_prev" in
      --actors) _tg_actors=$_tg_arg ;;
      --replay-shards) _tg_shards=$_tg_arg ;;
      --learner-dp) _tg_dp=$_tg_arg ;;
    esac
    _tg_prev=$_tg_arg
  done
  local _tg_axes=0
  [ "${_tg_actors:-0}" != 0 ] && _tg_axes=$((_tg_axes + 1))
  [ "${_tg_shards:-0}" != 0 ] && _tg_axes=$((_tg_axes + 1))
  [ "${_tg_dp:-0}" != 0 ] && _tg_axes=$((_tg_axes + 1))
  if [ "$_tg_axes" -lt 2 ]; then
    return 0  # single-axis run: its own gate covers it
  fi
  # train.py already stamps the richer four-stage describe() line into
  # <logdir>/topology.txt (it contains the actors=/replay_shards=/
  # learner_dp= triple); only write the fallback triple when the run
  # predates that stamp or used a different logdir.
  if ! [ -f "$dir/topology.txt" ]; then
    printf 'actors=%s replay_shards=%s learner_dp=%s\n' \
      "$_tg_actors" "$_tg_shards" "$_tg_dp" > "$dir/topology.txt"
  fi
  if [ -f "$dir/.topology_anchors_ok" ]; then
    return 0
  fi
  if timeout --kill-after=30 900 \
       env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu R2D2DPG_PALLAS_INTERPRET=1 \
       XLA_FLAGS= \
       python -m pytest tests/test_topology.py -q -p no:cacheprovider \
         -k 'determinism or anchor' \
       > "$dir/topology_gate.log" 2>&1; then
    touch "$dir/.topology_anchors_ok"
    return 0
  fi
  return 1
}

# Device-plane gate (ISSUE 14): NO evidence dir may be blessed (.done)
# while any of its flight dumps carries a steady_recompile event — a
# learn/drain program whose avals re-keyed after warm-up recompiled
# mid-measurement, so every rate in the dir includes a silent multi-
# second stall the record doesn't explain (the exact bug class the
# PR 9/11 out_shardings pins exist to prevent; obs/device.py is the
# sentinel).  Applies to EVERY run shape — the phase-locked loop arms
# the sentinel too — and re-runs on every gate pass (a cheap grep; no
# stamp file to go stale).  The verdict is stamped device_obs.txt
# beside topology.txt either way, so a blessed number always says its
# steady window was compile-clean.  Runs predating the sentinel leave
# no flight dumps with the event and pass through unchanged.
#   device_gate <dir> <train args...>
device_gate() {
  local dir=$1
  shift
  local f n hits=0 dumps=0
  for f in "$dir"/flight*.jsonl; do
    [ -f "$f" ] || continue
    dumps=$((dumps + 1))
    n=$(grep -c '"kind": "steady_recompile"' "$f")
    hits=$((hits + ${n:-0}))
  done
  printf 'steady_recompiles=%s flight_dumps=%s\n' "$hits" "$dumps" \
    > "$dir/device_obs.txt"
  if [ "$hits" -gt 0 ]; then
    echo "$dir: device_gate: $hits steady_recompile event(s) in the" \
         "run's flight dumps — a learn/drain program re-keyed mid-run" \
         "(grep steady_recompile $dir/flight*.jsonl for the program" \
         "labels); compile-stalled rates cannot be blessed as evidence"
    return 1
  fi
  return 0
}

# Autoscale evidence gate (ISSUE 16): a run dir trained with
# --autoscale 1 may only be blessed (.done) if (a) the non-slow
# kill-drill recovery test passes on this checkout — proof the policy
# loop (not the backoff ladder) restores a killed actor, with zero
# crash-restarts and zero sheds (tests/test_autoscaler.py) — and (b)
# every autoscale_action event in the dir's flight dumps pairs with a
# LANDED origin="autoscale" spawn/retire actuation: an action the
# supervisor never executed is a policy engine claiming recoveries it
# didn't perform, and no rate measured under it can be blessed.  The
# resolved autoscale knobs are stamped into the evidence dir
# (autoscale.txt), so a blessed number always says which policy bounds
# governed its population.  --autoscale 0 runs pass through untouched
# (the mode is structurally inert there — topology determinism anchors
# cover it).  Metric names (r2d2dpg_autoscale_*) conform to the
# lint_obs.sh scheme check; no allowlist entry needed.
#   autoscale_gate <dir> <train args...>
autoscale_gate() {
  local dir=$1
  shift
  local _as="" _as_min="" _as_max="" _as_prev=""
  local _as_arg
  for _as_arg in "$@"; do
    # Both argparse spellings: "--flag value" and "--flag=value".
    case "$_as_arg" in
      --autoscale=*) _as=${_as_arg#*=} ;;
      --autoscale-min=*) _as_min=${_as_arg#*=} ;;
      --autoscale-max=*) _as_max=${_as_arg#*=} ;;
    esac
    case "$_as_prev" in
      --autoscale) _as=$_as_arg ;;
      --autoscale-min) _as_min=$_as_arg ;;
      --autoscale-max) _as_max=$_as_arg ;;
    esac
    _as_prev=$_as_arg
  done
  if [ -z "$_as" ] || [ "$_as" = 0 ]; then
    return 0  # autoscale off: structurally inert, nothing to gate
  fi
  printf 'autoscale=%s min=%s max=%s\n' \
    "$_as" "${_as_min:-1}" "${_as_max:-actors}" > "$dir/autoscale.txt"
  # (b) action/actuation pairing over the run's own flight dumps — a
  # cheap scan, re-checked on every pass (no stamp to go stale).
  if ! python - "$dir"/flight*.jsonl <<'PYEOF'
import json
import sys

bad = False
for path in sys.argv[1:]:
    try:
        lines = open(path).read().splitlines()
    except OSError:
        continue
    actions = 0
    landed = 0
    for line in lines:
        try:
            e = json.loads(line)
        except ValueError:
            continue
        kind = e.get("kind", "")
        if kind == "autoscale_action":
            actions += 1
        elif (
            kind in ("actor_spawn", "actor_retire",
                     "shard_spawn", "shard_retire")
            and e.get("origin") == "autoscale"
        ):
            landed += 1
    if actions > landed:
        print(
            f"{path}: {actions} autoscale_action event(s) but only "
            f"{landed} landed origin=autoscale spawn/retire event(s) — "
            "the policy loop claimed an actuation the supervisor never "
            "executed"
        )
        bad = True
sys.exit(1 if bad else 0)
PYEOF
  then
    echo "$dir: autoscale_gate: flight dumps fail the action/actuation" \
         "pairing check (see lines above)"
    return 1
  fi
  # (a) the kill-drill recovery anchor, stamped per dir like the other
  # pytest-backed gates.
  if [ -f "$dir/.autoscale_recovery_ok" ]; then
    return 0
  fi
  if timeout --kill-after=30 900 \
       env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu R2D2DPG_PALLAS_INTERPRET=1 \
       XLA_FLAGS= \
       python -m pytest tests/test_autoscaler.py -q -p no:cacheprovider \
         -m 'not slow' -k kill_drill \
       > "$dir/autoscale_gate.log" 2>&1; then
    touch "$dir/.autoscale_recovery_ok"
    return 0
  fi
  return 1
}

# Experience-quality gate (ISSUE 18): a fleet run (--actors N) with the
# obs plane armed (--obs-fleet 1) may only be blessed (.done) if its
# final merged scrape carries an ARMED policy-lag distribution — the
# r2d2dpg_quality_policy_lag series with count > 0.  On such a run every
# drained sequence carries wire provenance (the actor stamps its
# behavior param version at staging), so a scrape without the lag
# series means the quality plane went dark: the run's numbers cannot
# say how STALE the experience they trained on was, and a rate measured
# over unknown-staleness experience is not evidence (the failure mode
# the plane exists to expose — a fleet can be green on every liveness
# signal while training on garbage).  The verdict context is stamped
# quality.txt beside autoscale.txt either way — threshold + armed lag
# count — so a blessed number always says what staleness bound it was
# judged under.  Cheap (grep + awk), so it re-runs on every gate pass
# instead of hiding behind a stamp.  --actors 0 runs pass through
# untouched: no wire hop means no provenance and the lag axis stays
# structurally disarmed (docs/OBSERVABILITY.md "Experience-quality
# plane").
#   quality_gate <dir> <train args...>
quality_gate() {
  local dir=$1
  shift
  local _qa=0 _qo=0 _ql="" _q_prev=""
  local _q_arg
  for _q_arg in "$@"; do
    # Both argparse spellings: "--flag value" and "--flag=value".
    case "$_q_arg" in
      --actors=*) _qa=${_q_arg#*=} ;;
      --obs-fleet=*) _qo=${_q_arg#*=} ;;
      --quality-max-lag=*) _ql=${_q_arg#*=} ;;
    esac
    case "$_q_prev" in
      --actors) _qa=$_q_arg ;;
      --obs-fleet) _qo=$_q_arg ;;
      --quality-max-lag) _ql=$_q_arg ;;
    esac
    _q_prev=$_q_arg
  done
  if [ "${_qa:-0}" = 0 ] || [ "${_qo:-0}" = 0 ]; then
    return 0  # no wire provenance or no obs plane: lag axis disarmed
  fi
  local prom=$dir/metrics_final.prom lag_count
  if [ ! -f "$prom" ]; then
    echo "$dir: quality_gate: metrics_final.prom missing — the run left" \
         "no final scrape to judge experience staleness from"
    return 1
  fi
  lag_count=$(grep -E '^r2d2dpg_quality_policy_lag_count' "$prom" \
                | awk '{s+=$2} END{print s+0}')
  printf 'quality_max_lag=%s policy_lag_count=%s\n' \
    "${_ql:-100.0}" "${lag_count:-0}" > "$dir/quality.txt"
  if ! awk -v c="${lag_count:-0}" 'BEGIN{exit !(c > 0)}'; then
    echo "$dir: quality_gate: metrics_final.prom lacks an armed" \
         "r2d2dpg_quality_policy_lag series (count=$lag_count) on an" \
         "--actors run with --obs-fleet 1 — the quality plane went dark" \
         "and the run cannot say how stale its trained experience was;" \
         "unknown-staleness rates cannot be blessed as evidence"
    return 1
  fi
  return 0
}

# Serving scale-out gate (ISSUE 20): an evidence dir produced with
# --serve-workers N (N >= 2, e.g. a BENCH_SERVE traffic run or a routed
# serve deployment's obs capture) may only be blessed if the off-setting
# anchors pass on this checkout — the 1-worker router path bit-identical
# to the PR-1 PolicyService through the serve CLI, interleaved routed
# traffic bit-identical per session to sequential rollouts, and the
# rendezvous hash's determinism/coverage pins (docs/SERVING.md
# "Scale-out").  A routed p50/p99 number over traffic that silently lost
# a session's carry to an affinity bug is not evidence.  The resolved
# worker count is stamped into the evidence dir (serve_workers.txt)
# beside the other topology stamps, so a blessed number always says how
# many workers served it.  Same stamping discipline as fleet_gate;
# single-worker runs pass through untouched.
#   serve_gate <dir> <serve/bench args...>
serve_gate() {
  local dir=$1
  shift
  local _sw="" _sw_prev=""
  local _sw_arg
  for _sw_arg in "$@"; do
    # Both argparse spellings: "--flag value" and "--flag=value".
    case "$_sw_arg" in
      --serve-workers=*) _sw=${_sw_arg#*=} ;;
    esac
    case "$_sw_prev" in
      --serve-workers) _sw=$_sw_arg ;;
    esac
    _sw_prev=$_sw_arg
  done
  if [ -z "$_sw" ] || [ "$_sw" = 0 ] || [ "$_sw" = 1 ]; then
    return 0  # single-worker (or non-serve) run: nothing to gate
  fi
  printf 'serve_workers=%s\n' "$_sw" > "$dir/serve_workers.txt"
  if [ -f "$dir/.serve_anchor_ok" ]; then
    return 0
  fi
  # XLA_FLAGS cleared like every gate pytest line: a serve evidence run
  # exports forced host devices, and an inherited count breaks
  # tests/conftest.py's device assert during collection.
  if timeout --kill-after=30 900 \
       env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu R2D2DPG_PALLAS_INTERPRET=1 \
       XLA_FLAGS= \
       python -m pytest tests/test_serve_router.py tests/test_serve_cli.py \
         -q -p no:cacheprovider -m 'not slow' \
         -k 'bit_identical or affine or rendezvous' \
       > "$dir/serve_gate.log" 2>&1; then
    touch "$dir/.serve_anchor_ok"
    return 0
  fi
  return 1
}

gate_on_box() {
  local artifact="$1" extra="${2:-}"
  while pgrep -f "$TRAIN_PAT" > /dev/null \
     || { [ -n "$extra" ] && pgrep -f "$extra" > /dev/null; }; do
    if pgrep -f "$CAMPAIGN_PAT" > /dev/null; then
      echo "TPU campaign owns the box; skipping $(date)"
      return 1
    fi
    sleep 60
  done
  if pgrep -f "$CAMPAIGN_PAT" > /dev/null \
     || { [ -n "$artifact" ] && [ -f "$artifact" ]; }; then
    echo "TPU campaign owns/owned the box; skipping $(date)"
    return 1
  fi
  return 0
}
