// Native batched MuJoCo environment pool for the DM-Control suite tasks the
// BASELINE configs need (walker, cheetah, humanoid — state observations).
//
// Reference parity: the reference's actor fleet is N Python processes each
// stepping one env through dm_control's Python layer (SURVEY.md §2.3, §3.2
// hot loop A).  This pool is the TPU-native runtime equivalent: one C++
// shared library owning E mjData instances over a single shared mjModel,
// stepping them on a persistent worker-thread pool, with task observation /
// reward / reset logic implemented in C++ against the MuJoCo C API.  Python
// is out of the per-step path entirely — the host boundary is one ctypes
// call per *batch* step (driven from JAX via `io_callback`; see
// r2d2dpg_tpu/envs/dmc_host.py).
//
// Fidelity contract (verified bit-for-bit by tests/test_native_pool.py):
// the step sequence reproduces dm_control's `legacy_step` Euler semantics —
// `mj_step2; mj_step(n-1); mj_step1` — so from identical (qpos, qvel,
// qacc_warmstart) and identical actions, trajectories, observations and
// rewards match dm_control's exactly.  Episode-reset randomization follows
// the same rules as dm_control's `randomize_limited_and_rotational_joints`
// (uniform in range for limited hinge/slide, uniform [-pi, pi] for
// unlimited hinges, uniform unit quaternion for free-joint orientations)
// with a per-env C++ RNG, so reset *distributions* match while draws differ.
//
// Note on actuation-disabled resets: dm_control wraps its reset-time
// `mj_forward` calls in a disable-actuation scope.  All suite models used
// here have pure <motor> actuators (force = gain * ctrl, ctrl zeroed by
// mj_resetData), for which actuation-disabled and ctrl==0 forwards are
// identical, so no model flag mutation is needed — which keeps the shared
// mjModel safely immutable across worker threads.

#include <mujoco/mujoco.h>

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

enum TaskId {
  kWalkerStand = 0,
  kWalkerWalk = 1,
  kWalkerRun = 2,
  kCheetahRun = 3,
  kHumanoidStand = 4,
  kHumanoidWalk = 5,
  kHumanoidRun = 6,
};

// ---------------------------------------------------------------- rewards
// dm_control.utils.rewards.tolerance, specialized to the sigmoids the suite
// tasks use (gaussian / linear / quadratic).

double SigmoidGaussian(double x, double value_at_1) {
  const double scale = std::sqrt(-2.0 * std::log(value_at_1));
  return std::exp(-0.5 * (x * scale) * (x * scale));
}

double SigmoidLinear(double x, double value_at_1) {
  const double scaled = x * (1.0 - value_at_1);
  return std::abs(scaled) < 1.0 ? 1.0 - scaled : 0.0;
}

double SigmoidQuadratic(double x, double value_at_1) {
  const double scaled = x * std::sqrt(1.0 - value_at_1);
  return std::abs(scaled) < 1.0 ? 1.0 - scaled * scaled : 0.0;
}

enum Sigmoid { kGaussian, kLinear, kQuadratic };

double Tolerance(double x, double lower, double upper, double margin,
                 Sigmoid sigmoid = kGaussian, double value_at_margin = 0.1) {
  const bool in_bounds = lower <= x && x <= upper;
  if (margin == 0.0) return in_bounds ? 1.0 : 0.0;
  if (in_bounds) return 1.0;
  const double d = (x < lower ? lower - x : x - upper) / margin;
  switch (sigmoid) {
    case kGaussian:
      return SigmoidGaussian(d, value_at_margin);
    case kLinear:
      return SigmoidLinear(d, value_at_margin);
    case kQuadratic:
      return SigmoidQuadratic(d, value_at_margin);
  }
  return 0.0;
}

// ------------------------------------------------------------------- pool

struct EnvSlot {
  mjData* data = nullptr;
  std::mt19937_64 rng;
  int step_count = 0;
};

struct Pool {
  mjModel* model = nullptr;
  TaskId task;
  double move_speed = 0.0;  // walker/humanoid tasks
  int num_envs = 0;
  int nsub = 1;        // physics substeps per control step
  int step_limit = 0;  // control steps per episode
  int obs_dim = 0;
  int n_threads = 1;   // resolved worker count (min(max(1,hw), num_envs))

  // Model lookups resolved once at creation.
  int torso_body = -1;
  int head_body = -1;
  int limb_bodies[4] = {-1, -1, -1, -1};  // left_hand, left_foot, right_hand, right_foot
  int subtreelinvel_adr = -1;

  std::vector<EnvSlot> envs;

  // Persistent worker threads: one dispatch per batch call, envs claimed via
  // an atomic counter so uneven step costs balance across workers.
  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv_work, cv_done;
  std::function<void(int)> job;
  std::atomic<int> next_env{0};
  int64_t generation = 0;
  int active = 0;
  bool shutdown = false;

  ~Pool() {
    {
      std::lock_guard<std::mutex> lock(mu);
      shutdown = true;
    }
    cv_work.notify_all();
    for (auto& t : workers) t.join();
    for (auto& e : envs)
      if (e.data) mj_deleteData(e.data);
    if (model) mj_deleteModel(model);
  }

  void WorkerLoop() {
    int64_t seen = 0;
    for (;;) {
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_work.wait(lock, [&] { return shutdown || generation != seen; });
        if (shutdown) return;
        seen = generation;
      }
      for (;;) {
        const int i = next_env.fetch_add(1);
        if (i >= num_envs) break;
        job(i);
      }
      {
        std::lock_guard<std::mutex> lock(mu);
        if (--active == 0) cv_done.notify_one();
      }
    }
  }

  void RunBatch(std::function<void(int)> fn) {
    if (workers.empty()) {
      for (int i = 0; i < num_envs; ++i) fn(i);
      return;
    }
    std::unique_lock<std::mutex> lock(mu);
    job = std::move(fn);
    next_env.store(0);
    active = static_cast<int>(workers.size());
    ++generation;
    cv_work.notify_all();
    cv_done.wait(lock, [&] { return active == 0; });
  }
};

double UniformDouble(std::mt19937_64& rng, double lo, double hi) {
  return lo + (hi - lo) * std::uniform_real_distribution<double>(0.0, 1.0)(rng);
}

// dm_control.suite.utils.randomizers.randomize_limited_and_rotational_joints:
// limited hinge/slide -> uniform in range; unlimited hinge -> uniform
// [-pi, pi]; free-joint orientation -> normalized uniform rand(4) (keeping
// dm_control's rand-not-randn choice); free-joint translation untouched.
void RandomizeJoints(const mjModel* m, mjData* d, std::mt19937_64& rng) {
  for (int j = 0; j < m->njnt; ++j) {
    const int adr = m->jnt_qposadr[j];
    const int type = m->jnt_type[j];
    const bool limited = m->jnt_limited[j] != 0;
    const double lo = m->jnt_range[2 * j], hi = m->jnt_range[2 * j + 1];
    if (limited) {
      if (type == mjJNT_HINGE || type == mjJNT_SLIDE) {
        d->qpos[adr] = UniformDouble(rng, lo, hi);
      } else if (type == mjJNT_BALL) {
        double axis[3], quat[4];
        std::normal_distribution<double> normal;
        for (double& a : axis) a = normal(rng);
        mju_normalize3(axis);
        const double angle = UniformDouble(rng, 0.0, hi);
        mju_axisAngle2Quat(quat, axis, angle);
        mju_copy4(d->qpos + adr, quat);
      }
    } else {
      if (type == mjJNT_HINGE) {
        d->qpos[adr] = UniformDouble(rng, -mjPI, mjPI);
      } else if (type == mjJNT_BALL || type == mjJNT_FREE) {
        const int qadr = type == mjJNT_FREE ? adr + 3 : adr;
        double quat[4];
        if (type == mjJNT_FREE) {
          for (double& q : quat)
            q = std::uniform_real_distribution<double>(0.0, 1.0)(rng);
        } else {
          std::normal_distribution<double> normal;
          for (double& q : quat) q = normal(rng);
        }
        mju_normalize4(quat);
        mju_copy4(d->qpos + qadr, quat);
      }
    }
  }
}

// dm_control legacy_step Euler semantics: the state invariant is "mj_step1
// has already run"; a control step is mj_step2 + mj_step(n-1) + mj_step1.
void LegacyStep(const mjModel* m, mjData* d, int nsub) {
  mj_step2(m, d);
  for (int s = 1; s < nsub; ++s) mj_step(m, d);
  mj_step1(m, d);
}

// ------------------------------------------------------- task definitions

void ResetEnv(Pool* p, int i) {
  EnvSlot& e = p->envs[i];
  const mjModel* m = p->model;
  mjData* d = e.data;
  mj_resetData(m, d);
  mj_forward(m, d);
  switch (p->task) {
    case kWalkerStand:
    case kWalkerWalk:
    case kWalkerRun:
      RandomizeJoints(m, d, e.rng);
      break;
    case kCheetahRun: {
      // qpos for limited joints uniform in range, then settle 200 control
      // steps (cheetah has nsub == 1) and rewind the clock — reproducing
      // dm_control's Cheetah.initialize_episode call-for-call.
      for (int j = 0; j < m->njnt; ++j)
        if (m->jnt_limited[j])
          d->qpos[m->jnt_qposadr[j]] =
              UniformDouble(e.rng, m->jnt_range[2 * j], m->jnt_range[2 * j + 1]);
      LegacyStep(m, d, 200);
      d->time = 0.0;
      break;
    }
    case kHumanoidStand:
    case kHumanoidWalk:
    case kHumanoidRun:
      // Rejection-sample a collision-free configuration.
      do {
        RandomizeJoints(m, d, e.rng);
        mj_forward(m, d);
      } while (d->ncon > 0);
      break;
  }
  mj_forward(m, d);  // dm_control's after_reset
  e.step_count = 0;
}

void WriteObs(const Pool* p, int i, float* out) {
  const mjModel* m = p->model;
  const mjData* d = p->envs[i].data;
  int k = 0;
  switch (p->task) {
    case kWalkerStand:
    case kWalkerWalk:
    case kWalkerRun:
      // orientations: xmat xx & xz of every non-world body; height: torso z;
      // velocity: qvel.  (dm_control walker.py get_observation order.)
      for (int b = 1; b < m->nbody; ++b) {
        out[k++] = static_cast<float>(d->xmat[9 * b + 0]);
        out[k++] = static_cast<float>(d->xmat[9 * b + 2]);
      }
      out[k++] = static_cast<float>(d->xpos[3 * p->torso_body + 2]);
      for (int v = 0; v < m->nv; ++v)
        out[k++] = static_cast<float>(d->qvel[v]);
      break;
    case kCheetahRun:
      // position: qpos[1:] (translation-invariant); velocity: qvel.
      for (int q = 1; q < m->nq; ++q)
        out[k++] = static_cast<float>(d->qpos[q]);
      for (int v = 0; v < m->nv; ++v)
        out[k++] = static_cast<float>(d->qvel[v]);
      break;
    case kHumanoidStand:
    case kHumanoidWalk:
    case kHumanoidRun: {
      // joint_angles, head_height, extremities, torso_vertical,
      // com_velocity, velocity  (dm_control humanoid.py get_observation).
      for (int q = 7; q < m->nq; ++q)
        out[k++] = static_cast<float>(d->qpos[q]);
      out[k++] = static_cast<float>(d->xpos[3 * p->head_body + 2]);
      const double* tf = d->xmat + 9 * p->torso_body;
      const double* tp = d->xpos + 3 * p->torso_body;
      for (const int body : p->limb_bodies) {
        const double* lp = d->xpos + 3 * body;
        const double v[3] = {lp[0] - tp[0], lp[1] - tp[1], lp[2] - tp[2]};
        // torso_to_limb.dot(torso_frame): out[j] = sum_i v[i] * tf[3i + j].
        for (int col = 0; col < 3; ++col)
          out[k++] = static_cast<float>(v[0] * tf[col] + v[1] * tf[3 + col] +
                                        v[2] * tf[6 + col]);
      }
      for (int col = 6; col < 9; ++col)  // zx, zy, zz
        out[k++] = static_cast<float>(tf[col]);
      for (int s = 0; s < 3; ++s)
        out[k++] = static_cast<float>(d->sensordata[p->subtreelinvel_adr + s]);
      for (int v = 0; v < m->nv; ++v)
        out[k++] = static_cast<float>(d->qvel[v]);
      break;
    }
  }
}

double ComputeReward(const Pool* p, int i) {
  const mjModel* m = p->model;
  const mjData* d = p->envs[i].data;
  switch (p->task) {
    case kWalkerStand:
    case kWalkerWalk:
    case kWalkerRun: {
      const double height = d->xpos[3 * p->torso_body + 2];
      const double upright_zz = d->xmat[9 * p->torso_body + 8];
      const double standing =
          Tolerance(height, 1.2, mjMAXVAL, 1.2 / 2.0);  // _STAND_HEIGHT
      const double upright = (1.0 + upright_zz) / 2.0;
      const double stand_reward = (3.0 * standing + upright) / 4.0;
      if (p->move_speed == 0.0) return stand_reward;
      const double hvel = d->sensordata[p->subtreelinvel_adr + 0];
      const double move = Tolerance(hvel, p->move_speed, mjMAXVAL,
                                    p->move_speed / 2.0, kLinear, 0.5);
      return stand_reward * (5.0 * move + 1.0) / 6.0;
    }
    case kCheetahRun: {
      const double speed = d->sensordata[p->subtreelinvel_adr + 0];
      return Tolerance(speed, 10.0, mjMAXVAL, 10.0, kLinear, 0.0);
    }
    case kHumanoidStand:
    case kHumanoidWalk:
    case kHumanoidRun: {
      const double head_height = d->xpos[3 * p->head_body + 2];
      const double upright_zz = d->xmat[9 * p->torso_body + 8];
      const double standing =
          Tolerance(head_height, 1.4, mjMAXVAL, 1.4 / 4.0);  // _STAND_HEIGHT
      const double upright =
          Tolerance(upright_zz, 0.9, mjMAXVAL, 1.9, kLinear, 0.0);
      const double stand_reward = standing * upright;
      double small_control = 0.0;
      for (int u = 0; u < m->nu; ++u)
        small_control +=
            Tolerance(d->ctrl[u], 0.0, 0.0, 1.0, kQuadratic, 0.0);
      small_control = (4.0 + small_control / m->nu) / 5.0;
      const double* cv = d->sensordata + p->subtreelinvel_adr;
      if (p->move_speed == 0.0) {
        const double dont_move = (Tolerance(cv[0], 0.0, 0.0, 2.0) +
                                  Tolerance(cv[1], 0.0, 0.0, 2.0)) /
                                 2.0;
        return small_control * stand_reward * dont_move;
      }
      const double com_speed = std::sqrt(cv[0] * cv[0] + cv[1] * cv[1]);
      const double move = Tolerance(com_speed, p->move_speed, mjMAXVAL,
                                    p->move_speed, kLinear, 0.0);
      return small_control * stand_reward * (5.0 * move + 1.0) / 6.0;
    }
  }
  return 0.0;
}

struct StepOut {
  float* obs;
  float* reward;
  float* discount;
  float* reset;
};

void StepEnv(Pool* p, int i, const float* actions, int repeat,
             const StepOut& out) {
  EnvSlot& e = p->envs[i];
  const mjModel* m = p->model;
  mjData* d = e.data;
  const float* act = actions + static_cast<int64_t>(i) * m->nu;
  // Action repeat: apply the same control for `repeat` control steps,
  // summing rewards (the DM-Control wrapper convention — episode return
  // keeps its 0..1000 scale), stopping at the episode boundary so a fresh
  // episode never sees the stale action.
  double reward = 0.0;
  bool last = false;
  for (int r = 0; r < repeat && !last; ++r) {
    for (int u = 0; u < m->nu; ++u) d->ctrl[u] = static_cast<double>(act[u]);
    LegacyStep(m, d, p->nsub);
    e.step_count += 1;
    reward += ComputeReward(p, i);
    // Suite walker/cheetah/humanoid tasks never terminate early
    // (get_termination is always None): discount is 1 and episodes end only
    // at the step limit, where the env auto-resets and flags the fresh obs.
    last = e.step_count >= p->step_limit;
  }
  if (last) ResetEnv(p, i);
  WriteObs(p, i, out.obs + static_cast<int64_t>(i) * p->obs_dim);
  out.reward[i] = static_cast<float>(reward);
  out.discount[i] = 1.0f;
  out.reset[i] = last ? 1.0f : 0.0f;
}

int LookupBody(const mjModel* m, const char* name) {
  return mj_name2id(m, mjOBJ_BODY, name);
}

}  // namespace

// ----------------------------------------------------------- C interface

extern "C" {

void* envpool_create(const char* xml_path, int task_id, int num_envs,
                     int num_threads, const int64_t* seeds, char* err,
                     int err_len) {
  char load_err[512] = {0};
  mjModel* model = mj_loadXML(xml_path, nullptr, load_err, sizeof(load_err));
  if (!model) {
    std::snprintf(err, err_len, "mj_loadXML(%s): %s", xml_path, load_err);
    return nullptr;
  }
  Pool* p = new Pool;
  p->model = model;
  p->task = static_cast<TaskId>(task_id);
  p->num_envs = num_envs;

  double control_dt = 0.0;  // 0 -> one physics step per control step
  switch (p->task) {
    case kWalkerStand:
      control_dt = 0.025;
      break;
    case kWalkerWalk:
      control_dt = 0.025;
      p->move_speed = 1.0;
      break;
    case kWalkerRun:
      control_dt = 0.025;
      p->move_speed = 8.0;
      break;
    case kCheetahRun:
      break;
    case kHumanoidStand:
      control_dt = 0.025;
      break;
    case kHumanoidWalk:
      control_dt = 0.025;
      p->move_speed = 1.0;
      break;
    case kHumanoidRun:
      control_dt = 0.025;
      p->move_speed = 10.0;
      break;
  }
  const double dt = model->opt.timestep;
  p->nsub = control_dt > 0.0 ? static_cast<int>(std::lround(control_dt / dt)) : 1;
  const double time_limit =
      (p->task == kCheetahRun) ? 10.0 : 25.0;  // suite _DEFAULT_TIME_LIMITs
  p->step_limit = static_cast<int>(std::lround(time_limit / (dt * p->nsub)));

  p->torso_body = LookupBody(model, "torso");
  p->head_body = LookupBody(model, "head");
  const char* limbs[4] = {"left_hand", "left_foot", "right_hand", "right_foot"};
  for (int j = 0; j < 4; ++j) p->limb_bodies[j] = LookupBody(model, limbs[j]);
  const int sensor =
      mj_name2id(model, mjOBJ_SENSOR, "torso_subtreelinvel");
  p->subtreelinvel_adr = sensor >= 0 ? model->sensor_adr[sensor] : -1;

  switch (p->task) {
    case kWalkerStand:
    case kWalkerWalk:
    case kWalkerRun:
      p->obs_dim = 2 * (model->nbody - 1) + 1 + model->nv;
      break;
    case kCheetahRun:
      p->obs_dim = (model->nq - 1) + model->nv;
      break;
    default:
      p->obs_dim = (model->nq - 7) + 1 + 12 + 3 + 3 + model->nv;
      break;
  }

  p->envs.resize(num_envs);
  for (int i = 0; i < num_envs; ++i) {
    p->envs[i].data = mj_makeData(model);
    if (!p->envs[i].data) {
      std::snprintf(err, err_len, "mj_makeData failed for env %d", i);
      delete p;
      return nullptr;
    }
    p->envs[i].rng.seed(static_cast<uint64_t>(seeds[i]));
  }

  const int hw = static_cast<int>(std::thread::hardware_concurrency());
  int threads = num_threads > 0 ? num_threads : std::max(1, hw);
  threads = std::min(threads, num_envs);
  p->n_threads = threads;
  if (threads > 1)
    for (int t = 0; t < threads; ++t)
      p->workers.emplace_back([p] { p->WorkerLoop(); });
  return p;
}

void envpool_destroy(void* h) { delete static_cast<Pool*>(h); }

int envpool_obs_dim(void* h) { return static_cast<Pool*>(h)->obs_dim; }
int envpool_action_dim(void* h) { return static_cast<Pool*>(h)->model->nu; }
int envpool_episode_len(void* h) { return static_cast<Pool*>(h)->step_limit; }
int envpool_nq(void* h) { return static_cast<Pool*>(h)->model->nq; }
int envpool_nv(void* h) { return static_cast<Pool*>(h)->model->nv; }
// Resolved worker-thread count — benchmarks divide pool throughput by this
// for the per-core ceiling rather than re-deriving the formula in Python.
int envpool_num_threads(void* h) { return static_cast<Pool*>(h)->n_threads; }

void envpool_seed(void* h, const int64_t* seeds) {
  Pool* p = static_cast<Pool*>(h);
  for (int i = 0; i < p->num_envs; ++i)
    p->envs[i].rng.seed(static_cast<uint64_t>(seeds[i]));
}

void envpool_reset_all(void* h, float* obs, float* reward, float* discount,
                       float* reset) {
  Pool* p = static_cast<Pool*>(h);
  p->RunBatch([p, obs](int i) {
    ResetEnv(p, i);
    WriteObs(p, i, obs + static_cast<int64_t>(i) * p->obs_dim);
  });
  for (int i = 0; i < p->num_envs; ++i) {
    reward[i] = 0.0f;
    discount[i] = 1.0f;
    reset[i] = 1.0f;
  }
}

void envpool_step(void* h, const float* actions, int repeat, float* obs,
                  float* reward, float* discount, float* reset) {
  Pool* p = static_cast<Pool*>(h);
  const StepOut out{obs, reward, discount, reset};
  p->RunBatch(
      [p, actions, repeat, &out](int i) { StepEnv(p, i, actions, repeat, out); });
}

// --------------------------- test hooks (state sync for parity checks)

void envpool_get_state(void* h, int i, double* qpos, double* qvel) {
  Pool* p = static_cast<Pool*>(h);
  const mjData* d = p->envs[i].data;
  std::memcpy(qpos, d->qpos, sizeof(double) * p->model->nq);
  std::memcpy(qvel, d->qvel, sizeof(double) * p->model->nv);
}

void envpool_set_state(void* h, int i, const double* qpos, const double* qvel,
                       const double* qacc_warmstart) {
  Pool* p = static_cast<Pool*>(h);
  mjData* d = p->envs[i].data;
  std::memcpy(d->qpos, qpos, sizeof(double) * p->model->nq);
  std::memcpy(d->qvel, qvel, sizeof(double) * p->model->nv);
  if (qacc_warmstart)
    std::memcpy(d->qacc_warmstart, qacc_warmstart,
                sizeof(double) * p->model->nv);
  mj_forward(p->model, d);
  p->envs[i].step_count = 0;
}

double envpool_reward_of(void* h, int i) {
  return ComputeReward(static_cast<Pool*>(h), i);
}

void envpool_obs_of(void* h, int i, float* obs) {
  WriteObs(static_cast<Pool*>(h), i, obs);
}

}  // extern "C"
