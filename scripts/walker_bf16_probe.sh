#!/bin/bash
# bf16 learning-parity evidence for config #3 (VERDICT r2 next #7).
#
# Mirrors runs/walker_probe_nstep3 — the WINNING plateau probe (final
# 20-ep eval 351.7 @ ~330k steps; seed 3, 16 envs, 1:20 ratio, 85 min,
# --n-step 3) — with only --compute-dtype bfloat16 changed, so the two
# curves are a controlled dtype A/B on the nstep3 recipe (NOT the full
# north-star flag set: the on-chip run adds --sigma-max 0.8, which has no
# fp32 control arm at this regime — the dtype call rests on the
# controlled pair).  If the bf16 curve matches fp32 (as it did on
# pendulum, docs/RESULTS.md), WALKER_R2D2's compute_dtype default flips
# to bfloat16 and bench.py's headline follows (~31k steps/s/chip
# measured round 2).
#
# Queued behind the other evidence drivers; preemptible by the TPU
# campaign (on-chip walker30_bf16 supersedes this CPU A/B).
HERE="$(cd "$(dirname "$0")" && pwd)"
cd "$HERE/.."
mkdir -p runs
exec >> runs/walker_bf16_probe.log 2>&1
source "$HERE/lib_gate.sh" || exit 1

DIR=runs/walker_probe_bf16
for attempt in 1 2 3; do
  if [ -f "$DIR/.done" ]; then
    echo "walker_bf16_probe: already done; exiting $(date)"
    exit 0
  fi
  # The on-chip bf16 run supersedes this CPU A/B entirely.
  if [ -f runs/tpu/walker30_bf16/.done ]; then
    echo "walker_bf16_probe: on-chip bf16 walker landed; skipping $(date)"
    exit 0
  fi
  wait_on_box "walker_probe\.sh|cheetah_mitigation\.sh"
  echo "=== walker_bf16_probe attempt $attempt start $(date) ==="
  rm -rf "$DIR"
  mkdir -p "$DIR"
  nice -n 19 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu R2D2DPG_PALLAS_INTERPRET=1 \
  python -m r2d2dpg_tpu.train --config walker_r2d2 --compute-dtype bfloat16 \
    --num-envs 16 --learner-steps 16 --batch-size 64 --min-replay 300 \
    --n-step 3 \
    --seed 3 --minutes 85 --log-every 10 --eval-every 150 --eval-envs 5 \
    --logdir "$DIR" --checkpoint-dir "$DIR/ckpt" \
    --checkpoint-every 150 > "$DIR/stdout.log" 2>&1
  rc=$?
  echo "=== walker_bf16_probe attempt $attempt train done rc=$rc $(date) ==="
  if [ $rc -eq 0 ] && [ -d "$DIR/ckpt" ] && [ -n "$(ls "$DIR/ckpt" 2>/dev/null)" ]; then
    wait_on_box "walker_probe\.sh|cheetah_mitigation\.sh"
    timeout --kill-after=30 --signal=TERM 1800 \
      env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu R2D2DPG_PALLAS_INTERPRET=1 \
      python -m r2d2dpg_tpu.eval --config walker_r2d2 --compute-dtype bfloat16 \
        --checkpoint-dir "$DIR/ckpt" --episodes 10 --rounds 2 \
        > "$DIR/final_eval.jsonl" 2> "$DIR/final_eval.stderr.log" \
      && tail -1 "$DIR/final_eval.jsonl" > "$DIR/final_eval.json" \
      && touch "$DIR/.done" \
      || echo "walker_bf16_probe eval FAILED"
  fi
done
