"""Pipelined collect/learn executor (training/pipeline.py).

The determinism test is the correctness anchor the ISSUE demands: the
``pipeline=off`` schedule must be BIT-identical to the phase-locked
``Trainer.run`` at a fixed seed — scripts/lib_gate.sh refuses to bless
pipelined evidence run dirs unless this test passes.
"""

import dataclasses
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2dpg_tpu.configs import PENDULUM_TINY
from r2d2dpg_tpu.training.pipeline import (
    PipelineConfig,
    PipelineExecutor,
    merge_state,
    split_state,
)

pytestmark = pytest.mark.pipeline

N_PHASES = 14  # PENDULUM_TINY: 2 warm + 2 fill + 10 train
LOG_EVERY = 3  # off-cadence vs N_PHASES so mid-run drains are exercised


def _leaves_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return [
        i
        for i, (x, y) in enumerate(zip(la, lb))
        if not np.array_equal(np.asarray(x), np.asarray(y))
    ]


def test_pipeline_off_determinism_bit_identical(phase_locked_reference_k10):
    """pipeline=off == the phase-locked schedule, leaf-for-leaf bitwise.

    Log cadence included: pop_episode_metrics drains device accumulators,
    so a cadence mismatch between the executor and Trainer.run would show
    up as differing state.  The reference half is the shared session
    fixture (tests/conftest.py) — this pairing keeps it honest."""
    assert (N_PHASES, LOG_EVERY) == (14, 3)  # == warm 2 + fill 2 + 10, k10
    s1 = phase_locked_reference_k10

    t2 = PENDULUM_TINY.build()
    ex = PipelineExecutor(t2, PipelineConfig(enabled=False))
    s2 = ex.run(N_PHASES, log_every=LOG_EVERY, log_fn=lambda *_: None)

    bad = _leaves_equal(s1, s2)
    assert not bad, f"state diverged at leaves {bad}"


def test_split_merge_round_trip():
    """merge(split(state)) preserves every leaf except the forked RNG."""
    t = PENDULUM_TINY.build()
    state = t.init()
    ref = jax.tree_util.tree_map(jnp.copy, state)  # init aliases survive split
    cstate, lstate = split_state(state)
    merged = merge_state(state, cstate, lstate, behavior_params=ref.behavior_params)

    stripped = lambda s: dataclasses.replace(s, rng=jnp.zeros(2, jnp.uint32))  # noqa: E731
    bad = _leaves_equal(stripped(ref), stripped(merged))
    assert not bad, f"leaves changed through split/merge: {bad}"
    # The two sides got INDEPENDENT streams, both distinct from the parent.
    assert not np.array_equal(np.asarray(cstate.rng), np.asarray(lstate.rng))
    assert not np.array_equal(np.asarray(cstate.rng), np.asarray(ref.rng))


def test_pipelined_executor_makes_progress():
    """Pipelined mode: same phase counts/data ratio as the schedule asks."""
    cfg = PENDULUM_TINY
    t = cfg.build()
    ex = PipelineExecutor(t, PipelineConfig(enabled=True, queue_depth=2))
    logged = []
    s = ex.run(
        N_PHASES,
        log_every=LOG_EVERY,
        metrics_fn=lambda phase, scalars: logged.append((phase, scalars)),
    )
    warm, fill = t.window_fill_phases, t.replay_fill_phases
    n_train = N_PHASES - warm - fill
    tc = cfg.trainer
    assert int(s.train.step) == n_train * tc.learner_steps
    assert int(s.env_steps) == N_PHASES * tc.stride * tc.num_envs
    # One emit per fill/train phase, all absorbed by the drain programs.
    assert int(t.arena.size(s.arena)) == (fill + n_train) * tc.num_envs
    stats = ex.stats()
    assert stats["train_phases"] == n_train
    assert 0.0 <= stats["overlap_fraction"] <= 1.0
    assert stats["learner_steps_per_sec"] > 0
    # The log cadence fired through the batched async fetch path.
    assert [p for p, _ in logged] == [
        p for p in range(1, N_PHASES + 1) if p % LOG_EVERY == 0
    ]
    for _, scalars in logged:
        assert "env_steps" in scalars and "episode_return_mean" in scalars


def test_prefetch_learn_matches_sequential_batches():
    """Double-buffered sampling draws the same batch keys; only the
    priorities sampled against may be one write-back stale.  With priority
    updates disabled (uniform replay) the two paths are bit-identical."""
    cfg = dataclasses.replace(
        PENDULUM_TINY,
        trainer=dataclasses.replace(
            PENDULUM_TINY.trainer, prioritized=False, learner_steps=3
        ),
    )
    t = cfg.build()
    s = t.run(6, log_every=0)  # through fill + a couple of train phases
    key = jax.random.PRNGKey(7)
    seq_train, seq_arena, seq_m = t._learn_many(s.train, s.arena, key)
    pre_train, pre_arena, pre_m = t._learn_many(
        s.train, s.arena, key, prefetch=True
    )
    assert not _leaves_equal(seq_train, pre_train)
    assert not _leaves_equal(seq_m, pre_m)


def test_prefetch_learn_prioritized_progresses():
    """Prioritized prefetch path: runs, finite metrics, priorities move."""
    t = PENDULUM_TINY.build()
    s = t.run(6, log_every=0)
    key = jax.random.PRNGKey(3)
    train, arena, metrics = t._learn_many(s.train, s.arena, key, prefetch=True)
    assert int(train.step) == int(s.train.step) + t.config.learner_steps
    assert np.isfinite(float(metrics["critic_loss"]))
    assert not np.array_equal(
        np.asarray(arena.priority), np.asarray(s.arena.priority)
    )


def test_staged_add_matches_add():
    from r2d2dpg_tpu.replay.arena import StagedSequences

    t = PENDULUM_TINY.build()
    s = t.run(5, log_every=0)
    from r2d2dpg_tpu.training.assembler import emit

    seq = emit(s.window)
    prios = jnp.arange(1.0, 1.0 + t.config.num_envs)
    direct = t.arena.add(s.arena, seq, prios)
    staged = t.arena.add_staged(
        s.arena, StagedSequences(seq=seq, priorities=prios)
    )
    assert not _leaves_equal(direct, staged)
    with pytest.raises(ValueError, match="resolved priorities"):
        t.arena.add_staged(s.arena, StagedSequences(seq=seq, priorities=None))


def test_executor_rejects_shard_map_trainers():
    fake = types.SimpleNamespace(axis="dp")
    with pytest.raises(ValueError, match="shard_map"):
        PipelineExecutor(fake)


@pytest.mark.slow
def test_pipelined_overlap_smoke():
    """Overlap smoke (ISSUE 2 satellite): collector and learner threads both
    make progress across a longer pipelined run, the staleness bound holds
    (same phase counts as phase-locked), and wait instrumentation filled."""
    from r2d2dpg_tpu.configs import PENDULUM_R2D2

    cfg = dataclasses.replace(
        PENDULUM_R2D2,
        trainer=dataclasses.replace(
            PENDULUM_R2D2.trainer,
            num_envs=2,
            min_replay=4,
            capacity=128,
            param_sync_every=2,
        ),
    )
    t = cfg.build()
    ex = PipelineExecutor(t, PipelineConfig(enabled=True, queue_depth=3))
    warm, fill = t.window_fill_phases, t.replay_fill_phases
    n_train = 12
    s = ex.run(warm + fill + n_train, log_every=0)
    tc = cfg.trainer
    assert int(s.train.step) == n_train * tc.learner_steps  # learner progressed
    assert int(s.env_steps) == (warm + fill + n_train) * tc.stride * tc.num_envs
    stats = ex.stats()
    assert stats["train_phases"] == n_train
    # Both stages were measured every phase: the queue mediated every batch.
    assert ex.learner_wait.count == n_train + 1  # + the sentinel wait
    assert ex.collect_wait.count == n_train
    assert 0.0 <= stats["overlap_fraction"] <= 1.0
