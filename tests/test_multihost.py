"""Multi-host (multi-process) training of host-pool envs (SURVEY.md §5.8).

Launches TWO real OS processes joined through ``jax.distributed`` on CPU
(2 virtual devices each -> a 4-device global dp mesh) and runs warm-up,
fill and train phases of ``HostSPMDTrainer`` at tiny walker shapes: each
process owns a 2-env MuJoCo pool, fresh observations re-enter the mesh via
``jax.make_array_from_process_local_data``, and the jitted phases execute
as lockstep SPMD with gradient sync over the simulated DCN.

This is the closest a single box gets to a pod: real process boundary, real
collective runtime, real per-host env pools.
"""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # two concurrent JAX compiles on one core

_WORKER = r"""
import dataclasses, os, sys

import jax

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["COORD"],
    num_processes=2,
    process_id=int(os.environ["RANK"]),
)
assert jax.process_count() == 2
assert len(jax.devices()) == 4  # 2 local x 2 processes

import numpy as np

from r2d2dpg_tpu.configs import WALKER_R2D2
from r2d2dpg_tpu.parallel import DP_AXIS, HostSPMDTrainer, make_mesh

cfg = dataclasses.replace(
    WALKER_R2D2,
    trainer=dataclasses.replace(
        WALKER_R2D2.trainer,
        num_envs=4,       # 2 per process
        stride=4,
        batch_size=4,
        capacity=64,
        min_replay=4,
        learner_steps=1,
        overlap_learner=bool(int(os.environ.get("OVERLAP", "0"))),
    ),
    hidden=32,
    agent=dataclasses.replace(WALKER_R2D2.agent, burnin=2, unroll=4, n_step=2),
)
mesh = make_mesh(4)
trainer = cfg.build_spmd(mesh)
assert isinstance(trainer, HostSPMDTrainer)
assert trainer._nproc == 2

state = trainer.init()
# The fleet is laid out over the GLOBAL mesh; this process addresses only
# its half of the rows.
assert state.obs.shape[0] == 4
assert sum(s.data.shape[0] for s in state.obs.addressable_shards) == 2

for _ in range(trainer.window_fill_phases):
    state = trainer.collect_phase(state)
state = trainer.fill_phase(state)
assert int(trainer.arena.size(state.arena)) == 4
state, metrics = trainer.train_phase(state)
assert int(state.train.step) == 1
for k, v in metrics.items():
    assert np.isfinite(float(v)), (k, metrics)
assert int(state.env_steps) == (trainer.window_fill_phases + 2) * 4 * 4

# Params identical across the global mesh after the synced update.
leaf = jax.tree_util.tree_leaves(state.train.critic_params)[0]
assert leaf.sharding.is_fully_replicated
shards = [np.asarray(s.data) for s in leaf.addressable_shards]
for other in shards[1:]:
    np.testing.assert_array_equal(shards[0], other)

print(f"RANK{os.environ['RANK']}_OK", flush=True)
"""


_SPMD_WORKER = r"""
import dataclasses, os

import jax

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["COORD"],
    num_processes=2,
    process_id=int(os.environ["RANK"]),
)
assert len(jax.devices()) == 4

import numpy as np

from r2d2dpg_tpu.agents import AgentConfig, R2D2DPG
from r2d2dpg_tpu.configs import PENDULUM_R2D2
from r2d2dpg_tpu.models import ActorNet, CriticNet
from r2d2dpg_tpu.parallel import DP_AXIS, SPMDTrainer, make_mesh

env = PENDULUM_R2D2.env_factory()
agent_cfg = dataclasses.replace(
    PENDULUM_R2D2.agent, burnin=2, unroll=4, n_step=2, axis_name=DP_AXIS
)
agent = R2D2DPG(
    ActorNet(action_dim=env.spec.action_dim, hidden=16, use_lstm=True),
    CriticNet(hidden=16, use_lstm=True),
    agent_cfg,
)
tcfg = dataclasses.replace(
    PENDULUM_R2D2.trainer,
    num_envs=4, stride=4, batch_size=8, capacity=32, min_replay=4,
    learner_steps=1,
)
trainer = SPMDTrainer(env, agent, tcfg, make_mesh(4))
state = trainer.run(
    trainer.window_fill_phases + trainer.replay_fill_phases + 2, log_every=0
)
assert int(state.train.step) == 2
# Gradient pmean crossed the process boundary: params replicated identical.
leaf = jax.tree_util.tree_leaves(state.train.critic_params)[0]
shards = [np.asarray(s.data) for s in leaf.addressable_shards]
for other in shards[1:]:
    np.testing.assert_array_equal(shards[0], other)
print(f"RANK{os.environ['RANK']}_OK", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_two_process(worker: str, extra_env=None):
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["R2D2DPG_PALLAS_INTERPRET"] = "1"
        env["COORD"] = f"127.0.0.1:{port}"
        env["RANK"] = str(rank)
        env.update(extra_env or {})
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", worker],
                env=env,
                cwd=repo,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=900)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multi-process run timed out:\n" + "\n".join(outs))
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
        assert f"RANK{rank}_OK" in out


@pytest.mark.parametrize("overlap", [0, 1])
def test_two_process_host_pool_training(overlap):
    _run_two_process(_WORKER, {"OVERLAP": str(overlap)})


def test_two_process_spmd_training():
    """Pure-JAX env path (shard_map) across a real process boundary."""
    _run_two_process(_SPMD_WORKER)
