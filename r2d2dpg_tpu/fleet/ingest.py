"""Learner-side experience ingest: N actor connections -> one staging queue.

The Ape-X topology's center (PAPERS.md 1803.00933): out-of-process actors
stream ``replay.StagedSequences`` batches over the fleet wire protocol
(``fleet/transport.py``); this server reassembles them onto the SAME
bounded staging queue / ``ReplayArena.add_staged`` path the in-process
pipelined executor uses (``training/pipeline.py``), so fleet experience
enters the arena through the exact drain program local experience does.

Per-connection protocol (one handler thread per actor; that thread is the
connection's ONLY writer, so acks and param pushes never interleave):

    actor                          ingest handler
    -----                          --------------
    HELLO {actor_id, wire...} ->        (wire mismatch: ACK refused_wire
                                         + close — fleet/wire.py)
                              <-   [PARAMS {version, params}]   (if any)
                              <-   ACK {code: ok, param_version}
    SEQS {staged, stats}      ->   staging_queue.put (bounded wait)
                              <-   [PARAMS]     (actor's version is stale)
                              <-   ACK {code: ok | shed_ingest_queue_full}
    TELEM {snapshot}          ->   fold into the obs RemoteMirror under
                                   actor=/host= labels (no ack; malformed
                                   frames drop with a flight event) — the
                                   learner's /metrics is the fleet's ONE
                                   scrape point (ISSUE 6)
    ...
    BYE                       ->   (or either side just closes)

Backpressure/shed contract: the actor blocks on the ACK, so it has at most
one unacknowledged batch in flight; the handler waits ``shed_after_s`` for
queue room and then **sheds loudly** — ``SHED_INGEST`` ack (the actor
counts and keeps collecting), a ``shed`` flight-recorder event, and the
per-actor shed counter.  Experience is the one payload that may be dropped
under pressure: fresher experience is already behind it.

The drain side (``FleetLearner``) runs on the caller's thread and is the
staging queue's single consumer — the single-writer contract
``ReplayArena.add_staged`` enforces (docs/FLEET.md "Single writer").
"""

from __future__ import annotations

import dataclasses
import hmac
import json
import os
import queue
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from r2d2dpg_tpu.fleet import transport, wire
from r2d2dpg_tpu.fleet.transport import (
    HEADER_BYTES,
    K_ACK,
    K_BYE,
    K_HELLO,
    K_PARAMS,
    K_SEQS,
    K_STATS,
    K_TELEM,
    FrameError,
    PeerDeadError,
    pack_obj,
    recv_frame,
    recv_frame_heartbeat,
    send_frame,
    to_host,
    unpack_obj,
)
from r2d2dpg_tpu.obs import flight_event, get_registry, get_remote_mirror
from r2d2dpg_tpu.obs import trace as obs_trace
from r2d2dpg_tpu.obs.device import flops_of, get_device_monitor
from r2d2dpg_tpu.obs.quality import (
    get_quality_plane,
    policy_lags,
    quality_stats_columns,
)
from r2d2dpg_tpu.replay.arena import stack_staged, staged_nbytes
from r2d2dpg_tpu.training.pipeline import (
    LearnerState,
    coalesce_from_queue,
    drain_staged,
    merge_state,
    split_state,
)
from r2d2dpg_tpu.training.trainer import Trainer, TrainerState
from r2d2dpg_tpu.utils.codes import (
    OK,
    REFUSED_AUTH,
    REFUSED_WIRE,
    SHED_INGEST,
)


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Static fleet knobs (the trainer's own config governs the rest)."""

    num_actors: int
    address: str = "127.0.0.1:0"  # "host:port" (0 = ephemeral) | "unix:/path"
    queue_depth: int = 4  # staging-queue capacity, in staged batches
    publish_every: int = 1  # drain phases between param publications
    prefetch: bool = True  # double-buffered sampling in the drain program
    shed_after_s: float = 1.0  # handler waits this long before shedding
    # Sheds are suppressed (handlers wait this long instead) until the
    # first drain-learn has EXECUTED: the drain program's one-time compile
    # takes tens of seconds on a small host, long enough that every
    # actor's pending put used to time out exactly once — the historical
    # "sheds == num_actors" startup artifact (docs/FLEET.md).
    startup_shed_grace_s: float = 120.0
    idle_timeout_s: float = 300.0  # no batch for this long = starved, abort
    max_frame_bytes: int = transport.MAX_FRAME_BYTES
    # The wire fast lane (fleet/wire.py): one encoding/compression per
    # fleet, negotiated at HELLO; actors with a different lane are refused.
    wire: wire.WireConfig = wire.WireConfig()
    # Max queued staged batches stacked into ONE compiled drain call (the
    # arena-add dispatch amortization); 1 = today's one-call-per-batch.
    drain_coalesce: int = 1
    # Liveness (docs/FLEET.md "Failure modes"): per-connection read
    # deadline in seconds — a peer silent past it is PINGed once and
    # reaped on a second silence (transport.recv_frame_heartbeat).  The
    # window between HELLO and the first SEQS frame uses the LARGER of
    # this and ``warmup_deadline_s`` (a fresh actor legitimately goes
    # silent for its collect-program compile).
    heartbeat_s: float = transport.READ_DEADLINE_S
    warmup_deadline_s: float = 120.0
    # Shared-secret HELLO authentication (hmac.compare_digest); None = no
    # auth.  REQUIRED before binding a routable (non-loopback) address on
    # anything but a trusted network.
    auth_token: Optional[str] = None
    # Split-plane wire (ISSUE 17): actors dial their shard DIRECTLY for
    # SEQS (the ingest ack carries the assignment + dialable address),
    # keeping the learner connection as a control plane for HELLO/params/
    # TELEM/accounting.  Requires the standalone shard tier; the actor
    # falls back LOUDLY to learner-forwarded SEQS when the direct dial is
    # refused, partitioned, or the tier is in-learner.
    shard_direct: bool = False
    # Sampling-boundary concurrency (ISSUE 17): N concurrent pullers over
    # M shards (0 = auto: min(shards, 8); 1 = serial, the control leg) and
    # one phase of batch prefetch overlapping the compiled learn step
    # (0 = off — the determinism-anchor default).
    shard_pullers: int = 0
    shard_prefetch: int = 0


class IngestServer:
    """Accepts actor connections and feeds the learner's staging queue."""

    def __init__(
        self,
        staging_queue: "queue.Queue",
        *,
        address: str = "127.0.0.1:0",
        shed_after_s: float = 1.0,
        startup_shed_grace_s: float = 120.0,
        max_frame_bytes: int = transport.MAX_FRAME_BYTES,
        wire_config: Optional[wire.WireConfig] = None,
        read_deadline_s: float = transport.READ_DEADLINE_S,
        warmup_deadline_s: float = 120.0,
        auth_token: Optional[str] = None,
        shards=None,
        expected_actors: Optional[int] = None,
        shard_assignment_fn: Optional[Callable[[str], Any]] = None,
    ):
        self.queue = staging_queue
        # In-network sampling (fleet/sampler.py, ISSUE 10): when a
        # ``ShardSet`` is given, SEQS batches bypass the staging queue —
        # each handler writes straight into its actor's replay shard
        # (consistent-hash routing assigned at HELLO) under that shard's
        # own lock, so N handlers add concurrently and NOTHING sheds
        # (a full shard ring FIFO-evicts re-collectable experience).
        # The standalone tier (fleet/shard.py ``RemoteShardSet``,
        # ISSUE 12) plugs in through the same two-call contract —
        # ``route(actor)`` at HELLO, ``add(shard_id, msg)`` per frame —
        # with ``add`` forwarding the experience over the shard's socket
        # (re-routing to survivors on shard death; the accounting deltas
        # bank learner-side inside ``add`` either way, so a dead shard
        # can never lose step/episode sums).  This handler is agnostic
        # to where replay lives.
        self.shards = shards
        # Direct data plane (ISSUE 17): when set, every ack on the control
        # connection carries {"shard", "address", "epoch"} for the actor's
        # home shard (``assignment_for`` on the RemoteShardSet) so the
        # actor can dial its shard directly for SEQS; epoch-bumped rejoins
        # re-advertise through the same ack field.  None (or a fn that
        # returns None — tier in-learner, shard down, address file not yet
        # published) means: keep forwarding through this server.
        self.shard_assignment_fn = shard_assignment_fn
        self._request_address = address
        self.shed_after_s = shed_after_s
        self.startup_shed_grace_s = startup_shed_grace_s
        self.max_frame_bytes = max_frame_bytes
        self.wire_config = (wire_config or wire.WireConfig()).validate()
        # Liveness: per-connection read deadline (the heartbeat bound).
        # Between HELLO and the first SEQS the LARGER of the two applies —
        # a fresh actor's collect compile is legitimate silence, and a
        # spurious reap per actor startup would drown the real signal.
        self.read_deadline_s = read_deadline_s
        self.warmup_deadline_s = max(warmup_deadline_s, read_deadline_s)
        self.auth_token = auth_token
        self.stop_join_s = 5.0  # handler join bound before leak reporting
        # Param snapshots are packed once per version and broadcast to all
        # handlers, so every frame inlines its schema — a freshly
        # reconnected (restarted) actor must decode it standalone.
        self._params_packer = wire.TreePacker(
            self.wire_config,
            always_inline=True,
            max_frame_bytes=max_frame_bytes,
        )
        # Until the first drain-learn executes (mark_steady), handlers
        # wait out the learner's compile instead of shedding (FleetConfig.
        # startup_shed_grace_s — the sheds==num_actors warmup artifact).
        # The grace also SELF-EXPIRES startup_shed_grace_s after the first
        # successful queue hand-off, so an embedder that consumes the
        # queue itself (IngestServer is public) and never calls
        # mark_steady still gets its configured shed_after_s back.
        self._steady = threading.Event()
        self._first_put_at: Optional[float] = None
        self.address: Optional[str] = None  # resolved at start()
        # What actors should DIAL: equals ``address`` except for wildcard
        # binds (0.0.0.0), where locally-spawned actors get loopback.
        self.connect_address: Optional[str] = None
        self._unix_path: Optional[str] = None
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._handlers: List[threading.Thread] = []
        self._conns: Dict[int, socket.socket] = {}  # ident -> live socket
        self._conn_actors: Dict[int, str] = {}  # ident -> actor id (HELLO'd)
        self._conn_seq = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # Latest published params: raw host trees swapped in by the drain
        # thread (cheap), packed ONCE per version — in the negotiated wire
        # encoding — on the first handler push (_params_snapshot); neither
        # the drain thread nor later pushes pay the pack.
        self._params_obj: Optional[Any] = None
        self._params_frame: Optional[bytes] = None
        self._param_version = 0
        self.shed_total = 0
        self.seqs_total = 0
        # Wire accounting (all SEQS frames, shed or not; under _lock):
        # bytes as received vs their declared decompressed size — the
        # bench probe's bytes-on-wire and compression-ratio columns.
        self.seqs_received_total = 0
        self.seqs_bytes_total = 0
        self.seqs_raw_bytes_total = 0
        # Scalar stats riding a shed SEQS message: the EXPERIENCE may be
        # dropped under pressure, but the episode/step accounting must not
        # be (the actor already drained its accumulators) — banked here,
        # folded back in by the learner (pop_shed_stats).
        self._shed_stats = {
            "env_steps_delta": 0.0, "ep_return_sum": 0.0, "ep_count": 0.0,
        }
        # Telemetry (obs/): per-actor label sets on shared instruments.
        reg = get_registry()
        self._obs_frames = reg.counter(
            "r2d2dpg_fleet_frames_total",
            "experience frames received from actors",
            labelnames=("actor",),
        )
        self._obs_seqs = reg.counter(
            "r2d2dpg_fleet_sequences_total",
            "sequences received from actors (pre-shed)",
            labelnames=("actor",),
        )
        self._obs_shed = reg.counter(
            "r2d2dpg_fleet_shed_total",
            "staged batches shed on a full staging queue",
            labelnames=("actor",),
        )
        self._obs_staleness = reg.gauge(
            "r2d2dpg_fleet_param_staleness_versions",
            "published param version minus the actor's last-applied version",
            labelnames=("actor",),
        )
        self._obs_connected = reg.gauge(
            "r2d2dpg_fleet_actors_connected", "live actor connections"
        )
        self._obs_connected.set_fn(lambda: float(len(self._conns)))
        if expected_actors:
            # The spawn TARGET on the scrape itself (ISSUE 13): the
            # /health actors_down rule compares the supervisor's
            # r2d2dpg_fleet_actors_alive against this, so the verdict
            # needs no out-of-band config to know what "all actors up"
            # means.  Kept as an attribute so autoscale resizes
            # (set_expected_actors) move the SAME series the health rule
            # reads — the verdict tracks the moving target, not the
            # startup value.
            self._obs_expected = reg.gauge(
                "r2d2dpg_fleet_actors_expected",
                "the fleet's actor spawn target (--actors N)",
            )
            self._obs_expected.set(float(expected_actors))
        else:
            self._obs_expected = None
        self._obs_peer_dead = reg.counter(
            "r2d2dpg_fleet_peer_dead_total",
            "connections reaped after a silent heartbeat deadline (the "
            "peer answered neither frames nor the PING probe)",
            labelnames=("actor",),
        )
        self._obs_bytes_in = reg.counter(
            "r2d2dpg_fleet_bytes_in_total",
            "bytes received off the fleet wire (frames + headers)",
            labelnames=("actor",),
        )
        self._obs_bytes_out = reg.counter(
            "r2d2dpg_fleet_bytes_out_total",
            "bytes sent on the fleet wire (acks + param pushes)",
            labelnames=("actor",),
        )
        self._obs_ratio = reg.gauge(
            "r2d2dpg_fleet_compress_ratio",
            "declared decompressed size over received payload size of the "
            "last SEQS frame (1.0 = uncompressed wire)",
        )
        # Fleet observability plane (ISSUE 6 leg 1): TELEM snapshots fold
        # into the process RemoteMirror (the exporter merges it into ONE
        # /metrics page), and each actor gets a live staleness gauge so a
        # wedged actor reads as STALE, never as silently frozen series.
        self._mirror = get_remote_mirror()
        self._telem_last: Dict[str, float] = {}
        self._obs_telem = reg.counter(
            "r2d2dpg_fleet_telem_frames_total",
            "TELEM registry snapshots received from actors",
            labelnames=("actor",),
        )
        self._obs_telem_staleness = reg.gauge(
            "r2d2dpg_fleet_telem_staleness_seconds",
            "seconds since this actor's last TELEM snapshot (a wedged or "
            "dead actor goes visibly stale)",
            labelnames=("actor",),
        )

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "IngestServer":
        if self._listener is not None:
            raise RuntimeError("ingest server already started")
        family, target = transport.parse_address(self._request_address)
        sock = socket.socket(family, socket.SOCK_STREAM)
        if family == socket.AF_INET:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        else:
            # A previous run's STALE socket file would fail the bind — but
            # only unlink if nothing answers: blindly unlinking would let a
            # second run silently steal a live run's ingest address (and
            # its restarting actors).
            import os

            if os.path.exists(target):
                probe = socket.socket(family, socket.SOCK_STREAM)
                probe.settimeout(0.5)
                try:
                    probe.connect(target)
                except OSError:
                    os.unlink(target)  # stale: nothing listening
                else:
                    raise RuntimeError(
                        f"ingest address unix:{target} already has a live "
                        f"server — is another fleet run using it?"
                    )
                finally:
                    probe.close()
        sock.bind(target)
        sock.listen(64)
        if family == socket.AF_INET:
            host, port = sock.getsockname()[:2]
            self.address = f"{host}:{port}"
            # A wildcard bind listens everywhere but is not DIALABLE as
            # written; locally-spawned actors get loopback (remote actors
            # are pointed at a routable interface by the operator).
            dial_host = "127.0.0.1" if host in ("0.0.0.0", "::", "") else host
            self.connect_address = f"{dial_host}:{port}"
        else:
            self.address = f"unix:{target}"
            self.connect_address = self.address
            self._unix_path = target
        self._listener = sock
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fleet-ingest-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            # A bare close does not wake a thread blocked in accept(2) —
            # the in-flight syscall pins the open file description and the
            # socket stays LISTENING in the kernel (still accepting
            # connects!), so the join below would eat its full timeout.
            # TCP: shutdown() tears the listen state down and wakes the
            # acceptor.  AF_UNIX: shutdown is a no-op on listeners, so
            # poke it awake with a throwaway connect (the accept loop
            # closes post-stop connections immediately).
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            if self._unix_path is not None:
                try:
                    poke = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                    poke.settimeout(0.5)
                    poke.connect(self._unix_path)
                    poke.close()
                except OSError:
                    pass
            try:
                self._listener.close()
            except OSError:
                pass
            if self._unix_path is not None:
                try:
                    import os

                    os.unlink(self._unix_path)
                except OSError:
                    pass
        with self._lock:
            conns = list(self._conns.values())
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
        for t in list(self._handlers):
            t.join(timeout=self.stop_join_s)
            if t.is_alive():
                # A handler that outlives its join window is WEDGED (its
                # socket is closed and _stop is set, so every legitimate
                # path exits in a slice) — report it instead of silently
                # leaking the thread, so a post-mortem sees the wedge.
                print(  # obs-lint: allow — teardown diagnostic
                    f"fleet ingest: handler thread {t.name} still alive "
                    f"{self.stop_join_s:.0f}s after stop — leaked (wedged "
                    f"handler; see flight.jsonl)",
                    flush=True,
                )
                flight_event("ingest_handler_leaked", thread=t.name)

    def mark_steady(self) -> None:
        """Startup is over (the drain loop's first compiled drain-learn
        has executed): from here on, queue-full waits shed after
        ``shed_after_s`` instead of the startup grace."""
        self._steady.set()

    @property
    def is_steady(self) -> bool:
        """Whether the warm-up grace has ended (mark_steady ran) — the
        autoscaler's warm-up exemption gate: load-based scale decisions
        are deferred until the loop is past its first compiled phase."""
        return self._steady.is_set()

    def set_expected_actors(self, n: int) -> None:
        """Move the fleet's actor population target (ISSUE 16): a landed
        autoscale resize updates ``r2d2dpg_fleet_actors_expected`` so the
        /health ``actors_down`` rule — and every scrape — judges against
        the CURRENT target, not the spawn-time ``--actors``.  A no-op
        when the server was built without an expected count (embedders
        that never declared a target don't grow one mid-run)."""
        if self._obs_expected is not None:
            self._obs_expected.set(float(n))

    # ---------------------------------------------------------------- params
    def publish_params(self, version: int, params: Any) -> None:
        """Swap in a new versioned param snapshot (numpy trees; callers use
        ``transport.to_host`` — the device fetch MUST happen caller-side,
        before donation invalidates the source buffers).  Handlers push it
        to each actor ahead of that actor's next ack."""
        with self._lock:
            self._param_version = int(version)
            self._params_obj = params
            self._params_frame = None

    def _params_snapshot(self):
        """Lazy pack on the FIRST push (a handler thread), once per
        version, in the negotiated wire encoding (fleet/wire.py — bf16
        params cross at half the bytes); the pack itself runs OUTSIDE the
        server lock so other handlers' acks and the drain thread's
        publishes never stall on it.  The packed payload is one bytes
        object broadcast to every handler thread."""
        with self._lock:
            version = self._param_version
            frame, obj = self._params_frame, self._params_obj
        if frame is None and obj is not None:
            frame = b"".join(
                self._params_packer.pack({"version": version, "params": obj})
            )
            with self._lock:
                if self._param_version == version and self._params_frame is None:
                    self._params_frame = frame
                # else a newer publish raced in: later pushes pack the new
                # version; THIS push still sends the frame it packed.
        return version, frame

    def _fold_telem(self, actor: str, telem: Any) -> None:
        """Fold one actor's TELEM snapshot into the remote mirror under
        ``actor=<id>`` (+ ``host=``) labels.

        Keyed by actor id, so a reconnecting (supervised-restarted) actor
        UPDATES its slot — label re-registration is idempotent and the
        scrape never grows duplicate sources.  The actor id comes from the
        connection's HELLO, never from the TELEM payload: a confused frame
        cannot relabel another actor's series.  Raises on malformed
        payloads (the handler drops them with a flight event)."""
        if not isinstance(telem, dict):
            raise ValueError("TELEM payload is not a dict")
        snapshot = telem.get("snapshot")
        if not isinstance(snapshot, dict):
            raise ValueError("TELEM snapshot is not a dict")
        labels = {"actor": actor}
        host = telem.get("host")
        if host:
            labels["host"] = str(host)
        self._mirror.update(f"actor:{actor}", labels, snapshot)
        with self._lock:
            self._telem_last[actor] = time.monotonic()
        self._arm_telem_staleness(actor)
        self._obs_telem.labels(actor=actor).inc()

    def _arm_telem_staleness(self, actor: str) -> None:
        """Install the actor's live staleness gauge (idempotent).

        Armed at HELLO — counting from connection time — so an actor that
        connects but never delivers a well-formed TELEM still shows a
        GROWING staleness series instead of being silently absent (the
        exact failure the staleness design exists to surface); each fold
        re-arms it, which just overwrites the same closure.  The
        ``.get(a, 0.0)`` default is the sentinel a fold always overwrites,
        so the closure never KeyErrors even if an operator clears state
        mid-scrape."""
        with self._lock:
            self._telem_last.setdefault(actor, time.monotonic())
        self._obs_telem_staleness.labels(actor=actor).set_fn(
            lambda a=actor: time.monotonic() - self._telem_last.get(a, 0.0)
        )

    def pop_shed_stats(self) -> Dict[str, float]:
        """Drain the scalar stats banked off shed messages (learner-side,
        on its log cadence)."""
        with self._lock:
            out = dict(self._shed_stats)
            for k in self._shed_stats:
                self._shed_stats[k] = 0.0
        return out

    def drop_connection(self, actor: Optional[str] = None) -> Optional[str]:
        """Abruptly close one live actor connection — the ``kill_ingest_conn``
        chaos boundary (fleet/chaos.py), equivalent to a mid-run network
        reset.  ``actor`` picks by HELLO'd id; ``None`` (or an id with no
        live connection) drops the oldest live connection instead, so a
        scheduled drill always drills SOMETHING when any peer is up.
        Returns the dropped actor id (or ``None`` when no connection is
        live).  The handler sees its blocking read fail and walks the
        normal torn-stream path; the actor reconnects with backoff."""
        with self._lock:
            ident = None
            if actor is not None:
                for i, a in self._conn_actors.items():
                    if a == str(actor) and i in self._conns:
                        ident = i
                        break
            if ident is None and self._conns:
                ident = next(iter(self._conns))
            if ident is None:
                return None
            conn = self._conns[ident]
            dropped = self._conn_actors.get(ident, "?")
        try:
            # SHUT_RDWR first: close() alone does not wake a handler whose
            # recv holds a reference to the open file description.
            conn.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass
        return dropped

    # ------------------------------------------------------------ connection
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            if self._stop.is_set():
                # stop()'s wake-up poke (or a raced late dial): drop it.
                try:
                    conn.close()
                except OSError:
                    pass
                return
            transport.configure_socket(conn)
            # Warmup deadline until the first SEQS frame: a fresh actor's
            # collect compile is legitimate silence; the handler tightens
            # to read_deadline_s once the connection is streaming.
            conn.settimeout(self.warmup_deadline_s)
            with self._lock:
                self._conn_seq += 1
                ident = self._conn_seq
                self._conns[ident] = conn
            # Prune finished handlers (only this thread mutates the list):
            # supervised restarts reconnect indefinitely, and the history
            # of dead Thread objects must not grow with them.
            self._handlers = [t for t in self._handlers if t.is_alive()]
            t = threading.Thread(
                target=self._handle,
                args=(ident, conn),
                name=f"fleet-ingest-conn{ident}",
                daemon=True,
            )
            self._handlers.append(t)
            t.start()

    def _push_params_if_stale(
        self, conn: socket.socket, sent_version: int, bytes_out
    ) -> int:
        version, frame = self._params_snapshot()
        if frame is not None and version > sent_version:
            bytes_out.inc(
                send_frame(
                    conn,
                    K_PARAMS,
                    frame,
                    max_frame_bytes=self.max_frame_bytes,
                )
            )
            return version
        return sent_version

    def _assignment(self, actor: str, wait_s: float = 0.0):
        """The actor's current shard assignment (or None — keep forwarding).

        Guarded: an assignment fn that raises must never cost the control
        connection.  ``wait_s`` bounds a HELLO-time poll for the shard
        tier's address file — a fresh fleet races actor HELLOs against
        the tier's atomic address publish, and waiting ~a second here
        means the actor's FIRST staged batch already rides the data plane
        (the bench leg's shard_forward_bytes == 0 depends on it).
        Steady-state refreshes (SEQS/STATS acks) pass 0: never block the
        experience path on an address lookup."""
        if self.shard_assignment_fn is None:
            return None
        deadline = time.monotonic() + wait_s
        while True:
            try:
                assignment = self.shard_assignment_fn(actor)
            except Exception as e:  # noqa: BLE001 - advisory, never fatal
                flight_event(
                    "assignment_error",
                    actor=actor,
                    error=f"{type(e).__name__}: {e}",
                )
                return None
            if assignment is not None or time.monotonic() >= deadline:
                return assignment
            if self._stop.is_set():
                return None
            time.sleep(0.1)

    def _put_or_shed(self, msg) -> bool:
        """Bounded-wait enqueue: True = queued, False = shed.

        The bound is ``shed_after_s`` once the drain loop marks steady —
        or once the grace window has elapsed since the FIRST hand-off
        (the self-expiry for embedders that never mark) — and the
        startup grace before that (the first drain-learn's compile must
        not cost every actor one shed).  The wait runs in short slices
        so a stopping server (learner aborted mid-compile) reclaims its
        handlers in ~a slice, not after a monolithic 120 s ``queue.put``
        that ignores ``_stop``."""
        now = time.monotonic()
        in_grace = not self._steady.is_set() and (
            self._first_put_at is None
            or now - self._first_put_at < self.startup_shed_grace_s
        )
        if in_grace:
            # Anchor the deadline at the END of the grace window (first
            # hand-off + grace), not now + grace: a wait that begins just
            # inside the window must not stretch the window to ~2x; it
            # gets its shed_after_s past the expiry and no more.
            anchor = now if self._first_put_at is None else self._first_put_at
            deadline = max(
                now + self.shed_after_s,
                anchor + self.startup_shed_grace_s,
            )
        else:
            deadline = now + self.shed_after_s
        while not self._stop.is_set():
            try:
                self.queue.put(
                    msg,
                    timeout=min(0.25, max(deadline - time.monotonic(), 0.0)),
                )
                if self._first_put_at is None:
                    self._first_put_at = time.monotonic()
                return True
            except queue.Full:
                if time.monotonic() >= deadline:
                    return False
        return False  # stopping: drop silently, the run is over

    def _handle(self, ident: int, conn: socket.socket) -> None:
        actor = "?"
        # Per-connection wire state: the peer's packer lives on its side
        # of this socket, so the schema cache must die with it too.
        unpacker = wire.TreeUnpacker(max_frame_bytes=self.max_frame_bytes)
        try:
            kind, payload = recv_frame(
                conn, max_frame_bytes=self.max_frame_bytes
            )
            if kind != K_HELLO:
                raise FrameError(f"expected HELLO, got kind {kind}")
            # JSON, never pickle: this parse runs BEFORE the auth check
            # below (the proof is inside the payload), on bytes from a
            # peer nothing has vouched for — transport.pack_hello.
            hello = transport.unpack_hello(payload)
            actor = str(hello.get("actor_id", "?"))
            if self.auth_token is not None:
                # Constant-time comparison of the HELLO proof against the
                # shared secret's (ROADMAP cross-host prerequisite): a
                # mismatch — or a missing proof — is refused at the door,
                # before wire negotiation or any tensor decode.  Also
                # before ANY per-actor state: the claimed actor_id is
                # attacker-controlled on exactly the routable binds auth
                # exists for, and registering labeled metric series or a
                # _conn_actors entry per unauthenticated HELLO would let a
                # port scanner grow the registry (and the /metrics page)
                # without bound.  The bounded flight ring may name it.
                want = transport.hello_auth_proof(self.auth_token)
                got = str(hello.get("auth", ""))
                if not hmac.compare_digest(want, got):
                    flight_event("auth_refused", actor=actor)
                    send_frame(
                        conn,
                        K_ACK,
                        pack_obj(  # wire-lint: control
                            {"code": REFUSED_AUTH, "param_version": 0}
                        ),
                    )
                    return
            with self._lock:
                self._conn_actors[ident] = actor
            bytes_in = self._obs_bytes_in.labels(actor=actor)
            bytes_out = self._obs_bytes_out.labels(actor=actor)
            bytes_in.inc(HEADER_BYTES + len(payload))
            mismatch = wire.check_negotiation(hello, self.wire_config)
            if mismatch is not None:
                # One fleet, one wire format: a mismatched actor would
                # poison every SEQS decode — refuse at the door, loudly.
                flight_event("wire_refused", actor=actor, reason=mismatch)
                bytes_out.inc(
                    send_frame(
                        conn,
                        K_ACK,
                        pack_obj(  # wire-lint: control
                            {
                                "code": REFUSED_WIRE,
                                "param_version": 0,
                                "reason": mismatch,
                                "expect": wire.negotiation_fields(
                                    self.wire_config
                                ),
                            }
                        ),
                    )
                )
                return
            # Accepted actor: staleness is visible from THIS moment, not
            # from its first well-formed TELEM (which may never come).
            self._arm_telem_staleness(actor)
            sent_version = self._push_params_if_stale(conn, 0, bytes_out)
            # Direct data plane (ISSUE 17): the HELLO ack advertises the
            # actor's shard assignment + dialable address.  Bounded poll:
            # a fresh tier publishes its address file a beat after the
            # first HELLOs land, and shipping the assignment NOW means no
            # forwarded warmup batches.
            hello_assignment = self._assignment(actor, wait_s=10.0)
            ack = {"code": OK, "param_version": sent_version}
            if hello_assignment is not None:
                ack["shard_assignment"] = hello_assignment
            bytes_out.inc(
                send_frame(
                    conn,
                    K_ACK,
                    pack_obj(ack),  # wire-lint: control
                )
            )
            streaming = False  # first SEQS tightens the read deadline
            while not self._stop.is_set():
                kind, payload = recv_frame_heartbeat(
                    conn,
                    max_frame_bytes=self.max_frame_bytes,
                    bytes_in=bytes_in.inc,
                    bytes_out=bytes_out.inc,
                )
                t_recv = time.time()
                bytes_in.inc(HEADER_BYTES + len(payload))
                if kind == K_BYE:
                    return
                if kind == K_TELEM:
                    # Fire-and-forget metric aggregation: fold or drop —
                    # a malformed snapshot must cost ONE flight event, not
                    # the connection (the experience path is unaffected).
                    try:
                        self._fold_telem(
                            actor, unpack_obj(payload)  # wire-lint: control
                        )
                    except Exception as e:  # noqa: BLE001 - quarantine
                        flight_event(
                            "telem_malformed",
                            actor=actor,
                            error=f"{type(e).__name__}: {e}",
                        )
                    continue
                if kind == K_STATS:
                    # Split-plane accounting (ISSUE 17): the staged batch
                    # went straight to the actor's shard on the data
                    # plane; this tiny control frame carries ONLY the
                    # accounting deltas, banked into the same sums the
                    # forwarded path's ``add`` banks — the actor clears
                    # its accumulators on THIS ack, so at-least-once
                    # accounting is plane-independent.
                    if not streaming:
                        conn.settimeout(self.read_deadline_s)
                        streaming = True
                    stats_msg = unpack_obj(payload)  # wire-lint: control
                    if self.shards is not None:
                        self.shards.bank_stats(stats_msg)
                    self._obs_staleness.labels(actor=actor).set(
                        self._param_version
                        - int(stats_msg.get("param_version", 0))
                    )
                    sent_version = self._push_params_if_stale(
                        conn, sent_version, bytes_out
                    )
                    ack = {"code": OK, "param_version": sent_version}
                    assignment = self._assignment(actor)
                    if assignment is not None:
                        ack["shard_assignment"] = assignment
                    bytes_out.inc(
                        send_frame(
                            conn,
                            K_ACK,
                            pack_obj(ack),  # wire-lint: control
                        )
                    )
                    continue
                if kind != K_SEQS:
                    raise FrameError(f"expected SEQS/BYE, got kind {kind}")
                if not streaming:
                    # The connection is streaming: from here on the peer's
                    # longest legitimate silence is one collect phase, and
                    # the heartbeat deadline bounds it.
                    conn.settimeout(self.read_deadline_s)
                    streaming = True
                msg = unpacker.unpack(payload)
                t_decode_end = time.time()
                tr = unpacker.last_trace
                if tr is not None and self.shards is not None:
                    # Sharded mode: the SEQS sidecar's hop chain has no
                    # completing drain to record it (the sampler path
                    # traces sample_req -> batch_return -> learn
                    # instead) — drop it rather than leave a partial
                    # chain (the all-or-nothing contract, obs/trace.py).
                    tr = None
                if tr is not None:
                    # The sampled batch's actor-side hops (off the wire
                    # sidecar) + this handler's transit/decode timestamps
                    # ride the queue message; NOTHING is recorded here.
                    # The drain loop records all 8 hops together for the
                    # batches it actually traces through learn, so every
                    # hop histogram shares ONE sample population — an
                    # absorb-phase or shed batch contributes no partial
                    # 4-hop chain ("absorb batches are untraced").
                    msg["trace"] = {
                        "id": tr.trace_id,
                        "actor": actor,
                        "t_collect_start": tr.t_collect_start,
                        "t_collect_end": tr.t_collect_end,
                        "t_encode_end": tr.t_encode_end,
                        "t_recv": t_recv,
                        "t_enqueue_start": t_decode_end,
                    }
                msg["actor_id"] = actor
                n_seqs = int(
                    np.shape(msg["staged"].seq.reward)[0]
                )
                self._obs_frames.labels(actor=actor).inc()
                self._obs_seqs.labels(actor=actor).inc(n_seqs)
                self._obs_staleness.labels(actor=actor).set(
                    self._param_version - int(msg.get("param_version", 0))
                )
                self._obs_ratio.set(
                    unpacker.last_raw_len
                    / max(unpacker.last_payload_len, 1)
                )
                with self._lock:
                    self.seqs_received_total += n_seqs
                    self.seqs_bytes_total += HEADER_BYTES + len(payload)
                    self.seqs_raw_bytes_total += unpacker.last_raw_len
                if self.shards is not None:
                    # In-network sampling: straight into this actor's
                    # shard — concurrent across handlers, never sheds
                    # (ring eviction is the backpressure), accounting
                    # deltas banked for the sampler learner's sums.
                    # Routed per FRAME, not per connection: the route is
                    # a pure actor-id hash on the loopback (identical
                    # every call), and liveness-aware on the standalone
                    # tier — an actor whose home shard was down at HELLO
                    # lands back home the moment it rejoins, instead of
                    # feeding a neighbor for the connection's lifetime.
                    self.shards.add(self.shards.route(actor), msg)
                    code = OK
                    with self._lock:
                        self.seqs_total += n_seqs
                elif self._put_or_shed(msg):
                    code = OK
                    with self._lock:  # N handler threads share these sums
                        self.seqs_total += n_seqs
                else:
                    if self._stop.is_set():
                        return
                    code = SHED_INGEST
                    with self._lock:
                        self.shed_total += 1
                        for k in self._shed_stats:
                            self._shed_stats[k] += float(msg.get(k, 0.0))
                    self._obs_shed.labels(actor=actor).inc()
                    flight_event(
                        "shed", code=code, actor=actor,
                        phase=int(msg.get("phase", -1)),
                    )
                sent_version = self._push_params_if_stale(
                    conn, sent_version, bytes_out
                )
                ack = {"code": code, "param_version": sent_version}
                # Assignment refresh on every ack (non-blocking): a
                # fallen-back actor re-learns its shard's address the
                # moment an epoch-bumped rejoin re-publishes it.
                assignment = self._assignment(actor)
                if assignment is not None:
                    ack["shard_assignment"] = assignment
                bytes_out.inc(
                    send_frame(
                        conn,
                        K_ACK,
                        pack_obj(ack),  # wire-lint: control
                    )
                )
        except PeerDeadError as e:
            if not self._stop.is_set():
                # The liveness verdict (docs/FLEET.md "Failure modes"): a
                # peer that answered neither frames nor the PING probe is
                # REAPED — connection closed, loudly attributed.  The
                # supervisor restarts a wedged actor when its stall
                # eventually crashes or exits it; a merely-slow actor
                # reconnects by itself.
                flight_event(
                    "peer_dead",
                    actor=actor,
                    deadline_s=self.read_deadline_s,
                    error=str(e),
                )
                self._obs_peer_dead.labels(actor=actor).inc()
        except (FrameError, OSError) as e:
            if not self._stop.is_set():
                # A crashed actor's torn stream: note it and drop the
                # connection — the supervisor owns the restart.
                flight_event(
                    "ingest_conn_error",
                    actor=actor,
                    error=f"{type(e).__name__}: {e}",
                )
        finally:
            with self._lock:
                self._conns.pop(ident, None)
                self._conn_actors.pop(ident, None)
            try:
                conn.close()
            except OSError:
                pass


def aval_tree(tree):
    """ShapeDtypeStruct tree of ``tree``'s leaves, shardings preserved —
    the aval capture shared by the drain loop and the coalesce-width
    precompile (one definition, so the warm-compiled avals can never
    silently diverge from what the drain loop passes)."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(
            np.shape(x), x.dtype, sharding=getattr(x, "sharding", None)
        ),
        tree,
    )


# -------------------------------------------------------- fleet checkpoints
# The learner-recovery contract (docs/FLEET.md "Failure modes & recovery"):
# a fleet checkpoint is the LEARNER subtree (params + targets + optimizer
# states + step; utils/checkpoint.py light layout) plus this sidecar of
# host-side monotone counters (env steps, episode sums, drained-phase
# count, param version).  The replay arena is deliberately NOT
# checkpointed — it is GBs of re-collectable experience — so a resumed run
# re-enters the absorb-to-min_replay phase with fresh actor experience
# before drain-learn phases continue, and every counter continues monotone
# from where the checkpoint left it.
def fleet_counters_path(directory: str, step: int) -> str:
    return os.path.join(
        os.path.abspath(directory), f"fleet_counters_{int(step)}.json"
    )


def save_fleet_counters(directory: str, step: int, counters: Dict) -> str:
    """Atomically write the monotone-counter sidecar next to the orbax
    step (tmp + rename: a torn write never masquerades as a counter
    state).  Returns the path."""
    path = fleet_counters_path(directory, step)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({k: float(v) for k, v in counters.items()}, f)
    os.replace(tmp, path)
    return path


def load_fleet_counters(directory: str, step: int) -> Dict[str, float]:
    """Read the sidecar for ``step``; missing file -> empty dict (callers
    warn loudly — counters would restart at zero, losing monotonicity
    against the previous incarnation's logs)."""
    path = fleet_counters_path(directory, step)
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        return {k: float(v) for k, v in json.load(f).items()}


def prune_fleet_counters(directory: str, keep_steps) -> None:
    """Drop sidecars whose orbax step was garbage-collected (max_to_keep),
    so the two never drift apart on disk."""
    keep = {int(s) for s in keep_steps}
    directory = os.path.abspath(directory)
    try:
        names = os.listdir(directory)
    except OSError:
        return
    for name in names:
        if not (name.startswith("fleet_counters_") and name.endswith(".json")):
            continue
        try:
            step = int(name[len("fleet_counters_"):-len(".json")])
        except ValueError:
            continue
        if step not in keep:
            try:
                os.unlink(os.path.join(directory, name))
            except OSError:
                pass


class FleetLearner:
    """The staging queue's single consumer: drain -> arena add -> K updates.

    Owns the ingest server and the drain/absorb device programs; runs on
    the calling thread.  ``fleet=off`` (``--actors 0``) never constructs
    this class — the phase-locked ``Trainer.run`` path is untouched, and
    tests/test_fleet.py pins that bit-identically.
    """

    def __init__(self, trainer: Trainer, config: FleetConfig):
        if trainer.axis is not None:
            raise ValueError(
                "FleetLearner needs a host-visible drain boundary; "
                "shard_map trainers (SPMDTrainer) fuse whole phases — use "
                "the base Trainer or HostSPMDTrainer"
            )
        if config.num_actors < 1:
            raise ValueError(
                "FleetLearner requires num_actors >= 1 (fleet=off runs "
                "Trainer.run directly; there is nothing to ingest)"
            )
        if config.queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")
        if config.drain_coalesce < 1:
            raise ValueError("drain_coalesce must be >= 1")
        config.wire.validate()
        self.trainer = trainer
        self.config = config
        self.queue: "queue.Queue" = queue.Queue(maxsize=config.queue_depth)
        self.server = IngestServer(
            self.queue,
            address=config.address,
            shed_after_s=config.shed_after_s,
            startup_shed_grace_s=config.startup_shed_grace_s,
            max_frame_bytes=config.max_frame_bytes,
            wire_config=config.wire,
            read_deadline_s=config.heartbeat_s,
            warmup_deadline_s=config.warmup_deadline_s,
            auth_token=config.auth_token,
            expected_actors=config.num_actors,
        )
        drain_kwargs: Dict[str, Any] = {"donate_argnums": (0,)}
        ls_sh = getattr(trainer, "lstate_shardings", None)
        if ls_sh is not None:
            # dp learner (parallel/dp_learner.py): pin the drain outputs
            # to the init layout so the donated chain's avals stay stable
            # — neither the jit cache nor the AOT-precompiled coalesce
            # widths below may re-key mid-run on a GSPMD layout drift.
            drain_kwargs["out_shardings"] = (ls_sh(), trainer._replicated)
        self._drain_prog = jax.jit(
            lambda ls, st: drain_staged(
                trainer, ls, st, learn=True, prefetch=config.prefetch
            ),
            **drain_kwargs,
        )
        self._absorb_prog = jax.jit(
            lambda ls, st: drain_staged(trainer, ls, st, learn=False),
            **drain_kwargs,
        )
        # Coalesce-width precompile (ISSUE 9 satellite — the BENCH_FLEET
        # coalesce regression): every power-of-two bucket width is a
        # distinct drain program, and compiling one MID-RUN stalls the
        # drain for tens of seconds — long enough to fill the queue and
        # shed.  A background thread AOT-compiles the widths during the
        # absorb phase (_warm_drain_widths); until a width's program is
        # READY the pull limit is clamped to the widths that are
        # (_coalesce_ready), so the drain never blocks on a width compile.
        self._drain_exec: Dict[int, Any] = {}  # total staged B -> compiled
        # Per-width cost_analysis FLOPs (the warm thread fills it): the
        # MFU accounting bills each coalesced dispatch its exact width.
        self._drain_flops: Dict[int, float] = {}
        self._coalesce_ready = 1
        self._warm_thread: Optional[threading.Thread] = None
        # Set when the run is over: the warm thread checks it between
        # width compiles, and run()'s finally JOINS the thread — a
        # daemon mid-XLA-compile at interpreter teardown aborts the
        # whole process (std::terminate), turning a finished short run
        # into rc=134.
        self._warm_stop = threading.Event()
        reg = get_registry()
        self._obs_queue_depth = reg.gauge(
            "r2d2dpg_fleet_staging_queue_depth",
            "staged batches awaiting drain",
        )
        self._obs_queue_depth.set_fn(self.queue.qsize)
        # Same split as the sampler's wait/absorb pair: absorb-phase
        # queue waits are EXPECTED (actor spawn + jax import + collect
        # compile — each Empty timeout lands a ~0.5s sample, right at the
        # /health learner_starving threshold) and would read a clean
        # warm-up as starving until ~window-size later waits flush them.
        self.learner_wait = reg.histogram(
            "r2d2dpg_fleet_learner_wait_seconds",
            "learner thread blocked on the fleet staging queue AFTER "
            "absorb (starvation — the /health learner_starving input)",
        )
        self.absorb_wait = reg.histogram(
            "r2d2dpg_fleet_absorb_wait_seconds",
            "learner thread blocked on the staging queue during the "
            "absorb-to-min_replay phase (cold start and --resume re-entry)",
        )
        self._obs_coalesce = reg.gauge(
            "r2d2dpg_fleet_drain_coalesce_width",
            "staged batches stacked into the most recent compiled drain",
        )
        self._stats: Dict[str, float] = {}
        self._counters: Dict[str, float] = {}

    # ------------------------------------------------------------- lifecycle
    def start(self) -> str:
        """Bind + start the ingest server; returns the resolved DIALABLE
        address the supervisor hands to actor subprocesses (loopback for a
        wildcard bind — ``IngestServer.connect_address``)."""
        self.server.start()
        return self.server.connect_address

    def close(self) -> None:
        """Stop the ingest server.  Callers stop the SUPERVISOR first: an
        actor that loses its connection while unsupervised exits cleanly,
        but one mid-send sees a reset — the supervisor must already be in
        its stopping state so that exit is not treated as a crash."""
        self.server.stop()
        self._obs_queue_depth.set(0.0)

    def stats(self) -> Dict[str, float]:
        """Instrumentation from the most recent ``run`` (throughput +
        shed/starvation accounting; ``arena_add_seqs_per_sec`` is the
        bench probe's headline)."""
        return dict(self._stats)

    def counters(self) -> Dict[str, float]:
        """The monotone counters as of the most recent ``run``'s end — the
        values the FINAL checkpoint's sidecar must record so a later
        ``--resume`` continues them (train.py writes it next to
        ``save_final``)."""
        return dict(self._counters)

    def _save_checkpoint(
        self, ckpt, step: int, state, cstate, lstate, counters: Dict
    ) -> None:
        """One periodic learner checkpoint: the merged state (a LIGHT
        manager persists only the ``train`` subtree — params, targets,
        optimizer, step) plus the monotone-counter sidecar, pruned in
        lockstep with orbax's ``max_to_keep``.  Runs on the drain thread
        between phases; the synchronous save completes before the next
        drain call donates ``lstate``'s buffers."""
        ckpt.save(step, merge_state(state, cstate, lstate))
        save_fleet_counters(ckpt.directory, step, counters)
        prune_fleet_counters(ckpt.directory, ckpt.all_steps())

    def _warm_drain_widths(self, ls_avals, staged_example) -> None:
        """Background AOT precompile of the power-of-two coalesce widths.

        Runs on a daemon thread started when the FIRST staged batch
        arrives (its shapes parameterize every width): for each width
        ``2^k <= drain_coalesce`` the drain-learn program is lowered and
        compiled against the width's avals — leading-dim-scaled from ONE
        ``trainer._put_staged`` placement of the example, so the
        compiled input layout matches what the drain loop will actually
        pass — and published to ``_drain_exec`` keyed by TOTAL staged B.
        ``_coalesce_ready`` rises as widths land, in order, so the pull
        clamp only ever admits a backlog width whose program exists; the
        drain thread keeps absorbing (tracing is thread-safe; the arena's
        staged-writer claim is skipped under trace — replay/arena.py).
        Any failure leaves the clamp at the widths already published
        (a ``drain_warm_failed`` flight event names it): narrower drains,
        never a wrong or stalling one.

        Device-plane attribution (ISSUE 14 satellite): this thread's
        compiles are DECLARED (an ``expected`` window — warm-window
        compiles may legitimately land after the first drain-learn
        marked steady in a future ordering) and labelled
        ``fleet_drain_warm``, so the compile histograms attribute them
        instead of leaving them invisible; each ``drain_width_ready``
        event carries the measured wall seconds of its width's
        lower+compile, and the width's ``cost_analysis`` FLOPs feed the
        MFU accounting exactly per dispatch width."""
        t = self.trainer
        mon = get_device_monitor()
        try:
            b0 = int(np.shape(staged_example.seq.reward)[0])
            # ONE width-1 placement yields the layout (dtype + sharding
            # per leaf — NamedShardings are shape-agnostic); each width's
            # avals just scale the leading dim.  No per-width dummy
            # stacks or device transfers competing with the absorb
            # phase's real traffic.  A width whose divisibility would
            # flip the placement decision (b0 not mesh-divisible but
            # w*b0 is) compiles against the width-1 layout and falls
            # back through the drain loop's exec_ guard — structural
            # argv pins b0 divisible fleet-wide, so that is theoretical.
            base_avals = aval_tree(t._put_staged(staged_example))
            # w starts at 1: when the FIRST learn pull is coalesced (a
            # backlog at the absorb->learn crossing dispatches through
            # the AOT object), the jit wrapper's width-1 cache entry is
            # never populated — a later width-1 pull would then compile
            # inline POST-steady, the exact stall this thread removes.
            w = 1
            while w <= self.config.drain_coalesce:
                if self._warm_stop.is_set():
                    return  # run over: don't start another width compile
                staged_avals = jax.tree_util.tree_map(
                    lambda a, _w=w: jax.ShapeDtypeStruct(
                        (_w * a.shape[0],) + tuple(a.shape[1:]),
                        a.dtype,
                        sharding=a.sharding,
                    ),
                    base_avals,
                )
                t_compile = time.monotonic()
                with mon.expected("drain_warm"), mon.program(
                    "fleet_drain_warm"
                ):
                    compiled = self._drain_prog.lower(
                        ls_avals, staged_avals
                    ).compile()
                compile_s = time.monotonic() - t_compile
                width_flops = flops_of(compiled)
                if width_flops:
                    self._drain_flops[w * b0] = width_flops
                self._drain_exec[w * b0] = compiled
                self._coalesce_ready = w
                flight_event(
                    "drain_width_ready",
                    width=w,
                    seqs=w * b0,
                    seconds=round(compile_s, 3),
                )
                w *= 2
        except Exception as e:  # noqa: BLE001 — degrade, never crash the run
            flight_event(
                "drain_warm_failed", error=f"{type(e).__name__}: {e}"
            )

    # ------------------------------------------------------------------- run
    def run(
        self,
        num_train_phases: int,
        state: Optional[TrainerState] = None,
        log_every: int = 50,
        log_fn=print,
        metrics_fn: Optional[Callable[[int, Dict[str, float]], None]] = None,
        minutes: Optional[float] = None,
        ckpt=None,
        checkpoint_every: int = 0,
        resume_from: Optional[Dict[str, float]] = None,
        phase_fn: Optional[Callable[[int], None]] = None,
    ) -> TrainerState:
        """Absorb staged batches until ``min_replay`` sequences are
        resident, then run ``num_train_phases`` drain-learn phases (one
        staged batch + K updates each — the phase-locked data-to-update
        ratio, fed from the fleet).  The server must already be started;
        the caller owns actor lifecycle (supervisor).

        ``ckpt`` (a LIGHT ``utils.CheckpointManager``) + ``checkpoint_every``
        arm periodic learner checkpoints: the learner subtree is saved
        every N drain phases with the monotone-counter sidecar
        (``save_fleet_counters``) — the recovery contract's durable half.
        ``resume_from`` (``load_fleet_counters`` of the restored step)
        continues counters, phase numbering and param versions where the
        previous incarnation left them; ``num_train_phases`` stays the
        TOTAL target across incarnations.  ``phase_fn(drained)`` runs
        after every drain-learn phase — the chaos engine's injection hook
        (fleet/chaos.py)."""
        if self.server.address is None:
            raise RuntimeError("call start() before run()")
        t = self.trainer
        # Device plane (ISSUE 14): the drain loop owns the run window —
        # steady arms at the existing mark_steady boundary (first
        # drain-learn executed AND warm-width compiles done).
        mon = get_device_monitor().install()
        mon.begin_run()
        state = t.init() if state is None else state
        cstate, lstate = split_state(state)
        deadline = (
            time.monotonic() + minutes * 60 if minutes is not None else None
        )
        self.learner_wait.reset()
        self.absorb_wait.reset()
        resume_from = resume_from or {}
        version = int(resume_from.get("param_version", 0)) + 1
        self.server.publish_params(version, self._snapshot_params(lstate))

        min_seqs = t.config.min_replay
        absorbed = 0
        # Monotone across learner incarnations: a resumed run continues
        # the drained-phase count and the host-side sums exactly where the
        # checkpoint's sidecar left them (the recovery contract).
        drained = int(resume_from.get("drained", 0))
        drained_at_start = drained
        last_metrics: Dict[str, Any] = {}
        # Host-side episode accounting: actors drain their device
        # accumulators each phase and ship DELTAS as plain floats, so the
        # sums here stay monotone across supervised actor restarts.
        ep_ret_sum = float(resume_from.get("ep_return_sum", 0.0))
        ep_count = float(resume_from.get("ep_count", 0.0))
        env_steps_total = float(resume_from.get("env_steps_total", 0.0))
        episodes_total = float(resume_from.get("episodes_total", 0.0))
        last_batch_t = time.monotonic()
        t0 = time.monotonic()
        # Steady-state window for throughput claims: everything before the
        # first drain-learn completes (actor subprocess spawn, jax imports,
        # program compiles, replay fill) is startup, not sustained rate.
        train_t0: Optional[float] = None
        seqs_at_train_t0 = 0
        marked_steady = False

        def emit_log(phase: int, scalars: Dict[str, float]) -> None:
            if metrics_fn is not None:
                metrics_fn(phase, scalars)
                return
            log_fn(
                f"fleet phase {phase}/{num_train_phases} "
                + " ".join(f"{k} {v:.3g}" for k, v in scalars.items())
            )

        coalesce_sum = 0
        coalesce_n = 0
        try:
            while drained < num_train_phases:
                if deadline is not None and time.monotonic() >= deadline:
                    break
                t_wait = time.monotonic()
                # Absorb-phase waits go to their own histogram (see the
                # registration comment): the learn-phase boundary is the
                # same absorbed>min_seqs crossing the drain programs use.
                wait_hist = (
                    self.learner_wait
                    if absorbed > min_seqs
                    else self.absorb_wait
                )
                try:
                    first = self.queue.get(timeout=0.5)
                except queue.Empty:
                    wait_hist.add(time.monotonic() - t_wait)
                    # Cold-start grace: the FIRST batch pays actor
                    # subprocess spawn + jax import + collect compile +
                    # window fill — give it double the steady-state bound.
                    bound = self.config.idle_timeout_s * (
                        2.0 if absorbed == 0 else 1.0
                    )
                    if time.monotonic() - last_batch_t > bound:
                        raise RuntimeError(
                            f"fleet starved: no staged batch in "
                            f"{self.config.idle_timeout_s:.0f}s — are the "
                            f"actors alive? (supervisor restarts crashed "
                            f"ones; check flight.jsonl)"
                        )
                    continue
                wait_hist.add(time.monotonic() - t_wait)
                last_batch_t = time.monotonic()
                t_dequeue = time.time()
                # Coalesced drain (drain_coalesce): the blocking-got batch
                # plus whatever backlog the queue ALREADY holds, stacked
                # into ONE compiled call — the arena-add dispatch is paid
                # once per backlog instead of once per actor batch.  A
                # keeping-up learner sees width 1 and the uncoalesced
                # schedule exactly.  The pull is clamped to the widths
                # whose drain program is READY (precompiled by the warm
                # thread below): a mid-run width compile stalls the drain
                # long enough to fill the queue and shed — the
                # BENCH_FLEET coalesce regression this clamp removes.
                # Absorb-phase pulls clamp to 1 outright: only the
                # drain-LEARN widths are warmed, and a wide pull there
                # would compile an absorb program used for seconds and
                # never again — the same inline stall in another coat.
                limit = (
                    1
                    if absorbed < min_seqs
                    else min(self.config.drain_coalesce, self._coalesce_ready)
                )
                msgs = coalesce_from_queue(self.queue, first, limit)
                if self.config.drain_coalesce > 1 and self._warm_thread is None:
                    # First batch ever: its shapes parameterize every
                    # coalesce width.  Capture the lstate avals NOW (the
                    # next drain call donates these buffers) and compile
                    # the widths in the background while absorb proceeds.
                    ls_avals = aval_tree(lstate)
                    self._warm_thread = threading.Thread(
                        target=self._warm_drain_widths,
                        args=(ls_avals, msgs[0]["staged"]),
                        name="fleet-drain-warm",
                        daemon=True,
                    )
                    self._warm_thread.start()
                coalesce_sum += len(msgs)
                coalesce_n += 1
                self._obs_coalesce.set(float(len(msgs)))
                # Fold shed-banked accounting EVERY iteration (a cheap
                # locked dict swap): only the experience of a shed message
                # was droppable, and the sums must be right whenever read
                # (log cadence, log_every=0 probes, end-of-run stats).
                shed_stats = self.server.pop_shed_stats()
                env_steps_total += shed_stats["env_steps_delta"]
                ep_ret_sum += shed_stats["ep_return_sum"]
                ep_count += shed_stats["ep_count"]
                episodes_total += shed_stats["ep_count"]
                staged = stack_staged([m["staged"] for m in msgs])
                t_stack_end = time.time()
                # Sampled batches' hops (obs/trace.py): absorb phases are
                # untraced (their "learn" would be a lie), so ALL 8 hops —
                # including the actor-side stamps riding the message — are
                # recorded only once the run is draining for real.
                traces = [m["trace"] for m in msgs if m.get("trace")]
                n_seqs = int(np.shape(staged.seq.reward)[0])
                for msg in msgs:
                    ep_ret_sum += float(msg.get("ep_return_sum", 0.0))
                    ep_count += float(msg.get("ep_count", 0.0))
                    episodes_total += float(msg.get("ep_count", 0.0))
                    env_steps_total += float(msg.get("env_steps_delta", 0.0))
                absorbed += n_seqs
                # Mesh placement BEFORE the compiled call (the dp
                # learner's _put_staged lays the batch over its dp axis —
                # jax.make_array_from_process_local_data when
                # multi-process; identity for single-chip trainers).
                placed = t._put_staged(staged)
                # staged_writer around the COMPILED call: inside the jit
                # the arena's own guard only runs at trace time, so the
                # single-writer claim must wrap the execution (replay/
                # arena.py "SINGLE-WRITER contract").
                if absorbed <= min_seqs:
                    with t.arena.staged_writer():
                        lstate, _ = self._absorb_prog(lstate, placed)
                    continue
                # Experience-quality fold (obs/quality.py), host numpy on
                # the already-decoded batch — zero device traffic.  Under
                # the central drain EVERY absorbed sequence crosses into
                # the training arena, so the per-actor counters attribute
                # train-visible experience by the HELLO-authenticated id
                # the handler stamped (never the payload's claim), and
                # the lag distribution is the published-version distance
                # at the moment the batch enters training.
                qplane = get_quality_plane()
                if staged.behavior_version is not None:
                    qplane.observe_lags(
                        policy_lags(version, staged.behavior_version)
                    )
                for m_q in msgs:
                    qplane.note_trained(
                        m_q["actor_id"],
                        int(np.shape(m_q["staged"].seq.reward)[0]),
                    )
                exec_ = self._drain_exec.get(n_seqs)
                note_width = getattr(t, "dp_note_learn_width", None)
                if note_width is not None:
                    # The dp learner's dispatch-width gauge, set at the
                    # REAL drain site (host-known B — no fetch).
                    note_width(n_seqs)
                mon.on_phase(drained + 1)
                if drained == drained_at_start:
                    # MFU numerator for the uncoalesced/width-1 path: one
                    # lazy lower() at these avals on the log cadence (the
                    # warm thread's per-width cost_analysis overrides per
                    # dispatch where it ran).
                    ls_avals_c, st_avals_c = (
                        aval_tree(lstate), aval_tree(placed),
                    )
                    mon.set_learn_cost(
                        lambda: flops_of(
                            self._drain_prog.lower(ls_avals_c, st_avals_c)
                        )
                    )
                mon.note_learn(self._drain_flops.get(n_seqs))
                with t.arena.staged_writer(), mon.program("fleet_drain"):
                    if exec_ is not None:
                        # AOT-precompiled width (the warm thread's
                        # contract): dispatch through the compiled object
                        # — the jit wrapper's cache never saw this width
                        # and would recompile on it.  An aval mismatch
                        # (foreign batch structure) raises BEFORE any
                        # donation, so falling back to the jit path is
                        # safe — it pays the compile this width's AOT
                        # object existed to avoid, once, loudly.
                        try:
                            lstate, last_metrics = exec_(lstate, placed)
                        except (TypeError, ValueError) as e:
                            flight_event(
                                "drain_exec_fallback",
                                seqs=n_seqs,
                                error=f"{type(e).__name__}: {e}",
                            )
                            self._drain_exec.pop(n_seqs, None)
                            lstate, last_metrics = self._drain_prog(
                                lstate, placed
                            )
                    else:
                        lstate, last_metrics = self._drain_prog(
                            lstate, placed
                        )
                t_dispatch_end = time.time()
                if traces:
                    # One block_until_ready per SAMPLED drain is what makes
                    # the learn hop honest (async dispatch otherwise
                    # returns immediately); unsampled drains pay nothing.
                    jax.block_until_ready(lstate.train.step)
                    t_done = time.time()
                    nbytes = staged_nbytes(staged)
                    for tr in traces:
                        tid, act = tr["id"], tr.get("actor")
                        obs_trace.record_hop(
                            "collect", tr["t_collect_start"],
                            tr["t_collect_end"], tid, actor=act,
                        )
                        obs_trace.record_hop(
                            "encode", tr["t_collect_end"],
                            tr["t_encode_end"], tid, actor=act,
                        )
                        obs_trace.record_hop(
                            "transit", tr["t_encode_end"], tr["t_recv"],
                            tid, actor=act,
                        )
                        obs_trace.record_hop(
                            "decode", tr["t_recv"], tr["t_enqueue_start"],
                            tid, actor=act,
                        )
                        obs_trace.record_hop(
                            "enqueue", tr["t_enqueue_start"], t_dequeue,
                            tid, actor=act,
                        )
                        obs_trace.record_hop(
                            "coalesce", t_dequeue, t_stack_end,
                            tid, actor=act, width=len(msgs),
                        )
                        obs_trace.record_hop(
                            "arena_add", t_stack_end, t_dispatch_end,
                            tid, actor=act, bytes=nbytes, seqs=n_seqs,
                        )
                        obs_trace.record_hop(
                            "learn", t_dispatch_end, t_done,
                            tid, actor=act,
                        )
                drained += 1
                if train_t0 is None:
                    # The first drain carries the compile; the sustained
                    # window starts once it has actually executed.
                    jax.block_until_ready(lstate.train.step)
                    train_t0 = time.monotonic()
                    seqs_at_train_t0 = absorbed
                if not marked_steady and (
                    self._warm_thread is None
                    or not self._warm_thread.is_alive()
                ):
                    # Startup is over: the first drain-learn has executed
                    # AND the background coalesce-width compiles (which
                    # contend for the same cores and would slow the drain
                    # into queue-full sheds) are done — handlers now shed
                    # on the real shed_after_s bound instead of the
                    # compile grace.
                    self.server.mark_steady()
                    # The compile sentinel arms at the SAME boundary: the
                    # drain programs (jit width-1 + every warm width) are
                    # materialized — any later compile outside a declared
                    # window is an aval-re-key alarm.
                    mon.mark_steady()
                    marked_steady = True
                if phase_fn is not None:
                    # The chaos engine's drain-clock hook (fleet/chaos.py):
                    # learner-boundary faults fire here, between phases.
                    phase_fn(drained)
                if (
                    ckpt is not None
                    and checkpoint_every > 0
                    and drained % checkpoint_every == 0
                ):
                    self._save_checkpoint(
                        ckpt, drained, state, cstate, lstate,
                        {
                            "drained": drained,
                            "env_steps_total": env_steps_total,
                            "ep_return_sum": ep_ret_sum,
                            "ep_count": ep_count,
                            "episodes_total": episodes_total,
                            "param_version": version,
                        },
                    )
                if drained % max(self.config.publish_every, 1) == 0:
                    version += 1
                    self.server.publish_params(
                        version, self._snapshot_params(lstate)
                    )
                    # Flight-ring discipline (training/pipeline.py
                    # _publish): record on the log cadence only, so
                    # publishes don't evict the rare events.
                    if log_every and drained % log_every == 0:
                        flight_event("param_publish", version=version)
                if log_every and drained % log_every == 0:
                    # The dp learner's per-shard gauges ride THIS batched
                    # fetch (Trainer._log_extra_refs — no fetches of
                    # their own on the hot path; ISSUE 9 obs satellite).
                    # expected(): the extra refs build small eager
                    # reductions on first use — declared, not an alarm.
                    with mon.expected("log_fetch"):
                        extra = t._log_extra_refs(lstate.arena)
                        lstep, m, *extra_vals = jax.device_get(
                            (lstate.train.step, last_metrics, *extra)
                        )
                    if extra:
                        t._log_extra_publish(extra_vals)
                    scalars = {
                        "episode_return_mean": ep_ret_sum / max(ep_count, 1.0),
                        "episodes": ep_count,
                        "env_steps": env_steps_total,
                        "learner_steps": float(lstep),
                        **{k: float(v) for k, v in m.items()},
                    }
                    ep_ret_sum = 0.0
                    ep_count = 0.0
                    t._obs_publish(scalars)
                    emit_log(drained, scalars)
        finally:
            jax.block_until_ready(lstate.train.step)
            # Disarm the sentinel + close any open profiler capture:
            # teardown/checkpoint compiles are a new window's business.
            mon.end_run()
            # The run's honest end — BEFORE reaping the warm thread, so
            # a pending width compile can't inflate the measured walls.
            t_end = time.monotonic()
            # Reap the width-precompile thread BEFORE teardown: a daemon
            # still inside an XLA compile when the interpreter exits
            # std::terminates the process (observed rc=134 on short
            # runs).  The stop flag caps the wait at the in-flight
            # compile; the join itself is unbounded because the thread
            # always terminates (compile returns or raises).
            self._warm_stop.set()
            if self._warm_thread is not None:
                self._warm_thread.join()
            wall = max(t_end - t0, 1e-9)
            _, lw_total, lw_p50, lw_p99 = self.learner_wait.snapshot()
            _, aw_total, _, _ = self.absorb_wait.snapshot()
            srv = self.server
            # Rates are per-INCARNATION (phases this process ran over this
            # process's wall clock); the monotone totals live in counters().
            drained_here = drained - drained_at_start
            self._counters = {
                "drained": float(drained),
                "env_steps_total": env_steps_total,
                "ep_return_sum": ep_ret_sum,
                "ep_count": ep_count,
                "episodes_total": episodes_total,
                "param_version": float(version),
            }
            self._stats = {
                "train_phases": float(drained_here),
                "train_phases_total": float(drained),
                "absorbed_seqs": float(absorbed),
                "wall_s": wall,
                "learner_steps_per_sec": (
                    drained_here * t.config.learner_steps / wall
                ),
                "arena_add_seqs_per_sec": absorbed / wall,
                "sheds": float(self.server.shed_total),
                "learner_wait_p50_ms": lw_p50 * 1e3,
                "learner_wait_p99_ms": lw_p99 * 1e3,
                "learner_wait_total_s": lw_total,
                "absorb_wait_s": aw_total,
                # The pipelined executor's overlap instrumentation on the
                # fleet schedule (ISSUE 11): fraction of the wall during
                # which the learner had staged data available — same
                # definition as PipelineExecutor.stats (1 - wait / wall).
                # Absorb waits still count as un-overlapped here even
                # though /health judges only the post-absorb histogram.
                "overlap_fraction": max(
                    0.0, 1.0 - (lw_total + aw_total) / wall
                ),
                # Wire accounting (docs/FLEET.md "Wire format"): frame
                # bytes as received vs the declared decompressed size.
                "bytes_in_total": float(srv.seqs_bytes_total),
                "bytes_per_seq": (
                    srv.seqs_bytes_total / max(srv.seqs_received_total, 1)
                ),
                # Bytes crossing into the TRAINING path per trained
                # sequence: under the central drain, EVERY collected
                # sequence crosses the wire into the arena whether or not
                # it is ever sampled — the in-network sampler's headline
                # comparison (bench.py fleet_sampler; docs/REPLAY.md).
                "bytes_per_trained_seq": (
                    srv.seqs_bytes_total
                    / max(
                        drained_here
                        * t.config.learner_steps
                        * t.config.batch_size,
                        1,
                    )
                ),
                "wire_ratio": (
                    srv.seqs_raw_bytes_total / max(srv.seqs_bytes_total, 1)
                ),
                "drain_coalesce_width_mean": (
                    coalesce_sum / max(coalesce_n, 1)
                ),
                # Experience-quality columns (obs/quality.py; the bench
                # fleet leg's algorithm-health read — -1 means the
                # signal never armed this run).
                **quality_stats_columns(),
                # Device plane (ISSUE 14): this run's compile ledger +
                # peak HBM — the bench columns, and what an evidence
                # gate reads off the printed stats line.
                **mon.run_stats(),
            }
            if train_t0 is not None:
                # Steady-state window rates (the bench probe's keys): the
                # plain *_per_sec above span the WHOLE run, startup
                # included — honest for operations, wrong for throughput
                # comparisons.
                train_wall = max(t_end - train_t0, 1e-9)
                self._stats["train_wall_s"] = train_wall
                self._stats["train_arena_add_seqs_per_sec"] = (
                    absorbed - seqs_at_train_t0
                ) / train_wall
                self._stats["train_learner_steps_per_sec"] = (
                    max(drained_here - 1, 0)
                    * t.config.learner_steps
                    / train_wall
                )
        # phase_idx is a collector-slice field the fleet learner never
        # advances; stamp the drained-phase count so the final checkpoint
        # step (and any tooling keyed on it) reflects the trained run.
        return dataclasses.replace(
            merge_state(state, cstate, lstate),
            phase_idx=cstate.phase_idx + drained,
        )

    def _snapshot_params(self, lstate: LearnerState) -> Any:
        return snapshot_params(lstate.train)


def snapshot_params(train) -> Any:
    """The published snapshot: everything an actor needs to act AND to
    rank fresh sequences locally (``agent.initial_priority`` burns in
    online + target nets of both cores — Ape-X actors rank with their
    stale copies of all four).  ONE definition for both learners (the
    central ``FleetLearner`` and the sampler's ``SamplerLearner``): a
    published field added here reaches every fleet flavor."""
    return to_host(
        {
            "actor_params": train.actor_params,
            "critic_params": train.critic_params,
            "target_actor_params": train.target_actor_params,
            "target_critic_params": train.target_critic_params,
            "step": train.step,
        }
    )
