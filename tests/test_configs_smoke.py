"""Every BASELINE DMC config drives end-to-end through the real CLI.

Tiny overrides keep each run to a couple of train phases; the point is that
each config's full path — env pool (native / Python / pixels+EGL), action
repeat, CNN/LSTM nets, prioritized replay, learner updates — executes and
produces finite metrics (SURVEY.md §4.3's integration matrix, configs #3-#5;
the pendulum configs #1-#2 are covered by test_trainer / test_utils).
"""

import numpy as np
import pytest

from r2d2dpg_tpu.train import parse_args, run

pytestmark = pytest.mark.slow


@pytest.mark.parametrize(
    "config", ["walker_r2d2", "humanoid_r2d2", "cheetah_pixels"]
)
def test_config_cli_smoke(config, tmp_path):
    args = parse_args(
        [
            "--config", config,
            "--num-envs", "4",
            "--batch-size", "4",
            "--min-replay", "8",
            "--phases", "2",
            "--log-every", "1",
            "--logdir", str(tmp_path / config),
        ]
    )
    final = run(args)
    assert final["env_steps"] > 0
    for key in ("critic_loss", "actor_loss", "q_mean"):
        assert np.isfinite(final[key]), (key, final)
