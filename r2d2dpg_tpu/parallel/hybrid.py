"""Multi-chip training for host-backed (dm_control) env pools.

Reference parity: SURVEY.md §2.8 / §5.8.  The pure-JAX ``SPMDTrainer`` runs
whole phases under ``shard_map``, which cannot contain the ordered
``io_callback`` a host env pool needs.  This trainer closes that gap (the
"known delta #3" of docs/PARITY.md) with the pjit layout style instead:

- every device-resident piece — policy forward, exploration noise, window
  assembler, HBM replay arena, the full learner step — runs under ``jit``
  on arrays laid out over the ``dp`` mesh axis via ``NamedSharding``
  (envs, window, arena, and batch sharded; params replicated);
- gradient synchronization needs no explicit collective: with replicated
  params and a dp-sharded batch, XLA inserts the ``psum`` over ICI on its
  own (the pjit/GSPMD recipe — pick a mesh, annotate shardings, let XLA
  place collectives);
- only the MuJoCo physics step leaves the device: once per collected agent
  step the [E, act] actions cross to host, the C++/Python pool steps all E
  envs, and the [E, obs] batch crosses back, sharded straight onto the mesh.

On one host this trains the DM-Control configs across all local chips.
Multi-host needs one pool per process plus
``jax.make_array_from_process_local_data`` for the obs batch — the
``parallel.distributed`` initializer is the entry point for that; single
host is what this box can validate (8-device virtual CPU mesh in tests).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from r2d2dpg_tpu.agents.ddpg import R2D2DPG
from r2d2dpg_tpu.envs.dmc_host import DMCHostEnv
from r2d2dpg_tpu.parallel.mesh import DP_AXIS
from r2d2dpg_tpu.parallel.spmd import _state_spec
from r2d2dpg_tpu.training.assembler import StepRecord, shift_in
from r2d2dpg_tpu.training.trainer import Trainer, TrainerConfig, TrainerState


class HostSPMDTrainer(Trainer):
    """dp-sharded training with the env fleet stepped from the host.

    ``config`` is global (fleet-wide env count, global batch size, total
    capacity); jitted functions see global shapes and XLA splits the work
    across the mesh from the array shardings.
    """

    axis = None  # pjit style: no named axis, XLA inserts the collectives

    def __init__(
        self,
        env: DMCHostEnv,
        agent: R2D2DPG,
        config: TrainerConfig,
        mesh: Mesh,
    ):
        if not getattr(env, "batched", False) or not hasattr(env, "host_step"):
            raise ValueError(
                "HostSPMDTrainer is for host-pool envs (DMCHostEnv); pure-JAX "
                "envs scale with parallel.SPMDTrainer instead"
            )
        if agent.config.axis_name is not None:
            raise ValueError(
                "HostSPMDTrainer uses pjit-style gradient sync; build the "
                "agent with axis_name=None (got "
                f"{agent.config.axis_name!r})"
            )
        if jax.process_count() > 1:
            raise ValueError(
                "HostSPMDTrainer is single-process: a multi-host pod needs "
                "one env pool per process plus "
                "jax.make_array_from_process_local_data for the obs batch "
                "(see parallel.distributed) — not yet wired up"
            )
        d = mesh.shape[DP_AXIS]
        # The arena is replicated (see layout note in _build_phases), so only
        # the genuinely dp-sharded axes need to divide the mesh.
        for field in ("num_envs", "batch_size"):
            if getattr(config, field) % d:
                raise ValueError(
                    f"TrainerConfig.{field}={getattr(config, field)} must "
                    f"be divisible by the mesh size {d}"
                )
        self.mesh = mesh
        self.num_devices = d
        super().__init__(env, agent, config)
        # Arena buffers carry explicit mesh shardings -> XLA scatter path.
        self.arena.use_pallas = False

    # --------------------------------------------------------------- builds
    def _build_phases(self):
        mesh = self.mesh
        # Layout deltas vs the shard_map spec: the host pool owns the real
        # env state (the device token is a scalar -> replicated), and the
        # replay arena is REPLICATED rather than capacity-sharded — per-chip
        # memory equals the single-chip arena, global adds cost one small
        # all-gather of E fresh sequences per phase, and every chip samples
        # the same global batch whose compute is then resharded over dp
        # (``_reshard_batch``).  This keeps the arena's gather/scatter free
        # of cross-shard index collectives.
        from r2d2dpg_tpu.replay.arena import ArenaState

        spec = dataclasses.replace(
            _state_spec(),
            env_state=P(),
            arena=ArenaState(data=P(), priority=P(), cursor=P(), total_added=P()),
        )
        self._shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            spec,
            is_leaf=lambda x: isinstance(x, P),
        )
        self._replicated = NamedSharding(mesh, P())
        self._dp1 = NamedSharding(mesh, P(DP_AXIS))  # [E, ...] leading axis
        self._dp2 = NamedSharding(mesh, P(None, DP_AXIS))  # [T, E] stacks
        self._act_step = jax.jit(self._act_step_impl)
        # One dispatch per phase instead of one jnp.where per param leaf
        # (ADVICE r1: _behavior_params evaluated eagerly was pure host-loop
        # overhead on the hot collect path).
        self._collect_setup = jax.jit(self._collect_setup_impl)
        # No donation: the state's obs/reset/carry buffers are also passed
        # as the t=0 entries of the per-step tuples (f(donate(a), a) is
        # rejected by PJRT on real devices).
        self._absorb = jax.jit(self._absorb_impl)
        self._emit_learn = jax.jit(self._emit_learn_impl, donate_argnums=(0,))
        self._emit_only = jax.jit(self._emit_and_add, donate_argnums=(0,))

    # ----------------------------------------------------------------- init
    def init(self, key: Optional[jax.Array] = None) -> TrainerState:
        state = super().init(key)  # eager io_callback reset fills the pool
        return jax.device_put(state, self._shardings)

    # --------------------------------------------------------- device parts
    def _collect_setup_impl(self, state: TrainerState):
        """Per-phase device prep: behavior snapshot + the stride's RNG keys.

        With ``param_sync_every > 0`` the snapshot must also PERSIST (the
        base trainer stores it before collecting so the params acted with
        are exactly the ones carried until the next sync phase); returning
        the updated state from here keeps that store inside this one jitted
        dispatch instead of an eager per-leaf ``jnp.where`` in train_phase.
        """
        rng, sk = jax.random.split(state.rng)
        keys = jax.random.split(sk, self.config.stride)
        behavior = self._behavior_params(state)
        if self.config.param_sync_every > 0:
            state = dataclasses.replace(state, behavior_params=behavior)
        return state, behavior, keys, rng

    def _act_step_impl(
        self, behavior, critic_params, obs, reset, a_carry, c_carry, noise_st,
        keys, t
    ):
        """One policy step for the whole fleet (the device half of hot loop A);
        the semantics live in Trainer._policy_step, shared with the in-graph
        scan collect.  ``keys`` is the phase's [stride, key] stack and ``t``
        a traced scalar so the per-step key gather happens in-graph (no eager
        host indexing per step)."""
        return self._policy_step(
            behavior, critic_params, obs, reset, a_carry, c_carry, noise_st,
            self._local_sigmas(), keys[t],
        )

    def _absorb_impl(
        self,
        state: TrainerState,
        obs_T: Tuple[jnp.ndarray, ...],  # T x [E, obs] — pre-step obs
        reset_T: Tuple[jnp.ndarray, ...],  # T x [E] — pre-step reset flags
        act_T: Tuple[jnp.ndarray, ...],  # T x [E, A]
        a_car_T: Tuple[Any, ...],  # T x carry — pre-step carries
        c_car_T: Tuple[Any, ...],
        rew_T: jnp.ndarray,  # [T, E] from host
        disc_T: jnp.ndarray,  # [T, E]
        done_T: jnp.ndarray,  # [T, E] post-step reset flags
        obs_next: jnp.ndarray,
        reset_next: jnp.ndarray,
        a_carry,
        c_carry,
        noise_st,
        rng,
    ) -> TrainerState:
        """Fold one phase of host-collected steps into the TrainerState."""
        cfg = self.config
        stack = lambda xs: jnp.stack(xs)  # noqa: E731 — time-major [T, E, ...]
        records = StepRecord(
            obs=stack(obs_T),
            action=stack(act_T),
            reward=rew_T,
            discount=disc_T,
            reset=stack(reset_T),
            carries={
                "actor": jax.tree_util.tree_map(lambda *xs: stack(xs), *a_car_T)
                if jax.tree_util.tree_leaves(a_car_T[0])
                else a_car_T[0],
                "critic": jax.tree_util.tree_map(lambda *xs: stack(xs), *c_car_T)
                if jax.tree_util.tree_leaves(c_car_T[0])
                else c_car_T[0],
            },
        )

        def ep_step(ep, inp):
            r, done = inp
            ep = ep + r
            completed = (jnp.where(done > 0, ep, 0.0).sum(), (done > 0).sum())
            return jnp.where(done > 0, 0.0, ep), completed

        ep_ret, (comp_sum, comp_cnt) = jax.lax.scan(
            ep_step, state.episode_return, (rew_T, done_T)
        )

        return dataclasses.replace(
            state,
            obs=obs_next,
            reset=reset_next,
            actor_carry=a_carry,
            critic_carry=c_carry,
            noise_state=noise_st,
            rng=rng,
            env_steps=state.env_steps + cfg.stride * self.global_envs,
            episode_return=ep_ret,
            completed_return_sum=state.completed_return_sum + comp_sum.sum(),
            completed_count=state.completed_count + comp_cnt.sum(),
            window=shift_in(state.window, records),
            phase_idx=state.phase_idx + 1,
        )

    def _emit_learn_impl(
        self, state: TrainerState
    ) -> Tuple[TrainerState, Dict[str, jnp.ndarray]]:
        return self._learn(self._emit_and_add(state))

    # ----------------------------------------------------------- reshards
    def _reshard_add(self, seq, prios):
        """Replicate the E fresh sequences + priorities for the (replicated)
        arena add — after initial_priority ran on the dp-sharded layout."""
        rep = lambda x: jax.sharding.reshard(x, self._replicated)  # noqa: E731
        return jax.tree_util.tree_map(rep, seq), rep(prios)

    def _reshard_batch(self, batch):
        """Shard the sampled batch over dp so learner compute splits and XLA
        psums the gradients (params replicated + batch sharded)."""
        return jax.tree_util.tree_map(
            lambda x: jax.sharding.reshard(
                x, NamedSharding(self.mesh, P(*([DP_AXIS] + [None] * (x.ndim - 1))))
            ),
            batch,
        )

    # ------------------------------------------------------------ host loop
    def _put_fleet(self, x: np.ndarray) -> jnp.ndarray:
        """Lay a host [E, ...] batch out over the dp mesh axis."""
        return jax.device_put(x, self._dp1)

    def _host_collect(self, state: TrainerState) -> TrainerState:
        cfg = self.config
        state, behavior, keys, rng = self._collect_setup(state)
        critic_params = state.train.critic_params

        obs, reset = state.obs, state.reset
        a_carry, c_carry = state.actor_carry, state.critic_carry
        noise_st = state.noise_state
        obs_T, reset_T, act_T, a_car_T, c_car_T = [], [], [], [], []
        rew_T, disc_T, done_T = [], [], []

        for t in range(cfg.stride):
            obs_T.append(obs)
            reset_T.append(reset)
            a_car_T.append(a_carry)
            c_car_T.append(c_carry)
            action, a_carry, c_carry, noise_st = self._act_step(
                behavior, critic_params, obs, reset, a_carry, c_carry,
                noise_st, keys, np.int32(t),
            )
            act_T.append(action)
            # ═══ the one host<->device boundary per collected step ═══
            o, r, d, res = self.env.host_step(np.asarray(action))
            rew_T.append(r)
            disc_T.append(d)
            done_T.append(res)
            obs = self._put_fleet(o)
            reset = self._put_fleet(res)

        return self._absorb(
            state,
            tuple(obs_T),
            tuple(reset_T),
            tuple(act_T),
            tuple(a_car_T),
            tuple(c_car_T),
            jax.device_put(np.stack(rew_T), self._dp2),
            jax.device_put(np.stack(disc_T), self._dp2),
            jax.device_put(np.stack(done_T), self._dp2),
            obs,
            reset,
            a_carry,
            c_carry,
            noise_st,
            rng,
        )

    # --------------------------------------------------------------- phases
    def collect_phase(self, state: TrainerState) -> TrainerState:
        return self._host_collect(state)

    def fill_phase(self, state: TrainerState) -> TrainerState:
        return self._emit_only(self._host_collect(state))

    def train_phase(
        self, state: TrainerState
    ) -> Tuple[TrainerState, Dict[str, jnp.ndarray]]:
        # Behavior-snapshot persistence happens inside _collect_setup (jit).
        return self._emit_learn(self._host_collect(state))
