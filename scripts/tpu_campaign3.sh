#!/bin/bash
# Round-3 TPU measurement campaign (VERDICT r2 "Next round" #1/#2/#3).
#
# Differences from the round-2 campaign (scripts/attic/tpu_campaign2.sh):
#   * ORDER: phase_throughput runs FIRST and its result picks the
#     north-star overlap/learner-steps flags (VERDICT weak #2: "prove or
#     kill the overlap bet on-chip BEFORE the 30-min run spends its budget
#     on it").  Fallback when the probe lands nothing: sequential
#     (--overlap-learner 0) with the full 48 learner steps — the
#     non-overlap path dispatches emit+learn as ONE jitted call
#     (parallel/hybrid.py:_emit_learn_impl), so sequential density is
#     cheap-by-construction, while overlap's per-substep dispatch is the
#     unproven part.
#   * IDEMPOTENT: every step has a completion artifact and is skipped when
#     it already exists, so the watcher can re-fire this script after a
#     mid-campaign tunnel wedge and it resumes where it left off.
#   * WEDGE BAIL: a step that hits its `timeout` bound (rc 124/137) means
#     the tunnel hung; the campaign exits immediately instead of throwing
#     more clients at a dead tunnel (the watcher keeps probing and
#     re-fires when it recovers).
#   * BACKEND GATES: an artifact only counts if it was measured on the
#     chip.  Train steps stamp <logdir>/backend.txt (train.py) and earn
#     .done only when it says tpu/axon; JSON benches carry a "backend"
#     field that is validated before the artifact is accepted (a silent
#     CPU fallback is treated as a failed step and re-runs on re-fire).
#   * TERMINAL MARKER: campaign3.complete (which stops the watcher) is
#     written only when every step's artifact exists — or after
#     MAX_ATTEMPTS full passes, so a persistent non-tunnel failure can't
#     re-fire forever.
#   * Eval stdout is a JSON stream (one line per round + summary last) —
#     teed to *.jsonl, summary extracted to *.json (ADVICE r2 #1).
#   * Extra-flag drop-ins: runs/tpu/northstar_extra_flags (walker30 train
#     steps) and runs/tpu/cheetah_extra_flags (config #5) are appended if
#     present, so a build session can redirect an armed campaign without
#     editing a possibly-running script.
#
# Every TPU client is separated from the previous one by >=60 s (the
# round-2 wedge lesson, .claude/skills/verify/SKILL.md).
HERE="$(cd "$(dirname "$0")" && pwd)"
cd "$HERE/.."
mkdir -p runs/tpu
exec >> runs/tpu/campaign3.log 2>&1
set -o pipefail  # let a timed-out producer fail the whole `... | tee` step
echo "=== TPU campaign3 start $(date) ==="

# Full passes that ended with missing artifacts (wedge-aborts don't count —
# those are tunnel weather, not code failures; the watcher retries them for
# free).  After MAX_ATTEMPTS such passes the campaign gives up so a
# persistent non-tunnel failure can't re-fire forever.
MAX_ATTEMPTS=5

# Preempt every prior driver and JAX client class (kill-list covers the
# retired v1/v2 automation and all CPU evidence drivers; NOT tpu_watcher3,
# which is this script's parent).  TERM first; escalate to KILL for
# anything stuck in an RPC, then settle 60 s.
VICTIMS='chain_runs|cheetah_then_humanoid|humanoid_retry|walker_long|walker_probe|tpu_campaign\.sh|tpu_campaign2|tpu_watcher\.sh|tpu_watcher2|r2d2dpg_tpu\.(train|eval)|bench\.py|phase_throughput|env_throughput'
pkill -f "$VICTIMS"
for i in $(seq 12); do
  pgrep -f "$VICTIMS" > /dev/null || break
  sleep 5
done
pgrep -f "$VICTIMS" > /dev/null && pkill -9 -f "$VICTIMS"
sleep 60

# rc 124 = `timeout` fired TERM; 137 = escalated KILL (or the kernel's OOM
# killer).  Either way the step died abnormally — stop the campaign (the
# watcher re-fires when the tunnel answers).  Wedge-aborts are budgeted
# separately from failed full passes: rc 137 can also be a persistent
# non-tunnel failure (e.g. OOM at the same step every time), so after
# MAX_WEDGES aborts the campaign gives up rather than re-firing forever.
# Re-arm the preempted CPU evidence queue (walker_probe was in VICTIMS; it
# skips probes whose artifacts already landed; the cheetah/bf16 drivers
# survive preemption on their own retry loops).
resume_cpu_queue() {
  # Round-5 evidence chain (combo/mpbf16/cheetah-twin/ns3-long).  NOT the
  # round-3 walker_probe.sh sweep: its artifacts did not survive the round
  # boundary (runs/ is ephemeral), so relaunching it would re-run hours of
  # already-answered probes on the single core.
  bash "$HERE/arm_cpu_queue.sh"
}

MAX_WEDGES=8
bail_if_wedged() {
  local rc=$1 step=$2
  if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    local w=$(($(cat runs/tpu/campaign3.wedges 2>/dev/null || echo 0) + 1))
    echo "$w" > runs/tpu/campaign3.wedges
    echo "!!! step '$step' hit its timeout/kill bound (rc=$rc) — abort #$w/$MAX_WEDGES $(date)"
    if [ "$w" -ge "$MAX_WEDGES" ]; then
      touch runs/tpu/campaign3.complete
      echo "=== TPU campaign3 wedge budget spent; giving up $(date) ==="
    fi
    # The tunnel may stay down for hours — give the single core back to
    # the CPU evidence queue meanwhile (the next re-fire preempts it again).
    resume_cpu_queue
    echo "=== TPU campaign3 ABORT $(date) ==="
    exit 1
  fi
}

# True iff FILE is a JSON-lines artifact whose every row says backend
# tpu/axon (a CPU-fallback measurement must not satisfy a skip guard).
json_backend_ok() {
  python - "$1" <<'EOF'
import json, sys
try:
    rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
    ok = bool(rows) and all(r.get("backend") in ("tpu", "axon") for r in rows)
except Exception:
    ok = False
sys.exit(0 if ok else 1)
EOF
}

# True iff DIR/backend.txt (stamped by train.py) says tpu/axon.
train_backend_ok() {
  grep -qE '^(tpu|axon)$' "$1/backend.txt" 2>/dev/null
}

# JSON-lines bench runner: $1 = artifact path, $2 = step name, $3 = timeout
# seconds, $4.. = command.  Skips when an on-chip artifact exists; accepts
# the .partial only on rc=0 with every row stamped tpu/axon.
run_bench() {
  local artifact=$1 step=$2 tmo=$3; shift 3
  if [ -s "$artifact" ] && json_backend_ok "$artifact"; then
    echo "--- $step: on-chip artifact exists, skipping $(date) ---"
    return
  fi
  rm -f "$artifact"   # stale or CPU-backend artifact
  echo "--- $step (TPU) $(date) ---"
  rm -f "$artifact.partial"
  timeout --kill-after=30 --signal=TERM "$tmo" "$@" | tee "$artifact.partial"
  local rc=$?
  bail_if_wedged $rc "$step"
  if [ $rc -eq 0 ] && json_backend_ok "$artifact.partial"; then
    mv "$artifact.partial" "$artifact"
  else
    echo "$step FAILED (rc=$rc or non-TPU backend); left .partial"
  fi
  sleep 60
}

# --------------------------------------------------------------- step 0
# North-star insurance (VERDICT r4 next #1): the campaign has twice died
# inside the 1800s phase_throughput step when the tunnel window closed,
# leaving four rounds with ZERO on-chip walker training artifacts.  So
# before spending a (possibly brief) window on flag tuning, bank a
# bounded walker SLICE — 10 min of config-default training + a short
# eval — the first on-chip return@wall-clock row, whatever the number.
# Skipped once any on-chip walker artifact exists.
if [ -f runs/tpu/walker30/.done ] || [ -f runs/tpu/walker30_slice/.done ]; then
  echo "--- walker30_slice: on-chip walker artifact already banked, skipping $(date) ---"
else
  echo "--- walker30_slice: 10-min north-star insurance $(date) ---"
  if [ -d runs/tpu/walker30_slice ]; then
    mv runs/tpu/walker30_slice "runs/tpu/walker30_slice.partial.$(date +%s)"
  fi
  mkdir -p runs/tpu/walker30_slice
  # Sequential-48 (the documented no-measurement fallback): the overlap
  # bet is exactly what phase_throughput has not yet proven.
  timeout --kill-after=60 --signal=TERM 1500 python -m r2d2dpg_tpu.train --config walker_r2d2 \
    --num-envs 64 --batch-size 64 --overlap-learner 0 --learner-steps 48 \
    --minutes 10 --log-every 10 --eval-every 100 --eval-envs 5 \
    --logdir runs/tpu/walker30_slice --checkpoint-dir runs/tpu/walker30_slice/ckpt \
    --checkpoint-every -1 --checkpoint-light | tail -30
  rc=$?
  bail_if_wedged $rc walker30_slice
  if [ $rc -eq 0 ] && train_backend_ok runs/tpu/walker30_slice; then
    touch runs/tpu/walker30_slice/.done
  else
    echo "walker30_slice FAILED (rc=$rc, backend=$(cat runs/tpu/walker30_slice/backend.txt 2>/dev/null || echo none)); preserving partial"
    mv "runs/tpu/walker30_slice" "runs/tpu/walker30_slice.failed.$(date +%s)"
  fi
  sleep 60
fi
if [ -f runs/tpu/walker30_slice/.done ] && [ ! -s runs/tpu/walker30_slice_eval.json ] \
   && [ -d runs/tpu/walker30_slice/ckpt ] && [ -n "$(ls runs/tpu/walker30_slice/ckpt 2>/dev/null)" ]; then
  echo "--- walker30_slice deterministic eval $(date) ---"
  timeout --kill-after=30 --signal=TERM 600 python -m r2d2dpg_tpu.eval --config walker_r2d2 \
    --checkpoint-dir runs/tpu/walker30_slice/ckpt --episodes 5 --rounds 2 \
    | tee runs/tpu/walker30_slice_eval.jsonl
  rc=$?
  bail_if_wedged $rc walker30_slice_eval
  [ $rc -eq 0 ] && tail -1 runs/tpu/walker30_slice_eval.jsonl > runs/tpu/walker30_slice_eval.json
  sleep 60
fi

# --------------------------------------------------------------- step 1
# Overlap proof at walker shapes (64 envs / stride 20 / 48 learner steps),
# plus a 192-density overlap row — on-chip the learner is ~free, so if the
# phase rate holds at 192 interleaved updates the north star runs at
# ratio ~1:7 instead of 1:26.  An artifact from an older campaign pass
# that predates the 192 row is stale — without this, run_bench would skip
# the re-measure and the flag picker could never choose the density.
if [ -s runs/tpu/phase_throughput.json ] \
   && ! grep -q overlap_ls192 runs/tpu/phase_throughput.json; then
  echo "phase_throughput artifact lacks the overlap_ls192 row; re-measuring"
  rm -f runs/tpu/phase_throughput.json
fi
run_bench runs/tpu/phase_throughput.json phase_throughput 1800 \
  python benchmarks/phase_throughput.py 64 12 48 192

# Pick north-star flags from the on-chip measurement (sequential-48
# fallback — see header).  Only a tpu/axon-backend artifact counts.
python - <<'EOF'
import json, os
flags = "--overlap-learner 0 --learner-steps 48"  # fallback: see header
path = "runs/tpu/phase_throughput.json"
try:
    rows = {}
    with open(path) as f:
        for line in f:
            if line.strip():
                r = json.loads(line)
                key = r["metric"].split("walker_phase_throughput_", 1)[-1]
                rows[key] = r["phases_per_sec"]
                assert r.get("backend") in ("tpu", "axon"), r
    col, seq, ovl = rows["collect"], rows["sequential"], rows["overlap"]
    if ovl >= 0.95 * seq:
        flags = "--overlap-learner 1 --learner-steps 48"
        # Densest sustainable overlap wins: 192 interleaved updates if the
        # phase rate holds within 10% of overlap-48.
        if rows.get("overlap_ls192", 0) >= 0.9 * ovl:
            flags = "--overlap-learner 1 --learner-steps 192"
    why = f"measured on-chip phases/s: {rows}"
except Exception as e:  # noqa: BLE001 — missing/partial/CPU artifact
    why = f"no usable on-chip measurement ({e}); using documented fallback"
with open("runs/tpu/northstar_flags", "w") as f:
    f.write(flags + "\n")
print(f"north-star flags: {flags}  [{why}]", flush=True)
EOF
NORTHSTAR_FLAGS="$(head -1 runs/tpu/northstar_flags)"
EXTRA_FLAGS=""
[ -f runs/tpu/northstar_extra_flags ] && EXTRA_FLAGS="$(head -1 runs/tpu/northstar_extra_flags)"
echo "north-star will run with: $NORTHSTAR_FLAGS $EXTRA_FLAGS"

# Checkpoint-shape-affecting flags that eval must repeat to restore a
# matching template (eval supports exactly these two).  Flags arrive as
# argv (no shell-into-python interpolation) and both argparse spellings
# ("--flag value" and "--flag=value") are recognized.
shape_flags() {
  python - "$@" <<'EOF'
import sys
toks = []
for t in sys.argv[1:]:
    toks.extend(t.split("=", 1) if t.startswith("--") and "=" in t else [t])
out = []
for i, t in enumerate(toks):
    if t in ("--twin-critic", "--compute-dtype") and i + 1 < len(toks):
        out += [t, toks[i + 1]]
print(" ".join(out))
EOF
}

# ----------------------------------------------------------- steps 2 + 3
# One 30-min walker train + deterministic eval; $1 = run name,
# $2.. = extra train flags.  .done requires rc=0 AND an on-chip backend
# stamp; a partial/CPU run is moved aside (forensics) and the re-fire
# starts a fresh directory (wall-clock purity: never resume a partial
# 30-min measurement).
run_walker() {
  local name=$1; shift
  if [ -f "runs/tpu/$name/.done" ]; then
    echo "--- $name: already done, skipping $(date) ---"
  else
    echo "--- $name: walker 30 min on TPU ($*) $(date) ---"
    # Preserve a wedge-interrupted partial (its metrics.csv is evidence)
    # rather than deleting it; the fresh run still starts clean.
    if [ -d "runs/tpu/$name" ]; then
      mv "runs/tpu/$name" "runs/tpu/$name.partial.$(date +%s)"
    fi
    mkdir -p "runs/tpu/$name"
    # Flag precedence (argparse last-wins): tunable defaults < chosen
    # overlap flags < generic drop-in < this run's own flags ("$@" so the
    # drop-in cannot clobber what distinguishes walker30_bf16) < the
    # INFRASTRUCTURE flags, which stay last so no drop-in can redirect
    # --logdir/--minutes/--checkpoint-dir out from under the step's
    # timeout bound and backend gate.
    # checkpoint-every -1 + light = ONE learner-subtree save at the
    # deadline (MBs): periodic/full saves would drag the ~1 GB
    # TrainerState (replay arena included) device->host through the
    # tunnel mid-measurement, and the deterministic eval restores only
    # the learner subtree anyway.  Wedged/failed runs are moved aside, not deleted.
    timeout --kill-after=60 --signal=TERM 2700 python -m r2d2dpg_tpu.train --config walker_r2d2 \
      --num-envs 64 --batch-size 64 \
      $NORTHSTAR_FLAGS $EXTRA_FLAGS "$@" \
      --minutes 30 --log-every 10 --eval-every 200 --eval-envs 5 \
      --logdir "runs/tpu/$name" --checkpoint-dir "runs/tpu/$name/ckpt" \
      --checkpoint-every -1 --checkpoint-light | tail -40
    local rc=$?
    bail_if_wedged $rc "$name"
    if [ $rc -eq 0 ] && train_backend_ok "runs/tpu/$name"; then
      touch "runs/tpu/$name/.done"
    else
      echo "$name FAILED (rc=$rc, backend=$(cat runs/tpu/$name/backend.txt 2>/dev/null || echo none)); preserving partial for forensics"
      mv "runs/tpu/$name" "runs/tpu/$name.failed.$(date +%s)"
    fi
    sleep 60
  fi

  if [ -s "runs/tpu/${name}_eval.json" ]; then
    echo "--- $name eval: artifact exists, skipping $(date) ---"
  elif [ -d "runs/tpu/$name/ckpt" ] && [ -n "$(ls runs/tpu/$name/ckpt 2>/dev/null)" ]; then
    echo "--- $name deterministic eval $(date) ---"
    # Repeat the shape-affecting train flags (drop-in first, "$@" last to
    # match the train command's precedence) or the restore template won't
    # match the checkpoint tree.
    timeout --kill-after=30 --signal=TERM 900 python -m r2d2dpg_tpu.eval --config walker_r2d2 \
      $(shape_flags $EXTRA_FLAGS "$@") \
      --checkpoint-dir "runs/tpu/$name/ckpt" --episodes 10 --rounds 2 \
      | tee "runs/tpu/${name}_eval.jsonl"
    local rc=$?
    bail_if_wedged $rc "${name}_eval"
    [ $rc -eq 0 ] && tail -1 "runs/tpu/${name}_eval.jsonl" > "runs/tpu/${name}_eval.json"
    sleep 60
  else
    echo "$name: no checkpoint — skipping eval"
  fi
}

run_walker walker30
run_walker walker30_bf16 --compute-dtype bfloat16

# --------------------------------------------------------------- step 4
# Mixed-precision cell throughput (VERDICT r4 next #4): the 31,282
# steps/s bf16 headline was measured on the OLD truncated-carry cell;
# the round-4 MixedPrecisionLSTMCell adds fp32 elementwise state math +
# casts and has no TPU number.  Two rows, same harness as the driver's
# headline bench (bench.py worker invoked directly — its outer main()
# preempts watcher/campaign automation, i.e. this script's own parent).
run_bench runs/tpu/bench_cell_fp32.json bench_cell_fp32 600 \
  env R2D2DPG_BENCH_WORKER=1 python bench.py float32
run_bench runs/tpu/bench_cell_bf16.json bench_cell_bf16 600 \
  env R2D2DPG_BENCH_WORKER=1 python bench.py bfloat16

run_bench runs/tpu/env_pendulum.json env_throughput 600 \
  python benchmarks/env_throughput.py 1024 200 pendulum

# ----------------------------------------------------------- steps 5 + 6
# 100-min learning-curve runs for configs #5/#4; $1 = name, $2 = config,
# $3.. = flags.  Same backend-gated .done as run_walker.
run_curve() {
  local name=$1 config=$2; shift 2
  if [ -f "runs/tpu/$name/.done" ]; then
    echo "--- $name: already done, skipping $(date) ---"
    return
  fi
  echo "--- $name ($config: $*) $(date) ---"
  if [ -d "runs/tpu/$name" ]; then
    mv "runs/tpu/$name" "runs/tpu/$name.partial.$(date +%s)"
  fi
  mkdir -p "runs/tpu/$name"
  # Tunables ("$@", incl. any drop-in) first; infrastructure flags last
  # and un-clobberable (same rationale as run_walker).  Periodic LIGHT
  # checkpoints (learner subtree, MBs): the pixel/humanoid arenas are GBs
  # and the deliverable is the metrics.csv curve — light saves add wedge
  # resilience and post-hoc eval without the arena transfer cost.
  timeout --kill-after=60 --signal=TERM 6900 python -m r2d2dpg_tpu.train --config "$config" \
    "$@" \
    --minutes 100 --log-every 10 --eval-every 150 --eval-envs 3 \
    --logdir "runs/tpu/$name" --checkpoint-dir "runs/tpu/$name/ckpt" \
    --checkpoint-every 300 --checkpoint-light | tail -30
  local rc=$?
  bail_if_wedged $rc "$name"
  if [ $rc -eq 0 ] && train_backend_ok "runs/tpu/$name"; then
    touch "runs/tpu/$name/.done"
  else
    echo "$name FAILED (rc=$rc, backend=$(cat runs/tpu/$name/backend.txt 2>/dev/null || echo none)); preserving partial for forensics"
    mv "runs/tpu/$name" "runs/tpu/$name.failed.$(date +%s)"
  fi
  sleep 60
}

CHEETAH_EXTRA=""
[ -f runs/tpu/cheetah_extra_flags ] && CHEETAH_EXTRA="$(head -1 runs/tpu/cheetah_extra_flags)"
run_curve cheetah_pixels cheetah_pixels \
  --num-envs 8 --learner-steps 8 --batch-size 16 --min-replay 200 \
  --overlap-learner 1 $CHEETAH_EXTRA
run_curve humanoid humanoid_r2d2 \
  --num-envs 16 --learner-steps 16 --batch-size 32 --min-replay 300 \
  --overlap-learner 1

# ------------------------------------------------------------- terminal
# Stop the watcher only when everything landed, or the attempt budget is
# spent (persistent non-tunnel failure must not re-fire forever).
ALL_DONE=1
for a in runs/tpu/phase_throughput.json runs/tpu/walker30/.done \
         runs/tpu/walker30_eval.json runs/tpu/walker30_bf16/.done \
         runs/tpu/walker30_bf16_eval.json runs/tpu/env_pendulum.json \
         runs/tpu/bench_cell_fp32.json runs/tpu/bench_cell_bf16.json \
         runs/tpu/cheetah_pixels/.done runs/tpu/humanoid/.done; do
  [ -e "$a" ] || { echo "missing artifact: $a"; ALL_DONE=0; }
done
resume_cpu_queue

if [ "$ALL_DONE" -eq 1 ]; then
  touch runs/tpu/campaign3.complete
  echo "=== TPU campaign3 COMPLETE $(date) ==="
else
  ATTEMPTS=$(($(cat runs/tpu/campaign3.attempts 2>/dev/null || echo 0) + 1))
  echo "$ATTEMPTS" > runs/tpu/campaign3.attempts
  if [ "$ATTEMPTS" -ge "$MAX_ATTEMPTS" ]; then
    touch runs/tpu/campaign3.complete
    echo "=== TPU campaign3 attempt budget spent ($ATTEMPTS); marking complete with missing artifacts $(date) ==="
  else
    echo "=== TPU campaign3 pass $ATTEMPTS/$MAX_ATTEMPTS finished with missing artifacts; watcher will re-fire $(date) ==="
  fi
fi
