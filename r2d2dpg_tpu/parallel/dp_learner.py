"""Data-parallel multi-chip learner: dp-sharded replay + batch, fed by ingest.

ISSUE 9 tentpole / ROADMAP "Break the learner ceiling": BENCH_FLEET.json
shows the fleet's single-chip learner STARVES at every fleet size
(learner_wait_p99 ~0.5 s, arena-add seqs/s flat from 1 to 3 actors) —
ingest stopped being the bottleneck in PR 5, the learner is.  This trainer
scales the learner side over the existing ``parallel/`` dp mesh in the
pjit layout style (annotate shardings, let GSPMD place the collectives —
the same recipe as ``HostSPMDTrainer``), while collection stays wherever
it already lives (fleet actor subprocesses under ``--actors N``, or the
in-graph collect under ``--actors 0``):

- **replay arena dp-sharded over capacity** — ``ArenaState.data`` /
  ``priority`` carry ``P(DP_AXIS)`` on axis 0, so replay capacity grows
  past one chip's HBM and the sample gather's bandwidth scales with the
  mesh (each shard gathers its rows; Accelerated Methods, PAPERS.md
  1803.02811, large-batch data parallelism).
- **learner batch dp-sharded, params replicated** — ``_reshard_batch``
  lays the sampled batch over dp, so the K-update ``lax.scan`` inside the
  one compiled drain dispatch (``Trainer._learn_many`` via
  ``training/pipeline.py::drain_staged``) splits its compute across the
  mesh and XLA psums the gradients.  K updates still cost ONE dispatch.
- **staged payloads mesh-placed before the drain** — ``_put_staged``
  mirrors the hybrid trainer's ``_put_fleet``: host numpy batches are
  laid over dp (``jax.make_array_from_process_local_data`` when
  multi-process), and ``_reshard_add`` replicates the B fresh rows only
  for the capacity-sharded ring scatter (B is small next to the arena).
- **everything else replicated** — train/optimizer/RNG/counters, and the
  env-side fields: with ``--actors 0`` the in-graph collect runs as the
  single logical stream the determinism anchor pins (a 1-device mesh is
  bit-identical to the base ``Trainer``; tests/test_dp_learner.py).

``SPMDTrainer`` (shard_map) remains the whole-loop-on-mesh design for
pure-JAX collect; this class is the LEARNER-side half that composes with
the fleet's host-visible drain boundary (``FleetLearner`` rejects
shard_map trainers).  docs/FLEET.md "Multi-chip learner" has the layout
table and the refused knob combos.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from r2d2dpg_tpu.agents.ddpg import R2D2DPG
from r2d2dpg_tpu.envs.core import Environment
from r2d2dpg_tpu.parallel.mesh import DP_AXIS
from r2d2dpg_tpu.replay.arena import ArenaState
from r2d2dpg_tpu.training.trainer import Trainer, TrainerConfig, TrainerState


class DPLearnerTrainer(Trainer):
    """dp-sharded replay + data-parallel learner in the pjit layout style.

    ``config`` is global (total capacity, global batch size); jitted
    programs see global shapes and XLA splits the work across the mesh
    from the array shardings.  ``axis`` stays ``None``: no named axis, no
    explicit collectives — replicated params + dp-sharded batch make
    GSPMD insert the gradient psum (the HostSPMDTrainer recipe, minus the
    host env pool: this trainer's envs are pure-JAX or fleet-remote).
    """

    axis = None  # pjit style: XLA inserts the gradient collectives

    def __init__(
        self,
        env: Environment,
        agent: R2D2DPG,
        config: TrainerConfig,
        mesh: Mesh,
    ):
        if agent.config.axis_name is not None:
            raise ValueError(
                "DPLearnerTrainer uses pjit-style gradient sync; build the "
                "agent with axis_name=None (got "
                f"{agent.config.axis_name!r})"
            )
        d = mesh.shape[DP_AXIS]
        # capacity: the arena shards over it; batch_size: the learner
        # splits over it; num_envs: staged batches arrive in multiples of
        # it, so the dp1 staged layout stays divisible at every coalesce
        # width (widths are num_envs multiples — replay/arena.stack_staged).
        for field in ("capacity", "batch_size", "num_envs"):
            if getattr(config, field) % d:
                raise ValueError(
                    f"TrainerConfig.{field}={getattr(config, field)} must "
                    f"be divisible by the mesh size {d}"
                )
        self.mesh = mesh
        self.num_devices = d
        self._nproc = jax.process_count()
        super().__init__(env, agent, config)
        # Arena buffers carry explicit mesh shardings -> XLA scatter path
        # (Pallas needs single-device refs; replay/arena.py).
        self.arena.use_pallas = False
        from r2d2dpg_tpu.obs import get_registry

        reg = get_registry()
        # ISSUE 9 obs satellite: per-shard arena occupancy (a skewed shard
        # = a skewed ring/scatter) and the per-shard rows of the most
        # recent staged drain dispatch.  Occupancy rides the log cadence's
        # batched device_get (_log_extra_refs); the width is host-known at
        # _put_staged time — neither adds a fetch to the hot path.
        self._obs_shard_occ = reg.gauge(
            "r2d2dpg_dp_shard_occupancy",
            "filled replay slots in this dp shard's capacity block",
            labelnames=("shard",),
        )
        self._obs_learn_width = reg.gauge(
            "r2d2dpg_dp_shard_learn_width",
            "staged sequences per dp shard in the most recent drain "
            "dispatch (global staged B / mesh size)",
        )

    # --------------------------------------------------------------- builds
    def _build_phases(self):
        mesh = self.mesh
        dp = P(DP_AXIS)
        # Layout: ONLY the learner side is sharded.  The arena shards over
        # capacity (axis 0 of data/priority — replay grows with the mesh);
        # train/behavior/RNG/counters replicate (GSPMD psums the grads);
        # the env-side fields replicate too — under --actors N this
        # process never collects, and under --actors 0 the in-graph
        # collect must stay the single logical stream the determinism
        # anchor pins (sharding it would change nothing numerically but
        # waste layout churn on a path the dp learner exists to starve).
        spec = TrainerState(
            env_state=P(),
            obs=P(),
            reset=P(),
            actor_carry=P(),
            critic_carry=P(),
            noise_state=P(),
            window=P(),
            arena=ArenaState(
                data=dp, priority=dp, cursor=P(), total_added=P(), meta=dp
            ),
            train=P(),
            behavior_params=P(),
            rng=P(),
            phase_idx=P(),
            env_steps=P(),
            episode_return=P(),
            completed_return_sum=P(),
            completed_count=P(),
        )
        self._shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            spec,
            is_leaf=lambda x: isinstance(x, P),
        )
        self._replicated = NamedSharding(mesh, P())
        self._dp_arena = NamedSharding(mesh, dp)
        super()._build_phases()

    def lstate_shardings(self):
        """The drain programs' output-sharding contract
        (``training/pipeline.py::LearnerState``): pinning the outputs to
        the init layout keeps the donated drain chain's avals STABLE, so
        the fleet learner's jit cache (and its AOT-precompiled coalesce
        widths) never re-keys mid-run on a GSPMD layout drift."""
        from r2d2dpg_tpu.training.pipeline import LearnerState

        return LearnerState(
            train=self._replicated,
            arena=ArenaState(
                data=self._dp_arena,
                priority=self._dp_arena,
                cursor=self._replicated,
                total_added=self._replicated,
                meta=self._dp_arena,
            ),
            rng=self._replicated,
        )

    # ----------------------------------------------------------------- init
    def init(self, key=None) -> TrainerState:
        state = super().init(key)
        return jax.device_put(state, self._shardings)

    # ------------------------------------------------------------- reshards
    def _reshard_add(self, seq, prios):
        """Replicate the B fresh rows for the capacity-sharded ring
        scatter — AFTER the initial-priority forward ran in the staged
        (dp-over-B) layout.  B (one emit / one staged drain) is small next
        to the arena, and a replicated operand keeps each capacity shard's
        ``.at[idx].set`` local instead of routing rows between shards.
        ``with_sharding_constraint`` (not device_put): these hooks run
        INSIDE the jitted phase/drain programs."""
        rep = lambda x: jax.lax.with_sharding_constraint(  # noqa: E731
            x, self._replicated
        )
        return jax.tree_util.tree_map(rep, seq), rep(prios)

    def _reshard_batch(self, batch):
        """Shard the sampled batch over dp so the learner step's compute
        splits and XLA psums the gradients (params replicated + batch
        sharded — the pjit/GSPMD recipe)."""
        return jax.tree_util.tree_map(
            lambda x: jax.lax.with_sharding_constraint(
                x,
                NamedSharding(self.mesh, P(*([DP_AXIS] + [None] * (x.ndim - 1)))),
            ),
            batch,
        )

    # ---------------------------------------------------------- fleet hooks
    def _put_staged(self, staged, axis: int = 0):
        """Lay a host batch tree over the dp mesh (the hybrid trainer's
        ``_put_fleet`` idiom): batch axis over dp, global assembly via
        ``jax.make_array_from_process_local_data`` when multi-process.  A
        width that does not divide the mesh (foreign actor shapes — a
        defensive case, ``structural_argv`` pins num_envs fleet-wide)
        replicates instead: correctness over bandwidth.

        ``axis=0`` is the staged fleet layout (leaves ``[B, ...]``);
        ``axis=1`` is the sampler learner's pulled layout (leaves
        ``[K, B, ...]``): each dp slice receives its ``B/D`` rows at
        placement time, so the composed sampler+dp run's learn program
        sees a batch already in the ``_reshard_batch`` layout — no
        central reshard hop (docs/TOPOLOGY.md)."""
        b = int(
            np.shape(jax.tree_util.tree_leaves(staged)[0])[axis]
        )
        # Divisibility is a GLOBAL property: each process contributes b
        # local rows, and the assembled array's batch dim is b * nproc.
        sharded = (b * self._nproc) % self.num_devices == 0
        if not sharded and self._nproc > 1:
            # The defensive replicate fallback is single-process-only:
            # device_put of process-LOCAL data against a replicated
            # global sharding would build per-process-inconsistent
            # arrays.  Multi-process widths must divide the mesh.
            raise ValueError(
                f"multi-process staged width {b} x {self._nproc} "
                f"processes does not divide the {self.num_devices}-device "
                f"mesh"
            )
        if axis != 0 and self._nproc > 1:
            # Only the staged axis-0 path is multi-process-shaped today
            # (the sampler learner is single-process; its multi-HOST pull
            # is a ROADMAP open item).
            raise ValueError(
                "batch-axis placement (axis != 0) is single-process only"
            )

        def put(x):
            x = np.asarray(x)
            if not sharded:
                return jax.device_put(x, self._replicated)
            spec = [None] * x.ndim
            spec[axis] = DP_AXIS
            sh = NamedSharding(self.mesh, P(*spec))
            if self._nproc == 1:
                return jax.device_put(x, sh)
            return jax.make_array_from_process_local_data(
                sh, x, (x.shape[0] * self._nproc,) + x.shape[1:]
            )

        return jax.tree_util.tree_map(put, staged)

    # ------------------------------------------------------------------ obs
    def dp_note_learn_width(self, b: int) -> None:
        """Record the per-shard rows of a REAL drain-learn dispatch
        (called by the fleet drain loop at the dispatch site — not from
        ``_put_staged``, which also places warm-precompile dummies and
        absorb batches that never learn)."""
        sharded = b % self.num_devices == 0
        self._obs_learn_width.set(float(b // self.num_devices if sharded else b))

    def _log_extra_refs(self, arena_state) -> list:
        return [self.arena.per_shard_occupancy(arena_state, self.num_devices)]

    def _log_extra_publish(self, fetched) -> None:
        for i, v in enumerate(np.asarray(fetched[0])):
            self._obs_shard_occ.labels(shard=str(i)).set(float(v))
