"""Agents (SURVEY.md §2.4): the R2D2-DPG learner as pure jittable functions."""

from r2d2dpg_tpu.agents.ddpg import AgentConfig, R2D2DPG, TrainState

__all__ = ["AgentConfig", "R2D2DPG", "TrainState"]
