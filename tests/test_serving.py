"""Serving subsystem tests (ISSUE 1): session isolation, micro-batching,
admission control, hot-reload validation, health.

Everything here is CPU-fast; the end-to-end acceptance flows (real
checkpoints, mid-stream reload, soak) live in test_serving_e2e.py.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2dpg_tpu.models import ActorNet, policy_step_fn
from r2d2dpg_tpu.serving import (
    BAD_REQUEST,
    MicroBatcher,
    PolicyService,
    Request,
    SessionStore,
    bucket_for,
)
from r2d2dpg_tpu.serving.batcher import OK, SHED_QUEUE, SHED_SESSIONS
from r2d2dpg_tpu.serving.service import compile_pinned
from r2d2dpg_tpu.utils.metrics import PercentileWindow

pytestmark = pytest.mark.serving

OBS = (5,)
ACT = 3


def make_actor(use_lstm=True, hidden=32):
    return ActorNet(action_dim=ACT, hidden=hidden, use_lstm=use_lstm)


def init_params(actor, seed=1):
    return actor.init(
        jax.random.PRNGKey(seed),
        jnp.zeros((1,) + OBS),
        actor.initial_carry(1),
        jnp.zeros((1,)),
    )


def make_service(actor=None, params=None, **kw):
    actor = actor or make_actor()
    params = params if params is not None else init_params(actor)
    kw.setdefault("obs_shape", OBS)
    kw.setdefault("max_sessions", 8)
    kw.setdefault("bucket_sizes", (1, 2, 4, 8))
    kw.setdefault("flush_ms", 1.0)
    return PolicyService(actor, params, **kw)


def reference_rollout(actor, params, obs_seq):
    """Sequential UNBATCHED rollout: the ground truth serving must match.

    Compiled through ``compile_pinned`` so the reference runs under the
    SAME compiler options the service pins — the conftest is free to dial
    XLA's backend level for suite speed without touching this contract."""
    carry = actor.initial_carry(1)
    step = jax.jit(policy_step_fn(actor))
    out = []
    exe = None
    for t in range(obs_seq.shape[0]):
        args = (
            params,
            obs_seq[t][None],
            carry,
            jnp.asarray([1.0 if t == 0 else 0.0]),
        )
        if exe is None:
            exe = compile_pinned(step, *args)
        a, carry = exe(*args)
        out.append(np.asarray(a[0]))
    return out


# --------------------------------------------------------------------- units
def test_bucket_for_picks_smallest_covering():
    sizes = (1, 2, 4, 8)
    assert bucket_for(1, sizes) == 1
    assert bucket_for(3, sizes) == 4
    assert bucket_for(8, sizes) == 8
    with pytest.raises(ValueError):
        bucket_for(9, sizes)


def test_percentile_window_nearest_rank():
    w = PercentileWindow(size=100)
    for v in range(1, 101):  # 1..100
        w.add(float(v))
    p50, p99 = w.percentiles((50.0, 99.0))
    assert p50 == 50.0 and p99 == 99.0
    assert PercentileWindow().percentiles((50.0,)) == (0.0,)
    # Window slides: old observations age out.
    w2 = PercentileWindow(size=4)
    for v in (1.0, 1.0, 1.0, 9.0, 9.0, 9.0, 9.0):
        w2.add(v)
    assert w2.percentiles((50.0,)) == (9.0,)
    assert w2.count == 7


def test_session_store_alloc_touch_ttl_evict():
    t = [0.0]
    store = SessionStore(
        2, make_actor().initial_carry, ttl_s=10.0, clock=lambda: t[0]
    )
    slot_a, new_a = store.acquire("a")
    assert new_a and store.active == 1
    assert store.acquire("a") == (slot_a, False)  # sticky + touch
    store.acquire("b")
    assert store.acquire("c") is None  # full, nothing expired
    t[0] = 11.0  # both now idle > ttl ... but "a" was touched at t=0
    slot_c, new_c = store.acquire("c")  # evicts expired, reuses a slot
    assert new_c and store.active == 1 and store.evictions == 2
    assert store.release("c") and not store.release("zzz")
    assert store.active == 0


def test_session_slabs_shapes_and_scratch_row():
    actor = make_actor(hidden=16)
    store = SessionStore(4, actor.initial_carry)
    slabs = store.init_slabs()
    for leaf in jax.tree_util.tree_leaves(slabs):
        assert leaf.shape[0] == 5  # max_sessions + scratch row
    assert store.scratch_slot == 4
    # Feedforward actor: empty carry pytree, no slab leaves.
    ff_store = SessionStore(4, make_actor(use_lstm=False).initial_carry)
    assert jax.tree_util.tree_leaves(ff_store.init_slabs()) == []


def test_batcher_bounded_queue_sheds():
    b = MicroBatcher((1, 2), max_queue=2, flush_ms=0.0)
    mk = lambda s: Request(s, np.zeros(OBS), False, time.monotonic())  # noqa: E731
    assert b.submit(mk("a")) and b.submit(mk("b"))
    assert not b.submit(mk("c"))  # full -> immediate refusal, no block
    assert b.shed_queue_full == 1 and b.depth == 2


def test_batcher_one_request_per_session_per_batch():
    b = MicroBatcher((4,), max_queue=16, flush_ms=0.0)
    r1 = Request("s", np.zeros(OBS), False, time.monotonic())
    r2 = Request("s", np.zeros(OBS), False, time.monotonic())
    r3 = Request("t", np.zeros(OBS), False, time.monotonic())
    for r in (r1, r2, r3):
        assert b.submit(r)
    first = b.next_batch(poll_s=0.0)
    assert [r.session_id for r in first] == ["s", "t"]
    assert first[0] is r1  # FIFO within the session
    second = b.next_batch(poll_s=0.0)
    assert second == [r2]  # holdover rides the next batch


# ------------------------------------------------------------------- service
def test_interleaved_sessions_match_sequential_rollouts():
    """Two sessions interleaved through the micro-batcher must reproduce the
    same action sequences as two sequential single-session rollouts."""
    actor = make_actor()
    params = init_params(actor)
    rng = np.random.default_rng(0)
    obs = {
        s: rng.standard_normal((5,) + OBS).astype(np.float32) for s in "ab"
    }
    got = {s: [] for s in "ab"}
    with make_service(actor, params) as svc:
        for t in range(5):
            pending = [
                (s, svc.act_async(s, obs[s][t], reset=(t == 0))) for s in "ab"
            ]
            for s, req in pending:
                assert req.wait(30.0)
                assert req.code == OK
                got[s].append(req.action)
    for s in "ab":
        want = reference_rollout(actor, params, obs[s])
        for t in range(5):
            np.testing.assert_array_equal(got[s][t], want[t])


def test_feedforward_actor_serves_too():
    actor = make_actor(use_lstm=False)
    params = init_params(actor)
    obs = np.ones(OBS, np.float32)
    with make_service(actor, params) as svc:
        res = svc.act("x", obs)
    assert res.code == OK
    # Pinned like every serving reference: an eager apply would dispatch
    # op-by-op under the suite's XLA_FLAGS instead.
    args = (params, obs[None], (), jnp.zeros((1,)))
    direct, _ = compile_pinned(jax.jit(actor.apply), *args)(*args)
    np.testing.assert_array_equal(res.action, np.asarray(direct[0]))


def test_queue_full_returns_shed_code_not_exception():
    # max_queue=0: every request sheds immediately — the admission-control
    # contract is a CODE on the result, never a raise.
    with make_service(max_queue=0) as svc:
        res = svc.act("a", np.zeros(OBS, np.float32))
    assert res.code == SHED_QUEUE
    assert res.action is None
    assert svc.health().requests_shed == 1


def test_session_capacity_sheds_with_session_code():
    with make_service(max_sessions=1, session_ttl_s=1e9) as svc:
        r1 = svc.act("a", np.zeros(OBS, np.float32))
        r2 = svc.act("b", np.zeros(OBS, np.float32))
        h = svc.health()
    assert r1.code == OK
    assert r2.code == SHED_SESSIONS and r2.action is None
    assert h.requests_shed == 1  # session-capacity sheds count as sheds too


def test_bad_obs_shape_is_rejected_before_queueing():
    with make_service() as svc:
        res = svc.act("a", np.zeros((7,), np.float32))
    assert res.code == BAD_REQUEST


def test_act_after_stop_returns_shutdown():
    svc = make_service()
    svc.start(warmup=False)
    svc.stop()
    assert svc.act("a", np.zeros(OBS, np.float32)).code == "shutdown"


def test_same_session_concurrent_requests_stay_ordered():
    """A client pipelining 2 steps of one session must see them applied in
    order (the batcher serializes same-session requests across batches)."""
    actor = make_actor()
    params = init_params(actor)
    rng = np.random.default_rng(1)
    obs = rng.standard_normal((4,) + OBS).astype(np.float32)
    with make_service(actor, params, flush_ms=5.0) as svc:
        reqs = [svc.act_async("s", obs[t], reset=(t == 0)) for t in range(4)]
        for r in reqs:
            assert r.wait(30.0) and r.code == OK
    want = reference_rollout(actor, params, obs)
    for t in range(4):
        np.testing.assert_array_equal(reqs[t].action, want[t])


def test_health_snapshot_counts_and_occupancy():
    actor = make_actor()
    with make_service(actor, params_step=42) as svc:
        n = 6
        pending = [
            svc.act_async(f"s{i}", np.zeros(OBS, np.float32), reset=True)
            for i in range(n)
        ]
        for r in pending:
            assert r.wait(30.0) and r.code == OK
        h = svc.health()
    assert h.requests_ok == n
    assert h.params_step == 42
    assert h.sessions_active == n
    assert 0.0 < h.batch_occupancy <= 1.0
    assert h.latency_p99_ms >= h.latency_p50_ms >= 0.0
    scalars = h.as_scalars()
    assert "last_reload_error" not in scalars
    assert all(isinstance(v, float) for v in scalars.values())


def test_worker_survives_a_poison_batch():
    """A batch that blows up inside the worker (injected device-step
    failure — the stand-in for a transient XLA error) must fail THOSE
    requests with internal_error and keep the service alive — a dead
    worker would turn every later act() into a silent hang."""
    from r2d2dpg_tpu.serving import INTERNAL_ERROR

    actor = make_actor()
    params = init_params(actor)
    svc = make_service(actor, params, bucket_sizes=(2,), flush_ms=50.0)
    real_step = svc._step

    def boom(*a, **k):
        raise RuntimeError("injected device failure")

    with svc:
        svc._step = boom
        poisoned = [
            svc.act_async("a", np.zeros(OBS, np.float32), reset=True),
            svc.act_async("b", np.zeros(OBS, np.float32), reset=True),
        ]
        for r in poisoned:
            assert r.wait(30.0)
            assert r.code == INTERNAL_ERROR and r.action is None
        # Service still serves once the fault clears; carries were rebuilt.
        svc._step = real_step
        ok = svc.act("a", np.zeros(OBS, np.float32), reset=True)
        assert ok.code == OK
        h = svc.health()
    assert h.worker_errors == 1
    assert "RuntimeError" in (h.last_worker_error or "")
    assert h.requests_ok == 1
    assert "worker_errors" in h.as_scalars()
    assert "last_worker_error" not in h.as_scalars()


def test_housekeeping_failure_is_contained_without_dropping_sessions():
    """A failing health logger (e.g. full disk) must be noted in health and
    NOT trigger the slab-rebuild recovery — session carries survive."""

    class BoomLogger:
        def log(self, step, scalars):
            raise OSError("disk full")

    actor = make_actor()
    params = init_params(actor)
    rng = np.random.default_rng(3)
    obs = rng.standard_normal((3,) + OBS).astype(np.float32)
    svc = make_service(actor, params, logger=BoomLogger(), log_every_s=0.0)
    got = []
    with svc:
        for t in range(3):
            res = svc.act("a", obs[t], reset=(t == 0))
            assert res.code == OK
            got.append(res.action)
        h = svc.health()
    assert h.worker_errors > 0 and "OSError" in h.last_worker_error
    assert h.sessions_active == 1  # never cleared by the logger failures
    want = reference_rollout(actor, params, obs)
    for t in range(3):  # carry continuity across the failing housekeeping
        np.testing.assert_array_equal(got[t], want[t])


def test_ragged_obs_without_configured_shape_fails_only_that_request():
    """obs_shape=None skips enqueue-time validation, so the worker screens
    shapes per batch: the odd one out gets bad_request; everyone else's
    carries and requests survive untouched."""
    actor = make_actor()
    params = init_params(actor)
    svc = PolicyService(
        actor, params, obs_shape=None, max_sessions=8,
        bucket_sizes=(2,), flush_ms=50.0,
    )
    with svc:
        good = svc.act_async("a", np.zeros(OBS, np.float32), reset=True)
        bad = svc.act_async("b", np.zeros((7,), np.float32), reset=True)
        assert good.wait(30.0) and bad.wait(30.0)
        assert good.code == OK
        assert bad.code == BAD_REQUEST
        h = svc.health()
    assert h.worker_errors == 0 and h.requests_ok == 1


def test_many_threads_hammering_is_safe_and_accounted():
    """Concurrency smoke: producers from many threads, bounded queue, every
    request gets exactly one terminal code."""
    actor = make_actor()
    params = init_params(actor)
    results = []
    lock = threading.Lock()

    with make_service(
        actor, params, max_queue=8, max_sessions=8, bucket_sizes=(1, 2, 4)
    ) as svc:

        def client(i):
            res = svc.act(f"s{i % 8}", np.zeros(OBS, np.float32), timeout=30.0)
            with lock:
                results.append(res.code)

        threads = [threading.Thread(target=client, args=(i,)) for i in range(32)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        h = svc.health()
    assert len(results) == 32
    assert set(results) <= {OK, SHED_QUEUE}
    assert h.requests_ok == results.count(OK)
    assert h.requests_shed == results.count(SHED_QUEUE)
