"""Chaos-injection drills: seeded faults at the fleet's real boundaries.

A fault-tolerance layer is only real if its recovery paths are exercised —
Ape-X-scale runs (PAPERS.md 1803.00933) and Podracer-style long-lived TPU
jobs (2104.06272) treat peer death, wedged processes and wire corruption
as table stakes, not exceptions.  This module injects exactly those
faults, on a deterministic seeded schedule, at the boundaries where they
happen for real:

===================  =============================  ========================
fault                injection boundary             documented recovery
===================  =============================  ========================
``kill_actor``       supervisor SIGKILL             backoff restart
                     (``ActorSupervisor.            (``actor_crash`` ->
                     kill_actor``)                  ``actor_restart``)
``stall_actor``      actor-side ``time.sleep``      heartbeat reap
                     mid-collect                    (``peer_dead``) + actor
                                                    reconnect
``corrupt_frame``    wire-level byte flip with the  CRC reject kills the
                     pristine CRC kept in the       connection
                     header (``send_corrupt_        (``ingest_conn_error``)
                     frame``)                       + actor reconnect
``kill_ingest_conn`` learner-side socket close      actor reconnect-with-
                     (``IngestServer.               backoff + fresh HELLO/
                     drop_connection``)             param snapshot
``kill_shard``       supervisor SIGKILL of a        quota renormalization
                     standalone shard process       over survivors + handler
                     (``ShardProcTier.kill_proc``)  re-route, then backoff
                                                    restart + epoch-fenced
                                                    rejoin (``shard_dead`` ->
                                                    ``shard_rejoin``)
``stall_shard``      in-shard-process response      both legs wait it out:
                     gate (``ShardChaos.gate``,     zero sheds, zero false
                     fleet/shard.py)                reaps (the pinned
                                                    property)
``partition_shard``  learner-side drop of BOTH      reconnect both legs;
                     legs' connections to one       shard data survives
                     shard (``RemoteShardSet.       under the SAME epoch (a
                     partition``)                   partition ≠ a restart)
``partition_data_    actor-side severing of its     loud fallback to the
plane``              direct actor->shard data leg   learner-forwarded SEQS
                     (``FleetActor._partition_      path (``data_plane_
                     data_plane``: shutdown, ref    fallback``), accounting
                     kept — the next send fails     intact, re-dial on the
                     mid-push like a real           next assignment advert
                     partition)
===================  =============================  ========================

**Spec grammar** (``--chaos-spec``)::

    spec  := fault ("," fault)*
    fault := kind "@p" phase [":" seconds "s"]
    e.g.    kill_actor@p3,stall_actor@p5:4s,corrupt_frame@p7,kill_ingest_conn@p9

``phase`` is 1-based on the *injecting* side: learner-side faults count
drain-learn phases, actor-side faults count the target actor's emitted
batches.  The duration suffix is only meaningful for ``stall_actor``.

**Determinism**: which actor a fault targets is derived from
``(seed, fault index, fault kind)`` by ``fault_target`` — a pure hash both
sides compute identically, so the learner-side engine and every actor
subprocess (the spawner forwards ``--chaos-spec`` verbatim) agree on the
schedule without coordination.  Same seed, same spec, same drill.

Every injection lands in the flight recorder (``chaos_inject`` with
``fault=``/``phase=``/``actor=``) and bumps
``r2d2dpg_fleet_chaos_drills_total{fault=...}``; the recovery events are
the subsystems' existing ones, so ``flight.jsonl`` (or a fleet-wide
``obs.flight merge``) pairs every injected fault with its recovery
(docs/FLEET.md "Failure modes & recovery").
"""

from __future__ import annotations

import dataclasses
import glob
import hashlib
import json
import os
import re
import socket
import threading
import time
import zlib
from typing import Optional, Sequence, Tuple

from r2d2dpg_tpu.fleet import transport
from r2d2dpg_tpu.obs import flight_event, get_flight_recorder, get_registry

# Faults injected from the learner process (its drain-phase clock) vs from
# inside the target actor process (its emitted-batch clock).
# ``kill_sampler_conn``/``stall_sampler`` drill the in-network-sampling
# peer class (fleet/sampler.py, ISSUE 10): the first drops the connection
# FEEDING the target actor's replay shard (recovery: actor reconnect with
# at-least-once accounting — a dead shard feed loses only re-collectable
# experience, never step/episode sums); the second stalls the sampler
# learner's own pull loop for its duration (recovery: nothing to recover —
# shards keep absorbing under their own locks and ring-evict instead of
# shedding, which is exactly the property the drill pins).
# ``kill_shard``/``stall_shard``/``partition_shard`` drill the standalone
# shard tier (fleet/shard.py, ISSUE 12): SIGKILL of a shard process
# (recovery: quota renormalization over survivors + handler re-route,
# then epoch-fenced rejoin after the supervisor's backoff restart), an
# in-shard-process response stall (recovery: nothing — both legs wait it
# out, zero sheds and zero false reaps), and a learner-side drop of BOTH
# legs' connections to one shard (recovery: reconnect; the shard's data
# survives under the SAME epoch — a partition is not a restart).
LEARNER_FAULTS = frozenset(
    {
        "kill_actor",
        "kill_ingest_conn",
        "kill_sampler_conn",
        "stall_sampler",
        "kill_shard",
        "partition_shard",
    }
)
# ``partition_data_plane`` drills the direct actor->shard data leg
# (ISSUE 17): the actor severs its own data socket at the transport and
# the next direct push fails mid-send — recovery is the LOUD fallback to
# the learner-forwarded path with accounting intact, then a re-dial off
# the next assignment advert.  train.py refuses it without --shard-direct
# (no data plane to partition).
ACTOR_FAULTS = frozenset(
    {"stall_actor", "corrupt_frame", "partition_data_plane"}
)
# Faults fired INSIDE a standalone shard process (fleet/shard.py parses
# the forwarded --chaos-spec; the clock is SEQS frames that process has
# absorbed).  ``kill_shard`` targets a shard PROCESS index (the
# supervisor's SIGKILL unit); ``partition_shard`` targets a SHARD index
# (the connection unit); ``stall_shard`` targets a process index.
SHARD_PROC_FAULTS = frozenset({"stall_shard"})
# The sampler peer class: train.py refuses these without --replay-shards
# (on the central drain a "sampler stall" would stall the DRAIN thread
# and shed — evidence for an invariant that path cannot exhibit).
SAMPLER_FAULTS = frozenset({"kill_sampler_conn", "stall_sampler"})
# The shard-tier class: refused without --shard-procs (the loopback
# shards share the learner's process — there is no shard to kill,
# partition, or stall independently of the learner itself).
SHARD_FAULTS = frozenset({"kill_shard", "stall_shard", "partition_shard"})
# The direct-data-plane class: refused without --shard-direct (with the
# experience riding the learner-forwarded path there is no data leg to
# partition — the drill would silently no-op).
DIRECT_FAULTS = frozenset({"partition_data_plane"})
FAULT_KINDS = tuple(sorted(LEARNER_FAULTS | ACTOR_FAULTS | SHARD_PROC_FAULTS))
# Faults that carry (and require) a :Ds duration suffix.
STALL_FAULTS = frozenset({"stall_actor", "stall_sampler", "stall_shard"})

_FAULT_RE = re.compile(
    r"^(?P<kind>[a-z_]+)@p(?P<phase>\d+)(?::(?P<dur>\d+(?:\.\d+)?)s)?$"
)


@dataclasses.dataclass(frozen=True)
class Fault:
    """One scheduled injection (parsed from the ``--chaos-spec`` grammar)."""

    kind: str
    phase: int  # 1-based, on the injecting side's phase clock
    duration_s: float = 0.0  # stall_actor only
    index: int = 0  # position in the spec: part of the target derivation


def parse_chaos_spec(spec: str) -> Tuple[Fault, ...]:
    """``"kill_actor@p3,stall_actor@p5:4s"`` -> ``(Fault, ...)``.

    Raises ``ValueError`` with the offending token on any malformed entry
    — a chaos schedule that silently dropped a fault would let a broken
    recovery path pass its drill."""
    faults = []
    for i, token in enumerate(t.strip() for t in spec.split(",")):
        if not token:
            raise ValueError(f"empty fault token in chaos spec {spec!r}")
        m = _FAULT_RE.match(token)
        if m is None:
            raise ValueError(
                f"malformed chaos fault {token!r} (grammar: "
                f"kind@pN[:Ds], e.g. stall_actor@p5:4s)"
            )
        kind = m.group("kind")
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown chaos fault {kind!r}; have {sorted(FAULT_KINDS)}"
            )
        phase = int(m.group("phase"))
        if phase < 1:
            raise ValueError(f"chaos fault {token!r}: phase must be >= 1")
        dur = float(m.group("dur") or 0.0)
        if dur and kind not in STALL_FAULTS:
            raise ValueError(
                f"chaos fault {token!r}: only {sorted(STALL_FAULTS)} take "
                f"a duration"
            )
        if kind in STALL_FAULTS and dur <= 0.0:
            raise ValueError(
                f"chaos fault {token!r}: {kind} needs a duration "
                f"(e.g. {kind}@p5:4s)"
            )
        faults.append(Fault(kind=kind, phase=phase, duration_s=dur, index=i))
    return tuple(faults)


def fault_target(fault: Fault, seed: int, num_actors: int) -> int:
    """Which actor id a fault hits: a pure seeded hash every process
    computes identically (no RNG state, no coordination — the learner
    engine and each forwarded-spec actor agree by construction)."""
    digest = hashlib.sha256(
        f"{seed}:{fault.index}:{fault.kind}".encode()
    ).digest()
    return int.from_bytes(digest[:8], "big") % max(num_actors, 1)


def _drill_counter():
    return get_registry().counter(
        "r2d2dpg_fleet_chaos_drills_total",
        "chaos faults injected (one per scheduled drill that fired)",
        labelnames=("fault",),
    )


def record_injection(fault: Fault, actor: int, **extra) -> None:
    """The one way an injection becomes visible: ``chaos_inject`` flight
    event + the per-fault drill counter — shared by both sides so every
    fault is attributable in ``flight.jsonl``/``obs.flight merge``."""
    flight_event(
        "chaos_inject",
        fault=fault.kind,
        phase=fault.phase,
        actor=actor,
        **extra,
    )
    _drill_counter().labels(fault=fault.kind).inc()
    # Flush the ring NOW (atomic; no-op when no dump path is installed):
    # several drills end in a SIGKILL — the injected fault's own, or a
    # teardown kill of a process whose SIGTERM is deferred behind a
    # compile — and a record that only lives in the in-memory ring dies
    # with it.  Durable-at-injection is what makes every fault
    # attributable in flight.jsonl no matter how the drill ends.
    get_flight_recorder().dump()


# ---------------------------------------------------------------- injectors
def send_corrupt_frame(
    sock: socket.socket, kind: int, parts: Sequence, *, flip_at: Optional[int] = None
) -> int:
    """The ``corrupt_frame`` boundary: one payload byte is flipped AFTER
    the header CRC is computed over the pristine bytes — exactly what
    bit-rot or a torn write produces on a real wire — so the receiver's
    CRC check must reject the frame (``FrameCRCError`` kills the
    connection; transport.py's rule).  Returns bytes sent."""
    payload = b"".join(bytes(p) for p in parts)
    if not payload:
        raise ValueError("cannot corrupt an empty payload")
    crc = zlib.crc32(payload)  # pristine: the header promises these bytes
    i = len(payload) // 2 if flip_at is None else flip_at % len(payload)
    corrupted = payload[:i] + bytes([payload[i] ^ 0xFF]) + payload[i + 1:]
    header = transport._HEADER.pack(
        transport.MAGIC, kind, len(payload), crc
    )
    sock.sendall(header)
    sock.sendall(corrupted)
    return transport.HEADER_BYTES + len(payload)


class ChaosEngine:
    """Learner-side scheduler: fires learner-boundary faults on the drain
    clock (``FleetLearner.run``'s ``phase_fn`` hook).

    Actor-boundary faults in the spec are NOT fired here — each actor
    fires its own from the forwarded spec (``ActorChaos``) — but the
    engine knows the whole schedule, so ``unfired()`` at end of run names
    any learner-side drill that never got its phase."""

    def __init__(
        self,
        faults: Sequence[Fault],
        *,
        seed: int,
        num_actors: int,
        supervisor=None,
        server=None,
        shard_tier=None,
    ):
        self.faults = tuple(faults)
        self.seed = seed
        self.num_actors = num_actors
        self.supervisor = supervisor
        self.server = server
        # The standalone shard tier (fleet/shard.py ShardProcTier, ISSUE
        # 12): the kill_shard boundary is its supervisor's SIGKILL
        # (``kill_proc``), the partition_shard boundary its shard map's
        # both-legs connection drop (``partition``).
        self.shard_tier = shard_tier
        self._fired = set()
        _drill_counter()  # register the family before any drill fires

    def on_phase(self, phase: int) -> None:
        """Fire every due learner-side fault (``phase`` is the drain-learn
        count, 1-based).  ``>=`` rather than ``==``: a resumed run whose
        checkpoint already passed a fault's phase fires it immediately
        rather than silently never.

        A fault is marked fired — and recorded — only when its injection
        actually LANDED (a kill delivered, a live connection dropped).  A
        no-op attempt (target already a corpse mid-backoff, no live
        connection) stays pending: it retries next phase, and if it never
        lands, ``unfired()`` reports it — recording a no-op would read as
        a drill that passed without its fault ever being injected."""
        for fault in self.faults:
            if (
                fault.kind not in LEARNER_FAULTS
                or fault.index in self._fired
                or phase < fault.phase
            ):
                continue
            target = fault_target(fault, self.seed, self.num_actors)
            if fault.kind == "kill_actor":
                killed = (
                    self.supervisor is not None
                    and self.supervisor.kill_actor(target)
                )
                if not killed:
                    continue
                self._fired.add(fault.index)
                record_injection(fault, target, at_phase=phase)
            elif fault.kind in ("kill_ingest_conn", "kill_sampler_conn"):
                # kill_sampler_conn shares the boundary (a learner-side
                # socket close) but names the SAMPLER peer class: the
                # dropped connection is the one feeding the target
                # actor's replay shard — the drill asserts the shard's
                # DATA survives and only the in-flight batch (plus its
                # re-banked accounting) is lost (tests/test_chaos.py).
                dropped = (
                    self.server.drop_connection(actor=str(target))
                    if self.server is not None
                    else None
                )
                if dropped is None:
                    continue
                self._fired.add(fault.index)
                record_injection(
                    fault, target, at_phase=phase, dropped=dropped
                )
            elif fault.kind == "stall_sampler":
                # The stall IS the fault: the pull loop (this thread)
                # stops sampling for the duration.  Recorded BEFORE the
                # sleep so evidence survives however the drill ends.
                self._fired.add(fault.index)
                record_injection(
                    fault, target, at_phase=phase,
                    duration_s=fault.duration_s,
                )
                time.sleep(fault.duration_s)
            elif fault.kind == "kill_shard":
                # SIGKILL one standalone shard PROCESS (target re-derived
                # modulo the tier's proc count): the drill the whole tier
                # exists to survive — quotas renormalize to the survivors
                # within a phase, handlers re-route, and the supervisor's
                # backoff restart rejoins the shard under a bumped epoch.
                tier = self.shard_tier
                if tier is None:
                    continue
                target = fault_target(fault, self.seed, tier.num_procs)
                if not tier.kill_proc(target):
                    continue
                self._fired.add(fault.index)
                record_injection(fault, target, at_phase=phase)
            elif fault.kind == "partition_shard":
                # Drop BOTH legs' connections to one shard (target modulo
                # the SHARD count — the connection unit): a network
                # partition, not a restart.  Recovery is reconnection on
                # both legs with the shard's data intact under the SAME
                # epoch (tests/test_shard.py pins that distinction).
                tier = self.shard_tier
                if tier is None:
                    continue
                target = fault_target(fault, self.seed, tier.num_shards)
                if not tier.shard_set.partition(target):
                    continue  # no live connection yet: stays pending
                self._fired.add(fault.index)
                record_injection(fault, target, at_phase=phase)

    def unfired(self) -> Tuple[Fault, ...]:
        """Learner-side faults whose phase never arrived (run too short):
        callers log these so a drill that never ran cannot read as one
        that passed."""
        return tuple(
            f
            for f in self.faults
            if f.kind in LEARNER_FAULTS and f.index not in self._fired
        )


def _faults_unfired_in_dumps(
    faults: Sequence[Fault],
    logdir: str,
    *,
    pattern: str,
    kinds: frozenset,
    seed: int,
    n: int,
) -> Tuple[Fault, ...]:
    """The shared no-evidence-means-unfired scan: faults of ``kinds``
    with no ``chaos_inject`` line in the ``pattern`` flight dumps under
    ``logdir``.  Evidence is matched on (kind, phase, target) — ``seed``
    and ``n`` recompute each fault's target — so duplicate spec entries
    hashing to different targets each need their own line."""
    expected = [f for f in faults if f.kind in kinds]
    if not expected:
        return ()
    seen = set()
    for path in glob.glob(os.path.join(logdir, pattern)):
        try:
            with open(path) as fh:
                for line in fh:
                    try:
                        e = json.loads(line)
                    except ValueError:
                        continue
                    if e.get("kind") == "chaos_inject":
                        seen.add(
                            (e.get("fault"), e.get("phase"), e.get("actor"))
                        )
        except OSError:
            continue
    return tuple(
        f
        for f in expected
        if (f.kind, f.phase, fault_target(f, seed, n)) not in seen
    )


def actor_faults_unfired(
    faults: Sequence[Fault], logdir: str, *, seed: int, num_actors: int
) -> Tuple[Fault, ...]:
    """Actor-boundary faults of a spec with NO injection evidence in the
    ``flight_actor*.jsonl`` dumps under ``logdir``.

    The learner-side engine cannot see an actor process fire (or fail to
    fire) its drills; what it CAN see, after teardown has flushed every
    incarnation's dump, is whether a ``chaos_inject`` line exists for each
    scheduled actor-side fault — ``record_injection`` flushes at injection
    time precisely so this evidence survives any way the drill ends.
    Callers warn on the returned faults: a drill that left no evidence
    must not read as one that passed (the ``unfired()`` contract)."""
    return _faults_unfired_in_dumps(
        faults,
        logdir,
        pattern="flight_actor*.jsonl",
        kinds=ACTOR_FAULTS,
        seed=seed,
        n=num_actors,
    )


class ActorChaos:
    """Actor-side scheduler: the faults of a forwarded spec that target
    THIS actor, fired on its emitted-batch clock (``FleetActor.run``).

    A supervised restart re-parses the same argv, so a restarted
    incarnation re-arms its schedule — harmless for the drill semantics
    (a stall is just slow; a corrupt frame re-drills the same recovery)
    and exactly what a deterministic schedule means."""

    def __init__(
        self, faults: Sequence[Fault], *, seed: int, num_actors: int, actor_id: int
    ):
        self.actor_id = actor_id
        self._mine = tuple(
            f
            for f in faults
            if f.kind in ACTOR_FAULTS
            and fault_target(f, seed, num_actors) == actor_id
        )
        self._fired = set()

    def maybe_stall(self, batch_idx: int) -> float:
        """Sleep out any due ``stall_actor`` fault (before collecting batch
        ``batch_idx``); returns seconds slept.  The sleep IS the fault: the
        actor stops reading and sending, so the ingest handler's heartbeat
        deadline reaps it as ``peer_dead``."""
        slept = 0.0
        for f in self._due("stall_actor", batch_idx):
            self._fired.add(f.index)
            record_injection(f, self.actor_id, at_phase=batch_idx)
            time.sleep(f.duration_s)
            slept += f.duration_s
        return slept

    def corrupt_next_frame(self, batch_idx: int) -> bool:
        """True when batch ``batch_idx``'s SEQS frame should go out through
        ``send_corrupt_frame`` (fires each due corrupt fault once)."""
        due = self._due("corrupt_frame", batch_idx)
        for f in due:
            self._fired.add(f.index)
            record_injection(f, self.actor_id, at_phase=batch_idx)
        return bool(due)

    def partition_data_plane(self, batch_idx: int) -> bool:
        """True when the direct data leg should be severed before batch
        ``batch_idx`` (fires each due partition fault once) — the actor
        shuts the socket down but keeps the reference, so the coming
        direct push fails mid-send like a real network partition and the
        loud-fallback recovery path runs."""
        due = self._due("partition_data_plane", batch_idx)
        for f in due:
            self._fired.add(f.index)
            record_injection(f, self.actor_id, at_phase=batch_idx)
        return bool(due)

    def _due(self, kind: str, batch_idx: int):
        return [
            f
            for f in self._mine
            if f.kind == kind
            and f.index not in self._fired
            and batch_idx >= f.phase
        ]


class ShardChaos:
    """Shard-process-side scheduler (fleet/shard.py, ISSUE 12): the
    ``stall_shard`` faults of a forwarded spec that target THIS shard
    process, fired on its absorbed-SEQS-frame clock.

    The stall is a RESPONSE gate, not a sleep in one handler: every leg's
    handler waits out the gate before replying (acks, BATCH responses),
    so for the duration the whole shard is unresponsive on every
    connection — exactly what a GC pause or an I/O wedge looks like from
    the learner side.  The drill's pinned property is that NOTHING breaks:
    actors keep streaming into the (eventually-answered) ack wait, the
    sampler waits out its exchange, zero sheds, zero false reaps."""

    def __init__(
        self,
        faults: Sequence[Fault],
        *,
        seed: int,
        num_shard_procs: int,
        proc_index: int,
    ):
        self.proc_index = proc_index
        self._mine = tuple(
            f
            for f in faults
            if f.kind in SHARD_PROC_FAULTS
            and fault_target(f, seed, num_shard_procs) == proc_index
        )
        self._fired = set()
        self._frames = 0
        self._stall_until = 0.0
        self._lock = threading.Lock()

    def on_seqs_frame(self) -> None:
        """One absorbed SEQS frame (any connection): advance the clock and
        arm any due stall (recorded at arm time — evidence survives
        however the drill ends)."""
        with self._lock:
            self._frames += 1
            for f in self._mine:
                if f.index in self._fired or self._frames < f.phase:
                    continue
                self._fired.add(f.index)
                record_injection(
                    f, self.proc_index, at_phase=self._frames,
                    duration_s=f.duration_s,
                )
                self._stall_until = max(
                    self._stall_until, time.monotonic() + f.duration_s
                )

    def gate(self) -> None:
        """Wait out any armed stall before replying (every handler calls
        this ahead of each ACK/BATCH send)."""
        delay = self._stall_until - time.monotonic()
        if delay > 0:
            time.sleep(delay)


def shard_faults_unfired(
    faults: Sequence[Fault], logdir: str, *, seed: int, num_shard_procs: int
) -> Tuple[Fault, ...]:
    """Shard-process-boundary faults of a spec with NO injection evidence
    in the ``flight_shard*.jsonl`` dumps under ``logdir`` — the
    ``actor_faults_unfired`` contract extended to the shard tier (a
    stall drill that never got its frame count must not read as one that
    passed)."""
    return _faults_unfired_in_dumps(
        faults,
        logdir,
        pattern="flight_shard*.jsonl",
        kinds=SHARD_PROC_FAULTS,
        seed=seed,
        n=num_shard_procs,
    )
