#!/usr/bin/env bash
# lint_obs.sh — operator output must flow through the telemetry layer.
#
# Fails on bare `print(` in r2d2dpg_tpu/ library code.  Library modules
# report through the obs registry / flight recorder / MetricLogger so that
# every operator-visible signal is scrapeable and post-mortem-able; a bare
# print is invisible to both.
#
# Exceptions:
#   - CLI entrypoints (train.py, serve.py, eval.py, __main__.py): their
#     job is stdout/stderr.
#   - Lines annotated `# obs-lint: allow` (e.g. MetricLogger's own stdout
#     sink, which IS the telemetry layer's print).
#
# Wired into the test run via tests/test_obs.py::test_lint_obs_clean.
set -euo pipefail
cd "$(dirname "$0")/.."

offenders=$(grep -rn 'print(' r2d2dpg_tpu \
    --include='*.py' \
    --exclude='train.py' \
    --exclude='serve.py' \
    --exclude='eval.py' \
    --exclude='__main__.py' \
    | grep -v '# obs-lint: allow' || true)

if [ -n "$offenders" ]; then
    echo "$offenders"
    echo "lint_obs: FAIL — bare print( in library code; route operator" \
         "output through the obs registry / flight recorder / MetricLogger" \
         "(or annotate deliberate sinks with '# obs-lint: allow')"
    exit 1
fi

# ---- metric naming scheme -------------------------------------------------
# Every metric name registered in library code must follow the documented
# r2d2dpg_<subsystem>_<metric> scheme (docs/OBSERVABILITY.md) or appear in
# scripts/obs_metric_allowlist.txt.  A scan of literal first arguments to
# .counter(/.gauge(/.histogram( — registrations span lines, so the scan is
# a small python (re over whole files), not a line grep; the rglob covers
# every library module incl. the shard-proc side (fleet/shard.py, whose
# registrations feed the TELEM plane — ISSUE 13).  The one f-string
# family (the per-hop trace histograms) is expanded EXPLICITLY from the
# hop namespace below, so a new hop (e.g. the shard-tier req_receive/
# shard_draw/batch_encode) cannot mint a non-conforming name unseen.
python - <<'EOF'
import re
import sys
from pathlib import Path

allow = set()
allow_path = Path("scripts/obs_metric_allowlist.txt")
if allow_path.exists():
    for line in allow_path.read_text().splitlines():
        line = line.strip()
        if line and not line.startswith("#"):
            allow.add(line)

pat = re.compile(r'\.(?:counter|gauge|histogram)\(\s*"([^"]+)"')
scheme = re.compile(r"^r2d2dpg_[a-z0-9]+_[a-z0-9_]*[a-z0-9]$")
bad = []
for path in sorted(Path("r2d2dpg_tpu").rglob("*.py")):
    for name in pat.findall(path.read_text()):
        if not scheme.match(name) and name not in allow:
            bad.append(f"{path}: {name}")
# The parameterized trace-hop histograms (obs/trace.py hop_histogram):
# expand the hop namespace and hold each concrete name to the scheme.
# (Guarded on the module existing so partial checkouts — the lint's own
# offender-fixture tree — still scan their literals.)
if Path("r2d2dpg_tpu/obs/trace.py").exists():
    from r2d2dpg_tpu.obs.trace import HOPS  # noqa: E402 (after the scan)

    for hop in HOPS:
        name = f"r2d2dpg_trace_{hop}_seconds"
        if not scheme.match(name) and name not in allow:
            bad.append(f"r2d2dpg_tpu/obs/trace.py (hop {hop!r}): {name}")
# The device-plane family (obs/device.py METRIC_NAMES, ISSUE 14): the
# module enumerates its namespace, so the scheme check covers every
# r2d2dpg_device_* name even if a registration ever goes non-literal —
# and a name added to the module without joining METRIC_NAMES is itself
# an offence (the enumeration IS the documented contract).
if Path("r2d2dpg_tpu/obs/device.py").exists():
    from r2d2dpg_tpu.obs.device import METRIC_NAMES  # noqa: E402

    for name in METRIC_NAMES:
        if not scheme.match(name) and name not in allow:
            bad.append(f"r2d2dpg_tpu/obs/device.py: {name}")
    declared = set(METRIC_NAMES)
    for name in pat.findall(Path("r2d2dpg_tpu/obs/device.py").read_text()):
        if name.startswith("r2d2dpg_device_") and name not in declared:
            bad.append(
                f"r2d2dpg_tpu/obs/device.py: {name} registered but "
                "missing from METRIC_NAMES"
            )
# The experience-quality family (obs/quality.py METRIC_NAMES, ISSUE 18):
# same contract as the device plane — the module enumerates its
# namespace, each concrete name is held to the scheme, and a
# r2d2dpg_quality_* registration missing from METRIC_NAMES is an
# offence.
if Path("r2d2dpg_tpu/obs/quality.py").exists():
    from r2d2dpg_tpu.obs.quality import (  # noqa: E402
        METRIC_NAMES as QUALITY_NAMES,
    )

    for name in QUALITY_NAMES:
        if not scheme.match(name) and name not in allow:
            bad.append(f"r2d2dpg_tpu/obs/quality.py: {name}")
    declared = set(QUALITY_NAMES)
    for name in pat.findall(Path("r2d2dpg_tpu/obs/quality.py").read_text()):
        if name.startswith("r2d2dpg_quality_") and name not in declared:
            bad.append(
                f"r2d2dpg_tpu/obs/quality.py: {name} registered but "
                "missing from METRIC_NAMES"
            )
# The serving family (serving/router.py METRIC_NAMES, ISSUE 20): same
# contract again, but the registrations SPAN two modules (the router's
# fleet-level instruments plus service.py's per-worker _WorkerInstruments)
# so the reverse check scans the whole serving/ package — a
# r2d2dpg_serve_* registration anywhere in it missing from the router's
# METRIC_NAMES is an offence.
if Path("r2d2dpg_tpu/serving/router.py").exists():
    from r2d2dpg_tpu.serving.router import (  # noqa: E402
        METRIC_NAMES as SERVE_NAMES,
    )

    for name in SERVE_NAMES:
        if not scheme.match(name) and name not in allow:
            bad.append(f"r2d2dpg_tpu/serving/router.py: {name}")
    declared = set(SERVE_NAMES)
    for path in sorted(Path("r2d2dpg_tpu/serving").rglob("*.py")):
        for name in pat.findall(path.read_text()):
            if name.startswith("r2d2dpg_serve_") and name not in declared:
                bad.append(
                    f"{path}: {name} registered but missing from "
                    "serving/router.py METRIC_NAMES"
                )
if bad:
    print("\n".join(bad))
    print(
        "lint_obs: FAIL — metric name outside the documented "
        "r2d2dpg_<subsystem>_<metric> scheme (docs/OBSERVABILITY.md); "
        "rename it, or allowlist it in scripts/obs_metric_allowlist.txt "
        "with a reason"
    )
    sys.exit(1)
EOF
echo "lint_obs: OK"
