"""Fleet actor: an out-of-process collector streaming experience upstream.

One actor subprocess owns its env pool (``--num-envs`` lanes of the
vmapped batch, or a host dm_control pool) and a stale copy of the
learner's nets, runs the R2D2-DPG rollout, computes initial priorities
locally with those stale nets (Ape-X §3: sequences enter replay already
ranked), and streams one ``replay.StagedSequences`` batch per collect
phase to the learner's ingest server — applying versioned param updates
between phases and ignoring regressions (a delayed PARAMS frame must
never roll the policy backwards).

Exploration: Ape-X gives actor ``i`` of ``N`` its own epsilon
(1803.00933 §D); the DPG analogue is this repo's sigma ladder
(``ops/noise.py``).  In-process the "actors" are env lanes, so the ladder
spans ``num_envs``; in a fleet it spans the GLOBAL ``num_actors *
num_envs`` lanes and each actor slices its contiguous block —
``FleetActorTrainer._local_sigmas`` below, the same slicing contract as
``SPMDTrainer``'s per-device shards.  A 3-actor pendulum fleet explores
exactly like one 3x-wider in-process batch.

CLI (spawned by ``fleet/supervisor.py``; runnable by hand for debugging):

    python -m r2d2dpg_tpu.fleet.actor --config pendulum_tiny \\
        --connect 127.0.0.1:7450 --actor-id 0 --num-actors 3 --seed 0
"""

from __future__ import annotations

import argparse
import dataclasses
import socket as socket_mod
import sys
import threading
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from r2d2dpg_tpu.configs import CONFIGS, ExperimentConfig, get_config
from r2d2dpg_tpu.fleet import chaos as fleet_chaos
from r2d2dpg_tpu.fleet import wire
from r2d2dpg_tpu.fleet.transport import (
    HEADER_BYTES,
    MAX_FRAME_BYTES,
    READ_DEADLINE_S,
    K_ACK,
    K_BYE,
    K_HELLO,
    K_PARAMS,
    K_SEQS,
    K_STATS,
    K_TELEM,
    FrameError,
    PeerDeadError,
    connect,
    hello_auth_proof,
    pack_hello,
    pack_obj,
    recv_frame_heartbeat,
    send_frame,
    send_frame_parts,
    unpack_obj,
)
from r2d2dpg_tpu.obs import flight_event, get_registry, set_flight_identity
from r2d2dpg_tpu.obs import trace as obs_trace
from r2d2dpg_tpu.ops import sigma_ladder
from r2d2dpg_tpu.replay.arena import StagedSequences
from r2d2dpg_tpu.training.assembler import emit
from r2d2dpg_tpu.training.pipeline import CollectorState, split_state
from r2d2dpg_tpu.training.trainer import Trainer, TrainerConfig
from r2d2dpg_tpu.utils.codes import (
    EXIT_AUTH_REFUSED,
    EXIT_WIRE_REFUSED,
    OK,
    REFUSED_AUTH,
    REFUSED_WIRE,
    SHED_INGEST,
)


class FleetActorTrainer(Trainer):
    """A ``Trainer`` whose noise ladder is one actor's slice of the fleet's.

    Everything else (collect scan, window assembler, episode accounting)
    is the base trainer verbatim — the actor IS a collector, just living
    in its own process with ``num_envs`` local lanes of a
    ``num_actors * num_envs``-lane fleet."""

    def __init__(
        self,
        env,
        agent,
        config: TrainerConfig,
        *,
        actor_index: int,
        num_actors: int,
    ):
        if not 0 <= actor_index < num_actors:
            raise ValueError(
                f"actor_index {actor_index} outside fleet of {num_actors}"
            )
        self.actor_index = actor_index
        self.num_actors = num_actors
        super().__init__(env, agent, config)

    def _local_sigmas(self) -> jnp.ndarray:
        sigmas = sigma_ladder(
            self.num_actors * self.config.num_envs,
            sigma_max=self.config.sigma_max,
            alpha=self.config.ladder_alpha,
            kind=self.config.ladder_kind,
        )
        lo = self.actor_index * self.config.num_envs
        return sigmas[lo : lo + self.config.num_envs]


def build_actor_trainer(
    exp: ExperimentConfig, *, actor_index: int, num_actors: int
) -> FleetActorTrainer:
    """The actor's trainer: full net/agent recipe, TINY arena (the actor
    never samples — replay lives learner-side; allocating the config's
    full capacity here would burn host RAM per actor for buffers that
    only ever hold ``init_state`` zeros)."""
    env = exp.env_factory()
    agent = exp.build_agent(env)
    tcfg = dataclasses.replace(
        exp.trainer, capacity=max(exp.trainer.num_envs, 1), min_replay=1
    )
    return FleetActorTrainer(
        env, agent, tcfg, actor_index=actor_index, num_actors=num_actors
    )


class FleetActor:
    """The worker loop: collect -> rank -> stream -> apply params."""

    def __init__(
        self,
        exp: ExperimentConfig,
        *,
        actor_id: int,
        num_actors: int,
        address: str,
        seed: Optional[int] = None,
        wire_config: Optional[wire.WireConfig] = None,
        max_frame_bytes: int = MAX_FRAME_BYTES,
        telem_every: float = 0.0,
        trace_sample: float = 0.0,
        read_deadline_s: float = READ_DEADLINE_S,
        warmup_deadline_s: float = 120.0,
        auth_token: Optional[str] = None,
        shard_direct: bool = False,
        chaos_spec: Optional[str] = None,
        reconnect_tries: int = 4,
        reconnect_base_s: float = 0.5,
        reconnect_max_s: float = 10.0,
    ):
        self.actor_id = actor_id
        self.address = address
        # Liveness bound on this end of the wire (transport.py): no ack
        # wait or backpressured send ever hangs past the deadline; a
        # silent learner is PINGed once, then treated as dead (reconnect
        # attempts below, then a retryable exit for the supervisor).
        # Until a session's FIRST ack the LARGER of the two deadlines
        # applies — the learner's first drain-learn compile legitimately
        # parks the handler in a queue-full wait (not reading, so no PONG
        # either), and a dialed-down heartbeat must not read that warmup
        # as a dead learner and churn the whole fleet through restarts.
        # The ingest server holds the mirror-image warmup window.
        self.read_deadline_s = read_deadline_s
        self.warmup_deadline_s = max(warmup_deadline_s, read_deadline_s)
        self.auth_token = auth_token
        # Reconnect-with-backoff (docs/FLEET.md "Failure modes"): a torn
        # connection — ingest restart, reaped stall, dropped conn — is
        # retried in-process with a fresh socket + HELLO + param snapshot
        # before the actor gives the incarnation up to the supervisor.  A
        # session that delivered at least one acked batch resets the
        # ladder (the same healthy-uptime contract as the supervisor's).
        self.reconnect_tries = reconnect_tries
        self.reconnect_base_s = reconnect_base_s
        self.reconnect_max_s = reconnect_max_s
        # Fleet observability plane (ISSUE 6): TELEM snapshot cadence in
        # seconds (0 = off; train.py --obs-fleet spawns actors at 1 Hz)
        # and the experience-path trace sampling rate (0 = off).
        self.telem_every = float(telem_every)
        self.trace_sample = float(trace_sample)
        self._telem_last = 0.0
        # The wire fast lane (fleet/wire.py): must MIRROR the learner's
        # --fleet-wire/--fleet-compress — the ingest server refuses a
        # mismatched HELLO (one fleet, one wire format).
        self.wire_config = (wire_config or wire.WireConfig()).validate()
        # Frame ceiling: must mirror the learner's FleetConfig value too
        # (the spawner forwards it) — a packer pinned to the default would
        # FrameTooLarge-crash-loop a fleet configured for larger frames,
        # and a larger actor ceiling would emit frames the server refuses.
        self.max_frame_bytes = max_frame_bytes
        self.trainer = build_actor_trainer(
            exp, actor_index=actor_id, num_actors=num_actors
        )
        t = self.trainer
        # Host-pool envs label their r2d2dpg_envpool_* series per ROLE so a
        # fleet's actor pools never interleave with a learner-side pool.
        if hasattr(t.env, "set_role"):
            t.env.set_role("actor")
        seed = t.config.seed if seed is None else seed
        # Distinct stream per actor: same base seed, folded actor index —
        # a fleet at seed S is a different (equally valid) trajectory per
        # actor, never N copies of one rollout.
        key = jax.random.fold_in(jax.random.PRNGKey(seed), actor_id)
        state = t.init(key)
        self._cstate, lstate = split_state(state)
        # The stale learner-net copy: acts AND ranks until the first
        # PARAMS frame lands (version 0 = own init).
        self._train = lstate.train
        self._param_version = 0
        self._sheds = 0
        self._phase = 0
        self._batches = 0  # emitted (post-warmup) batches: the chaos clock
        # Orderly drain (ISSUE 16 scale-down): once set — SIGUSR1 from
        # the supervisor's retire_slot, or request_drain() in-process —
        # the session loop exits after the CURRENT phase's ack lands, so
        # the banked accounting is folded, then falls through to BYE and
        # a zero exit.  Scale-down loses no steps and looks nothing like
        # a crash.
        self._drain = threading.Event()
        self._last_env_steps = 0.0  # for per-phase deltas (see run)
        # At-least-once stats accounting: the per-phase episode/step
        # DELTAS ride the SEQS message and are cleared only once an ack
        # proves the server owns them (OK folds them; SHED banks them
        # server-side).  A connection lost before the ack re-banks them
        # into the NEXT send, so a drill's dropped frame loses experience
        # (droppable by contract) but never loses accounting.  The rare
        # double-count window — server queued the batch but its OK ack
        # died on the wire — is the price of never silently losing steps.
        self._pending_stats = {
            "env_steps_delta": 0.0, "ep_return_sum": 0.0, "ep_count": 0.0,
        }
        # Direct data plane (ISSUE 17): when the learner advertises a shard
        # assignment on an ack, dial the shard and ship SEQS to it directly
        # — the control connection then carries only a tiny K_STATS frame
        # per phase (params/telem/accounting), shedding the ingest forward
        # hop from the experience path.  Any data-leg failure falls back
        # LOUDLY to the learner-forwarded path; _pending_stats is cleared
        # only on a control-plane ack, so accounting is plane-independent.
        self.shard_direct = bool(shard_direct)
        self._assignment: Optional[dict] = None  # last dialed advert
        self._failed_assignment: Optional[dict] = None  # don't re-hammer
        self._data_sock = None  # live => ship SEQS direct
        self._data_packer: Optional[wire.TreePacker] = None
        self._data_epoch = -1  # epoch the data HELLO ack pinned
        # Actor-side chaos faults (fleet/chaos.py): the forwarded
        # --chaos-spec's stall/corrupt drills that target THIS actor.
        self.chaos: Optional[fleet_chaos.ActorChaos] = None
        if chaos_spec:
            # ``seed`` is already resolved above (config default or
            # override) — the same value the learner's engine hashes, so
            # both sides agree on every fault's target actor.
            self.chaos = fleet_chaos.ActorChaos(
                fleet_chaos.parse_chaos_spec(chaos_spec),
                seed=seed,
                num_actors=num_actors,
                actor_id=actor_id,
            )
        self._warm_prog = jax.jit(
            lambda cs, behavior, critic: t._collect(
                cs, behavior=behavior, critic_params=critic
            ),
            donate_argnums=(0,),
        )
        self._collect_prog = jax.jit(self._collect_emit, donate_argnums=(0,))
        self._local_priorities = (
            t.config.prioritized and t.config.initial_priority == "td"
        )
        if self._local_priorities:
            self._prio_prog = jax.jit(t.agent.initial_priority)
        reg = get_registry()
        self._obs_phases = reg.counter(
            "r2d2dpg_actor_phases_total", "collect phases completed"
        )
        self._obs_shed = reg.counter(
            "r2d2dpg_actor_shed_total", "batches the ingest server shed"
        )
        self._obs_version = reg.gauge(
            "r2d2dpg_actor_param_version", "last applied param version"
        )
        self._obs_bytes_out = reg.counter(
            "r2d2dpg_actor_bytes_out_total",
            "bytes this actor put on the fleet wire (frames + headers)",
        )
        self._obs_bytes_in = reg.counter(
            "r2d2dpg_actor_bytes_in_total",
            "bytes this actor received off the fleet wire (acks + params)",
        )
        self._obs_telem = reg.counter(
            "r2d2dpg_actor_telem_sent_total",
            "TELEM registry snapshots pushed to the learner's ingest",
        )
        self._obs_reconnects = reg.counter(
            "r2d2dpg_actor_reconnects_total",
            "successful in-process reconnects after a torn connection "
            "(fresh socket + HELLO + param snapshot, same incarnation)",
        )
        # Per-plane byte accounting (ISSUE 17 satellite): the data leg's
        # bytes land here and ONLY here — never in the actor/control
        # totals above — so control-vs-data traffic stays separable.  The
        # r2d2dpg_fleet_ prefix keeps these out of the shard TELEM echo.
        self._obs_data_out = reg.counter(
            "r2d2dpg_fleet_data_bytes_out_total",
            "bytes sent on the direct actor->shard data plane",
            labelnames=("plane",),
        ).labels(plane="data")
        self._obs_data_in = reg.counter(
            "r2d2dpg_fleet_data_bytes_in_total",
            "bytes received on the direct actor->shard data plane",
            labelnames=("plane",),
        ).labels(plane="data")
        self._obs_fallback = reg.counter(
            "r2d2dpg_actor_data_fallback_total",
            "direct data-plane failures that fell back to the "
            "learner-forwarded path (dial refused, torn leg, partition)",
        )
        self._session_delivered = False

    # ---------------------------------------------------------- device parts
    def _collect_emit(self, cstate: CollectorState, behavior, critic):
        cstate = self.trainer._collect(
            cstate, behavior=behavior, critic_params=critic
        )
        return cstate, emit(cstate.window)

    # -------------------------------------------------------------- params
    def maybe_apply_params(self, msg: Any) -> bool:
        """Apply a versioned snapshot; IGNORE stale or replayed versions.

        The regression guard: acks/pushes can interleave across a
        reconnect, and a policy must only ever move forward — an actor
        that applied version 7 then saw a delayed 5 would collect with
        nets the learner has already trained past twice over."""
        version = int(msg["version"])
        if version <= self._param_version:
            flight_event(
                "param_regression_ignored",
                got=version,
                have=self._param_version,
            )
            return False
        # device_put ONCE at apply time: leaving numpy leaves in _train
        # would re-upload the whole param set on every jitted collect call.
        p = jax.device_put(msg["params"])
        self._train = dataclasses.replace(
            self._train,
            actor_params=p["actor_params"],
            critic_params=p["critic_params"],
            target_actor_params=p["target_actor_params"],
            target_critic_params=p["target_critic_params"],
        )
        self._param_version = version
        self._obs_version.set(float(version))
        return True

    # ------------------------------------------------------------ one phase
    def collect_phase(self) -> Optional[StagedSequences]:
        """One stride of env steps; returns the emitted batch (None during
        window warm-up, when the window still contains init padding)."""
        behavior = self._train.actor_params
        critic = self.trainer.agent.behavior_critic_params(self._train)
        if self._phase < self.trainer.window_fill_phases:
            self._cstate = self._warm_prog(self._cstate, behavior, critic)
            self._phase += 1
            self._obs_phases.inc()
            return None
        self._cstate, seq = self._collect_prog(self._cstate, behavior, critic)
        self._phase += 1
        self._obs_phases.inc()
        prios = (
            self._prio_prog(self._train, seq)
            if self._local_priorities
            else None
        )
        return StagedSequences(seq=seq, priorities=prios)

    def _pop_episode_stats(self):
        """Drain the device accumulators (refs leave ``_cstate`` before the
        next donating collect call — the pipeline collector's discipline)."""
        cs = self._cstate
        refs = (jnp.copy(cs.env_steps), cs.completed_return_sum, cs.completed_count)
        self._cstate = dataclasses.replace(
            cs,
            completed_return_sum=jnp.zeros(()),
            completed_count=jnp.zeros(()),
        )
        return refs

    # ------------------------------------------------------------------ run
    def request_drain(self) -> None:
        """Ask the actor to leave the fleet cleanly: finish the current
        phase (its ack folds the pending accounting), send BYE, return.
        Signal-safe and idempotent — the supervisor's retire path routes
        SIGUSR1 here, and the autoscaler's scale-down rides on it."""
        if not self._drain.is_set():
            self._drain.set()
            flight_event("actor_drain", phase=self._phase)

    def run(self, max_phases: Optional[int] = None) -> None:
        """Stream until the server goes away (orderly end) or an
        unrecoverable error surfaces (crash — nonzero exit, the supervisor
        restarts).

        A torn connection — ingest restart, a heartbeat reap after a
        stall, a chaos conn-drop — is retried IN-process first: fresh
        socket, fresh HELLO (the server re-pushes its current param
        snapshot ahead of the hello ack), fresh wire schema cache, with
        exponential backoff between attempts.  Collection state (window,
        env pool, phase count, pending accounting deltas) survives the
        reconnect, so a recovered actor resumes streaming where it left
        off instead of re-paying its warm-up.  Only after
        ``reconnect_tries`` consecutive failed sessions does the error
        propagate (nonzero exit; the supervisor's backoff restart takes
        over)."""
        attempts = 0
        backoff = self.reconnect_base_s
        while True:
            self._session_delivered = False
            try:
                self._run_session(max_phases, reconnected=attempts > 0)
                return
            except (_OrderlyShutdown, _WireRefused, _AuthRefused):
                raise  # deterministic verdicts: never retried here
            except (FrameError, OSError) as e:
                if isinstance(e, PeerDeadError):
                    # Mirror of the ingest handler's reap: the learner
                    # answered neither frames nor our PING.
                    flight_event(
                        "peer_dead",
                        phase=self._phase,
                        deadline_s=self.read_deadline_s,
                        error=str(e),
                    )
                if self._session_delivered:
                    # A healthy session resets the ladder (the supervisor's
                    # healthy-uptime contract): only CONSECUTIVE failures
                    # walk toward giving the incarnation up.
                    attempts = 0
                    backoff = self.reconnect_base_s
                attempts += 1
                if attempts > self.reconnect_tries:
                    raise
                err = f"{type(e).__name__}: {e}"
                flight_event(
                    "actor_reconnect_wait",
                    phase=self._phase,
                    attempt=attempts,
                    backoff_s=round(backoff, 3),
                    error=err,
                )
                time.sleep(backoff)
                backoff = min(backoff * 2, self.reconnect_max_s)

    def _run_session(
        self, max_phases: Optional[int], *, reconnected: bool = False
    ) -> None:
        """One connection's lifetime: HELLO -> stream -> BYE."""
        # Warmup window until the first SEQS ack (see __init__): the
        # learner's first compile parks its handler, which is legitimate
        # silence — the steady-state deadline arms after the ack.
        sock = connect(self.address, read_deadline_s=self.warmup_deadline_s)
        # Wire state lives and dies with the socket: a reconnect gets a
        # fresh packer whose first SEQS frame re-inlines its schema.
        packer = wire.TreePacker(
            self.wire_config, max_frame_bytes=self.max_frame_bytes
        )
        self._unpacker = wire.TreeUnpacker(
            max_frame_bytes=self.max_frame_bytes
        )
        try:
            hello = {
                "actor_id": self.actor_id,
                "num_envs": self.trainer.config.num_envs,
                **wire.negotiation_fields(self.wire_config),
            }
            if self.auth_token is not None:
                hello["auth"] = hello_auth_proof(self.auth_token)
            self._obs_bytes_out.inc(
                send_frame(
                    sock,
                    K_HELLO,
                    pack_hello(hello),  # JSON: parsed pre-auth on the far end
                    max_frame_bytes=self.max_frame_bytes,
                )
            )
            hello_ack = self._await_ack(sock)
            if hello_ack.get("code") == REFUSED_WIRE:
                raise _WireRefused(
                    f"ingest refused wire negotiation "
                    f"({hello_ack.get('reason')}); launch this actor with "
                    f"the learner's --fleet-wire/--fleet-compress "
                    f"(server expects {hello_ack.get('expect')})"
                )
            if hello_ack.get("code") == REFUSED_AUTH:
                raise _AuthRefused(
                    "ingest refused HELLO authentication; launch this "
                    "actor with the learner's --fleet-token"
                )
            if reconnected:
                flight_event("actor_reconnect", phase=self._phase)
                self._obs_reconnects.inc()
            # The HELLO ack may carry the shard assignment advert
            # (ingest._assignment waits for the tier at HELLO time): dial
            # the data plane before the first phase so the forward hop is
            # shed from batch one, not batch two.
            self._maybe_update_assignment(hello_ack)
            self._maybe_send_telem(sock, force=True)
            while (
                max_phases is None or self._phase < max_phases
            ) and not self._drain.is_set():
                if self.chaos is not None:
                    # The stall drill: stop reading AND sending mid-loop,
                    # exactly what a wedged env or GC pause looks like on
                    # the wire — the ingest handler's heartbeat reaps us.
                    self.chaos.maybe_stall(self._batches + 1)
                    if self.chaos.partition_data_plane(self._batches + 1):
                        # The partition drill: sever the data leg under
                        # our feet (shutdown, reference kept) so the next
                        # direct send hits a dead socket and the LOUD
                        # fallback path runs — the control plane keeps
                        # the accounting whole throughout.
                        self._partition_data_plane()
                # Trace sampling decided at collection time (obs/trace.py):
                # rate 0 allocates nothing and the frame is byte-identical
                # to an untraced wire.
                tr = obs_trace.maybe_start(self.trace_sample)
                staged = self.collect_phase()
                if staged is None:
                    # Warm-up: window not yet real.  The TELEM cadence must
                    # still tick — warm-up phases (the first carries the
                    # JIT compile, tens of seconds) would otherwise read as
                    # a wedged actor on the staleness gauge after every
                    # supervised restart.
                    self._maybe_send_telem(sock)
                    continue
                self._batches += 1
                # ONE batched device fetch per phase (episode stats + the
                # staged pytree + priorities) — the pop_episode_metrics
                # lesson; separate fetches would be three host syncs on
                # every actor's critical path.  None priorities pass
                # through device_get as an empty subtree.
                (env_steps, ret_sum, count), seq_host, prios_host = (
                    jax.device_get(
                        (
                            self._pop_episode_stats(),
                            staged.seq,
                            staged.priorities,
                        )
                    )
                )
                if tr is not None:
                    # Collection "ends" when the host holds the batch: the
                    # fetch above is part of the collect hop.
                    tr.t_collect_end = time.time()
                # DELTAS, not cumulative: a supervised restart resets this
                # process, and the learner's fleet-wide sums must stay
                # monotone across incarnations (ingest just accumulates).
                # Folded into _pending_stats, which is cleared only on an
                # ack — a frame lost to a torn connection re-banks its
                # accounting into the next send (at-least-once; __init__).
                steps_delta = float(env_steps) - self._last_env_steps
                self._last_env_steps = float(env_steps)
                self._pending_stats["env_steps_delta"] += steps_delta
                self._pending_stats["ep_return_sum"] += float(ret_sum)
                self._pending_stats["ep_count"] += float(count)
                # Provenance stamps ride the already-fetched host batch:
                # the behavior version these sequences were collected
                # under and this actor's monotone phase clock.  The
                # learner folds lag/age from them without any extra
                # device traffic on either side.
                seq_b = jax.tree_util.tree_leaves(seq_host)[0].shape[0]
                staged_host = StagedSequences(
                    seq=seq_host,
                    priorities=prios_host,
                    behavior_version=np.full(
                        (seq_b,), self._param_version, np.int64
                    ),
                    collect_id=np.full((seq_b,), self._phase, np.int64),
                )
                sent_direct = self._data_sock is not None and (
                    self._send_direct(staged_host)
                )
                if sent_direct:
                    # Experience is shard-owned; only the accounting
                    # deltas ride the control connection now — a tiny
                    # pickled K_STATS frame, acked like SEQS so the
                    # at-least-once clear below is plane-independent.
                    self._obs_bytes_out.inc(
                        send_frame(
                            sock,
                            K_STATS,
                            pack_obj(  # wire-lint: control
                                {
                                    "phase": self._phase,
                                    "param_version": self._param_version,
                                    **self._pending_stats,
                                }
                            ),
                            max_frame_bytes=self.max_frame_bytes,
                        )
                    )
                else:
                    # The learner-forwarded path: steady state when
                    # --shard-direct is off, the LOUD fallback when the
                    # data leg just died (the staged batch that failed
                    # mid-push retries here — nothing is dropped).
                    # Schema-cached binary frames (fleet/wire.py), tensor
                    # bytes streamed without an intermediate payload join
                    # (send_frame_parts).
                    parts = packer.pack(
                        {
                            "phase": self._phase,
                            "param_version": self._param_version,
                            **self._pending_stats,
                            "staged": staged_host,
                        },
                        trace=tr,
                    )
                    if self.chaos is not None and (
                        self.chaos.corrupt_next_frame(self._batches)
                    ):
                        # The corrupt-frame drill: pristine CRC over
                        # flipped bytes — the server MUST reject it
                        # (FrameCRCError) and kill the connection; we
                        # reconnect and re-bank.
                        self._obs_bytes_out.inc(
                            fleet_chaos.send_corrupt_frame(
                                sock, K_SEQS, parts
                            )
                        )
                    else:
                        self._obs_bytes_out.inc(
                            send_frame_parts(
                                sock,
                                K_SEQS,
                                parts,
                                max_frame_bytes=self.max_frame_bytes,
                            )
                        )
                ack = self._await_ack(sock)
                # Acked (OK or shed): the server owns the accounting now —
                # OK folds it with the batch, a shed banks it server-side.
                for k in self._pending_stats:
                    self._pending_stats[k] = 0.0
                if not self._session_delivered:
                    # First ack of the session: warmup is over, arm the
                    # steady-state heartbeat deadline (mirror of the
                    # ingest handler tightening on its first SEQS).
                    sock.settimeout(self.read_deadline_s)
                self._session_delivered = True
                if ack["code"] == SHED_INGEST:
                    self._sheds += 1
                    self._obs_shed.inc()
                # Every control ack may carry a (re-)advert: the first
                # one after an epoch-bumped shard rejoin re-dials the new
                # incarnation; an unchanged advert on a live leg is a
                # no-op.
                self._maybe_update_assignment(ack)
                self._maybe_send_telem(sock)
            try:
                send_frame(sock, K_BYE, b"")  # wire-lint: control
            except OSError:
                pass
        finally:
            # The data leg lives and dies with the control session: a
            # reconnect re-dials from the fresh HELLO ack's advert.
            self._drop_data_plane(reason=None)
            try:
                sock.close()
            except OSError:
                pass

    def _maybe_send_telem(self, sock, force: bool = False) -> None:
        """The ~1 Hz TELEM cadence rider (ISSUE 6 leg 1): push this
        process's registry snapshot so the learner's exporter is the
        fleet's single scrape point.  Fire-and-forget control frame — no
        ack (the next SEQS ack already paces the connection); rides the
        collect loop, so a wedged actor's silence is itself the signal
        (the ingest side's per-actor staleness gauge keeps counting)."""
        if self.telem_every <= 0.0:
            return
        now = time.monotonic()
        if not force and now - self._telem_last < self.telem_every:
            return
        self._telem_last = now
        self._obs_telem.inc()
        self._obs_bytes_out.inc(
            send_frame(
                sock,
                K_TELEM,
                pack_obj(  # wire-lint: control
                    {
                        "actor_id": self.actor_id,
                        "host": socket_mod.gethostname(),
                        "t_wall": time.time(),
                        "snapshot": get_registry().snapshot(),
                    }
                ),
                max_frame_bytes=self.max_frame_bytes,
            )
        )

    # ------------------------------------------------- direct data plane
    def _maybe_update_assignment(self, ack: Any) -> None:
        """Track the learner's shard-assignment advert; (re)dial the data
        plane when it changes.

        The advert rides control acks (HELLO/SEQS/STATS), so this runs at
        most once per phase — natural rate limiting on re-dials.  An
        advert identical to the last FAILED one is skipped (no hammering
        a refusing shard every phase); the learner re-adverts with a
        bumped epoch once the shard rejoins, which unsticks us."""
        if not self.shard_direct or not isinstance(ack, dict):
            return
        advert = ack.get("shard_assignment")
        if not isinstance(advert, dict):
            return
        if advert == self._failed_assignment:
            return
        if (
            self._data_sock is not None
            and self._assignment is not None
            and advert.get("address") == self._assignment.get("address")
            and int(advert.get("epoch", -1)) == self._data_epoch
        ):
            return  # same shard incarnation, leg already live
        self._dial_data_plane(advert)

    def _dial_data_plane(self, advert: dict) -> bool:
        """Dial the advertised shard: connect + plane="data" HELLO (same
        token as the control HELLO) + OK ack.  A refusal or dead address
        is LOUD but non-fatal — the learner-forwarded path keeps the
        experience flowing."""
        address = str(advert.get("address") or "")
        if not address:
            return False
        self._drop_data_plane(reason=None)  # replace any previous leg
        sock = None
        try:
            sock = connect(address, read_deadline_s=self.read_deadline_s)
            hello = {
                "actor_id": self.actor_id,
                "plane": "data",
                **wire.negotiation_fields(self.wire_config),
            }
            if self.auth_token is not None:
                hello["auth"] = hello_auth_proof(self.auth_token)
            self._obs_data_out.inc(
                send_frame(
                    sock,
                    K_HELLO,
                    pack_hello(hello),
                    max_frame_bytes=self.max_frame_bytes,
                )
            )
            hello_ack = self._await_data_ack(sock)
            if hello_ack.get("code") != OK:
                raise FrameError(
                    f"shard refused data-plane HELLO: "
                    f"code={hello_ack.get('code')} "
                    f"reason={hello_ack.get('reason')}"
                )
        except (FrameError, OSError) as e:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            self._failed_assignment = dict(advert)
            self._obs_fallback.inc()
            flight_event(
                "data_plane_dial_failed",
                phase=self._phase,
                shard=advert.get("shard"),
                address=address,
                error=f"{type(e).__name__}: {e}",
            )
            return False
        self._data_sock = sock
        # Fresh packer per leg: its first SEQS frame re-inlines the
        # schema, exactly like a control reconnect.
        self._data_packer = wire.TreePacker(
            self.wire_config, max_frame_bytes=self.max_frame_bytes
        )
        self._data_epoch = int(advert.get("epoch", -1))
        self._assignment = dict(advert)
        self._failed_assignment = None
        flight_event(
            "data_plane_dialed",
            phase=self._phase,
            shard=advert.get("shard"),
            address=address,
            epoch=self._data_epoch,
        )
        return True

    def _send_direct(self, staged: StagedSequences) -> bool:
        """Ship one staged batch straight to the shard; True only once
        its ack lands.  ANY failure tears the leg down loudly and returns
        False — the caller then sends the SAME batch on the control
        connection, so a mid-push shard death drops nothing."""
        try:
            parts = self._data_packer.pack({"staged": staged})
            self._obs_data_out.inc(
                send_frame_parts(
                    self._data_sock,
                    K_SEQS,
                    parts,
                    max_frame_bytes=self.max_frame_bytes,
                )
            )
            ack = self._await_data_ack(self._data_sock)
            if ack.get("code") != OK:
                raise FrameError(
                    f"shard data-plane ack code {ack.get('code')}"
                )
            return True
        except (FrameError, OSError) as e:
            self._drop_data_plane(reason=f"{type(e).__name__}: {e}")
            return False

    def _await_data_ack(self, sock) -> Any:
        """Read to the shard's next ACK on the data leg.  The shard rides
        TELEM pushes on any authenticated connection — the learner is
        their consumer, so here they are counted and dropped."""
        while True:
            kind, payload = recv_frame_heartbeat(
                sock,
                max_frame_bytes=self.max_frame_bytes,
                bytes_in=self._obs_data_in.inc,
                bytes_out=self._obs_data_out.inc,
            )
            self._obs_data_in.inc(HEADER_BYTES + len(payload))
            if kind == K_TELEM:
                continue
            if kind == K_ACK:
                return unpack_obj(payload)  # wire-lint: control
            raise FrameError(f"unexpected data-plane frame kind {kind}")

    def _drop_data_plane(self, reason: Optional[str]) -> None:
        """Tear down the data leg.  A non-None reason is a FAILURE — loud
        flight event + fallback counter; None is lifecycle (session end,
        re-dial replacing the leg)."""
        sock, self._data_sock = self._data_sock, None
        self._data_packer = None
        self._assignment = None
        self._data_epoch = -1
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if reason is not None:
            self._obs_fallback.inc()
            flight_event(
                "data_plane_fallback",
                phase=self._phase,
                error=reason,
            )

    def _partition_data_plane(self) -> None:
        """Chaos partition_data_plane: sever the leg at the transport
        (shutdown both directions) but KEEP the reference, so the next
        direct send surfaces the failure exactly like a real network
        partition would — mid-send, not at dial time."""
        if self._data_sock is None:
            return
        try:
            self._data_sock.shutdown(socket_mod.SHUT_RDWR)
        except OSError:
            pass

    def _await_ack(self, sock) -> Any:
        """Read to the next ACK, applying any PARAMS pushed ahead of it
        (the server orders PARAMS-then-ACK so a fresh snapshot is live
        before the next collect phase).

        Deadline-aware (transport.recv_frame_heartbeat): a learner silent
        past the read deadline is PINGed once and declared dead on a
        second silence — this wait was the fleet's last unbounded read."""
        while True:
            kind, payload = recv_frame_heartbeat(
                sock,
                max_frame_bytes=self.max_frame_bytes,
                bytes_in=self._obs_bytes_in.inc,
                bytes_out=self._obs_bytes_out.inc,
            )
            self._obs_bytes_in.inc(HEADER_BYTES + len(payload))
            if kind == K_PARAMS:
                self.maybe_apply_params(self._unpacker.unpack(payload))
                continue
            if kind == K_ACK:
                return unpack_obj(payload)  # wire-lint: control
            if kind == K_BYE:
                raise _OrderlyShutdown()
            raise FrameError(f"unexpected frame kind {kind}")


class _OrderlyShutdown(Exception):
    """Server said BYE mid-stream: exit 0, nothing crashed."""


class _WireRefused(FrameError):
    """HELLO refused: deterministic config mismatch, not a transient crash.

    Exits with ``EXIT_WIRE_REFUSED`` so the supervisor gives the slot up
    instead of crash-restarting a misconfigured actor forever (every
    incarnation would be refused again within milliseconds)."""


class _AuthRefused(FrameError):
    """HELLO refused on the --fleet-token proof: deterministic
    misconfiguration, same terminal contract as ``_WireRefused`` (exits
    ``EXIT_AUTH_REFUSED``; the supervisor gives the slot up)."""


# ---------------------------------------------------------------------- CLI
def structural_argv(exp: ExperimentConfig):
    """The actor flags that must MIRROR the learner's resolved config —
    net/param-tree structure (a mismatched tree crash-loops every actor)
    and the exploration ladder.  THE single source for the spawner
    (train.py forwards exactly this); a new structural knob is added here
    plus the parser/_apply_overrides below, never hand-copied into
    spawners."""
    return [
        "--num-envs", str(exp.trainer.num_envs),
        "--n-step", str(exp.agent.n_step),
        "--twin-critic", "1" if exp.agent.twin_critic else "0",
        "--sigma-max", str(exp.trainer.sigma_max),
        "--ladder-alpha", str(exp.trainer.ladder_alpha),
        "--compute-dtype", exp.compute_dtype,
    ]


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="python -m r2d2dpg_tpu.fleet.actor", description=__doc__
    )
    p.add_argument("--config", required=True, choices=sorted(CONFIGS))
    p.add_argument("--connect", required=True, help="ingest server address")
    p.add_argument("--actor-id", type=int, required=True)
    p.add_argument("--num-actors", type=int, required=True)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--phases", type=int, default=None,
                   help="stop after this many collect phases (default: "
                   "stream until the server disconnects)")
    # Structural/exploration overrides — must match the learner's so the
    # published param trees fit the actor's nets (train.py forwards them).
    p.add_argument("--num-envs", type=int, default=None)
    p.add_argument("--n-step", type=int, default=None)
    p.add_argument("--twin-critic", type=int, default=None, choices=[0, 1])
    p.add_argument("--sigma-max", type=float, default=None)
    p.add_argument("--ladder-alpha", type=float, default=None)
    p.add_argument("--compute-dtype", default=None,
                   choices=["float32", "bfloat16"])
    # Wire fast lane — must mirror the learner's --fleet-wire/
    # --fleet-compress (the ingest server refuses a mismatched HELLO).
    p.add_argument("--wire", default="f32", choices=list(wire.ENCODINGS),
                   help="payload precision on the wire (bf16: observations/"
                   "carries/params downcast; rewards/priorities stay f32)")
    p.add_argument("--compress", default="none",
                   choices=list(wire.COMPRESSIONS),
                   help="frame compression (zstd only where the zstandard "
                   "module is installed)")
    p.add_argument("--max-frame-bytes", type=int, default=MAX_FRAME_BYTES,
                   help="frame ceiling — must mirror the learner's "
                   "FleetConfig.max_frame_bytes (the spawner forwards it)")
    p.add_argument("--flight-path", default=None,
                   help="dump this actor's flight ring here on exit")
    # Fleet observability plane (ISSUE 6; train.py --obs-fleet/
    # --trace-sample forward these).
    p.add_argument("--telem-every", type=float, default=0.0,
                   help="seconds between TELEM registry-snapshot pushes to "
                   "the learner's ingest (0 = off; --obs-fleet spawns 1.0)")
    p.add_argument("--trace-sample", type=float, default=0.0,
                   help="experience-path trace sampling rate in [0, 1] "
                   "(0 = off: no trace sidecar, byte-identical wire)")
    # Fault tolerance (ISSUE 7; docs/FLEET.md "Failure modes & recovery").
    p.add_argument("--read-deadline", type=float, default=READ_DEADLINE_S,
                   help="seconds a blocking wire read may wait before the "
                   "PING-then-reap liveness protocol runs — must mirror "
                   "the learner's --fleet-heartbeat (the spawner forwards "
                   "it)")
    p.add_argument("--fleet-token", default=None,
                   help="shared HELLO-authentication secret; defaults to "
                   "$R2D2DPG_FLEET_TOKEN (the spawner passes the secret "
                   "via the environment so it never shows in ps)")
    # Direct data plane (ISSUE 17; train.py --shard-direct forwards it).
    p.add_argument("--shard-direct", type=int, default=0, choices=[0, 1],
                   help="1: dial the learner-advertised replay shard and "
                   "ship SEQS to it directly (control connection carries "
                   "params/telem/accounting only); falls back loudly to "
                   "the learner-forwarded path on any data-leg failure")
    p.add_argument("--chaos-spec", default=None,
                   help="seeded chaos schedule (fleet/chaos.py grammar); "
                   "this actor fires the stall/corrupt faults that target "
                   "its id (the learner's engine fires the rest)")
    return p.parse_args(argv)


def _apply_overrides(exp: ExperimentConfig, args) -> ExperimentConfig:
    t = {
        k: getattr(args, k)
        for k in ("num_envs", "sigma_max", "ladder_alpha", "seed")
        if getattr(args, k) is not None
    }
    if t:
        exp = dataclasses.replace(
            exp, trainer=dataclasses.replace(exp.trainer, **t)
        )
    a = {}
    if args.n_step is not None:
        a["n_step"] = args.n_step
    if args.twin_critic is not None:
        a["twin_critic"] = bool(args.twin_critic)
    if a:
        exp = dataclasses.replace(
            exp, agent=dataclasses.replace(exp.agent, **a)
        )
    if args.compute_dtype is not None:
        exp = dataclasses.replace(exp, compute_dtype=args.compute_dtype)
    return exp


def main(argv=None) -> None:
    args = parse_args(argv)
    set_flight_identity(actor=args.actor_id)
    if args.flight_path:
        import os
        import signal

        from r2d2dpg_tpu.obs import get_flight_recorder

        flight_path = args.flight_path
        if os.path.exists(flight_path):
            # A predecessor incarnation (supervised restart) already
            # dumped here — its ring is post-mortem EVIDENCE (possibly a
            # chaos injection flushed moments before its SIGKILL), and an
            # overwrite would destroy it.  Dump beside it instead; the
            # fleet timeline merge globs flight*.jsonl, so both
            # incarnations stay attributable.
            root, ext = os.path.splitext(flight_path)
            flight_path = f"{root}.pid{os.getpid()}{ext}"
        get_flight_recorder().install(flight_path)
        # The supervisor's orderly teardown is a SIGTERM, whose default
        # disposition skips atexit — and with it the flight dump this
        # flag just armed.  Convert it to a clean SystemExit so every
        # incarnation leaves its flight_actor<i>.jsonl for the fleet
        # timeline merge (obs/flight.py).
        signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    exp = _apply_overrides(get_config(args.config), args)
    try:
        wire_config = wire.WireConfig(
            encoding=args.wire, compress=args.compress
        ).validate()
    except ValueError as e:
        raise SystemExit(f"fleet actor {args.actor_id}: --compress: {e}")
    if not 0.0 <= args.trace_sample <= 1.0:
        raise SystemExit(
            f"fleet actor {args.actor_id}: --trace-sample must be in [0, 1]"
        )
    auth_token = args.fleet_token
    if auth_token is None:
        import os

        auth_token = os.environ.get("R2D2DPG_FLEET_TOKEN") or None
    try:
        actor = FleetActor(
            exp,
            actor_id=args.actor_id,
            num_actors=args.num_actors,
            address=args.connect,
            seed=args.seed,
            wire_config=wire_config,
            max_frame_bytes=args.max_frame_bytes,
            telem_every=args.telem_every,
            trace_sample=args.trace_sample,
            read_deadline_s=args.read_deadline,
            auth_token=auth_token,
            shard_direct=bool(args.shard_direct),
            chaos_spec=args.chaos_spec,
        )
    except ValueError as e:
        # e.g. a malformed --chaos-spec: deterministic misconfiguration,
        # refused at startup rather than as a crash-looping fleet.
        raise SystemExit(f"fleet actor {args.actor_id}: {e}")
    # The supervisor's retire_slot speaks SIGUSR1 (ISSUE 16 scale-down):
    # finish the phase, fold the accounting via its ack, BYE, exit 0.
    # PEP 475 restarts the interrupted socket call, so a drain never
    # tears a frame — it lands at the next loop check.
    import signal

    signal.signal(signal.SIGUSR1, lambda *_: actor.request_drain())
    flight_event("actor_start", phase=0, address=args.connect)
    try:
        actor.run(max_phases=args.phases)
    except _OrderlyShutdown:
        # The server said BYE: the learner is done — exit 0, nothing broke.
        flight_event("actor_disconnect", phase=actor._phase)
    except (_WireRefused, _AuthRefused) as e:
        # Deterministic misconfiguration — a restart would be refused
        # again within milliseconds.  Exit with the dedicated code so the
        # supervisor gives this slot up instead of crash-looping it.
        err = f"{type(e).__name__}: {e}"
        auth = isinstance(e, _AuthRefused)
        flight_event(
            "actor_auth_refused" if auth else "actor_wire_refused",
            phase=actor._phase,
            error=err,
        )
        print(  # obs-lint: allow — CLI entrypoint, routed to the actor log
            f"fleet actor {args.actor_id}: {err}",
            file=sys.stderr,
            flush=True,
        )
        raise SystemExit(EXIT_AUTH_REFUSED if auth else EXIT_WIRE_REFUSED)
    except (FrameError, OSError) as e:
        # Anything else — refused connect, CRC violation, torn stream — is
        # a CRASH per this module's contract: record the actual error
        # (flight ring + stderr, which the supervisor routes to the
        # per-actor log) and exit nonzero so the supervisor restarts us.
        err = f"{type(e).__name__}: {e}"
        flight_event("actor_conn_lost", phase=actor._phase, error=err)
        raise SystemExit(
            f"fleet actor {args.actor_id}: connection lost at phase "
            f"{actor._phase}: {err}"
        )
    flight_event("actor_exit", phase=actor._phase, sheds=actor._sheds)


if __name__ == "__main__":
    main()
