"""SPMD tests on the virtual 8-device CPU mesh (SURVEY.md §4.4):
collective correctness, sharded training phases, sigma-ladder sharding."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from r2d2dpg_tpu.agents import AgentConfig, R2D2DPG
from r2d2dpg_tpu.configs import PENDULUM_R2D2
from r2d2dpg_tpu.models import ActorNet, CriticNet
from r2d2dpg_tpu.ops import sigma_ladder
from r2d2dpg_tpu.parallel import DP_AXIS, SPMDTrainer, make_mesh

try:
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def build_spmd(n_devices=8, **trainer_kw):
    mesh = make_mesh(n_devices)
    env = PENDULUM_R2D2.env_factory()
    agent_cfg = dataclasses.replace(
        PENDULUM_R2D2.agent, burnin=2, unroll=4, n_step=2, axis_name=DP_AXIS
    )
    actor = ActorNet(action_dim=env.spec.action_dim, hidden=16, use_lstm=True)
    critic = CriticNet(hidden=16, use_lstm=True)
    agent = R2D2DPG(actor, critic, agent_cfg)
    tcfg = dataclasses.replace(
        PENDULUM_R2D2.trainer,
        num_envs=trainer_kw.pop("num_envs", 8),
        stride=4,
        batch_size=trainer_kw.pop("batch_size", 16),
        capacity=trainer_kw.pop("capacity", 64),
        min_replay=trainer_kw.pop("min_replay", 8),
        **trainer_kw,
    )
    return SPMDTrainer(env, agent, tcfg, mesh), mesh


def test_psum_of_known_values():
    """Collective plumbing: psum over the dp mesh sums device contributions."""
    mesh = make_mesh(8)

    def f(x):
        return jax.lax.psum(x.sum(), DP_AXIS)

    x = jnp.arange(8.0)
    out = jax.jit(
        shard_map(f, mesh=mesh, in_specs=(P(DP_AXIS),), out_specs=P())
    )(x)
    assert float(out) == 28.0


def test_spmd_phases_run_and_stay_sharded():
    t, mesh = build_spmd()
    s = t.init()
    assert s.obs.sharding.spec == P(DP_AXIS)
    assert s.arena.priority.sharding.spec == P(DP_AXIS)
    n = t.window_fill_phases + t.replay_fill_phases + 2
    s = t.run(n, log_every=0)
    assert int(s.train.step) == 2 * t.config.learner_steps
    assert int(s.env_steps) == n * 4 * 8  # stride * global envs
    # Params stay replicated and identical across devices.
    leaf = jax.tree_util.tree_leaves(s.train.actor_params)[0]
    assert leaf.sharding.is_fully_replicated


def test_spmd_learner_matches_gradient_sync():
    """After one train phase, every device holds the same params (pmean'd
    grads from different local batches -> consistent replicated update)."""
    t, mesh = build_spmd()
    s = t.run(t.window_fill_phases + t.replay_fill_phases + 1, log_every=0)
    leaf = jax.tree_util.tree_leaves(s.train.critic_params)[0]
    shards = [np.asarray(sh.data) for sh in leaf.addressable_shards]
    for other in shards[1:]:
        np.testing.assert_array_equal(shards[0], other)


def test_sigma_ladder_is_global_across_shards():
    """Each device slices its rows of the *global* ladder — exploration
    heterogeneity must span the fleet, not repeat per device."""
    t, mesh = build_spmd()

    def local_sig(_):
        return t._local_sigmas()

    out = jax.jit(
        shard_map(
            local_sig, mesh=mesh, in_specs=(P(DP_AXIS),), out_specs=P(DP_AXIS)
        )
    )(jnp.zeros(8))
    want = sigma_ladder(8, sigma_max=t.config.sigma_max, alpha=t.config.ladder_alpha)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), rtol=1e-6)


def test_divisibility_validation():
    mesh = make_mesh(8)
    env = PENDULUM_R2D2.env_factory()
    agent_cfg = dataclasses.replace(PENDULUM_R2D2.agent, axis_name=DP_AXIS)
    actor = ActorNet(action_dim=1, hidden=8, use_lstm=True)
    critic = CriticNet(hidden=8, use_lstm=True)
    agent = R2D2DPG(actor, critic, agent_cfg)
    bad = dataclasses.replace(PENDULUM_R2D2.trainer, num_envs=6)
    with pytest.raises(ValueError, match="num_envs"):
        SPMDTrainer(env, agent, bad, mesh)


def test_axis_name_required():
    mesh = make_mesh(8)
    env = PENDULUM_R2D2.env_factory()
    actor = ActorNet(action_dim=1, hidden=8, use_lstm=True)
    critic = CriticNet(hidden=8, use_lstm=True)
    agent = R2D2DPG(actor, critic, PENDULUM_R2D2.agent)  # no axis_name
    with pytest.raises(ValueError, match="axis_name"):
        SPMDTrainer(env, agent, PENDULUM_R2D2.trainer, mesh)


def test_graft_entry_dryrun():
    """The driver's multi-chip dry run must pass on the CPU mesh."""
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


# ------------------------------------------------------- multi-host wrapper
def test_distributed_initialize_noop_and_global_mesh(monkeypatch):
    from r2d2dpg_tpu.parallel import DP_AXIS, distributed

    # No cluster env, CPU backend: must be a silent no-op.
    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    distributed.initialize()
    assert jax.process_count() == 1
    assert distributed.is_primary()

    mesh = distributed.global_mesh()
    assert mesh.shape[DP_AXIS] == len(jax.devices())


def test_distributed_initialize_already_up_is_noop(monkeypatch):
    from r2d2dpg_tpu.parallel import distributed

    # Simulate an already-initialized multi-process runtime: must return
    # before touching jax.distributed.initialize.
    monkeypatch.setattr(
        jax._src.distributed.global_state, "client", object(), raising=False
    )
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "localhost:1234")

    def boom(**kw):  # pragma: no cover - called only on regression
        raise AssertionError("re-initialized a live distributed runtime")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    distributed.initialize()


def test_distributed_single_host_tpu_worker_hostnames_is_noop(monkeypatch):
    # The axon plugin exports TPU_WORKER_HOSTNAMES=localhost even on a
    # single-host box; a single worker must not trigger pod bring-up.
    from r2d2dpg_tpu.parallel import distributed

    for var in ("JAX_COORDINATOR_ADDRESS", "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")

    def boom(**kw):  # pragma: no cover - called only on regression
        raise AssertionError("brought up distributed runtime on single host")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    distributed.initialize()
