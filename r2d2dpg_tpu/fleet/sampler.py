"""In-network experience sampling: sharded replay, learner-pulled batches.

The central-drain fleet (``fleet/ingest.py``) funnels EVERY collected
sequence through one staging queue into one device arena behind one drain
thread — the wire and the drain both carry experience that may never be
sampled.  This module inverts the topology (ISSUE 10; In-Network
Experience Sampling, PAPERS.md 2110.13506; Ape-X distributed replay,
1803.00933):

::

    actor 0 ── SEQS ──▶ handler ──▶ [shard 0]  priority structure + ring
    actor 1 ── SEQS ──▶ handler ──▶ [shard 1]      (replay/sharded.py)
    actor … ── SEQS ──▶ handler ──▶ [shard h(actor) mod N]
                                        ▲ │
                     SAMPLE_REQ {quota} ─┘ │ BATCH {seqs, slots/gens,
                     PRIO {slot, gen, p}◀──┘        probs, Σp^α}
                                      sampler learner:
                                      quotas ∝ Σp^α → K·B draws →
                                      learn program → TD write-back

- **Adds are concurrent**: each ingest handler writes straight into its
  actor's shard (consistent-hash ``shard_for_actor`` routing assigned at
  HELLO) under that shard's own lock — the central drain thread stops
  being a serialization point, and replay capacity is a per-shard slice
  (horizontal, not one device ring).
- **The learner pulls**: each train phase draws per-shard quotas from a
  multinomial over the shards' advertised ``Σ p^alpha``
  (``replay.sharded.shard_quotas``), samples within-shard
  proportionally, and learns on the assembled ``[K, B]`` batch with
  importance weights computed from the COMBINED two-level probabilities —
  exactly the central proportional distribution
  (tests/test_replay.py pins this on exact-integer priorities).
- **Priority write-back rides the versioned path in reverse**: PRIO
  frames keyed ``(shard, slot, generation)``; a slot the ring has
  evicted since the sample ignores the stale verdict, the same posture
  as the actors' param-version regression guard.
- **Backpressure becomes ring eviction**: shards never shed — a full ring
  FIFO-overwrites its oldest (re-collectable) sequences, so actor acks
  are always ``OK`` and a stalled learner never sheds or reaps a healthy
  fleet (the ``stall_sampler`` chaos drill pins this).

**Deployment shape**: the shards run as in-learner handlers behind
``--replay-shards N`` today, but every sample/write-back crosses the REAL
``SAMPLE_REQ``/``BATCH``/``PRIO`` frame codecs (``fleet/wire.py``
``pack_sample_req``/``pack_shard_batch``/``pack_prio_update``, on the
fleet's negotiated lane) through an in-process loopback — the byte
accounting is the honest cross-process cost, and moving a shard out of
the learner process is a listening socket away, not a format change
(docs/REPLAY.md "Topology").  The headline this buys: only SAMPLED
sequences cross the sampling boundary into training
(``bytes_per_trained_seq`` — ``bench.py fleet_sampler``).

``--replay-shards 1 --actors 0`` routes the untouched phase-locked loop
(nothing to shard without a fleet) and is pinned bit-identical to
``Trainer.run`` through the CLI — ``scripts/lib_gate.sh sampler_gate``
refuses to bless ``--replay-shards N`` evidence without that anchor plus
the sampling-equivalence test.

**Composes with ``--learner-dp`` since ISSUE 11** (docs/TOPOLOGY.md):
with a ``DPLearnerTrainer``, the pulled ``[K, B]`` batch is placed
through ``Trainer._put_staged(..., axis=1)`` so each dp slice receives
its ``B/D`` rows at device_put time — the compiled K-update scan runs
dp-sharded with no central reshard hop, and the learn program's outputs
stay pinned to the replicated layout (stable donated avals).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from r2d2dpg_tpu.fleet import transport, wire
from r2d2dpg_tpu.fleet.ingest import (
    FleetConfig,
    IngestServer,
    prune_fleet_counters,
    save_fleet_counters,
    snapshot_params,
)
from r2d2dpg_tpu.obs import flight_event, get_registry
from r2d2dpg_tpu.obs import trace as obs_trace
from r2d2dpg_tpu.obs.device import avals_of, flops_of, get_device_monitor
from r2d2dpg_tpu.obs.quality import (
    PROVENANCE_ABSENT,
    get_quality_plane,
    policy_lags,
    quality_stats_columns,
    replay_ages,
)
from r2d2dpg_tpu.ops import anneal_beta, importance_weights
from r2d2dpg_tpu.replay.arena import SequenceBatch, StagedSequences
from r2d2dpg_tpu.replay.sharded import (
    ReplayShard,
    actor_code,
    combine_probs,
    shard_quotas,
)
from r2d2dpg_tpu.training.pipeline import merge_state, split_state
from r2d2dpg_tpu.training.trainer import Trainer, TrainerState


def _resp_provenance(resp: Dict[str, Any]) -> tuple:
    """(behavior, collect, actors) of one BATCH response, sentinel-filled
    when the frame carried no provenance (old shard procs) — the quality
    folds disarm on the sentinel instead of refusing the batch."""
    n = int(np.shape(resp["slots"])[0])

    def get(k: str) -> np.ndarray:
        v = resp.get(k)
        if v is None:
            return np.full((n,), PROVENANCE_ABSENT, np.int64)
        return np.asarray(v, np.int64)

    return (get("behavior"), get("collect"), get("actors"))


def shard_for_actor(actor_id: Any, num_shards: int) -> int:
    """Consistent actor→shard routing, assigned at HELLO.

    A pure function of the actor id (not the connection), so a
    supervised restart or an in-process reconnect lands the SAME actor
    back on the SAME shard — its slice of replay keeps one feed across
    incarnations, and every process (ingest, tests, a future cross-host
    spawner) computes the route identically with no coordination.
    Integer ids (the supervisor's 0..N-1) route round-robin by modulo —
    perfect balance at fleet sizes where a generic hash would collide —
    and any other id falls back to a crc32 consistent hash."""
    s = str(actor_id)
    if s.lstrip("-").isdigit():
        return int(s) % max(num_shards, 1)
    return zlib.crc32(s.encode()) % max(num_shards, 1)


class ShardSet:
    """N replay shards + routing + the fleet-side accounting bank.

    Owned by the sampler learner, written by the ingest handler threads
    (``add`` routes each actor's SEQS batch into its shard under that
    shard's lock).  Episode/step accounting deltas ride the same bank the
    central path uses for shed stats: the experience goes to a shard, the
    ACCOUNTING goes to the learner (popped once per train phase), so the
    fleet-wide sums stay monotone whatever the sampler is doing."""

    def __init__(
        self,
        num_shards: int,
        shard_capacity: int,
        *,
        alpha: float = 0.6,
        prioritized: bool = True,
    ):
        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        # Eviction visibility (ISSUE 12 satellite): ring FIFO-overwrites
        # replaced shedding in PR 10 but previously left no trace — the
        # labeled counter (bumped under the shard's own add lock via
        # evict_cb) plus the sampler stats' ``evictions`` column make
        # silent experience recycling a first-class signal.
        evict = get_registry().counter(
            "r2d2dpg_replay_shard_evictions_total",
            "filled replay-shard slots FIFO-overwritten by the ring "
            "(re-collectable experience recycled before it was sampled)",
            labelnames=("shard",),
        )
        # Quality plane (ISSUE 18): evicted-before-ever-sampled churn per
        # shard — reported from inside the shard's add lock, where the
        # verdict is exact.
        qplane = get_quality_plane()
        self.shards = [
            ReplayShard(
                shard_capacity,
                alpha=alpha,
                prioritized=prioritized,
                shard_id=i,
                evict_cb=evict.labels(shard=str(i)).inc,
                evict_unsampled_cb=(
                    lambda evicted, unsampled, _i=i: qplane.note_evictions(
                        _i, evicted, unsampled
                    )
                ),
            )
            for i in range(num_shards)
        ]
        self._stats_lock = threading.Lock()
        self._stats = {
            "env_steps_delta": 0.0, "ep_return_sum": 0.0, "ep_count": 0.0,
        }
        # Per-shard gauges (ISSUE 10 obs satellite): the shards are
        # host-side, so the values are lock-guarded floats — set_fn
        # closures evaluated at scrape/log time, NO device fetch rides
        # anywhere (cheaper than the central arena's gauges, which need
        # the log cadence's batched device_get).
        reg = get_registry()
        psum = reg.gauge(
            "r2d2dpg_replay_shard_priority_sum",
            "raw priority sum of one replay shard (the quota weight is "
            "sum p^alpha — ReplayShard.scaled_sum)",
            labelnames=("shard",),
        )
        occ = reg.gauge(
            "r2d2dpg_replay_shard_occupancy",
            "filled slots of one replay shard",
            labelnames=("shard",),
        )
        for i, s in enumerate(self.shards):
            psum.labels(shard=str(i)).set_fn(s.priority_sum)
            occ.labels(shard=str(i)).set_fn(s.occupancy)

    @property
    def num_shards(self) -> int:
        return len(self.shards)

    def route(self, actor_id: Any) -> int:
        return shard_for_actor(actor_id, len(self.shards))

    def add(self, shard_id: int, msg: Dict[str, Any]) -> int:
        """One SEQS message into its shard (handler-thread side): the
        staged sequences enter the ring (None priorities resolve to the
        shard's max — the central "max" entry semantics), the accounting
        deltas enter the bank.  Never sheds: a full ring FIFO-evicts."""
        staged: StagedSequences = msg["staged"]
        # msg["actor_id"] is the HELLO-authenticated identity — the
        # ingest handler overwrites any payload-carried claim before the
        # message reaches this fold (the PR 6 TELEM posture), so the
        # slot's actor code can never be spoofed from a SEQS body.
        actor = msg.get("actor_id")
        n = self.shards[shard_id].add(
            staged.seq,
            staged.priorities,
            behavior=staged.behavior_version,
            collect=staged.collect_id,
            actor=None if actor is None else actor_code(actor),
        )
        self.bank_stats(msg)
        return n

    def bank_stats(self, msg: Dict[str, Any]) -> None:
        """Bank one message's accounting deltas (the K_STATS control
        frame's landing spot on the split-plane wire, ISSUE 17 — same
        bank ``add`` feeds on the forwarded path)."""
        with self._stats_lock:
            for k in self._stats:
                self._stats[k] += float(msg.get(k, 0.0))

    def pop_stats(self) -> Dict[str, float]:
        with self._stats_lock:
            out = dict(self._stats)
            for k in self._stats:
                self._stats[k] = 0.0
        return out

    def occupancy_total(self) -> int:
        return sum(s.occupancy() for s in self.shards)

    def scaled_sums(self) -> np.ndarray:
        return np.asarray([s.scaled_sum() for s in self.shards], np.float64)

    def evictions_total(self) -> int:
        return sum(s.evictions_total for s in self.shards)


class _PrefetchPull:
    """One background pull (``--shard-prefetch 1``): phase ``p+1``'s
    two-level draw/encode/transit overlaps phase ``p``'s compiled learn
    step, the way the pipelined executor overlaps collect.  Exactly one
    pull is ever in flight (kicked only after the previous completed),
    so the learner's np_rng stays a sequentially-consumed stream — same
    draws as the unprefetched schedule.  Daemon thread: a pull stuck on
    a dead tier must never pin process exit."""

    def __init__(self, fn: Callable[[], Any]):
        self._result: Any = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(fn,), name="sampler-prefetch", daemon=True
        )
        self._thread.start()

    def _run(self, fn) -> None:
        try:
            self._result = fn()
        except BaseException as e:  # noqa: BLE001 - re-raised at result()
            self._error = e
        finally:
            self._done.set()

    def result(self) -> Any:
        self._done.wait()
        if self._error is not None:
            raise self._error
        return self._result


class SamplerLearner:
    """The learner side of in-network sampling (``--replay-shards N``).

    Mirrors ``FleetLearner``'s lifecycle (start/run/close, counters,
    checkpoint sidecar, param publication, chaos ``phase_fn`` hook) but
    replaces the drain loop with a PULL loop: no staging queue, no device
    arena on the hot path — each train phase assembles ``K`` batches of
    ``batch_size`` from the shards through the SAMPLE_REQ/BATCH loopback
    codecs and runs one compiled K-update program on them, then writes
    TD priorities back through PRIO frames.

    The learner free-runs at its own pace (the Ape-X relation): phases
    are not arrival-paced, so the data-to-update ratio floats with the
    collection/consumption balance — a *different, equally valid*
    trajectory class than the phase-locked schedule, like the fleet
    itself (docs/REPLAY.md "Pacing").
    """

    def __init__(
        self,
        trainer: Trainer,
        config: FleetConfig,
        *,
        num_shards: int,
        total_capacity: Optional[int] = None,
        shard_set=None,
    ):
        if trainer.axis is not None:
            raise ValueError(
                "SamplerLearner needs a host-visible learn boundary; "
                "shard_map trainers fuse whole phases — use the base "
                "Trainer"
            )
        if config.num_actors < 1:
            raise ValueError(
                "SamplerLearner requires num_actors >= 1 (replay shards "
                "are fed by actor SEQS traffic)"
            )
        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        if config.drain_coalesce != 1:
            raise ValueError(
                "--drain-coalesce shapes the central drain the sampler "
                "path replaces; it does not compose with --replay-shards"
            )
        # The shards own the REPLAY capacity; train.py shrinks the
        # trainer's (unused) device arena in sampler mode and passes the
        # experiment's real capacity here instead.
        cap = (
            int(total_capacity)
            if total_capacity is not None
            else trainer.config.capacity
        )
        if cap % num_shards:
            raise ValueError(
                f"replay capacity {cap} not divisible by {num_shards} "
                f"shards (each shard owns an equal slice)"
            )
        config.wire.validate()
        self.trainer = trainer
        self.config = config
        self.num_shards = num_shards
        # Where replay LIVES is deployment, not semantics (ISSUE 12): the
        # default is the in-learner loopback ShardSet (PR 10's path,
        # pinned bit-identical through the CLI); a ``shard_set`` — the
        # standalone tier's RemoteShardSet (fleet/shard.py, behind
        # train.py --shard-procs N) — swaps every shard interaction onto
        # real sockets while this class's lifecycle stays identical.
        self._remote = shard_set is not None
        if self._remote:
            if shard_set.num_shards != num_shards:
                raise ValueError(
                    f"shard_set has {shard_set.num_shards} shards, "
                    f"expected {num_shards}"
                )
            self.shards = shard_set
        else:
            self.shards = ShardSet(
                num_shards,
                cap // num_shards,
                alpha=trainer.config.priority_alpha,
                prioritized=trainer.config.prioritized,
            )
        # Direct data plane (ISSUE 17): with a standalone tier, the
        # ingest acks advertise each actor's shard assignment + address
        # so actors ship SEQS straight to their shard; in-learner shards
        # have no dialable address — the fn stays None and actors keep
        # forwarding (the documented fallback).
        assignment_fn = None
        if config.shard_direct and self._remote:
            assignment_fn = self.shards.assignment_for
        # Sampling-boundary concurrency (ISSUE 17): N pullers over M
        # shards, one in-flight SAMPLE_REQ per live shard per quota
        # round.  0 = auto (min(shards, 8)); 1 = the serial control leg.
        if config.shard_pullers < 0:
            raise ValueError("shard_pullers must be >= 0")
        self._pullers = (
            int(config.shard_pullers)
            if config.shard_pullers > 0
            else min(num_shards, 8)
        )
        # The ingest server routes SEQS straight into the shards; its
        # staging queue exists only structurally (nothing ever enqueues,
        # so nothing can shed — ring eviction is the backpressure).
        self.queue: "queue.Queue" = queue.Queue(maxsize=config.queue_depth)
        self.server = IngestServer(
            self.queue,
            address=config.address,
            shed_after_s=config.shed_after_s,
            startup_shed_grace_s=config.startup_shed_grace_s,
            max_frame_bytes=config.max_frame_bytes,
            wire_config=config.wire,
            read_deadline_s=config.heartbeat_s,
            warmup_deadline_s=config.warmup_deadline_s,
            auth_token=config.auth_token,
            shards=self.shards,
            expected_actors=config.num_actors,
            shard_assignment_fn=assignment_fn,
        )
        # Loopback frame codecs, one packer/unpacker pair per direction
        # (the sampler loop is the only caller — single-threaded).  The
        # negotiated fleet lane applies, so the counted bytes are exactly
        # what a cross-process shard would put on a real socket; on the
        # default f32/none lane the roundtrip is bit-exact.
        self._req_packer = wire.TreePacker(
            config.wire, max_frame_bytes=config.max_frame_bytes
        )
        self._req_unpacker = wire.TreeUnpacker(
            max_frame_bytes=config.max_frame_bytes
        )
        self._batch_packer = wire.TreePacker(
            config.wire, max_frame_bytes=config.max_frame_bytes
        )
        self._batch_unpacker = wire.TreeUnpacker(
            max_frame_bytes=config.max_frame_bytes
        )
        # dp-mesh composition (ISSUE 11, docs/TOPOLOGY.md): a
        # DPLearnerTrainer replicates train and shards the pulled batch
        # over dp via _put_staged(axis=1) below.  Pinning the outputs to
        # the replicated layout keeps the donated chain's avals stable
        # (the FleetLearner drain's out_shardings discipline); None for
        # single-device trainers.
        self._replicated = getattr(trainer, "_replicated", None)
        learn_kwargs: Dict[str, Any] = {"donate_argnums": (0,)}
        if self._replicated is not None:
            learn_kwargs["out_shardings"] = (
                self._replicated, self._replicated, self._replicated
            )
        self._learn_prog = jax.jit(self._learn_impl, **learn_kwargs)
        self._req_id = 0
        self._phase_stall_s = 0.0  # per-pull dead-tier wait side channel
        self.sample_bytes_total = 0  # SAMPLE_REQ + BATCH + PRIO, with headers
        self.trained_seqs_total = 0
        # Quality-fold context (ISSUE 18): (published param version,
        # drained phases) as of the last run-loop iteration — the pull
        # fold reads it to turn provenance into lag/age without touching
        # the device (beta is reconstructed from the phase clock, K
        # updates per phase, exactly the annealed schedule).
        self._quality_ctx = (0, 0)
        reg = get_registry()
        # Two DISTINCT waits, two histograms: the one-off cold-start /
        # resume absorb (expected to take tens of seconds — compile +
        # actor spawn) and mid-run pull stalls (a live-but-empty or dead
        # shard tier).  Folding the absorb into the wait histogram made
        # its p99 equal the absorb duration for the whole run, so the
        # /health learner_starving rule read every sampler run as
        # permanently starving off its single cold-start sample.
        self.sampler_wait = reg.histogram(
            "r2d2dpg_sampler_wait_seconds",
            "seconds the pull loop stalled waiting for a live non-empty "
            "shard, one sample PER PHASE (zeros included, so a past "
            "outage decays out of the p99 — the /health learner_starving "
            "input; cold-start absorb is r2d2dpg_sampler_absorb_seconds)",
        )
        self.sampler_absorb = reg.histogram(
            "r2d2dpg_sampler_absorb_seconds",
            "absorb-to-min_replay wait, one sample per incarnation "
            "(cold start and --resume re-entry)",
        )
        self.sample_assemble = reg.histogram(
            "r2d2dpg_sampler_sample_seconds",
            "one phase's SAMPLE_REQ -> stacked-batch assembly (pack, "
            "shard draws, decode, stack)",
        )
        self.puller_wait = reg.histogram(
            "r2d2dpg_sampler_puller_wait_seconds",
            "one puller's SAMPLE_REQ -> BATCH exchange wall time, one "
            "sample per per-shard draw (N concurrent pullers overlap "
            "these; the serial control leg sums them)",
        )
        self._obs_trained = reg.counter(
            "r2d2dpg_sampler_trained_seqs_total",
            "sequences pulled across the sampling boundary into training",
        )
        self._obs_bytes = reg.counter(
            "r2d2dpg_sampler_bytes_total",
            "bytes crossing the sampling boundary (SAMPLE_REQ + BATCH + "
            "PRIO frames, headers included)",
        )
        if self._remote:
            # The honest sampling-boundary byte count now includes real
            # socket traffic (REQ/BATCH/PRIO + their acks + HELLOs).
            self.shards.bind_sample_bytes(self._obs_bytes.inc)
        self._stats: Dict[str, float] = {}
        self._counters: Dict[str, float] = {}

    # ------------------------------------------------------------- lifecycle
    def start(self) -> str:
        self.server.start()
        return self.server.connect_address

    def close(self) -> None:
        self.server.stop()

    def stats(self) -> Dict[str, float]:
        return dict(self._stats)

    def counters(self) -> Dict[str, float]:
        return dict(self._counters)

    # ------------------------------------------------------- device program
    def _learn_impl(self, train, seqs: SequenceBatch, probs, size, key):
        """K importance-weighted updates on pulled batches.

        ``seqs`` leaves are ``[K, B, ...]``, ``probs`` the COMBINED
        two-level probabilities ``[K, B]``, ``size`` the fleet-wide
        occupancy (the N of the IS correction).  Same anneal / weight /
        smoothing-key semantics as ``Trainer._update_step`` — only the
        sample source moved; there is no arena scatter because priorities
        ride back to the shards host-side."""
        t = self.trainer
        cfg = t.config
        keys = jax.random.split(key, cfg.learner_steps)

        def one(train, inp):
            batch, p, k = inp
            kl = jax.random.fold_in(k, 1)
            if cfg.prioritized:
                beta = anneal_beta(
                    train.step, beta0=cfg.beta0, steps=cfg.beta_steps
                )
                w = importance_weights(p, size, beta=beta)
            else:
                w = jnp.ones((cfg.batch_size,))
            train, prios, metrics = t.agent.learner_step(
                train, t._reshard_batch(batch), w, key=kl
            )
            return train, (prios, metrics)

        train, (prios, metrics) = lax.scan(one, train, (seqs, probs, keys))
        metrics = jax.tree_util.tree_map(lambda m: m.mean(), metrics)
        return train, prios, metrics

    # ------------------------------------------------------- sample assembly
    def _roundtrip(self, unpacker, parts) -> Any:
        """One loopback frame (already packed ``parts``): count its
        honest wire bytes (header included), decode through the real
        unpacker.  This IS the cross-process hot path minus the
        socket."""
        payload = b"".join(bytes(p) for p in parts)
        n = transport.HEADER_BYTES + len(payload)
        self.sample_bytes_total += n
        self._obs_bytes.inc(n)
        return unpacker.unpack(payload)

    def _fold_quality(
        self, behavior, collect, actors, probs, occupancy
    ) -> None:
        """Quality-plane fold at the batch-assembly site (ISSUE 18).

        Everything here is host numpy the pull already holds — zero new
        device fetches.  Lag/age disarm on absent provenance (the -1
        sentinel masks out inside ``policy_lags``/``replay_ages``); beta
        is reconstructed from the phase clock (exactly K updates per
        drained phase, so ``step = phase * K`` matches the in-graph
        anneal bit-for-bit as a float schedule)."""
        plane = get_quality_plane()
        version, phase = self._quality_ctx
        if behavior is not None:
            plane.observe_lags(policy_lags(version, behavior))
        if collect is not None:
            plane.observe_ages(replay_ages(phase, collect))
        cfg = self.trainer.config
        if cfg.prioritized:
            step = phase * cfg.learner_steps
            frac = min(step / max(cfg.beta_steps, 1), 1.0)
            beta = cfg.beta0 + (1.0 - cfg.beta0) * frac
        else:
            beta = 0.0
        plane.observe_probs(probs, occupancy, beta)
        if actors is not None:
            a = np.asarray(actors, np.int64).ravel()
            a = a[a != PROVENANCE_ABSENT]
            if a.size:
                codes, counts = np.unique(a, return_counts=True)
                for c, n in zip(codes, counts):
                    plane.note_trained(str(int(c)), int(n))

    def _pull_phase_batches(
        self, n_draws: int, rng: np.random.Generator, tr=None
    ):
        """One phase's two-level pull: quotas ∝ advertised Σp^α, one
        SAMPLE_REQ/BATCH exchange per non-empty shard, PRIO handles and
        combined probabilities assembled for the learn program.

        Returns ``(seq [n,...], probs [n], handles, occupancy_total)``
        with the concatenated draws PERMUTED (seeded) before the caller
        reshapes to ``[K, B]`` — quota counts are per shard, and without
        the shuffle update k would correlate with shard identity.

        ``tr`` is the phase's sampled trace (ISSUE 13): on the remote
        path its id rides each SAMPLE_REQ's 32B sidecar so the shard
        procs stamp their own hops into the same trace; the loopback has
        no process boundary to trace (the sampler chain covers it).

        Side channel: ``self._phase_stall_s`` accumulates any dead-tier
        wait this pull spent (remote path only; the loopback cannot
        stall).  The caller observes it into ``sampler_wait`` ONCE PER
        PHASE, zeros included — a rare 30s outage sample would otherwise
        sit at the window's p99 indefinitely and keep /health reading a
        long-recovered incident as starving-now."""
        self._phase_stall_s = 0.0
        if self._remote:
            return self._pull_phase_batches_remote(n_draws, rng, tr)
        sums = self.shards.scaled_sums()
        quotas = shard_quotas(sums, n_draws, rng)
        total = float(sums.sum())
        seqs: List[SequenceBatch] = []
        probs: List[np.ndarray] = []
        handles: List[tuple] = []  # (shard, slots, gens) per response
        prov: List[tuple] = []  # (behavior, collect, actors) per response
        for shard_id, quota in enumerate(quotas):
            if quota == 0:
                continue
            self._req_id += 1
            req = wire.unpack_sample_req(
                self._roundtrip(
                    self._req_unpacker,
                    wire.pack_sample_req(
                        self._req_packer,
                        req_id=self._req_id,
                        shard=shard_id,
                        quota=int(quota),
                    ),
                )
            )
            shard = self.shards.shards[req["shard"]]
            s = shard.sample(req["quota"], rng)
            resp = wire.unpack_shard_batch(
                self._roundtrip(
                    self._batch_unpacker,
                    wire.pack_shard_batch(
                        self._batch_packer,
                        req_id=req["req_id"],
                        shard=req["shard"],
                        staged=StagedSequences(seq=s.seq, priorities=None),
                        slots=s.slots,
                        gens=s.gens,
                        probs=s.probs,
                        priority_sum=shard.scaled_sum(),
                        occupancy=shard.occupancy(),
                        behavior=s.behavior,
                        collect=s.collect,
                        actors=s.actors,
                    ),
                )
            )
            seqs.append(resp["staged"].seq)
            probs.append(
                combine_probs(resp["probs"], float(sums[shard_id]), total)
            )
            handles.append((req["shard"], resp["slots"], resp["gens"]))
            prov.append(_resp_provenance(resp))
        seq = jax.tree_util.tree_map(
            lambda *xs: np.concatenate([np.asarray(x) for x in xs], axis=0),
            *seqs,
        )
        prob = np.concatenate(probs)
        shard_of = np.concatenate(
            [np.full(len(h[1]), h[0], np.int64) for h in handles]
        )
        slots = np.concatenate([h[1] for h in handles])
        gens = np.concatenate([h[2] for h in handles])
        perm = rng.permutation(n_draws)
        seq = jax.tree_util.tree_map(lambda x: x[perm], seq)
        occ_total = self.shards.occupancy_total()
        # Quality fold AT the assembly site (permutation-invariant): the
        # combined probs + provenance arrays are already on the host.
        self._fold_quality(
            np.concatenate([p[0] for p in prov]),
            np.concatenate([p[1] for p in prov]),
            np.concatenate([p[2] for p in prov]),
            prob,
            occ_total,
        )
        return (
            seq,
            prob[perm],
            (shard_of[perm], slots[perm], gens[perm]),
            occ_total,
        )

    def _pull_phase_batches_remote(
        self, n_draws: int, rng: np.random.Generator, tr=None
    ):
        """The ``--shard-procs`` pull: same two-level math, real sockets,
        plus the graceful-degradation contract — a shard whose exchange
        fails mid-phase is marked dead, its quota redistributed over the
        SURVIVORS' advertised Σp^α within this very phase (the
        renormalization acceptance), and a fully-dead tier is waited out
        (bounded by ``idle_timeout_s``) while the supervisor restarts it.
        Handles carry each batch's shard EPOCH so the write-back can
        fence a restart that happens between sample and verdict."""
        from r2d2dpg_tpu.fleet.shard import ShardUnavailableError

        shards = self.shards
        shards.maybe_rejoin()
        seqs: List[SequenceBatch] = []
        probs: List[np.ndarray] = []
        shard_of: List[np.ndarray] = []
        slots: List[np.ndarray] = []
        gens: List[np.ndarray] = []
        epochs: List[np.ndarray] = []
        prov: List[tuple] = []  # (behavior, collect, actors) per response
        remaining = int(n_draws)
        deadline = time.monotonic() + self.config.idle_timeout_s
        stall_t0: Optional[float] = None
        while remaining > 0:
            sums = shards.scaled_sums()
            total = float(sums.sum())
            if total <= 0.0:
                # Every shard dead or freshly-rejoined-empty: degrade by
                # WAITING (sampling stalls, training pauses, actors keep
                # streaming into re-routed/absorbing shards) — never by
                # fabricating draws.
                if stall_t0 is None:
                    stall_t0 = time.monotonic()
                if time.monotonic() >= deadline:
                    raise RuntimeError(
                        "sampler starved: no live non-empty replay shard "
                        "to draw from (shard tier down past "
                        f"{self.config.idle_timeout_s:.0f}s — check "
                        "flight.jsonl for shard_dead/shard_crash events)"
                    )
                shards.maybe_rejoin()
                time.sleep(0.1)
                continue
            if stall_t0 is not None:
                # Banked into this PHASE's wait sample (see the caller):
                # the mid-run learner-starving signal /health judges.
                self._phase_stall_s += time.monotonic() - stall_t0
                stall_t0 = None
            quotas = shard_quotas(sums, remaining, rng)
            remaining = 0
            # Concurrent pullers (ISSUE 17): one quota round = one job
            # per non-empty shard, req_ids assigned in SHARD-ID ORDER
            # BEFORE any exchange dispatches and results processed in
            # shard-id order after the join — the learner rng is consumed
            # only by shard_quotas above and the final permutation, so
            # arrival order cannot reach any seeded draw (the puller
            # determinism pin, tests/test_shard_direct.py).
            jobs: List[tuple] = []  # (shard_id, quota, req_id, req_tr)
            for shard_id, quota in enumerate(quotas):
                if quota == 0:
                    continue
                self._req_id += 1
                req_tr = None
                if tr is not None:
                    # A fresh stamp per REQ, sharing the phase's trace id:
                    # the sidecar's collect-start slot carries the REQ's
                    # birth time, and the packer stamps encode-end — the
                    # shard's req_receive hop starts where that stamp
                    # ends (obs/trace.py SHARD_HOPS).
                    req_tr = obs_trace.TraceStamp(
                        trace_id=tr.trace_id, t_collect_start=time.time()
                    )
                jobs.append((shard_id, int(quota), self._req_id, req_tr))
            for (shard_id, quota, _, _), outcome in zip(
                jobs, self._exchange_jobs(shards, jobs)
            ):
                if isinstance(outcome, ShardUnavailableError):
                    # The mid-phase degradation moment: the dead shard's
                    # draws go back into the pool; the NEXT loop
                    # iteration's quota draw sees its weight zeroed
                    # (``_mark_dead`` records the renormalization) — the
                    # phase still delivers its full n_draws, from the
                    # survivors.
                    shards._mark_dead(shard_id, str(outcome))
                    flight_event(
                        "shard_draws_redistributed",
                        shard=shard_id,
                        redistributed_draws=int(quota),
                    )
                    remaining += int(quota)
                    continue
                resp = outcome
                if resp is None:
                    # LIVE but empty (a stale quota weight met a freshly
                    # restarted ring): not a death — the ack's advert
                    # zeroed its weight, so the re-draw below lands on
                    # shards that actually hold data.
                    remaining += int(quota)
                    continue
                seqs.append(resp["staged"].seq)
                probs.append(
                    combine_probs(resp["probs"], float(sums[shard_id]), total)
                )
                n_got = int(resp["slots"].shape[0])
                shard_of.append(np.full(n_got, shard_id, np.int64))
                slots.append(np.asarray(resp["slots"], np.int64))
                gens.append(np.asarray(resp["gens"], np.int64))
                epochs.append(np.full(n_got, int(resp["epoch"]), np.int64))
                prov.append(_resp_provenance(resp))
        seq = jax.tree_util.tree_map(
            lambda *xs: np.concatenate([np.asarray(x) for x in xs], axis=0),
            *seqs,
        )
        perm = rng.permutation(n_draws)
        seq = jax.tree_util.tree_map(lambda x: x[perm], seq)
        prob = np.concatenate(probs)
        occ_total = self.shards.occupancy_total()
        self._fold_quality(
            np.concatenate([p[0] for p in prov]),
            np.concatenate([p[1] for p in prov]),
            np.concatenate([p[2] for p in prov]),
            prob,
            occ_total,
        )
        return (
            seq,
            prob[perm],
            (
                np.concatenate(shard_of)[perm],
                np.concatenate(slots)[perm],
                np.concatenate(gens)[perm],
                np.concatenate(epochs)[perm],
            ),
            occ_total,
        )

    def _exchange_jobs(self, shards, jobs: List[tuple]) -> List[Any]:
        """Run one quota round's SAMPLE_REQ/BATCH exchanges — results in
        JOB ORDER regardless of arrival order.

        ``--shard-pullers 1`` (the serial control leg) runs them inline,
        exactly the pre-ISSUE-17 loop; otherwise up to ``self._pullers``
        exchanges are in flight at once, one per shard (each RemoteShard
        owns its own socket + leg lock, so per-shard exchanges never
        contend).  A dead shard's ``ShardUnavailableError`` is an OUTCOME
        (the caller redistributes its quota); anything else re-raises on
        the caller's thread.  Every exchange lands one sample in the
        puller-wait histogram — the overlap this buys is the gap between
        its sum and the phase's assemble time."""
        from r2d2dpg_tpu.fleet.shard import ShardUnavailableError

        def one(shard_id: int, quota: int, req_id: int, req_tr) -> Any:
            t0 = time.monotonic()
            try:
                return shards.shards[shard_id].sample(
                    quota, req_id, trace=req_tr
                )
            except ShardUnavailableError as e:
                return e
            finally:
                self.puller_wait.add(time.monotonic() - t0)

        if self._pullers <= 1 or len(jobs) <= 1:
            return [one(*job) for job in jobs]
        results: List[Any] = [None] * len(jobs)
        errors: List[BaseException] = []
        sem = threading.BoundedSemaphore(self._pullers)

        def work(i: int, job: tuple) -> None:
            with sem:
                try:
                    results[i] = one(*job)
                except BaseException as e:  # noqa: BLE001 - re-raised below
                    errors.append(e)

        threads = [
            threading.Thread(
                target=work,
                args=(i, job),
                name=f"sampler-puller-{job[0]}",
                daemon=True,
            )
            for i, job in enumerate(jobs)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return results

    def _write_back_remote(self, handles, prios: np.ndarray) -> None:
        """TD write-back to standalone shards, grouped per (shard, epoch):
        a shard that died since the sample drops its verdict loudly
        (re-collectable, like the experience itself), and handles whose
        epoch no longer matches the shard's live incarnation are fenced
        LEARNER-side before a byte crosses — the shard's own epoch check
        (``ShardServer``) remains the authoritative backstop."""
        from r2d2dpg_tpu.fleet.shard import ShardUnavailableError

        shard_of, slots, gens, epochs = handles
        prios = np.asarray(prios, np.float32).reshape(-1)
        for shard_id in np.unique(shard_of):
            sh = self.shards.shards[int(shard_id)]
            m_shard = shard_of == shard_id
            for ep in np.unique(epochs[m_shard]):
                m = m_shard & (epochs == ep)
                if not sh.alive:
                    flight_event(
                        "prio_dropped_shard_dead",
                        shard=int(shard_id),
                        entries=int(m.sum()),
                    )
                    continue
                if sh.epoch != int(ep):
                    flight_event(
                        "stale_epoch_prio_dropped",
                        shard=int(shard_id),
                        got_epoch=int(ep),
                        epoch=sh.epoch,
                        entries=int(m.sum()),
                    )
                    continue
                # Coalesced write-back (ISSUE 17): with-replacement draws
                # repeat (slot, gen) keys within a phase — dedupe to the
                # LAST write (sequential application is last-write-wins)
                # so one (shard, epoch) PRIO frame carries each key once.
                c_slots, c_gens, c_prios = wire.coalesce_prio_update(
                    slots[m], gens[m], prios[m]
                )
                try:
                    sh.write_back(
                        c_slots, c_gens, c_prios, epoch=int(ep)
                    )
                except ShardUnavailableError as e:
                    self.shards._mark_dead(int(shard_id), str(e))
                    flight_event(
                        "prio_dropped_shard_dead",
                        shard=int(shard_id),
                        entries=int(m.sum()),
                    )

    def _write_back(self, handles, prios: np.ndarray) -> None:
        """TD write-back through PRIO frames, grouped per shard; stale
        generations (ring-evicted slots) are ignored shard-side."""
        if self._remote:
            return self._write_back_remote(handles, prios)
        shard_of, slots, gens = handles
        prios = np.asarray(prios, np.float32).reshape(-1)
        for shard_id in np.unique(shard_of):
            m = shard_of == shard_id
            # Same coalesce as the remote path: one PRIO frame per shard
            # per phase, each (slot, gen) key once (last write wins).
            c_slots, c_gens, c_prios = wire.coalesce_prio_update(
                slots[m], gens[m], prios[m]
            )
            upd = wire.unpack_prio_update(
                self._roundtrip(
                    self._req_unpacker,
                    wire.pack_prio_update(
                        self._req_packer,
                        shard=int(shard_id),
                        slots=c_slots,
                        gens=c_gens,
                        priorities=c_prios,
                    ),
                )
            )
            if upd["shard"] >= self.num_shards:
                # The codec checks >= 0; the upper bound is deployment
                # state only this side knows.  Unreachable via the
                # loopback (we packed it), load-bearing the day a remote
                # shard speaks these frames.
                raise wire.WireFormatError(
                    f"PRIO shard {upd['shard']} outside fleet of "
                    f"{self.num_shards}"
                )
            self.shards.shards[upd["shard"]].update_priorities(
                upd["slots"], upd["gens"], upd["priorities"]
            )

    # ------------------------------------------------------------------- run
    def run(
        self,
        num_train_phases: int,
        state: Optional[TrainerState] = None,
        log_every: int = 50,
        log_fn=print,
        metrics_fn: Optional[Callable[[int, Dict[str, float]], None]] = None,
        minutes: Optional[float] = None,
        ckpt=None,
        checkpoint_every: int = 0,
        resume_from: Optional[Dict[str, float]] = None,
        phase_fn: Optional[Callable[[int], None]] = None,
        trace_sample: float = 0.0,
    ) -> TrainerState:
        """Wait for ``min_replay`` resident sequences across the shards,
        then run ``num_train_phases`` pull-learn phases (K·B two-level
        draws + K compiled updates + PRIO write-back each).  Same
        checkpoint/resume/counter contract as ``FleetLearner.run`` (the
        shards, like the central arena, are never checkpointed: a
        resumed learner re-fills them from live actors)."""
        if self.server.address is None:
            raise RuntimeError("call start() before run()")
        t = self.trainer
        cfg = t.config
        # Device plane (ISSUE 14): the pull loop owns the run window.
        mon = get_device_monitor().install()
        mon.begin_run()
        state = t.init() if state is None else state
        cstate, lstate = split_state(state)
        train = lstate.train
        rng = lstate.rng
        np_rng = np.random.default_rng(cfg.seed)
        deadline = (
            time.monotonic() + minutes * 60 if minutes is not None else None
        )
        self.sampler_wait.reset()
        self.sampler_absorb.reset()
        self.sample_assemble.reset()
        self.puller_wait.reset()
        resume_from = resume_from or {}
        version = int(resume_from.get("param_version", 0)) + 1
        self.server.publish_params(version, self._snapshot_params(train))

        n_draws = cfg.learner_steps * cfg.batch_size
        drained = int(resume_from.get("drained", 0))
        drained_at_start = drained
        last_metrics: Dict[str, Any] = {}
        ep_ret_sum = float(resume_from.get("ep_return_sum", 0.0))
        ep_count = float(resume_from.get("ep_count", 0.0))
        env_steps_total = float(resume_from.get("env_steps_total", 0.0))
        episodes_total = float(resume_from.get("episodes_total", 0.0))
        t0 = time.monotonic()
        train_t0: Optional[float] = None
        marked_steady = False

        def emit_log(phase: int, scalars: Dict[str, float]) -> None:
            if metrics_fn is not None:
                metrics_fn(phase, scalars)
                return
            log_fn(
                f"sampler phase {phase}/{num_train_phases} "
                + " ".join(f"{k} {v:.3g}" for k, v in scalars.items())
            )

        def fold_stats() -> None:
            nonlocal env_steps_total, ep_ret_sum, ep_count, episodes_total
            s = self.shards.pop_stats()
            env_steps_total += s["env_steps_delta"]
            ep_ret_sum += s["ep_return_sum"]
            ep_count += s["ep_count"]
            episodes_total += s["ep_count"]

        try:
            # ------------------------------------------------ absorb phase
            # The recovery contract's re-entry point too: a resumed
            # learner waits here while reconnecting actors refill shards.
            last_growth = time.monotonic()
            last_occ = -1
            t_wait = time.monotonic()
            # Direct data plane (ISSUE 17): SEQS bypass the learner, so
            # no forward ack refreshes the occupancy view — poke the
            # shards' adverts over the sampler leg or the gate would
            # starve against a tier the actors are actively filling.
            poke_adverts = (
                bool(self.config.shard_direct)
                and self._remote
                and hasattr(self.shards, "refresh_adverts")
            )
            last_poke = 0.0
            while self.shards.occupancy_total() < cfg.min_replay:
                if deadline is not None and time.monotonic() >= deadline:
                    break
                if poke_adverts and time.monotonic() - last_poke >= 0.25:
                    self.shards.refresh_adverts()
                    last_poke = time.monotonic()
                occ = self.shards.occupancy_total()
                if occ != last_occ:
                    last_occ = occ
                    last_growth = time.monotonic()
                # Cold start pays actor spawn + jax import + collect
                # compile — double the steady bound, like the drain loop.
                bound = self.config.idle_timeout_s * (2.0 if occ == 0 else 1.0)
                if time.monotonic() - last_growth > bound:
                    raise RuntimeError(
                        f"sampler starved: shard occupancy stuck at {occ} "
                        f"for {bound:.0f}s — are the actors alive? "
                        f"(check flight.jsonl)"
                    )
                time.sleep(0.05)
            self.sampler_absorb.add(time.monotonic() - t_wait)

            # Batch prefetch (ISSUE 17, --shard-prefetch 1): pull phase
            # p+1 on a background thread while phase p learns.  The
            # np_rng stays sequential (one pull in flight, ever) so the
            # DRAWS are anchor-identical; what moves by one phase is the
            # write-back visibility — phase p+1 samples against
            # priorities that do not yet reflect phase p's TD verdict
            # (stale-by-one, the documented overlap tradeoff, docs/
            # REPLAY.md "Direct data plane").  0 (default) keeps the
            # strict pull->learn->write-back interleave.
            prefetch_on = bool(self.config.shard_prefetch) and self._remote
            pending: Optional[_PrefetchPull] = None

            def pull_once() -> Dict[str, Any]:
                tr = obs_trace.maybe_start(trace_sample)
                t_req = time.time()
                t_assemble = time.monotonic()
                out = self._pull_phase_batches(n_draws, np_rng, tr)
                return {
                    "out": out,
                    "tr": tr,
                    "t_req": t_req,
                    "assemble_s": time.monotonic() - t_assemble,
                    "stall_s": self._phase_stall_s,
                }

            while drained < num_train_phases:
                if deadline is not None and time.monotonic() >= deadline:
                    break
                fold_stats()
                mon.on_phase(drained + 1)
                # The pull fold's clock view (published version + phase);
                # a prefetched pull reads the previous iteration's pair —
                # one phase of skew, same as the sample it describes.
                self._quality_ctx = (version, drained)
                if pending is not None:
                    pulled, pending = pending.result(), None
                else:
                    pulled = pull_once()
                if (
                    prefetch_on
                    and drained + 1 < num_train_phases
                    and (deadline is None or time.monotonic() < deadline)
                ):
                    pending = _PrefetchPull(pull_once)
                tr = pulled["tr"]
                t_req = pulled["t_req"]
                seq_np, probs_np, handles, occ = pulled["out"]
                t_batches = time.time()
                self.sample_assemble.add(pulled["assemble_s"])
                # One wait sample per PHASE, zeros included (see the
                # _pull_phase_batches docstring): stall-free phases
                # dilute and eventually evict a past outage's sample, so
                # the /health p99 answers "starving NOW", not "ever".
                self.sampler_wait.add(pulled["stall_s"])
                # [n] -> [K, B] for the compiled K-update scan, then
                # mesh placement through the _put_staged hook on the
                # BATCH axis (axis=1): under --learner-dp each dp slice
                # receives its B/D rows here, at device_put time, so the
                # learn program's _reshard_batch constraint is already
                # satisfied — the BATCH frames from M shards land
                # per-dp-slice with no central reshard hop (identity for
                # single-device trainers; docs/TOPOLOGY.md).
                seqs = t._put_staged(
                    jax.tree_util.tree_map(
                        lambda x: np.reshape(
                            x,
                            (cfg.learner_steps, cfg.batch_size)
                            + x.shape[1:],
                        ),
                        seq_np,
                    ),
                    axis=1,
                )
                probs = t._put_staged(
                    np.reshape(
                        probs_np.astype(np.float32),
                        (cfg.learner_steps, cfg.batch_size),
                    ),
                    axis=1,
                )
                size = np.float32(occ)
                if self._replicated is not None:
                    # Scalars replicate explicitly so every learn input
                    # shares the mesh's device set (uncommitted host
                    # scalars would otherwise default single-device).
                    size = jax.device_put(size, self._replicated)
                rng, key = jax.random.split(rng)
                if drained == drained_at_start:
                    # MFU numerator: one lazy lower() of the pull-learn
                    # program at these avals, evaluated on the log
                    # cadence — never a second backend compile.
                    learn_avals = avals_of((train, seqs, probs, size, key))
                    mon.set_learn_cost(
                        lambda: flops_of(
                            self._learn_prog.lower(*learn_avals)
                        )
                    )
                mon.note_learn()
                with mon.program("sampler_learn"):
                    train, prios_dev, last_metrics = self._learn_prog(
                        train, seqs, probs, size, key
                    )
                t_dispatch = time.time()
                # ONE host fetch per phase: the write-back priorities
                # must come back to the host-side shards (there is no
                # in-graph arena scatter on this path).  The blocking
                # fetch also makes the learn hop honest for free.
                prios = jax.device_get(prios_dev)
                t_learn_done = time.time()
                self._write_back(handles, prios)
                self.trained_seqs_total += n_draws
                self._obs_trained.inc(n_draws)
                if tr is not None:
                    # The sampler-path trace chain (obs/trace.py): the
                    # two new hops + learn, recorded together
                    # (all-or-nothing, like the 8-hop wire chain).
                    obs_trace.record_hop(
                        "sample_req", t_req, t_batches, tr.trace_id,
                        draws=n_draws,
                    )
                    obs_trace.record_hop(
                        "batch_return", t_batches, t_dispatch,
                        tr.trace_id, seqs=n_draws,
                    )
                    obs_trace.record_hop(
                        "learn", t_dispatch, t_learn_done, tr.trace_id
                    )
                drained += 1
                if train_t0 is None:
                    jax.block_until_ready(train.step)
                    train_t0 = time.monotonic()
                if not marked_steady:
                    self.server.mark_steady()
                    # The pull-learn program is warm: the compile
                    # sentinel arms (obs/device.py).
                    mon.mark_steady()
                    marked_steady = True
                if phase_fn is not None:
                    phase_fn(drained)
                if (
                    ckpt is not None
                    and checkpoint_every > 0
                    and drained % checkpoint_every == 0
                ):
                    self._save_checkpoint(
                        ckpt, drained, state, cstate, train, rng, lstate,
                        {
                            "drained": drained,
                            "env_steps_total": env_steps_total,
                            "ep_return_sum": ep_ret_sum,
                            "ep_count": ep_count,
                            "episodes_total": episodes_total,
                            "param_version": version,
                        },
                    )
                if drained % max(self.config.publish_every, 1) == 0:
                    version += 1
                    self.server.publish_params(
                        version, self._snapshot_params(train)
                    )
                    if log_every and drained % log_every == 0:
                        flight_event("param_publish", version=version)
                if log_every and drained % log_every == 0:
                    with mon.expected("log_fetch"):
                        lstep, m = jax.device_get(
                            (train.step, last_metrics)
                        )
                    scalars = {
                        "episode_return_mean": ep_ret_sum / max(ep_count, 1.0),
                        "episodes": ep_count,
                        "env_steps": env_steps_total,
                        "learner_steps": float(lstep),
                        "replay_occupancy": float(occ),
                        **{k: float(v) for k, v in m.items()},
                    }
                    ep_ret_sum = 0.0
                    ep_count = 0.0
                    t._obs_publish(scalars)
                    emit_log(drained, scalars)
        finally:
            jax.block_until_ready(train.step)
            # Sentinel disarmed + any open profiler capture closed before
            # teardown's own device work runs.
            mon.end_run()
            t_end = time.monotonic()
            fold_stats()
            wall = max(t_end - t0, 1e-9)
            _, sw_total, sw_p50, sw_p99 = self.sampler_wait.snapshot()
            _, sa_total, _, _ = self.sampler_absorb.snapshot()
            _, pw_total, _, pw_p99 = self.puller_wait.snapshot()
            srv = self.server
            drained_here = drained - drained_at_start
            trained = drained_here * n_draws
            if self._remote:
                # Real-socket accounting: the shard set counted every
                # sampler-leg byte (REQ/BATCH/PRIO + acks + HELLOs).
                self.sample_bytes_total = self.shards.sample_bytes_total
            self._counters = {
                "drained": float(drained),
                "env_steps_total": env_steps_total,
                "ep_return_sum": ep_ret_sum,
                "ep_count": ep_count,
                "episodes_total": episodes_total,
                "param_version": float(version),
            }
            self._stats = {
                "train_phases": float(drained_here),
                "train_phases_total": float(drained),
                "trained_seqs": float(trained),
                "wall_s": wall,
                "learner_steps_per_sec": (
                    drained_here * cfg.learner_steps / wall
                ),
                # The headline boundary: only SAMPLED sequences cross
                # into training (bench.py fleet_sampler compares this
                # against the central drain's bytes_per_trained_seq).
                "sample_bytes_total": float(self.sample_bytes_total),
                "bytes_per_trained_seq": (
                    self.sample_bytes_total / max(trained, 1)
                ),
                # The actor wire, for honesty: collection traffic still
                # lands on the (in-learner) shards today.
                "seqs_bytes_total": float(srv.seqs_bytes_total),
                "collected_seqs": float(srv.seqs_received_total),
                "sheds": float(srv.shed_total),  # structurally 0
                # Eviction visibility (ISSUE 12 satellite): ring FIFO
                # overwrites of filled slots — the quantity shedding
                # turned into in PR 10, now first-class in the stats row.
                "evictions": float(self.shards.evictions_total()),
                "replay_occupancy": float(self.shards.occupancy_total()),
                "sampler_wait_p50_ms": sw_p50 * 1e3,
                "sampler_wait_p99_ms": sw_p99 * 1e3,
                "sampler_wait_total_s": sw_total,
                "sampler_absorb_s": sa_total,
                # Puller concurrency (ISSUE 17): per-exchange wall times;
                # with N pullers the phase pays ~the max, the serial
                # control leg pays the sum.
                "shard_pullers": float(self._pullers if self._remote else 1),
                "puller_wait_p99_ms": pw_p99 * 1e3,
                "puller_wait_total_s": pw_total,
                # The pipelined executor's overlap instrumentation,
                # riding the composed loop (ISSUE 11): fraction of the
                # wall during which the learner had sample data available
                # (1.0 = collection fully hidden behind learning — same
                # definition as PipelineExecutor.stats / FleetLearner).
                # Absorb counts as un-overlapped wait here even though it
                # lives in its own histogram for /health.
                "overlap_fraction": max(
                    0.0, 1.0 - (sw_total + sa_total) / wall
                ),
                # Experience-quality columns (obs/quality.py; -1 =
                # signal never armed this run).
                **quality_stats_columns(),
                # Device plane (ISSUE 14): compile ledger + peak HBM.
                **mon.run_stats(),
            }
            if self._remote:
                # The standalone tier's robustness ledger (ISSUE 12).
                self._stats.update(
                    {
                        "shard_deaths": float(self.shards.deaths_total),
                        "shard_rejoins": float(self.shards.rejoins_total),
                        "shard_forward_bytes_total": float(
                            self.shards.forward_bytes_total
                        ),
                        # Observability riders, apart from the sampling
                        # boundary's wire-cost contract.
                        "telem_bytes_total": float(
                            self.shards.telem_bytes_total
                        ),
                    }
                )
            if train_t0 is not None:
                train_wall = max(t_end - train_t0, 1e-9)
                self._stats["train_wall_s"] = train_wall
                self._stats["train_learner_steps_per_sec"] = (
                    max(drained_here - 1, 0) * cfg.learner_steps / train_wall
                )
        lstate = dataclasses.replace(lstate, train=train, rng=rng)
        return dataclasses.replace(
            merge_state(state, cstate, lstate),
            phase_idx=cstate.phase_idx + drained,
        )

    def _save_checkpoint(
        self, ckpt, step: int, state, cstate, train, rng, lstate, counters
    ) -> None:
        # The ADVANCED per-phase rng, not lstate's run-start key: a light
        # checkpoint persists only the train subtree today, but the saved
        # state must never claim a key stream the run already consumed.
        lstate = dataclasses.replace(lstate, train=train, rng=rng)
        ckpt.save(step, merge_state(state, cstate, lstate))
        save_fleet_counters(ckpt.directory, step, counters)
        prune_fleet_counters(ckpt.directory, ckpt.all_steps())

    def _snapshot_params(self, train) -> Any:
        """The shared published-snapshot contract (ingest.snapshot_params):
        all four net cores + step, one definition for both learners."""
        return snapshot_params(train)
