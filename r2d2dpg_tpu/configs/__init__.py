"""The five BASELINE capability configs (BASELINE.json `configs`).

Reference parity: SURVEY.md §2.5 — the reference keeps hyperparameters as
constants in ``main.py``; here each BASELINE config is a named experiment
(SURVEY §5.6: "the five named configs become configs/*").

| # | name              | BASELINE.json line                                          |
|---|-------------------|-------------------------------------------------------------|
| 1 | pendulum_ddpg     | Pendulum-v1, 1 actor, feedforward DDPG, uniform replay      |
| 2 | pendulum_r2d2     | Pendulum-v1, 4 actors, LSTM + burn-in, prioritized replay   |
| 3 | walker_r2d2       | DM-Control Walker-walk, 64 actors, seq-len 40, n-step 3     |
|   |                   | (evidence-flipped default; the BASELINE-verbatim n-step-5   |
|   |                   | spelling is `walker_r2d2_ns5`)                              |
| 4 | humanoid_r2d2     | DM-Control Humanoid-run, 256 actors, seq-len 80, soft-update|
| 5 | cheetah_pixels    | DM-Control Cheetah-run from pixels, CNN+LSTM, 256 actors    |
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict

from r2d2dpg_tpu.agents.ddpg import AgentConfig, R2D2DPG
from r2d2dpg_tpu.envs.core import Environment
from r2d2dpg_tpu.models import ActorNet, CriticNet
from r2d2dpg_tpu.training.trainer import Trainer, TrainerConfig


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    """One runnable experiment: env factory + net shape + agent + trainer."""

    name: str
    env_factory: Callable[[], Environment]
    agent: AgentConfig
    trainer: TrainerConfig
    use_lstm: bool = True
    pixels: bool = False
    hidden: int = 256
    # Activation/compute dtype for the nets ("float32" | "bfloat16").
    # Params, optimizer state, and losses stay float32 (flax mixed
    # precision); bfloat16 halves HBM traffic and doubles MXU rate.
    compute_dtype: str = "float32"

    def build(self) -> Trainer:
        env = self.env_factory()
        agent = self.build_agent(env)
        if self.trainer.overlap_learner:
            # The interleaved-learner path lives in HostSPMDTrainer (the
            # updates hide under the host env pool's MuJoCo step); on one
            # device that trainer degenerates cleanly to a 1-mesh.  The
            # base Trainer would silently ignore the flag — refuse to.
            if not getattr(env, "batched", False):
                raise ValueError(
                    "overlap_learner requires a host-pool env (pure-JAX "
                    "envs collect in-graph; there is no host gap to hide "
                    "updates under)"
                )
            from r2d2dpg_tpu.parallel import HostSPMDTrainer, make_mesh

            return HostSPMDTrainer(env, agent, self.trainer, make_mesh(1))
        return Trainer(env, agent, self.trainer)

    def build_agent(self, env: Environment, axis_name=None) -> R2D2DPG:
        import jax.numpy as jnp

        dtype = jnp.dtype(self.compute_dtype)
        actor = ActorNet(
            action_dim=env.spec.action_dim,
            hidden=self.hidden,
            use_lstm=self.use_lstm,
            pixels=self.pixels,
            dtype=dtype,
        )
        critic = CriticNet(
            hidden=self.hidden,
            use_lstm=self.use_lstm,
            pixels=self.pixels,
            dtype=dtype,
        )
        agent_cfg = (
            dataclasses.replace(self.agent, axis_name=axis_name)
            if axis_name != self.agent.axis_name
            else self.agent
        )
        return R2D2DPG(actor, critic, agent_cfg)

    def build_dp_learner(self, mesh, collect_local: bool) -> Trainer:
        """Data-parallel LEARNER on ``mesh`` (``--learner-dp N``): replay
        capacity-sharded + learner batch dp-sharded, pjit style
        (parallel/dp_learner.py).  ``collect_local`` says this process
        also collects (``--actors 0``): that in-graph path needs a
        pure-JAX env — host-pool envs stitch ordered ``io_callback``
        physics into the phase programs, which the dp learner does not
        compose with (use HostSPMDTrainer/--spmd for those); under
        ``--actors N`` the actors own collection and any config works."""
        env = self.env_factory()
        if collect_local and getattr(env, "batched", False):
            raise ValueError(
                "--learner-dp with --actors 0 requires a pure-JAX env "
                "config (host-pool envs scale with --spmd / "
                "HostSPMDTrainer); with --actors N the fleet collects and "
                "any config works"
            )
        if self.trainer.overlap_learner:
            raise ValueError(
                "overlap_learner requires a host-pool env trainer "
                "(HostSPMDTrainer); the dp learner would silently ignore it"
            )
        from r2d2dpg_tpu.parallel import DPLearnerTrainer

        agent = self.build_agent(env, axis_name=None)
        return DPLearnerTrainer(env, agent, self.trainer, mesh)

    def build_spmd(self, mesh) -> "Trainer":
        """Multi-chip variant on ``mesh``: pure-JAX envs run whole phases
        under ``shard_map`` (SPMDTrainer); host-pool envs use the pjit-style
        HostSPMDTrainer (sharded device compute, pool stepped from host)."""
        from r2d2dpg_tpu.parallel import DP_AXIS, HostSPMDTrainer, SPMDTrainer

        env = self.env_factory()
        if getattr(env, "batched", False):
            agent = self.build_agent(env, axis_name=None)
            return HostSPMDTrainer(env, agent, self.trainer, mesh)
        if self.trainer.overlap_learner:
            raise ValueError(
                "overlap_learner requires a host-pool env (pure-JAX envs "
                "collect in-graph; there is no host gap to hide updates "
                "under) — SPMDTrainer would silently ignore it"
            )
        agent = self.build_agent(env, axis_name=DP_AXIS)
        return SPMDTrainer(env, agent, self.trainer, mesh)


def _pendulum():
    from r2d2dpg_tpu.envs.pendulum import Pendulum

    return Pendulum()


def _dmc(domain: str, task: str, pixels: bool = False, action_repeat: int = 1):
    def factory():
        from r2d2dpg_tpu.envs.dmc_host import DMCHostEnv

        return DMCHostEnv(
            domain, task, pixels=pixels, action_repeat=action_repeat
        )

    return factory


# 1: classic DDPG smoke slice (SURVEY §4.3's golden-learning config).
PENDULUM_DDPG = ExperimentConfig(
    name="pendulum_ddpg",
    env_factory=_pendulum,
    use_lstm=False,
    hidden=256,
    agent=AgentConfig(
        burnin=0,
        unroll=1,
        n_step=1,
        gamma=0.99,
        tau=5e-3,
        actor_lr=1e-3,
        critic_lr=1e-3,
        use_huber=False,
    ),
    trainer=TrainerConfig(
        num_envs=1,
        stride=1,
        learner_steps=1,
        batch_size=128,
        capacity=100_000,
        prioritized=False,
        min_replay=1_000,
        sigma_max=0.15,
        ladder_kind="constant",
    ),
)

# 2: the full R2D2 recurrent-replay recipe on the toy env.
PENDULUM_R2D2 = ExperimentConfig(
    name="pendulum_r2d2",
    env_factory=_pendulum,
    use_lstm=True,
    hidden=128,
    agent=AgentConfig(
        burnin=10,
        unroll=20,
        n_step=5,
        gamma=0.99,
        tau=5e-3,
        actor_lr=5e-4,
        critic_lr=1e-3,
    ),
    trainer=TrainerConfig(
        num_envs=4,
        stride=10,
        learner_steps=1,
        batch_size=64,
        capacity=50_000,
        prioritized=True,
        min_replay=200,
        sigma_max=0.3,
        ladder_alpha=3.0,
    ),
)

# 3: the north-star metric config (walker-walk @ 30 min).
#
# n_step=3 (was 5): the round-3 4-probe sweep (docs/RESULTS.md "walker
# plateau") showed the long-standing 160-250 return band was an
# n-step-5 bootstrap-horizon cap, not a data wall — n-step 3 reached
# 351.7 (20-ep eval, seed 3) vs the prior 198.9 best, still climbing at
# the probe's 330k-step cutoff.
#
# sigma_max=0.4 (round 5 reverted a round-4 flip to 0.8): the seed-4
# combined-recipe probe (docs/RESULTS.md "combined-recipe probe")
# measured n-step 3 + sigma 0.8 TOGETHER at 202 @ 247k steps / 220.7
# final — far below n-step-3-alone's 334 @ 247k at equal steps — so the
# round-3 "sigma 0.8 mildly ahead" single-change result does not
# compose with the shorter bootstrap horizon, and the recorded recipe
# stays n_step=3 + sigma_max=0.4.  BASELINE.json's literal n-step-5
# spelling is preserved as walker_r2d2_ns5 below (VERDICT r3 "next" #1:
# the recipe must live in tracked state, not a gitignored flags file).
WALKER_R2D2 = ExperimentConfig(
    name="walker_r2d2",
    env_factory=_dmc("walker", "walk", action_repeat=2),
    use_lstm=True,
    agent=AgentConfig(
        burnin=20,
        unroll=20,
        n_step=3,
        gamma=0.99,
        tau=5e-3,
        actor_lr=1e-4,
        critic_lr=1e-3,
    ),
    trainer=TrainerConfig(
        num_envs=64,
        stride=20,
        learner_steps=4,
        batch_size=64,
        capacity=100_000,
        prioritized=True,
        min_replay=2_000,
        sigma_max=0.4,
        ladder_alpha=7.0,
    ),
)

# BASELINE.json config #3 verbatim (n-step 5, sigma 0.4) — kept runnable so
# the literal contract spelling stays one --config flag away.
WALKER_R2D2_NS5 = dataclasses.replace(
    WALKER_R2D2,
    name="walker_r2d2_ns5",
    agent=dataclasses.replace(WALKER_R2D2.agent, n_step=5),
    trainer=dataclasses.replace(WALKER_R2D2.trainer, sigma_max=0.4),
)

# 4: long sequences (seq-len 80) at 256 actors.
HUMANOID_R2D2 = ExperimentConfig(
    name="humanoid_r2d2",
    env_factory=_dmc("humanoid", "run", action_repeat=2),
    use_lstm=True,
    agent=AgentConfig(
        burnin=40,
        unroll=40,
        n_step=5,
        gamma=0.99,
        tau=5e-3,
        actor_lr=1e-4,
        critic_lr=1e-3,
    ),
    trainer=TrainerConfig(
        num_envs=256,
        stride=40,
        learner_steps=4,
        batch_size=64,
        capacity=50_000,
        prioritized=True,
        min_replay=2_000,
        sigma_max=0.4,
        ladder_alpha=7.0,
    ),
)

# 5: from-pixels (CNN+LSTM encoder).
CHEETAH_PIXELS = ExperimentConfig(
    name="cheetah_pixels",
    env_factory=_dmc("cheetah", "run", pixels=True, action_repeat=4),
    use_lstm=True,
    pixels=True,
    agent=AgentConfig(
        burnin=20,
        unroll=20,
        n_step=5,
        gamma=0.99,
        tau=5e-3,
        # 5e-5 (was 1e-4): the round-2 evidence run collapsed from critic
        # overestimation at 1e-4 (eval 4.1 -> 1.5 by 94 min); the round-3
        # run at 5e-5 + batch 16 is monotone 0.8 -> 2.5 -> 4.3 through
        # 102 min / 76k steps with no collapse (docs/RESULTS.md).  Twin
        # critic (clipped double-Q) remains the stronger, opt-in fix.
        actor_lr=5e-5,
        critic_lr=5e-4,
    ),
    trainer=TrainerConfig(
        num_envs=256,
        stride=20,
        learner_steps=2,
        batch_size=32,
        capacity=8_000,
        prioritized=True,
        min_replay=1_000,
        sigma_max=0.4,
        ladder_alpha=7.0,
    ),
)

# Not a BASELINE config: a seconds-scale smoke slice (CI / CLI sanity) with
# the full R2D2 recipe at toy shapes.
PENDULUM_TINY = ExperimentConfig(
    name="pendulum_tiny",
    env_factory=_pendulum,
    use_lstm=True,
    hidden=32,
    agent=AgentConfig(burnin=2, unroll=4, n_step=2),
    trainer=TrainerConfig(
        num_envs=4,
        stride=4,
        learner_steps=1,
        batch_size=8,
        capacity=256,
        prioritized=True,
        min_replay=8,
        sigma_max=0.3,
    ),
)

CONFIGS: Dict[str, ExperimentConfig] = {
    c.name: c
    for c in (
        PENDULUM_DDPG,
        PENDULUM_R2D2,
        WALKER_R2D2,
        WALKER_R2D2_NS5,
        HUMANOID_R2D2,
        CHEETAH_PIXELS,
        PENDULUM_TINY,
    )
}


def get_config(name: str) -> ExperimentConfig:
    if name not in CONFIGS:
        raise KeyError(f"unknown config {name!r}; have {sorted(CONFIGS)}")
    return CONFIGS[name]
