"""HBM sequence replay (SURVEY.md §2.2): ring arena, prioritized sampling."""

from r2d2dpg_tpu.replay.arena import (
    ArenaState,
    ReplayArena,
    SampleResult,
    SequenceBatch,
    StagedSequences,
)

__all__ = [
    "ArenaState",
    "ReplayArena",
    "SampleResult",
    "SequenceBatch",
    "StagedSequences",
]
