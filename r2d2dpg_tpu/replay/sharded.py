"""Sharded prioritized replay at the ingest edge (ISSUE 10).

The central ``ReplayArena`` is one device-resident ring behind one drain
thread: every sequence the fleet collects crosses the wire into it and is
scattered by a single consumer, whether or not it is ever sampled.
In-Network Experience Sampling (PAPERS.md 2110.13506) inverts this: replay
lives in N **shards at the ingest edge**, each owning a slice of capacity
with its own priority structure, fed concurrently by the actor traffic
routed to it — and the learner *pulls* training-ready batches, so only
sampled sequences cross into the training path.

This module is the shard itself plus the two-level sampling math; the
fleet-side plumbing (actor→shard routing, SAMPLE_REQ/BATCH/PRIO frames,
the learner pull loop) lives in ``fleet/sampler.py``.

**Two-level sampling** (docs/REPLAY.md has the derivation): the central
proportional distribution draws slot ``i`` with probability
``p_i^alpha / sum_j p_j^alpha`` over ALL slots.  Factor the global sum by
shard::

    P(slot i in shard s) = (S_s / S_total) * (p_i^alpha / S_s)
                         =  p_i^alpha / S_total          where S_s = sum over shard s

so drawing shard assignments from a multinomial over the per-shard sums
``S_s`` (``shard_quotas``) and then within-shard proportionally
reproduces the central distribution EXACTLY — sharding is layout, never
semantics (tests/test_replay.py pins this on exact-integer priorities).
The combined per-draw probability for importance weights is
``(S_s / S_total) * within_prob``, i.e. exactly what the central
``ReplayArena.sample`` reports.

**Write-back versioning**: every slot carries a monotone *generation*
(bumped each time the ring overwrites it).  A sample hands out
``(slot, generation)`` pairs; a later priority write-back is applied only
where the generation still matches — a slot the ring has since evicted
ignores the stale update, the same posture as the actors' param-version
regression guard (docs/FLEET.md).

The shard is **host-side numpy** on purpose: it lives where experience
arrives (the ingest edge), is written by that connection's handler
thread and read by the sampler — a per-shard lock suffices, and N shards
make adds concurrent across handlers, which is exactly the serialization
point the central drain was.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Optional, Sequence

import numpy as np

from r2d2dpg_tpu.obs.quality import PROVENANCE_ABSENT
from r2d2dpg_tpu.ops.priority import PRIORITY_EPS
from r2d2dpg_tpu.replay.arena import SequenceBatch


def actor_code(actor_id: Any) -> int:
    """Slot-storable int64 code for a HELLO-authenticated actor id.

    Fleet actor ids are small non-negative ints ("--actor-id 0"), which
    map to themselves so the quality plane's ``actor=`` labels match the
    ids everywhere else in the obs surface; any other identity hashes
    stably (crc32) into the non-negative code space.  Never returns the
    ``PROVENANCE_ABSENT`` sentinel."""
    s = str(actor_id)
    if s.isdigit():
        return int(s)
    import zlib

    return int(zlib.crc32(s.encode("utf-8")))


@dataclasses.dataclass(frozen=True)
class ShardSample:
    """One shard's answer to a sample request (within-shard quantities).

    ``probs`` are WITHIN-shard probabilities (``p^alpha / S_s``); the
    learner combines them with the shard-level factor ``S_s / S_total``
    (``combine_probs``) to recover the central distribution's
    per-draw probability for importance weighting.  ``gens`` are the
    sampled slots' generations at sample time — the write-back version
    key (stale generations are ignored by ``update_priorities``).

    ``behavior``/``collect``/``actors`` are the drawn slots' quality
    provenance (ISSUE 18): behavior param version and collector phase
    clock from the staged stamp, plus the HELLO-authenticated actor code
    the owning ingest/shard server passed to ``add`` — the sentinel
    ``PROVENANCE_ABSENT`` (-1) where unknown, so old frames sample
    cleanly with the quality folds disarmed."""

    seq: SequenceBatch  # numpy leaves [n, L, ...]
    slots: np.ndarray  # [n] int64 shard-local slot indices
    gens: np.ndarray  # [n] int64 slot generations at sample time
    probs: np.ndarray  # [n] float64 within-shard probabilities
    behavior: Any = None  # [n] int64 behavior param versions (or None)
    collect: Any = None  # [n] int64 collector phase clocks (or None)
    actors: Any = None  # [n] int64 authenticated actor codes (or None)


class ReplayShard:
    """One slice of replay capacity: a host-side prioritized ring.

    Thread contract: the feeding handler thread calls ``add``; the
    sampler thread calls ``sample``/``update_priorities``/the stat
    reads.  Every public method takes the shard lock, so concurrency is
    per-shard — N shards, N concurrent writers fleet-wide.
    """

    def __init__(
        self,
        capacity: int,
        *,
        alpha: float = 0.6,
        prioritized: bool = True,
        shard_id: int = 0,
        evict_cb=None,
        evict_unsampled_cb=None,
    ):
        if capacity < 1:
            raise ValueError("shard capacity must be >= 1")
        self.capacity = capacity
        self.alpha = alpha
        self.prioritized = prioritized
        self.shard_id = shard_id
        self._lock = threading.Lock()
        self._data = None  # struct-of-arrays, allocated from the first add
        self._priority = np.zeros((capacity,), np.float64)  # raw; 0 = empty
        self._scaled = np.zeros((capacity,), np.float64)  # p^alpha (or 1.0)
        self._generation = np.zeros((capacity,), np.int64)
        # Quality-plane slot metadata (ISSUE 18): stamped at add, handed
        # back by sample, overwritten with its slot — eviction and
        # generation bumps can never leave stale provenance behind.
        self._behavior = np.full((capacity,), PROVENANCE_ABSENT, np.int64)
        self._collect = np.full((capacity,), PROVENANCE_ABSENT, np.int64)
        self._actor = np.full((capacity,), PROVENANCE_ABSENT, np.int64)
        self._ever_sampled = np.zeros((capacity,), bool)
        self._cursor = 0
        self.total_added = 0
        # FIFO-eviction visibility (ISSUE 12 satellite): ring overwrites of
        # FILLED slots replaced shedding in PR 10 but left no trace — a
        # too-small shard silently recycled experience faster than the
        # learner could sample it.  Counted here; ``evict_cb(n)`` (when
        # given) bumps the owner's obs counter under the same add, so the
        # count and the metric can never drift.  ``evict_unsampled_cb
        # (evicted, unsampled)`` (ISSUE 18) additionally reports how many
        # of those evictions the learner NEVER sampled — churn the run
        # paid collect+wire for and trained on zero times.
        self.evictions_total = 0
        self.evicted_unsampled_total = 0
        self._evict_cb = evict_cb
        self._evict_unsampled_cb = evict_unsampled_cb

    # ------------------------------------------------------------------ add
    def _alloc(self, seq: SequenceBatch) -> None:
        import jax

        def zeros(x):
            x = np.asarray(x)
            return np.zeros((self.capacity,) + x.shape[1:], x.dtype)

        self._data = jax.tree_util.tree_map(zeros, seq)

    def add(
        self,
        seq: SequenceBatch,
        priorities: Optional[np.ndarray],
        *,
        behavior: Optional[np.ndarray] = None,
        collect: Optional[np.ndarray] = None,
        actor: Optional[int] = None,
    ) -> int:
        """Ring-write B sequences at the cursor (FIFO overwrite).

        ``priorities=None`` (a config whose actors do not rank locally)
        enters at the shard's max priority so far, floor 1.0 — the
        central ``initial_priority="max"`` semantics.  Overwritten slots
        bump their generation, which is what makes a stale write-back
        detectable.  ``behavior``/``collect`` are the staged batch's
        quality provenance ([B] int64 or None -> sentinel); ``actor`` is
        the feeding connection's HELLO-AUTHENTICATED id — the caller
        must never pass a payload-carried id here (the PR 6 TELEM
        identity posture).  Returns B."""
        import jax

        b = int(np.shape(seq.reward)[0])
        with self._lock:
            if self._data is None:
                self._alloc(seq)
            if priorities is None:
                entry = max(float(self._priority.max()), 1.0)
                prios = np.full((b,), entry, np.float64)
            else:
                prios = np.asarray(priorities, np.float64)
            prios = np.maximum(prios, PRIORITY_EPS)
            idx = (self._cursor + np.arange(b)) % self.capacity
            filled = self._priority[idx] > 0
            evicted = int(filled.sum())
            if evicted:
                unsampled = int(
                    (filled & ~self._ever_sampled[idx]).sum()
                )
                self.evictions_total += evicted
                self.evicted_unsampled_total += unsampled
                if self._evict_cb is not None:
                    self._evict_cb(evicted)
                if self._evict_unsampled_cb is not None:
                    self._evict_unsampled_cb(evicted, unsampled)
            jax.tree_util.tree_map(
                lambda buf, new: buf.__setitem__(idx, np.asarray(new)),
                self._data,
                seq,
            )
            self._priority[idx] = prios
            self._scaled[idx] = prios**self.alpha if self.prioritized else 1.0
            self._generation[idx] += 1
            self._behavior[idx] = (
                PROVENANCE_ABSENT
                if behavior is None
                else np.asarray(behavior, np.int64)
            )
            self._collect[idx] = (
                PROVENANCE_ABSENT
                if collect is None
                else np.asarray(collect, np.int64)
            )
            self._actor[idx] = (
                PROVENANCE_ABSENT if actor is None else int(actor)
            )
            self._ever_sampled[idx] = False
            self._cursor = int((self._cursor + b) % self.capacity)
            self.total_added += b
        return b

    # --------------------------------------------------------------- sample
    def sample(self, n: int, rng: np.random.Generator) -> ShardSample:
        """Draw ``n`` sequences proportional to ``p^alpha`` within this
        shard (uniform over filled slots when unprioritized).  Caller
        guarantees the shard is non-empty (quota draws weight empty
        shards at 0 — ``shard_quotas``)."""
        with self._lock:
            if self._data is None or not (self._priority > 0).any():
                raise ValueError(
                    f"shard {self.shard_id} is empty; quotas must not "
                    f"route draws here"
                )
            scaled = self._scaled
            cdf = np.cumsum(scaled)
            # ``total`` must be cdf[-1] itself, NOT scaled.sum(): numpy's
            # pairwise summation can make the latter exceed the
            # sequential cumsum's last element, and a draw landing in
            # that float gap would searchsort past the end.  The clamp
            # goes to the last FILLED slot (side="right" never selects an
            # interior zero slot; empties are a suffix until the ring
            # wraps) — clamping to capacity-1 could hand out an EMPTY
            # slot whose generation-0 handle a later write-back would
            # wrongly match.
            total = float(cdf[-1])
            u = rng.random(n) * total
            last_filled = int(np.flatnonzero(scaled)[-1])
            slots = np.minimum(
                np.searchsorted(cdf, u, side="right"), last_filled
            )
            probs = scaled[slots] / max(total, 1e-300)
            import jax

            seq = jax.tree_util.tree_map(lambda buf: buf[slots], self._data)
            gens = self._generation[slots].copy()
            behavior = self._behavior[slots].copy()
            collect = self._collect[slots].copy()
            actors = self._actor[slots].copy()
            # Quality-plane churn accounting: these slots have now been
            # trained on at least once — a later eviction is ordinary ring
            # turnover, not untrained churn.  No extra rng is consumed
            # anywhere in this method (the determinism anchors pin the
            # draw stream).
            self._ever_sampled[slots] = True
        return ShardSample(
            seq=seq,
            slots=slots.astype(np.int64),
            gens=gens,
            probs=probs.astype(np.float64),
            behavior=behavior,
            collect=collect,
            actors=actors,
        )

    # ------------------------------------------------------- priority update
    def update_priorities(
        self,
        slots: np.ndarray,
        gens: np.ndarray,
        priorities: np.ndarray,
    ) -> int:
        """Learner TD-error write-back, version-checked.

        Applied only where the slot's generation still equals ``gens``
        (the sample-time version): a slot the ring has overwritten since
        holds a NEWER sequence whose priority must not be clobbered by a
        verdict about the old one — stale versions are ignored, like
        param regressions on the actor side.  Duplicate slots in one
        batch resolve last-write-wins, matching the central scatter.
        Returns how many entries applied."""
        slots = np.asarray(slots, np.int64)
        gens = np.asarray(gens, np.int64)
        prios = np.maximum(np.asarray(priorities, np.float64), PRIORITY_EPS)
        if slots.size and not (
            0 <= int(slots.min()) and int(slots.max()) < self.capacity
        ):
            # Out-of-range handles would alias (negative python indexing)
            # or IndexError mid-update — refuse the whole frame loudly,
            # the wire validators' contract carried to the ring boundary.
            raise ValueError(
                f"write-back slots outside shard capacity {self.capacity}"
            )
        with self._lock:
            fresh = self._generation[slots] == gens
            idx = slots[fresh]
            self._priority[idx] = prios[fresh]
            self._scaled[idx] = (
                prios[fresh] ** self.alpha if self.prioritized else 1.0
            )
            return int(fresh.sum())

    # ------------------------------------------------------------------ stats
    def occupancy(self) -> int:
        with self._lock:
            return int((self._priority > 0).sum())

    def priority_sum(self) -> float:
        """Raw priority sum (the obs gauge's value — mirrors the central
        ``r2d2dpg_replay_priority_sum``)."""
        with self._lock:
            return float(self._priority.sum())

    def scaled_sum(self) -> float:
        """The quota weight this shard advertises: ``sum p^alpha`` over
        filled slots (occupancy when unprioritized)."""
        with self._lock:
            return float(self._scaled.sum())


def shard_quotas(
    scaled_sums: Sequence[float], n: int, rng: np.random.Generator
) -> np.ndarray:
    """Level 1 of two-level sampling: how many of ``n`` draws each shard
    serves, multinomial over the advertised ``sum p^alpha`` weights.

    Empty shards (weight 0) get quota 0; an all-empty fleet is a caller
    error (the absorb gate holds until ``min_replay``)."""
    w = np.asarray(scaled_sums, np.float64)
    if (w < 0).any():
        raise ValueError("negative shard priority sum")
    total = w.sum()
    if total <= 0:
        raise ValueError("all shards empty: nothing to sample")
    return rng.multinomial(n, w / total)


def combine_probs(
    within_probs: np.ndarray, shard_sum: float, total_sum: float
) -> np.ndarray:
    """Level-2 probabilities -> the per-draw probability of the REALIZED
    two-stage procedure: ``(S_s / S_total) * within_prob`` (the
    module-doc factorization) — what importance weights must see.

    Deliberate under concurrency: ``shard_sum``/``total_sum`` are the
    QUOTA-time snapshot (the multinomial really was drawn from them)
    while ``within_probs`` are normalized against the shard's
    SAMPLE-time state (the within-draw really used it), so the product
    is exactly the marginal probability with which each slot was drawn
    even when handlers added between the two moments.  "Correcting"
    either factor to the other timepoint would make the weights describe
    a draw that never happened."""
    return np.asarray(within_probs, np.float64) * (
        shard_sum / max(total_sum, 1e-300)
    )
