"""Shared response/shed codes: one vocabulary for every admission layer.

Serving's micro-batcher (serving/batcher.py) and the fleet ingest server
(fleet/ingest.py) both degrade under load by *refusing with a code* rather
than queueing unboundedly or raising — overload is an expected state, not
an error.  The codes live here so the two subsystems cannot drift apart
(an operator's shed-rate alert matches one string set) and stay dumb
strings on purpose: they cross process boundaries via the serving JSONL
CLI and the fleet wire protocol and land verbatim in logs and
``flight.jsonl`` events.
"""

from __future__ import annotations

OK = "ok"
# Serving admission: the micro-batcher's bounded request queue is full.
SHED_QUEUE = "shed_queue_full"
# Serving admission: the session-slot table is full after a TTL sweep.
SHED_SESSIONS = "shed_session_capacity"
# Fleet ingest: the learner's staging queue is full — the actor sheds the
# batch (collection outran learning past the queue bound) and keeps going.
SHED_INGEST = "shed_ingest_queue_full"
# Fleet ingest HELLO: the actor's wire version/encoding/compression does
# not match the learner's negotiated fast lane (fleet/wire.py) — the
# connection is refused outright; a fleet runs ONE wire format.
REFUSED_WIRE = "refused_wire_mismatch"
# Fleet ingest HELLO: the actor's --fleet-token proof does not match the
# learner's shared secret (hmac.compare_digest; fleet/ingest.py) — refused
# at the door with an ``auth_refused`` flight event, the prerequisite for
# routable (non-loopback) ingest binds.
REFUSED_AUTH = "refused_auth"
SHUTDOWN = "shutdown"

# Process exit codes for refused HELLOs: the actor failures that are
# deterministic misconfiguration, not transient crashes.  The actor exits
# with these codes and the supervisor gives the slot up instead of walking
# the restart ladder forever (fleet/actor.py main / fleet/supervisor.py).
EXIT_WIRE_REFUSED = 64
EXIT_AUTH_REFUSED = 65
TERMINAL_ACTOR_EXITS = {
    EXIT_WIRE_REFUSED: "wire_refused",
    EXIT_AUTH_REFUSED: "auth_refused",
}

ALL_SHED_CODES = (SHED_QUEUE, SHED_SESSIONS, SHED_INGEST)
