"""Test configuration: run on a virtual 8-device CPU mesh (SURVEY.md §4.4).

Multi-chip TPU hardware is unavailable in CI; all sharding/collective code
paths execute on 8 virtual CPU devices via
``--xla_force_host_platform_device_count``.

This box routes JAX to one real TPU chip through the "axon" plugin, which a
sitecustomize hook registers for *every* python process when
``PALLAS_AXON_POOL_IPS`` is set, pinning ``JAX_PLATFORMS=axon``.  Tests must
run on the CPU mesh, so both knobs are overridden — unconditionally, and
before jax is imported.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
# Compile time dominates the suite on a small CPU host (tiny shapes,
# hundreds of jit programs) and XLA:CPU's backend optimization pipeline
# is most of each compile: level 0 cuts ~30% of suite wall-clock
# (ROADMAP.md's 870 s tier-1 budget).  Execution of the tiny test shapes
# is not measurably slower, and numerics stay self-consistent — every
# trainer-side bit-identity anchor and its subject run under the SAME
# flags (subprocess legs inherit this env), while the serving plane is
# flag-INDEPENDENT by design: PolicyService and the serving tests'
# references compile through ``serving.compile_pinned``, which pins the
# backend level per-executable (level 0 would otherwise pick per-bucket
# reduction strategies and break the cross-bucket row-identity contract).
# Real-chip runs never see this: it applies only when conftest is in the
# process.
if "xla_backend_optimization_level" not in flags:
    flags = (flags + " --xla_backend_optimization_level=0").strip()
os.environ["XLA_FLAGS"] = flags
# Exercise Pallas kernels via the interpreter on CPU (SURVEY §4: the kernel
# logic itself is under test; the Mosaic-compiled path runs on real TPU).
os.environ.setdefault("R2D2DPG_PALLAS_INTERPRET", "1")

import jax  # noqa: E402

# The axon sitecustomize hook pins jax_platforms="axon,cpu" at interpreter
# startup (before conftest runs); config.update after import wins it back.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

assert jax.default_backend() == "cpu", (
    "tests must run on the virtual CPU mesh, got " + jax.default_backend()
)
assert len(jax.devices()) == 8


# --------------------------------------------------- shared anchor references
# The determinism anchors (fleet/pipeline/dp-learner/sampler/topology
# gates) all pin their subsystem's off-setting BIT-IDENTICAL to the same
# quantity: the phase-locked ``Trainer.run`` of PENDULUM_TINY over
# warm + fill + N train phases at a fixed log cadence (the cadence is part
# of the state — pop_episode_metrics drains device accumulators).  Each
# anchor used to recompute that identical reference (~12 s of jit compiles
# apiece); these session fixtures compute each (N, cadence) flavor ONCE
# and every anchor compares against the shared copy.  Coverage is
# unchanged — the schedule UNDER TEST still runs fresh inside each anchor;
# only the never-mutated reference state is shared (tests read leaves,
# nothing donates them).  The tier-1 wall-clock budget is the point
# (ROADMAP.md's 870 s timeout).

import pytest  # noqa: E402


def _phase_locked_reference(n_train: int, log_every: int):
    from r2d2dpg_tpu.configs import PENDULUM_TINY

    t = PENDULUM_TINY.build()
    warm, fill = t.window_fill_phases, t.replay_fill_phases
    return t.run(
        warm + fill + n_train, log_every=log_every, log_fn=lambda *_: None
    )


@pytest.fixture(scope="session")
def phase_locked_reference_k10():
    """PENDULUM_TINY warm+fill+10 train phases at log_every=3 (the
    fleet / pipeline / dp-learner anchors' reference)."""
    return _phase_locked_reference(10, 3)


@pytest.fixture(scope="session")
def phase_locked_reference_k6():
    """PENDULUM_TINY warm+fill+6 train phases at log_every=2 (the
    sampler / topology anchors' reference)."""
    return _phase_locked_reference(6, 2)


@pytest.fixture(scope="session")
def tiny_cli_checkpoint(tmp_path_factory):
    """A 2-phase pendulum_tiny training checkpoint written through the
    real train CLI (checkpoint-every 1) — shared by the eval-CLI tests
    that only READ a checkpoint (each used to train its own identical
    one; same tier-1 budget rationale as the anchor references above).
    Consumers that need a different flavor (bf16 train) or mutate the
    directory keep training their own."""
    from r2d2dpg_tpu.train import main as train_main

    ckdir = str(tmp_path_factory.mktemp("shared_ck") / "ck")
    train_main(
        [
            "--config", "pendulum_tiny",
            "--phases", "2",
            "--log-every", "0",
            "--checkpoint-dir", ckdir,
            "--checkpoint-every", "1",
        ]
    )
    return ckdir


# NB the jax persistent compilation cache was evaluated for the tier-1
# budget and REJECTED: this jax build (0.4.37 CPU) segfaults when a fresh
# process deserializes existing entries, and aborts (SIGABRT) mid-suite
# even with a per-run-unique directory.  Do not re-enable without a jax
# upgrade and a full green double-run.
