"""serve CLI: flag plumbing (fast) and the stdio/selftest loops (slow,
subprocess — covers the ``python -m r2d2dpg_tpu serve`` dispatch too)."""

import json
import os
import subprocess
import sys

import pytest

from r2d2dpg_tpu.serve import build_service, parse_args

pytestmark = pytest.mark.serving

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_parse_args_plumbing():
    args = parse_args(
        [
            "--config", "pendulum_tiny", "--checkpoint-dir", "ck",
            "--bucket-sizes", "2,8", "--flush-ms", "1.5", "--max-queue", "7",
            "--max-sessions", "3", "--session-ttl", "9", "--poll-every", "0.5",
        ]
    )
    assert args.config == "pendulum_tiny" and args.checkpoint_dir == "ck"
    assert args.bucket_sizes == "2,8" and args.flush_ms == 1.5
    assert (args.max_queue, args.max_sessions) == (7, 3)
    assert (args.session_ttl, args.poll_every) == (9.0, 0.5)
    assert args.serve_workers == 1  # scale-out is opt-in
    assert parse_args(
        ["--config", "pendulum_tiny", "--checkpoint-dir", "ck",
         "--serve-workers", "4"]
    ).serve_workers == 4


def _cli_args(ckpt_dir, *extra):
    return parse_args(
        ["--config", "pendulum_tiny", "--checkpoint-dir", ckpt_dir,
         "--bucket-sizes", "1,2", "--flush-ms", "1", *extra]
    )


def test_build_service_workers_flag_selects_plain_service_or_router(ckpt_dir):
    """Structural half of the off-setting anchor: ``--serve-workers 1``
    (default or explicit) builds the PR-1 single-worker PolicyService with
    NO router and NO worker label in the path; ``--serve-workers N``
    builds the session-affine router over N labelled per-device workers
    sharing one fanout reloader."""
    from r2d2dpg_tpu.serving import PolicyService, ServiceRouter
    from r2d2dpg_tpu.serving.router import FanoutReloader

    for argv_extra in ((), ("--serve-workers", "1")):
        svc, _env = build_service(_cli_args(ckpt_dir, *argv_extra))
        assert type(svc) is PolicyService
        assert svc.worker_label is None and svc.device is None

    router, _env = build_service(_cli_args(ckpt_dir, "--serve-workers", "2"))
    assert type(router) is ServiceRouter and router.num_workers == 2
    fanouts = set()
    for w, svc in enumerate(router.services):
        assert svc.worker_label == str(w)
        assert svc.device is not None
        fanouts.add(id(svc.reloader._fanout))
        assert isinstance(svc.reloader._fanout, FanoutReloader)
    assert len(fanouts) == 1, "workers must share ONE checkpoint poller"


def test_serve_workers_1_bit_identical_to_pr1_path(ckpt_dir):
    """Determinism half of the anchor: the CLI-built ``--serve-workers 1``
    service serves the exact bits a directly-constructed PR-1
    PolicyService serves for the same traffic."""
    import numpy as np

    from r2d2dpg_tpu.configs import get_config
    from r2d2dpg_tpu.serving import CheckpointHotReloader, PolicyService
    from r2d2dpg_tpu.serving.reload import actor_params_template

    cfg = get_config("pendulum_tiny")
    env = cfg.env_factory()
    actor = cfg.build_agent(env).actor
    obs_shape = tuple(env.spec.obs_shape)
    rng = np.random.default_rng(5)
    sids = ["a", "b", "c"]
    obs = {
        s: rng.standard_normal((3,) + obs_shape).astype(np.float32)
        for s in sids
    }

    def drive(service):
        got = {s: [] for s in sids}
        with service:
            for t in range(3):
                pending = [
                    (s, service.act_async(s, obs[s][t], reset=(t == 0)))
                    for s in sids
                ]
                for s, req in pending:
                    assert req.wait(30.0) and req.code == "ok", req.code
                    got[s].append(req.action)
        return got

    via_cli, _ = build_service(_cli_args(ckpt_dir, "--serve-workers", "1"))
    pr1 = PolicyService(
        actor,
        obs_shape=obs_shape,
        bucket_sizes=(1, 2),
        flush_ms=1.0,
        reloader=CheckpointHotReloader(
            ckpt_dir, actor_params_template(actor, obs_shape),
            poll_every_s=2.0,
        ),
    )
    got_cli, got_pr1 = drive(via_cli), drive(pr1)
    for s in sids:
        for t in range(3):
            np.testing.assert_array_equal(got_cli[s][t], got_pr1[s][t])


@pytest.fixture(scope="module")
def ckpt_dir(tmp_path_factory):
    """A real pendulum_tiny light checkpoint for the subprocess to serve."""
    from r2d2dpg_tpu.configs import get_config
    from r2d2dpg_tpu.utils.checkpoint import CheckpointManager

    cfg = get_config("pendulum_tiny")
    state = cfg.build().init()
    d = str(tmp_path_factory.mktemp("serve") / "ckpt")
    mgr = CheckpointManager(d, save_every=1, light=True)
    mgr.save(5, state)
    mgr.wait()
    mgr.close()
    return d


def _serve_env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("R2D2DPG_PALLAS_INTERPRET", "1")
    return env


@pytest.mark.slow
def test_serve_stdio_loop_end_to_end(ckpt_dir):
    lines = "\n".join(
        [
            json.dumps({"session": "u1", "obs": [0.1, 0.2, 0.3], "reset": True}),
            json.dumps({"session": "u1", "obs": [0.2, 0.3, 0.4]}),
            json.dumps({"cmd": "health"}),
            json.dumps({"cmd": "end_session", "session": "u1"}),
            "not json",
            # Valid JSON, poisonous payloads: each must answer THIS client
            # with a code, not crash the server (np.asarray raises on
            # strings; a non-object line has no .get).
            json.dumps({"session": "u9", "obs": ["boom"]}),
            json.dumps([1, 2, 3]),
            json.dumps({"cmd": "quit"}),
        ]
    )
    proc = subprocess.run(
        [sys.executable, "-m", "r2d2dpg_tpu", "serve",
         "--config", "pendulum_tiny", "--checkpoint-dir", ckpt_dir,
         "--flush-ms", "1", "--selftest", "0"],
        input=lines, capture_output=True, text=True, cwd=HERE,
        env=_serve_env(), timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = [json.loads(l) for l in proc.stdout.strip().splitlines()]
    assert len(out) == 7
    act1, act2, health, ended, bad_json, bad_obs, bad_type = out
    assert act1["code"] == "ok" and len(act1["action"]) == 1
    assert act1["params_step"] == 5 and act2["code"] == "ok"
    assert health["params_step"] == 5 and health["requests_ok"] == 2
    assert ended == {"code": "ok", "released": True}
    assert bad_json["code"] == "bad_request"
    assert bad_obs["code"] == "bad_request" and "ValueError" in bad_obs["error"]
    assert bad_type["code"] == "bad_request"


@pytest.mark.slow
def test_serve_selftest_smoke(ckpt_dir):
    proc = subprocess.run(
        [sys.executable, "-m", "r2d2dpg_tpu", "serve",
         "--config", "pendulum_tiny", "--checkpoint-dir", ckpt_dir,
         "--flush-ms", "1", "--selftest", "24"],
        capture_output=True, text=True, cwd=HERE, env=_serve_env(),
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["selftest"] == 24
    assert rec["codes"] == {"ok": 24}
    assert rec["params_step"] == 5 and rec["sessions_active"] == 8
