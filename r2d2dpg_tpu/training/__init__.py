"""Training orchestration (SURVEY.md §2.5): the Anakin phase loop."""

from r2d2dpg_tpu.training.assembler import StepRecord, emit, init_window, shift_in
from r2d2dpg_tpu.training.evaluator import Evaluator
from r2d2dpg_tpu.training.trainer import Trainer, TrainerConfig, TrainerState

__all__ = [
    "Evaluator",
    "StepRecord",
    "Trainer",
    "TrainerConfig",
    "TrainerState",
    "emit",
    "init_window",
    "shift_in",
]
