"""Actor supervision: spawn, watch, restart with exponential backoff.

The reference repo's ``main.py`` spawns actor processes and forgets them;
a crashed actor silently thins the fleet forever.  Here the supervisor is
the fleet's process-lifecycle owner: it spawns each actor as a
subprocess, polls liveness on a monitor thread, and restarts any actor
that exits while the fleet is live — after an exponential backoff (a
crash-looping actor must not fork-bomb the host), reset once an
incarnation survives ``healthy_after_s`` (a crash after an hour is bad
luck, not a loop).  Every crash lands in the flight recorder
(``actor_crash`` with actor id, returncode, restart count), so a fleet
post-mortem's first question — "who died, when, how often" — reads
straight out of ``flight.jsonl``.

Actors are forced onto CPU (``JAX_PLATFORMS=cpu`` + the axon plugin gate
cleared): env stepping is host work, and an actor subprocess grabbing the
learner's accelerator would wedge both.
"""

from __future__ import annotations

import dataclasses
import os
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from r2d2dpg_tpu.obs import flight_event, get_registry
from r2d2dpg_tpu.utils.codes import TERMINAL_ACTOR_EXITS


@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    backoff_base_s: float = 0.5  # first restart delay; doubles per crash
    backoff_max_s: float = 30.0
    healthy_after_s: float = 60.0  # uptime that resets the backoff ladder
    max_restarts: Optional[int] = None  # per actor; None = never give up
    poll_s: float = 0.2


@dataclasses.dataclass
class _ActorSlot:
    proc: Optional[subprocess.Popen] = None
    started_at: float = 0.0
    restarts: int = 0
    consecutive_crashes: int = 0
    restart_at: Optional[float] = None  # backoff deadline when dead
    gave_up: bool = False


class ActorSupervisor:
    """Owns ``num_actors`` worker subprocesses for the life of a fleet run.

    ``argv_fn(actor_id)`` builds each worker's command line (train.py wires
    ``python -m r2d2dpg_tpu.fleet.actor ...`` with the ingest address);
    ``log_path_fn(actor_id)``, when given, routes the worker's
    stdout/stderr to a per-worker file for post-mortems.

    ``role`` names the supervised process class: ``"actor"`` (default,
    the historical metric/event names) or ``"shard"`` (the standalone
    replay-shard tier, ISSUE 12 — ``r2d2dpg_shard_alive`` /
    ``r2d2dpg_shard_restarts_total`` gauges, ``shard_crash`` /
    ``shard_restart`` / ``shard_gave_up`` flight events).  The whole
    backoff/give-up/terminal-exit ladder is role-agnostic — one
    supervision contract for every fleet process class.
    """

    def __init__(
        self,
        argv_fn: Callable[[int], List[str]],
        num_actors: int,
        *,
        config: SupervisorConfig = SupervisorConfig(),
        env: Optional[Dict[str, str]] = None,
        log_path_fn: Optional[Callable[[int], str]] = None,
        clock: Callable[[], float] = time.monotonic,
        role: str = "actor",
        id_field: Optional[str] = None,
    ):
        if num_actors < 1:
            raise ValueError("num_actors must be >= 1")
        self.argv_fn = argv_fn
        self.num_actors = num_actors
        self.config = config
        self.log_path_fn = log_path_fn
        self.role = role
        # The flight-event key carrying the supervised slot index.  The
        # shard tier names it "shard_proc": its slot is a PROCESS hosting
        # M/N shards, and reusing "shard" would collide with the shard-ID
        # unit the learner's shard_dead/shard_rejoin events carry — a
        # flight-merge post-mortem must never conflate the two.
        self.id_field = id_field or role
        # Injectable clock: the backoff/give-up timing contract is tested
        # against a FAKE clock (tests drive _poll_once directly), so the
        # healthy-uptime reset and restart_at deadlines are pinned without
        # real sleeps.
        self._clock = clock
        self._env = dict(os.environ if env is None else env)
        # CPU discipline (module docstring): clear the axon sitecustomize
        # gate so the plugin never registers in the child, and pin cpu.
        self._env.pop("PALLAS_AXON_POOL_IPS", None)
        self._env["JAX_PLATFORMS"] = "cpu"
        self._env.setdefault("R2D2DPG_PALLAS_INTERPRET", "1")
        self._slots: Dict[int, _ActorSlot] = {
            i: _ActorSlot() for i in range(num_actors)
        }
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        # Fleet health at scrape time (ISSUE 6): the central process-health
        # view Ape-X-scale fleets live on — live process count (set_fn:
        # evaluated per scrape) and cumulative restarts.  Metric names are
        # per-ROLE so an actor fleet and a shard tier in one learner never
        # share (or clobber) a series.
        reg = get_registry()
        if role == "actor":
            alive_name = "r2d2dpg_fleet_actors_alive"
            restarts_name = "r2d2dpg_fleet_actor_restarts_total"
        else:
            alive_name = f"r2d2dpg_{role}_alive"
            restarts_name = f"r2d2dpg_{role}_restarts_total"
        self._obs_alive = reg.gauge(
            alive_name,
            f"live supervised {role} subprocesses",
        )
        self._obs_alive.set_fn(lambda: float(self.alive_count()))
        self._obs_restarts = reg.counter(
            restarts_name,
            f"supervised {role} restarts (crash -> backoff -> respawn)",
        )

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "ActorSupervisor":
        if self._monitor is not None:
            raise RuntimeError("supervisor already started")
        for i in range(self.num_actors):
            self._spawn(i)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="fleet-supervisor", daemon=True
        )
        self._monitor.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        """Orderly teardown: no restarts from here on, SIGTERM the fleet,
        SIGKILL stragglers.  Call BEFORE stopping the ingest server so a
        connection reset never masquerades as a crash."""
        self._stopping.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
        with self._lock:
            procs = [s.proc for s in self._slots.values() if s.proc is not None]
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + timeout
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(deadline - time.monotonic(), 0.1))
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()

    # ------------------------------------------------------------ inspection
    def alive_count(self) -> int:
        with self._lock:
            return sum(
                1
                for s in self._slots.values()
                if s.proc is not None and s.proc.poll() is None
            )

    @property
    def restarts_total(self) -> int:
        with self._lock:
            return sum(s.restarts for s in self._slots.values())

    def kill_actor(self, actor_id: int) -> bool:
        """Test/drill hook: hard-kill one actor (the supervisor sees a
        crash and walks the restart path — the soak test's lever).
        Returns True when a kill was actually delivered — False for a slot
        that is already a corpse or mid-backoff, so a chaos drill can tell
        a real injection from a no-op (fleet/chaos.py keeps no-ops
        pending instead of recording a drill that never ran)."""
        with self._lock:
            proc = self._slots[actor_id].proc
        if proc is not None and proc.poll() is None:
            proc.kill()
            return True
        return False

    # -------------------------------------------------------------- internal
    def _spawn(self, actor_id: int) -> None:
        slot = self._slots[actor_id]
        stdout = subprocess.DEVNULL
        if self.log_path_fn is not None:
            stdout = open(self.log_path_fn(actor_id), "ab")
        try:
            slot.proc = subprocess.Popen(
                self.argv_fn(actor_id),
                env=self._env,
                stdout=stdout,
                stderr=subprocess.STDOUT,
                stdin=subprocess.DEVNULL,
            )
        finally:
            if stdout is not subprocess.DEVNULL:
                stdout.close()  # child holds its own fd
        slot.started_at = self._clock()
        slot.restart_at = None

    def _monitor_loop(self) -> None:
        while not self._stopping.is_set():
            self._poll_once(self._clock())
            self._stopping.wait(self.config.poll_s)

    def _poll_once(self, now: float) -> None:
        """One supervision pass at time ``now`` — the whole timing contract
        (healthy-uptime ladder reset, backoff arming, restart_at deadline,
        give-up paths) in one directly-testable step (the fake-clock tests
        call this; the monitor thread calls it on ``poll_s``)."""
        cfg = self.config
        with self._lock:
            for actor_id, slot in self._slots.items():
                if slot.gave_up:
                    continue
                if slot.proc is not None and slot.proc.poll() is None:
                    # Healthy uptime resets the backoff ladder.
                    if (
                        slot.consecutive_crashes
                        and now - slot.started_at > cfg.healthy_after_s
                    ):
                        slot.consecutive_crashes = 0
                    continue
                if slot.proc is not None and slot.restart_at is None:
                    # Fresh corpse: record, arm the backoff.
                    rc = slot.proc.returncode
                    slot.consecutive_crashes += 1
                    backoff = min(
                        cfg.backoff_base_s
                        * (2 ** (slot.consecutive_crashes - 1)),
                        cfg.backoff_max_s,
                    )
                    flight_event(
                        f"{self.role}_crash",
                        **{self.id_field: actor_id},
                        returncode=rc,
                        restarts=slot.restarts,
                        backoff_s=round(backoff, 3),
                    )
                    if rc in TERMINAL_ACTOR_EXITS:
                        # Deterministic HELLO refusal (wire mismatch or
                        # auth failure): every restart would be refused
                        # again within milliseconds (healthy_after_s never
                        # resets the ladder) — give the slot up NOW with a
                        # terminal event instead of churning forever.
                        slot.gave_up = True
                        flight_event(
                            f"{self.role}_gave_up",
                            **{self.id_field: actor_id},
                            restarts=slot.restarts,
                            reason=TERMINAL_ACTOR_EXITS[rc],
                        )
                        continue
                    if (
                        cfg.max_restarts is not None
                        and slot.restarts >= cfg.max_restarts
                    ):
                        slot.gave_up = True
                        flight_event(
                            f"{self.role}_gave_up",
                            **{self.id_field: actor_id},
                            restarts=slot.restarts,
                        )
                        continue
                    slot.restart_at = now + backoff
                if (
                    slot.restart_at is not None
                    and now >= slot.restart_at
                ):
                    # A failed spawn (logdir vanished, ENOSPC, exec
                    # error) must not kill THIS thread — supervision
                    # is the subsystem's headline feature.  Note it
                    # and retry on the max backoff.
                    try:
                        self._spawn(actor_id)
                    except Exception as e:  # noqa: BLE001
                        flight_event(
                            f"{self.role}_spawn_failed",
                            **{self.id_field: actor_id},
                            error=f"{type(e).__name__}: {e}",
                        )
                        slot.restart_at = now + cfg.backoff_max_s
                        continue
                    slot.restarts += 1
                    self._obs_restarts.inc()
                    flight_event(
                        f"{self.role}_restart",
                        **{self.id_field: actor_id},
                        restarts=slot.restarts,
                    )


def default_actor_argv(
    actor_id: int,
    *,
    config_name: str,
    address: str,
    num_actors: int,
    seed: Optional[int] = None,
    extra: Optional[List[str]] = None,
) -> List[str]:
    """The standard actor command line (train.py's spawner)."""
    argv = [
        sys.executable,
        "-m",
        "r2d2dpg_tpu.fleet.actor",
        "--config",
        config_name,
        "--connect",
        address,
        "--actor-id",
        str(actor_id),
        "--num-actors",
        str(num_actors),
    ]
    if seed is not None:
        argv += ["--seed", str(seed)]
    if extra:
        argv += list(extra)
    return argv
