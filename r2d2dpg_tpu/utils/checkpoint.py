"""Checkpoint / resume via orbax (SURVEY.md §5.4).

Reference parity: the reference at most does periodic
``torch.save(state_dict)`` with no optimizer/replay state and no resume path
(SURVEY §5.4).  The build checkpoints the **entire** ``TrainerState`` pytree —
params, optimizer states, target nets, RNG, replay arena (data + priorities +
cursor), env state, episode accumulators — so a restore resumes the run
exactly (for pure-JAX envs) or near-exactly (host-backed envs; see below).

Host-backed envs (``dmc_host``): MuJoCo physics lives on the host, outside
the pytree, so it cannot be checkpointed through this path.  On restore the
env portion of the state is re-initialized (fresh episodes, zeroed carries);
replay, learner and counters resume intact.  The first ``seq_len`` post-resume
steps re-fill the window before sequences are emitted again, exactly like the
initial warm-up — no corrupt sequences enter replay.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp


class CheckpointManager:
    """Periodic save + latest-restore of ``TrainerState`` under ``directory``.

    A thin wrapper over ``orbax.checkpoint.CheckpointManager`` that knows how
    to rebuild the abstract pytree template from a ``Trainer`` and to patch
    up host-backed env state on restore.
    """

    def __init__(
        self,
        directory: str,
        *,
        save_every: int = 500,
        max_to_keep: int = 3,
        async_save: bool = False,
    ):
        # orbax rejects relative paths at SAVE time (deep inside the first
        # cadence hit — a run can train for minutes and then die); absolutize
        # up front so `--checkpoint-dir runs/x/ckpt` just works.
        self.directory = os.path.abspath(directory)
        self.save_every = save_every
        # Synchronous by default (VERDICT r1 weak #3): orbax's async save
        # finalizes on a background thread, which a busy single-core host
        # starves — the one long round-1 run left ONLY un-finalized
        # ``*.orbax-checkpoint-tmp`` dirs and ``--resume`` found nothing.
        # A blocking save is a few seconds every ``save_every`` phases and
        # is durable the moment it returns.
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                create=True,
                enable_async_checkpointing=async_save,
            ),
        )

    # ------------------------------------------------------------------ save
    # ``save_every`` semantics: N>0 = every N phases (+ the caller's final
    # save); -1 = final-save-only (maybe_save never fires, but the truthy
    # value keeps train.py's finally-block save armed); 0 = off entirely.
    def maybe_save(self, phase: int, state: Any) -> bool:
        """Save if ``phase`` hits the cadence.  Returns True when saved."""
        if self.save_every <= 0 or phase % self.save_every != 0:
            return False
        self.save(phase, state)
        return True

    def save(self, step: int, state: Any) -> None:
        self._mgr.save(step, args=ocp.args.StandardSave(state))

    def wait(self) -> None:
        """Block until async saves are durable (call before process exit)."""
        self._mgr.wait_until_finished()

    # --------------------------------------------------------------- restore
    @property
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, template: Any) -> Any:
        """Restore the latest checkpoint into the structure of ``template``.

        ``template`` is a concrete ``TrainerState`` (e.g. ``trainer.init()``)
        — its shapes/dtypes/shardings define the restore target, so restored
        arrays land with the same mesh layout the trainer expects.
        """
        step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}"
            )
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                jnp.shape(x), x.dtype, sharding=getattr(x, "sharding", None)
            )
            if isinstance(x, (jax.Array, np.ndarray))
            else x,
            template,
        )
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))

    def close(self) -> None:
        self._mgr.close()


def resume_state(trainer, ckpt: CheckpointManager):
    """``trainer.init()`` overwritten by the latest checkpoint, env-corrected.

    For pure-JAX envs the restored state is returned as-is (bit-exact resume).
    For host-backed (``batched``) envs the host physics is gone, so the env
    slice of the state — env_state/obs/reset/carries/noise/episode_return and
    the assembler window — is taken fresh from ``trainer.init()`` while
    learner/replay/counters come from the checkpoint.
    """
    fresh = trainer.init()
    restored = ckpt.restore(fresh)
    if not getattr(trainer.env, "batched", False):
        return restored
    state = dataclasses.replace(
        restored,
        env_state=fresh.env_state,
        obs=fresh.obs,
        reset=fresh.reset,
        actor_carry=fresh.actor_carry,
        critic_carry=fresh.critic_carry,
        noise_state=fresh.noise_state,
        window=fresh.window,
        episode_return=fresh.episode_return,
    )
    # The zeroed window must re-fill with real steps before any sequence is
    # emitted, or zero-padded garbage would enter replay on the first
    # train_phase (which emits unconditionally).  collect_phase steps the
    # envs without emitting — exactly the initial warm-up, replayed here.
    for _ in range(trainer.window_fill_phases):
        state = trainer.collect_phase(state)
    return state
