"""Experience-quality plane (ISSUE 18): read the run as an RL experiment.

Every earlier plane watches the *system* — bytes, traces, verdicts,
compiles/HBM/MFU.  None watches the *algorithm*: an Ape-X/R2D2-style
decoupled fleet (PAPERS.md 1803.00933) can be green on every scrape while
training on stale, low-diversity experience, which is exactly the failure
mode a shared replay service must surface (PAPERS.md 2110.13506).  This
module is the one registration point for the ``r2d2dpg_quality_*`` family
plus the pure math the assembly sites fold through:

- **policy lag** — ``learner_version - behavior_version`` per trained
  sequence, from provenance stamped at staging (``StagedSequences
  .behavior_version``) and carried through the wire, the arena meta
  buffer, and the shard slot arrays.
- **replay age at train** — phases since collect (``collect_id``
  provenance vs the trainer's phase clock; the in-graph path rides the
  arena's ``meta`` stamp in learner-step units).
- **ESS/B fraction** — effective sample size of the drawn sampling
  distribution, ``(sum w)^2 / (B * sum w^2)`` with ``w = 1/p`` over the
  drawn probs: 1.0 = uniform draw, ``1/B`` = one slot dominating
  (priority collapse).
- **IS-weight saturation** — fraction of the batch sitting at the
  normalized importance-weight ceiling (weights are max-normalized, so
  the ceiling is 1.0).
- **per-actor trained-seqs** — ``actor=`` labelled counters keyed on the
  HELLO-authenticated identity, NEVER a payload-carried id (the PR 6
  TELEM posture): sigma-ladder coverage / Ape-X lane health.
- **evicted-before-ever-sampled** — per-shard counters + fraction: a ring
  recycling experience the learner never looked at.

ZERO new device fetches: every fold site is host-side numpy where the
batch is already assembled (sampler pull loop, fleet drain) or a scalar
riding the log cadence's existing batched ``device_get`` (phase-locked
in-graph metrics -> ``publish_scalars``).

Absent provenance (old-schema wire frames, pre-plane checkpoints) is the
sentinel ``PROVENANCE_ABSENT`` and DISARMS the lag/age folds — labelled
cells are only created when real samples arrive, which is what lets the
``obs/health.py`` quality rules stay absence-disarmed.

``METRIC_NAMES`` enumerates the whole family; ``scripts/lint_obs.sh``
holds every name to the ``r2d2dpg_<subsystem>_<metric>`` scheme and
refuses a registration that skips the enumeration (the device-plane
contract, ISSUE 14).  See docs/OBSERVABILITY.md "Experience-quality
plane".
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

# Sentinel for "no provenance": old-schema frames decode to this, and the
# arena/shard meta buffers initialize to it.  Folds mask it out, so a
# mixed fleet (old actors + new learner) degrades to fewer samples, never
# to a refused frame or a fake lag of ``version - (-1)``.
PROVENANCE_ABSENT = -1

# The family contract: every r2d2dpg_quality_* registration in this
# module MUST appear here (lint_obs.sh refuses otherwise), and every name
# here must pass the documented naming scheme.
METRIC_NAMES = (
    "r2d2dpg_quality_policy_lag",
    "r2d2dpg_quality_replay_age",
    "r2d2dpg_quality_ess_frac",
    "r2d2dpg_quality_is_saturation",
    "r2d2dpg_quality_trained_seqs_total",
    "r2d2dpg_quality_evicted_unsampled_total",
    "r2d2dpg_quality_evicted_unsampled_frac",
)


# --------------------------------------------------------------- pure math
def ess_fraction(probs: np.ndarray) -> float:
    """ESS/B of a drawn batch from its sampling probabilities.

    Importance weights are ``w_i = 1/p_i`` up to a constant (the constant
    cancels): ``ESS/B = (sum w)^2 / (B * sum w^2)`` — 1.0 when the draw
    was uniform over the batch, ``1/B`` when one slot soaked up the whole
    distribution.  NaN-free: empty/invalid input returns 0.0 (callers
    gate on batch presence before arming gauges)."""
    p = np.asarray(probs, np.float64).ravel()
    p = p[np.isfinite(p) & (p > 0.0)]
    if p.size == 0:
        return 0.0
    w = 1.0 / p
    return float((w.sum() ** 2) / (p.size * np.square(w).sum()))


def is_saturation_fraction(
    probs: np.ndarray, occupancy: float, beta: float
) -> float:
    """Fraction of the batch at the normalized IS-weight ceiling.

    Mirrors the trainer's ``importance_weights``: ``w_i = (N p_i)^-beta``
    max-normalized to [0, 1] — the ceiling (1.0) lands on the
    minimum-probability draw(s).  A fraction near 1.0 means beta-annealed
    correction has flattened (weights all equal, e.g. beta ~ 0 or a
    collapsed distribution); computed host-side from the same probs array
    the batch assembly already holds."""
    p = np.asarray(probs, np.float64).ravel()
    p = p[np.isfinite(p) & (p > 0.0)]
    if p.size == 0:
        return 0.0
    w = (max(float(occupancy), 1.0) * p) ** (-float(beta))
    wmax = float(w.max())
    if not np.isfinite(wmax) or wmax <= 0.0:
        return 0.0
    return float(np.mean(w >= wmax * (1.0 - 1e-9)))


def policy_lags(
    learner_version: int, behavior_versions: np.ndarray
) -> np.ndarray:
    """Per-sequence policy lag, provenance-masked.

    Drops ``PROVENANCE_ABSENT`` entries (old-schema frames disarm rather
    than pollute) and clamps at 0 — an actor that raced a param publish
    ahead of the learner's own clock is lag 0, not negative."""
    bv = np.asarray(behavior_versions, np.int64).ravel()
    bv = bv[bv != PROVENANCE_ABSENT]
    if bv.size == 0:
        return np.zeros((0,), np.int64)
    return np.maximum(int(learner_version) - bv, 0)


def replay_ages(phase_now: int, collect_ids: np.ndarray) -> np.ndarray:
    """Per-sequence replay age (phases since collect), provenance-masked.

    ``collect_id`` is the COLLECTOR's phase clock at staging; actor and
    learner phase clocks both count from run start, so the difference is
    the phases-since-collect estimate (exact under ``--actors 0``).
    Clamped at 0: a free-running actor ahead of the learner reads as
    fresh, never negative."""
    ci = np.asarray(collect_ids, np.int64).ravel()
    ci = ci[ci != PROVENANCE_ABSENT]
    if ci.size == 0:
        return np.zeros((0,), np.int64)
    return np.maximum(int(phase_now) - ci, 0)


# ------------------------------------------------------------------ plane
class QualityPlane:
    """The family's registration point + final-stamp aggregates.

    Instruments live in the process registry (idempotent re-registration,
    like every other plane); the plane itself only adds the running
    aggregates ``snapshot_final()`` stamps into ``quality_final.json`` —
    histograms are bounded windows, so the stamp carries full-run counts
    the scrape cannot."""

    def __init__(self, registry=None):
        from r2d2dpg_tpu.obs.registry import get_registry

        reg = registry if registry is not None else get_registry()
        self.lag = reg.histogram(
            "r2d2dpg_quality_policy_lag",
            "per-trained-sequence policy lag "
            "(learner param version - behavior param version)",
        )
        self.age = reg.histogram(
            "r2d2dpg_quality_replay_age",
            "per-trained-sequence replay age at train (phases since "
            "collect; learner steps on the in-graph path)",
        )
        self.ess = reg.gauge(
            "r2d2dpg_quality_ess_frac",
            "ESS/B of the last trained batch's sampling distribution "
            "(1.0 uniform, 1/B collapsed)",
        )
        self.saturation = reg.gauge(
            "r2d2dpg_quality_is_saturation",
            "fraction of the last trained batch at the normalized "
            "IS-weight ceiling",
        )
        self.trained = reg.counter(
            "r2d2dpg_quality_trained_seqs_total",
            "trained sequences by HELLO-authenticated collector identity",
            labelnames=("actor",),
        )
        self.evicted_unsampled = reg.counter(
            "r2d2dpg_quality_evicted_unsampled_total",
            "ring evictions of slots the learner never sampled",
            labelnames=("shard",),
        )
        self.evicted_unsampled_frac = reg.gauge(
            "r2d2dpg_quality_evicted_unsampled_frac",
            "fraction of this shard's evictions that were never sampled",
            labelnames=("shard",),
        )
        self._lock = threading.Lock()
        self._lag_n = 0
        self._lag_sum = 0.0
        self._lag_max = 0.0
        self._age_n = 0
        self._age_sum = 0.0
        self._age_max = 0.0
        self._ess_last: Optional[float] = None
        self._sat_last: Optional[float] = None
        self._trained_by_actor: Dict[str, int] = {}
        self._evicted_by_shard: Dict[str, Dict[str, int]] = {}

    # ------------------------------------------------------------- folds
    def observe_lags(self, lags: np.ndarray) -> None:
        lags = np.asarray(lags, np.float64).ravel()
        if lags.size == 0:
            return
        for v in lags:
            self.lag.observe(float(v))
        with self._lock:
            self._lag_n += int(lags.size)
            self._lag_sum += float(lags.sum())
            self._lag_max = max(self._lag_max, float(lags.max()))

    def observe_ages(self, ages: np.ndarray) -> None:
        ages = np.asarray(ages, np.float64).ravel()
        if ages.size == 0:
            return
        for v in ages:
            self.age.observe(float(v))
        with self._lock:
            self._age_n += int(ages.size)
            self._age_sum += float(ages.sum())
            self._age_max = max(self._age_max, float(ages.max()))

    def observe_probs(
        self, probs: np.ndarray, occupancy: float, beta: float
    ) -> None:
        """Fold one assembled batch's sampling distribution (host-side)."""
        self.publish_scalars(
            ess_frac=ess_fraction(probs),
            is_saturation=is_saturation_fraction(probs, occupancy, beta),
        )

    def publish_scalars(
        self,
        ess_frac: Optional[float] = None,
        is_saturation: Optional[float] = None,
        replay_age_mean: Optional[float] = None,
    ) -> None:
        """Scalar leg for values that rode an EXISTING batched device_get
        (the phase-locked in-graph metrics) — the plane never fetches."""
        if ess_frac is not None and np.isfinite(ess_frac):
            self.ess.set(float(ess_frac))
            with self._lock:
                self._ess_last = float(ess_frac)
        if is_saturation is not None and np.isfinite(is_saturation):
            self.saturation.set(float(is_saturation))
            with self._lock:
                self._sat_last = float(is_saturation)
        if replay_age_mean is not None and np.isfinite(replay_age_mean):
            self.age.observe(float(replay_age_mean))
            with self._lock:
                self._age_n += 1
                self._age_sum += float(replay_age_mean)
                self._age_max = max(self._age_max, float(replay_age_mean))

    def note_trained(self, actor: str, n: int) -> None:
        """``actor`` MUST be the HELLO-authenticated identity (ingest
        overwrites any payload-carried id before the msg reaches a fold
        site; shard slots stamp the authenticated code at add)."""
        if n <= 0:
            return
        self.trained.labels(actor=str(actor)).inc(float(n))
        with self._lock:
            key = str(actor)
            self._trained_by_actor[key] = (
                self._trained_by_actor.get(key, 0) + int(n)
            )

    def note_evictions(
        self, shard: int, evicted: int, unsampled: int
    ) -> None:
        """One shard add's eviction verdict: ``evicted`` filled slots
        overwritten, ``unsampled`` of them never sampled."""
        if evicted <= 0:
            return
        key = str(shard)
        if unsampled > 0:
            self.evicted_unsampled.labels(shard=key).inc(float(unsampled))
        with self._lock:
            rec = self._evicted_by_shard.setdefault(
                key, {"evicted": 0, "unsampled": 0}
            )
            rec["evicted"] += int(evicted)
            rec["unsampled"] += int(unsampled)
            frac = rec["unsampled"] / max(rec["evicted"], 1)
        self.evicted_unsampled_frac.labels(shard=key).set(frac)

    # ------------------------------------------------------------- stamp
    def snapshot_final(self) -> dict:
        """Full-run aggregates for ``quality_final.json`` (histogram
        windows are bounded; this stamp is not)."""
        with self._lock:
            lag_count, lag_total, lag_p50, lag_p99 = self.lag.snapshot()
            age_count, age_total, age_p50, age_p99 = self.age.snapshot()
            return {
                "policy_lag": {
                    "count": self._lag_n,
                    "mean": self._lag_sum / max(self._lag_n, 1),
                    "max": self._lag_max,
                    "window_p50": lag_p50,
                    "window_p99": lag_p99,
                },
                "replay_age": {
                    "count": self._age_n,
                    "mean": self._age_sum / max(self._age_n, 1),
                    "max": self._age_max,
                    "window_p50": age_p50,
                    "window_p99": age_p99,
                },
                "ess_frac": self._ess_last,
                "is_saturation": self._sat_last,
                "trained_seqs_by_actor": dict(self._trained_by_actor),
                "evictions_by_shard": {
                    k: dict(v) for k, v in self._evicted_by_shard.items()
                },
            }


def quality_stats_columns() -> Dict[str, float]:
    """Flat quality columns for learner ``stats()`` dicts — the bench
    fleet/sampler legs' algorithm-health read.  ``-1`` marks a signal
    that never armed this run (absence, not a measured zero), so a bench
    table distinguishes "no provenance" from "perfectly fresh"."""
    q = get_quality_plane().snapshot_final()
    lag, age = q["policy_lag"], q["replay_age"]
    return {
        "quality_lag_mean": lag["mean"] if lag["count"] else -1.0,
        "quality_lag_p99": lag["window_p99"] if lag["count"] else -1.0,
        "quality_replay_age_mean": age["mean"] if age["count"] else -1.0,
        "quality_ess_frac": (
            q["ess_frac"] if q["ess_frac"] is not None else -1.0
        ),
        "quality_is_saturation": (
            q["is_saturation"] if q["is_saturation"] is not None else -1.0
        ),
    }


_lock = threading.Lock()
_plane: Optional[QualityPlane] = None


def get_quality_plane() -> QualityPlane:
    """THE process quality plane (instruments in the process registry)."""
    global _plane
    with _lock:
        if _plane is None:
            _plane = QualityPlane()
        return _plane


def reset_quality_plane() -> None:
    """Drop the singleton (tests; pairs with registry clears)."""
    global _plane
    with _lock:
        _plane = None
