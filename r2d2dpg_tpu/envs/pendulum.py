"""Pure-JAX Pendulum-v1 (gymnasium classic-control dynamics).

BASELINE configs #1-2 run on Pendulum; implementing the ~40-LoC dynamics in
JAX keeps the whole loop one jit graph from day one (SURVEY.md §7 step 3).
Dynamics match gymnasium's ``PendulumEnv`` (g=10, m=1, l=1, dt=0.05, torque
in [-2, 2], reward = -(theta^2 + 0.1*thdot^2 + 0.001*u^2), 200-step episodes,
time-limit truncation only — never termination, so ``discount`` stays 1 and
the step carrying ``reset=1`` marks a truncation boundary: the learner's
n-step targets shorten their horizon there and bootstrap at the last stored
pre-limit state (see ``ops.returns.n_step_targets``).

Envs take canonical actions in [-1, 1] (the tanh policy range) and rescale
internally; ``spec`` records the true torque range.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from r2d2dpg_tpu.envs.core import EnvSpec, TimeStep


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PendulumState:
    theta: jnp.ndarray
    thdot: jnp.ndarray
    t: jnp.ndarray  # step count within the episode


def _angle_normalize(x):
    return ((x + jnp.pi) % (2.0 * jnp.pi)) - jnp.pi


class Pendulum:
    """Functional Pendulum-v1. All methods are pure; vmap/scan freely."""

    MAX_TORQUE = 2.0
    MAX_SPEED = 8.0
    DT = 0.05
    G = 10.0

    def __init__(self, episode_length: int = 200):
        self.spec = EnvSpec(
            name="Pendulum-v1",
            obs_shape=(3,),
            action_dim=1,
            action_min=-self.MAX_TORQUE,
            action_max=self.MAX_TORQUE,
            episode_length=episode_length,
        )

    def _obs(self, s: PendulumState) -> jnp.ndarray:
        return jnp.stack([jnp.cos(s.theta), jnp.sin(s.theta), s.thdot], axis=-1)

    def _init_state(self, key: jax.Array) -> PendulumState:
        k1, k2 = jax.random.split(key)
        return PendulumState(
            theta=jax.random.uniform(k1, (), minval=-jnp.pi, maxval=jnp.pi),
            thdot=jax.random.uniform(k2, (), minval=-1.0, maxval=1.0),
            t=jnp.zeros((), jnp.int32),
        )

    def reset(self, key: jax.Array) -> Tuple[PendulumState, TimeStep]:
        s = self._init_state(key)
        ts = TimeStep(
            obs=self._obs(s),
            reward=jnp.zeros(()),
            discount=jnp.ones(()),
            reset=jnp.ones(()),
        )
        return s, ts

    def step(
        self, state: PendulumState, action: jnp.ndarray, key: jax.Array
    ) -> Tuple[PendulumState, TimeStep]:
        u = jnp.clip(action[..., 0], -1.0, 1.0) * self.MAX_TORQUE
        th, thdot = state.theta, state.thdot
        cost = _angle_normalize(th) ** 2 + 0.1 * thdot**2 + 0.001 * u**2

        newthdot = thdot + (
            3.0 * self.G / 2.0 * jnp.sin(th) + 3.0 * u
        ) * self.DT
        newthdot = jnp.clip(newthdot, -self.MAX_SPEED, self.MAX_SPEED)
        newth = th + newthdot * self.DT
        t = state.t + 1

        done = t >= self.spec.episode_length
        fresh = self._init_state(key)
        nxt = PendulumState(
            theta=jnp.where(done, fresh.theta, newth),
            thdot=jnp.where(done, fresh.thdot, newthdot),
            t=jnp.where(done, fresh.t, t),
        )
        ts = TimeStep(
            obs=self._obs(nxt),
            reward=-cost,
            discount=jnp.ones(()),  # truncation, not termination
            reset=done.astype(jnp.float32),
        )
        return nxt, ts
