"""Environments (SURVEY.md §2.6): pure-JAX on-device + host-callback pools."""

import os

# dm_control chooses its GL backend once, at import time.  Any entry point
# in this package may be the first to import dm_control (env construction,
# the native pool's asset lookup, tests in any order), so pin the headless
# EGL backend here — before a pixels config needs to render — unless the
# user chose one explicitly.
os.environ.setdefault("MUJOCO_GL", "egl")

from r2d2dpg_tpu.envs.core import Environment, EnvSpec, EnvState, TimeStep
from r2d2dpg_tpu.envs.dmc_host import DMCHostEnv
from r2d2dpg_tpu.envs.pendulum import Pendulum

__all__ = ["DMCHostEnv", "Environment", "EnvSpec", "EnvState", "Pendulum", "TimeStep"]
