"""Standalone crash-tolerant replay shard tier (ISSUE 12): supervised
shard processes, quota renormalization on shard loss, epoch-fenced
rejoin (fleet/shard.py).

Anchors ``scripts/lib_gate.sh shard_gate`` enforces before blessing
``--shard-procs N`` evidence dirs:

- **determinism** — the loopback-vs-out-of-process boundary is layout,
  never semantics: a BATCH through a REAL socket decodes bit-identically
  to the in-learner loopback roundtrip on the f32 lane (plus the
  ``--shard-procs 0`` off-setting riding the sampler CLI anchor in
  tests/test_sampler.py).
- **kill_shard** — the non-slow chaos e2e: 2 actors x 2 shard procs,
  ``kill_shard`` mid-run -> the run completes, counters stay monotone,
  quotas renormalize to the surviving shard, the restarted shard rejoins
  under a bumped epoch and serves traffic, and stale-epoch PRIO frames
  are ignored with a flight event; ``stall_shard`` pins zero sheds and
  zero false reaps through the stall.
"""

import glob
import json
import re
import socket
import threading
import time

import numpy as np
import pytest

from r2d2dpg_tpu import obs
from r2d2dpg_tpu.configs import PENDULUM_TINY
from r2d2dpg_tpu.fleet import chaos as fleet_chaos
from r2d2dpg_tpu.fleet import transport, wire
from r2d2dpg_tpu.fleet.shard import (
    RemoteShard,
    RemoteShardSet,
    ShardProcTier,
    ShardServer,
    ShardUnavailableError,
)
from r2d2dpg_tpu.fleet.supervisor import SupervisorConfig
from r2d2dpg_tpu.obs import get_flight_recorder
from r2d2dpg_tpu.obs import registry as obs_registry
from r2d2dpg_tpu.obs.trace import SHARD_HOPS
from r2d2dpg_tpu.replay.arena import SequenceBatch, StagedSequences
from r2d2dpg_tpu.replay.sharded import ReplayShard

pytestmark = pytest.mark.shard


def _np_staged(b=3, l=3, prios=(1.0, 2.0, 3.0), seed=1):
    rng = np.random.default_rng(seed)
    return StagedSequences(
        seq=SequenceBatch(
            obs=rng.normal(size=(b, l, 3)).astype(np.float32),
            action=rng.normal(size=(b, l, 1)).astype(np.float32),
            reward=rng.normal(size=(b, l)).astype(np.float32),
            discount=np.ones((b, l), np.float32),
            reset=np.zeros((b, l), np.float32),
            carries={},
        ),
        priorities=(
            None if prios is None else np.asarray(prios, np.float64)
        ),
    )


def _server(shard_id=0, epoch=1, capacity=8, auth=None, chaos=None):
    return ShardServer(
        ReplayShard(capacity, alpha=1.0, shard_id=shard_id),
        epoch=epoch,
        seed=0,
        auth_token=auth,
        chaos=chaos,
    ).start()


def _client(srv, auth=None, **kw):
    return RemoteShard(
        srv.shard.shard_id,
        lambda: srv.address,
        wire_config=wire.WireConfig(),
        auth_token=auth,
        max_frame_bytes=transport.MAX_FRAME_BYTES,
        read_deadline_s=30.0,
        **kw,
    )


# ------------------------------------------------------- determinism anchor
def test_socket_vs_loopback_batch_determinism_bitwise():
    """The shard_gate anchor: the SAME ShardSample through (a) the
    in-learner loopback pack/unpack and (b) a REAL ShardServer socket
    exchange decodes bit-identically on the f32 lane — moving a shard
    out of process is layout, never semantics."""
    staged = _np_staged(b=4, prios=(1.0, 2.0, 3.0, 4.0))
    srv = _server(capacity=8)
    client = _client(srv)
    try:
        # Seed the remote shard, then mirror its exact ring state locally.
        client.forward_seqs(staged)
        local = ReplayShard(8, alpha=1.0, shard_id=0)
        local.add(staged.seq, staged.priorities)
        # Remote draw (real socket), then replay the identical draw
        # locally: the shard process seeds its rng (seed, shard, epoch).
        resp = client.sample(5, req_id=1)
        rng = np.random.default_rng((0, 0, 1))
        s = local.sample(5, rng)
        packer = wire.TreePacker(wire.WireConfig())
        unpacker = wire.TreeUnpacker()
        loop = wire.unpack_shard_batch(
            unpacker.unpack(
                b"".join(
                    bytes(p)
                    for p in wire.pack_shard_batch(
                        packer,
                        req_id=1,
                        shard=0,
                        staged=StagedSequences(seq=s.seq, priorities=None),
                        slots=s.slots,
                        gens=s.gens,
                        probs=s.probs,
                        priority_sum=local.scaled_sum(),
                        occupancy=local.occupancy(),
                        epoch=1,
                    )
                )
            )
        )
        np.testing.assert_array_equal(resp["slots"], loop["slots"])
        np.testing.assert_array_equal(resp["gens"], loop["gens"])
        np.testing.assert_array_equal(resp["probs"], loop["probs"])
        for a, b in zip(
            [resp["staged"].seq.obs, resp["staged"].seq.reward],
            [loop["staged"].seq.obs, loop["staged"].seq.reward],
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert resp["epoch"] == loop["epoch"] == 1
        assert resp["priority_sum"] == loop["priority_sum"]
    finally:
        client.close()
        srv.stop()


# ----------------------------------------------------------- shard protocol
def test_shard_server_auth_epoch_and_stale_prio_fence():
    """Protocol + fences on one in-process server: HELLO auth refusal,
    the SEQS ack advertisement, BATCH epoch stamping, and the
    authoritative shard-side stale-epoch PRIO ignore (applied=0 + flight
    event + counter) that protects a restarted ring from its
    predecessor's verdicts."""
    srv = _server(shard_id=3, epoch=7, auth="sekrit")
    n0 = len(get_flight_recorder().events())
    try:
        # Wrong token: refused at the door.
        bad = _client(srv, auth="wrong")
        with pytest.raises(RuntimeError, match="refused"):
            bad.forward_seqs(_np_staged())
        bad.close()
        client = _client(srv, auth="sekrit")
        ack = client.forward_seqs(_np_staged(prios=(1.0, 2.0, 4.0)))
        assert ack["code"] == "ok" and ack["epoch"] == 7
        assert ack["occupancy"] == 3 and ack["scaled_sum"] == 7.0
        assert ack["priority_sum"] == 7.0 and ack["evictions"] == 0
        assert client.epoch == 7 and client.occupancy == 3
        resp = client.sample(2, req_id=5)
        assert resp["epoch"] == 7 and resp["req_id"] == 5
        # Fresh-epoch write-back applies; stale-epoch is IGNORED loudly.
        ok = client.write_back(
            resp["slots"], resp["gens"],
            np.full(2, 9.0, np.float32), epoch=7,
        )
        assert ok["applied"] == 2 and not ok["stale"]
        stale = client.write_back(
            resp["slots"], resp["gens"],
            np.full(2, 1.0, np.float32), epoch=6,
        )
        assert stale["applied"] == 0 and stale["stale"]
        # A SAMPLE_REQ at a live-but-EMPTY shard answers with an
        # empty-marked advert ack (None here), never a torn connection —
        # a stale quota weight meeting a fresh ring must not read as a
        # dead process (the connection stays usable).
        empty_srv = _server(shard_id=9, epoch=1)
        empty_client = _client(empty_srv)
        try:
            assert empty_client.sample(3, req_id=1) is None
            assert empty_client.scaled_sum == 0.0
            empty_client.forward_seqs(_np_staged())
            # The SAMPLE leg survived the empty answer: the very same
            # connection now serves a real BATCH.
            assert empty_client.sample(2, req_id=2) is not None
        finally:
            empty_client.close()
            empty_srv.stop()
        evs = [
            e for e in get_flight_recorder().events()[n0:]
            if e["kind"] == "stale_epoch_prio_ignored"
        ]
        assert evs and evs[-1]["got_epoch"] == 6 and evs[-1]["epoch"] == 7
        client.close()
    finally:
        srv.stop()


def test_remote_set_reroute_renorm_and_epoch_fenced_rejoin():
    """The degradation half without processes: kill server 0 (stop =
    dial refused), the set marks it dead — quota weights zero, routing
    falls to the survivor in ring order, accounting banks regardless —
    then a NEW incarnation (bumped epoch) rejoins: routing returns home,
    the stale advert is zeroed (an empty restarted ring must not inherit
    the dead ring's sums), and the learner-side epoch fence drops
    write-backs against the old incarnation."""
    addrs = {}
    srv0 = _server(shard_id=0, epoch=1)
    srv1 = _server(shard_id=1, epoch=1)
    addrs[0], addrs[1] = srv0.address, srv1.address
    ss = RemoteShardSet(
        2,
        lambda sid: addrs[sid],
        wire_config=wire.WireConfig(),
        rejoin_interval_s=0.0,
    )
    n0 = len(get_flight_recorder().events())
    try:
        for sid in (0, 1):
            ss.add(sid, {"staged": _np_staged(), "env_steps_delta": 9.0})
        assert ss.occupancy_total() == 6
        np.testing.assert_allclose(ss.scaled_sums(), [6.0, 6.0])
        resp = ss.shards[0].sample(2, req_id=1)
        handles_epoch = resp["epoch"]
        # --- death: server 0 gone, dial refused.
        srv0.stop()
        with pytest.raises(ShardUnavailableError):
            ss.shards[0].sample(1, req_id=2)
        ss._mark_dead(0, "drill")
        np.testing.assert_allclose(ss.scaled_sums(), [0.0, 6.0])
        assert ss.route(0) == 1  # home shard dead -> survivor, in ring order
        # adds (home 0) re-route; the accounting banks either way.
        ss.add(0, {"staged": _np_staged(), "env_steps_delta": 9.0,
                   "actor_id": 0})
        assert ss.shards[1].occupancy == 6  # ring of 8 holds both adds
        assert ss.pop_stats()["env_steps_delta"] == 27.0
        # --- rejoin: new incarnation, bumped epoch, empty ring.
        srv0b = _server(shard_id=0, epoch=2, capacity=8)
        addrs[0] = srv0b.address
        ss.maybe_rejoin()
        assert ss.shards[0].alive and ss.shards[0].epoch == 2
        assert ss.route(0) == 0  # traffic lands back home
        # The rejoined ring is EMPTY: its weight stays 0 (the dead ring's
        # sums are never inherited); the survivor holds both adds' sums.
        np.testing.assert_allclose(ss.scaled_sums(), [0.0, 12.0])
        kinds = [e["kind"] for e in get_flight_recorder().events()[n0:]]
        assert "shard_dead" in kinds and "shard_rejoin" in kinds
        # Learner-side epoch fence: handles from incarnation 1 never even
        # cross the wire (fleet/sampler.py groups per (shard, epoch)).
        assert handles_epoch == 1 != ss.shards[0].epoch
        srv0b.stop()
    finally:
        ss.close()
        srv1.stop()


def test_shard_chaos_stall_gate_arms_and_waits():
    fs = fleet_chaos.parse_chaos_spec("stall_shard@p2:0.3s")
    target = fleet_chaos.fault_target(fs[0], seed=0, num_actors=2)
    chaos = fleet_chaos.ShardChaos(
        fs, seed=0, num_shard_procs=2, proc_index=target
    )
    chaos.on_seqs_frame()
    t0 = time.monotonic()
    chaos.gate()
    assert time.monotonic() - t0 < 0.05  # phase 1: not due yet
    chaos.on_seqs_frame()  # phase 2: arms the stall
    t0 = time.monotonic()
    chaos.gate()
    assert time.monotonic() - t0 >= 0.25
    other = fleet_chaos.ShardChaos(
        fs, seed=0, num_shard_procs=2, proc_index=1 - target
    )
    other.on_seqs_frame()
    other.on_seqs_frame()
    t0 = time.monotonic()
    other.gate()
    assert time.monotonic() - t0 < 0.05  # not its fault


# ----------------------------------------------------- shard TELEM (ISSUE 13)
@pytest.fixture
def fresh_obs(monkeypatch):
    """A fresh process registry + remote mirror for the duration of one
    test: the TELEM fold and the /health rules read process singletons,
    and an earlier test's armed staleness set_fn (its server long closed)
    would otherwise fire the telem_stale rule forever."""
    monkeypatch.setattr(obs_registry, "_REGISTRY", obs_registry.Registry())
    monkeypatch.setattr(obs_registry, "_MIRROR", obs_registry.RemoteMirror())
    return obs_registry.get_registry(), obs_registry.get_remote_mirror()


def test_shard_telem_folds_with_staleness_and_epoch_rearm(fresh_obs):
    """Leg 1 of the health plane: a shard proc's TELEM push lands in the
    learner's RemoteMirror under shard=/host= labels (idempotently keyed
    — a respawned incarnation UPDATES its slot), the per-shard staleness
    gauge grows while the shard is silent, and an epoch-bumped rejoin
    RESTARTS the clock so a fresh incarnation's absorb phase never reads
    as wedged (the actor warm-up cadence fix, carried to the shard
    tier)."""
    reg, mirror = fresh_obs
    srv = ShardServer(
        ReplayShard(8, alpha=1.0, shard_id=0),
        epoch=1, seed=0, telem_every=0.01,
    ).start()
    addrs = {0: srv.address}
    ss = RemoteShardSet(
        1, lambda sid: addrs[sid],
        wire_config=wire.WireConfig(), rejoin_interval_s=0.0,
    )
    try:
        # First exchange: HELLO arms the staleness clock, and the forced
        # post-HELLO TELEM push folds on this exchange's reply read.
        ss.add(0, {"staged": _np_staged()})
        sources = mirror.sources()
        assert len(sources) == 1
        key, labels, snap = sources[0]
        assert key == "shard:0"
        assert labels["shard"] == "0" and labels["host"]
        # The pushes ride AFTER replies, so a snapshot folds on the NEXT
        # exchange's read — and the cadence gate makes WHICH rider
        # carries a given snapshot scheduling-dependent: poll adds until
        # a fold with real occupancy lands instead of assuming the
        # schedule (a descheduled handler shifts it by one exchange).
        occ = []
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            time.sleep(0.03)  # past the 0.01 s cadence: the rider is due
            ss.add(0, {"staged": _np_staged()})
            snap = mirror.sources()[0][2]
            occ = snap.get("r2d2dpg_replay_shard_occupancy", {}).get(
                "samples", []
            )
            if occ and occ[0]["value"] >= 3.0:
                break
        assert occ and occ[0]["value"] >= 3.0
        assert occ[0]["labels"]["shard"] == "0"
        # The fold's own accounting must NOT ride the push (echo
        # suppression): the learner's staleness gauge stays live-only.
        assert "r2d2dpg_shard_telem_staleness_seconds" not in snap
        # Same echo class, whole learner-owned FAMILIES: with a shared
        # registry (this very test) the proc-wide slice would push a
        # frozen copy of e.g. the learner's wait histogram back under
        # shard= attribution — and /health's learner_starving would keep
        # judging the dead mirrored sample after the live one recovered.
        reg.histogram("r2d2dpg_sampler_wait_seconds").observe(99.0)
        reg.gauge("r2d2dpg_health_status").set(1.0)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            time.sleep(0.03)
            ss.add(0, {"staged": _np_staged()})
            snap = mirror.sources()[0][2]
            if "r2d2dpg_replay_shard_occupancy" in snap:
                break
        assert "r2d2dpg_sampler_wait_seconds" not in snap
        assert "r2d2dpg_health_status" not in snap
        stale = reg.get("r2d2dpg_shard_telem_staleness_seconds").labels(
            shard="0"
        )
        # Silence makes the gauge GROW — a wedged shard is visibly
        # stale, never a silently flat mirrored series.
        s0 = stale.value
        time.sleep(0.3)
        assert stale.value >= s0 + 0.25
        # --- respawn under a bumped epoch: new server, same shard id.
        srv2 = ShardServer(
            ReplayShard(8, alpha=1.0, shard_id=0),
            epoch=2, seed=0, telem_every=0.01,
        ).start()
        addrs[0] = srv2.address
        srv.stop()
        ss.add(0, {"staged": _np_staged()})  # torn conn -> re-dial -> HELLO
        assert ss.shards[0].epoch == 2
        # The incarnation's HELLO re-armed the clock: staleness restarted
        # well below the dead incarnation's accumulated silence.
        assert stale.value < 0.25
        assert len(mirror.sources()) == 1  # same key, updated in place
        srv2.stop()
    finally:
        ss.close()
        srv.stop()


def test_shard_telem_malformed_dropped_without_connection_loss(fresh_obs):
    """A malformed TELEM frame on a shard leg costs one flight event,
    never the connection: the tolerant reply read keeps the exchange
    alive, and a payload that contradicts its connection's shard id is
    malformed by definition (identity comes from the socket, so a
    confused frame cannot relabel another shard's series)."""
    reg, mirror = fresh_obs
    ss = RemoteShardSet(
        1, lambda sid: None, wire_config=wire.WireConfig()
    )
    rs = ss.shards[0]
    a, b = socket.socketpair()
    n0 = len(get_flight_recorder().events())
    try:
        a.settimeout(10)
        # Three TELEM pushes ahead of the real reply: garbage, a wrong
        # shard claim, then a WELL-FORMED one; the ACK follows.
        transport.send_frame(
            b, transport.K_TELEM, transport.pack_obj(["not", "a", "dict"])
        )
        transport.send_frame(
            b,
            transport.K_TELEM,
            transport.pack_obj({"shard": 5, "snapshot": {}}),
        )
        transport.send_frame(
            b,
            transport.K_TELEM,
            transport.pack_obj(
                {"shard": 0, "epoch": 1, "host": "h", "snapshot": {}}
            ),
        )
        transport.send_frame(
            b, transport.K_ACK, transport.pack_obj({"code": "ok"})
        )
        kind, _payload = rs._recv("ingest", a)
        assert kind == transport.K_ACK  # the reply survived all three
        drops = [
            e
            for e in get_flight_recorder().events()[n0:]
            if e["kind"] == "shard_telem_malformed"
        ]
        assert len(drops) == 2  # one per malformed frame, none for the good
        assert [s[0] for s in mirror.sources()] == ["shard:0"]
    finally:
        a.close()
        b.close()
        ss.close()


def test_stall_shard_staleness_health_degraded_then_ok(fresh_obs):
    """The stall drill as the /health fixture: mid-``stall_shard`` the
    shard answers nothing, so its TELEM staleness crosses the threshold
    and ``GET /health`` reads ``degraded`` with a ``telem_stale`` finding
    naming the shard; once the gate lifts and the next exchange folds the
    buffered push, the verdict recovers to ``ok`` — and both transitions
    are durable flight events."""
    reg, mirror = fresh_obs
    faults = fleet_chaos.parse_chaos_spec("stall_shard@p2:1.2s")
    chaos = fleet_chaos.ShardChaos(
        faults, seed=0, num_shard_procs=1, proc_index=0
    )
    srv = ShardServer(
        ReplayShard(16, alpha=1.0, shard_id=0),
        epoch=1, seed=0, chaos=chaos, telem_every=0.01,
    ).start()
    addrs = {0: srv.address}
    ss = RemoteShardSet(
        1, lambda sid: addrs[sid],
        wire_config=wire.WireConfig(), rejoin_interval_s=0.0,
    )
    engine = obs.HealthEngine(
        obs.HealthConfig(
            telem_stale_after_s=0.3, learner_wait_p99_s=1e9,
            eviction_churn_per_s=1e18,
        ),
        registry=reg,
        mirror=mirror,
    )
    n0 = len(get_flight_recorder().events())
    try:
        ss.add(0, {"staged": _np_staged()})  # frame 1: TELEM armed + folded
        assert engine.evaluate()["verdict"] == "ok"
        # Frame 2 arms the stall: the gated ack parks this add for the
        # stall's duration, during which the shard pushes nothing.
        blocked = threading.Thread(
            target=lambda: ss.add(0, {"staged": _np_staged()}), daemon=True
        )
        t_stall = time.monotonic()
        blocked.start()
        time.sleep(0.7)  # mid-stall, well past the 0.3 s threshold
        res = engine.evaluate()
        stale = [f for f in res["findings"] if f["rule"] == "telem_stale"]
        assert res["verdict"] == "degraded"
        assert stale and "shard 0" in stale[0]["detail"]
        blocked.join(timeout=10)
        assert time.monotonic() - t_stall >= 1.0  # the gate really held
        # Recovery: the post-stall ack's TELEM rider folds on the next
        # exchange, resetting the staleness clock.
        ss.add(0, {"staged": _np_staged()})
        res = engine.evaluate()
        assert res["verdict"] == "ok"
        verdicts = [
            (e.get("previous"), e["verdict"])
            for e in get_flight_recorder().events()[n0:]
            if e["kind"] == "health_verdict"
        ]
        assert (None, "ok") in verdicts  # armed
        assert ("ok", "degraded") in verdicts  # degraded during the stall
        assert ("degraded", "ok") in verdicts  # recovered after it
        assert reg.get("r2d2dpg_health_status").value == 0.0
    finally:
        ss.close()
        srv.stop()


# --------------------------------------------------------------- chaos e2e
@pytest.mark.chaos
def test_chaos_kill_shard_stall_and_partition_e2e(tmp_path, fresh_obs):
    """The acceptance drill (non-slow, 2 actors x 2 REAL shard procs):
    ``stall_shard`` + ``partition_shard`` + ``kill_shard`` in one run —
    the run completes its full phase schedule, counters stay monotone,
    zero sheds and zero false reaps through the stall, the dead shard's
    quota renormalizes to the survivor, and after the supervisor's
    backoff restart the shard rejoins EMPTY under a bumped epoch, serves
    traffic on both legs, and fences stale-epoch write-backs.

    The ISSUE 13 health-plane half rides the same run: every shard's
    ring series + staleness gauge in ONE merged scrape (shard-proc TELEM
    at 0.05 s cadence), ``/health`` degraded with a ``shards_down``
    finding during the kill window and ``ok`` after the rejoin, and the
    trace plane (rate 1.0) yielding complete learner->shard->learner
    chains fused into one timeline by ``obs.flight merge --trace-out``."""
    import queue as _q

    from r2d2dpg_tpu.fleet import FleetConfig, SamplerLearner
    from r2d2dpg_tpu.fleet.transport import (
        K_ACK,
        K_HELLO,
        K_SEQS,
        pack_hello,
        recv_frame,
        send_frame,
        send_frame_parts,
    )
    from r2d2dpg_tpu.training.pipeline import split_state

    SEED = 2  # pinned: stall->proc0, partition->shard1, kill->proc0
    N_TRAIN = 6
    spec = "stall_shard@p1:0.6s,partition_shard@p1,kill_shard@p2"
    faults = fleet_chaos.parse_chaos_spec(spec)
    assert fleet_chaos.fault_target(faults[2], SEED, 2) == 0  # kill proc 0
    assert fleet_chaos.fault_target(faults[1], SEED, 2) == 1  # partition 1

    import dataclasses as dc

    import jax

    trainer = PENDULUM_TINY.build()
    state = trainer.init()
    _, lstate = split_state(state)
    # The arena's storage tree IS the staged-batch template (leaves
    # [capacity, L, ...]): synthetic actors emit exactly the structure
    # the learn program expects, without paying a collect-program
    # compile this drill does not test.
    template = jax.device_get(lstate.arena.data)

    def synth_staged(rng, b=4):
        data = jax.tree_util.tree_map(
            lambda buf: (
                rng.normal(size=(b,) + np.shape(buf)[1:]).astype(buf.dtype)
                if buf.dtype.kind == "f"
                else np.zeros((b,) + np.shape(buf)[1:], buf.dtype)
            ),
            template,
        )
        data = dc.replace(
            data,
            discount=np.ones_like(data.discount),
            reset=np.zeros_like(data.reset),
        )
        return StagedSequences(
            seq=data, priorities=rng.uniform(0.5, 4.0, size=b)
        )

    tier = ShardProcTier(
        num_shards=2,
        num_procs=2,
        capacity_per_shard=128,
        alpha=trainer.config.priority_alpha,
        prioritized=True,
        dirpath=str(tmp_path / "shards"),
        seed=SEED,
        wire_config=wire.WireConfig(),
        chaos_spec=spec,
        flight_dir=str(tmp_path),
        telem_every=0.05,
        supervisor_config=SupervisorConfig(
            backoff_base_s=0.2, poll_s=0.05
        ),
    )
    learner = SamplerLearner(
        trainer,
        FleetConfig(num_actors=2, idle_timeout_s=60),
        num_shards=2,
        shard_set=tier.shard_set,
    )
    engine = fleet_chaos.ChaosEngine(
        faults, seed=SEED, num_actors=2, server=learner.server,
        shard_tier=tier,
    )
    # The /health verdict engine over the run's registry+mirror: every
    # rule but shards_down disarmed (generous thresholds) so the ONE
    # deterministic degraded window — the kill -> backoff-restart gap —
    # is what the verdict sequence pins.
    health = obs.HealthEngine(
        obs.HealthConfig(
            learner_wait_p99_s=1e9,
            telem_stale_after_s=1e9,
            eviction_churn_per_s=1e18,
            occupancy_skew_min_mean=1e18,
            # ISSUE 18 quality rules disarmed too: this drill churns a
            # tiny ring far faster than its starved learner samples, so
            # untrained_churn would (correctly) stay degraded past the
            # rejoin and blur the one shards_down window under test.
            quality_min_lag_count=1e18,
            quality_ess_floor=0.0,
            quality_churn_min_evictions=1e18,
            quality_actor_skew_min_mean=1e18,
            expected_shard_procs=2,
        ),
        registry=fresh_obs[0],
        mirror=fresh_obs[1],
    )
    health_findings = []

    def phase_hook(p):
        engine.on_phase(p)
        if p == 2:
            # kill_shard just landed on proc 0: the supervisor's backoff
            # (0.2 s) guarantees a down window — catch the shards_down
            # verdict inside it.
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                res = health.evaluate()
                down = [
                    f for f in res["findings"]
                    if f["rule"] == "shards_down"
                ]
                if down:
                    health_findings.append((res["verdict"], down[0]))
                    break
                time.sleep(0.01)

    tier.start()
    address = learner.start()
    stop = threading.Event()

    def actor_loop(actor_id):
        # A wire-real synthetic actor: HELLO + streamed SEQS frames (the
        # collect compile is not what this drill tests); param pushes are
        # read and discarded.
        rng = np.random.default_rng(100 + actor_id)
        try:
            sock = transport.connect(address, read_deadline_s=60)
            packer = wire.TreePacker(wire.WireConfig())
            send_frame(
                sock,
                K_HELLO,
                pack_hello(
                    {
                        "actor_id": actor_id,
                        **wire.negotiation_fields(wire.WireConfig()),
                    }
                ),
            )
            while recv_frame(sock)[0] != K_ACK:
                pass
            phase = 0
            while not stop.is_set():
                send_frame_parts(
                    sock,
                    K_SEQS,
                    packer.pack(
                        {
                            "phase": phase,
                            "param_version": 0,
                            "env_steps_delta": 16.0,
                            "ep_return_sum": -1.0,
                            "ep_count": 1.0,
                            "staged": synth_staged(rng),
                        }
                    ),
                )
                while recv_frame(sock)[0] != K_ACK:
                    pass
                phase += 1
            sock.close()
        except Exception:  # noqa: BLE001 — teardown cuts the socket
            pass

    threads = [
        threading.Thread(target=actor_loop, args=(i,), daemon=True)
        for i in range(2)
    ]
    logged = []
    n0 = len(get_flight_recorder().events())
    s0 = len(get_flight_recorder().spans())
    try:
        for t in threads:
            t.start()
        state = learner.run(
            N_TRAIN,
            state=state,
            log_every=2,
            metrics_fn=lambda p, s: logged.append((p, dict(s))),
            phase_fn=phase_hook,
            trace_sample=1.0,
        )
    finally:
        stop.set()
        learner.close()
        for t in threads:
            t.join(timeout=10)

    # Run completed its exact schedule despite a shard dying mid-run.
    assert int(state.train.step) == N_TRAIN * trainer.config.learner_steps
    stats = learner.stats()
    assert stats["train_phases"] == N_TRAIN
    assert stats["sheds"] == 0  # zero sheds through the stall
    assert stats["shard_deaths"] >= 1
    assert engine.unfired() == ()  # kill + partition both landed
    # Monotone counters through stall, partition, death, re-route.
    env_steps = [s["env_steps"] for _, s in logged]
    assert env_steps == sorted(env_steps) and env_steps[-1] > 0
    evs = get_flight_recorder().events()[n0:]
    kinds = [e["kind"] for e in evs]
    assert "shard_dead" in kinds
    assert "shard_quota_renorm" in kinds  # survivors re-quota'd on death
    # Zero false reaps: nothing declared an actor or shard peer dead.
    assert "peer_dead" not in kinds
    # --- epoch-fenced rejoin: the killed proc's shard comes back under a
    # bumped epoch and serves BOTH legs.
    ss = tier.shard_set
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and not ss.shards[0].alive:
        ss.maybe_rejoin()
        time.sleep(0.05)
    try:
        assert ss.shards[0].alive and ss.shards[0].epoch == 2
        occ_before = ss.shards[0].occupancy
        rng = np.random.default_rng(0)
        ss.add(0, {"staged": synth_staged(rng), "actor_id": 0})
        # Restarted shard serves the ingest leg (occupancy grew by B
        # relative to whatever it re-absorbed since rejoin)...
        assert ss.shards[0].occupancy == occ_before + 4
        # ...and the sampler leg.
        resp = ss.shards[0].sample(2, req_id=99)
        assert resp["epoch"] == 2
        # Stale-epoch PRIO against the new incarnation: ignored loudly.
        stale = ss.shards[0].write_back(
            resp["slots"], resp["gens"], np.ones(2, np.float32), epoch=1
        )
        assert stale["applied"] == 0 and stale["stale"]
        # --- health plane (ISSUE 13): degraded with a shards_down
        # finding during the kill window, ok after the rejoin.
        assert health_findings, "no shards_down verdict in the kill window"
        verdict, finding = health_findings[0]
        assert verdict == "degraded" and finding["value"] == 1.0
        assert health.evaluate()["verdict"] == "ok"
        # --- ONE merged scrape carries every shard's ring series (from
        # the shard procs' TELEM pushes) AND both staleness gauges.
        reg, mirror = fresh_obs
        assert {k for k, _, _ in mirror.sources()} >= {"shard:0", "shard:1"}
        text = obs.render_prometheus(
            obs.merge_remote(reg.snapshot(), mirror.sources())
        )
        for sid in ("0", "1"):
            for metric in (
                "r2d2dpg_replay_shard_occupancy",
                "r2d2dpg_replay_shard_priority_sum",
                "r2d2dpg_replay_shard_evictions_total",
                "r2d2dpg_shard_telem_staleness_seconds",
            ):
                assert re.search(
                    metric + r'\{[^}]*shard="' + sid + '"', text
                ), f"{metric}{{shard={sid}}} missing from the merged scrape"
    finally:
        tier.stop()
    # The shard-side stall drill left durable evidence in its dump, and
    # every scheduled shard-proc fault fired (the unfired contract).
    assert (
        fleet_chaos.shard_faults_unfired(
            faults, str(tmp_path), seed=SEED, num_shard_procs=2
        )
        == ()
    )
    restarts = tier.restarts_total
    assert restarts >= 1  # the supervisor's ladder did the rejoin
    # --- cross-boundary tracing (ISSUE 13 leg 2): every phase was
    # sampled (rate 1.0); the learner chain's contiguous hops sum to its
    # end-to-end within 10%, and the shard procs stamped their own
    # contiguous req_receive -> shard_draw -> batch_encode chains into
    # the SAME trace ids, dumped as trace_shard<i>.jsonl at SIGTERM.
    spans = get_flight_recorder().spans()[s0:]
    by_id = {}
    for s in spans:
        by_id.setdefault(s["trace_id"], {})[s["hop"]] = s
    chains = {
        tid: h
        for tid, h in by_id.items()
        if {"sample_req", "batch_return", "learn"} <= set(h)
    }
    assert len(chains) == N_TRAIN
    for h in chains.values():
        end_to_end = (
            h["learn"]["t_wall"] + h["learn"]["dur_s"]
            - h["sample_req"]["t_wall"]
        )
        total = sum(
            h[k]["dur_s"] for k in ("sample_req", "batch_return", "learn")
        )
        assert abs(total - end_to_end) <= 0.1 * end_to_end
    shard_spans = []
    for path in glob.glob(str(tmp_path / "trace_shard*.jsonl")):
        with open(path) as f:
            for line in f:
                s = json.loads(line)
                s["file"] = path.rsplit("/", 1)[-1]
                shard_spans.append(s)
    shard_chains = {}
    for s in shard_spans:
        shard_chains.setdefault(
            (s["file"], s["trace_id"]), {}
        )[s["hop"]] = s
    complete = {
        k: h
        for k, h in shard_chains.items()
        if set(SHARD_HOPS) <= set(h) and k[1] in chains
    }
    assert complete, "no complete shard-side chain matched a learner trace"
    for (_, tid), h in complete.items():
        # Contiguous by construction, nested inside the learner's
        # sample_req window (both clocks are this host's wall clock).
        assert (
            h["req_receive"]["t_wall"] + h["req_receive"]["dur_s"]
            == h["shard_draw"]["t_wall"]
        )
        assert (
            h["shard_draw"]["t_wall"] + h["shard_draw"]["dur_s"]
            == h["batch_encode"]["t_wall"]
        )
        shard_total = sum(h[k]["dur_s"] for k in SHARD_HOPS)
        assert shard_total <= chains[tid]["sample_req"]["dur_s"] + 0.05
    # --- one fused Perfetto timeline: learner spans (trace.json) +
    # shard-proc span rings, merged by the run-dir CLI.
    from r2d2dpg_tpu.obs.flight import main as flight_main

    get_flight_recorder().dump_trace(str(tmp_path / "trace.json"))
    flight_main(
        ["merge", str(tmp_path), "--trace-out", str(tmp_path / "fused.json")]
    )
    with open(tmp_path / "fused.json") as f:
        fused = json.load(f)
    names = {e["name"] for e in fused["traceEvents"]}
    assert {"sample_req", "batch_return", "learn"} <= names
    assert set(SHARD_HOPS) <= names
    stamped = {
        e["args"].get("file")
        for e in fused["traceEvents"]
        if e["name"] in SHARD_HOPS
    }
    assert all(s and s.startswith("trace_shard") for s in stamped)
