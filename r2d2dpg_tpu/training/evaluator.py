"""Deterministic evaluation rollouts (noise-free policy).

Reference parity: the reference's only "evaluation" is the noisy actors'
episode returns printed to stdout (SURVEY.md §2.7).  Heterogeneous-noise
returns systematically understate the policy (the high-sigma rungs of the
ladder drag the mean down), so the build adds what the BASELINE metric
actually needs — **return of the deterministic policy mu(s)** — measured by
rolling a fleet of eval envs for one episode each with zero exploration
noise.  This is the number the north star (walker-walk >= 900 @ 30 min) is
scored on.

The rollout is one jitted ``lax.scan`` over ``episode_length`` steps (the
whole eval is a single device program; for host-callback envs the physics
crosses to host per step exactly as in training).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from r2d2dpg_tpu.envs.core import Environment
from r2d2dpg_tpu.models.actor_critic import ActorNet


class Evaluator:
    """Rolls ``num_envs`` noise-free episodes and reports the mean return.

    Separate env instance from the training fleet (host-backed pools are
    stateful; sharing one would corrupt training episodes).
    """

    def __init__(self, env: Environment, actor: ActorNet, num_envs: int = 10):
        self.env = env
        # Host-pool envs label their metrics per role: the eval fleet's
        # step latencies must not interleave with the training pool's
        # (docs/OBSERVABILITY.md r2d2dpg_envpool_* role label).
        if hasattr(env, "set_role"):
            env.set_role("eval")
        self.actor = actor
        self.num_envs = num_envs
        self._rollout = jax.jit(self._rollout_impl)

    def _rollout_impl(self, actor_params, key) -> Tuple[jnp.ndarray, jnp.ndarray]:
        env, e = self.env, self.num_envs
        k_reset, k_scan = jax.random.split(key)
        if getattr(env, "batched", False):
            env_state, ts = env.reset(k_reset, e)
        else:
            env_state, ts = jax.vmap(env.reset)(jax.random.split(k_reset, e))

        carry0 = self.actor.initial_carry(e)

        def step(carry, k):
            env_state, obs, reset, a_carry, alive, ep_ret = carry
            action, a_carry = self.actor.apply(actor_params, obs, a_carry, reset)
            if getattr(env, "batched", False):
                env_state, ts = env.step(env_state, action, k)
            else:
                env_state, ts = jax.vmap(env.step)(
                    env_state, action, jax.random.split(k, e)
                )
            # ts.reward belongs to the episode that was live before any
            # auto-reset (envs/core.py TimeStep contract), so credit it while
            # ``alive``; then retire envs whose episode just ended.
            ep_ret = ep_ret + ts.reward * alive
            alive = alive * (1.0 - ts.reset)
            return (env_state, ts.obs, ts.reset, a_carry, alive, ep_ret), ()

        init = (
            env_state,
            ts.obs,
            ts.reset,
            carry0,
            jnp.ones((e,)),
            jnp.zeros((e,)),
        )
        keys = jax.random.split(k_scan, env.spec.episode_length)
        (_, _, _, _, alive, ep_ret), _ = lax.scan(step, init, keys)
        return ep_ret, alive

    def run(self, actor_params, key: jax.Array) -> dict:
        """Mean/min/max deterministic return over the eval fleet."""
        ep_ret, alive = self._rollout(actor_params, key)
        # Episodes still alive after episode_length steps (possible only if
        # the env's true horizon exceeds spec.episode_length) still count:
        # their partial return is a lower bound.
        ep_ret = jax.device_get(ep_ret)
        return {
            "eval_return_mean": float(ep_ret.mean()),
            "eval_return_min": float(ep_ret.min()),
            "eval_return_max": float(ep_ret.max()),
        }
