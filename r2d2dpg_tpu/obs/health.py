"""Topology-wide health verdicts (ISSUE 13 leg 3): is this run healthy NOW?

The obs plane answers "what is the value of X" (registry/exporter) and
"what happened" (flight recorder); nothing answers the operational
question an autoscaler — or an operator mid-incident — actually asks:
*is this composed topology healthy right now, and if not, which part*.
This module is that decision layer: a small rule engine over the signals
already on the single fleet scrape (the Ape-X operator-visibility line,
PAPERS.md 1803.00933 — drive decisions from the ONE /metrics page), with
a machine-readable verdict::

    GET /health  ->  {"verdict": "ok" | "degraded" | "critical",
                      "findings": [{"rule", "severity", "detail",
                                    "value", "threshold"}, ...],
                      "t_wall": ...}

Rules (each one maps a documented failure mode to the gauge that is its
evidence — docs/FLEET.md / docs/REPLAY.md failure matrices):

- ``learner_starving``   learner/sampler wait p99 over threshold: the
  fleet is not feeding the learner (add actors, or a shard is wedged).
- ``telem_stale``        an actor's or standalone shard's TELEM staleness
  gauge over threshold: that process is wedged, partitioned, or dead —
  its mirrored series are holding last values, not reporting.
- ``shard_skew``         one replay shard empty while the tier holds
  real occupancy: routing/quota skew (a rejoined-empty shard absorbing
  is expected and brief; a PERSISTENT zero is a feed problem).
- ``eviction_churn``     ring evictions/s over threshold: experience is
  being recycled before it is sampled — replay is undersized for the
  collection rate (shed actors, or grow capacity).
- ``actors_down``        live supervised actors below the spawn target.
- ``shards_down``        live shard processes below the spawn target
  (``critical`` when zero: sampling is fully degraded).
- ``recompile_churn``    new ``steady_recompile`` sentinel trips inside
  the evaluation window (obs/device.py): a learn/drain program's avals
  re-keyed after warm-up — the silent-compile-stall bug class, live.
  Warm-up compiles never increment the counter (the sentinel arms at
  ``mark_steady``), so the rule is warm-up-exempt by construction, and
  it CLEARS once a full window passes with no new trips.
- ``hbm_pressure``       a device's ``bytes_in_use`` over the headroom
  fraction of its ``bytes_limit``: the next drain width or batch bump
  OOMs.  Backends without allocator limits (CPU fallback) register no
  limit series, so absence of evidence stays non-degrading.
- ``stale_experience``   quality policy-lag p99 over ``--quality-max-lag``
  (obs/quality.py, ISSUE 18): the learner is training on experience
  collected too many param versions ago.  Warm-up exempt via a sample
  floor; absent provenance never arms the histogram, so the rule stays
  disarmed on old-schema fleets.
- ``priority_collapse``  ESS/B of the trained batches under the floor:
  the sampling distribution has collapsed onto a handful of slots (a
  true ESS is always positive, so the never-armed gauge's 0 disarms).
- ``untrained_churn``    a shard's evicted-before-ever-sampled fraction
  over threshold once enough evictions accumulated: the ring is
  recycling experience the learner NEVER looked at — worse than
  eviction_churn, which also counts sampled-then-evicted slots.
- ``actor_skew``         one actor's trained-seqs counter far below the
  fleet mean: a lane of the sigma ladder is not reaching training
  (dead env pool, wedged actor, or routing starvation).
- ``serve_queue_saturated``  a routed serving worker's micro-batch queue
  depth over the saturation fraction of its admission bound: that
  worker is one burst away from shedding.  Warm-up exempt — the queue
  legitimately piles while the worker's first bucket compiles, so the
  rule only judges workers that have served at least one request.
- ``serve_shed_churn``   a serving worker's shed rate (all shed codes)
  over threshold, judged per ``worker=`` label on the eviction_churn
  windowed-rate pattern: sustained shedding on ONE device must name
  that device, not hide behind a fleet-wide average.

The verdict is the max severity across findings; every verdict
TRANSITION lands in the flight ring (``health_verdict`` events), so a
post-mortem shows when the run degraded and when it recovered, and
``r2d2dpg_health_*`` gauges put the verdict itself on the scrape.  This
is precisely the input contract the ROADMAP autoscaler consumes — an
autoscale decision is a planned reaction to a ``/health`` finding —
built as observability first.

Evaluation is pull-time (each ``GET /health`` — or an explicit
``evaluate()``) over ``Registry.snapshot()`` merged with the
``RemoteMirror``: no background thread, no extra device syncs, and a
broken instrument degrades to "signal absent" (rules skip what they
cannot read) rather than taking the endpoint down.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from typing import Dict, List, Optional

from r2d2dpg_tpu.obs.flight import flight_event
from r2d2dpg_tpu.obs.registry import (
    Registry,
    RemoteMirror,
    get_registry,
    merge_remote,
)

VERDICT_OK = "ok"
VERDICT_DEGRADED = "degraded"
VERDICT_CRITICAL = "critical"
_SEVERITY = {VERDICT_OK: 0, VERDICT_DEGRADED: 1, VERDICT_CRITICAL: 2}

# The fixed rule namespace: every rule's firing state is exported as
# r2d2dpg_health_rule_firing{rule=...} including the ZEROS, so a cleared
# finding reads as an explicit 0, never as a vanished series.
RULES = (
    "learner_starving",
    "telem_stale",
    "shard_skew",
    "eviction_churn",
    "actors_down",
    "shards_down",
    "recompile_churn",
    "hbm_pressure",
    "stale_experience",
    "priority_collapse",
    "untrained_churn",
    "actor_skew",
    "serve_queue_saturated",
    "serve_shed_churn",
    # The synthetic finding a raising rule degrades into (never a 500):
    # exported like the real rules so a degraded verdict is always
    # attributable to SOME firing series on the scrape.
    "engine_error",
)


@dataclasses.dataclass
class HealthConfig:
    """Verdict thresholds.  Defaults are deliberately loose (a health
    endpoint that cries wolf on warm-up noise trains operators to ignore
    it); train.py exposes the two an operator actually tunes
    (``--health-wait-p99``, ``--health-stale-after``)."""

    learner_wait_p99_s: float = 0.5
    telem_stale_after_s: float = 10.0
    eviction_churn_per_s: float = 50.0
    # Eviction rate windows shorter than this re-judge the previous full
    # window: FIFO evictions land in whole-batch bursts, and a burst
    # divided by a sub-second poll gap is not a sustained rate.
    eviction_rate_min_dt_s: float = 5.0
    # Skew is only judged once the tier holds real data: a shard at 0
    # while the MEAN occupancy is below this floor is warm-up, not skew
    # (the rejoined-empty-shard absorb phase must not read as degraded —
    # the same fix class as the actor warm-up TELEM cadence).
    occupancy_skew_min_mean: float = 64.0
    expected_actors: int = 0  # 0 = rule disarmed
    expected_shard_procs: int = 0  # 0 = rule disarmed
    # Device plane (obs/device.py).  recompile_churn fires on ANY new
    # steady_recompile inside a window at the 0.0 default — one post-warm
    # re-key is already the bug class the sentinel exists for; polls
    # closer than the min dt re-judge the last full window (the
    # eviction_churn burst guard, same rationale).
    steady_recompiles_per_window: float = 0.0
    recompile_rate_min_dt_s: float = 5.0
    # hbm_pressure: in_use over this fraction of the device's reported
    # bytes_limit reads as "the next allocation bump OOMs".
    hbm_pressure_frac: float = 0.92
    # Staleness gauges arm at HELLO whether or not the peers were told to
    # push TELEM (actor/shard --telem-every rides --obs-fleet): on a run
    # without it every clock grows forever, and firing telem_stale there
    # would stamp every healthy non-obs-fleet run degraded.  train.py
    # sets this from the resolved --obs-fleet; the default keeps the
    # standalone-engine behavior (a gauge that exists is judged).
    telem_expected: bool = True
    # Experience-quality plane (obs/quality.py).  stale_experience judges
    # the policy-lag p99 only after the histogram holds a real sample
    # population: the first drained phases after min_replay legitimately
    # carry warm-up lag (actors filled replay while the learner sat on
    # version 0), and a p99 over a handful of observations is noise.
    quality_max_lag: float = 100.0
    quality_min_lag_count: float = 100.0
    # A true ESS/B is always positive (probs are positive), so 0 means
    # the gauge never armed — the floor only judges armed values.
    quality_ess_floor: float = 0.05
    # untrained_churn arms once a shard has evicted a real population;
    # the fraction alone would fire on the first tiny FIFO batch.
    quality_untrained_frac: float = 0.5
    quality_churn_min_evictions: float = 256.0
    # actor_skew needs >=2 actors with a trained-seqs ladder and a real
    # mean before min/mean is meaningful (the occupancy_skew_min_mean
    # warm-up posture, keyed on trained sequences instead of slots).
    quality_actor_skew_frac: float = 0.1
    quality_actor_skew_min_mean: float = 256.0
    # Serving scale-out plane (serving/router.py, ISSUE 20).  Queue depth
    # is judged per worker only once that worker has served >= 1 request
    # (warm-up exemption: admission legitimately piles while the first
    # bucket compiles); sheds are judged as a per-worker windowed rate
    # with the eviction_churn burst guard.
    serve_queue_saturated_frac: float = 0.9
    serve_shed_per_s: float = 1.0
    serve_shed_rate_min_dt_s: float = 5.0


def _samples(snap: Dict, name: str) -> List[Dict]:
    entry = snap.get(name)
    if not isinstance(entry, dict):
        return []
    samples = entry.get("samples", ())
    return [s for s in samples if isinstance(s, dict)]


def _per_label_max(snap: Dict, name: str, label: str) -> Dict[object, float]:
    """One value per ``label`` from a possibly-duplicated family: a
    series can appear TWICE in a merged snapshot — a local copy and a
    TELEM-mirrored copy share the metric name (deployment, not
    semantics) — so samples dedupe on the label with max() (for monotone
    counters the larger IS the fresher copy; for gauges it errs toward
    the worse reading).  Samples without the label keep their own slots."""
    per_label: Dict[object, float] = {}
    for i, s in enumerate(_samples(snap, name)):
        v = _finite(s.get("value"))
        if v is None:
            continue
        labels = s.get("labels")
        key = (
            labels.get(label)
            if isinstance(labels, dict) and label in labels
            else ("unlabelled", i)
        )
        per_label[key] = max(per_label.get(key, 0.0), v)
    return per_label


def _per_shard_max(snap: Dict, name: str) -> Dict[object, float]:
    """One value per shard — the learner's advert mirror and the shard
    proc's TELEM copy share metric names; see ``_per_label_max``."""
    return _per_label_max(snap, name, "shard")


def _finite(v) -> Optional[float]:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


class HealthEngine:
    """The rule engine behind ``GET /health``.

    ``evaluate()`` is cheap (one registry snapshot + mirror merge) and
    thread-safe; the exporter calls it per request.  State across calls:
    the last verdict (for transition flight events) and the last
    eviction total/timestamp (the churn rule needs a rate, and counters
    only carry totals)."""

    def __init__(
        self,
        config: Optional[HealthConfig] = None,
        *,
        registry: Optional[Registry] = None,
        mirror: Optional[RemoteMirror] = None,
    ):
        self.config = config or HealthConfig()
        self.registry = registry if registry is not None else get_registry()
        self.mirror = mirror
        self._lock = threading.Lock()
        self._last_verdict: Optional[str] = None
        self._evict_last: Optional[tuple] = None  # (t_mono, total)
        self._evict_rate: Optional[float] = None  # last full-window rate
        self._recompile_last: Optional[tuple] = None  # (t_mono, total)
        self._recompile_new: Optional[float] = None  # last full window's new
        # serve_shed_churn keeps one rate window PER worker label (the
        # rule's whole point is naming the shedding device).
        self._serve_shed_last: Dict[object, tuple] = {}  # w -> (t, total)
        self._serve_shed_rate: Dict[object, float] = {}  # w -> full-window
        self._rules = (
            self._rule_learner_starving,
            self._rule_telem_stale,
            self._rule_shard_skew,
            self._rule_eviction_churn,
            self._rule_procs_down,
            self._rule_recompile_churn,
            self._rule_hbm_pressure,
            self._rule_stale_experience,
            self._rule_priority_collapse,
            self._rule_untrained_churn,
            self._rule_actor_skew,
            self._rule_serve_queue_saturated,
            self._rule_serve_shed_churn,
        )
        reg = self.registry
        self._obs_status = reg.gauge(
            "r2d2dpg_health_status",
            "the /health verdict as a level: 0 ok, 1 degraded, 2 critical "
            "(refreshed at each /health evaluation)",
        )
        self._obs_findings = reg.gauge(
            "r2d2dpg_health_findings",
            "live /health findings at the last evaluation",
        )
        self._obs_rule = reg.gauge(
            "r2d2dpg_health_rule_firing",
            "1 while this health rule has a live finding, else 0",
            labelnames=("rule",),
        )
        self._obs_transitions = reg.counter(
            "r2d2dpg_health_transitions_total",
            "verdict transitions (each one also lands in flight.jsonl as "
            "a health_verdict event)",
        )

    # ----------------------------------------------------------------- rules
    def _rule_learner_starving(self, snap, findings) -> None:
        for name in (
            "r2d2dpg_fleet_learner_wait_seconds",
            "r2d2dpg_sampler_wait_seconds",
        ):
            for s in _samples(snap, name):
                if not s.get("count"):
                    continue
                p99 = _finite(s.get("p99"))
                if p99 is not None and p99 > self.config.learner_wait_p99_s:
                    findings.append(
                        {
                            "rule": "learner_starving",
                            "severity": VERDICT_DEGRADED,
                            "detail": f"{name} p99 over threshold — the "
                            "learner is waiting on experience",
                            "value": p99,
                            "threshold": self.config.learner_wait_p99_s,
                        }
                    )

    def _rule_telem_stale(self, snap, findings) -> None:
        if not self.config.telem_expected:
            return  # no TELEM cadence armed: a growing clock is not a wedge
        for name, unit in (
            ("r2d2dpg_fleet_telem_staleness_seconds", "actor"),
            ("r2d2dpg_shard_telem_staleness_seconds", "shard"),
        ):
            for s in _samples(snap, name):
                v = _finite(s.get("value"))
                if v is not None and v > self.config.telem_stale_after_s:
                    who = s.get("labels", {}).get(unit, "?")
                    findings.append(
                        {
                            "rule": "telem_stale",
                            "severity": VERDICT_DEGRADED,
                            "detail": f"{unit} {who} TELEM stale — wedged, "
                            "partitioned, or dead (its mirrored series "
                            "hold last values)",
                            "value": v,
                            "threshold": self.config.telem_stale_after_s,
                        }
                    )

    def _rule_shard_skew(self, snap, findings) -> None:
        # Dedupe per shard label (see _per_shard_max): raw samples would
        # defeat the len>=2 single-shard guard, and a lagging TELEM copy
        # (0 from the forced HELLO push) beside a climbing advert would
        # read as a spuriously empty shard.  max() errs toward "holds
        # data": this rule exists to flag an empty shard, and either
        # copy showing occupancy disproves that.
        occ = list(
            _per_shard_max(snap, "r2d2dpg_replay_shard_occupancy").values()
        )
        if len(occ) < 2:
            return
        mean = sum(occ) / len(occ)
        if mean >= self.config.occupancy_skew_min_mean and min(occ) == 0.0:
            findings.append(
                {
                    "rule": "shard_skew",
                    "severity": VERDICT_DEGRADED,
                    "detail": "a replay shard sits empty while the tier "
                    "holds data — routing/quota skew or a shard not "
                    "being fed",
                    "value": min(occ),
                    "threshold": mean,
                }
            )

    def _rule_eviction_churn(self, snap, findings) -> None:
        # Both copies track one monotone quantity, so _per_shard_max's
        # dedupe picks the fresher (larger); summing raw samples would
        # double the rate and fire the rule at half the threshold.
        per_shard = _per_shard_max(
            snap, "r2d2dpg_replay_shard_evictions_total"
        )
        if not per_shard:
            return
        total = sum(per_shard.values())
        now = time.monotonic()
        with self._lock:
            last = self._evict_last
            if (
                last is not None
                and now - last[0] < self.config.eviction_rate_min_dt_s
            ):
                # Closely spaced polls (autoscaler racing an operator
                # curl) re-judge the LAST full window instead of a
                # fresh sub-second one: a single FIFO batch eviction —
                # e.g. 64 slots in one instant — over a 0.5s gap reads
                # as 128/s and flaps the verdict on a non-event.
                rate = self._evict_rate
            else:
                if last is not None and now > last[0]:
                    self._evict_rate = max(total - last[1], 0.0) / (
                        now - last[0]
                    )
                self._evict_last = (now, total)
                rate = self._evict_rate if last is not None else None
        if rate is None:
            return  # first sighting: no window yet
        if rate > self.config.eviction_churn_per_s:
            findings.append(
                {
                    "rule": "eviction_churn",
                    "severity": VERDICT_DEGRADED,
                    "detail": "replay rings are recycling experience "
                    "faster than the threshold — replay undersized for "
                    "the collection rate",
                    "value": rate,
                    "threshold": self.config.eviction_churn_per_s,
                }
            )

    def _rule_recompile_churn(self, snap, findings) -> None:
        samples = _samples(
            snap, "r2d2dpg_device_steady_recompiles_total"
        )
        if not samples:
            return  # no device monitor in this process: rule disarmed
        total = max(
            (v for v in (_finite(s.get("value")) for s in samples)
             if v is not None),
            default=None,
        )
        if total is None:
            return
        now = time.monotonic()
        with self._lock:
            last = self._recompile_last
            if (
                last is not None
                and now - last[0] < self.config.recompile_rate_min_dt_s
            ):
                # Sub-window poll gap: re-judge the last FULL window (the
                # eviction_churn burst guard) so an operator curl racing
                # the autoscaler cannot flap the verdict.
                new = self._recompile_new
            else:
                if last is not None:
                    self._recompile_new = max(total - last[1], 0.0)
                else:
                    # First sighting: a counter that is ALREADY nonzero
                    # is live evidence (the drill fired before the first
                    # /health poll), not a rate — judge the absolute
                    # total, and keep judging it (sub-window re-polls
                    # included) until a full quiet window clears it.
                    self._recompile_new = total
                self._recompile_last = (now, total)
                new = self._recompile_new
        if new is None:
            return
        if new > self.config.steady_recompiles_per_window:
            findings.append(
                {
                    "rule": "recompile_churn",
                    "severity": VERDICT_DEGRADED,
                    "detail": "steady-state recompiles: a learn/drain "
                    "program's avals re-keyed after warm-up (see "
                    "steady_recompile flight events for the program "
                    "label) — each one is a silent multi-second stall",
                    "value": new,
                    "threshold": self.config.steady_recompiles_per_window,
                }
            )

    def _rule_hbm_pressure(self, snap, findings) -> None:
        limits: Dict[object, float] = {}
        for s in _samples(snap, "r2d2dpg_device_hbm_bytes_limit"):
            v = _finite(s.get("value"))
            labels = s.get("labels")
            if v and v > 0 and isinstance(labels, dict):
                limits[labels.get("device")] = v
        if not limits:
            return  # CPU fallback reports no capacity: never degrading
        for s in _samples(snap, "r2d2dpg_device_hbm_bytes_in_use"):
            v = _finite(s.get("value"))
            labels = s.get("labels")
            if v is None or not isinstance(labels, dict):
                continue
            limit = limits.get(labels.get("device"))
            if limit is None:
                continue
            if v > self.config.hbm_pressure_frac * limit:
                findings.append(
                    {
                        "rule": "hbm_pressure",
                        "severity": VERDICT_DEGRADED,
                        "detail": f"device {labels.get('device')} HBM in "
                        "use over the headroom threshold — the next "
                        "drain-width/batch allocation bump OOMs",
                        "value": v,
                        "threshold": self.config.hbm_pressure_frac * limit,
                    }
                )

    def _rule_procs_down(self, snap, findings) -> None:
        for name, rule, expected in (
            (
                "r2d2dpg_fleet_actors_alive",
                "actors_down",
                self._expected_actors(snap),
            ),
            (
                "r2d2dpg_shard_alive",
                "shards_down",
                self.config.expected_shard_procs,
            ),
        ):
            if expected <= 0:
                continue
            samples = _samples(snap, name)
            if not samples:
                continue  # no supervisor in this process: rule disarmed
            alive = _finite(samples[0].get("value"))
            if alive is None or alive >= expected:
                continue
            findings.append(
                {
                    "rule": rule,
                    "severity": (
                        VERDICT_CRITICAL if alive == 0 else VERDICT_DEGRADED
                    ),
                    "detail": f"{name}: live supervised processes below "
                    "the spawn target",
                    "value": alive,
                    "threshold": float(expected),
                }
            )

    def _expected_actors(self, snap) -> int:
        # The scrape itself carries the target when the ingest server
        # registered it (r2d2dpg_fleet_actors_expected); the config value
        # is the fallback for processes without an ingest server.
        for s in _samples(snap, "r2d2dpg_fleet_actors_expected"):
            v = _finite(s.get("value"))
            if v is not None and v > 0:
                return int(v)
        return self.config.expected_actors

    def _rule_stale_experience(self, snap, findings) -> None:
        # Provenance-absent frames never observe into this histogram
        # (obs/quality.py disarms the fold on the -1 sentinel), so an
        # old-schema fleet simply has no samples here and stays green.
        for s in _samples(snap, "r2d2dpg_quality_policy_lag"):
            count = _finite(s.get("count"))
            if not count or count < self.config.quality_min_lag_count:
                continue  # warm-up: too few lag observations to judge
            p99 = _finite(s.get("p99"))
            if p99 is not None and p99 > self.config.quality_max_lag:
                findings.append(
                    {
                        "rule": "stale_experience",
                        "severity": VERDICT_DEGRADED,
                        "detail": "policy-lag p99 over --quality-max-lag — "
                        "the learner is training on experience collected "
                        "too many param versions ago (publish cadence, "
                        "actor pull wedge, or replay far oversized)",
                        "value": p99,
                        "threshold": self.config.quality_max_lag,
                    }
                )

    def _rule_priority_collapse(self, snap, findings) -> None:
        for s in _samples(snap, "r2d2dpg_quality_ess_frac"):
            v = _finite(s.get("value"))
            if v is None or v <= 0.0:
                continue  # never armed: a real ESS/B is strictly positive
            if v < self.config.quality_ess_floor:
                findings.append(
                    {
                        "rule": "priority_collapse",
                        "severity": VERDICT_DEGRADED,
                        "detail": "ESS/B of trained batches under the "
                        "floor — the priority distribution collapsed onto "
                        "a handful of slots (alpha too hot or a priority "
                        "spike recycling the same transitions)",
                        "value": v,
                        "threshold": self.config.quality_ess_floor,
                    }
                )

    def _rule_untrained_churn(self, snap, findings) -> None:
        # Dedupe per shard label (see _per_shard_max): the learner's
        # advert mirror and a shard proc's TELEM copy share these names.
        totals = _per_shard_max(
            snap, "r2d2dpg_quality_evicted_unsampled_total"
        )
        fracs = _per_shard_max(
            snap, "r2d2dpg_quality_evicted_unsampled_frac"
        )
        for shard, frac in fracs.items():
            if (
                totals.get(shard, 0.0)
                < self.config.quality_churn_min_evictions
            ):
                continue  # warm-up: not enough evictions to call a trend
            if frac > self.config.quality_untrained_frac:
                findings.append(
                    {
                        "rule": "untrained_churn",
                        "severity": VERDICT_DEGRADED,
                        "detail": f"shard {shard} is evicting experience "
                        "the learner never sampled — collection outruns "
                        "training reach (replay undersized or sample "
                        "quota starving this shard)",
                        "value": frac,
                        "threshold": self.config.quality_untrained_frac,
                    }
                )

    def _rule_actor_skew(self, snap, findings) -> None:
        # Dedupe per actor label with max() (monotone counters: the
        # larger copy is the fresher) — the mirror/TELEM duplication
        # that motivates _per_shard_max applies to actor series too.
        per_actor: Dict[object, float] = {}
        for s in _samples(snap, "r2d2dpg_quality_trained_seqs_total"):
            v = _finite(s.get("value"))
            labels = s.get("labels")
            if v is None or not isinstance(labels, dict):
                continue
            actor = labels.get("actor")
            if actor is None:
                continue
            per_actor[actor] = max(per_actor.get(actor, 0.0), v)
        if len(per_actor) < 2:
            return  # skew needs a ladder: single-actor runs never fire
        mean = sum(per_actor.values()) / len(per_actor)
        if mean < self.config.quality_actor_skew_min_mean:
            return  # warm-up: the fleet has not trained enough to judge
        low_actor, low = min(per_actor.items(), key=lambda kv: kv[1])
        threshold = self.config.quality_actor_skew_frac * mean
        if low < threshold:
            findings.append(
                {
                    "rule": "actor_skew",
                    "severity": VERDICT_DEGRADED,
                    "detail": f"actor {low_actor} trained-seqs far below "
                    "the fleet mean — its lane of the sigma ladder is "
                    "not reaching training (dead env pool, wedged "
                    "actor, or routing starvation)",
                    "value": low,
                    "threshold": threshold,
                }
            )

    def _rule_serve_queue_saturated(self, snap, findings) -> None:
        # Dedupe every family per worker label (_per_label_max): a future
        # cross-process serving tier mirrors these series the same way
        # shard TELEM does, and gauges err toward the worse reading.
        depths = _per_label_max(snap, "r2d2dpg_serve_queue_depth", "worker")
        if not depths:
            return  # no routed serving workers in this process: disarmed
        limits = _per_label_max(snap, "r2d2dpg_serve_queue_limit", "worker")
        served = _per_label_max(
            snap, "r2d2dpg_serve_requests_total", "worker"
        )
        for worker, depth in sorted(depths.items(), key=str):
            limit = limits.get(worker)
            if limit is None or limit <= 0:
                continue
            if served.get(worker, 0.0) <= 0:
                # Warm-up exemption: admission piles up while this
                # worker's first bucket compiles — saturation is only a
                # finding once it has proven it can drain at all.
                continue
            threshold = self.config.serve_queue_saturated_frac * limit
            if depth >= threshold:
                findings.append(
                    {
                        "rule": "serve_queue_saturated",
                        "severity": VERDICT_DEGRADED,
                        "detail": f"serving worker {worker} queue depth "
                        "at the saturation fraction of its admission "
                        "bound — one burst away from shedding (grow "
                        "--serve-workers, raise --max-queue, or slow "
                        "the client)",
                        "value": depth,
                        "threshold": threshold,
                    }
                )

    def _rule_serve_shed_churn(self, snap, findings) -> None:
        # Sheds are labelled {worker, code}; dedupe per cell with max()
        # (monotone counters, mirror-safe), then sum a worker's codes —
        # the rule judges "this worker is shedding", whatever the mode.
        cells: Dict[tuple, float] = {}
        for s in _samples(snap, "r2d2dpg_serve_sheds_total"):
            v = _finite(s.get("value"))
            labels = s.get("labels")
            if v is None or not isinstance(labels, dict):
                continue
            worker = labels.get("worker")
            if worker is None:
                continue
            key = (worker, labels.get("code"))
            cells[key] = max(cells.get(key, 0.0), v)
        if not cells:
            return  # no routed serving workers in this process: disarmed
        per_worker: Dict[object, float] = {}
        for (worker, _code), v in cells.items():
            per_worker[worker] = per_worker.get(worker, 0.0) + v
        now = time.monotonic()
        for worker in sorted(per_worker, key=str):
            total = per_worker[worker]
            with self._lock:
                last = self._serve_shed_last.get(worker)
                if (
                    last is not None
                    and now - last[0] < self.config.serve_shed_rate_min_dt_s
                ):
                    # Sub-window poll gap: re-judge the last FULL window
                    # (the eviction_churn burst guard) — one shed burst
                    # over a 0.5s curl gap is not a sustained rate.
                    rate = self._serve_shed_rate.get(worker)
                else:
                    if last is not None and now > last[0]:
                        self._serve_shed_rate[worker] = max(
                            total - last[1], 0.0
                        ) / (now - last[0])
                    self._serve_shed_last[worker] = (now, total)
                    rate = (
                        self._serve_shed_rate.get(worker)
                        if last is not None
                        else None
                    )
            if rate is None:
                continue  # first sighting of this worker: no window yet
            if rate > self.config.serve_shed_per_s:
                findings.append(
                    {
                        "rule": "serve_shed_churn",
                        "severity": VERDICT_DEGRADED,
                        "detail": f"serving worker {worker} is shedding "
                        "at a sustained rate — its admission bound or "
                        "session slab is persistently full (grow "
                        "--serve-workers or per-worker capacity)",
                        "value": rate,
                        "threshold": self.config.serve_shed_per_s,
                    }
                )

    # -------------------------------------------------------------- evaluate
    def evaluate(self) -> Dict:
        """One verdict over the current registry (+ mirror) state.  Never
        raises: a rule that cannot read its signal contributes nothing
        (absence of evidence is not degradation — staleness gauges exist
        so absence itself becomes a visible signal)."""
        snap = self.registry.snapshot()
        if self.mirror is not None:
            sources = self.mirror.sources()
            if sources:
                snap = merge_remote(snap, sources)
        findings: List[Dict] = []
        for rule in self._rules:
            try:
                rule(snap, findings)
            except Exception as e:  # noqa: BLE001 - verdict isolation
                findings.append(
                    {
                        "rule": "engine_error",
                        "severity": VERDICT_DEGRADED,
                        "detail": f"health rule failed: "
                        f"{type(e).__name__}: {e}",
                        "value": None,
                        "threshold": None,
                    }
                )
        verdict = VERDICT_OK
        for f in findings:
            if _SEVERITY[f["severity"]] > _SEVERITY[verdict]:
                verdict = f["severity"]
        firing = {f["rule"] for f in findings}
        self._obs_status.set(_SEVERITY[verdict])
        self._obs_findings.set(len(findings))
        for rule in RULES:
            self._obs_rule.labels(rule=rule).set(1.0 if rule in firing else 0.0)
        with self._lock:
            previous = self._last_verdict
            transition = verdict != previous
            self._last_verdict = verdict
        if transition:
            # Every transition is post-mortem evidence: flight.jsonl says
            # WHEN the run degraded and when it recovered, with the rules
            # that drove the change.
            self._obs_transitions.inc()
            flight_event(
                "health_verdict",
                verdict=verdict,
                previous=previous,
                rules=sorted(firing),
            )
        return {
            "verdict": verdict,
            "findings": findings,
            "t_wall": time.time(),
        }
