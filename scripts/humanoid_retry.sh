#!/bin/bash
# Longer config-#4 CPU evidence retry: the first 95-min run plateaued at
# eval ~1 (peak 1.9 at 33 min) — humanoid-run needs more data and a denser
# update ratio than the 1-core window allowed.  ~3.7 h at ratio ~1:13.
# Skips itself if the TPU campaign has claimed the box.
HERE="$(cd "$(dirname "$0")" && pwd)"
cd "$HERE/.."
mkdir -p runs
exec >> runs/humanoid_retry.log 2>&1

# Wait for the box; bail if the TPU campaign ever claims it (its on-chip
# config-#4 run supersedes this retry).  Gate on the campaign's COMPLETION
# marker, not metrics.csv, which appears seconds into a run and would
# suppress this fallback forever after a killed campaign (ADVICE r2 #2).
source "$HERE/lib_gate.sh" || exit 1
gate_on_box runs/tpu/humanoid/.done || exit 0

echo "=== humanoid retry start $(date) ==="
mkdir -p runs/humanoid_r2_long
nice -n 19 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu R2D2DPG_PALLAS_INTERPRET=1 \
python -m r2d2dpg_tpu.train --config humanoid_r2d2 \
  --num-envs 16 --learner-steps 24 --batch-size 48 --min-replay 300 \
  --seed 1 --minutes 220 --log-every 10 --eval-every 150 --eval-envs 4 \
  --logdir runs/humanoid_r2_long --checkpoint-dir runs/humanoid_r2_long/ckpt \
  --checkpoint-every 150 > runs/humanoid_r2_long/stdout.log 2>&1
echo "=== humanoid retry done $(date) ==="
