#!/bin/bash
# Everything TPU-gated, in one unattended sequence. Fired by tpu_watcher.sh
# the moment the axon tunnel answers. Logs under runs/tpu/.
HERE="$(cd "$(dirname "$0")" && pwd)"
cd "$HERE/.."
mkdir -p runs/tpu
exec > runs/tpu/campaign.log 2>&1
echo "=== TPU campaign start $(date) ==="

# The host core must be free for the north-star run's env pool: stop the
# CPU evidence runs (the TPU measurement supersedes them) and the chain.
pkill -f chain_runs
pkill -f "r2d2dpg_tpu.train"
sleep 5

echo "--- bench fp32 ---"
python bench.py | tee runs/tpu/bench_fp32.json
echo "--- bench bf16 ---"
python bench.py bfloat16 | tee runs/tpu/bench_bf16.json

echo "--- phase throughput (TPU) ---"
python benchmarks/phase_throughput.py 64 20 16 | tee runs/tpu/phase_throughput.json

echo "--- env throughput (pendulum on TPU) ---"
python benchmarks/env_throughput.py 1024 200 pendulum | tee runs/tpu/env_pendulum.json

echo "--- north star: walker 30 min on TPU ---"
mkdir -p runs/tpu/walker30
python -m r2d2dpg_tpu.train --config walker_r2d2 \
  --overlap-learner 1 --learner-steps 48 --num-envs 64 --batch-size 64 \
  --minutes 30 --log-every 10 --eval-every 50 --eval-envs 10 \
  --logdir runs/tpu/walker30 --checkpoint-dir runs/tpu/walker30/ckpt \
  --checkpoint-every 200 | tail -50

echo "--- final deterministic eval ---"
python -m r2d2dpg_tpu.eval --config walker_r2d2 \
  --checkpoint-dir runs/tpu/walker30/ckpt --episodes 10 --rounds 2 \
  | tee runs/tpu/walker30_eval.json

echo "=== TPU campaign done $(date) ==="
# Resume the CPU evidence chain for whatever window remains.
setsid nohup bash "$HERE/chain_runs.sh" > runs/chain.log 2>&1 < /dev/null &
