#!/bin/bash
# Learning validation for the round-3 MixedPrecisionLSTMCell (bf16 gate
# matmuls, fp32 state accumulation — models/actor_critic.py).
#
# The original dtype A/B (runs/walker_probe_bf16, OLD truncated-carry
# cell) fell ~3x behind its fp32 control (145.5 vs 351.7 final eval on
# the nstep3 recipe, docs/RESULTS.md).  This run repeats the EXACT same
# arm — seed 3, 16 envs, 1:20 ratio, 85 min, --n-step 3, only
# --compute-dtype bfloat16 — now routed through the fp32-carry cell, so
# it answers: does keeping the cell state fp32 recover the fp32 learning
# curve while keeping the MXU matmuls bf16?  Success bar: final 20-ep
# eval within ~15% of the fp32 control's 351.7 (i.e. >= ~300) decides
# the WALKER_R2D2.compute_dtype flip (bench headline ~31k steps/s/chip).
#
# Preemptible by the TPU campaign; superseded by the on-chip
# walker30_bf16 (same cell, same question, better hardware).
HERE="$(cd "$(dirname "$0")" && pwd)"
cd "$HERE/.."
mkdir -p runs
exec >> runs/walker_mpbf16_probe.log 2>&1
source "$HERE/lib_gate.sh" || exit 1

run_evidence runs/walker_probe_mpbf16 runs/tpu/walker30_bf16/.done \
  "^[^ ]*bash [^ ]*walker_combo_probe\.sh" \
  85 3 "--config walker_r2d2 --compute-dtype bfloat16" \
  --config walker_r2d2 --compute-dtype bfloat16 \
  --num-envs 16 --learner-steps 16 --batch-size 64 --min-replay 300 \
  --n-step 3
