"""Actor (deterministic policy) and Critic (Q) networks with carried LSTM state.

Reference parity: SURVEY.md §2.1 / §3.4 —

- ``ActorNet``: obs -> tanh-squashed deterministic action in
  [-action_scale, action_scale]; encoder -> LSTM core -> output head.
- ``CriticNet``: (obs, action) -> scalar Q; the action enters after the first
  encoder layer (SURVEY §3.4: "action enters after layer 1").
- Both take and return recurrent state ``(h, c)`` **carried by the caller** —
  THE defining R2D2 detail (SURVEY §2.1): the actor phase threads it per env
  step and stores it into replay; the learner re-initializes from *stored*
  state and burns in.
- Feedforward variants (``use_lstm=False``, BASELINE config #1) keep the same
  carried-state API with an empty carry, so actor/learner code is uniform.
- Episode boundaries: the carry is zeroed where ``reset`` is set *before* the
  cell runs (SURVEY §2.1 "per-step hidden-state reset on episode boundary").

TPU notes: the single-step call is what the actor phase vmaps over envs; the
learner unrolls it with ``lax.scan`` over time (SURVEY §2.9 — burn-in+unroll
as one jitted scan instead of cuDNN LSTM calls).  All matmuls are MXU-shaped
([B, hidden] x [hidden, 4*hidden]); ``dtype=bfloat16`` is supported
throughout with float32 params.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from r2d2dpg_tpu.models.torsos import (
    ConvTorso,
    MLPTorso,
    fan_in_uniform,
    symmetric_uniform,
)

# Carry is a pytree: () for feedforward nets, flax's (c, h) tuple for LSTM.
Carry = Any


def lstm_initial_carry(batch_size: int, hidden: int, use_lstm: bool) -> Carry:
    """Fresh carry for a net: flax's (c, h) zeros for LSTM, () for feedforward.

    (c, h) are distinct buffers — aliased leaves break argument donation in
    the trainer's jitted phases.
    """
    if not use_lstm:
        return ()
    return (
        jnp.zeros((batch_size, hidden), jnp.float32),
        jnp.zeros((batch_size, hidden), jnp.float32),
    )


def zeros_where_reset(carry: Carry, reset: jnp.ndarray) -> Carry:
    """Zero the recurrent state for batch rows where ``reset`` is truthy."""
    if not jax.tree_util.tree_leaves(carry):
        return carry
    mask = reset.astype(bool)

    def _mask(x):
        return jnp.where(mask.reshape(mask.shape + (1,) * (x.ndim - mask.ndim)), 0, x)

    return jax.tree_util.tree_map(_mask, carry)


class _GateParams(nn.Module):
    """Parameter-only Dense (kernel [+ bias]) occupying the same tree path
    as one of flax OptimizedLSTMCell's per-gate Dense submodules, so the
    mixed cell's checkpoint tree is leaf-for-leaf identical to the stock
    cell's and fp32<->bf16 checkpoints interchange (VERDICT r3 weak #1)."""

    in_features: int
    features: int
    use_bias: bool
    kernel_init: Any

    @nn.compact
    def __call__(self):
        kernel = self.param(
            "kernel", self.kernel_init, (self.in_features, self.features)
        )
        bias = (
            self.param("bias", nn.initializers.zeros_init(), (self.features,))
            if self.use_bias
            else None
        )
        return kernel, bias


class MixedPrecisionLSTMCell(nn.Module):
    """LSTM cell with ``dtype`` gate matmuls but FLOAT32 state arithmetic.

    Motivation (docs/RESULTS.md round-3 dtype A/B): with flax's cell at
    ``dtype=bfloat16`` the carry itself is returned in bf16, so the cell
    state ``c`` accumulates rounding across every unroll step — walker
    learning fell ~3x behind fp32 while short-horizon pendulum masked it.
    Here the two gate projections (the MXU work, >95% of the FLOPs) run in
    ``dtype`` while the state update ``c' = f*c + i*g`` and the carry stay
    float32, targeting exactly the compounding path at ~none of the
    throughput cost.

    Semantics AND param tree mirror flax's OptimizedLSTMCell exactly —
    gate order (i, f, g, o), zero-init recurrent biases with NO extra
    forget offset, lecun input kernels ``ii/if/ig/io`` (no bias), per-gate
    orthogonal recurrent kernels ``hi/hf/hg/ho`` (with bias) — declared as
    per-gate ``_GateParams`` leaves and fused into one [in, 4H] / [H, 4H]
    matmul pair at apply time (loop-invariant: XLA hoists the concat out
    of the unroll scan).  A bf16-vs-fp32 comparison therefore measures
    precision alone, and a checkpoint written under either dtype restores
    under the other.

    Measured outcome (round-5 controlled A/B, docs/RESULTS.md
    "Mixed-precision cell learning probe", taken on the fp32-CARRY
    revision of this cell BEFORE the fp32-accumulator dots below): the
    fp32 carry alone did NOT recover walker learning parity — final
    146.6 vs the fp32 control's 351.7, within noise of the old
    truncated-carry cell's 145.5 — implicating the bf16-truncated matmul
    accumulator, which the ``preferred_element_type`` dots below remove
    (unrolled |h| error vs fp32 drops ~16x).  The accumulator variant's
    round-5 measurement (RESULTS.md "fp32-accumulator cell probe"):
    final 274.4 vs fp32's 351.7 — a ~60% recovery over the carry-only
    cells (145.5/146.6) but still short of parity, so ``compute_dtype``
    defaults stay float32; the residual loss is bf16 rounding of the
    streamed operands themselves.
    """

    hidden: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, carry: Carry, x: jnp.ndarray):
        c, h = carry  # float32 by contract (lstm_initial_carry)
        lecun = nn.initializers.lecun_normal()
        orth = nn.initializers.orthogonal()
        wi, wh, bh = [], [], []
        for g in "ifgo":
            k, _ = _GateParams(
                x.shape[-1], self.hidden, False, lecun, name=f"i{g}"
            )()
            wi.append(k)
            k, b = _GateParams(
                self.hidden, self.hidden, True, orth, name=f"h{g}"
            )()
            wh.append(k)
            bh.append(b)
        # Operands stream in ``dtype`` (the HBM/MXU win) but the dot
        # ACCUMULATES in fp32 via preferred_element_type — free on TPU,
        # whose MXU natively accumulates bf16 products into fp32; without
        # it XLA truncates the accumulator to bf16 at every step of the
        # recurrence, which the round-5 A/B implicates as the remaining
        # compounding-error path (docs/RESULTS.md "Mixed-precision cell").
        zx = jnp.matmul(
            x.astype(self.dtype),
            jnp.concatenate(wi, axis=1).astype(self.dtype),
            preferred_element_type=jnp.float32,
        )
        zh = jnp.matmul(
            h.astype(self.dtype),
            jnp.concatenate(wh, axis=1).astype(self.dtype),
            preferred_element_type=jnp.float32,
        )
        # Gate math + state update in fp32 (bias join included).
        z = zx + zh + jnp.concatenate(bh, axis=0)
        i, f, g, o = jnp.split(z, 4, axis=-1)
        c = nn.sigmoid(f) * c + nn.sigmoid(i) * jnp.tanh(g)
        h = nn.sigmoid(o) * jnp.tanh(c)
        return (c, h), h.astype(self.dtype)


class _Core(nn.Module):
    """Shared recurrent-or-dense core: LSTM cell when ``use_lstm`` else Dense."""

    hidden: int
    use_lstm: bool
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, carry: Carry, reset: jnp.ndarray):
        if self.use_lstm:
            carry = zeros_where_reset(carry, reset)
            if self.dtype != jnp.float32:
                # Reduced-precision mode routes through the fp32-carry cell
                # (see MixedPrecisionLSTMCell); the fp32 default keeps the
                # stock flax cell bit-for-bit.  The explicit name pins the
                # mixed cell to the tree path the stock cell gets by
                # auto-naming, so checkpoints interchange across dtypes.
                carry, y = MixedPrecisionLSTMCell(
                    self.hidden, dtype=self.dtype, name="OptimizedLSTMCell_0"
                )(carry, x)
            else:
                carry, y = nn.OptimizedLSTMCell(self.hidden, dtype=self.dtype)(
                    carry, x
                )
            return y, carry
        y = nn.relu(
            nn.Dense(self.hidden, kernel_init=fan_in_uniform(), dtype=self.dtype)(x)
        )
        return y, carry


def _make_torso(pixels: bool, hidden: int, dtype: Any) -> nn.Module:
    if pixels:
        return ConvTorso(out_size=hidden, dtype=dtype)
    return MLPTorso(layer_sizes=(hidden,), dtype=dtype)


class ActorNet(nn.Module):
    """Deterministic policy mu(obs) with optional LSTM core."""

    action_dim: int
    hidden: int = 256
    use_lstm: bool = True
    pixels: bool = False
    action_scale: float = 1.0
    dtype: Any = jnp.float32

    def setup(self):
        self.torso = _make_torso(self.pixels, self.hidden, self.dtype)
        self.core = _Core(self.hidden, self.use_lstm, self.dtype)
        self.head = nn.Dense(
            self.action_dim, kernel_init=symmetric_uniform(3e-3), dtype=self.dtype
        )

    def __call__(
        self, obs: jnp.ndarray, carry: Carry, reset: jnp.ndarray
    ) -> Tuple[jnp.ndarray, Carry]:
        """Single step: obs [B, ...], reset [B] -> (action [B, A], new carry)."""
        x = self.torso(obs)
        y, carry = self.core(x, carry, reset)
        action = jnp.tanh(self.head(y)).astype(jnp.float32) * self.action_scale
        return action, carry

    def initial_carry(self, batch_size: int) -> Carry:
        return lstm_initial_carry(batch_size, self.hidden, self.use_lstm)


class CriticNet(nn.Module):
    """Q(obs, action) with optional LSTM core; action concatenated after layer 1."""

    hidden: int = 256
    use_lstm: bool = True
    pixels: bool = False
    dtype: Any = jnp.float32

    def setup(self):
        self.torso = _make_torso(self.pixels, self.hidden, self.dtype)
        self.mix = nn.Dense(
            self.hidden, kernel_init=fan_in_uniform(), dtype=self.dtype
        )
        self.core = _Core(self.hidden, self.use_lstm, self.dtype)
        self.head = nn.Dense(1, kernel_init=symmetric_uniform(3e-3), dtype=self.dtype)

    def __call__(
        self,
        obs: jnp.ndarray,
        action: jnp.ndarray,
        carry: Carry,
        reset: jnp.ndarray,
    ) -> Tuple[jnp.ndarray, Carry]:
        """Single step -> (q [B], new carry)."""
        x = self.torso(obs)
        x = nn.relu(self.mix(jnp.concatenate([x, action.astype(x.dtype)], axis=-1)))
        y, carry = self.core(x, carry, reset)
        q = self.head(y).astype(jnp.float32)
        return jnp.squeeze(q, axis=-1), carry

    def initial_carry(self, batch_size: int) -> Carry:
        return lstm_initial_carry(batch_size, self.hidden, self.use_lstm)


def policy_step_fn(actor: "ActorNet") -> Callable[..., Tuple[jnp.ndarray, Carry]]:
    """Pure single-step policy function for inference-serving callers.

    Returns ``step(params, obs, carry, reset) -> (action, new_carry)`` — a
    closure over only the static module (hyperparameters), so it is safe to
    ``jax.jit`` once and reuse across hot-reloaded param versions: params
    are a traced argument, never baked into the compiled executable.  This
    is exactly ``actor.apply`` with the argument order the serving batcher
    threads through its session slabs; it exists so serving code never
    reaches into flax module internals.
    """

    def step(params, obs: jnp.ndarray, carry: Carry, reset: jnp.ndarray):
        return actor.apply(params, obs, carry, reset)

    return step


def unroll(
    apply_step: Callable[..., Tuple[jnp.ndarray, Carry]],
    carry: Carry,
    *step_inputs: jnp.ndarray,
) -> Tuple[jnp.ndarray, Carry]:
    """Unroll a single-step net over time with ``lax.scan``.

    Args:
      apply_step: closure ``(carry, *inputs_t) -> (out_t, carry)`` — e.g.
        ``lambda c, obs, reset: actor.apply(params, obs, c, reset)``.
      carry: initial recurrent state.
      *step_inputs: time-major arrays ``[T, B, ...]`` passed per step.

    Returns:
      ``(outputs [T, ...], final_carry)``.
    """

    def step(c, inputs):
        out, c = apply_step(c, *inputs)
        return c, out

    carry, outs = lax.scan(step, carry, step_inputs)
    return outs, carry


def time_major(x: jnp.ndarray) -> jnp.ndarray:
    """[B, T, ...] -> [T, B, ...] (replay is batch-major; scan is time-major)."""
    return jnp.swapaxes(x, 0, 1)
