#!/bin/bash
# Config-#5 twin-critic de-confound (VERDICT r4 next #2: "cheetah
# twin-critic-only arm (round-2 regime + --twin-critic 1)").
#
# Round 2 collapsed at the ORIGINAL regime (8 envs, 4 updates/phase,
# batch 8, actor-lr 1e-4): eval 4.1 -> 1.5 by 94 min / 67k steps.
# Round 3's mitigation changed TWO knobs at once (batch 16x2 AND
# actor-lr 5e-5) and the collapse disappeared — so which knob fixed it
# is confounded, and twin critic (the stronger, opt-in fix per
# configs/__init__.py) has never been tested alone.  This arm replays
# the round-2 collapse regime exactly (actor-lr pinned back to 1e-4,
# overriding the round-3 config default of 5e-5) with ONLY
# --twin-critic 1 changed.  Success bar: eval monotone past the round-2
# collapse point (~67k env steps / ~94 min) => clipped double-Q alone
# defeats the overestimation collapse; collapse anyway => the actor-lr
# knob was the load-bearing fix.
#
# Queued behind the walker mpbf16 probe (single-core box); preemptible
# by the TPU campaign.  Not superseded by the campaign's cheetah step:
# that run uses the mitigated defaults + drop-in flags, so it cannot
# answer the twin-critic-only question.
HERE="$(cd "$(dirname "$0")" && pwd)"
cd "$HERE/.."
mkdir -p runs
exec >> runs/cheetah_twin_probe.log 2>&1
source "$HERE/lib_gate.sh" || exit 1

run_evidence runs/cheetah_twin_probe "" \
  "^[^ ]*bash [^ ]*(walker_combo_probe|walker_mpbf16_probe)\.sh" \
  115 1 "--config cheetah_pixels --twin-critic 1" \
  --config cheetah_pixels \
  --num-envs 8 --learner-steps 4 --batch-size 8 --min-replay 200 \
  --actor-lr 1e-4 --twin-critic 1
