"""Per-session recurrent-state store: preallocated device-resident slabs.

THE R2D2 serving problem: a recurrent policy's action depends on the LSTM
carry accumulated over the whole session, so a policy service is stateful
per client.  Keeping one small ``(c, h)`` pair per session as separate
device arrays would fragment HBM and force a gather/concat on every batch;
instead the store follows the ``ReplayArena`` slab idiom (replay/arena.py):
ONE preallocated ``[max_sessions + 1, ...]`` buffer per carry leaf, with
per-batch access as an indexed gather/scatter that lives *inside* the
jitted policy step — no host round-trip ever touches a carry.

Row ``max_sessions`` (``scratch_slot``) is a write-only scratch row: the
micro-batcher pads every bucket to its static size by pointing padding rows
at it, so the scatter needs no validity mask (duplicate scatter writes to
the scratch row are don't-cares).

Slot bookkeeping (which client owns which row, TTL) is host-side and cheap:
a dict + free-list guarded by a lock.  Freed rows are NOT zeroed on the
device — a new session's first request carries ``reset=1`` and the actor
zeroes the carry *inside* the step (``zeros_where_reset``), exactly the
episode-boundary mechanic training uses, so slab hygiene costs nothing.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

Carry = Any


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SessionSlabs:
    """Device-resident carry storage: a pytree with ``[S + 1, ...]`` leaves
    (``S = max_sessions``; the extra row is the padding scratch row).  Empty
    pytree for feedforward actors — gather/scatter degrade to no-ops."""

    carries: Carry


def gather_carries(slabs: SessionSlabs, slots: jnp.ndarray) -> Carry:
    """Read the carries for one batch of slot indices (jit-safe)."""
    return jax.tree_util.tree_map(lambda buf: buf[slots], slabs.carries)


def scatter_carries(
    slabs: SessionSlabs, slots: jnp.ndarray, carries: Carry
) -> SessionSlabs:
    """Write updated carries back at ``slots`` (jit-safe; donation-friendly).

    Padding rows all point at the scratch row; ``.at[].set`` with duplicate
    indices is nondeterministic about which write wins, which is fine there
    — the scratch row is never read as real state.
    """
    return SessionSlabs(
        carries=jax.tree_util.tree_map(
            lambda buf, new: buf.at[slots].set(new), slabs.carries, carries
        )
    )


@dataclasses.dataclass
class _SlotInfo:
    slot: int
    last_used: float


class SessionStore:
    """Host-side session table over a fixed pool of slab rows.

    The instance holds static config plus the slot map; the device slabs are
    a separate ``SessionSlabs`` pytree threaded through the jitted policy
    step by the service (same state-outside-the-object discipline as
    ``ReplayArena``).

    TTL eviction is lazy: expired sessions are swept on every allocation
    attempt (and on demand via ``evict_expired``), so an idle service holds
    stale rows but a full one always reclaims them before shedding.
    """

    def __init__(
        self,
        max_sessions: int,
        initial_carry_fn: Callable[[int], Carry],
        *,
        ttl_s: float = 300.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if max_sessions < 1:
            raise ValueError("max_sessions must be >= 1")
        self.max_sessions = max_sessions
        self.ttl_s = ttl_s
        self._initial_carry_fn = initial_carry_fn
        self._clock = clock
        self._lock = threading.Lock()
        self._by_id: Dict[str, _SlotInfo] = {}
        self._free: List[int] = list(range(max_sessions - 1, -1, -1))
        self._evictions = 0

    # ----------------------------------------------------------------- slabs
    @property
    def scratch_slot(self) -> int:
        return self.max_sessions

    def init_slabs(self) -> SessionSlabs:
        """Preallocate the carry slabs (zeros; see module docstring on why
        rows never need re-zeroing afterwards)."""
        example = self._initial_carry_fn(1)

        def alloc(leaf):
            return jnp.zeros(
                (self.max_sessions + 1,) + leaf.shape[1:], leaf.dtype
            )

        return SessionSlabs(
            carries=jax.tree_util.tree_map(alloc, example)
        )

    # ----------------------------------------------------------------- slots
    def acquire(self, session_id: str) -> Optional[Tuple[int, bool]]:
        """Slot for ``session_id``, allocating on first sight.

        Returns ``(slot, is_new)``, or ``None`` when the table is full even
        after TTL eviction (the caller sheds the request).  Touches the
        session's TTL clock.
        """
        now = self._clock()
        with self._lock:
            info = self._by_id.get(session_id)
            if info is not None:
                info.last_used = now
                return info.slot, False
            if not self._free:
                self._evict_expired_locked(now)
            if not self._free:
                return None
            slot = self._free.pop()
            self._by_id[session_id] = _SlotInfo(slot=slot, last_used=now)
            return slot, True

    def release(self, session_id: str) -> bool:
        """Explicitly end a session (client said goodbye); True if it existed."""
        with self._lock:
            info = self._by_id.pop(session_id, None)
            if info is None:
                return False
            self._free.append(info.slot)
            return True

    def evict_expired(self) -> int:
        """Sweep sessions idle for longer than ``ttl_s``; returns count."""
        with self._lock:
            return self._evict_expired_locked(self._clock())

    def clear(self) -> int:
        """Drop EVERY session (service-side state-loss recovery: the caller
        just rebuilt the slabs, so all carries are gone; clients' next
        request re-allocates with ``is_new`` -> reset).  Returns count."""
        with self._lock:
            n = len(self._by_id)
            for info in self._by_id.values():
                self._free.append(info.slot)
            self._by_id.clear()
            self._evictions += n
            return n

    def _evict_expired_locked(self, now: float) -> int:
        dead = [
            sid
            for sid, info in self._by_id.items()
            if now - info.last_used > self.ttl_s
        ]
        for sid in dead:
            self._free.append(self._by_id.pop(sid).slot)
        self._evictions += len(dead)
        return len(dead)

    # ----------------------------------------------------------------- stats
    @property
    def active(self) -> int:
        with self._lock:
            return len(self._by_id)

    @property
    def evictions(self) -> int:
        return self._evictions
